"""The complete pipeline (complete-inference-pipeline.yaml): single-node
components (frontend, vision encoder) + multi-node disaggregated LLM
prefill/decode groups + explicit startup ordering, in one PodCliqueSet."""

from common import clique, pcs, report, run
from grove_tpu.api.types import (
    CliqueStartupType,
    PodCliqueScalingGroupConfig,
    PodCliqueSetTemplateSpec,
)


def build():
    return pcs("pipeline", PodCliqueSetTemplateSpec(
        startup_type=CliqueStartupType.EXPLICIT,
        cliques=[
            clique("frontend", replicas=2, cpu=0.5, memory=1.0),
            clique("vision-encoder", replicas=1, cpu=2.0, memory=4.0,
                   tpu=1.0),
            clique("pleader", replicas=1, cpu=2.0, memory=4.0),
            clique("pworker", replicas=2, cpu=4.0, memory=8.0, tpu=2.0),
            clique("dleader", replicas=1, cpu=2.0, memory=4.0,
                   starts_after=("pleader",)),
            clique("dworker", replicas=2, cpu=4.0, memory=8.0, tpu=2.0,
                   starts_after=("pleader",)),
        ],
        pod_clique_scaling_group_configs=[
            PodCliqueScalingGroupConfig(
                name="prefill", clique_names=["pleader", "pworker"],
                replicas=2, min_available=1),
            PodCliqueScalingGroupConfig(
                name="decode", clique_names=["dleader", "dworker"],
                replicas=2, min_available=1),
        ],
    ))


if __name__ == "__main__":
    report(run(build(), nodes=64))
