"""Single-node aggregated serving: one clique of identical replicas, each
pod a complete engine (samples/user-guide/concept-overview/
single-node-aggregated.yaml). Simplest archetype: no gangs-of-gangs, one
base PodGang per PCS replica."""

from common import clique, pcs, report, run
from grove_tpu.api.types import PodCliqueSetTemplateSpec


def build():
    return pcs("aggregated", PodCliqueSetTemplateSpec(
        cliques=[clique("engine", replicas=4, cpu=4.0, memory=8.0, tpu=1.0)],
    ))


if __name__ == "__main__":
    report(run(build()))
