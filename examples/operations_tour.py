"""Operations tour: node disruptions (drain + failure-domain outage), then
the placement SERVICE (the operator/external-scheduler process split) with
live TLS rotation and both introspection surfaces.

Covers the ops features the other examples don't touch:
  - gang-aware node drain and rack-outage recovery (the NodeMonitor;
    docs/operations.md "Node disruptions") — runs fully in-process
  - grove-placement-service with self-managed TLS (CertRotator +
    RotatingTLSServer hot restart; docs/operations.md)
  - RemotePlacementEngine injected as the scheduler's engine
  - harness.debug_dump() and the grove.Placement/Debug health probe
"""

from __future__ import annotations

import json
import socket
from functools import partial

from common import clique, pcs, report, run  # noqa: F401 (shared runner)
from grove_tpu.api.types import PodCliqueSetTemplateSpec


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def node_lifecycle_tour() -> None:
    """Executable doc for the node-lifecycle subsystem: a maintenance
    drain that respects each clique's MinAvailable, then a whole-rack
    outage that the control plane detects, grace-evicts and repairs onto
    healthy domains. Pure in-process — no service dependencies."""
    from grove_tpu.api.types import Node, node_ready
    from grove_tpu.cluster.inventory import RACK_KEY

    workload = pcs("node-tour", PodCliqueSetTemplateSpec(cliques=[
        clique("workers", replicas=6, cpu=1.0),
    ]))
    # short lifecycle windows so the tour's virtual-clock advances stay
    # readable (production defaults: 40s lease / 300s grace / 60s stable)
    harness = run(workload, nodes=8, config={"cluster": {
        "node_lease_duration_seconds": 10.0,
        "pod_eviction_grace_seconds": 20.0,
        "node_stable_ready_seconds": 15.0,
    }})
    cluster = harness.cluster

    def placements():
        return sorted(
            (p.metadata.name, p.node_name)
            for p in harness.store.list("Pod")
        )

    # 1. gang-aware drain: cordon + paced eviction, never dipping the
    # clique below MinAvailable by more than the one pod in flight
    target = placements()[0][1]
    print(f"\nnode lifecycle: draining {target} "
          f"({sum(1 for _, n in placements() if n == target)} pods on it)")
    cluster.drain(target)
    for _ in range(30):
        harness.advance(6.0)
        if cluster.node_drained(target):
            break
    assert cluster.node_drained(target), "drain did not complete"
    evicted = cluster.metrics.counter(
        "grove_node_drain_evictions_total"
    ).total()
    print(f"  drained: {int(evicted)} paced evictions, every pod "
          "re-placed and Ready elsewhere")
    cluster.uncordon(target)

    # 2. failure-domain outage: one rack goes NotReady in one tick; after
    # the eviction grace its pods are swept and repaired onto healthy
    # racks; the rack rides the stable-ready window back in
    rack_of = {
        n.metadata.name: n.metadata.labels[RACK_KEY]
        for n in harness.store.list(Node.KIND)
    }
    victim_rack = rack_of[placements()[0][1]]
    failed = cluster.fail_domain(RACK_KEY, victim_rack)
    harness.settle()
    print(f"  rack outage: {victim_rack} -> nodes {failed} NotReady")
    harness.advance(25.0)  # past pod_eviction_grace_seconds
    survivors = {rack_of[n] for _, n in placements()}
    assert victim_rack not in survivors, survivors
    print(f"  repaired onto healthy racks: {sorted(survivors)}")
    cluster.recover_domain(RACK_KEY, victim_rack)
    harness.advance(1.0)    # first post-recovery heartbeat
    harness.advance(16.0)   # stable-ready window elapses
    back = [
        n.metadata.name
        for n in harness.store.list(Node.KIND)
        if rack_of[n.metadata.name] == victim_rack and node_ready(n)
    ]
    assert sorted(back) == sorted(failed)
    print(f"  rack recovered: {back} Ready again "
          "(after the stable-ready window)")
    dump = harness.debug_dump()
    print(f"  node lifecycle debug: {dump['node_lifecycle']}")


def cold_restart_tour() -> None:
    """Executable doc for the durable state store (docs/operations.md
    "Cold restart & disaster recovery"): run the control plane with a
    write-ahead-logged store, kill the whole process state at steady
    state, and recover from disk — replay, soft-state rebuild, and the
    same fixpoint. Pure in-process — no service dependencies."""
    import tempfile

    from grove_tpu.chaos.harness import settled_fingerprint
    from grove_tpu.cluster.store import ObjectStore

    workload = pcs("restart-tour", PodCliqueSetTemplateSpec(cliques=[
        clique("router", replicas=1, cpu=0.5),
        clique("workers", replicas=4, cpu=1.0),
    ]))
    with tempfile.TemporaryDirectory(prefix="grove-tour-wal-") as wal_dir:
        # 1. a durable control plane: every committed store mutation is
        # WAL-appended; snapshots cut on cadence and bound replay
        harness = run(workload, nodes=8, config={
            "durability": {"wal_dir": wal_dir, "fsync": "never"},
        })
        fixpoint = settled_fingerprint(harness.store)
        wal = harness.cluster.durability.debug_state()
        print(f"\ncold restart: steady state journaled — "
              f"{wal['wal_records_total']} WAL records, "
              f"{wal['wal_bytes_total']} bytes on disk")

        # 2. the disk image alone rebuilds a bit-identical store (what a
        # standalone inspection/repair tool would do)
        recovered = ObjectStore.recover(wal_dir)
        assert settled_fingerprint(recovered) == fixpoint
        print(f"  standalone ObjectStore.recover: "
              f"{recovered.recovery_stats['wal_records_replayed']} records "
              f"replayed -> bit-identical store "
              f"(outcome={recovered.recovery_stats['outcome']})")

        # 3. the full cold restart: drop the live store, recover from
        # disk, re-derive ALL soft state (leases expired, manager +
        # scheduler + kubelet caches rebuilt), settle to the same fixpoint
        stats = harness.cold_restart()
        harness.settle()
        assert settled_fingerprint(harness.store) == fixpoint
        print(f"  harness.cold_restart: outcome={stats['outcome']}, "
              f"replayed {stats['wal_records_replayed']} records, "
              "re-settled to the identical fixpoint")

        # 4. the restarted plane is fully live: new work schedules
        harness.apply(pcs("post-restart", PodCliqueSetTemplateSpec(
            cliques=[clique("w", replicas=2, cpu=0.5)],
        )))
        harness.settle()
        bound = sum(1 for p in harness.store.list("Pod") if p.node_name)
        dump = harness.debug_dump()["store"]["durability"]
        print(f"  post-restart workload bound ({bound} pods total); "
              f"recovery checkpoint at seq {dump['last_snapshot_seq']}")

        # 5. disaster recovery: the crashed process is GONE — a brand-new
        # one boots from the files alone and resumes journaling
        from grove_tpu.controller import Harness

        fixpoint = settled_fingerprint(harness.store)
        harness.cluster.durability.close()
        fresh = Harness.recover({"durability": {"wal_dir": wal_dir,
                                                "fsync": "never"}})
        fresh.settle()
        assert settled_fingerprint(fresh.store) == fixpoint
        print("  Harness.recover: a NEW process booted from the files "
              "alone and reached the identical fixpoint")


def main() -> None:
    node_lifecycle_tour()
    cold_restart_tour()
    try:
        from grove_tpu.service import (
            CertRotator,
            RemotePlacementEngine,
            RotatingTLSServer,
        )
        from grove_tpu.service.tls import make_ca
    except ImportError as exc:
        # the service stack needs grpcio + cryptography; the node
        # lifecycle tour above is dependency-free and already ran
        print(f"\nservice tour skipped (missing optional dependency: "
              f"{exc.name})")
        return
    # 1. the long-lived placement service, TLS from a self-managed CA
    ca_cert, ca_key = make_ca()
    rotator = CertRotator(ca_cert, ca_key, hostname="127.0.0.1")
    address = f"127.0.0.1:{_free_port()}"
    server = RotatingTLSServer(address, rotator)
    server.start()
    try:
        # 2. the control plane, solving THROUGH the service boundary
        workload = pcs("ops-tour", PodCliqueSetTemplateSpec(cliques=[
            clique("router", replicas=1, cpu=0.5),
            clique("workers", replicas=4, cpu=1.0),
        ]))
        harness = run(
            workload,
            nodes=8,
            engine_cls=partial(
                RemotePlacementEngine, address=address,
                root_ca=rotator.bundle.ca_cert,
            ),
        )
        report(harness)

        # 3. introspection: the in-process dump ...
        dump = harness.debug_dump()
        mgr = dump["manager"]["controllers"]
        print("\nintrospection (harness.debug_dump):")
        for name, stats in sorted(mgr.items()):
            print(f"  {name:<24} reconciles={int(stats['reconciles']):>4} "
                  f"p99={stats['duration_seconds']['p99'] * 1000:.1f}ms")
        print(f"  store objects: {dump['store']['objects_by_kind']}")

        # ... and the service's Debug RPC (the health probe)
        import grpc

        creds = grpc.ssl_channel_credentials(
            root_certificates=rotator.bundle.ca_cert
        )
        with grpc.secure_channel(address, creds) as ch:
            svc = json.loads(
                ch.unary_unary("/grove.Placement/Debug")(b"", timeout=10.0)
            )
        print(f"\nservice Debug probe: epochs={list(svc['epochs'])} "
              f"solves={svc['solves_total']}")

        # 4. live certificate rotation: re-issue under the same CA and
        # hot-restart the listener; the next solve reconnects on its own
        import datetime

        # widen the renewal window past the cert's whole validity: renewal
        # is immediately due (the public knob; tests inject now_fn instead)
        rotator.renew_before = datetime.timedelta(
            days=rotator.valid_days + 1
        )
        assert server.maybe_rotate(), "rotation was due"
        harness.apply(pcs("after-rotation", PodCliqueSetTemplateSpec(
            cliques=[clique("w", replicas=2, cpu=0.5)],
        )))
        harness.settle()
        print("\nsolved a new workload through the ROTATED listener "
              f"(rotations={rotator.rotations})")
    finally:
        server.stop(grace=None)


if __name__ == "__main__":
    main()
