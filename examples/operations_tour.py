"""Operations tour: run the control plane against the placement SERVICE
(the operator/external-scheduler process split), rotate its TLS
certificate live, and read both introspection surfaces.

Covers the ops features the other examples don't touch:
  - grove-placement-service with self-managed TLS (CertRotator +
    RotatingTLSServer hot restart; docs/operations.md)
  - RemotePlacementEngine injected as the scheduler's engine
  - harness.debug_dump() and the grove.Placement/Debug health probe
"""

from __future__ import annotations

import json
import socket
from functools import partial

from common import clique, pcs, report, run  # noqa: F401 (shared runner)
from grove_tpu.api.types import PodCliqueSetTemplateSpec
from grove_tpu.service import (
    CertRotator,
    RemotePlacementEngine,
    RotatingTLSServer,
)
from grove_tpu.service.tls import make_ca


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def main() -> None:
    # 1. the long-lived placement service, TLS from a self-managed CA
    ca_cert, ca_key = make_ca()
    rotator = CertRotator(ca_cert, ca_key, hostname="127.0.0.1")
    address = f"127.0.0.1:{_free_port()}"
    server = RotatingTLSServer(address, rotator)
    server.start()
    try:
        # 2. the control plane, solving THROUGH the service boundary
        workload = pcs("ops-tour", PodCliqueSetTemplateSpec(cliques=[
            clique("router", replicas=1, cpu=0.5),
            clique("workers", replicas=4, cpu=1.0),
        ]))
        harness = run(
            workload,
            nodes=8,
            engine_cls=partial(
                RemotePlacementEngine, address=address,
                root_ca=rotator.bundle.ca_cert,
            ),
        )
        report(harness)

        # 3. introspection: the in-process dump ...
        dump = harness.debug_dump()
        mgr = dump["manager"]["controllers"]
        print("\nintrospection (harness.debug_dump):")
        for name, stats in sorted(mgr.items()):
            print(f"  {name:<24} reconciles={int(stats['reconciles']):>4} "
                  f"p99={stats['duration_seconds']['p99'] * 1000:.1f}ms")
        print(f"  store objects: {dump['store']['objects_by_kind']}")

        # ... and the service's Debug RPC (the health probe)
        import grpc

        creds = grpc.ssl_channel_credentials(
            root_certificates=rotator.bundle.ca_cert
        )
        with grpc.secure_channel(address, creds) as ch:
            svc = json.loads(
                ch.unary_unary("/grove.Placement/Debug")(b"", timeout=10.0)
            )
        print(f"\nservice Debug probe: epochs={list(svc['epochs'])} "
              f"solves={svc['solves_total']}")

        # 4. live certificate rotation: re-issue under the same CA and
        # hot-restart the listener; the next solve reconnects on its own
        import datetime

        # widen the renewal window past the cert's whole validity: renewal
        # is immediately due (the public knob; tests inject now_fn instead)
        rotator.renew_before = datetime.timedelta(
            days=rotator.valid_days + 1
        )
        assert server.maybe_rotate(), "rotation was due"
        harness.apply(pcs("after-rotation", PodCliqueSetTemplateSpec(
            cliques=[clique("w", replicas=2, cpu=0.5)],
        )))
        harness.settle()
        print("\nsolved a new workload through the ROTATED listener "
              f"(rotations={rotator.rotations})")
    finally:
        server.stop(grace=None)


if __name__ == "__main__":
    main()
