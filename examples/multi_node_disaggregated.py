"""Multi-node disaggregated serving (multi-node-disaggregated.yaml): the
full Grove shape — prefill and decode each a leader/worker scaling
group, scaled independently. The base gang carries each group's
min_available replicas; further replicas are scaled gangs that never
block the base system."""

from common import clique, pcs, report, run
from grove_tpu.api.types import (
    PodCliqueScalingGroupConfig,
    PodCliqueSetTemplateSpec,
)


def build():
    return pcs("mn-disagg", PodCliqueSetTemplateSpec(
        cliques=[
            clique("pleader", replicas=1, cpu=2.0, memory=4.0),
            clique("pworker", replicas=4, cpu=4.0, memory=8.0, tpu=2.0),
            clique("dleader", replicas=1, cpu=2.0, memory=4.0),
            clique("dworker", replicas=4, cpu=4.0, memory=8.0, tpu=2.0),
        ],
        pod_clique_scaling_group_configs=[
            PodCliqueScalingGroupConfig(
                name="prefill", clique_names=["pleader", "pworker"],
                replicas=2, min_available=1),
            PodCliqueScalingGroupConfig(
                name="decode", clique_names=["dleader", "dworker"],
                replicas=1, min_available=1),
        ],
    ))


if __name__ == "__main__":
    report(run(build(), nodes=64))
