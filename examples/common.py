"""Shared runner for the workload-archetype examples.

Each example mirrors one of the reference's concept-overview samples
(operator/samples/user-guide/concept-overview/*.yaml) re-expressed
against grove_tpu's API, and runs end-to-end on the simulated cluster:
apply -> reconcile -> gang-schedule -> bound, ready pods.
"""

from __future__ import annotations

import sys
from pathlib import Path

# runnable from anywhere: the repo root holds the grove_tpu package
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from grove_tpu.api.meta import ObjectMeta  # noqa: E402
from grove_tpu.api.types import (
    Container,
    PodCliqueSet,
    PodCliqueSetSpec,
    PodCliqueSetTemplateSpec,
    PodCliqueSpec,
    PodCliqueTemplateSpec,
    PodSpec,
)
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness


def clique(name: str, replicas: int, cpu: float = 1.0, memory: float = 2.0,
           tpu: float = 0.0, min_available: int | None = None,
           starts_after: tuple[str, ...] = ()) -> PodCliqueTemplateSpec:
    return PodCliqueTemplateSpec(name=name, spec=PodCliqueSpec(
        replicas=replicas,
        min_available=min_available,
        starts_after=list(starts_after),
        pod_spec=PodSpec(containers=[Container(
            name=name, image="inference-engine:latest",
            resources={"cpu": cpu, "memory": memory, "tpu": tpu},
        )]),
    ))


def pcs(name: str, template: PodCliqueSetTemplateSpec,
        replicas: int = 1) -> PodCliqueSet:
    return PodCliqueSet(metadata=ObjectMeta(name=name),
                        spec=PodCliqueSetSpec(replicas=replicas,
                                              template=template))


def run(workload: PodCliqueSet, nodes: int = 32, **harness_kwargs) -> Harness:
    """harness_kwargs pass through (e.g. engine_cls for the remote
    placement-service engine — see operations_tour.py)."""
    h = Harness(
        nodes=make_nodes(nodes, racks_per_block=4, hosts_per_rack=4),
        **harness_kwargs,
    )
    h.apply(workload)
    h.settle()
    return h


def report(h: Harness) -> None:
    print(f"{'POD':42s} {'NODE':10s} READY")
    for pod in h.store.list("Pod"):
        print(f"{pod.metadata.name:42s} {pod.node_name:10s} "
              f"{pod.status.ready}")
    print()
    print(f"{'PODGANG':34s} {'PHASE':10s} SCORE")
    for gang in h.store.list("PodGang"):
        print(f"{gang.metadata.name:34s} {gang.status.phase.value:10s} "
              f"{gang.status.placement_score}")
