"""Single-node disaggregated serving: prefill and decode as separate
cliques behind a frontend, decode only starting after prefill
(single-node-disaggregated.yaml). One base gang carries all three
roles — they schedule all-or-nothing."""

from common import clique, pcs, report, run
from grove_tpu.api.types import CliqueStartupType, PodCliqueSetTemplateSpec


def build():
    return pcs("disagg", PodCliqueSetTemplateSpec(
        startup_type=CliqueStartupType.EXPLICIT,
        cliques=[
            clique("frontend", replicas=1, cpu=0.5, memory=1.0),
            clique("prefill", replicas=2, cpu=4.0, memory=8.0, tpu=1.0),
            clique("decode", replicas=2, cpu=4.0, memory=8.0, tpu=1.0,
                   starts_after=("prefill",)),
        ],
    ))


if __name__ == "__main__":
    report(run(build()))
