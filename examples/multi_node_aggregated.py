"""Multi-node aggregated serving: one model instance spans a leader and
worker pods (multi-node-aggregated.yaml); the instance is a scaling
group, so adding capacity means whole new leader+workers gangs that
schedule all-or-nothing and pack a rack."""

from common import clique, pcs, report, run
from grove_tpu.api.types import (
    PodCliqueScalingGroupConfig,
    PodCliqueSetTemplateSpec,
    TopologyConstraintSpec,
    TopologyPackConstraintSpec,
)


def build():
    return pcs("multinode", PodCliqueSetTemplateSpec(
        cliques=[
            clique("leader", replicas=1, cpu=2.0, memory=4.0),
            clique("worker", replicas=4, cpu=4.0, memory=8.0, tpu=2.0),
        ],
        pod_clique_scaling_group_configs=[PodCliqueScalingGroupConfig(
            name="instance", clique_names=["leader", "worker"],
            replicas=2, min_available=1,
            topology_constraint=TopologyConstraintSpec(
                pack_constraint=TopologyPackConstraintSpec(preferred="rack"),
            ),
        )],
    ))


if __name__ == "__main__":
    report(run(build()))
