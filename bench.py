#!/usr/bin/env python
"""Benchmark: TPU placement engine vs serial baseline on the stress config.

Stress config (BASELINE.json): a backlog of 8-pod gangs (default 1000) over a
kwok-style simulated cluster (default 5000 nodes, 3-tier block/rack/host
topology). The reference publishes no numbers (BASELINE.md), so the serial
scorer implemented in grove_tpu/solver/serial.py IS the baseline; the north
star is <1 s p99 full-backlog bind latency and >= 20x the serial scorer.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "gangs/sec", "vs_baseline": N, ...}
vs_baseline = serial_wall / engine_wall (speedup; >1 is better than baseline).

Usage: bench.py [--small] [--nodes N] [--gangs G] [--iters K] [--serial-sample S]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import Node, TopologyLevel
from grove_tpu.solver import PlacementEngine, SolverGang, solve_serial
from grove_tpu.topology import default_cluster_topology, encode_topology


def make_cluster(num_nodes: int):
    """3-tier topology: ~16 racks/block, 16 hosts/rack."""
    nodes = []
    i = 0
    while i < num_nodes:
        b, rem = divmod(i, 256)
        r = rem // 16
        nodes.append(
            Node(
                metadata=ObjectMeta(
                    name=f"n{i}",
                    labels={"t/block": f"b{b}", "t/rack": f"b{b}r{r}"},
                ),
                allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0},
            )
        )
        i += 1
    ct = default_cluster_topology(
        [
            TopologyLevel(domain="block", key="t/block"),
            TopologyLevel(domain="rack", key="t/rack"),
        ]
    )
    return encode_topology(ct, nodes)


def make_gangs(num_gangs: int, grouped: bool = False) -> list[SolverGang]:
    """Mixed backlog: plain 8-pod gangs (block-required, rack-preferred) and
    leader/worker gangs whose two groups each pack a rack.

    grouped=True additionally ties each leader/worker pair into a
    CONSTRAINT GROUP (block-required, like a PCSG inside a base gang —
    the reference's disaggregated prefill/decode shape, README.md:38-44)
    and gives the plain gangs a group-preferred rack level; this variant
    proves the native repair covers the full constraint model with zero
    Python fallbacks."""
    gangs = []
    for i in range(num_gangs):
        if i % 4 == 3:
            # leader/worker: 2 groups x 4 pods, each group rack-packed
            demand = np.tile(np.array([4.0, 16.0, 1.0], np.float32), (8, 1))
            gangs.append(
                SolverGang(
                    name=f"gang{i:05d}",
                    namespace="bench",
                    demand=demand,
                    pod_names=[f"gang{i:05d}-p{j}" for j in range(8)],
                    group_ids=np.repeat(np.arange(2, dtype=np.int32), 4),
                    group_names=["leader", "worker"],
                    group_required_level=np.array([1, 1], np.int32),
                    group_preferred_level=np.array([-1, -1], np.int32),
                    required_level=0,
                    constraint_groups=(
                        [([0, 1], 0, 1)] if grouped else []
                    ),
                )
            )
        else:
            demand = np.tile(np.array([4.0, 16.0, 1.0], np.float32), (8, 1))
            gangs.append(
                SolverGang(
                    name=f"gang{i:05d}",
                    namespace="bench",
                    demand=demand,
                    pod_names=[f"gang{i:05d}-p{j}" for j in range(8)],
                    group_ids=np.zeros(8, np.int32),
                    group_names=["workers"],
                    group_required_level=np.array([-1], np.int32),
                    group_preferred_level=np.array(
                        [1 if grouped else -1], np.int32
                    ),
                    required_level=0,
                    preferred_level=1,
                )
            )
    return gangs


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="CPU-friendly quick run (512 nodes, 64 gangs)")
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--gangs", type=int, default=1000)
    ap.add_argument("--iters", type=int, default=9)
    ap.add_argument("--serial-sample", type=int, default=0,
                    help="measure serial baseline on this many gangs and "
                    "extrapolate (0 = run the full backlog serially)")
    ap.add_argument("--sharded", action="store_true",
                    help="run the measured engine as ShardedPlacementEngine "
                    "over a mesh of ALL visible devices (1-device mesh on a "
                    "single chip; virtual CPU mesh under "
                    "xla_force_host_platform_device_count)")
    ap.add_argument("--cp-replicas", type=int, default=1000,
                    help="control-plane bench: PCS replicas driven through "
                    "the FULL path (apply -> pods -> gangs -> scheduler -> "
                    "bound/ready) at the same scale as the solver stress "
                    "config; 0 disables")
    ap.add_argument("--service", action="store_true",
                    help="benchmark the solve THROUGH the placement-service "
                    "gRPC boundary (server spawned as a subprocess on this "
                    "machine's accelerator; measures whether the RPC hop + "
                    "codec amortize at full-backlog batches)")
    args = ap.parse_args()
    if args.service:
        return bench_service(args)
    if args.small:
        args.nodes, args.gangs, args.iters = 512, 64, 3
        args.cp_replicas = min(args.cp_replicas, 20)
        if args.serial_sample == 0:
            args.serial_sample = 32

    snapshot = make_cluster(args.nodes)
    gangs = make_gangs(args.gangs)

    # The engine feeds the in-framework metrics registry (the same one
    # GangScheduler uses); the bench numbers are READ from it rather than
    # re-derived (SURVEY §5 / VERDICT r1 #4).
    from grove_tpu.observability import MetricsRegistry

    if args.sharded:
        from grove_tpu.parallel import ShardedPlacementEngine, make_solver_mesh

        mesh = make_solver_mesh()

        def mk_engine(**kw):
            return ShardedPlacementEngine(snapshot, mesh, **kw)
    else:
        def mk_engine(**kw):
            return PlacementEngine(snapshot, **kw)

    warm = mk_engine()
    warm.solve(gangs)  # warm-up: compile + caches (not recorded)

    registry = MetricsRegistry()
    engine = mk_engine(metrics=registry)
    # Each iteration is one "bind the whole backlog" event.
    placed = 0
    phase_stats: dict[str, list[float]] = {}
    for _ in range(args.iters):
        res = engine.solve(gangs)
        placed = res.num_placed
        for k in ("encode_seconds", "device_seconds", "repair_seconds"):
            phase_stats.setdefault(k, []).append(res.stats.get(k, 0.0))

    bind_h = registry.histogram("grove_solver_backlog_bind_seconds")
    # Throughput (value, vs_baseline) uses the MEDIAN solve wall: through
    # the shared dev tunnel a single congested iteration can triple the
    # max, and p99-of-K IS the max — one hiccup would misreport steady
    # throughput 3x low. The p99 is still reported for BASELINE's <1s
    # latency north star.
    engine_wall = bind_h.percentile(50)
    engine_p99 = bind_h.percentile(99)
    score = registry.histogram("grove_solver_placement_score").mean()
    # counters accumulate across the identical iterations; report per-solve
    fallbacks = int(
        registry.counter("grove_solver_repair_fallbacks_total").total()
        / max(args.iters, 1)
    )

    # Serial baseline on the identical problem. Prefer the native (C++)
    # scorer so the speedup is measured against compiled code; fall back to
    # the Python serial path when no toolchain exists.
    from grove_tpu.native import solve_serial_native

    sample = args.serial_sample or len(gangs)
    serial_runs = []
    baseline = "native-cpp"
    for _ in range(3):  # median-of-3: same noise treatment as the engine
        t0 = time.perf_counter()
        sres = solve_serial_native(snapshot, gangs[:sample])
        if sres is None:
            sres = solve_serial(snapshot, gangs[:sample])
            baseline = "python"
        serial_runs.append(time.perf_counter() - t0)
    serial_sample_wall = sorted(serial_runs)[1]
    serial_wall = serial_sample_wall * (len(gangs) / max(sample, 1))

    # Grouped-constraint variant (VERDICT r3 #3): the same backlog with
    # constraint groups + preferred levels — the native repair must take
    # it (0 fallbacks) at full speed.
    grouped_gangs = make_gangs(args.gangs, grouped=True)
    mk_engine(**{}).solve(grouped_gangs)  # warm-up (new jit shapes possible)
    g_registry = MetricsRegistry()
    g_engine = mk_engine(metrics=g_registry)
    g_placed = 0
    g_iters = max(3, args.iters // 3)
    for _ in range(g_iters):
        g_placed = g_engine.solve(grouped_gangs).num_placed
    g_wall = g_registry.histogram(
        "grove_solver_backlog_bind_seconds"
    ).percentile(50)
    g_fallbacks = int(
        g_registry.counter("grove_solver_repair_fallbacks_total").total()
        / max(g_iters, 1)
    )

    # Scale-ceiling probe (VERDICT r3 #8): one datapoint at 2x the north
    # star (2000 gangs / 10000 nodes) proving the bucketing/padding
    # strategy and memory hold past the stress config.
    probe = {}
    if not args.small and args.nodes >= 5000:
        p_snapshot = make_cluster(args.nodes * 2)
        p_gangs = make_gangs(args.gangs * 2)
        p_engine = PlacementEngine(p_snapshot)  # single-device probe
        p_engine.solve(p_gangs)  # warm-up: new shapes compile
        p_walls = []
        p_placed = 0
        for _ in range(3):
            t0 = time.perf_counter()
            p_placed = p_engine.solve(p_gangs).num_placed
            p_walls.append(time.perf_counter() - t0)
        p_walls.sort()
        probe = {
            "scale2x_nodes": args.nodes * 2,
            "scale2x_gangs": args.gangs * 2,
            "scale2x_placed": p_placed,
            "scale2x_p50_backlog_bind_seconds": round(p_walls[1], 4),
            "scale2x_gangs_per_sec": round(args.gangs * 2 / p_walls[1], 1),
        }

    # Control-plane bench (VERDICT r1 #4): the FULL path — apply one PCS
    # with N replicas of an 8-pod clique against the same-size inventory,
    # reconcile to quiescence (gated pods -> deferred gangs -> scheduler ->
    # bound + ready). Reported warm (second PCS; first pays jit compile).
    cp = {}
    if args.cp_replicas > 0:
        cp = bench_controlplane(args.nodes, args.cp_replicas)

    gangs_per_sec = args.gangs / engine_wall
    out = {
        "metric": f"gang placements/sec ({args.gangs} x 8-pod gangs, "
        f"{args.nodes} nodes, 3-tier topology)",
        "value": round(gangs_per_sec, 1),
        "unit": "gangs/sec",
        "vs_baseline": round(serial_wall / engine_wall, 2),
        # r3 basis change, recorded so BENCH files are self-describing:
        # r1/r2 computed value+vs_baseline from p99 (=max of iters); a
        # single tunnel hiccup misreported steady throughput 3x low
        "throughput_basis": "p50_of_iters",
        "p50_backlog_bind_seconds": round(engine_wall, 4),
        "p99_backlog_bind_seconds": round(engine_p99, 4),
        "serial_baseline_seconds": round(serial_wall, 2),
        "serial_baseline_impl": baseline,
        "serial_sampled_gangs": sample,
        "placed": placed,
        "serial_placed_sampled": sres.num_placed,
        "mean_placement_score": round(score, 4),
        "repair_fallbacks": fallbacks,
        # solve-phase split (p50 across iters): host encode, device
        # score+commit-scan (incl. D2H of the packed top-k), host exact
        # repair — where the next optimization lives is visible, not
        # guessed (VERDICT r3 #2)
        **{
            f"p50_{k}": round(sorted(v)[len(v) // 2], 4)
            for k, v in phase_stats.items()
        },
        "grouped_gangs_per_sec": round(args.gangs / g_wall, 1),
        "grouped_placed": g_placed,
        "grouped_repair_fallbacks": g_fallbacks,
        **probe,
        "backend": __import__("jax").default_backend(),
        "engine": "sharded" if args.sharded else "single",
        **({"mesh": dict(mesh.shape)} if args.sharded else {}),
        **cp,
    }
    print(json.dumps(out))
    return 0


def bench_service(args) -> int:
    """Solve the stress backlog through the gRPC service boundary: the
    server subprocess owns the accelerator; this process only encodes,
    ships, and decodes. SURVEY hard part (d): the RPC hop + host->device
    transfer must amortize over whole-backlog batches — this measures it
    against the in-process engine wall."""
    import os
    import signal
    import subprocess
    import tempfile

    if args.small:
        args.nodes, args.gangs, args.iters = 512, 64, 3

    snapshot = make_cluster(args.nodes)
    gangs = make_gangs(args.gangs)

    sock = os.path.join(tempfile.mkdtemp(), "placement.sock")
    address = f"unix:{sock}"
    proc = subprocess.Popen(
        [sys.executable, "-m", "grove_tpu.service.server",
         "--address", address],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        # scan a few lines for the banner (interpreter warnings may
        # precede it); a dead process means startup failed — surface its
        # output instead of hanging in a blocking read on a live pipe
        seen = []
        for _ in range(10):
            line = proc.stdout.readline()
            seen.append(line)
            if "listening" in line:
                break
            if not line or proc.poll() is not None:
                raise RuntimeError(
                    "placement service failed to start:\n" + "".join(seen)
                )
        else:
            proc.send_signal(signal.SIGTERM)
            raise RuntimeError(
                "placement service never reported listening:\n"
                + "".join(seen)
            )
        from grove_tpu.service import RemotePlacementEngine
        from grove_tpu.service.codec import encode_solve_request

        engine = RemotePlacementEngine(snapshot, address)
        engine.solve(gangs)  # warm-up: server-side compile + caches
        walls = []
        placed = 0
        for _ in range(args.iters):
            t0 = time.perf_counter()
            result = engine.solve(gangs)
            walls.append(time.perf_counter() - t0)
            placed = result.num_placed
        walls.sort()
        p99 = walls[min(len(walls) - 1, int(round(0.99 * (len(walls) - 1))))]
        wire = len(encode_solve_request(
            engine.epoch, gangs, snapshot.free.copy()))
        out = {
            "metric": f"gang placements/sec over the gRPC service boundary "
            f"({args.gangs} x 8-pod gangs, {args.nodes} nodes)",
            "value": round(args.gangs / p99, 1),
            "unit": "gangs/sec",
            "vs_baseline": 0.0,  # no serial comparison in service mode
            "p99_backlog_bind_seconds": round(p99, 4),
            "p50_backlog_bind_seconds": round(walls[len(walls) // 2], 4),
            "placed": placed,
            "request_bytes": wire,
            "engine": "service",
        }
        print(json.dumps(out))
        return 0
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            # a lingering server holds the accelerator and poisons the
            # next run's device acquisition (advisor r3)
            proc.kill()
            proc.wait(timeout=10)


def bench_controlplane(num_nodes: int, replicas: int) -> dict:
    from grove_tpu.api.meta import ObjectMeta as Meta
    from grove_tpu.api.types import (
        Container,
        Pod,
        PodCliqueSet,
        PodCliqueSetSpec,
        PodCliqueSetTemplateSpec,
        PodCliqueSpec,
        PodCliqueTemplateSpec,
        PodSpec,
    )
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness

    def pcs(name):
        return PodCliqueSet(
            metadata=Meta(name=name),
            spec=PodCliqueSetSpec(
                replicas=replicas,
                template=PodCliqueSetTemplateSpec(
                    cliques=[
                        PodCliqueTemplateSpec(
                            name="w",
                            spec=PodCliqueSpec(
                                replicas=8,
                                pod_spec=PodSpec(
                                    containers=[
                                        Container(
                                            name="m", resources={"cpu": 1.0}
                                        )
                                    ]
                                ),
                            ),
                        )
                    ]
                ),
            ),
        )

    h = Harness(
        nodes=make_nodes(
            num_nodes,
            allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0},
        )
    )
    t0 = time.perf_counter()
    h.apply(pcs("cpwarm"))
    h.settle()
    cold = time.perf_counter() - t0
    # production process posture for the warm measurement (and for the
    # real server, service/server.py:main): freeze the steady-state object
    # graph, stop paying ~630 stop-the-world GC runs per settle
    from grove_tpu.tuning import tune_gc

    tune_gc()
    solve_h = h.cluster.metrics.histogram("grove_solver_backlog_bind_seconds")
    solve_before = solve_h.sum
    t0 = time.perf_counter()
    h.apply(pcs("cpbench"))
    h.settle()
    warm = time.perf_counter() - t0
    # solver-vs-controllers attribution: how much of the warm settle was
    # accelerator solve wall (the rest is the host-side control plane —
    # store writes, watch fan-out, reconciles; see BASELINE.md)
    solve_wall = solve_h.sum - solve_before
    bound = sum(1 for p in h.store.scan(Pod.KIND) if p.node_name)
    if bound != 2 * replicas * 8:  # not assert: must survive python -O
        raise RuntimeError(
            f"controlplane bench invalid: {bound} pods bound, "
            f"expected {2 * replicas * 8}"
        )
    return {
        "controlplane_replicas": replicas,
        "controlplane_settle_seconds": round(warm, 2),
        "controlplane_cold_settle_seconds": round(cold, 2),
        "controlplane_gangs_per_sec": round(replicas / warm, 1),
        "controlplane_solve_seconds": round(solve_wall, 3),
        "controlplane_host_seconds": round(warm - solve_wall, 3),
    }


if __name__ == "__main__":
    sys.exit(main())
