#!/usr/bin/env python
"""Benchmark: TPU placement engine vs serial baseline on the stress config.

Stress config (BASELINE.json): a backlog of 8-pod gangs (default 1000) over a
kwok-style simulated cluster (default 5000 nodes, 3-tier block/rack/host
topology). The reference publishes no numbers (BASELINE.md), so the serial
scorer implemented in grove_tpu/solver/serial.py IS the baseline; the north
star is <1 s p99 full-backlog bind latency and >= 20x the serial scorer.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "gangs/sec", "vs_baseline": N, ...}
vs_baseline = serial_wall / engine_wall (speedup; >1 is better than baseline).

Usage: bench.py [--small] [--nodes N] [--gangs G] [--iters K] [--serial-sample S]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import Node, TopologyLevel
from grove_tpu.solver import PlacementEngine, SolverGang, solve_serial
from grove_tpu.topology import default_cluster_topology, encode_topology


def make_cluster(num_nodes: int):
    """3-tier topology: ~16 racks/block, 16 hosts/rack."""
    nodes = []
    i = 0
    while i < num_nodes:
        b, rem = divmod(i, 256)
        r = rem // 16
        nodes.append(
            Node(
                metadata=ObjectMeta(
                    name=f"n{i}",
                    labels={"t/block": f"b{b}", "t/rack": f"b{b}r{r}"},
                ),
                allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0},
            )
        )
        i += 1
    ct = default_cluster_topology(
        [
            TopologyLevel(domain="block", key="t/block"),
            TopologyLevel(domain="rack", key="t/rack"),
        ]
    )
    return encode_topology(ct, nodes)


def make_tier_cluster(num_nodes: int):
    """Synthetic 4-level topology for the --scale-tier regimes:
    zone (4096 nodes) > block (256) > rack (16) > host. At 100k nodes
    this is ~25 zones / ~391 blocks / 6250 racks — the shape whose flat
    [G, D] cost tensor (D ~ 107k with the per-node host level) is
    infeasible to materialize, which is exactly what the hierarchical
    solve exists for."""
    nodes = []
    for i in range(num_nodes):
        z, zr = divmod(i, 4096)
        b = zr // 256
        r = (zr % 256) // 16
        nodes.append(
            Node(
                metadata=ObjectMeta(
                    name=f"n{i}",
                    labels={
                        "t/zone": f"z{z}",
                        "t/block": f"z{z}b{b}",
                        "t/rack": f"z{z}b{b}r{r}",
                    },
                ),
                allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0},
            )
        )
    ct = default_cluster_topology(
        [
            TopologyLevel(domain="zone", key="t/zone"),
            TopologyLevel(domain="block", key="t/block"),
            TopologyLevel(domain="rack", key="t/rack"),
        ]
    )
    return encode_topology(ct, nodes)


def make_tier_gangs(num_gangs: int) -> list[SolverGang]:
    """Block-confined 8-pod gangs (required block, preferred rack) for
    the tier regimes — the gang-packing shape the reference's workloads
    carry, and what confines the backlog so the hierarchy can prune at
    the block level."""
    gangs = []
    demand = np.tile(np.array([4.0, 16.0, 1.0], np.float32), (8, 1))
    for i in range(num_gangs):
        gangs.append(
            SolverGang(
                name=f"tier{i:06d}",
                namespace="bench",
                demand=demand,
                pod_names=[f"tier{i:06d}-p{j}" for j in range(8)],
                group_ids=np.zeros(8, np.int32),
                group_names=["workers"],
                group_required_level=np.array([-1], np.int32),
                group_preferred_level=np.array([-1], np.int32),
                required_level=1,
                preferred_level=2,
            )
        )
    return gangs


def make_gangs(num_gangs: int, grouped: bool = False) -> list[SolverGang]:
    """Mixed backlog: plain 8-pod gangs (block-required, rack-preferred) and
    leader/worker gangs whose two groups each pack a rack.

    grouped=True additionally ties each leader/worker pair into a
    CONSTRAINT GROUP (block-required, like a PCSG inside a base gang —
    the reference's disaggregated prefill/decode shape, README.md:38-44)
    and gives the plain gangs a group-preferred rack level; this variant
    proves the native repair covers the full constraint model with zero
    Python fallbacks."""
    gangs = []
    for i in range(num_gangs):
        if i % 4 == 3:
            # leader/worker: 2 groups x 4 pods, each group rack-packed
            demand = np.tile(np.array([4.0, 16.0, 1.0], np.float32), (8, 1))
            gangs.append(
                SolverGang(
                    name=f"gang{i:05d}",
                    namespace="bench",
                    demand=demand,
                    pod_names=[f"gang{i:05d}-p{j}" for j in range(8)],
                    group_ids=np.repeat(np.arange(2, dtype=np.int32), 4),
                    group_names=["leader", "worker"],
                    group_required_level=np.array([1, 1], np.int32),
                    group_preferred_level=np.array([-1, -1], np.int32),
                    required_level=0,
                    constraint_groups=(
                        [([0, 1], 0, 1)] if grouped else []
                    ),
                )
            )
        else:
            demand = np.tile(np.array([4.0, 16.0, 1.0], np.float32), (8, 1))
            gangs.append(
                SolverGang(
                    name=f"gang{i:05d}",
                    namespace="bench",
                    demand=demand,
                    pod_names=[f"gang{i:05d}-p{j}" for j in range(8)],
                    group_ids=np.zeros(8, np.int32),
                    group_names=["workers"],
                    group_required_level=np.array([-1], np.int32),
                    group_preferred_level=np.array(
                        [1 if grouped else -1], np.int32
                    ),
                    required_level=0,
                    preferred_level=1,
                )
            )
    return gangs


def p50(walls: list[float]) -> float:
    """Median by the bench's nearest-rank convention (upper median)."""
    return sorted(walls)[len(walls) // 2]


def wall_stats(walls: list[float], prefix: str = "",
               suffix: str = "seconds", round_to: int = 4) -> dict:
    """min/median/max summary of one interleaved-A/B side — the shared
    bench-noise discipline: this host's throttling swings walls ~2x
    run-to-run, so a single uninterleaved number misleads and every
    probe reports the range."""
    s = sorted(walls)
    return {
        f"{prefix}p50_{suffix}": round(s[len(s) // 2], round_to),
        f"{prefix}min_{suffix}": round(s[0], round_to),
        f"{prefix}max_{suffix}": round(s[-1], round_to),
    }


def interleaved_ab(measure_a, measure_b, repeats: int, *more) -> tuple:
    """The interleaved A/B loop every comparative regime shares: each
    repeat times side A then side B BACK-TO-BACK, so a host-load burst
    lands on both sides of the pair — the reported speedup (a ratio of
    p50s over interleaved samples) is far more stable than two
    separately measured medians. The callables take the repeat index;
    whatever they return is collected per side (None returns are the
    caller's skip convention). Extra sides (`*more`) join the same
    per-repeat interleave — an A/B/C regime (e.g. the scale tier's
    wave/serial/flat triple) keeps every side under the same load
    bursts."""
    sides = (measure_a, measure_b, *more)
    samples = tuple([] for _ in sides)
    for i in range(repeats):
        for fn, out in zip(sides, samples):
            out.append(fn(i))
    return samples


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="CPU-friendly quick run (512 nodes, 64 gangs)")
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--gangs", type=int, default=1000)
    ap.add_argument("--iters", type=int, default=9)
    ap.add_argument("--serial-sample", type=int, default=0,
                    help="measure serial baseline on this many gangs and "
                    "extrapolate (0 = run the full backlog serially)")
    ap.add_argument("--sharded", action="store_true",
                    help="run the measured engine as ShardedPlacementEngine "
                    "over a mesh of ALL visible devices (1-device mesh on a "
                    "single chip; virtual CPU mesh under "
                    "xla_force_host_platform_device_count)")
    ap.add_argument("--engine", choices=("fused", "delta", "full", "pallas"),
                    default="fused",
                    help="solve-path regime of the measured engine: "
                    "'fused' (the default, the deployed configuration) "
                    "runs the single-dispatch fused program — staged "
                    "free-state delta + gang inputs in one buffer, one "
                    "launch, one D2H — on top of the device-resident "
                    "state; 'delta' is the split (pre-fused) dispatch "
                    "discipline with the state cache on; 'full' "
                    "additionally disables the cache so every solve "
                    "re-ships the full [N, R] matrix. The measured "
                    "engines run with the incremental re-solve OFF (a "
                    "repeated identical backlog would degenerate into "
                    "the zero-dispatch reuse tier); the incremental "
                    "dirty-tick probes below measure it explicitly; "
                    "'pallas' is the fused regime with the Pallas "
                    "scoring kernel + on-device commit forced on "
                    "(interpret-lowered off-TPU) and adds an "
                    "interleaved kernel-vs-XLA device-seconds A/B")
    ap.add_argument("--equivalence", action="store_true",
                    help="instead of benchmarking, solve every scenario "
                    "(plain, grouped, dispatch/adopt + staled dispatch, "
                    "a seeded bind/unbind churn sweep, fairness, and the "
                    "incremental suite: seeded churn dirtying 1/3/all "
                    "gangs, dispatch-adoption under a dirty tick, rebind "
                    "mid-stream) with the delta, fused and "
                    "fused+incremental engines AGAINST the full-re-encode "
                    "reference and exit nonzero on any placement "
                    "divergence — every path must be bit-identical. Also "
                    "gates the hierarchical tier (score-equal vs flat) "
                    "and the WAVE-PARALLEL fine-solve driver (bitwise "
                    "equal to the serial workers=0 path across memo "
                    "replays, dirty ticks, churn and a fail/recover "
                    "rebind)")
    ap.add_argument("--churn-rate", type=float, default=300.0,
                    help="sustained-churn bench: offered gang arrival "
                    "rate (gangs/sec) against the warm control plane; "
                    "chosen inside the plane's measured ~400/s capacity "
                    "so the p99 reflects steady-state latency, not "
                    "unbounded overload queueing")
    ap.add_argument("--churn-duration", type=float, default=60.0,
                    help="sustained-churn bench: virtual seconds of "
                    "steady arrival (0 disables)")
    ap.add_argument("--cp-replicas", type=int, default=1000,
                    help="control-plane bench: PCS replicas driven through "
                    "the FULL path (apply -> pods -> gangs -> scheduler -> "
                    "bound/ready) at the same scale as the solver stress "
                    "config; 0 disables")
    ap.add_argument("--shards", type=int, default=1,
                    help="control-plane bench: ALSO measure the "
                    "horizontally sharded control plane with N worker "
                    "replicas (controller/sharding.py). Reports the "
                    "modeled parallel throughput "
                    "(controlplane_sharded_gangs_per_sec: serial residue "
                    "+ the slowest worker's wall — what N separate "
                    "processes would see, since workers share nothing "
                    "but the store), the per-shard settle skew, and a "
                    "failover probe (kill the scheduler-owning worker "
                    "mid-settle, measure virtual seconds to "
                    "re-convergence — bounded by one shard lease "
                    "duration). 1 disables")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="profile this run: write a Chrome trace-event "
                    "JSON (Perfetto / chrome://tracing loadable) with the "
                    "engine's encode/device/repair spans and the "
                    "control-plane benches' reconcile/solve spans. "
                    "Composes with --stream (each rung's stream/round "
                    "sides land as their own Perfetto process, with the "
                    "fleet critical-path breakdown in the JSON and the "
                    "telescoping regression gate on the exit code) and "
                    "with --scale-tier (the wave and serial engines' "
                    "coarse/fine spans plus causal flow arrows). Tracing "
                    "adds a little overhead — leave unset for record "
                    "runs (see docs/observability.md)")
    ap.add_argument("--aggregate-overhead", action="store_true",
                    help="add the always-on tracing tax probe: the "
                    "controlplane settle workload with tracing OFF vs "
                    "tracing.mode=aggregate (span ring skipped, bounded "
                    "critical-path sketches only), interleaved p50; "
                    "exits nonzero above the 5%% acceptance bound or if "
                    "the aggregate side folded zero paths. A wall-ratio "
                    "gate flakes on throttling hosts, so it only arms "
                    "when this flag is passed explicitly")
    ap.add_argument("--tenants", type=int, default=0,
                    help="multi-tenant sustained-churn regime: drive a "
                    "Zipf-skewed gang arrival stream across N tenant "
                    "queues (quota + DRF fairness enabled) and assert "
                    "the north-star fairness contract — zero starved "
                    "tenants and bounded max fairness error "
                    "(|dominant share - entitlement| over burst-eligible "
                    "tenants). 0 disables; the ROADMAP regime is "
                    "--tenants 50")
    ap.add_argument("--stream", action="store_true",
                    help="streaming-admission A/B regime: max sustained "
                    "gang arrival rate (gangs/sec) whose p99 bind "
                    "latency stays under --stream-slo, under Poisson "
                    "arrivals with periodic 10x bursts — the streaming "
                    "admission front (micro-batch windows + deadline-"
                    "budget shedding, grove_tpu/streaming) vs classic "
                    "round-based draining on the identical arrival "
                    "schedule, over a 1x/2x/4x rate ladder; exits "
                    "nonzero when the stream side misses the SLO at the "
                    "base rate or sustains less than round-draining")
    ap.add_argument("--stream-slo", type=float, default=2.0,
                    help="--stream: declared p99 bind-latency SLO in "
                    "wall seconds over ADMITTED binds (sheds are "
                    "structured refusals, reported separately)")
    ap.add_argument("--fairness-bound", type=float, default=0.1,
                    help="--tenants: max tolerated fairness error as a "
                    "fraction of cluster dominant capacity (exit 1 "
                    "above it)")
    ap.add_argument("--diurnal", action="store_true",
                    help="elastic-serving bench regime (ROADMAP item 4): "
                    "drive a multi-hour virtual diurnal traffic trace "
                    "(10x load swing + spikes, prefill/decode/router "
                    "disaggregated tiers) through the FULL control plane "
                    "— kubelet metrics reporting -> HPA sync -> scale "
                    "subresource -> scaled-PodGang create/delete -> "
                    "reservation-reuse placement — reporting end-to-end "
                    "scale-up latency (demand step -> capacity restored, "
                    "p50/p99 virtual seconds), placement-score drift "
                    "across the day, reservation-reuse hit rate and "
                    "starved-interval count, with an interleaved "
                    "reuse-on/reuse-off A/B. Exits nonzero on any "
                    "starved interval or a zero reuse hit rate")
    ap.add_argument("--diurnal-hours", type=float, default=3.0,
                    help="--diurnal: virtual hours of trace (two full "
                    "diurnal cycles span the run, so troughs scale the "
                    "fleet down and the second ramp re-places onto "
                    "remembered reservations); --small clamps to 2.0")
    ap.add_argument("--scale-tier", choices=("20k", "100k"), default=None,
                    help="hierarchical scale-tier regime (ROADMAP item 1): "
                    "solve a block-confined backlog over a synthetic "
                    "4-level topology (zone/block/rack/host; 20k nodes / "
                    "4k gangs or 100k nodes / 20k gangs) with the "
                    "HIERARCHICAL two-level engine — coarse block-level "
                    "pruning + per-domain sub-solves with shard-local "
                    "incrementality — reporting p50/min/max backlog-bind "
                    "over dirty-tick repeats plus the dispatch-kind "
                    "counters proving the incremental tier ran. "
                    "Interleaved A/B against the flat engine where the "
                    "flat cost tensor is still materializable (20k); at "
                    "100k the flat side is reported as skipped — its "
                    "[G, D] tensor alone is tens of GB, which is the "
                    "ceiling this regime exists to break. Combine with "
                    "--sharded for the mesh path; exits nonzero if the "
                    "incremental tier never ran shard-locally")
    ap.add_argument("--wave-workers", type=int, default=None,
                    help="--scale-tier: hier_parallel_workers of the "
                    "measured hierarchical engine (wave-parallel fine "
                    "solves: dispatch-all then collect-in-order across "
                    "domains). Default None = the engine's auto "
                    "resolution (host cores, widened to the mesh's "
                    "local device fan-out under --sharded); 0 pins the "
                    "serial one-domain-at-a-time fine phase. The A/B "
                    "side at workers=0 is always measured alongside")
    ap.add_argument("--tier-repeats", type=int, default=5,
                    help="--scale-tier: dirty-tick repeats per side "
                    "(min/median/max reported; this host's throttling "
                    "swings walls ~2x run-to-run, so single numbers "
                    "mislead)")
    ap.add_argument("--recovery", action="store_true",
                    help="add the cold-restart recovery probe: run the "
                    "control-plane workload with the durable store "
                    "(WAL + snapshots in a temp dir), kill the process "
                    "state at steady state, and report recovery_seconds "
                    "(disk replay + soft-state rebuild + re-settle to "
                    "the same fixpoint), plus the same probe on the "
                    "PARTITIONED store (--partitions K) reporting "
                    "recovery_partitioned_seconds — the merged "
                    "per-partition replay path")
    ap.add_argument("--store-bench", action="store_true",
                    help="durable-store write-path regime (ROADMAP item "
                    "4a): committed-write throughput of the PARTITIONED "
                    "write path (per-(namespace, kind) WAL chains, "
                    "--partitions K) vs the classic single WAL, both "
                    "under the --shards N fanned control-plane "
                    "workload, interleaved A/B with min/median/max "
                    "(this host's throttling swings walls ~2x "
                    "run-to-run). The partitioned side reports the "
                    "modeled parallel commit wall (max per-partition "
                    "wall — partitions commit to independent files, so "
                    "a real deployment overlaps them) next to the "
                    "in-process sum; exits nonzero if the writes never "
                    "actually spread past one partition")
    ap.add_argument("--partitions", type=int, default=4,
                    help="--store-bench / --recovery: durable write-path "
                    "partition count for the partitioned side "
                    "(DurabilityConfig.partitions; default 4)")
    ap.add_argument("--replication", action="store_true",
                    help="HA object-store failover regime (ROADMAP item "
                    "4b): run the fanned control-plane workload on the "
                    "durable store with a SEMI-SYNC log-shipping "
                    "standby, model total leader loss (host AND disk: "
                    "no final catch-up) and measure "
                    "failover-to-standby seconds (promote + settle) "
                    "against the cold-restart recovery seconds of the "
                    "SAME workload, interleaved A/B with min/median/"
                    "max; asserts ZERO committed-write loss (promoted "
                    "store seq + fingerprint against the leader's "
                    "committed history). Also reports replication lag "
                    "p50/p99 under --shards N fanned load (async "
                    "bounded-lag mode) and the semi-sync "
                    "commit-throughput tax vs async. Exits nonzero on "
                    "any lost write, a failover median not beating the "
                    "cold-restart median, or a vacuous run")
    ap.add_argument("--federation", action="store_true",
                    help="Multi-cluster federation regime "
                    "(grove_tpu/federation): the same fanned workload "
                    "settled on one 3N-node cluster vs routed across a "
                    "3-member federation of N-node clusters, "
                    "interleaved A/B with min/median/max. Members "
                    "share nothing, so the modeled federation wall is "
                    "the routing wall plus the SLOWEST member's settle "
                    "wall — the near-linear-throughput claim under "
                    "test. Exits nonzero on a vacuous spread (the "
                    "workload never lands on >= 2 members) or a "
                    "modeled speedup <= 1.0")
    ap.add_argument("--defrag", action="store_true",
                    help="continuous-defragmentation bench regime (ROADMAP "
                    "item 3): drive a LONG-CHURN gang arrival/departure "
                    "stream that fragments free capacity across racks, "
                    "with the defragmenter ON vs OFF interleaved step by "
                    "step, and gate the contract — placement-score drift "
                    "held within --defrag-band with defrag on while the "
                    "off side monotonically degrades, migration cost "
                    "(evictions/hour) under the configured bound, "
                    "make-before-break hit rate reported, and ZERO full "
                    "re-encodes attributable to defrag sweeps in the "
                    "steady-state window (what-if dispatch attribution). "
                    "Exits nonzero on any violated bound or a vacuous A/B")
    ap.add_argument("--defrag-hours", type=float, default=2.0,
                    help="--defrag: virtual hours of churn (default 2)")
    ap.add_argument("--defrag-band", type=float, default=0.05,
                    help="--defrag: max tolerated on-side placement-score "
                    "drift (initial window mean - final window mean)")
    ap.add_argument("--service", action="store_true",
                    help="benchmark the solve THROUGH the placement-service "
                    "gRPC boundary (server spawned as a subprocess on this "
                    "machine's accelerator; measures whether the RPC hop + "
                    "codec amortize at full-backlog batches)")
    args = ap.parse_args()
    # persistent XLA compilation cache: repeat bench runs (and any other
    # grove_tpu process on this machine) skip the 10-20 s stress-shape
    # compiles; the cold-settle field reflects a warm cache when one
    # exists, which IS the deployed steady state (see tuning.py)
    from grove_tpu.tuning import enable_compilation_cache

    enable_compilation_cache()
    if args.stream:
        return bench_stream(args)
    if args.store_bench:
        return bench_store(args)
    if args.replication:
        return bench_replication(args)
    if args.federation:
        return bench_federation(args)
    if args.scale_tier:
        return bench_scale_tier(args)
    if args.diurnal:
        return bench_diurnal(args)
    if args.defrag:
        return bench_defrag(args)
    if args.service:
        if args.trace:
            ap.error("--trace is not supported with --service: the span "
                     "tracer is in-process and the service bench drives "
                     "the solver behind gRPC (trace the in-process paths "
                     "without --service)")
        return bench_service(args)
    if args.small:
        args.nodes, args.gangs, args.iters = 512, 64, 3
        args.cp_replicas = min(args.cp_replicas, 20)
        # clamps are LOUD: a capped churn run must not read as a full one
        # (the JSON reports the clamped rate with no other trace)
        if args.churn_rate > 20.0:
            print(
                f"bench --small: clamping --churn-rate "
                f"{args.churn_rate:g} -> 20.0 gangs/s",
                file=sys.stderr,
            )
        args.churn_rate = min(args.churn_rate, 20.0)
        if args.churn_duration > 3.0:
            print(
                f"bench --small: clamping --churn-duration "
                f"{args.churn_duration:g} -> 3.0 virtual seconds",
                file=sys.stderr,
            )
        args.churn_duration = min(args.churn_duration, 3.0)
        if args.serial_sample == 0:
            args.serial_sample = 32

    if args.tenants > 0:
        return bench_tenants(args)

    snapshot = make_cluster(args.nodes)
    gangs = make_gangs(args.gangs)

    # The engine feeds the in-framework metrics registry (the same one
    # GangScheduler uses); the bench numbers are READ from it rather than
    # re-derived (SURVEY §5 / VERDICT r1 #4).
    from grove_tpu.observability import MetricsRegistry

    state_cache = args.engine != "full"
    fused = args.engine in ("fused", "pallas")
    # the pallas regime is the fused discipline with the kernel tiers
    # forced on (the flat sharded mesh ignores them — its shard_map
    # program is a documented capability miss)
    pallas_knobs = (
        {"pallas_core": True, "device_commit": True}
        if args.engine == "pallas" else {}
    )
    if args.sharded:
        from grove_tpu.parallel import ShardedPlacementEngine, make_solver_mesh

        mesh = make_solver_mesh()

        def mk_engine(**kw):
            kw.setdefault("state_cache", state_cache)
            kw.setdefault("fused", fused)
            kw.setdefault("incremental", False)
            for k, v in pallas_knobs.items():
                kw.setdefault(k, v)
            return ShardedPlacementEngine(snapshot, mesh, **kw)
    else:
        def mk_engine(**kw):
            kw.setdefault("state_cache", state_cache)
            kw.setdefault("fused", fused)
            kw.setdefault("incremental", False)
            for k, v in pallas_knobs.items():
                kw.setdefault(k, v)
            return PlacementEngine(snapshot, **kw)

    if args.equivalence:
        return bench_equivalence(args, snapshot, gangs, mk_engine)

    warm = mk_engine()
    warm.solve(gangs)  # warm-up: compile + caches (not recorded)

    #: --trace: {group label -> Tracer} for the offline Chrome trace;
    #: each bench section lands as its own Perfetto process, and passing
    #: the Tracer (not its span list) lets chrome_trace align the
    #: sections' private perf_counter epochs onto one real time axis
    trace_groups: dict = {}
    tracer = None
    if args.trace:
        from grove_tpu.observability.tracing import Tracer

        tracer = Tracer()
        trace_groups["solver"] = tracer

    registry = MetricsRegistry()
    engine = mk_engine(
        metrics=registry, **({"tracer": tracer} if tracer else {})
    )
    # Each iteration is one "bind the whole backlog" event.
    placed = 0
    phase_stats: dict[str, list[float]] = {}
    for _ in range(args.iters):
        res = engine.solve(gangs)
        placed = res.num_placed
        for k in ("encode_seconds", "device_seconds", "repair_seconds"):
            phase_stats.setdefault(k, []).append(res.stats.get(k, 0.0))

    bind_h = registry.histogram("grove_solver_backlog_bind_seconds")
    # Throughput (value, vs_baseline) uses the MEDIAN solve wall: through
    # the shared dev tunnel a single congested iteration can triple the
    # max, and p99-of-K IS the max — one hiccup would misreport steady
    # throughput 3x low. The p99 is still reported for BASELINE's <1s
    # latency north star.
    engine_wall = bind_h.percentile(50)
    engine_p99 = bind_h.percentile(99)
    score = registry.histogram("grove_solver_placement_score").mean()
    # counters accumulate across the identical iterations; report per-solve
    fallbacks = int(
        registry.counter("grove_solver_repair_fallbacks_total").total()
        / max(args.iters, 1)
    )

    # Serial baseline on the identical problem. Prefer the native (C++)
    # scorer so the speedup is measured against compiled code; fall back to
    # the Python serial path when no toolchain exists.
    from grove_tpu.native import solve_serial_native

    sample = args.serial_sample or len(gangs)
    serial_runs = []
    baseline = "native-cpp"
    for _ in range(3):  # median-of-3: same noise treatment as the engine
        t0 = time.perf_counter()
        sres = solve_serial_native(snapshot, gangs[:sample])
        if sres is None:
            sres = solve_serial(snapshot, gangs[:sample])
            baseline = "python"
        serial_runs.append(time.perf_counter() - t0)
    serial_sample_wall = sorted(serial_runs)[1]
    serial_wall = serial_sample_wall * (len(gangs) / max(sample, 1))

    # Grouped-constraint variant (VERDICT r3 #3): the same backlog with
    # constraint groups + preferred levels — the native repair must take
    # it (0 fallbacks) at full speed.
    grouped_gangs = make_gangs(args.gangs, grouped=True)
    mk_engine(**{}).solve(grouped_gangs)  # warm-up (new jit shapes possible)
    g_registry = MetricsRegistry()
    g_engine = mk_engine(metrics=g_registry)
    g_placed = 0
    g_iters = max(3, args.iters // 3)
    for _ in range(g_iters):
        g_placed = g_engine.solve(grouped_gangs).num_placed
    g_wall = g_registry.histogram(
        "grove_solver_backlog_bind_seconds"
    ).percentile(50)
    g_fallbacks = int(
        g_registry.counter("grove_solver_repair_fallbacks_total").total()
        / max(g_iters, 1)
    )

    # Pipelined throughput: the dispatch/adopt API (engine.dispatch ->
    # solve(dispatch=...)) lets a steady-arrival operator overlap solve
    # k+1's device phase + result transfer with solve k's host repair,
    # so the per-solve cost approaches max(transport, host) instead of
    # their sum. This is the sustained-stream regime; the blocking
    # p50/p99 above remain the single-backlog LATENCY numbers. Runs on
    # the metrics-free warm engine so the bind histogram stays clean.
    pipe_iters = max(5, args.iters)
    handle = warm.dispatch(gangs, free=snapshot.free.copy())
    pipe_adopted = 0
    pipe_walls = []
    for _ in range(pipe_iters):
        # each call gets its own pristine copy (solve's repair phase
        # mutates the matrix it is handed); with the state cache on, the
        # sync recognizes the content as unchanged and the adoption guard
        # is the O(1) epoch compare — free0 no longer rides the handle
        t0 = time.perf_counter()
        nxt = warm.dispatch(gangs, free=snapshot.free.copy())
        pr = warm.solve(gangs, free=snapshot.free.copy(), dispatch=handle)
        pipe_walls.append(time.perf_counter() - t0)
        if pr.stats.get("dispatch_overlap"):
            pipe_adopted += 1
        handle = nxt
    pipe_wall = sorted(pipe_walls)[len(pipe_walls) // 2]
    warm.solve(gangs, free=snapshot.free.copy(), dispatch=handle)  # drain
    # EVERY iteration must have adopted its in-flight dispatch, else the
    # wall mixes synchronous solves and the number is not pipelined;
    # pipelined_adopted_iters is always emitted so a 0.0 throughput is
    # distinguishable from "bench not run"
    if pipe_adopted != pipe_iters:
        pipe_wall = 0.0

    # Device free-state upload accounting across the measured iters (read
    # BEFORE measure_device_split, whose probe syncs would inflate the
    # counters): the warm path of a steady-arrival operator should show
    # one full upload at engine birth and small row deltas per solve.
    ds = engine.debug_summary()["device_state"]

    # Device compute-vs-transport split (VERDICT r4 #3): dispatch-to-
    # dispatch over K iterations isolates device compute from the dev
    # tunnel's fixed round-trip latency, making the co-located projection
    # reproducible from shipped JSON instead of prose. mode follows the
    # engine regime: "warm" is the resident free state's steady-state hit
    # path (the headline transport number); an --engine full run measures
    # mode="full" so its transport includes the per-solve free re-encode
    # that regime actually pays — the whole point of the A/B.
    split = engine.measure_device_split(
        gangs, mode="full" if args.engine == "full" else "warm"
    )
    split["full_uploads"] = ds["full_uploads"]
    split["delta_uploads"] = ds["delta_uploads"]
    split["state_sync_hits"] = ds["hits"]
    split["state_cache_enabled"] = ds["cache_enabled"]
    phase_p50 = {k: p50(v) for k, v in phase_stats.items()}
    colocated_wall = (
        phase_p50["encode_seconds"]
        + split["device_compute_seconds"]
        + phase_p50["repair_seconds"]
    )
    split["colocated_projection_gangs_per_sec"] = round(
        args.gangs / colocated_wall, 1
    )
    # self-describing basis (r6): the projection is NOT a measured number
    # — it models the same solve on colocated host+accelerator by summing
    # the measured host phases with device COMPUTE only, excluding every
    # host<->device transfer (per-solve input upload, packed-result
    # readback, free-state full/delta uploads — the dev tunnel's fixed
    # per-transfer latency that colocation would not pay)
    split["colocated_projection_basis"] = (
        "p50_encode_seconds + device_compute_seconds + p50_repair_seconds;"
        " excludes all host<->device transfers (device_transport_seconds:"
        " gang-input H2D, packed-result D2H, free-state uploads)"
    )
    split["pipelined_adopted_iters"] = f"{pipe_adopted}/{pipe_iters}"
    split["pipelined_iter_seconds"] = round(pipe_wall, 4)
    split["pipelined_gangs_per_sec"] = (
        round(args.gangs / pipe_wall, 1) if pipe_wall > 0 else 0.0
    )

    # Per-solve dispatch accounting (PR 7): the fused path's whole point
    # is fewer program launches — report them so the trajectory captures
    # the collapse (split warm solve: score launch + any delta scatter;
    # fused: exactly one; incremental reuse: zero).
    disp = ds.get("dispatches", {})
    split["dispatches_by_kind"] = dict(disp)
    # tier kinds attribute a launch already counted under its base kind
    # (fused/split/incremental) — excluded so this stays a LAUNCH count
    split["dispatches_per_solve"] = round(
        sum(v for k, v in disp.items()
            if k not in ("pallas", "device_commit"))
        / max(args.iters, 1), 2
    )

    # Fused-vs-split A/B on identical blocking solves: the same backlog
    # through the split (separate-dispatch) discipline, so the JSON
    # carries the fusion win itself, independent of adoption overlap.
    inc_fields = {}
    if args.engine == "fused":
        split_eng = mk_engine(fused=False)
        split_eng.solve(gangs)  # warm-up: split program compile
        s_walls = []
        for _ in range(max(3, args.iters // 3)):
            t0 = time.perf_counter()
            split_eng.solve(gangs)
            s_walls.append(time.perf_counter() - t0)
        split_p50 = sorted(s_walls)[len(s_walls) // 2]
        inc_fields["split_blocking_p50_seconds"] = round(split_p50, 4)
        inc_fields["fused_vs_split_speedup"] = round(
            split_p50 / engine_wall, 3
        )

    # Incremental dirty-tick probes (single-device only; the sharded
    # engine always runs the full fused program): a churn tick that
    # dirties K gangs against an unchanged free state must re-score
    # O(K) rows, and an identical retry tick must skip the device
    # entirely (the zero-dispatch reuse tier).
    if args.engine == "fused" and not args.sharded:
        inc_eng = mk_engine(incremental=True)
        base = list(gangs)
        inc_eng.solve(base, free=snapshot.free.copy())  # arm the cache

        def fresh_gang(tag):
            g = make_gangs(1)[0]
            g.name = f"inc-{tag}"
            return g

        r_walls = []
        rr = None
        for _ in range(3):
            t0 = time.perf_counter()
            rr = inc_eng.solve(base, free=snapshot.free.copy())
            r_walls.append(time.perf_counter() - t0)
        inc_fields["incremental_reuse_hit"] = bool(rr.stats.get("reused"))
        inc_fields["incremental_reuse_tick_seconds"] = round(
            sorted(r_walls)[1], 4
        )
        DIRTY, TICKS = 3, 5
        walls, rows = [], 0
        inc_eng.solve(base, free=snapshot.free.copy())
        for t in range(TICKS):
            for j in range(DIRTY):
                base[(t * DIRTY + j) % len(base)] = fresh_gang(
                    f"{t}-{j}"
                )
            t0 = time.perf_counter()
            rr = inc_eng.solve(base, free=snapshot.free.copy())
            walls.append(time.perf_counter() - t0)
            rows += int(rr.stats.get("incremental_rows", 0))
        tick = sorted(walls)[len(walls) // 2]
        inc_fields.update({
            "incremental_tick_dirty_gangs": DIRTY,
            "incremental_tick_seconds": round(tick, 4),
            "incremental_rows_per_tick": round(rows / TICKS, 1),
            "incremental_vs_full_speedup": round(engine_wall / tick, 2),
        })

    # Pallas kernel-vs-XLA A/B (--engine pallas): the SAME backlog
    # through the kernel-tier engine and the XLA fused engine,
    # interleaved, comparing the per-solve DEVICE phase (score + commit
    # scan + D2H of the packed result — the phase the kernel rewrites).
    # Off-TPU the kernel runs interpret-lowered (reported, and much
    # slower — the speedup gate is native-lowering-only); the fields
    # always carry the tier/backend so the JSON is self-describing.
    if args.engine == "pallas":
        pal_eng = mk_engine()
        xla_eng = mk_engine(pallas_core=False, device_commit=False)
        pal_eng.solve(gangs, free=snapshot.free.copy())  # warm-up
        xla_eng.solve(gangs, free=snapshot.free.copy())
        dev_secs = {"pallas": [], "xla": []}

        def timed_side(eng, side):
            def run(_i):
                t0 = time.perf_counter()
                res = eng.solve(gangs, free=snapshot.free.copy())
                dev_secs[side].append(res.stats.get("device_seconds", 0.0))
                return time.perf_counter() - t0
            return run

        p_walls, x_walls = interleaved_ab(
            timed_side(pal_eng, "pallas"), timed_side(xla_eng, "xla"),
            max(3, args.iters // 2),
        )
        pal_ds = pal_eng.debug_summary()["device_state"]
        inc_fields["pallas_ab"] = {
            "kernel_tier": pal_ds["core_tier"],
            "pallas_interpret": pal_ds["pallas_interpret"],
            "device_commit": pal_ds["device_commit"],
            "pallas_dispatches": pal_ds["dispatches"].get("pallas", 0),
            "device_commit_dispatches": pal_ds["dispatches"].get(
                "device_commit", 0
            ),
            "pallas_fallbacks": pal_ds["pallas_fallbacks"],
            "pallas_device_p50_seconds": round(p50(dev_secs["pallas"]), 4),
            "xla_device_p50_seconds": round(p50(dev_secs["xla"]), 4),
            # > 1.0 = the kernel tier's device phase is cheaper
            "device_seconds_speedup": round(
                p50(dev_secs["xla"]) / max(p50(dev_secs["pallas"]), 1e-9),
                3,
            ),
            **wall_stats(p_walls, "pallas_", suffix="bind_seconds"),
            **wall_stats(x_walls, "xla_", suffix="bind_seconds"),
            "interleaved": True,
        }

    # Scale-ceiling probes (VERDICT r3 #8 + r4 #9): datapoints at 2x and
    # 4x the north star proving the bucketing/padding strategy and memory
    # hold past the stress config (and mapping where the curve bends).
    # Each probe is an INTERLEAVED hierarchical-vs-flat A/B with
    # min/median/max over repeats — this host's throttling swings walls
    # ~2x run-to-run, so single uninterleaved numbers mislead (the flat
    # fields keep their historical names for trajectory continuity).
    probe = {}
    if not args.small and args.nodes >= 5000:
        for factor in (2, 4):
            p_snapshot = make_cluster(args.nodes * factor)
            p_gangs = make_gangs(args.gangs * factor)
            # single-device probes; incremental off on BOTH sides (the
            # knob also disables the hierarchy's domain-reuse memo) —
            # repeated identical solves would otherwise degenerate into
            # the zero-dispatch reuse tiers and misreport solve cost
            p_flat = PlacementEngine(p_snapshot, incremental=False)
            p_hier = PlacementEngine(
                p_snapshot, incremental=False, hierarchical=True
            )
            p_flat.solve(p_gangs)  # warm-up: new shapes compile
            p_hier.solve(p_gangs)
            placed = {}

            def timed(engine, side, placed=placed):
                def run(_i):
                    t0 = time.perf_counter()
                    placed[side] = engine.solve(p_gangs).num_placed
                    return time.perf_counter() - t0
                return run

            h_walls, f_walls = interleaved_ab(
                timed(p_hier, "hier"), timed(p_flat, "flat"), 3
            )
            probe.update({
                f"scale{factor}x_nodes": args.nodes * factor,
                f"scale{factor}x_gangs": args.gangs * factor,
                f"scale{factor}x_placed": placed["flat"],
                **wall_stats(f_walls, f"scale{factor}x_",
                             suffix="backlog_bind_seconds"),
                f"scale{factor}x_gangs_per_sec": round(
                    args.gangs * factor / p50(f_walls), 1
                ),
                f"scale{factor}x_hier_placed": placed["hier"],
                **wall_stats(h_walls, f"scale{factor}x_hier_",
                             suffix="backlog_bind_seconds"),
                f"scale{factor}x_hier_vs_flat_speedup": round(
                    p50(f_walls) / p50(h_walls), 2
                ),
            })

    # Control-plane bench (VERDICT r1 #4): the FULL path — apply one PCS
    # with N replicas of an 8-pod clique against the same-size inventory,
    # reconcile to quiescence (gated pods -> deferred gangs -> scheduler ->
    # bound + ready). Warm = p50 of 3 post-warmup runs against a
    # constant-size store (the first apply pays jit compile and is
    # reported as cold); see bench_controlplane.
    cp = {}
    if args.cp_replicas > 0:
        cp = bench_controlplane(
            args.nodes, args.cp_replicas,
            trace_groups=trace_groups if args.trace else None,
        )
        if args.shards > 1:
            # the sharded control plane needs enough work per shard for
            # the parallel model to mean anything: under --small the
            # single-replica section clamps to 20 replicas (whole settles
            # ~tens of ms, fixed per-round costs dominate), so the shard
            # section runs its own CPU-friendly floor — and measures its
            # OWN single-replica reference at that same scale, so the
            # reported speedup is always same-workload/same-machine
            shard_replicas = max(args.cp_replicas, 500) if args.small \
                else args.cp_replicas
            cp.update(bench_controlplane_sharded(
                args.nodes, shard_replicas, args.shards,
            ))
        # Sustained-churn regime (VERDICT r4 #2): the reference's actual
        # operating claim is a long-lived operator under a continuous
        # event stream, not a one-shot backlog settle — measure steady
        # arrival with deletes, scale events and crashes mixed in.
        cp.update(
            bench_churn(
                args.nodes,
                rate=args.churn_rate,
                duration=args.churn_duration,
                trace_groups=trace_groups if args.trace else None,
            )
        )
        if args.recovery:
            cp.update(bench_recovery(
                args.nodes, args.cp_replicas,
                partitions=args.partitions,
            ))

    # always-on tracing tax probe (--aggregate-overhead): off vs
    # tracing.mode=aggregate on the settle workload, <5% acceptance
    agg_probe: dict = {}
    agg_failures: list[str] = []
    if args.aggregate_overhead:
        agg_probe, agg_failures = bench_aggregate_overhead(
            args.nodes, args.cp_replicas or 20,
        )

    # Headline basis (r7, recorded so BENCH files stay self-describing,
    # like the r3 p99->p50 change): the fused regime's headline is the
    # dispatch/adopt steady state — the scheduler's DEPLOYED posture
    # (pre_round dispatches, the round's host work overlaps device
    # compute + D2H, _reconcile adopts) — because a blocking roundtrip
    # through the dev tunnel is transport-latency-bound no matter how
    # little is shipped. Blocking p50/p99 remain as the latency fields.
    headline_wall = engine_wall
    basis = "p50_of_iters"
    if args.engine == "fused" and pipe_wall > 0:
        headline_wall = pipe_wall
        basis = "p50_pipelined_adopted"
    gangs_per_sec = args.gangs / headline_wall
    out = {
        "metric": f"gang placements/sec ({args.gangs} x 8-pod gangs, "
        f"{args.nodes} nodes, 3-tier topology)",
        "value": round(gangs_per_sec, 1),
        "unit": "gangs/sec",
        "vs_baseline": round(serial_wall / headline_wall, 2),
        # r1/r2 computed value+vs_baseline from p99 (=max of iters); a
        # single tunnel hiccup misreported steady throughput 3x low
        "throughput_basis": basis,
        "engine_regime": args.engine,
        "p50_backlog_bind_seconds": round(engine_wall, 4),
        "p99_backlog_bind_seconds": round(engine_p99, 4),
        "serial_baseline_seconds": round(serial_wall, 2),
        "serial_baseline_impl": baseline,
        "serial_sampled_gangs": sample,
        "placed": placed,
        "serial_placed_sampled": sres.num_placed,
        "mean_placement_score": round(score, 4),
        "repair_fallbacks": fallbacks,
        # solve-phase split (p50 across iters): host encode, device
        # score+commit-scan (incl. D2H of the packed top-k), host exact
        # repair — where the next optimization lives is visible, not
        # guessed (VERDICT r3 #2)
        **{
            f"p50_{k}": round(sorted(v)[len(v) // 2], 4)
            for k, v in phase_stats.items()
        },
        "grouped_gangs_per_sec": round(args.gangs / g_wall, 1),
        "grouped_placed": g_placed,
        "grouped_repair_fallbacks": g_fallbacks,
        **split,
        **inc_fields,
        **probe,
        "backend": __import__("jax").default_backend(),
        "engine": "sharded" if args.sharded else "single",
        **({"mesh": dict(mesh.shape)} if args.sharded else {}),
        **cp,
        **agg_probe,
    }
    trace_failures: list[str] = []
    if args.trace:
        from grove_tpu.observability.tracing import chrome_trace

        with open(args.trace, "w") as fh:
            json.dump(chrome_trace(trace_groups), fh)
            fh.write("\n")
        n_spans = sum(len(v.finished) for v in trace_groups.values())
        print(f"wrote {n_spans} spans to {args.trace}", file=sys.stderr)
        # the fleet latency breakdown over the traced control-plane
        # sections, with the telescoping gate (the churn ring may have
        # evicted early gangs' create spans, so only the bounded
        # controlplane section arms the non-vacuity check)
        breakdown: dict = {}
        for lbl in ("controlplane", "churn"):
            tr = trace_groups.get(lbl)
            if tr is None:
                continue
            report, fails = _trace_critical_path(
                tr, binds=1 if lbl == "controlplane" else 0, label=lbl,
            )
            breakdown[lbl] = report
            trace_failures.extend(fails)
        if breakdown:
            out["critical_path_breakdown"] = breakdown
            print(json.dumps({"critical_path_breakdown": breakdown}),
                  file=sys.stderr)
    print(json.dumps(out))
    for f in (*trace_failures, *agg_failures):
        print(f"BENCH FAILURE: {f}", file=sys.stderr)
    return 1 if (trace_failures or agg_failures) else 0


def bench_equivalence(args, snapshot, gangs, mk_engine) -> int:
    """Placement-equivalence gate (`--equivalence`, run by CI): solve
    every scenario with the delta (split dispatch, state cache +
    superset-contract verify), fused (single-dispatch program) and
    fused+incremental (dirty-row re-solve) engines AGAINST the
    full-re-encode reference (cache off, the pre-delta behavior) and
    exit nonzero on any divergence. The resident state, the fused
    launch, and the incremental value-row cache change WHERE and HOW
    OFTEN things are computed and shipped, never what is computed:
    placements, unplaced reasons, and the post-solve free matrix must
    all be bit-identical on every path.

    Scenarios: the plain backlog solved repeatedly (warm hit / reuse
    tier), the grouped-constraint backlog, a dispatch/adopt round plus a
    dispatch deliberately staled by a free mutation (the epoch guard
    must refuse it), a seeded bind/unbind churn sweep carrying committed
    capacity forward, tenant-fairness weights, and the INCREMENTAL
    suite: seeded churn dirtying 1/3/all gangs against an unchanged free
    state, dispatch-adoption under a dirty tick, and a rebind
    (cordon-shaped schedulable flip) mid-stream that must force the
    full-solve fallback. The gate also fails if the incremental engine
    never actually exercised its dirty-row / reuse tiers — a vacuous
    pass must not read as coverage.

    Two more tiers ride the same gate: the HIERARCHICAL two-level solve
    (score-equal vs flat — see section 7) and the WAVE-PARALLEL fine
    phase (section 8), which must stay BITWISE equal to the serial
    workers=0 wave driver, with its own never-ran-a-multi-domain-wave
    vacuity guard.

    The PALLAS kernel tiers (section 9 + the pallas / pallas-commit
    candidates) grow the n-way to four: the fp32 scoring kernel and the
    on-device greedy commit are BITWISE candidates (same arithmetic,
    same candidate walk), the hierarchical candidate runs its
    sub-engines on the kernel tier, a bf16 run pins the documented
    reduced-precision tie policy (placed set / unplaced codes /
    committed totals invariant), and kernel-never-ran or
    silent-fallback turns the gate vacuous -> nonzero exit."""
    import dataclasses

    eng_f = mk_engine(state_cache=False, fused=False, incremental=False)
    candidates = {
        "delta": mk_engine(state_cache=True, state_verify=True,
                           fused=False, incremental=False),
        "fused": mk_engine(state_cache=True, state_verify=True,
                           fused=True, incremental=False),
        "inc": mk_engine(state_cache=True, state_verify=True,
                         fused=True, incremental=True),
        # the kernel tiers (PR: one-kernel solve): the fused program
        # with the Pallas fp32 scoring kernel, then additionally the
        # on-device greedy commit — both BITWISE against the reference
        # (fp32 kernel replicates the XLA arithmetic op-for-op; the
        # commit scan replays the candidate walk at aggregate
        # granularity, conflicts fall to the same serial net). On a
        # flat sharded mesh the knobs resolve off (capability miss) and
        # these rows degenerate into fused re-runs — the kernel
        # coverage guards below are gated accordingly.
        "pallas": mk_engine(state_cache=True, state_verify=True,
                            fused=True, incremental=False,
                            pallas_core=True),
        "pallas-commit": mk_engine(state_cache=True, state_verify=True,
                                   fused=True, incremental=False,
                                   pallas_core=True, device_commit=True),
    }
    rng = np.random.default_rng(7)
    n = snapshot.num_nodes
    failures: list[str] = []
    solves = 0

    def diff(label, name, res_c, res_f, free_c, free_f) -> None:
        if sorted(res_c.placed) != sorted(res_f.placed):
            only_c = sorted(set(res_c.placed) - set(res_f.placed))[:4]
            only_f = sorted(set(res_f.placed) - set(res_c.placed))[:4]
            failures.append(
                f"{label}[{name}]: placed sets differ ({name}-only "
                f"{only_c}, full-only {only_f})"
            )
            return
        for gname, p_c in res_c.placed.items():
            p_f = res_f.placed[gname]
            if p_c.pod_to_node != p_f.pod_to_node or not np.array_equal(
                p_c.node_indices, p_f.node_indices
            ):
                failures.append(
                    f"{label}[{name}]: {gname} placed differently"
                )
        if res_c.unplaced != res_f.unplaced:
            failures.append(f"{label}[{name}]: unplaced reasons differ")
        if not np.array_equal(free_c, free_f):
            bad = np.flatnonzero((free_c != free_f).any(axis=1))[:8]
            failures.append(
                f"{label}[{name}]: post-solve free matrices differ on "
                f"rows {bad.tolist()}"
            )

    #: the sharded engine forces incremental off by design (the value
    #: cache permutation would be a cross-shard collective), so the
    #: path EXPECTATIONS and coverage asserts are single-device-only;
    #: the bitwise comparisons — the actual gate — run everywhere
    check_paths = candidates["inc"].incremental

    def solve_all(label, gang_list, free, fairness=None,
                  declare=None, unknown=False, expect_inc=None):
        """Solve `gang_list` against `free` content on the reference and
        every candidate (each on its own copy; `declare`/`unknown` feed
        note_free_rows per the superset contract), compare bitwise, and
        return the reference's post-solve free (the carried canonical
        state). `expect_inc` pins the inc engine's path: "inc" (dirty-row
        re-score), "reused", or "full" (neither stat present)."""
        nonlocal solves
        solves += 1
        free_f = free.copy()
        res_f = eng_f.solve(gang_list, free=free_f, fairness=fairness)
        for name, eng in candidates.items():
            if unknown:
                eng.note_free_rows(None)
            elif declare is not None:
                eng.note_free_rows(declare)
            free_c = free.copy()
            res_c = eng.solve(gang_list, free=free_c, fairness=fairness)
            diff(label, name, res_c, res_f, free_c, free_f)
            if name == "inc" and expect_inc is not None and check_paths:
                got = (
                    "inc" if res_c.stats.get("incremental")
                    else "reused" if res_c.stats.get("reused")
                    else "full"
                )
                if got != expect_inc:
                    failures.append(
                        f"{label}[inc]: expected the {expect_inc} path, "
                        f"engine took {got}"
                    )
        return free_f

    # 1) plain backlog, twice: the second solve rides a pure state hit —
    #    and the incremental engine's zero-dispatch REUSE tier
    free = solve_all("plain[0]", gangs, snapshot.free.copy())
    solve_all("plain[1]", gangs, snapshot.free.copy(),
              expect_inc="reused")

    # 2) grouped-constraint backlog (same snapshot, richer constraints).
    #    The grouped variant reuses the plain backlog's names and
    #    per-gang DEVICE rows (constraint groups and group preferences
    #    never enter the device phase — they are repair-side exact
    #    constraints), so the incremental engine legitimately serves it
    #    from the cache: the reused scores are bitwise what a full
    #    re-score would compute, and the exact repair applies the richer
    #    constraints fresh. The diff against the reference proves it.
    grouped = make_gangs(len(gangs), grouped=True)
    solve_all("grouped", grouped, snapshot.free.copy(),
              expect_inc="reused")

    # 3) dispatch/adopt per candidate: an unchanged dispatch must be
    #    adopted via the O(1) epoch guard; one staled by a declared free
    #    mutation must be refused, and the fallback solve must match
    for name, eng in candidates.items():
        handle = eng.dispatch(gangs, free=snapshot.free.copy())
        free_c, free_f = snapshot.free.copy(), snapshot.free.copy()
        res_c = eng.solve(gangs, free=free_c, dispatch=handle)
        if not res_c.stats.get("dispatch_overlap"):
            failures.append(
                f"dispatch-adopt[{name}]: unchanged dispatch not adopted"
            )
        solves += 1
        diff("dispatch-adopt", name, res_c,
             eng_f.solve(gangs, free=free_f), free_c, free_f)
        handle = eng.dispatch(gangs, free=snapshot.free.copy())
        stale_free = snapshot.free.copy()
        row = int(rng.integers(n))
        stale_free[row] *= 0.5
        eng.note_free_rows((row,))
        free_c, free_f = stale_free.copy(), stale_free.copy()
        res_c = eng.solve(gangs, free=free_c, dispatch=handle)
        if res_c.stats.get("dispatch_overlap"):
            failures.append(
                f"dispatch-stale[{name}]: epoch guard adopted stale "
                "scores"
            )
        solves += 1
        diff("dispatch-stale", name, res_c,
             eng_f.solve(gangs, free=free_f), free_c, free_f)
        # re-align every engine's resident content before the next
        # candidate: the stale solve reverts UNDECLARED (each candidate
        # staled a different row), so this must ride the unknown-scope
        # full-diff path per the note_free_rows contract
        solve_all(f"dispatch-realign[{name}]", gangs,
                  snapshot.free.copy(), unknown=True)

    # 4) seeded bind/unbind churn: capacity committed by round k's repair
    #    carries forward into round k+1 through the delta path, with
    #    extra seeded row churn (release/claw-back) declared per the
    #    note_free_rows superset contract. The free content moves every
    #    round, so the inc engine must take the full path (epoch
    #    divergence fallback).
    rounds, subset_size = (4, max(8, len(gangs) // 8))
    free = snapshot.free.copy()
    for rnd in range(rounds):
        rows = rng.choice(n, size=min(24, n), replace=False)
        scale = rng.uniform(0.4, 1.1, size=(rows.size, 1)).astype(np.float32)
        free[rows] = np.minimum(
            snapshot.capacity[rows], free[rows] * scale
        ).astype(np.float32)
        subset = [
            gangs[i]
            for i in sorted(rng.choice(
                len(gangs), size=min(subset_size, len(gangs)), replace=False
            ))
        ]
        # one round declares UNKNOWN scope (None) instead of the rows:
        # the engine must fall back to the full content diff and stay
        # correct — the other rounds ride the row-scoped delta path
        free = solve_all(
            f"churn[{rnd}]", subset, free,
            declare=rows.tolist(), unknown=(rnd == 2),
            expect_inc="full",
        )

    # 5) tenant fairness terms (grove_tpu/tenancy): seeded per-gang DRF
    #    weights reorder the commit scan and ride the cost tensor as an
    #    extra column; a changed weight also changes the gang's content
    #    fingerprint, so the first fairness solve is an incremental
    #    all-dirty -> full fallback and a repeat is a reuse
    fair = {
        g.name: round(float(rng.uniform(-0.5, 1.5)), 6) for g in gangs
    }
    free = solve_all("fairness", gangs, free, fairness=fair)
    handle = candidates["inc"].dispatch(
        gangs, free=free.copy(), fairness=fair
    )
    free_c, free_f = free.copy(), free.copy()
    res_c = candidates["inc"].solve(
        gangs, free=free_c, dispatch=handle, fairness=fair
    )
    if not res_c.stats.get("dispatch_overlap"):
        failures.append(
            "fairness-dispatch[inc]: unchanged fairness-stamped dispatch "
            "not adopted"
        )
    solves += 1
    diff("fairness-dispatch", "inc", res_c,
         eng_f.solve(gangs, free=free_f, fairness=fair), free_c, free_f)

    # 6) INCREMENTAL suite: seeded churn ticks against an UNCHANGED free
    #    state, dirtying 1, 3, then all gangs — the 1/3 ticks must ride
    #    the dirty-row re-score, the all-dirty tick the full fallback —
    #    plus dispatch-adoption under a dirty tick and a rebind
    #    (schedulable flip) mid-stream forcing the full-solve fallback.
    current = list(gangs)
    fresh_seq = [0]

    def freshen(k):
        start = fresh_seq[0]
        for j in range(k):
            g = make_gangs(1)[0]
            g.name = f"inc-fresh-{start + j}"
            current[(start + j) % len(current)] = g
        fresh_seq[0] += k

    solve_all("inc-warm", current, free)  # arm the caches on this content
    for k, label in ((1, "inc-dirty-1"), (3, "inc-dirty-3")):
        freshen(k)
        solve_all(label, current, free, expect_inc="inc")
    freshen(len(current))
    solve_all("inc-dirty-all", current, free, expect_inc="full")

    # dispatch-adoption under a dirty tick: the dispatched incremental
    # scores must be adopted and match the reference
    freshen(2)
    inc_eng = candidates["inc"]
    handle = inc_eng.dispatch(current, free=free.copy())
    if check_paths and handle is not None and handle.path != "incremental":
        failures.append(
            f"inc-adopt-dirty: dispatch took {handle.path}, expected the "
            "incremental path"
        )
    free_c, free_f = free.copy(), free.copy()
    res_c = inc_eng.solve(current, free=free_c, dispatch=handle)
    if not res_c.stats.get("dispatch_overlap"):
        failures.append("inc-adopt-dirty: incremental dispatch not adopted")
    solves += 1
    diff("inc-adopt-dirty", "inc", res_c,
         eng_f.solve(current, free=free_f), free_c, free_f)

    # rebind mid-stream: a cordon-shaped schedulable flip must clear the
    # value cache and force the full-solve fallback (a stale re-score
    # against the old mask would place onto the cordoned node)
    flip = int(rng.integers(n))
    sched = snapshot.schedulable.copy()
    sched[flip] = ~sched[flip]
    snap2 = dataclasses.replace(snapshot, schedulable=sched)
    for eng in (eng_f, *candidates.values()):
        if not eng.rebind(snap2):
            failures.append("inc-rebind: rebind rejected a pure "
                            "schedulable flip")
    solve_all("inc-rebind", current, free, expect_inc="full")
    # and the tier must RESUME once re-armed on the new mask
    freshen(1)
    solve_all("inc-rebind-resume", current, free, expect_inc="inc")

    # 7) HIERARCHICAL two-level vs flat — the gate's n-way grows the
    #    tier that restructures the solve itself. The coarse assignment
    #    legitimately resolves cross-domain ties differently than the
    #    flat scan's jitter (a gang may land in a DIFFERENT
    #    equal-scoring domain), so the pin here is SCORE-equality, not
    #    bitwise: identical placed set, identical per-gang
    #    placement_score, identical unplaced reason codes, and identical
    #    per-resource committed totals. Everything else about the gate
    #    (carried state, seeded churn, coverage-or-fail) mirrors the
    #    bitwise tiers above.
    from grove_tpu.observability.explain import unsat_code

    # the hierarchical candidate ALSO runs the kernel tier (where it
    # resolves on): its per-domain sub-engines inherit pallas_core, so
    # the dirty-tick/churn scenarios below double as the hierarchical
    # kernel-equivalence coverage
    eng_h = mk_engine(hierarchical=True, state_cache=True,
                      state_verify=True, fused=True, incremental=True,
                      pallas_core=True)
    hier_pruned = 0
    hier_solves = 0

    def diff_hier(label, res_h, res_f, free_h, free_f) -> None:
        if sorted(res_h.placed) != sorted(res_f.placed):
            only_h = sorted(set(res_h.placed) - set(res_f.placed))[:4]
            only_f = sorted(set(res_f.placed) - set(res_h.placed))[:4]
            failures.append(
                f"hier[{label}]: placed sets differ (hier-only {only_h}, "
                f"flat-only {only_f})"
            )
            return
        for gname, p_h in res_h.placed.items():
            if p_h.placement_score != res_f.placed[gname].placement_score:
                failures.append(
                    f"hier[{label}]: {gname} score "
                    f"{p_h.placement_score} != flat "
                    f"{res_f.placed[gname].placement_score}"
                )
        for gname, reason_f in res_f.unplaced.items():
            code_h = unsat_code(res_h.unplaced.get(gname))
            if code_h != unsat_code(reason_f):
                failures.append(
                    f"hier[{label}]: {gname} unplaced code {code_h} != "
                    f"flat {unsat_code(reason_f)}"
                )
        # committed capacity totals: the same gangs bound the same
        # demand, wherever the ties landed them
        if not np.allclose(
            free_h.sum(axis=0), free_f.sum(axis=0), rtol=1e-5, atol=1e-3
        ):
            failures.append(
                f"hier[{label}]: committed per-resource totals diverge"
            )

    def solve_hier(label, gang_list, free, expect_hier=True):
        """Solve on the flat reference and the hierarchical candidate
        (each from the same free content; the reference's post-solve
        free is the carried canonical state)."""
        nonlocal hier_pruned, hier_solves, solves
        solves += 1
        hier_solves += 1
        free_f, free_h = free.copy(), free.copy()
        res_f = eng_f.solve(gang_list, free=free_f)
        # the carried canonical state is the flat REFERENCE's committed
        # free — which diverges row-wise from the hier engine's own
        # commits (same demand, different tie-broken nodes), so its
        # mutations were never declared to eng_h: unknown scope per the
        # note_free_rows contract (full content diff, stays correct)
        eng_h.note_free_rows(None)
        res_h = eng_h.solve(gang_list, free=free_h)
        took_hier = bool(res_h.stats.get("hierarchical"))
        if took_hier != expect_hier:
            failures.append(
                f"hier[{label}]: expected "
                f"{'hierarchical' if expect_hier else 'flat'} path, "
                f"engine took the other"
            )
        hier_pruned += int(res_h.stats.get("hier_pruned_pairs", 0))
        diff_hier(label, res_h, res_f, free_h, free_f)
        return free_f

    # 7a) plain backlog with one coarse domain drained near-empty: the
    #     coarse pass must PRUNE it (aggregate capacity cut) and route
    #     every gang around it — pruning coverage is asserted below
    drained = snapshot.free.copy()
    block_ids = snapshot.domain_ids[0]
    drained[block_ids == (int(block_ids.max()))] *= 0.01
    free = solve_hier("drained-domain", gangs, drained)

    # 7b) seeded bind/unbind churn with carried committed state: every
    #     round moves the free content and re-solves a subset
    for rnd in range(3):
        rows = rng.choice(n, size=min(24, n), replace=False)
        scale = rng.uniform(0.4, 1.1, size=(rows.size, 1)).astype(
            np.float32
        )
        free[rows] = np.minimum(
            snapshot.capacity[rows], free[rows] * scale
        ).astype(np.float32)
        subset = [
            gangs[i]
            for i in sorted(rng.choice(
                len(gangs), size=min(max(8, len(gangs) // 8), len(gangs)),
                replace=False,
            ))
        ]
        free = solve_hier(f"churn[{rnd}]", subset, free)

    # 7c) structurally unplaceable gangs (per-pod demand no node can
    #     hold): both paths must report the same CAPACITY verdicts
    doomed = make_gangs(4)
    for j, g in enumerate(doomed):
        g.name = f"doomed{j:02d}"
        g.demand = g.demand * 0 + np.array([64.0, 16.0, 1.0], np.float32)
    solve_hier("doomed", list(gangs[:8]) + doomed, snapshot.free.copy())

    # 7d) repeat of an identical solve: the domain-reuse memo must
    #     replay bitwise-identical outcomes (compared against the flat
    #     reference exactly like a fresh solve), then a DIRTY TICK on
    #     unchanged free content — one replaced gang — must ride the
    #     shard-local incremental re-solve inside its domain
    solve_hier("domain-reuse", gangs, snapshot.free.copy())
    solve_hier("domain-reuse[1]", gangs, snapshot.free.copy())
    dirty_backlog = list(gangs)
    fresh = make_gangs(1)[0]
    fresh.name = "hier-dirty-0"
    dirty_backlog[3] = fresh
    solve_hier("dirty-tick", dirty_backlog, snapshot.free.copy())

    # 7e) unconfined backlog (a root-level gang): a documented
    #     forced-flat trigger — the hierarchical engine must take the
    #     flat path and stay bitwise-compatible there
    unconfined = make_gangs(8)
    for g in unconfined:
        g.required_level = -1
    solve_hier("unconfined-flat", unconfined, snapshot.free.copy(),
               expect_hier=False)

    # vacuous-coverage guard (same pattern as the incremental tiers
    # above): if the coarse level never pruned a single (gang, domain)
    # pair across the scenario set, the hierarchical gate proved nothing
    if hier_pruned == 0:
        failures.append("coverage: the hierarchical coarse level never "
                        "pruned anything — the gate is vacuous")
    hier_ds = eng_h.debug_summary()
    # shard-local incrementality works on the SHARDED engine too (the
    # domain is the shard unit), so this coverage check has no
    # single-device gate — unlike the flat incremental tier's above
    if eng_h._hier_incremental and (
        hier_ds["device_state"]["dispatches"]["incremental"] == 0
    ):
        failures.append("coverage: the hierarchical tier's shard-local "
                        "incremental re-solve never ran")

    # 8) WAVE-PARALLEL fine solves vs the serial wave driver: the
    #    dispatch-all/collect-in-order restructure changes WHEN each
    #    domain's encode/launch/repair runs, never what is computed —
    #    domains partition node rows and collection commits in
    #    deterministic domain order — so unlike the hier-vs-flat tier's
    #    score-equality pin, this one is BITWISE (placements, unplaced
    #    reasons, post-solve free), across fresh solves, the
    #    domain-reuse memo, dirty ticks, seeded churn, and a
    #    fail/recover-shaped rebind mid-stream.
    eng_ws = mk_engine(hierarchical=True, hier_parallel_workers=0,
                       state_cache=True, fused=True, incremental=True)
    eng_wp = mk_engine(hierarchical=True, hier_parallel_workers=4,
                       state_cache=True, fused=True, incremental=True)
    wave_width_max = 0
    wave_solves = 0
    # a backlog that genuinely SPREADS across coarse domains (the
    # best-fit coarse commit otherwise piles one demand class onto the
    # single tightest block and every wave is width-1): two demand
    # classes + half the blocks drained below the big class's per-pod
    # fit, so the fit cut confines big gangs to the loose blocks while
    # small gangs best-fit the tight ones — multi-domain waves by
    # construction, which the width coverage guard below pins
    wave_gangs = make_gangs(len(gangs))
    for i, g in enumerate(wave_gangs):
        g.name = f"wave{i:05d}"
        if i % 2:
            g.demand = g.demand * np.float32(3.0)
    block_ids = snapshot.domain_ids[0]
    wave_free = snapshot.free.copy()
    drained_rows = block_ids >= (int(block_ids.max()) + 1) // 2
    # the drain must tighten EVERY resource (the best-fit slack is the
    # max over resources — a cpu-only drain leaves memory slack
    # dominant and the tie-broken pick collapses back to one block)
    wave_free[drained_rows] = np.minimum(
        wave_free[drained_rows],
        np.array([8.0, 24.0, 2.0], np.float32),
    )

    def solve_wave(label, gang_list, free, declare=None,
                   expect_memo=False):
        """Solve on the workers=0 reference and the wave-parallel
        candidate from the same free content; the serial side's
        post-solve free is the carried canonical state (the gate proves
        the parallel side's is bit-identical anyway). `expect_memo`
        asserts both sides actually replayed the domain-reuse memo —
        a scenario named for the memo must not silently re-solve."""
        nonlocal solves, wave_solves, wave_width_max
        solves += 1
        wave_solves += 1
        free_s, free_p = free.copy(), free.copy()
        if declare is not None:
            eng_ws.note_free_rows(declare)
            eng_wp.note_free_rows(declare)
        res_s = eng_ws.solve(gang_list, free=free_s)
        res_p = eng_wp.solve(gang_list, free=free_p)
        if not res_s.stats.get("hierarchical"):
            failures.append(f"wave[{label}]: reference ran flat — the "
                            "scenario proves nothing")
        if expect_memo and (
            res_s.stats.get("hier_domain_reuse", 0) < 1
            or res_p.stats.get("hier_domain_reuse", 0) < 1
        ):
            failures.append(
                f"wave[{label}]: the domain-reuse memo never replayed "
                "— the memo scenario is vacuous"
            )
        wave_width_max = max(
            wave_width_max, int(res_p.stats.get("hier_wave_width", 0))
        )
        diff(f"wave[{label}]", "parallel", res_p, res_s, free_p, free_s)
        return free_s

    wave_input = wave_free.copy()
    solve_wave("fresh", wave_gangs, wave_free)
    # identical repeat of the SAME input content: both sides must
    # replay the domain-reuse memo (memo keys on the PRE-solve rows,
    # so the repeat re-solves the fresh input, not the carried post —
    # the expect_memo assert keeps this scenario honest)
    solve_wave("memo", wave_gangs, wave_input, expect_memo=True)
    # dirty tick against the same input: the dirty gangs' domains
    # re-solve, clean domains keep the memo
    wdirty = list(wave_gangs)
    for j in (1, 5, 9):
        g = make_gangs(1)[0]
        g.name = f"wave-dirty-{j}"
        wdirty[j % len(wdirty)] = g
    wfree = solve_wave("dirty-tick", wdirty, wave_input)
    # seeded bind/unbind churn with carried committed state, declared
    # per the note_free_rows superset contract
    for rnd in range(2):
        rows = rng.choice(n, size=min(24, n), replace=False)
        scale = rng.uniform(0.4, 1.1, size=(rows.size, 1)).astype(
            np.float32
        )
        wfree[rows] = np.minimum(
            snapshot.capacity[rows], wfree[rows] * scale
        ).astype(np.float32)
        subset = [
            wave_gangs[i]
            for i in sorted(rng.choice(
                len(wave_gangs),
                size=min(max(8, len(wave_gangs) // 8), len(wave_gangs)),
                replace=False,
            ))
        ]
        wfree = solve_wave(f"churn[{rnd}]", subset, wfree,
                           declare=rows.tolist())
    # fail/recover-shaped rebind mid-stream: a node drops out of the
    # schedulable set and comes back — both sides must ride the shard
    # rebind path and stay bitwise-aligned through both flips
    fail_row = int(rng.integers(n))
    for flip_to in (False, True):
        sched_w = eng_ws.snapshot.schedulable.copy()
        sched_w[fail_row] = flip_to
        snap_w = dataclasses.replace(eng_ws.snapshot,
                                     schedulable=sched_w)
        if not (eng_ws.rebind(snap_w) and eng_wp.rebind(snap_w)):
            failures.append("wave[rebind]: rebind rejected a pure "
                            "schedulable flip")
        wfree = solve_wave(
            "fail-node" if not flip_to else "recover-node", wave_gangs,
            wfree,
        )
    if wave_width_max < 2:
        failures.append(
            "coverage: the wave-parallel driver never ran a "
            "multi-domain wave — the wave gate is vacuous"
        )
    if eng_ws.debug_summary()["hierarchical"]["wave_workers"] != 0:
        failures.append("wave: the workers=0 reference resolved a "
                        "nonzero wave width")

    # 9) reduced-precision tie policy (pallas_precision="bf16"): the
    #    kernel accumulates the score chain in bf16, so values may move
    #    by a quantization epsilon and re-rank exact-tie neighbors —
    #    the pin is NOT bitwise. The documented+pinned contract
    #    (docs/scheduling.md): feasibility masks stay fp32-exact in
    #    both tiers and the host repair backstops every candidate walk
    #    with the complete serial net, so the PLACED SET, the unplaced
    #    reason codes, and the committed per-resource totals are
    #    invariant; only within-epsilon candidate order may shift.
    eng_bf = mk_engine(state_cache=True, fused=True, incremental=False,
                       pallas_core=True, pallas_precision="bf16")
    if eng_bf.pallas_core:
        free_c, free_f = snapshot.free.copy(), snapshot.free.copy()
        res_f = eng_f.solve(gangs, free=free_f)
        res_c = eng_bf.solve(gangs, free=free_c)
        solves += 1
        if sorted(res_c.placed) != sorted(res_f.placed):
            failures.append("bf16-tie-policy: placed sets differ")
        for gname, reason_f in res_f.unplaced.items():
            if unsat_code(res_c.unplaced.get(gname)) != unsat_code(
                reason_f
            ):
                failures.append(
                    f"bf16-tie-policy: {gname} unplaced code differs"
                )
        if not np.allclose(
            free_c.sum(axis=0), free_f.sum(axis=0), rtol=1e-5, atol=1e-3
        ):
            failures.append(
                "bf16-tie-policy: committed per-resource totals diverge"
            )

    # kernel-tier coverage: where the knobs resolved ON, the tiers must
    # have actually run (and never silently fallen back) — a vacuous
    # pass must not read as kernel equivalence. On a flat sharded mesh
    # the knobs resolve off by design (capability miss) and only the
    # hierarchical sub-engine guard below applies.
    pal_ds = candidates["pallas"].debug_summary()["device_state"]
    pc_ds = candidates["pallas-commit"].debug_summary()["device_state"]
    for nm in ("pallas", "pallas-commit"):
        nds = candidates[nm].debug_summary()["device_state"]
        if nds["pallas_fallbacks"]:
            failures.append(
                f"{nm}: kernel launch fell back to XLA "
                f"({nds['pallas_fallbacks']}x)"
            )
    if candidates["pallas"].pallas_core and (
        pal_ds["dispatches"].get("pallas", 0) == 0
    ):
        failures.append("coverage: the pallas kernel tier never ran — "
                        "the four-way gate is vacuous")
    if candidates["pallas-commit"].device_commit and (
        pc_ds["dispatches"].get("device_commit", 0) == 0
    ):
        failures.append("coverage: the on-device commit tier never ran "
                        "— the four-way gate is vacuous")
    if eng_h._hier_pallas_core and (
        hier_ds["device_state"]["dispatches"].get("pallas", 0) == 0
    ):
        failures.append("coverage: the hierarchical sub-engines never "
                        "ran the kernel tier")

    # the gate is only meaningful if the incremental tiers actually ran
    inc_ds = candidates["inc"].debug_summary()["device_state"]
    if check_paths and inc_ds["dispatches"]["incremental"] == 0:
        failures.append("coverage: the incremental dirty-row path never "
                        "ran — the gate is vacuous")
    if check_paths and inc_ds["reuse_hits"] == 0:
        failures.append("coverage: the zero-dispatch reuse tier never "
                        "ran — the gate is vacuous")

    ds = candidates["delta"].debug_summary()["device_state"]
    out = {
        "metric": "delta/fused/incremental/pallas vs full placement "
        f"equivalence ({args.gangs} x 8-pod gangs, {args.nodes} nodes)",
        "value": len(failures),
        "unit": "divergences",
        "vs_baseline": 0.0,
        "solves_compared": solves,
        "full_uploads": ds["full_uploads"],
        "delta_uploads": ds["delta_uploads"],
        "state_sync_hits": ds["hits"],
        "incremental_dispatches": inc_ds["dispatches"]["incremental"],
        "incremental_rows": inc_ds["incremental_rows"],
        "reuse_hits": inc_ds["reuse_hits"],
        "hier_solves_compared": hier_solves,
        "hier_pruned_pairs": hier_pruned,
        "wave_solves_compared": wave_solves,
        "wave_width_max": wave_width_max,
        "hier_incremental_dispatches": (
            hier_ds["device_state"]["dispatches"]["incremental"]
        ),
        "pallas_kernel_tier": pal_ds["core_tier"],
        "pallas_dispatches": pal_ds["dispatches"].get("pallas", 0),
        "device_commit_dispatches": pc_ds["dispatches"].get(
            "device_commit", 0
        ),
        "hier_pallas_dispatches": (
            hier_ds["device_state"]["dispatches"].get("pallas", 0)
        ),
        "bf16_tie_policy_checked": bool(eng_bf.pallas_core),
        "engine": "sharded" if args.sharded else "single",
        "backend": __import__("jax").default_backend(),
    }
    for f in failures:
        print(f"EQUIVALENCE FAILURE: {f}", file=sys.stderr)
    print(json.dumps(out))
    return 1 if failures else 0


#: --scale-tier regimes: nodes / gangs. 20k mirrors the scale4x probe's
#: size on the 4-level topology (flat A/B still feasible); 100k is the
#: ROADMAP tier whose flat tensor does not fit.
_TIERS = {"20k": (20_000, 4_000), "100k": (100_000, 20_000)}

#: past roughly this many value-tensor entries (G_pad x D, f32) the flat
#: engine's device matrices stop fitting CI-class hosts — the flat A/B
#: side is SKIPPED (loudly) above it rather than OOM-killed
_FLAT_TENSOR_CEILING = 2.5e8


def bench_scale_tier(args) -> int:
    """The hierarchical scale-tier regime (--scale-tier 20k|100k): a
    block-confined backlog over the synthetic 4-level topology, solved
    by the two-level engine with a dirty tick per repeat (a few gangs
    replaced) so the SHARD-LOCAL incremental tier genuinely runs —
    clean domains ride the domain-reuse memo / sub-engine reuse, dirty
    domains re-score O(dirty) rows — and the dispatch-kind counters
    prove it. Interleaved A/B/C: the wave-parallel engine (dispatch-all
    then collect-in-order fine solves, --wave-workers) vs the SERIAL
    fine phase (workers=0) vs the flat engine where its tensor still
    fits, with a phase wall breakdown (coarse / fine-solve /
    exactness-net + per-domain fine-wall spread) in the JSON;
    min/median/max over repeats because this class of host throttles
    hard run-to-run. On a >= 2-device mesh the wave side's fine-phase
    median must beat the serial side's (exit nonzero otherwise)."""
    from grove_tpu.observability import MetricsRegistry
    from grove_tpu.solver.engine import _bucket

    num_nodes, num_gangs = _TIERS[args.scale_tier]
    if args.small:
        # CI-friendly miniature of the same shape (still 4-level, still
        # hierarchical): the regime's mechanics, not its scale
        num_nodes, num_gangs = 8_192, 1_024
        print(
            f"bench --small: clamping --scale-tier {args.scale_tier} to "
            f"{num_nodes} nodes / {num_gangs} gangs",
            file=sys.stderr,
        )
    snapshot = make_tier_cluster(num_nodes)
    gangs = make_tier_gangs(num_gangs)
    registry = MetricsRegistry()

    #: --trace composition: the wave and serial engines each trace into
    #: their own group (own Perfetto process), so the export shows the
    #: dispatch-all/collect-in-order overlap against the one-domain-at-
    #: a-time serial fine phase side by side, with the causal flow
    #: arrows (engine.hierarchical -> per-domain engine.fine_solve)
    #: linking each coarse assignment to its fine solves. Walls measured
    #: under --trace carry the tracing overhead — not record numbers.
    trace_groups: dict = {}
    if args.trace:
        from grove_tpu.observability.tracing import Tracer

        trace_groups = {"wave": Tracer(), "serial": Tracer()}

    if args.sharded:
        from grove_tpu.parallel import ShardedPlacementEngine, make_solver_mesh

        mesh = make_solver_mesh()

        def mk(**kw):
            return ShardedPlacementEngine(snapshot, mesh, **kw)
    else:
        mesh = None

        def mk(**kw):
            return PlacementEngine(snapshot, **kw)

    hier = mk(hierarchical=True, metrics=registry,
              hier_parallel_workers=args.wave_workers,
              **({"tracer": trace_groups["wave"]} if trace_groups
                 else {}))
    # solver microbench: decision-ring recording off (the documented
    # opt-out) — at 20k gangs/solve the ring's LRU churn is a visible
    # constant the deployed path amortizes across its cluster-owned log
    hier.decisions = None
    # the wave-parallel A/B side: the SAME hierarchical engine pinned to
    # the serial one-domain-at-a-time fine phase (workers=0), solving
    # the identical backlog sequence interleaved — the fine-phase
    # speedup is the dispatch-all/collect-in-order overlap, nothing
    # else. Its own registry, so both sides pay the identical per-gang
    # metrics recording (an asymmetry here skews the bind-wall fields)
    hier_serial = mk(hierarchical=True, hier_parallel_workers=0,
                     metrics=MetricsRegistry(),
                     **({"tracer": trace_groups["serial"]} if trace_groups
                        else {}))
    hier_serial.decisions = None
    DIRTY = 8

    def dirty_tick(backlog, tick):
        """Replace DIRTY gangs with fresh content UNDER THE SAME sort
        position (name-adjacent successor): the control plane's churn
        shape — a rebuilt replica keeps its identity — so a tick
        dirties its gangs' own domains instead of shifting every
        gang's position in the sorted order (which would re-chunk the
        whole coarse assignment and invalidate every domain). The
        positions are SPREAD one per backlog stride: fleet churn lands
        across blocks, not clustered in one, so a tick dirties ~DIRTY
        distinct domains — which is also what gives the wave-parallel
        A/B real concurrent fine solves to overlap (a clustered tick
        dirties 1-2 domains and measures nothing)."""
        out = list(backlog)
        stride = max(1, len(out) // DIRTY)
        for j in range(DIRTY):
            pos = (j * stride + tick) % len(out)
            g = make_tier_gangs(1)[0]
            g.name = out[pos].name.split(".")[0] + f".{tick}"
            out[pos] = g
        return out

    # flat A/B feasibility: G_pad x D value tensor (the [N, D]
    # membership product behind it is bigger still)
    num_domains = 1 + int(np.asarray(snapshot.num_domains).sum())
    flat_entries = _bucket(num_gangs) * num_domains
    flat_ok = flat_entries <= _FLAT_TENSOR_CEILING
    # the flat A/B side keeps its incremental tier ON (the deployed
    # default): every timed repeat is a DIRTY tick, so the flat engine
    # legitimately re-scores O(dirty) rows too — unlike the scale2x/4x
    # probes' identical repeats, pinning incremental off here would
    # compare hier-with-incrementality against a flat config nobody
    # deploys and overstate the win
    flat = mk(hierarchical=False) if flat_ok else None

    # warm-up: compile + device-resident state + sub-engine population,
    # plus one untimed dirty tick so the incremental program's shapes
    # compile OUTSIDE the timed window (every bench here excludes
    # compile; the first-ever dirty tick would otherwise carry it)
    backlog = list(gangs)
    hier.solve(backlog, free=snapshot.free.copy())
    hier_serial.solve(backlog, free=snapshot.free.copy())
    backlog = dirty_tick(backlog, -1)
    hier.solve(backlog, free=snapshot.free.copy())
    hier_serial.solve(backlog, free=snapshot.free.copy())

    state = {"backlog": backlog, "placed": 0}
    #: per-side phase walls (hier_coarse / fine-solve / exactness-net
    #: seconds per repeat) + per-domain fine-wall spread + wave width —
    #: the breakdown that names WHICH phase regressed, not just the p50
    phase_keys = ("hier_coarse_seconds", "hier_fine_seconds",
                  "hier_net_seconds")
    track = {
        side: {"phases": {k: [] for k in phase_keys},
               "dom_min": [], "dom_med": [], "dom_max": [],
               "width": 0}
        for side in ("wave", "serial")
    }

    def record(side, res):
        t = track[side]
        for k in phase_keys:
            t["phases"][k].append(res.stats.get(k, 0.0))
        t["dom_min"].append(res.stats.get("hier_fine_wall_min", 0.0))
        t["dom_med"].append(res.stats.get("hier_fine_wall_med", 0.0))
        t["dom_max"].append(res.stats.get("hier_fine_wall_max", 0.0))
        t["width"] = max(t["width"],
                         int(res.stats.get("hier_wave_width", 0)))

    def run_side(side, eng):
        t0 = time.perf_counter()
        res = eng.solve(state["backlog"], free=snapshot.free.copy())
        wall = time.perf_counter() - t0
        state["placed"] = res.num_placed
        record(side, res)
        return wall

    def run_pair(rep):
        """One dirty tick, then the wave and serial sides back-to-back
        in ALTERNATING order, so any load burst mid-pair lands on both
        sides across the repeat set rather than always on the same
        one."""
        state["backlog"] = dirty_tick(state["backlog"], rep)
        if rep % 2:
            s_wall = run_side("serial", hier_serial)
            w_wall = run_side("wave", hier)
        else:
            w_wall = run_side("wave", hier)
            s_wall = run_side("serial", hier_serial)
        return w_wall, s_wall

    repeats = max(args.tier_repeats, 3)
    # phase A: the wave-vs-serial pair, tight back-to-back and NOTHING
    # in between — the flat engine's much larger solve leaves a
    # cache/thermal wake that would land on whichever side follows it
    # (measured ~4x on this host class), drowning the ~2x effect under
    # measurement; even repeat count so the alternating order splits
    # any residual order bias evenly
    pair_walls = [run_pair(rep) for rep in range(repeats + repeats % 2)]
    h_walls = [w for w, _s in pair_walls]
    s_walls = [s for _w, s in pair_walls]

    # phase B: the historical hierarchical-vs-flat A/B (where the flat
    # tensor is still materializable), classic interleave. The wave
    # engine keeps ticking the same backlog stream; its phase-B walls
    # feed only the flat comparison (a 50-100x ratio that tolerates
    # the wake), never the wave-vs-serial medians above.
    def run_hier_flat(rep):
        state["backlog"] = dirty_tick(state["backlog"], 1000 + rep)
        t0 = time.perf_counter()
        state["placed"] = hier.solve(
            state["backlog"], free=snapshot.free.copy()
        ).num_placed
        return time.perf_counter() - t0

    def run_flat(_rep):
        if flat is None:
            return None
        t0 = time.perf_counter()
        flat.solve(state["backlog"], free=snapshot.free.copy())
        return time.perf_counter() - t0

    if flat is not None:
        # the flat warm-up (compile + device state; at this tier a
        # much larger solve than anything hierarchical) runs HERE, not
        # before phase A — its cache/thermal wake must never land on a
        # timed wave-vs-serial sample
        flat.solve(state["backlog"], free=snapshot.free.copy())
        hf_walls, f_walls = interleaved_ab(run_hier_flat, run_flat,
                                           repeats)
        f_walls = [w for w in f_walls if w is not None]
    else:
        hf_walls, f_walls = [], []

    # phase C (--engine pallas): kernel-vs-XLA on the FINE phase — two
    # fresh hierarchical engines (kernel tiers on vs off) over the same
    # dirty-ticked backlog stream, back-to-back per tick, comparing the
    # per-solve hier_fine_seconds (the phase whose sub-engine launches
    # the kernel rewrites). Interpret-lowered off-TPU, reported as such.
    pallas_fine = {}
    if args.engine == "pallas":
        hp = mk(hierarchical=True, hier_parallel_workers=args.wave_workers,
                pallas_core=True, device_commit=True)
        hx = mk(hierarchical=True, hier_parallel_workers=args.wave_workers)
        hp.decisions = None
        hx.decisions = None
        for eng in (hp, hx):  # warm: compile + shards + one dirty tick
            eng.solve(state["backlog"], free=snapshot.free.copy())
        state["backlog"] = dirty_tick(state["backlog"], 2000)
        for eng in (hp, hx):
            eng.solve(state["backlog"], free=snapshot.free.copy())
        fine_c = {"pallas": [], "xla": []}

        def run_kernel_side(side, eng):
            res = eng.solve(state["backlog"], free=snapshot.free.copy())
            fine_c[side].append(res.stats.get("hier_fine_seconds", 0.0))

        for rep in range(repeats + repeats % 2):
            state["backlog"] = dirty_tick(state["backlog"], 2001 + rep)
            order = (("pallas", hp), ("xla", hx))
            for side, eng in (order if rep % 2 == 0 else order[::-1]):
                run_kernel_side(side, eng)
        hp_ds = hp.debug_summary()["device_state"]
        pallas_fine = {
            "pallas_fine_ab": {
                "kernel_tier": hp_ds["core_tier"],
                "pallas_interpret": hp_ds["pallas_interpret"],
                "pallas_dispatches": hp_ds["dispatches"].get("pallas", 0),
                "device_commit_dispatches": hp_ds["dispatches"].get(
                    "device_commit", 0
                ),
                "pallas_fallbacks": hp_ds["pallas_fallbacks"],
                **wall_stats(fine_c["pallas"], "pallas_fine_"),
                **wall_stats(fine_c["xla"], "xla_fine_"),
                "fine_device_speedup": round(
                    p50(fine_c["xla"]) / max(p50(fine_c["pallas"]), 1e-9),
                    3,
                ),
                "interleaved": True,
            }
        }
    placed = state["placed"]
    ds = hier.debug_summary()
    disp = ds["device_state"]["dispatches"]
    hier_block = ds["hierarchical"]
    failures = []
    if disp.get("incremental", 0) == 0:
        failures.append(
            "coverage: the shard-local incremental tier never ran — the "
            "dirty ticks should have re-scored O(dirty) rows per "
            "affected domain"
        )
    if hier_block["last_pruned_pairs"] == 0 and hier_block["shards_built"] <= 1:
        failures.append(
            "coverage: the coarse level neither pruned nor partitioned "
            "anything — the tier ran effectively flat"
        )
    wave_workers = hier_block["wave_workers"]
    wave_fine = track["wave"]["phases"]["hier_fine_seconds"]
    serial_fine = track["serial"]["phases"]["hier_fine_seconds"]
    fine_speedup = round(p50(serial_fine) / max(p50(wave_fine), 1e-9), 2)
    if wave_workers >= 1 and track["wave"]["width"] < 2:
        failures.append(
            "coverage: the wave-parallel fine phase never dispatched a "
            "multi-domain wave — the wave A/B is vacuous"
        )
    local_devices = len(mesh.local_devices) if mesh is not None else 1
    if pallas_fine:
        pab = pallas_fine["pallas_fine_ab"]
        if pab["kernel_tier"] != "xla" and pab["pallas_dispatches"] == 0:
            failures.append(
                "coverage: --engine pallas fine phase never launched the "
                "kernel tier — the pallas A/B is vacuous"
            )
    if wave_workers >= 1 and local_devices >= 2 and fine_speedup <= 1.0:
        # the mesh gate (ROADMAP item 1 follow-up): with the domains
        # round-robined across >= 2 devices, dispatch-all/collect-in-
        # order must beat one-domain-at-a-time on the fine phase median
        # (single-device runs report the ratio without gating — there
        # the overlap is host-vs-device only and throttling noise on
        # this host class swings walls ~2x)
        failures.append(
            f"wave-parallel fine-phase speedup {fine_speedup} <= 1 on a "
            f"{local_devices}-device mesh — the wave overlap bought "
            "nothing"
        )
    tier_p50 = p50(h_walls)
    out = {
        "metric": f"hierarchical scale tier ({num_gangs} x 8-pod gangs, "
        f"{num_nodes} nodes, 4-level topology)",
        "value": round(num_gangs / tier_p50, 1),
        "unit": "gangs/sec",
        # flat comparison against the phase-B hier walls measured in
        # the SAME interleave as the flat side (never phase A's)
        "vs_baseline": round(
            (p50(f_walls) / p50(hf_walls)), 2
        ) if f_walls else 0.0,
        "tier": args.scale_tier,
        "placed": placed,
        **wall_stats(h_walls, "tier_", suffix="backlog_bind_seconds"),
        "tier_sub_second_p50": tier_p50 < 1.0,
        "tier_repeats": len(h_walls),
        "tier_dirty_gangs_per_tick": DIRTY,
        # phase wall breakdown (wave side): a future regression names
        # the PHASE — coarse assignment, fine solves, or the serial
        # exactness net — plus the per-domain fine-wall spread naming
        # whether one slow domain or the whole wave moved
        "phase_breakdown": {
            **wall_stats(track["wave"]["phases"]["hier_coarse_seconds"],
                         "coarse_"),
            **wall_stats(wave_fine, "fine_solve_"),
            **wall_stats(track["wave"]["phases"]["hier_net_seconds"],
                         "exactness_net_"),
            "domain_fine_wall_min_seconds": round(
                min(track["wave"]["dom_min"]), 4
            ),
            "domain_fine_wall_median_seconds": round(
                p50(track["wave"]["dom_med"]), 4
            ),
            "domain_fine_wall_max_seconds": round(
                max(track["wave"]["dom_max"]), 4
            ),
        },
        # wave-parallel vs serial fine phase, interleaved (the same
        # dirty-ticked backlogs back-to-back; ranges reported because
        # this host class throttles ~2x run-to-run)
        "wave_parallel_ab": {
            "wave_workers": wave_workers,
            "wave_width_max": track["wave"]["width"],
            **wall_stats(wave_fine, "wave_fine_"),
            **wall_stats(serial_fine, "serial_fine_"),
            **wall_stats(s_walls, "serial_",
                         suffix="backlog_bind_seconds"),
            "fine_phase_speedup_p50": fine_speedup,
            "bind_speedup_p50": round(p50(s_walls) / tier_p50, 2),
            "interleaved": True,
        },
        **pallas_fine,
        "dispatches_by_kind": dict(disp),
        "incremental_rows": ds["device_state"]["incremental_rows"],
        "reuse_hits": ds["device_state"]["reuse_hits"],
        "hier_prune_level": hier_block["prune_level"],
        "hier_coarse_domains": hier_block["coarse_domains"],
        "hier_shards_built": hier_block["shards_built"],
        "hier_last_pruned_pairs": hier_block["last_pruned_pairs"],
        "flat_ab": (
            {
                **wall_stats(f_walls, "flat_"),
                "interleaved": True,
            }
            if f_walls
            else f"skipped: flat [G_pad x D] = {flat_entries:.2e} "
            "value-tensor entries exceeds the materializable ceiling "
            f"({_FLAT_TENSOR_CEILING:.0e}) — the wall the hierarchy "
            "removes"
        ),
        "engine": "sharded" if args.sharded else "single",
        **({"mesh": dict(mesh.shape)} if mesh is not None else {}),
        **({"traced": True} if trace_groups else {}),
        "backend": __import__("jax").default_backend(),
    }
    if args.trace:
        from grove_tpu.observability.tracing import chrome_trace

        with open(args.trace, "w") as fh:
            json.dump(chrome_trace(trace_groups), fh)
            fh.write("\n")
        n_spans = sum(len(v.finished) for v in trace_groups.values())
        print(f"wrote {n_spans} spans to {args.trace}", file=sys.stderr)
    for f in failures:
        print(f"SCALE-TIER FAILURE: {f}", file=sys.stderr)
    print(json.dumps(out))
    return 1 if failures else 0


def bench_service(args) -> int:
    """Solve the stress backlog through the gRPC service boundary: the
    server subprocess owns the accelerator; this process only encodes,
    ships, and decodes. SURVEY hard part (d): the RPC hop + host->device
    transfer must amortize over whole-backlog batches — this measures it
    against the in-process engine wall."""
    import os
    import signal
    import subprocess
    import tempfile

    if args.small:
        args.nodes, args.gangs, args.iters = 512, 64, 3

    snapshot = make_cluster(args.nodes)
    gangs = make_gangs(args.gangs)

    sock = os.path.join(tempfile.mkdtemp(), "placement.sock")
    address = f"unix:{sock}"
    proc = subprocess.Popen(
        [sys.executable, "-m", "grove_tpu.service.server",
         "--address", address],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        # scan a few lines for the banner (interpreter warnings may
        # precede it); a dead process means startup failed — surface its
        # output instead of hanging in a blocking read on a live pipe
        seen = []
        for _ in range(10):
            line = proc.stdout.readline()
            seen.append(line)
            if "listening" in line:
                break
            if not line or proc.poll() is not None:
                raise RuntimeError(
                    "placement service failed to start:\n" + "".join(seen)
                )
        else:
            proc.send_signal(signal.SIGTERM)
            raise RuntimeError(
                "placement service never reported listening:\n"
                + "".join(seen)
            )
        from grove_tpu.service import RemotePlacementEngine
        from grove_tpu.service.codec import encode_solve_request

        engine = RemotePlacementEngine(snapshot, address)
        engine.solve(gangs)  # warm-up: server-side compile + caches
        walls = []
        placed = 0
        for _ in range(args.iters):
            t0 = time.perf_counter()
            result = engine.solve(gangs)
            walls.append(time.perf_counter() - t0)
            placed = result.num_placed
        walls.sort()
        p99 = walls[min(len(walls) - 1, int(round(0.99 * (len(walls) - 1))))]
        wire = len(encode_solve_request(
            engine.epoch, gangs, snapshot.free.copy()))
        out = {
            "metric": f"gang placements/sec over the gRPC service boundary "
            f"({args.gangs} x 8-pod gangs, {args.nodes} nodes)",
            "value": round(args.gangs / p99, 1),
            "unit": "gangs/sec",
            "vs_baseline": 0.0,  # no serial comparison in service mode
            "p99_backlog_bind_seconds": round(p99, 4),
            "p50_backlog_bind_seconds": round(walls[len(walls) // 2], 4),
            "placed": placed,
            "request_bytes": wire,
            "engine": "service",
        }
        print(json.dumps(out))
        return 0
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            # a lingering server holds the accelerator and poisons the
            # next run's device acquisition (advisor r3)
            proc.kill()
            proc.wait(timeout=10)


def _trace_critical_path(tracer, metrics=None, binds: int = 0,
                         label: str = "trace") -> tuple[dict, list[str]]:
    """One tracer's fleet critical-path breakdown plus the regression
    gate: re-fold the retained ring and check (a) the telescoping
    invariant — every COMPLETE reconstructed path's segments sum
    exactly to its created->running total, the guarantee
    observability/causal.py pins — and (b) non-vacuity — a side that
    actually bound gangs must have reconstructed at least one path
    (zero paths with binds means an instrumentation hop fell off a
    subsystem). Returns (observatory report, failure strings)."""
    from grove_tpu.observability.causal import CriticalPathFolder

    failures: list[str] = []
    paths: list[dict] = []
    CriticalPathFolder(sink=paths.append).fold_all(tracer.finished)
    for p in paths:
        if not p["complete"]:
            continue
        drift = abs(sum(p["segments"].values()) - p["total"])
        if drift > 1e-6:
            failures.append(
                f"{label}: gang {p['gang']} critical path does not "
                f"telescope (drift {drift:.2e}s over {p['total']:.4f}s "
                "total)"
            )
    if binds > 0 and not paths:
        failures.append(
            f"{label}: {binds} gangs bound but zero critical paths "
            "reconstructed — the latency breakdown is vacuous"
        )
    return tracer.flush_critical_paths(metrics), failures


def bench_aggregate_overhead(num_nodes: int, replicas: int,
                             repeats: int = 5) -> tuple[dict, list[str]]:
    """The always-on mode's tax (`tracing.mode: aggregate`): the same
    apply+settle+delete workload on two harnesses — tracing off vs
    aggregate — interleaved in alternating order, p50 per side, with
    the <5% acceptance bound on the ratio. The aggregate side must also
    have FOLDED paths (its observatory is the whole point; zero folded
    paths would pass the wall gate vacuously). Returns (fields,
    failures); main() arms the gate only under --aggregate-overhead
    because a wall-ratio bound flakes on throttling hosts."""
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness
    from grove_tpu.tuning import tune_gc

    def mk_h(aggregate: bool) -> "Harness":
        return Harness(
            nodes=make_nodes(
                num_nodes,
                allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0},
            ),
            config=(
                {"tracing": {"enabled": True, "mode": "aggregate"}}
                if aggregate else None
            ),
        )

    sides = {True: mk_h(True), False: mk_h(False)}
    for h in sides.values():
        h.settle()
    tune_gc()
    walls: dict[bool, list[float]] = {True: [], False: []}
    seq = [0]

    def run(aggregate: bool) -> None:
        h = sides[aggregate]
        name = f"aggov{seq[0]}"
        seq[0] += 1
        t0 = time.perf_counter()
        h.apply(_churn_pcs(name, replicas))
        h.settle()
        walls[aggregate].append(time.perf_counter() - t0)
        # delete + resettle OUTSIDE the timed window, so every repeat
        # settles against the identical store population
        h.store.delete("PodCliqueSet", "default", name)
        h.settle()

    run(True)   # warm: compile + caches on both sides, untimed
    run(False)
    walls = {True: [], False: []}
    for rep in range(repeats):
        for side in ((True, False) if rep % 2 == 0 else (False, True)):
            run(side)
    p50_agg, p50_off = p50(walls[True]), p50(walls[False])
    overhead = p50_agg / p50_off - 1.0
    paths_folded = sides[True].cluster.tracer.critical.paths
    fields = {
        "aggregate_overhead_fraction": round(overhead, 4),
        "aggregate_settle_p50_seconds": round(p50_agg, 4),
        "baseline_settle_p50_seconds": round(p50_off, 4),
        "aggregate_paths_folded": paths_folded,
        "aggregate_overhead_bound": 0.05,
        "aggregate_overhead_ok": overhead <= 0.05,
        "aggregate_overhead_repeats": repeats,
        "aggregate_dominant_segment":
            sides[True].cluster.tracer.critical.dominant(),
    }
    failures = []
    if overhead > 0.05:
        failures.append(
            f"aggregate-mode overhead {overhead:.1%} exceeds the 5% "
            f"acceptance bound (aggregate p50 {p50_agg:.4f}s vs off "
            f"{p50_off:.4f}s over {repeats} interleaved repeats)"
        )
    if paths_folded == 0:
        failures.append(
            "aggregate-mode probe is vacuous: zero critical paths "
            "folded — the observatory never saw a bind"
        )
    return fields, failures


def bench_controlplane(
    num_nodes: int, replicas: int, trace_groups: dict | None = None
) -> dict:
    from grove_tpu.api.meta import ObjectMeta as Meta
    from grove_tpu.api.types import (
        Container,
        Pod,
        PodCliqueSet,
        PodCliqueSetSpec,
        PodCliqueSetTemplateSpec,
        PodCliqueSpec,
        PodCliqueTemplateSpec,
        PodSpec,
    )
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness

    def pcs(name):
        return PodCliqueSet(
            metadata=Meta(name=name),
            spec=PodCliqueSetSpec(
                replicas=replicas,
                template=PodCliqueSetTemplateSpec(
                    cliques=[
                        PodCliqueTemplateSpec(
                            name="w",
                            spec=PodCliqueSpec(
                                replicas=8,
                                pod_spec=PodSpec(
                                    containers=[
                                        Container(
                                            name="m", resources={"cpu": 1.0}
                                        )
                                    ]
                                ),
                            ),
                        )
                    ]
                ),
            ),
        )

    h = Harness(
        nodes=make_nodes(
            num_nodes,
            allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0},
        ),
        config=(
            {"tracing": {"enabled": True}} if trace_groups is not None
            else None
        ),
    )
    t0 = time.perf_counter()
    h.apply(pcs("cpwarm"))
    h.settle()
    cold = time.perf_counter() - t0
    # production process posture for the warm measurement (and for the
    # real server, service/server.py:main): freeze the steady-state object
    # graph, stop paying ~630 stop-the-world GC runs per settle
    from grove_tpu.tuning import tune_gc

    tune_gc()
    # Median-of-3 warm settles, DELETING the workload between runs so the
    # store population (and thus the scan/event cost) is identical each
    # time: one congested device round trip on the shared tunnel moved a
    # single-shot measurement by ±20%, the same treatment the solver wall
    # gets (p50-of-iters). The delete+resettle between runs is excluded.
    solve_h = h.cluster.metrics.histogram("grove_solver_backlog_bind_seconds")
    runs: list[tuple[float, float]] = []
    for i in range(3):
        name = f"cpbench{i}"
        solve_before = solve_h.sum
        t0 = time.perf_counter()
        h.apply(pcs(name))
        h.settle()
        wall = time.perf_counter() - t0
        runs.append((wall, solve_h.sum - solve_before))
        bound = sum(1 for p in h.store.scan(Pod.KIND) if p.node_name)
        if bound != 2 * replicas * 8:  # not assert: must survive python -O
            raise RuntimeError(
                f"controlplane bench invalid: {bound} pods bound, "
                f"expected {2 * replicas * 8}"
            )
        h.store.delete("PodCliqueSet", "default", name)
        h.settle()
    runs.sort()
    warm, solve_wall = runs[1]
    if trace_groups is not None:
        trace_groups["controlplane"] = h.cluster.tracer
    return {
        "controlplane_replicas": replicas,
        "controlplane_settle_seconds": round(warm, 2),
        "controlplane_cold_settle_seconds": round(cold, 2),
        "controlplane_gangs_per_sec": round(replicas / warm, 1),
        "controlplane_solve_seconds": round(solve_wall, 3),
        "controlplane_host_seconds": round(warm - solve_wall, 3),
        "controlplane_settle_basis": "p50_of_3",
    }


def _fanned_workload(fan: int, per_pcs: int, tag: str,
                     namespaces: int = 1) -> list:
    """The sharded/store regimes' fanned workload: `fan` PodCliqueSets
    of `per_pcs` replicas each (a PCS is one reconcile key, so a single
    mega-PCS would pin all parent-controller work — and all its durable
    writes — to one shard no matter how wide the plane runs).
    `namespaces` > 1 spreads the sets over that many namespaces, which
    is what spreads a partitioned store's (namespace, kind) write
    routing across partitions — the multi-namespace fleet shape."""
    from grove_tpu.api.meta import ObjectMeta as Meta
    from grove_tpu.api.types import (
        Container,
        PodCliqueSet,
        PodCliqueSetSpec,
        PodCliqueSetTemplateSpec,
        PodCliqueSpec,
        PodCliqueTemplateSpec,
        PodSpec,
    )

    return [
        PodCliqueSet(
            metadata=Meta(
                name=f"{tag}-{j}",
                namespace=(
                    f"bench-ns{j % namespaces}" if namespaces > 1
                    else "default"
                ),
            ),
            spec=PodCliqueSetSpec(
                replicas=per_pcs,
                template=PodCliqueSetTemplateSpec(
                    cliques=[
                        PodCliqueTemplateSpec(
                            name="w",
                            spec=PodCliqueSpec(
                                replicas=8,
                                pod_spec=PodSpec(
                                    containers=[
                                        Container(
                                            name="m",
                                            resources={"cpu": 1.0},
                                        )
                                    ]
                                ),
                            ),
                        )
                    ]
                ),
            ),
        )
        for j in range(fan)
    ]


def bench_store(args) -> int:
    """Durable-store write-path regime (`--store-bench`, ROADMAP item
    4a): committed-write throughput of the PARTITIONED write path
    (cluster/durability.PartitionedLog, `--partitions K`) vs the classic
    single WAL, both driving the same fanned workload through the full
    control plane under `--shards N`.

    Throughput is computed from each side's WAL COMMIT WALL
    (DurableLog.wall_seconds deltas: append + cadence-snapshot work),
    not the whole settle — the probe measures the durable write path,
    with the control plane as the load generator. The partitioned side
    reports two numbers:

      modeled    records / max(per-partition commit wall) — partitions
                 append and fsync to independent files, so a real
                 deployment overlaps them (one appender per partition;
                 the same parallel model as the sharded control-plane
                 bench's N-process fleet)
      in-process records / sum(per-partition walls) — what this
                 single-threaded sim actually pays (per-partition
                 snapshot cuts pickle only the partition's slice, so
                 even the same-thread number can win)

    Interleaved A/B with min/median/max per the shared bench-noise
    discipline (this host's throttling swings walls ~2x run-to-run).
    Exits nonzero when the writes never spread past one partition
    (vacuous coverage) or the modeled median fails to beat the single
    WAL."""
    import os
    import tempfile

    from grove_tpu.api.types import Pod
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness
    from grove_tpu.tuning import tune_gc

    shards = max(args.shards, 1)
    partitions = max(args.partitions, 2)
    repeats = 3 if args.small else 5
    num_nodes = 64 if args.small else min(args.nodes, 512)
    fan = max(8, shards * 8)
    per_pcs = 2 if args.small else 6
    namespaces = min(fan, 8)

    def durable_harness(wal_dir: str, parts: int) -> Harness:
        cfg: dict = {
            "durability": {
                "wal_dir": wal_dir,
                # fsync "never": the sim never kills the interpreter, so
                # physical durability is not what this probe measures —
                # the commit wall is serialization + append + snapshot
                # work; with fsync on, the per-partition overlap the
                # parallel model captures only widens
                "fsync": "never",
                "snapshot_interval_seconds": 120.0,
                "wal_max_bytes": 1 << 20,
                **({"partitions": parts} if parts > 1 else {}),
            }
        }
        if shards > 1:
            cfg["controllers"] = {"shards": shards}
        return Harness(
            nodes=make_nodes(
                num_nodes,
                allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0},
            ),
            config=cfg,
        )

    def measure_cycle(h: Harness, tag: str) -> dict:
        """One apply+settle cycle (the committed-write burst), deltas
        read from the durable layer; the teardown settles outside the
        measured window so the store population is constant run to
        run."""
        dur = h.cluster.durability
        walls0 = (
            dur.partition_walls() if hasattr(dur, "partition_walls")
            else None
        )
        wall0 = dur.wall_seconds
        rec0 = dur.wal_records_total
        workload = _fanned_workload(fan, per_pcs, tag, namespaces)
        t0 = time.perf_counter()
        for pcs in workload:
            h.apply(pcs)
        h.settle()
        settle_wall = time.perf_counter() - t0
        bound = sum(1 for p in h.store.scan(Pod.KIND) if p.node_name)
        if bound != fan * per_pcs * 8:
            raise RuntimeError(
                f"store bench invalid: {bound} pods bound, expected "
                f"{fan * per_pcs * 8}"
            )
        out = {
            "records": dur.wal_records_total - rec0,
            "commit_wall": dur.wall_seconds - wall0,
            "settle_wall": settle_wall,
        }
        if walls0 is not None:
            per = [b - a for a, b in zip(walls0, dur.partition_walls())]
            out["partition_walls"] = per
            out["modeled_wall"] = max(per)
        for pcs in workload:
            h.store.delete(
                "PodCliqueSet", pcs.metadata.namespace, pcs.metadata.name
            )
        h.settle()
        return out

    with tempfile.TemporaryDirectory(prefix="grove-store-bench-") as td:
        single = durable_harness(os.path.join(td, "single"), 1)
        part = durable_harness(os.path.join(td, "part"), partitions)
        # warm-up cycle per side: jit compiles + store shapes land
        # outside the measured window
        measure_cycle(single, "warm-s")
        measure_cycle(part, "warm-p")
        tune_gc()
        s_runs, p_runs = interleaved_ab(
            lambda i: measure_cycle(single, f"sbs{i}"),
            lambda i: measure_cycle(part, f"sbp{i}"),
            repeats,
        )
        active = sum(
            1 for p in part.cluster.durability.partitions
            if p.wal_records_total > 0
        )

    s_tp = [r["records"] / r["commit_wall"] for r in s_runs]
    p_tp_model = [r["records"] / r["modeled_wall"] for r in p_runs]
    p_tp_inproc = [r["records"] / r["commit_wall"] for r in p_runs]
    speedup = p50(p_tp_model) / p50(s_tp)
    failures = []
    if active <= 1:
        failures.append(
            "coverage: committed writes never spread past one partition "
            "— the fanned workload should hash (namespace, kind) keys "
            "across the layout"
        )
    if speedup <= 1.0:
        failures.append(
            f"partitioned commit did not beat the single WAL at the "
            f"median (modeled speedup {speedup:.2f})"
        )
    out = {
        "metric": (
            f"durable committed-write throughput ({partitions} "
            f"partitions vs single WAL, {fan}x{per_pcs}-replica fanned "
            f"workload, shards={shards})"
        ),
        "value": round(p50(p_tp_model), 1),
        "unit": "committed-writes/sec",
        "vs_baseline": round(speedup, 2),
        "store_bench_shards": shards,
        "store_bench_partitions": partitions,
        "store_bench_active_partitions": active,
        "store_bench_namespaces": namespaces,
        "store_bench_records_per_cycle": s_runs[-1]["records"],
        "store_bench_repeats": repeats,
        "store_bench_interleaved": True,
        "store_bench_model": "records_over_max_partition_commit_wall",
        **wall_stats(s_tp, "store_single_",
                     suffix="writes_per_sec", round_to=1),
        **wall_stats(p_tp_model, "store_partitioned_",
                     suffix="writes_per_sec", round_to=1),
        "store_partitioned_inprocess_p50_writes_per_sec": round(
            p50(p_tp_inproc), 1
        ),
        "store_partitioned_inprocess_speedup": round(
            p50(p_tp_inproc) / p50(s_tp), 2
        ),
        **wall_stats([r["commit_wall"] for r in s_runs],
                     "store_single_commit_wall_"),
        **wall_stats([r["modeled_wall"] for r in p_runs],
                     "store_partitioned_commit_wall_"),
        **wall_stats([r["commit_wall"] for r in p_runs],
                     "store_partitioned_inprocess_wall_"),
        "store_partition_commit_walls": [
            round(w, 4) for w in p_runs[-1]["partition_walls"]
        ],
        **wall_stats([r["settle_wall"] for r in s_runs],
                     "store_single_settle_"),
        **wall_stats([r["settle_wall"] for r in p_runs],
                     "store_partitioned_settle_"),
        "backend": __import__("jax").default_backend(),
    }
    for f in failures:
        print(f"STORE BENCH FAILURE: {f}", file=sys.stderr)
    print(json.dumps(out))
    return 1 if failures else 0


def bench_recovery(num_nodes: int, replicas: int,
                   partitions: int = 1) -> dict:
    """Cold-restart recovery probe (`--recovery`): settle the standard
    control-plane workload on a DURABLE store (WAL + snapshots in a temp
    dir, fsync per commit — the honest production posture), then model a
    whole-process crash at steady state: Harness.cold_restart drops the
    live store, recovers it from disk (latest valid snapshot + WAL
    replay), expires coordination leases, rebuilds manager/scheduler/
    kubelet soft state, and settle() re-derives the fixpoint.

    recovery_seconds is the whole outage window the operator would see:
    disk replay + soft-state rebuild + re-settle. The split fields say
    where it went (recovery_replay_seconds is the store-rebuild part
    alone). Durable write-path overhead is visible by comparing
    recovery_durable_cold_settle_seconds (this harness's first settle,
    WAL armed, jit-cold) against controlplane_cold_settle_seconds from
    the same run.

    partitions > 1 runs the same probe a second time on the PARTITIONED
    store (per-(namespace, kind) WAL chains; recovery heap-merges the
    partition replay streams by global seq) and reports the
    recovery_partitioned_* fields alongside."""
    import os
    import tempfile

    from grove_tpu.api.meta import ObjectMeta as Meta
    from grove_tpu.api.types import (
        Container,
        Pod,
        PodCliqueSet,
        PodCliqueSetSpec,
        PodCliqueSetTemplateSpec,
        PodCliqueSpec,
        PodCliqueTemplateSpec,
        PodSpec,
    )
    from grove_tpu.chaos.harness import settled_fingerprint
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness

    workload = PodCliqueSet(
        metadata=Meta(name="recovery"),
        spec=PodCliqueSetSpec(
            replicas=replicas,
            template=PodCliqueSetTemplateSpec(
                cliques=[
                    PodCliqueTemplateSpec(
                        name="w",
                        spec=PodCliqueSpec(
                            replicas=8,
                            pod_spec=PodSpec(
                                containers=[
                                    Container(
                                        name="m", resources={"cpu": 1.0}
                                    )
                                ]
                            ),
                        ),
                    )
                ]
            ),
        ),
    )
    def probe(wal_dir: str, parts: int) -> dict:
        h = Harness(
            nodes=make_nodes(
                num_nodes,
                allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0},
            ),
            config={"durability": {
                "wal_dir": wal_dir,
                **({"partitions": parts} if parts > 1 else {}),
            }},
        )
        t0 = time.perf_counter()
        h.apply(workload)  # create() clones its input; reuse is safe
        h.settle()
        durable_settle = time.perf_counter() - t0
        fixpoint = settled_fingerprint(h.store)
        wal = h.cluster.durability.debug_state()
        t0 = time.perf_counter()
        stats = h.cold_restart()
        replay = time.perf_counter() - t0
        h.settle()
        wall = time.perf_counter() - t0
        if settled_fingerprint(h.store) != fixpoint:  # survives python -O
            raise RuntimeError(
                "recovery bench invalid: post-recovery fixpoint diverged"
            )
        return {
            "seconds": round(wall, 3),
            "replay_seconds": round(replay, 3),
            "durable_cold_settle_seconds": round(durable_settle, 2),
            "wal": wal,
            "stats": stats,
        }

    with tempfile.TemporaryDirectory(prefix="grove-bench-wal-") as td:
        single = probe(os.path.join(td, "single"), 1)
        out = {
            "recovery_replicas": replicas,
            "recovery_seconds": single["seconds"],
            "recovery_replay_seconds": single["replay_seconds"],
            "recovery_durable_cold_settle_seconds": single[
                "durable_cold_settle_seconds"
            ],
            "recovery_wal_records": single["wal"]["wal_records_total"],
            "recovery_wal_bytes": single["wal"]["wal_bytes_total"],
            "recovery_outcome": single["stats"]["outcome"],
            "recovery_records_replayed": single["stats"][
                "wal_records_replayed"
            ],
        }
        if partitions > 1:
            part = probe(os.path.join(td, "part"), partitions)
            out.update({
                "recovery_partitions": partitions,
                "recovery_partitioned_seconds": part["seconds"],
                "recovery_partitioned_replay_seconds": part[
                    "replay_seconds"
                ],
                "recovery_partitioned_outcome": part["stats"]["outcome"],
                "recovery_partitioned_records_replayed": part["stats"][
                    "wal_records_replayed"
                ],
            })
    return out


def bench_replication(args) -> int:
    """HA failover regime (`--replication`, ROADMAP item 4b) — three
    probes over the fanned multi-namespace workload, every comparison
    interleaved A/B (this host's throttling swings walls ~2x run-to-run;
    the shared bench-noise discipline):

      failover vs cold restart   Both sides settle the same workload on
          a durable store and then lose the leader process at steady
          state. The recovery side replays the WAL from disk
          (Harness.cold_restart — the PR 9 posture, outage proportional
          to history). The failover side promotes its SEMI-SYNC standby
          with catch_up=False — total leader loss, host AND disk: only
          the standby's already-applied state survives — and must come
          back with ZERO committed-write loss (promoted seq equals the
          leader's committed head; the settled fingerprint matches the
          pre-kill fixpoint). The headline gate: failover p50 strictly
          under recovery p50.

      replication lag            The async bounded-lag mode under the
          --shards N fanned control plane: lag sampled (records +
          leader-clock seconds) after every settle step BEFORE the
          driver's poll, p50/p99 reported — the alerting numbers the
          runbook quotes.

      semi-sync commit tax       The same apply/settle/delete cycle on
          two live planes — ack async vs semi-sync — interleaved; the
          tax is the ratio of settle-wall p50s (semi-sync pays one
          standby apply + durable append inside every commit)."""
    import os
    import tempfile

    from grove_tpu.chaos.harness import settled_fingerprint
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness

    small = args.small
    num_nodes = 64 if small else 200
    fan = 8
    per_pcs = 3 if small else 8
    namespaces = 4
    repeats = 3 if small else 5
    churn_cycles = 1 if small else 2
    partitions = max(args.partitions, 1)
    failures: list[str] = []

    def nodes():
        return make_nodes(
            num_nodes,
            allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0},
        )

    def durable_cfg(root: str, replication: bool = True,
                    ack: str = "semi-sync", shards: int = 1) -> dict:
        cfg: dict = {
            "durability": {
                "wal_dir": os.path.join(root, "wal"),
                **({"partitions": partitions} if partitions > 1 else {}),
            },
        }
        if replication:
            cfg["replication"] = {
                "enabled": True,
                "ack_mode": ack,
                "standby_wal_dir": os.path.join(root, "standby"),
            }
        if shards > 1:
            cfg["controllers"] = {"shards": shards}
        return cfg

    def apply_all(h, tag: str) -> None:
        for pcs in _fanned_workload(fan, per_pcs, tag, namespaces):
            h.apply(pcs)

    def delete_all(h, tag: str) -> None:
        for j in range(fan):
            h.store.delete(
                "PodCliqueSet", f"bench-ns{j % namespaces}", f"{tag}-{j}"
            )

    def settled(h, tag: str) -> None:
        """Settle the fanned workload plus churn cycles, growing the WAL
        history the cold-restart side must replay (and the failover side
        must NOT care about)."""
        apply_all(h, tag)
        h.settle()
        for k in range(churn_cycles):
            apply_all(h, f"{tag}c{k}")
            h.settle()
            delete_all(h, f"{tag}c{k}")
            h.settle()

    # -- probe A: failover vs cold restart, interleaved ---------------------
    def failover_once(i: int) -> dict:
        with tempfile.TemporaryDirectory(prefix="grove-repl-fo-") as td:
            h = Harness(nodes=nodes(), config=durable_cfg(td))
            settled(h, f"fo{i}")
            fixpoint = settled_fingerprint(h.store)
            committed = h.store.last_seq
            t0 = time.perf_counter()
            stats = h.promote_standby(catch_up=False)
            promote_wall = time.perf_counter() - t0
            if stats["lost_records"] or h.store.last_seq != committed:
                failures.append(
                    f"failover[{i}]: committed-write loss — leader head "
                    f"{committed}, promoted head {h.store.last_seq}, "
                    f"lost_records={stats['lost_records']}"
                )
            h.settle()
            wall = time.perf_counter() - t0
            if settled_fingerprint(h.store) != fixpoint:
                failures.append(
                    f"failover[{i}]: post-promotion fixpoint diverged"
                )
            return {"seconds": wall, "promote_seconds": promote_wall,
                    "term": stats["term"]}

    def recovery_once(i: int) -> dict:
        with tempfile.TemporaryDirectory(prefix="grove-repl-cr-") as td:
            h = Harness(
                nodes=nodes(), config=durable_cfg(td, replication=False)
            )
            settled(h, f"cr{i}")
            fixpoint = settled_fingerprint(h.store)
            t0 = time.perf_counter()
            stats = h.cold_restart()
            replay_wall = time.perf_counter() - t0
            h.settle()
            wall = time.perf_counter() - t0
            if settled_fingerprint(h.store) != fixpoint:
                failures.append(
                    f"recovery[{i}]: post-recovery fixpoint diverged"
                )
            return {"seconds": wall, "replay_seconds": replay_wall,
                    "records": stats["wal_records_replayed"]}

    fo_runs, cr_runs = interleaved_ab(failover_once, recovery_once,
                                      repeats)
    fo_walls = [r["seconds"] for r in fo_runs]
    cr_walls = [r["seconds"] for r in cr_runs]
    if p50(fo_walls) >= p50(cr_walls):
        failures.append(
            f"failover p50 {p50(fo_walls):.3f}s did not beat the "
            f"cold-restart p50 {p50(cr_walls):.3f}s"
        )

    # -- probe B: replication lag under the sharded fanned load -------------
    lag_records: list[int] = []
    lag_seconds: list[float] = []
    with tempfile.TemporaryDirectory(prefix="grove-repl-lag-") as td:
        h = Harness(
            nodes=nodes(),
            config=durable_cfg(td, ack="async", shards=args.shards),
        )
        standby = h.cluster.standby
        for step in range(4 if small else 6):
            apply_all(h, f"lag{step}")
            h.settle()
            lag_records.append(standby.lag_records())
            lag_seconds.append(standby.lag_seconds())
            standby.poll()
            delete_all(h, f"lag{step}")
            h.settle()
            lag_records.append(standby.lag_records())
            lag_seconds.append(standby.lag_seconds())
            standby.poll()
            h.advance(1.0)
        if standby.records_applied_total == 0:
            failures.append("lag probe vacuous: standby applied nothing")
        max_lag_bound = h.config.replication.max_lag_records

    def pctl(samples: list, q: float):
        s = sorted(samples)
        return s[min(len(s) - 1, int(q * (len(s) - 1) + 0.999))]

    # -- probe C: semi-sync commit tax, interleaved -------------------------
    with tempfile.TemporaryDirectory(prefix="grove-repl-tax-") as td:
        ha = Harness(
            nodes=nodes(), config=durable_cfg(
                os.path.join(td, "a"), ack="async"
            )
        )
        hs = Harness(
            nodes=nodes(), config=durable_cfg(
                os.path.join(td, "s"), ack="semi-sync"
            )
        )
        for h, tag in ((ha, "warma"), (hs, "warms")):
            apply_all(h, tag)
            h.settle()

        def cycle(h, tag: str) -> float:
            t0 = time.perf_counter()
            apply_all(h, tag)
            h.settle()
            delete_all(h, tag)
            h.settle()
            # async mode may still trail: drain so both sides end each
            # cycle fully shipped and the next cycle starts equal
            h.cluster.standby.poll()
            return time.perf_counter() - t0

        async_walls, semi_walls = interleaved_ab(
            lambda i: cycle(ha, f"taxa{i}"),
            lambda i: cycle(hs, f"taxs{i}"),
            repeats,
        )
    tax = p50(semi_walls) / p50(async_walls) if p50(async_walls) else 0.0

    out = {
        "metric": "store_failover",
        "unit": "seconds",
        "value": round(p50(fo_walls), 3),
        "replication_nodes": num_nodes,
        "replication_gangs": fan * per_pcs,
        "replication_partitions": partitions,
        "replication_lag_shards": args.shards,
        "replication_repeats": repeats,
        "failover_zero_loss": not any("loss" in f for f in failures),
        "failover_terms": [r["term"] for r in fo_runs],
        **wall_stats(fo_walls, "failover_", round_to=3),
        **wall_stats([r["promote_seconds"] for r in fo_runs],
                     "failover_promote_", round_to=3),
        **wall_stats(cr_walls, "recovery_", round_to=3),
        **wall_stats([r["replay_seconds"] for r in cr_runs],
                     "recovery_replay_", round_to=3),
        "recovery_records_replayed_p50": p50(
            [r["records"] for r in cr_runs]
        ),
        "failover_vs_recovery_speedup": round(
            p50(cr_walls) / p50(fo_walls), 2
        ) if p50(fo_walls) else None,
        "replication_lag_records_p50": pctl(lag_records, 0.50),
        "replication_lag_records_p99": pctl(lag_records, 0.99),
        "replication_lag_seconds_p50": round(pctl(lag_seconds, 0.50), 3),
        "replication_lag_seconds_p99": round(pctl(lag_seconds, 0.99), 3),
        "replication_max_lag_records_bound": max_lag_bound,
        "semi_sync_tax": round(tax, 3),
        **wall_stats(async_walls, "ack_async_cycle_", round_to=3),
        **wall_stats(semi_walls, "ack_semi_sync_cycle_", round_to=3),
        "backend": __import__("jax").default_backend(),
    }
    if pctl(lag_records, 0.99) > max_lag_bound:
        failures.append(
            f"async lag p99 {pctl(lag_records, 0.99)} exceeded the "
            f"configured bound {max_lag_bound}"
        )
    for f in failures:
        print(f"REPLICATION BENCH FAILURE: {f}", file=sys.stderr)
    print(json.dumps(out))
    return 1 if failures else 0


def bench_federation(args) -> int:
    """Federation throughput regime (`--federation`,
    grove_tpu/federation): the same fanned workload settled on one
    3N-node cluster vs routed across a 3-member federation of N-node
    clusters, interleaved A/B (the shared bench-noise discipline).

    Throughput model: member control planes share NOTHING — not even a
    store — so a real deployment runs them as independent processes
    whose settle walls overlap (the bench_controlplane_sharded modeling
    argument, one level up, with zero cross-plane serial residue). The
    deterministic simulation settles members sequentially, so the
    modeled federation wall is

        routing wall (the coordinator's aggregate cuts + least-loaded
        pick, genuinely serial) + the SLOWEST member's settle wall

    and near-linear scaling is the claim under test: each member
    solves a third of the gangs over a third of the nodes.

    Gates (nonzero exit): the routed workload must actually land on >=
    2 members — a vacuous spread (everything on one member) would make
    the comparison meaningless, not just slow — and the modeled
    federation p50 must beat the single-cluster p50."""
    import os
    import tempfile
    from collections import Counter

    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness
    from grove_tpu.federation import FederationCoordinator

    small = args.small
    clusters = 3
    per_cluster_nodes = 24 if small else 64
    fan = 6 if small else 12
    per_pcs = 2 if small else 4
    repeats = 3 if small else 5
    total_gangs = fan * per_pcs
    alloc = {"cpu": 32.0, "memory": 128.0, "tpu": 8.0}
    failures: list[str] = []

    with tempfile.TemporaryDirectory() as td:
        fed = FederationCoordinator(
            {
                "durability": {"wal_dir": os.path.join(td, "fed")},
                "federation": {"enabled": True, "clusters": clusters},
            },
            [
                make_nodes(
                    per_cluster_nodes, allocatable=dict(alloc),
                    name_prefix=f"c{i}-n",
                )
                for i in range(clusters)
            ],
        )
        single = Harness(
            nodes=make_nodes(
                clusters * per_cluster_nodes, allocatable=dict(alloc)
            ),
            config={"durability": {"wal_dir": os.path.join(td, "single")}},
        )
        spread: Counter = Counter()
        routing_walls: list[float] = []
        member_walls: list[float] = []

        def measure_fed(i: int) -> float:
            tag = f"ff{i}"
            workload = _fanned_workload(fan, per_pcs, tag)
            t0 = time.perf_counter()
            homes = [fed.apply(pcs) for pcs in workload]
            routing = time.perf_counter() - t0
            walls = []
            for cell in fed.cells:
                t1 = time.perf_counter()
                cell.harness.settle()
                walls.append(time.perf_counter() - t1)
            spread.update(h for h in homes if h)
            routing_walls.append(routing)
            member_walls.append(max(walls))
            # constant store population run to run (the
            # bench_controlplane delete discipline)
            for j, home in enumerate(homes):
                if home is None:
                    continue
                cell = fed.by_name[home]
                cell.cluster.store.delete(
                    "PodCliqueSet", "default", f"{tag}-{j}"
                )
                fed._routes.pop(("default", f"{tag}-{j}"), None)
            for cell in fed.cells:
                cell.harness.settle()
            return routing + max(walls)

        def measure_single(i: int) -> float:
            tag = f"fs{i}"
            t0 = time.perf_counter()
            for pcs in _fanned_workload(fan, per_pcs, tag):
                single.apply(pcs)
            single.settle()
            wall = time.perf_counter() - t0
            for j in range(fan):
                single.store.delete("PodCliqueSet", "default", f"{tag}-{j}")
            single.settle()
            return wall

        # warm both sides once (JIT compilation + store genesis land
        # outside the timed repeats on both sides equally)
        measure_fed(-1)
        measure_single(-1)
        spread.clear()
        routing_walls.clear()
        member_walls.clear()
        fed_walls, single_walls = interleaved_ab(
            measure_fed, measure_single, repeats
        )
        fed.close()

    speedup = p50(single_walls) / max(p50(fed_walls), 1e-9)
    out = {
        "bench": "federation",
        "clusters": clusters,
        "nodes_per_cluster": per_cluster_nodes,
        "total_gangs": total_gangs,
        "repeats": repeats,
        "modeled_speedup": round(speedup, 3),
        "spread": {name: spread[name] for name in sorted(spread)},
        **wall_stats(fed_walls, "federation_modeled_", round_to=3),
        **wall_stats(single_walls, "single_cluster_", round_to=3),
        **wall_stats(routing_walls, "routing_", round_to=4),
        **wall_stats(member_walls, "slowest_member_", round_to=3),
        "backend": __import__("jax").default_backend(),
    }
    if len([c for c in spread if spread[c] > 0]) < 2:
        failures.append(
            f"vacuous spread: the routed workload landed on "
            f"{sorted(spread)} — a federation comparison needs >= 2 "
            "members doing work"
        )
    if speedup <= 1.0:
        failures.append(
            f"modeled federation throughput gained nothing: speedup "
            f"{round(speedup, 3)} <= 1.0 over the single cluster"
        )
    for f in failures:
        print(f"FEDERATION BENCH FAILURE: {f}", file=sys.stderr)
    print(json.dumps(out))
    return 1 if failures else 0


def bench_controlplane_sharded(
    num_nodes: int, replicas: int, shards: int,
) -> dict:
    """The horizontally sharded control plane (controller/sharding.py)
    through the same full path as bench_controlplane, plus a failover
    probe.

    Throughput model: workers share nothing but the store (the
    apiserver), so a real deployment runs them as N processes whose
    walls overlap. The deterministic simulation steps them sequentially
    and accumulates per-worker wall clocks, so the modeled parallel
    settle wall is

        serial residue (kubelet ticks + harness glue, measured as
        settle wall minus the sum of worker walls) + the SLOWEST
        worker's wall

    — the critical path an N-process fleet pays. The per-shard settle
    skew (max - min worker wall) is reported alongside: consistent
    hashing only helps while the key space spreads evenly.

    Failover probe: apply a fresh workload, run two rounds (work in
    flight), kill the worker owning the scheduler singleton, and
    measure VIRTUAL seconds to full re-convergence — the protocol
    bounds it by one shard lease duration (orphaned-lease detection)
    plus one coordination round."""
    from grove_tpu.api.types import Pod
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness
    from grove_tpu.tuning import tune_gc

    # The workload FANS OUT across PodCliqueSets (8 per worker replica,
    # see _fanned_workload): the sharded regime models the
    # many-workload fleet the plane actually scales for. The
    # single-replica reference below measures the SAME fanned workload,
    # so the speedup is workload-for-workload.
    fan = max(1, shards * 8)
    per_pcs = max(1, replicas // fan)
    total_gangs = fan * per_pcs

    def apply_workload(h, tag: str) -> None:
        for pcs in _fanned_workload(fan, per_pcs, tag):
            h.apply(pcs)

    def delete_workload(h, tag: str) -> None:
        for j in range(fan):
            h.store.delete("PodCliqueSet", "default", f"{tag}-{j}")

    def nodes():
        return make_nodes(
            num_nodes, allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0}
        )

    def measure_once(h, tag: str) -> tuple[float, dict | None]:
        """One warm settle (the bench_controlplane discipline: delete
        after, so the store population is constant run to run)."""
        sm = h.manager
        if hasattr(sm, "reset_walls"):
            sm.reset_walls()
        t0 = time.perf_counter()
        apply_workload(h, tag)
        h.settle()
        wall = time.perf_counter() - t0
        bound = sum(1 for p in h.store.scan(Pod.KIND) if p.node_name)
        if bound != 2 * total_gangs * 8:
            raise RuntimeError(
                f"sharded controlplane bench invalid: {bound} pods "
                f"bound, expected {2 * total_gangs * 8}"
            )
        walls = sm.worker_walls() if hasattr(sm, "worker_walls") else None
        delete_workload(h, tag)
        h.settle()
        return wall, walls

    out: dict = {"controlplane_shards": shards}

    # Single-replica reference on the SAME fanned workload (the main
    # section's 1-PCS number is a different workload shape, so it is
    # never reused here). Both planes stay alive and their measurement
    # runs INTERLEAVE: this machine's load noise arrives in bursts that
    # slow whole runs, and adjacent pairs share the burst, so the
    # reported speedup (a ratio of p50s over interleaved samples) is far
    # more stable than two separately-measured medians.
    ref = Harness(nodes=nodes())
    apply_workload(ref, "warmref")
    ref.settle()
    h = Harness(
        nodes=nodes(), config={"controllers": {"shards": shards}}
    )
    apply_workload(h, "warmsh")
    h.settle()
    tune_gc()
    # ladder warm-up: sharded settles can slice the backlog differently
    # run to run (staggered ungates across workers), and an XLA compile
    # for a fresh bucket shape landing inside the measured phase would be
    # misread as host cost — two throwaway apply/delete cycles cover the
    # shapes (same treatment as the churn bench's warmup ladder)
    for i in range(2):
        apply_workload(h, f"cpshwarm{i}")
        h.settle()
        delete_workload(h, f"cpshwarm{i}")
        h.settle()
    ref_walls, runs = interleaved_ab(
        lambda i: measure_once(ref, f"cpsr{i}")[0],
        lambda i: measure_once(h, f"cpsh{i}"),
        5,
    )
    single_gangs_per_sec = total_gangs / p50(ref_walls)
    out["controlplane_sharded_baseline_gangs_per_sec"] = round(
        single_gangs_per_sec, 1
    )
    modeled = []
    for wall, walls in runs:
        worker_sum = sum(walls.values())
        worker_max = max(walls.values())
        serial_residue = max(0.0, wall - worker_sum)
        modeled.append((serial_residue + worker_max, wall, walls))
    modeled.sort(key=lambda r: r[0])
    m_wall, in_process_wall, walls = modeled[len(modeled) // 2]
    skew = max(walls.values()) - min(walls.values())
    out.update({
        "controlplane_sharded_gangs_per_sec": round(
            total_gangs / m_wall, 1
        ),
        "controlplane_sharded_settle_seconds": round(m_wall, 3),
        "controlplane_sharded_model": "serial_residue_plus_max_worker_wall",
        "controlplane_sharded_replicas": total_gangs,
        "controlplane_sharded_workloads": fan,
        "controlplane_sharded_inprocess_wall_seconds": round(
            in_process_wall, 3
        ),
        "controlplane_shard_walls": {
            k: round(v, 3) for k, v in sorted(walls.items())
        },
        "controlplane_shard_settle_skew_seconds": round(skew, 4),
        "controlplane_sharded_speedup": round(
            (total_gangs / m_wall) / single_gangs_per_sec, 2
        ),
        "controlplane_sharded_settle_basis": "p50_of_5",
    })

    # -- failover probe ----------------------------------------------------
    sm = h.manager
    lease = h.config.controllers.shard_lease_duration_seconds
    _shard, owner = sm.shard_owner("", "schedule")
    idx = next(w.index for w in sm.workers if w.identity == owner)
    # the scheduler's worker dies AS WORK ARRIVES (a control-plane round
    # batches the whole pipeline, so any later kill would land after the
    # binds): the workload fans out on the survivors while the
    # scheduler's shard sits orphaned, and recovery measures the full
    # orphan-detect -> reassign -> relist -> solve path
    apply_workload(h, "cpfail")
    killed_at = h.clock.now()
    if not sm.kill_worker(idx):  # not assert: must survive python -O
        raise RuntimeError(
            "failover probe could not kill the scheduler worker"
        )
    recovery = None
    for _ in range(256):
        h.settle()
        bound = sum(
            1 for p in h.store.scan(Pod.KIND)
            if p.node_name
            and (
                p.metadata.labels.get("app.kubernetes.io/part-of") or ""
            ).startswith("cpfail-")
        )
        if bound == total_gangs * 8:
            recovery = h.clock.now() - killed_at
            break
        h.advance(0.5)
    out["shard_failover_recovery_seconds"] = (
        round(recovery, 2) if recovery is not None else None
    )
    out["shard_failover_lease_bound_seconds"] = lease
    out["shard_failover_recovered"] = recovery is not None
    sm.revive_worker(idx)
    delete_workload(h, "cpfail")
    h.settle()
    return out


def churn_workload(
    h,
    rate: float,
    duration: float,
    batch_dt: float = 0.5,
    population: int = 600,
    standing_name: str = "standing",
    warmup_batches: int = 3,
    measure: bool = True,
    scale_every: float = 10.0,
    crash_every: float = 7.0,
    update_every: float = 25.0,
) -> dict:
    """Drive a steady gang-arrival stream against a WARM control plane:
    every batch_dt virtual seconds, rate*batch_dt single-replica 8-pod
    PCS arrive and the oldest beyond `population` are deleted (full
    cascade: finalizers, pods, gangs, cliques, services), with a scale
    event on the standing PCS every ~10 virtual seconds and a container
    crash + recovery every ~7. The virtual clock advances batch_dt per
    batch so retry/termination timers fire naturally.

    Latency is measured in WALL seconds per gang, creation->Scheduled
    (the bind lands inside the batch's settle, so a gang's latency
    includes its queueing behind the rest of the batch and any carryover
    backlog — exactly the p99 a steady-arrival operator sees). Shared by
    bench.py (full scale) and the CI-speed variant in
    tests/test_controlplane_scale.py.

    Ref anchor: the reference operator's E2E gang-scheduling suite tests
    under contention and churn, not bulk apply
    (operator/e2e/tests/gang_scheduling_test.go:34-1187); its README
    claims sustained operation at fleet scale (README.md:9).
    """
    import collections

    from grove_tpu.api.meta import get_condition
    from grove_tpu.api.naming import base_podgang_name
    from grove_tpu.api.podgang import PodGang, PodGangConditionType

    store = h.store
    # name prefix unique per invocation (store seqs are monotonic), so
    # repeated churn phases against one harness never collide on names
    prefix = f"churn-{store.last_seq}"
    batch = max(1, int(round(rate * batch_dt)))
    n_batches = max(1, int(round(duration / batch_dt)))
    alive: collections.deque[str] = collections.deque()
    pending: dict[str, float] = {}  # gang name -> creation wall time
    latencies: list[float] = []
    seq = 0
    crashed: str | None = None
    scale_dir = 1
    created = deleted = scale_events = crashes = updates = 0
    deleted_before_bind = 0
    measured_wall = 0.0

    # Warmup covers the whole solver BUCKET LADDER up to the batch size,
    # not just the steady batch: scale events and crash recoveries produce
    # small odd-sized solves mid-stream, and an XLA compile for a fresh
    # bucket shape (seconds) landing inside the measured phase would be
    # misread as a multi-second p99 bind.
    ladder = []
    size = 1
    while size < batch:
        ladder.append(size)
        size *= 2
    warmup_sizes = (ladder + [batch] * warmup_batches)

    for b in range(-len(warmup_sizes), n_batches):
        measuring = measure and b >= 0
        this_batch = batch if b >= 0 else warmup_sizes[b + len(warmup_sizes)]
        t0 = time.perf_counter()
        for _ in range(this_batch):
            name = f"{prefix}-{seq}"
            seq += 1
            h.apply(_churn_pcs(name))
            alive.append(name)
            pending[base_podgang_name(name, 0)] = time.perf_counter()
            if measuring:
                created += 1
        while len(alive) > population:
            victim = alive.popleft()
            store.delete("PodCliqueSet", "default", victim)
            # a gang deleted while still awaiting bind leaves the latency
            # sample — its (worst-case) latency is unknowable — but is
            # COUNTED: bound + unbound_final + deleted_before_bind always
            # reconciles with created, so censored samples are visible
            if pending.pop(base_podgang_name(victim, 0), None) is not None:
                if measuring:
                    deleted_before_bind += 1
            if measuring:
                deleted += 1
        # mixed events on the standing workload (the reference's E2E fault
        # model: scale churn + container crashes + rolling updates
        # mid-stream)
        vnow = h.clock.now()

        def crossed(period: float) -> bool:
            return b >= 0 and int(vnow / period) != int(
                (vnow - batch_dt) / period
            )

        if crossed(scale_every):
            pcs_obj = store.get("PodCliqueSet", "default", standing_name)
            if pcs_obj is not None:
                pcs_obj.spec.replicas += 10 * scale_dir
                scale_dir = -scale_dir
                store.update(pcs_obj)
                scale_events += 1
        if crossed(update_every):
            # rolling update IN the stream: flip a small CANARY
            # workload's template (cpu request), changing its hash — the
            # replica-at-a-time / pod-at-a-time rollout then runs to
            # completion inside the batch settle while arrivals keep
            # flowing. The canary is deliberately small: the simulated
            # kubelet makes pods ready instantly, so settle() drives a
            # whole rollout to its fixpoint within one batch, and a
            # full-standing-fleet rollout would blow the harness round
            # budget rather than model anything realistic.
            canary = f"{standing_name}-canary"
            pcs_obj = store.get("PodCliqueSet", "default", canary)
            if pcs_obj is None:
                h.apply(_churn_pcs(canary, 2))  # born; first FLIP counts
            else:
                c = pcs_obj.spec.template.cliques[0].spec.pod_spec.containers[0]
                cur = c.resources.get("cpu", 1.0)
                c.resources = dict(c.resources, cpu=(
                    1.05 if cur == 1.0 else 1.0
                ))
                store.update(pcs_obj)
                updates += 1  # a real template change -> rollout ran
        if crossed(crash_every):
            if crashed is not None:
                h.kubelet.recover_pod("default", crashed)
                crashed = None
            else:
                from grove_tpu.api import constants
                from grove_tpu.api.types import Pod

                target = next(
                    (
                        p for p in store.scan(
                            Pod.KIND,
                            labels={constants.LABEL_PART_OF: standing_name},
                        )
                        if p.status.ready
                    ),
                    None,
                )
                if target is not None:
                    crashed = target.metadata.name
                    h.kubelet.crash_pod("default", crashed)
                    crashes += 1
        h.clock.advance(batch_dt)
        h.settle()
        # long-run hygiene: the steady stream would otherwise grow the
        # append-only event log without bound (~3k events/batch), and
        # every consumer's drain slices an ever-longer list
        h.compact_events()
        now = time.perf_counter()
        if measuring:
            measured_wall += now - t0
        # collect bind latencies for gangs whose Scheduled landed
        done = []
        for gname, t_created in pending.items():
            gang = store.peek(PodGang.KIND, "default", gname)
            if gang is None:
                continue
            cond = get_condition(
                gang.status.conditions,
                PodGangConditionType.SCHEDULED.value,
            )
            if cond is not None and cond.status == "True":
                if measuring:
                    latencies.append(now - t_created)
                done.append(gname)
        for gname in done:
            del pending[gname]
    if crashed is not None:
        h.kubelet.recover_pod("default", crashed)
        h.settle()
    latencies.sort()

    def pct(p):
        if not latencies:
            return 0.0
        return latencies[min(len(latencies) - 1,
                             int(round(p * (len(latencies) - 1))))]

    return {
        "offered_gangs_per_sec": rate,
        "sustained_gangs_per_sec": (
            round(created / measured_wall, 1) if measured_wall else 0.0
        ),
        "bound": len(latencies),
        "created": created,
        "deleted": deleted,
        "deleted_before_bind": deleted_before_bind,
        "scale_events": scale_events,
        "crashes": crashes,
        "updates": updates,
        "unbound_final": len(pending),
        "p50_bind_seconds": round(pct(0.50), 4),
        "p99_bind_seconds": round(pct(0.99), 4),
        "virtual_seconds": round(n_batches * batch_dt, 1),
    }


def _churn_pcs(name: str, replicas: int = 1):
    from grove_tpu.api.meta import ObjectMeta as Meta
    from grove_tpu.api.types import (
        Container,
        PodCliqueSet,
        PodCliqueSetSpec,
        PodCliqueSetTemplateSpec,
        PodCliqueSpec,
        PodCliqueTemplateSpec,
        PodSpec,
    )

    return PodCliqueSet(
        metadata=Meta(name=name),
        spec=PodCliqueSetSpec(
            replicas=replicas,
            template=PodCliqueSetTemplateSpec(
                cliques=[
                    PodCliqueTemplateSpec(
                        name="w",
                        spec=PodCliqueSpec(
                            replicas=8,
                            pod_spec=PodSpec(
                                containers=[
                                    Container(name="m", resources={"cpu": 1.0})
                                ]
                            ),
                        ),
                    )
                ]
            ),
        ),
    )


def bench_churn(
    num_nodes: int, rate: float, duration: float,
    trace_groups: dict | None = None,
) -> dict:
    """Steady-arrival churn against a warm plane (churn_workload); returns
    churn_*-prefixed fields for the bench JSON line."""
    if duration <= 0:
        return {}
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness
    from grove_tpu.tuning import tune_gc

    h = Harness(
        nodes=make_nodes(
            num_nodes,
            allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0},
        ),
        config=(
            {"tracing": {"enabled": True}} if trace_groups is not None
            else None
        ),
    )
    h.apply(_churn_pcs("standing", 200 if num_nodes >= 2000 else 10))
    h.settle()
    tune_gc()
    stats = churn_workload(h, rate=rate, duration=duration)
    if trace_groups is not None:
        trace_groups["churn"] = h.cluster.tracer
    return {f"churn_{k}": v for k, v in stats.items()}


def bench_diurnal(args) -> int:
    """Elastic-serving bench regime (`--diurnal`, ROADMAP item 4): a
    multi-hour virtual diurnal trace — 10x base..peak swing, seeded
    noise, a spike on each cycle's rising edge — drives the FULL serving
    loop: the kubelet reports per-pod utilization each tick, the HPA
    sync runs on the validated `autoscaler.*` cadence, scale writes land
    on the PCSG/PodClique scale subresources, the reconcilers
    create/delete scaled PodGangs, and the scheduler re-places scale-ups
    against the vacating gangs' own reservations.

    The run spans TWO full diurnal cycles, so the trough genuinely
    scales the fleet down and the second ramp re-creates the same-named
    scaled gangs — the reservation-reuse hit path the scheduler must
    serve near-free and topology-stable.

    Reported (all latencies in VIRTUAL seconds — deterministic, immune
    to this host's wall noise):
      - end-to-end scale-up latency: each under-capacity episode (a
        tier's ready pods below what current demand requires at the
        HPA's effective target) from the demand step to capacity
        restored — detection + sync + reconcile + solve + bind + pod
        startup; p50/p99 over episodes;
      - starved intervals: episodes longer than the grace window (one
        sync interval + 3 steps) — the bench FAILS (exit 1) on any;
      - placement-score drift: max - min of the mean placement score
        sampled across the day (reuse keeps re-placements where they
        were, so the on-side drift should stay near zero);
      - reservation-reuse hit rate (exit 1 when zero hits — a vacuous
        run must not read as coverage).

    The reuse-on and reuse-off harnesses run INTERLEAVED step by step
    (per the bench-noise discipline: this host's load arrives in bursts,
    so A/B wall comparisons must share them) and both sides' numbers
    ship in the JSON."""
    import math as _math

    from grove_tpu.api import constants as _constants
    from grove_tpu.api.meta import ObjectMeta as Meta
    from grove_tpu.api.podgang import PodGang
    from grove_tpu.api.types import (
        AutoScalingConfig,
        Container,
        Pod,
        PodCliqueScalingGroupConfig,
        PodCliqueSet,
        PodCliqueSetSpec,
        PodCliqueSetTemplateSpec,
        PodCliqueSpec,
        PodCliqueTemplateSpec,
        PodSpec,
    )
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness
    from grove_tpu.tuning import tune_gc

    small = args.small
    hours = min(args.diurnal_hours, 2.0) if small else args.diurnal_hours
    duration = hours * 3600.0
    period = duration / 2.0  # two full cycles per run
    step = 30.0 if small else 20.0
    sync, stabilization, tolerance = 60.0, 300.0, 0.1
    target = 0.7
    base, peak = (30.0, 300.0) if small else (120.0, 1200.0)
    # one spike per cycle, riding the ramp (x1.4 on top of the curve)
    spikes = [
        {"at_seconds": round(c * period + 0.30 * period, 1),
         "duration_seconds": 8 * step, "multiplier": 1.4}
        for c in (0, 1)
    ]
    #: serving tiers — the reference's disaggregated roles: prefill
    #: (compute-bound PCSG), decode (memory-bound PCSG), router (a
    #: standalone clique whose HPA scales pod count directly, covering
    #: the PodClique-target path). pods = pods per scale unit (PCSG
    #: replica gang size, or 1 for the clique-target tier).
    tiers = {
        "prefill": dict(shape="prefill", rps=15.0, frac=0.45, pods=4,
                        min_r=1, max_r=6 if small else 18, pcsg=True),
        "decode": dict(shape="decode", rps=30.0, frac=0.45, pods=4,
                       min_r=1, max_r=4 if small else 10, pcsg=True),
        "router": dict(shape="router", rps=30.0 if small else 60.0,
                       frac=0.10, pods=1, min_r=2, max_r=4 if small else 6,
                       pcsg=False),
    }
    serving_cfg = {
        "enabled": True,
        "trace": {"base_rps": base, "peak_rps": peak,
                  "period_seconds": period, "noise": 0.02,
                  "sample_seconds": step, "spikes": spikes},
        "workloads": [
            {"clique": name, "shape": t["shape"],
             "rps_per_replica": t["rps"], "demand_fraction": t["frac"]}
            for name, t in tiers.items()
        ],
    }

    def mk_harness(reuse: bool) -> Harness:
        h = Harness(
            nodes=make_nodes(
                64 if small else 96, racks_per_block=4, hosts_per_rack=4,
                allocatable={"cpu": 4.0, "memory": 32.0, "tpu": 0.0},
            ),
            config={
                "serving": serving_cfg,
                "autoscaler": {
                    "tolerance": tolerance,
                    "sync_interval_seconds": sync,
                    "scale_down_stabilization_seconds": stabilization,
                    "metrics_max_age_seconds": 3 * sync,
                },
                "solver": {"reservation_reuse": reuse},
            },
        )
        cliques, sgs = [], []
        for name, t in tiers.items():
            sc = AutoScalingConfig(
                min_replicas=t["min_r"], max_replicas=t["max_r"],
                target_utilization=target,
            )
            pod_spec = PodSpec(
                containers=[Container(name="m", resources={"cpu": 1.0})]
            )
            if t["pcsg"]:
                cliques.append(PodCliqueTemplateSpec(
                    name=name,
                    spec=PodCliqueSpec(replicas=t["pods"], pod_spec=pod_spec),
                ))
                sgs.append(PodCliqueScalingGroupConfig(
                    name=f"{name}sg", clique_names=[name], replicas=1,
                    min_available=1, scale_config=sc,
                ))
            else:
                cliques.append(PodCliqueTemplateSpec(
                    name=name,
                    spec=PodCliqueSpec(
                        replicas=t["min_r"], scale_config=sc,
                        pod_spec=pod_spec,
                    ),
                ))
        h.apply(PodCliqueSet(
            metadata=Meta(name="serve"),
            spec=PodCliqueSetSpec(
                replicas=1,
                template=PodCliqueSetTemplateSpec(
                    cliques=cliques,
                    pod_clique_scaling_group_configs=sgs,
                ),
            ),
        ))
        h.settle()
        return h

    sides = {"on": mk_harness(True), "off": mk_harness(False)}
    tune_gc()

    #: under-capacity detection uses the HPA's EFFECTIVE target: the
    #: loop legitimately holds anywhere inside the tolerance band, so
    #: the guaranteed capacity floor is demand / (target * (1 + tol)).
    #: The pod-count core is the serving model's own oracle
    #: (WorkloadShape.required_pods); the bench only adds the HPA-side
    #: unit rounding (gang size) and min/max replica clamps.
    from grove_tpu.serving import WorkloadShape

    target_eff = target * (1.0 + tolerance)
    shapes = {
        name: WorkloadShape(clique=name, shape=t["shape"],
                            rps_per_replica=t["rps"],
                            demand_fraction=t["frac"])
        for name, t in tiers.items()
    }

    def required_pods(name: str, tier: dict, demand: float) -> int:
        want = shapes[name].required_pods(demand, target_eff)
        units = _math.ceil(want / tier["pods"] - 1e-9)
        units = min(max(units, tier["min_r"]), tier["max_r"])
        return units * tier["pods"]

    def tier_ready(h) -> dict[str, int]:
        counts = dict.fromkeys(tiers, 0)
        serving = h.cluster.serving
        for p in h.store.scan(Pod.KIND):
            if not p.status.ready or p.metadata.deletion_timestamp is not None:
                continue
            clique = p.metadata.labels.get(_constants.LABEL_PODCLIQUE, "")
            if not clique:
                continue
            tmpl = serving.template_of(
                h.store, p.metadata.namespace, clique
            )
            if tmpl in counts:
                counts[tmpl] += 1
        return counts

    grace = sync + 3 * step
    n_steps = int(round(duration / step))
    track = {
        side: {
            "episode_start": dict.fromkeys(tiers),
            "episodes": [],
            "scores": [],
            "walls": [],
        }
        for side in sides
    }
    for _ in range(n_steps):
        # interleaved per the bench-noise discipline: a host-load burst
        # lands on both sides of the A/B, not on one
        for side, h in sides.items():
            st = track[side]
            t0 = time.perf_counter()
            h.advance(step)
            h.maybe_autoscale()
            h.compact_events()
            st["walls"].append(time.perf_counter() - t0)
            now = h.clock.now()
            demand = h.cluster.serving.demand(now)
            ready = tier_ready(h)
            for name, t in tiers.items():
                lagging = ready[name] < required_pods(name, t, demand)
                start = st["episode_start"][name]
                if lagging and start is None:
                    st["episode_start"][name] = now
                elif not lagging and start is not None:
                    st["episodes"].append(now - start)
                    st["episode_start"][name] = None
            scores = [
                g.status.placement_score
                for g in h.store.scan(PodGang.KIND)
                if g.status.placement_score is not None
            ]
            if scores:
                st["scores"].append(sum(scores) / len(scores))
    for side, h in sides.items():
        # an episode still open at end of trace is a failure to catch up
        now = h.clock.now()
        for name, start in track[side]["episode_start"].items():
            if start is not None:
                track[side]["episodes"].append(now - start)

    def side_stats(side: str) -> dict:
        h = sides[side]
        st = track[side]
        episodes = sorted(st["episodes"])

        def pct(p):
            if not episodes:
                return 0.0
            return episodes[min(len(episodes) - 1,
                                int(round(p * (len(episodes) - 1))))]

        reuse_ctr = h.cluster.metrics.counter(
            "grove_scheduler_reservation_reuse_total"
        )
        hits = reuse_ctr.value(outcome="hit")
        attempts = reuse_ctr.total()
        scale_ctr = h.cluster.metrics.counter(
            "grove_autoscaler_scale_events_total"
        )
        walls = st["walls"]
        scores = st["scores"]
        return {
            "scaleup_events": len(episodes),
            "scaleup_p50_seconds": round(pct(0.50), 1),
            "scaleup_p99_seconds": round(pct(0.99), 1),
            "starved_intervals": sum(1 for e in episodes if e > grace),
            "placement_score_drift": (
                round(max(scores) - min(scores), 4) if scores else 0.0
            ),
            "placement_score_mean": (
                round(sum(scores) / len(scores), 4) if scores else 0.0
            ),
            "reservation_reuse_hits": int(hits),
            "reservation_reuse_attempts": int(attempts),
            "reservation_reuse_hit_rate": (
                round(hits / attempts, 3) if attempts else 0.0
            ),
            "scale_ups": int(scale_ctr.value(direction="up")),
            "scale_downs": int(scale_ctr.value(direction="down")),
            "stabilized_holds": int(
                h.cluster.metrics.counter(
                    "grove_autoscaler_stabilized_holds_total"
                ).total()
            ),
            "settle_wall_p50_seconds": (
                round(p50(walls), 4) if walls else 0.0
            ),
        }

    on = side_stats("on")
    off = side_stats("off")
    out = {
        "metric": "elastic serving: diurnal trace through the full "
        f"control plane ({hours:g} virtual hours, {peak / base:g}x swing, "
        "prefill/decode/router tiers)",
        "value": on["scaleup_p50_seconds"],
        "unit": "virtual seconds (p50 end-to-end scale-up)",
        "vs_baseline": 0.0,
        "diurnal_virtual_hours": hours,
        "diurnal_steps": n_steps,
        "diurnal_step_seconds": step,
        "load_swing": round(peak / base, 1),
        "spikes": len(spikes),
        "hpa_sync_interval_seconds": sync,
        "scale_down_stabilization_seconds": stabilization,
        "starved_interval_grace_seconds": grace,
        **on,
        "reuse_off": off,
        "backend": __import__("jax").default_backend(),
        "engine": "single",
    }
    print(json.dumps(out))
    ok = on["starved_intervals"] == 0 and on["reservation_reuse_hits"] > 0
    if on["starved_intervals"]:
        print(
            f"DIURNAL BENCH FAILURE: {on['starved_intervals']} starved "
            f"interval(s) (> {grace:g}s under capacity)", file=sys.stderr,
        )
    if on["reservation_reuse_hits"] == 0:
        print(
            "DIURNAL BENCH FAILURE: zero reservation-reuse hits — the "
            "trough/ramp cycle never exercised the reuse path",
            file=sys.stderr,
        )
    if off["starved_intervals"]:
        # informational: the off side is the comparison arm, not the gate
        print(
            f"diurnal reuse-off side: {off['starved_intervals']} starved "
            "interval(s)", file=sys.stderr,
        )
    return 0 if ok else 1


def bench_defrag(args) -> int:
    """Continuous-defragmentation long-churn regime (`--defrag`, ROADMAP
    item 3): a seeded arrival/departure stream of whole-node gangs
    (each pod fills a node, so a gang is a PAIR of nodes and its
    placement score is the narrowness of the domain containing the
    pair) against a near-full fleet. Random departures punch node-sized
    holes into random racks; arrivals that find no rack-local pair of
    holes must span racks or blocks — placement-score drift IS the
    fragmentation. The defrag-ON side runs Harness.maybe_defrag on the
    config cadence; the OFF side runs the identical op stream untouched.

    Both sides execute the SAME pre-generated op sequence INTERLEAVED
    step by step (the shared interleaved_ab/wall_stats helpers — this
    host's walls swing ~2x run-to-run, so each side's settle walls ship
    as min/median/max and a load burst lands on both sides of a pair).

    Gates (exit nonzero on any):
      - on-side drift (initial-window mean - final-window mean score)
        within --defrag-band;
      - the OFF side actually degrades by more than the band AND ends
        below the on side — otherwise the A/B is vacuous;
      - defrag evictions/hour under the configured
        defrag.max_evictions_per_hour bound;
      - make-before-break coverage: > 0 migration-ticket attempts, and
        the hit rate ships in the JSON;
      - ZERO full re-encodes (state_full_uploads / fused / split
        launches) attributable to defrag engine calls after the first
        sweep — the what-if contract, measured from the controller's
        dispatch attribution."""
    import random as _random

    from grove_tpu.api.meta import ObjectMeta as Meta
    from grove_tpu.api.podgang import PodGang
    from grove_tpu.api.types import (
        Container,
        PodCliqueSet,
        PodCliqueSetSpec,
        PodCliqueSetTemplateSpec,
        PodCliqueSpec,
        PodCliqueTemplateSpec,
        PodSpec,
    )
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness
    from grove_tpu.tuning import tune_gc

    small = args.small
    hours = min(args.defrag_hours, 1.0) if small else args.defrag_hours
    step = 30.0
    n_steps = int(round(hours * 3600.0 / step))
    num_nodes = 24 if small else 48
    #: the fragmenting mix (every pod fills a whole 1-cpu node, so a
    #: gang IS a node set and its score is that set's narrowness): TRIO
    #: gangs (3 nodes) + PAIR gangs (2) + FILL singles (1) tile the
    #: 4-host racks EXACTLY at start (trio+fill racks, pair+pair racks
    #: — staged apply, descending size, so the initial state is
    #: optimally packed and drift starts from zero entropy). Churn then
    #: fragments structurally: replacements arrive one step LATE, so a
    #: departure's hole stays open across a step and same-batch smaller
    #: arrivals (fills sort — and place — first) bite chunks out of it;
    #: the late trio/pair replacement must take whatever scattered
    #: nodes remain. Without the size mix AND the lag every replacement
    #: refills its predecessor's hole exactly and nothing ever
    #: fragments (measured).
    trios = 4 if small else 8
    pairs = 4 if small else 8
    solos = 4 if small else 8
    #: churn scales with the pool (same per-gang lifetime both sizes):
    #: less relative churn both fragments less AND starves defrag of
    #: the transient rack-local holes it re-packs into
    churn_per_step = 2 if small else 4
    sync = 60.0                         # defrag sweep cadence
    #: evictions/hour ceiling — scaled with the churn it must repair
    #: (at the full size the 4-gang/step stream fragments faster than
    #: 60 moves/hour can re-pack, measured: drift 0.09 rate-limited
    #: vs 0.03 with headroom)
    evict_bound = 60.0 if small else 150.0
    defrag_cfg = {
        "sync_interval_seconds": sync,
        "min_score_gain": 0.05,
        "migration_cost_score": 0.02,
        "max_moves_per_sweep": 6,
        "max_evictions_per_hour": evict_bound,
        "candidates_per_sweep": 32,
    }
    sizes = {"trio": 3, "pair": 2, "fill": 1}

    def pcs(name):
        pods = sizes[name.split("-")[0]]
        return PodCliqueSet(
            metadata=Meta(name=name),
            spec=PodCliqueSetSpec(
                replicas=1,
                template=PodCliqueSetTemplateSpec(cliques=[
                    PodCliqueTemplateSpec(
                        name="w",
                        spec=PodCliqueSpec(
                            replicas=pods,
                            pod_spec=PodSpec(containers=[
                                Container(
                                    name="m", resources={"cpu": 1.0}
                                )
                            ]),
                        ),
                    )
                ]),
            ),
        )

    def mk_harness(defrag_on: bool) -> Harness:
        return Harness(
            nodes=make_nodes(
                num_nodes, racks_per_block=2, hosts_per_rack=4,
                allocatable={"cpu": 1.0, "memory": 8.0, "tpu": 0.0},
            ),
            config={
                "defrag": {"enabled": defrag_on, **defrag_cfg},
            },
        )

    # pre-generate the seeded op stream ONCE so both sides execute the
    # identical arrivals/departures in the identical order. Each
    # departure is replaced by a fresh-named gang of the SAME kind (the
    # offered load shape is stationary; only placement quality drifts)
    # arriving one step LATER — ops[i] = (born_i, doomed_i) with
    # born_i = replacements for doomed_{i-1}.
    rng = _random.Random(42)
    stages = [
        [f"trio-{i}" for i in range(trios)],
        [f"pair-{i}" for i in range(pairs)],
        [f"fill-{i}" for i in range(solos)],
    ]
    alive: list[str] = [n for stage in stages for n in stage]
    next_id = 100
    ops: list[tuple[list[str], list[str]]] = []
    carry: list[str] = []
    for _ in range(n_steps):
        born = carry
        alive.extend(born)
        doomed = sorted(
            rng.sample(sorted(alive), min(churn_per_step, len(alive)))
        )
        carry = []
        for name in doomed:
            kind = name.split("-")[0]
            carry.append(f"{kind}-{next_id}")
            next_id += 1
            alive.remove(name)
        ops.append((born, doomed))

    sides = {"on": mk_harness(True), "off": mk_harness(False)}
    import io as _io

    sides["on"].defrag.log.stream = _io.StringIO()  # moves go to JSON
    for h in sides.values():
        # staged by descending gang size: each stage packs into the
        # residue of the previous, producing the exact rack tiling
        for stage in stages:
            for name in stage:
                h.apply(pcs(name))
            h.settle()
    tune_gc()

    track = {
        side: {"scores": [], "walls": []} for side in sides
    }
    whatif_baseline = {}  # attribution snapshot after the first sweep

    def fleet_score(h) -> float:
        scores = [
            g.status.placement_score
            for g in h.store.scan(PodGang.KIND)
            if g.status.placement_score is not None
        ]
        return sum(scores) / len(scores) if scores else 0.0

    def step_side(side: str, i: int):
        h = sides[side]
        born, doomed = ops[i]
        t0 = time.perf_counter()
        for name in born:  # last step's replacements, one step late
            h.apply(pcs(name))
        h.settle()
        for name in doomed:
            h.store.delete(PodCliqueSet.KIND, "default", name)
        h.settle()
        h.advance(step)
        swept = h.maybe_defrag()
        h.compact_events()
        wall = time.perf_counter() - t0
        if side == "on" and swept and "kinds" not in whatif_baseline:
            # steady-state window starts after the FIRST sweep (engine
            # birth may legitimately pay one full upload there)
            whatif_baseline["kinds"] = dict(h.defrag.dispatch_kinds)
        st = track[side]
        st["walls"].append(wall)
        st["scores"].append(fleet_score(h))
        return wall

    interleaved_ab(
        lambda i: step_side("on", i),
        lambda i: step_side("off", i),
        n_steps,
    )

    def drift(scores: list[float]) -> tuple[float, float, float]:
        """(initial-window mean, final-window mean, drift) over the
        first/last 10% of samples (>= 1 sample each)."""
        w = max(1, len(scores) // 10)
        first = sum(scores[:w]) / w
        last = sum(scores[-w:]) / w
        return round(first, 4), round(last, 4), round(first - last, 4)

    on_h = sides["on"]
    on_first, on_last, on_drift = drift(track["on"]["scores"])
    off_first, off_last, off_drift = drift(track["off"]["scores"])
    evictions = on_h.cluster.metrics.counter(
        "grove_defrag_evictions_total"
    ).total()
    evictions_per_hour = evictions / hours
    mig = on_h.cluster.metrics.counter(
        "grove_scheduler_migration_bind_total"
    )
    mig_hits = mig.value(outcome="hit")
    mig_attempts = mig.total()
    moves = on_h.cluster.metrics.counter("grove_defrag_moves_total")
    verdicts = {
        ls["verdict"]: int(moves.value(**ls))
        for ls in moves.label_sets()
    }
    # the what-if contract, measured: engine launches attributable to
    # defrag AFTER its first sweep must contain no full re-encode
    steady = {
        k: v - whatif_baseline.get("kinds", {}).get(k, 0)
        for k, v in on_h.defrag.dispatch_kinds.items()
    }
    full_reencodes = (
        steady.get("state_full_uploads", 0)
        + steady.get("fused", 0)
        + steady.get("split", 0)
    )

    failures = []
    if on_drift > args.defrag_band:
        failures.append(
            f"on-side drift {on_drift} exceeds band {args.defrag_band}"
        )
    if off_drift <= args.defrag_band or off_last >= on_last:
        failures.append(
            f"vacuous A/B: off-side drift {off_drift} within the band "
            f"(or off final {off_last} >= on final {on_last}) — the "
            "churn never fragmented the fleet"
        )
    if evictions_per_hour > evict_bound + 1e-9:
        failures.append(
            f"migration cost: {evictions_per_hour:.1f} evictions/hour "
            f"over the {evict_bound:g} bound"
        )
    if mig_attempts == 0:
        failures.append(
            "zero migration-ticket binds: make-before-break never "
            "exercised — vacuous coverage"
        )
    if full_reencodes:
        failures.append(
            f"what-if contract: {full_reencodes} full re-encode(s) "
            f"attributable to defrag sweeps in the steady-state window "
            f"(attribution: {steady})"
        )

    out = {
        "metric": "continuous defragmentation: long-churn drift A/B "
        f"({hours:g} virtual hours, {num_nodes} nodes, "
        f"{trios} trio + {pairs} pair + {solos} fill gangs)",
        "value": on_drift,
        "unit": "placement-score drift (defrag on)",
        "vs_baseline": off_drift,
        "defrag_steps": n_steps,
        "defrag_step_seconds": step,
        "defrag_band": args.defrag_band,
        "score_on_initial": on_first,
        "score_on_final": on_last,
        "score_on_drift": on_drift,
        "score_off_initial": off_first,
        "score_off_final": off_last,
        "score_off_drift": off_drift,
        "defrag_sweeps": on_h.defrag.sweeps_total,
        "defrag_moves": on_h.defrag.moves_total,
        "move_verdicts": verdicts,
        "evictions_per_hour": round(evictions_per_hour, 2),
        "evictions_per_hour_bound": evict_bound,
        "migration_bind_attempts": int(mig_attempts),
        "migration_bind_hits": int(mig_hits),
        "make_before_break_hit_rate": (
            round(mig_hits / mig_attempts, 3) if mig_attempts else 0.0
        ),
        "defrag_dispatch_attribution_steady": steady,
        "whatif_path": (
            on_h.defrag.debug_state()["last_sweep"] or {}
        ).get("whatif"),
        **wall_stats(track["on"]["walls"], "defrag_on_step_"),
        **wall_stats(track["off"]["walls"], "defrag_off_step_"),
        "backend": __import__("jax").default_backend(),
        "engine": "single",
    }
    for f in failures:
        print(f"DEFRAG BENCH FAILURE: {f}", file=sys.stderr)
    print(json.dumps(out))
    return 1 if failures else 0


def bench_tenants(args) -> int:
    """Multi-tenant sustained-churn regime (`--tenants N`, ROADMAP item
    3's "millions of users" scenario): N tenant queues with guaranteed/
    burst cpu quota and equal DRF weight, driven by a Zipf-skewed gang
    arrival stream (tenant 0 offers ~an order of magnitude more load
    than the tail) against the full control plane with tenancy enabled.

    Asserts the fairness contract and exits nonzero on violation:
      - ZERO starved tenants: every tenant that offered load gets at
        least one gang bound (the guarantee band must hold under skew);
      - bounded fairness error: the max |dominant share - entitlement|
        over burst-eligible tenants, sampled every batch, stays under
        --fairness-bound (DRF must keep redistributing the burst band).

    Prints one JSON line (same shape as the other bench modes) carrying
    the per-tenant outcome distribution, shed counts and the sampled
    fairness-error peak."""
    import collections

    from grove_tpu.api.meta import get_condition
    from grove_tpu.api.naming import base_podgang_name
    from grove_tpu.api.podgang import PodGang, PodGangConditionType
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness
    from grove_tpu.tuning import tune_gc

    T = args.tenants
    tenants = [f"t{i:03d}" for i in range(T)]
    # quota: every tenant is guaranteed 2 gangs' worth of cpu and may
    # burst to 5; the cluster itself has headroom, so sheds come from
    # QUOTA (the admission contract under test), not raw capacity
    gang_cpu = 8.0  # 8 pods x 1 cpu
    config = {
        "tenancy": {
            "enabled": True,
            "fairness_weight": 0.5,
            "tenants": [
                {
                    "name": t,
                    "guaranteed": {"cpu": 2 * gang_cpu},
                    "burst": {"cpu": 5 * gang_cpu},
                    "weight": 1.0,
                }
                for t in tenants
            ],
        }
    }
    h = Harness(
        nodes=make_nodes(
            args.nodes,
            allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0},
        ),
        config=config,
    )
    h.settle()
    tune_gc()

    rng = np.random.default_rng(11)
    batch_dt = 0.5
    n_arrivals = max(int(round(args.churn_rate * args.churn_duration)),
                     3 * T)
    # skewed offered load with full coverage: the first T arrivals hit
    # every tenant once (a tenant that never offers load cannot starve),
    # the rest draw Zipf — tenant 0 dominates the offered stream
    zipf_w = 1.0 / np.arange(1, T + 1, dtype=np.float64) ** 1.2
    zipf_w /= zipf_w.sum()
    sequence = list(rng.permutation(T)) + list(
        rng.choice(T, size=max(0, n_arrivals - T), p=zipf_w)
    )
    batch = max(1, int(round(args.churn_rate * batch_dt)))
    population = 4 * T

    alive: collections.deque[tuple[str, str]] = collections.deque()
    pending: dict[tuple[str, str], str] = {}  # (ns, gang) -> tenant
    created = collections.Counter()
    bound = collections.Counter()
    max_fairness_error = 0.0
    seq = 0
    t0 = time.perf_counter()

    def sample_bound() -> None:
        done = []
        for (ns, gname), tenant in pending.items():
            gang = h.store.peek(PodGang.KIND, ns, gname)
            if gang is None:
                continue
            cond = get_condition(
                gang.status.conditions,
                PodGangConditionType.SCHEDULED.value,
            )
            if cond is not None and cond.status == "True":
                bound[tenant] += 1
                done.append((ns, gname))
        for key in done:
            del pending[key]

    while sequence:
        for tenant_idx in sequence[:batch]:
            tenant = tenants[int(tenant_idx)]
            name = f"mt-{seq}"
            seq += 1
            pcs = _churn_pcs(name)
            pcs.metadata.namespace = tenant
            h.apply(pcs)
            alive.append((tenant, name))
            pending[(tenant, base_podgang_name(name, 0))] = tenant
            created[tenant] += 1
        sequence = sequence[batch:]
        while len(alive) > population:
            tenant, victim = alive.popleft()
            h.store.delete("PodCliqueSet", tenant, victim)
            pending.pop((tenant, base_podgang_name(victim, 0)), None)
        h.clock.advance(batch_dt)
        h.settle()
        h.compact_events()
        sample_bound()
        snapshot = h.cluster.topology_snapshot()
        h.cluster.tenancy.refresh_and_export(
            h.store, snapshot,
            h.cluster.pod_demand_fn(snapshot.resource_names),
        )
        max_fairness_error = max(
            max_fairness_error, h.cluster.tenancy.fairness_error()
        )
    # drain: fire the scheduler's quota-retry timers a few times so
    # gangs shed at peak skew get their post-churn admission chance
    for _ in range(4):
        h.advance(6.0)
        sample_bound()
    wall = time.perf_counter() - t0

    starved = sorted(
        t for t in tenants if created[t] > 0 and bound[t] == 0
    )
    sheds = h.cluster.metrics.counter("grove_tenant_gangs_shed_total")
    preempts = h.cluster.metrics.counter(
        "grove_tenant_preemption_evictions_total"
    )
    bound_counts = [bound[t] for t in tenants]
    out = {
        "metric": f"multi-tenant skewed churn ({T} tenants, "
        f"{args.nodes} nodes, Zipf offered load)",
        "value": round(sum(bound_counts) / wall, 1) if wall else 0.0,
        "unit": "gangs/sec",
        "vs_baseline": 0.0,
        "tenants": T,
        "tenants_offered": sum(1 for t in tenants if created[t] > 0),
        "tenants_starved": len(starved),
        "starved": starved[:8],
        "created": int(sum(created.values())),
        "bound": int(sum(bound_counts)),
        "unbound_final": len(pending),
        "sheds": int(sheds.total()),
        "preemption_evictions": int(preempts.total()),
        "bound_per_tenant_min": int(min(bound_counts)) if bound_counts else 0,
        "bound_per_tenant_max": int(max(bound_counts)) if bound_counts else 0,
        "max_fairness_error": round(max_fairness_error, 4),
        "fairness_bound": args.fairness_bound,
        "wall_seconds": round(wall, 2),
        "backend": __import__("jax").default_backend(),
        "engine": "single",
    }
    print(json.dumps(out))
    ok = not starved and max_fairness_error <= args.fairness_bound
    if starved:
        print(f"TENANT BENCH FAILURE: {len(starved)} starved tenant(s): "
              f"{starved[:8]}", file=sys.stderr)
    if max_fairness_error > args.fairness_bound:
        print(
            f"TENANT BENCH FAILURE: max fairness error "
            f"{max_fairness_error:.4f} > bound {args.fairness_bound}",
            file=sys.stderr,
        )
    return 0 if ok else 1


def _stream_schedule(rate: float, duration: float, batch_dt: float,
                     burst_every: float, burst_mult: int,
                     seed: int) -> list[int]:
    """Pre-generated arrival schedule (gangs per batch_dt step): Poisson
    at `rate` with a `burst_mult`x burst landing every `burst_every`
    virtual seconds. Generated ONCE per rung and replayed verbatim on
    BOTH A/B sides, so the comparison sees the identical offered load."""
    rng = np.random.default_rng(seed)
    n_batches = max(1, int(round(duration / batch_dt)))
    sched = [int(rng.poisson(rate * batch_dt)) for _ in range(n_batches)]
    if burst_every > 0 and burst_mult > 1:
        step = max(1, int(round(burst_every / batch_dt)))
        for i in range(step - 1, n_batches, step):
            sched[i] += int(round(burst_mult * rate * batch_dt))
    return sched


def _stream_run(h, schedule: list[int], batch_dt: float,
                steady_batch: int, population: int) -> dict:
    """Drive one pre-generated arrival schedule against a warm harness
    and measure wall-clock creation->Scheduled latency per gang (the
    churn_workload convention: the bind lands inside the batch's settle,
    so a gang's latency includes queueing behind the batch and any
    carryover backlog).

    Gangs the streaming front sheds (SCHEDULED False with reason
    DeadlineExceeded) leave the latency sample at first observation —
    a shed is a structured refusal, not a slow bind — and are counted
    separately; a shed gang that re-admits and binds later counts as
    `bound_after_shed`, still censored from the percentile (its latency
    is a shed-then-readmit lifecycle, not an admitted bind). On the
    round-draining side nothing sheds, so every created gang is either
    bound or still pending at the end — the two sides' samples reconcile
    against the same created total either way.

    Warmup covers the solver bucket ladder up to the STEADY batch only,
    on both sides: a 10x burst then lands as one monolithic (cold-
    bucket) solve under round-draining but stays inside the warmed
    ladder under micro-batching — that asymmetry is the measured
    phenomenon, not a harness artifact."""
    import collections

    from grove_tpu.api.meta import get_condition
    from grove_tpu.api.naming import base_podgang_name
    from grove_tpu.api.podgang import PodGang, PodGangConditionType
    from grove_tpu.observability.explain import UnsatCode

    store = h.store
    prefix = f"stream-{store.last_seq}"
    alive: collections.deque[str] = collections.deque()
    pending: dict[str, float] = {}
    shed_pending: set[str] = set()
    latencies: list[float] = []
    created = sheds_observed = bound_after_shed = 0
    seq = 0
    measured_wall = 0.0

    ladder = []
    size = 1
    while size < steady_batch:
        ladder.append(size)
        size *= 2
    warmup = ladder + [steady_batch] * 2

    def sample(now: float, measuring: bool) -> None:
        nonlocal sheds_observed, bound_after_shed
        done = []
        for gname, t_created in pending.items():
            gang = store.peek(PodGang.KIND, "default", gname)
            if gang is None:
                done.append(gname)
                continue
            cond = get_condition(
                gang.status.conditions,
                PodGangConditionType.SCHEDULED.value,
            )
            if cond is None:
                continue
            if cond.status == "True":
                if measuring:
                    if gname in shed_pending:
                        bound_after_shed += 1
                    else:
                        latencies.append(now - t_created)
                done.append(gname)
            elif cond.reason == UnsatCode.DEADLINE.value \
                    and gname not in shed_pending:
                shed_pending.add(gname)
                if measuring:
                    sheds_observed += 1
        for gname in done:
            del pending[gname]
            shed_pending.discard(gname)

    for b in range(-len(warmup), len(schedule)):
        measuring = b >= 0
        this_batch = schedule[b] if b >= 0 else warmup[b + len(warmup)]
        t0 = time.perf_counter()
        for _ in range(this_batch):
            name = f"{prefix}-{seq}"
            seq += 1
            h.apply(_churn_pcs(name))
            alive.append(name)
            pending[base_podgang_name(name, 0)] = time.perf_counter()
            if measuring:
                created += 1
        while len(alive) > population:
            victim = alive.popleft()
            store.delete("PodCliqueSet", "default", victim)
            gname = base_podgang_name(victim, 0)
            pending.pop(gname, None)
            shed_pending.discard(gname)
        h.clock.advance(batch_dt)
        h.settle()
        h.compact_events()
        now = time.perf_counter()
        if measuring:
            measured_wall += now - t0
        sample(now, measuring)
    # drain: fire the front's window timers / the scheduler's retry
    # timers so late admits and post-storm re-admissions land
    for _ in range(6):
        t0 = time.perf_counter()
        h.advance(1.0)
        sample(time.perf_counter(), True)
        measured_wall += time.perf_counter() - t0
    latencies.sort()

    def pct(p):
        if not latencies:
            return 0.0
        return latencies[min(len(latencies) - 1,
                             int(round(p * (len(latencies) - 1))))]

    return {
        "created": created,
        "bound": len(latencies),
        "sheds_observed": sheds_observed,
        "bound_after_shed": bound_after_shed,
        "unbound_final": len(pending),
        "p50_bind_seconds": round(pct(0.50), 4),
        "p99_bind_seconds": round(pct(0.99), 4),
        "measured_wall": measured_wall,
        "sustained_gangs_per_sec": (
            round((len(latencies) + bound_after_shed) / measured_wall, 1)
            if measured_wall else 0.0
        ),
    }


def bench_stream(args) -> int:
    """Streaming-admission A/B regime (`--stream`, ROADMAP item 1's
    continuous scheduling): the max sustained gang arrival rate
    (gangs/sec) whose p99 bind latency stays under the DECLARED SLO
    (--stream-slo wall seconds), under Poisson arrivals with a periodic
    10x burst — the streaming admission front (micro-batch windows +
    deadline-budget shedding; grove_tpu/streaming) against classic
    round-based draining, interleaved A/B on the identical pre-generated
    arrival schedule per rung.

    The rate ladder runs 1x/2x/4x the base rate; a side's "max
    sustained rate at SLO" is the highest rung whose measured p99 (over
    ADMITTED binds — sheds are structured refusals, reported separately)
    meets the SLO. Exit is nonzero when the stream side fails its SLO at
    the base rung or sustains a lower max rate than round-draining: the
    front exists to keep admitted-work latency bounded under overload by
    shedding the excess with DeadlineExceeded, and a regression in
    either direction is a contract violation."""
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness
    from grove_tpu.tuning import tune_gc

    small = args.small
    num_nodes = 128 if small else min(args.nodes, 512)
    base_rate = min(args.churn_rate, 16.0) if small else min(
        args.churn_rate, 64.0
    )
    duration = min(args.churn_duration, 5.0) if small else min(
        args.churn_duration, 20.0
    )
    batch_dt = 0.5
    slo = args.stream_slo
    rates = [base_rate, 2 * base_rate, 4 * base_rate]
    tune_gc()

    def stream_config(rate: float) -> dict:
        batch = max(1, int(round(rate * batch_dt)))
        # sized against the burst shape: the queue cap holds ~2 seconds
        # of offered load (a 10x burst overflows it and SHEDS), the
        # micro-batch matches one steady batch (stream throughput equals
        # round throughput when nothing is burning), and the virtual
        # deadline budget spans a few batch intervals
        return {
            "stream": {
                "enabled": True,
                "slo_seconds": 8 * batch_dt,
                "window_min_seconds": 0.1,
                "window_max_seconds": 1.0,
                "max_batch_gangs": batch,
                "queue_cap_gangs": 4 * batch,
                "brownout_depth_fraction": 0.5,
                "readmit_depth_fraction": 0.25,
            }
        }

    #: --trace composition: every rung's stream/round side gets its own
    #: full-ring tracer (its own Perfetto process in the export), the
    #: per-side fleet critical-path report rides in the rung dict, and
    #: the telescoping/non-vacuity failures gate the exit code alongside
    #: the SLO scorecard. Both sides trace, so the A/B stays symmetric.
    trace_groups: dict = {}
    trace_failures: list[str] = []
    rungs = []
    for rung_idx, rate in enumerate(rates):
        batch = max(1, int(round(rate * batch_dt)))
        population = min(10 * batch, 2 * num_nodes)
        schedule = _stream_schedule(
            rate, duration, batch_dt, burst_every=max(2.0, duration / 2),
            burst_mult=10, seed=17 + rung_idx,
        )

        def measure(stream_on: bool):
            cfg: dict = dict(stream_config(rate)) if stream_on else {}
            if args.trace:
                cfg["tracing"] = {"enabled": True}
            h = Harness(
                nodes=make_nodes(
                    num_nodes,
                    allocatable={"cpu": 32.0, "memory": 128.0,
                                 "tpu": 8.0},
                ),
                config=cfg or None,
            )
            h.settle()
            out = _stream_run(h, schedule, batch_dt, batch, population)
            if stream_on:
                m = h.cluster.metrics
                out["front_sheds"] = int(m.counter(
                    "grove_stream_shed_total",
                    "gangs shed by the streaming front",
                ).total())
                out["front_readmitted"] = int(m.counter(
                    "grove_stream_readmitted_total",
                    "shed gangs re-admitted",
                ).total())
            if args.trace:
                side = "stream" if stream_on else "round"
                report, fails = _trace_critical_path(
                    h.cluster.tracer, h.cluster.metrics,
                    binds=out["bound"],
                    label=f"{side} @ {rate:g} gangs/s",
                )
                out["critical_path"] = report
                trace_failures.extend(fails)
                trace_groups[f"{side}-{rate:g}gps"] = h.cluster.tracer
            return out

        (s_runs, r_runs) = interleaved_ab(
            lambda _i: measure(True), lambda _i: measure(False), 1,
        )
        stream_r, round_r = s_runs[0], r_runs[0]
        rungs.append({
            "offered_gangs_per_sec": rate,
            "stream": stream_r,
            "round": round_r,
        })

    def max_rate(side: str) -> float:
        best = 0.0
        for rung in rungs:
            if rung[side]["bound"] and \
                    rung[side]["p99_bind_seconds"] <= slo:
                best = rung["offered_gangs_per_sec"]
        return best

    stream_max, round_max = max_rate("stream"), max_rate("round")
    top = rungs[-1]
    # the bench verdicts ride the SLO scorecard schema (one verdict
    # vocabulary across bench, chaos, and the live engine — ROADMAP
    # item 3): each contract is a static_entry whose breach/ok verdict
    # IS the exit-code decision below
    from grove_tpu.observability.slo import (
        VERDICT_BREACH, compose_scorecard, static_entry,
    )
    base_p99 = rungs[0]["stream"]["p99_bind_seconds"]
    card = compose_scorecard([
        static_entry(
            "stream-base-p99", "bind_latency_p99", base_p99,
            threshold=slo, unit="seconds",
            offered_gangs_per_sec=rates[0],
        ),
        static_entry(
            "stream-max-rate", "sustained_rate", stream_max,
            threshold=round_max, unit="gangs/sec", higher_is_better=True,
            round_max_gangs_per_sec=round_max,
        ),
        static_entry(
            "stream-sheds", "shed_count",
            float(top["stream"].get("front_sheds", 0)),
            unit="gangs", readmitted=top["stream"].get(
                "front_readmitted", 0
            ),
        ),
    ])
    out = {
        "metric": f"streaming admission max sustained rate at p99 <= "
        f"{slo:g}s SLO ({num_nodes} nodes, Poisson + 10x bursts)",
        "value": stream_max,
        "unit": "gangs/sec",
        "vs_baseline": (
            round(stream_max / round_max, 2) if round_max else 0.0
        ),
        "round_max_gangs_per_sec": round_max,
        "p99_slo_seconds": slo,
        "rate_ladder": rates,
        "rungs": rungs,
        "top_rung_stream_p99": top["stream"]["p99_bind_seconds"],
        "top_rung_round_p99": top["round"]["p99_bind_seconds"],
        "scorecard": card,
        "backend": __import__("jax").default_backend(),
        "engine": "single",
    }
    if args.trace:
        from grove_tpu.observability.tracing import chrome_trace

        with open(args.trace, "w") as fh:
            json.dump(chrome_trace(trace_groups), fh)
            fh.write("\n")
        n_spans = sum(len(v.finished) for v in trace_groups.values())
        print(f"wrote {n_spans} spans to {args.trace}", file=sys.stderr)
        # fleet breakdown at the TOP rung (the overload point the bench
        # exists to characterize): stream vs round, where the latency
        # went on each side — also echoed to stderr so a CI log shows
        # the dominating segment without parsing the JSON
        out["critical_path_breakdown"] = {
            "offered_gangs_per_sec": rates[-1],
            "stream": top["stream"].get("critical_path"),
            "round": top["round"].get("critical_path"),
        }
        print(json.dumps(
            {"critical_path_breakdown": out["critical_path_breakdown"]}
        ), file=sys.stderr)
    print(json.dumps(out))
    by_name = {e["slo"]: e for e in card["slos"]}
    if by_name["stream-base-p99"]["verdict"] == VERDICT_BREACH:
        print(
            f"STREAM BENCH FAILURE: p99 {base_p99}s > SLO {slo}s at "
            f"the base rate {rates[0]:g} gangs/s",
            file=sys.stderr,
        )
    if by_name["stream-max-rate"]["verdict"] == VERDICT_BREACH:
        print(
            f"STREAM BENCH FAILURE: stream sustains {stream_max:g} "
            f"gangs/s at SLO but round-draining sustains {round_max:g}",
            file=sys.stderr,
        )
    for f in trace_failures:
        print(f"STREAM BENCH FAILURE: {f}", file=sys.stderr)
    if card["verdict"] == VERDICT_BREACH or trace_failures:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
