"""Wide-matrix chaos sweep: the CI-scale version of tests/test_chaos.py.

tests/test_chaos.py pins a handful of fixed seeds so the tier-1 gate stays
at ~seconds; this script sweeps an arbitrary seed range of deterministic
fault plans (grove_tpu.chaos.FaultPlan) over the reference workload and
checks the convergence contract for each: once faults stop, the
workload-level fingerprint must equal a fault-free run's and the fuzz
invariants must hold. Any failing seed reproduces exactly with

    python scripts/chaos_sweep.py --start <seed> --seeds 1

(see docs/operations.md "Fault tolerance & chaos testing").

Output: one JSON line per seed plus a summary line; exit 1 when any seed
fails.

    python scripts/chaos_sweep.py --seeds 60
    python scripts/chaos_sweep.py --start 100 --seeds 20 --nodes 32
"""

from __future__ import annotations

import argparse
import io
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from grove_tpu.api.types import PodCliqueScalingGroupConfig  # noqa: E402
from grove_tpu.chaos import (  # noqa: E402
    ChaosHarness,
    FaultPlan,
    check_invariants,
    settled_fingerprint,
)
from grove_tpu.cluster import make_nodes  # noqa: E402
from grove_tpu.controller import Harness  # noqa: E402


def sweep_workload(scaled: bool = False, hierarchical: bool = False):
    """The reference chaos workload: startup ordering + a scaling group —
    every orchestration flow (gang create/defer, gates, scaled gangs,
    RBAC) is on the fault path. `scaled=True` (the --serving axis) adds
    an HPA scaleConfig on the scaling group so the traffic-driven scale
    loop has a subresource to write. `hierarchical=True` (the
    --hierarchical axis) adds a rack-level pack constraint so the
    backlog is CONFINED — the two-level solve only engages on confined
    backlogs, and node faults then land between its coarse assignments
    and shard-local fine solves."""
    from grove_tpu.api.meta import ObjectMeta
    from grove_tpu.api.types import (
        AutoScalingConfig,
        Container,
        PodCliqueSet,
        PodCliqueSetSpec,
        PodCliqueSetTemplateSpec,
        PodCliqueSpec,
        PodCliqueTemplateSpec,
        PodSpec,
        TopologyConstraintSpec,
        TopologyPackConstraintSpec,
    )

    def _clique(name, replicas, starts_after=()):
        return PodCliqueTemplateSpec(
            name=name,
            spec=PodCliqueSpec(
                replicas=replicas,
                starts_after=list(starts_after),
                pod_spec=PodSpec(
                    containers=[
                        Container(name="main", resources={"cpu": 1.0})
                    ]
                ),
            ),
        )

    return PodCliqueSet(
        metadata=ObjectMeta(name="chaos"),
        spec=PodCliqueSetSpec(
            replicas=2,
            template=PodCliqueSetTemplateSpec(
                topology_constraint=(
                    TopologyConstraintSpec(
                        pack_constraint=TopologyPackConstraintSpec(
                            required="rack"
                        )
                    )
                    if hierarchical else None
                ),
                cliques=[
                    _clique("fe", 2),
                    _clique("be", 3, starts_after=["fe"]),
                ],
                pod_clique_scaling_group_configs=[
                    PodCliqueScalingGroupConfig(
                        name="g", clique_names=["be"],
                        replicas=2, min_available=1,
                        scale_config=(
                            AutoScalingConfig(
                                min_replicas=1, max_replicas=4,
                                target_utilization=0.7,
                            )
                            if scaled else None
                        ),
                    )
                ],
                startup_type="CliqueStartupTypeExplicit",
            ),
        ),
    )


#: tenancy config for --tenant-skew sweeps: two tenants with tight burst
#: ceilings so the injected skew bursts actually cross the admission
#: bands (some of the load sheds with QuotaExceeded and must recover to
#: the fault-free fixpoint once the skew leaves at disarm)
TENANT_SKEW_CONFIG = {
    "tenancy": {
        "enabled": True,
        "tenants": [
            {"name": "skew-a", "guaranteed": {"cpu": 2.0},
             "burst": {"cpu": 6.0}},
            {"name": "skew-b", "guaranteed": {"cpu": 2.0},
             "burst": {"cpu": 6.0}},
        ],
    }
}


#: serving config for --serving sweeps: a FLAT trace (base == peak,
#: noise 0) so the autoscaler fixpoint is time-invariant — the chaotic
#: run's injected spikes scale the fleet up mid-storm, and at disarm the
#: drain must bring it back to exactly the fault-free equilibrium
#: (PCSG replicas 3 at these numbers: 126 rps over 2 PCS replicas x
#: 3 PCSG replicas x 3 be-pods x 10 rps/pod = 0.7 utilization, on
#: target). Short stabilization window so the sweep drains fast.
SERVING_CONFIG = {
    "serving": {
        "enabled": True,
        "trace": {"base_rps": 126.0, "peak_rps": 126.0, "noise": 0.0},
        "workloads": [
            {"clique": "be", "shape": "decode", "rps_per_replica": 10.0,
             "demand_fraction": 1.0},
        ],
    },
    "autoscaler": {
        "sync_interval_seconds": 10.0,
        "scale_down_stabilization_seconds": 30.0,
    },
}


#: durability config for --durability sweeps: aggressive snapshot cadence
#: so crashes land on every recovery path (fresh WAL tail, snapshot +
#: replay, post-checkpoint generations). fsync "never" deliberately: the
#: sim never kills the interpreter, so physical-durability tears are
#: injected explicitly (wal_torn_write), and skipping fsync keeps the
#: sweep fast on CI disks.
DURABILITY_CONFIG = {
    "fsync": "never",
    "snapshot_interval_seconds": 30.0,
    "wal_max_bytes": 262144,
}


#: replication config for --replication sweeps: the log-shipping
#: standby in SEMI-SYNC (the zero-loss mode — every commit ships before
#: it returns), on top of the --durability axis. standby_wal_dir is
#: filled in per seed next to the leader's wal_dir.
REPLICATION_CONFIG = {
    "enabled": True,
    "ack_mode": "semi-sync",
}


#: solver config for --hierarchical sweeps: the min-nodes forced-flat
#: threshold dropped to 0 so the two-level solve engages on the sweep's
#: small clusters (the workload adds the rack confinement it needs)
HIERARCHICAL_CONFIG = {"solver": {"hierarchical_min_nodes": 0}}


#: defrag config for --defrag sweeps: a tight sweep cadence so the
#: chaotic maybe_defrag loop actually fires between fault steps, a
#: small per-sweep move cap (bounded disruption mid-storm), and a rate
#: ceiling generous enough that storms are bounded by budgets/gain, not
#: silently by the rate limiter
DEFRAG_CONFIG = {
    "defrag": {
        "enabled": True,
        "sync_interval_seconds": 20.0,
        "min_score_gain": 0.05,
        "max_moves_per_sweep": 2,
        "max_evictions_per_hour": 240.0,
    }
}


#: stream config for --stream sweeps: the streaming admission front with
#: a queue cap SMALLER than one injected burst storm (20 gangs) so a
#: storm actually crosses the overflow + brownout ladder and sheds with
#: structured DeadlineExceeded, a small batch size so micro-batch
#: windows are on the fault path, and a readmit floor low enough that
#: shed workload gangs re-enter only once the storm drains at disarm
STREAM_CONFIG = {
    "stream": {
        "enabled": True,
        "slo_seconds": 20.0,
        "window_min_seconds": 0.25,
        "window_max_seconds": 2.0,
        "max_batch_gangs": 4,
        "queue_cap_gangs": 12,
        "brownout_depth_fraction": 0.5,
        "readmit_depth_fraction": 0.25,
    }
}


#: SLO config for --slo sweeps: the continuous evaluator swept on a
#: cadence TIGHTER than the fault plan's 2-second steps aggregate (4s =
#: every other step), with window pairs scaled to the 80-virtual-second
#: storm so a single bad sweep trips pending and the next confirming
#: sweep fires — alerts must fire DURING the fault window, and the
#: short windows must forget the fault within a few clean sweeps so
#: resolution lands during the post-settle drain. Objectives cover the
#: burst_storm/tenant_skew shed path, the backlog starvation path, and
#: the promote_standby/process-crash failover path.
SLO_CONFIG = {
    "slo": {
        "enabled": True,
        "sync_interval_seconds": 4.0,
        "budget_window_seconds": 600.0,
        "page_short_seconds": 8.0,
        "page_long_seconds": 24.0,
        "page_burn_threshold": 5.0,
        "ticket_short_seconds": 24.0,
        "ticket_long_seconds": 80.0,
        "ticket_burn_threshold": 2.0,
        "objectives": [
            {"name": "bind-p99", "kind": "bind_latency_p99",
             "target": 0.98, "threshold_seconds": 30.0,
             "per_tenant": True},
            {"name": "shed-rate", "kind": "shed_rate",
             "target": 0.98, "ceiling_per_second": 0.25},
            {"name": "starvation", "kind": "starvation",
             "target": 0.98, "max_starved_seconds": 30.0},
            {"name": "placement-drift", "kind": "placement_drift",
             "target": 0.95, "band": 0.4},
            {"name": "failover-wall", "kind": "failover_wall",
             "target": 0.98, "max_failovers": 0},
        ],
    }
}


#: federation config for --federation sweeps: a 3-member federation with
#: a SHORT outage window (a seeded cluster_partition of a few 2-second
#: steps can outlive it, so the healed-zombie fence path is actually on
#: the sweep's fault path) and a drain window generous enough that
#: pacing — not the deadline — bounds the failover. wal_dir is filled in
#: per seed (each member + the coordinator journal get subdirectories).
FEDERATION_CONFIG = {
    "federation": {
        "enabled": True,
        "clusters": 3,
        "heartbeat_interval_seconds": 2.0,
        "outage_detection_window_seconds": 12.0,
        "drain_window_seconds": 400.0,
        "drain_max_gangs_per_round": 4,
    }
}


def run_seed(seed: int, nodes: int, baseline: dict,
             trace_dir: Path | None = None,
             explain_dir: Path | None = None,
             tenant_skew: bool = False,
             shards: int = 1,
             durability: bool = False,
             partitions: int = 1,
             replication: bool = False,
             serving: bool = False,
             hierarchical: bool = False,
             defrag: bool = False,
             stream: bool = False,
             slo: bool = False) -> dict:
    overrides = {"tenant_skew_rate": 0.35} if tenant_skew else {}
    if stream:
        # the streaming-admission fault axis: seeded ~10x burst storms
        # (the front must shed with structured DeadlineExceeded, never
        # wedge; the storm load leaves at disarm and shed workload gangs
        # re-admit) and arrival stalls (budgets burn through the hold —
        # the stall ends in a batched admit or a deadline shed)
        overrides.update(
            burst_storm_rate=0.3,
            arrival_stall_rate=0.15,
        )
    if replication:
        # the HA-replication fault axis: standby tailing stalls
        # (semi-sync degrades for the window, must catch up), mid-plan
        # failovers (promote + manager rebuild + re-armed standby),
        # dual-leader fence proofs (the deposed log's append must be
        # refused or the seed fails), standby crashes re-seeding from
        # the leader's snapshots
        overrides.update(
            replication_stall_rate=0.2,
            standby_promotion_rate=0.08,
            dual_leader_rate=0.06,
            standby_crash_rate=0.1,
        )
    if defrag:
        # the continuous-defragmentation fault axis: forced migration
        # storms (stage + evict waves mid-chaos), crashes right after a
        # storm (tickets are soft state; evicted gangs must still
        # re-place), and destination-node faults before the re-bind —
        # with the disruption-budget audit armed throughout
        overrides.update(
            migration_storm_rate=0.3,
            migration_crash_rate=0.25,
            migration_node_fault_rate=0.3,
        )
    if serving:
        # the elastic-serving fault axis: seeded traffic spikes onto the
        # flat trace (the HPA loop scales up mid-storm and must
        # stabilize back down after disarm) + metrics-pipeline dropouts
        # (stale samples must HOLD the fleet, never collapse it)
        overrides.update(
            traffic_spike_rate=0.3,
            metrics_dropout_rate=0.25,
        )
    wal_tmp = None
    if durability:
        # the durable-store fault axis: whole-process crashes recovering
        # from disk mid-plan, torn WAL tails, corrupted snapshots, disk
        # stalls — convergence is still checked against the same
        # fault-free fixpoint (recovery must be workload-invisible)
        overrides.update(
            process_crash_rate=0.12,
            wal_torn_write_rate=0.4,
            snapshot_corruption_rate=0.3,
            disk_stall_rate=0.1,
        )
        if partitions > 1:
            # the partitioned-WAL fault axis on top: crashes with ONE
            # partition's tail torn (divergent streams merged back at
            # recovery) and per-partition disk stalls (one partition's
            # snapshot cadence defers while the others keep theirs)
            overrides.update(
                partition_divergence_rate=0.2,
                partition_stall_rate=0.15,
            )
        import tempfile

        wal_tmp = tempfile.TemporaryDirectory(prefix=f"grove-wal-{seed}-")
    if shards > 1:
        # the shard-failover axis: worker crashes, frozen map views,
        # handoff storms — convergence is still checked against the
        # SINGLE-replica fault-free fixpoint (sharding must be
        # workload-invisible), with the ownership audit armed
        overrides.update(
            shard_crash_rate=0.1,
            shard_map_stale_rate=0.1,
            handoff_storm_rate=0.08,
        )
    plan = FaultPlan.from_seed(seed, **overrides)
    trace_path = (
        str(trace_dir / f"seed-{seed}-flight.json")
        if trace_dir is not None else None
    )
    config = dict(TENANT_SKEW_CONFIG) if tenant_skew else {}
    if serving:
        config = {**config, **SERVING_CONFIG}
    if hierarchical:
        config = {**config, **HIERARCHICAL_CONFIG}
    if defrag:
        config = {**config, **DEFRAG_CONFIG}
    if stream:
        config = {**config, **STREAM_CONFIG}
    if slo:
        # evaluation-only: the engine's Events ride the raw store (zero
        # fault-plan draws), so composing --slo changes no seed's
        # workload trajectory — the shared fault-free baseline holds
        config = {**config, **SLO_CONFIG}
    if shards > 1:
        config = {**config, "controllers": {"shards": shards}}
    if wal_tmp is not None:
        config = {
            **config,
            "durability": {
                **DURABILITY_CONFIG,
                "wal_dir": str(Path(wal_tmp.name) / "wal"),
                "partitions": max(partitions, 1),
            },
        }
        if replication:
            config = {
                **config,
                "replication": {
                    **REPLICATION_CONFIG,
                    "standby_wal_dir": str(Path(wal_tmp.name) / "standby"),
                },
            }
    try:
        return _run_seed_inner(
            seed, nodes, baseline, plan, config, trace_path,
            explain_dir, durability, serving, hierarchical, defrag,
            replication, stream, slo, tenant_skew,
        )
    finally:
        # exception-safe: a seed that raises out of harness construction
        # or the dump paths must not leak its per-seed WAL dir across a
        # multi-seed CI sweep
        if wal_tmp is not None:
            wal_tmp.cleanup()


def _run_seed_inner(seed, nodes, baseline, plan, config, trace_path,
                    explain_dir, durability, serving=False,
                    hierarchical=False, defrag=False,
                    replication=False, stream=False, slo=False,
                    tenant_skew=False) -> dict:
    ch = ChaosHarness(
        plan, nodes=make_nodes(nodes), trace_path=trace_path,
        config=config or None,
    )
    # silence the expected fault-storm error logs (with_name children
    # copy the stream at creation, so the manager's logger needs its own
    # reassignment; restarted managers inherit the cluster logger's)
    quiet = io.StringIO()
    ch.harness.cluster.logger.stream = quiet
    ch.harness.manager.logger.stream = quiet
    ch.harness.scheduler.log.stream = quiet
    ch.harness.defrag.log.stream = quiet
    for w in getattr(ch.harness.manager, "workers", ()):
        w.manager.logger.stream = quiet
        w.components["scheduler"].log.stream = quiet
        w.components["defrag"].log.stream = quiet
    t0 = time.perf_counter()
    error = None
    try:
        ch.apply(sweep_workload(scaled=serving, hierarchical=hierarchical))
        if serving:
            # reach the traffic-driven equilibrium BEFORE the storm, the
            # same way the baseline does — chaos then measures recovery
            # back to it, not initial convergence under fire
            ch.settle()
            for _ in range(4):
                ch.harness.advance(11.0)
                ch.harness.autoscale()
        ch.run_chaos()
        fingerprint_ok = settled_fingerprint(ch.raw_store) == baseline
        violations = check_invariants(ch.raw_store)
    except Exception as exc:  # a non-converging seed must not stop the sweep
        fingerprint_ok, violations = False, []
        error = f"{type(exc).__name__}: {exc}"
    ok = fingerprint_ok and not violations and error is None
    result = {
        "seed": seed,
        "ok": ok,
        "fingerprint_match": fingerprint_ok,
        "invariant_violations": violations,
        "error": error,
        "faults_injected": dict(sorted(plan.counts.items())),
        "manager_restarts": ch.manager_restarts,
        "wall_seconds": round(time.perf_counter() - t0, 3),
    }
    if durability:
        result["process_restarts"] = ch.process_restarts
        result["recovery_outcomes"] = [
            s["outcome"] for s in ch.recovery_stats
        ]
    if stream:
        front = getattr(ch.harness.scheduler, "stream", None)
        metrics = ch.harness.cluster.metrics
        result["stream_queue_depth_at_settle"] = (
            front.queue_depth() if front is not None else None
        )
        result["stream_shed_registry_at_settle"] = (
            front.shed_registry_size() if front is not None else None
        )
        result["stream_sheds"] = metrics.counter(
            "grove_stream_shed_total", "gangs shed by the streaming front"
        ).total()
        if error is None and (
            front is None or front.queue_depth() != 0
        ):
            # a drained settle with waiters still parked is a wedged
            # queue — exactly what the storm axis exists to catch
            result["ok"] = False
            result["error"] = (
                "stream queue not drained at settle (depth="
                f"{None if front is None else front.queue_depth()})"
            )
    if slo:
        engine = ch.harness.cluster.slo
        # capture BEFORE the resolve drain: these transitions happened
        # while the plan was armed — the fire-during-fault half of the
        # lifecycle invariant
        fired = [
            h for h in engine.history if h["to"] == "firing"
        ] if engine is not None else []
        sync = SLO_CONFIG["slo"]["sync_interval_seconds"]
        if engine is not None and error is None:
            # resolve drain: the faults are gone and the workload is
            # settled, so every firing alert's short window must forget
            # the storm within a bounded number of clean sweeps
            for _ in range(80):
                if not engine.firing():
                    break
                ch.harness.advance(sync)
                ch.harness.maybe_slo_sweep()
        still_firing = engine.firing() if engine is not None else []
        result["slo"] = {
            "alerts_fired": len(fired),
            "slos_fired": sorted({
                (h["slo"], h["tenant"] or "") for h in fired
            }),
            "firing_after_settle": len(still_firing),
            "transitions": len(engine.history) if engine is not None else 0,
        }
        # the scorecard itself is the CI artifact (--scorecard pops it
        # into one JSON per sweep); keep the per-seed result line lean
        result["slo_scorecard"] = (
            engine.scorecard() if engine is not None else {"enabled": False}
        )
        if error is None and engine is None:
            result["ok"] = False
            result["error"] = "slo: engine missing despite --slo config"
        elif error is None and (stream or tenant_skew) and not fired:
            # the storm axes shed/starve by construction — a sweep where
            # no alert ever fired means the evaluator missed the fault
            result["ok"] = False
            result["error"] = "slo: no alert fired during the fault phase"
        elif error is None and still_firing:
            result["ok"] = False
            result["error"] = (
                "slo: alerts still firing after settle: "
                + ", ".join(
                    f"{a['slo']}"
                    + (f"[{a['tenant']}]" if a["tenant"] else "")
                    + f"/{a['severity']}"
                    for a in still_firing
                )
            )
    if replication:
        result["standby_promotions"] = ch.standby_promotions
        standby = ch.harness.cluster.standby
        # the settled standby must have converged to the leader's
        # committed head — a lagging settle is a replication failure
        # even when the workload fingerprint matches
        lag = standby.lag_records() if standby is not None else None
        result["standby_lag_at_settle"] = lag
        if error is None and (standby is None or lag != 0):
            result["ok"] = False
            result["error"] = (
                f"standby not converged at settle (lag={lag})"
            )
    if not ok and trace_path is not None:
        # every failure class leaves the postmortem, not just the wedged
        # settle that settle_recovered auto-dumps (a diverged fingerprint
        # settles fine — the flight ring is how you see WHY it diverged)
        ch.dump_flight(trace_path)
        result["flight_dump"] = trace_path
    if explain_dir is not None:
        # placement-decision dump for every gang UNSCHEDULED at settle —
        # written for passing seeds too (a gang can settle unscheduled
        # legally); render with python -m grove_tpu.observability.explain
        try:
            explain_path = str(explain_dir / f"seed-{seed}-explain.json")
            if ch.dump_explain(explain_path) is not None:
                result["explain_dump"] = explain_path
        except Exception as exc:  # never fail the sweep on the dump
            result["explain_error"] = f"{type(exc).__name__}: {exc}"
    return result


def run_aggregate_ab(seed: int, nodes: int, stream: bool = False) -> dict:
    """One seed run TWICE — full-ring tracing vs `tracing.mode:
    aggregate` — asserting the always-on mode is bit-identical: same
    settled workload fingerprint and the exact same fault-plan draw
    counts (the causal ledger and critical-path folder do no store
    writes and consume no RNG, so enabling them may not perturb a single
    decision). The CI streaming-chaos smoke runs this on pre-existing
    seeds."""
    overrides: dict = {}
    config: dict = {}
    if stream:
        overrides.update(burst_storm_rate=0.3, arrival_stall_rate=0.15)
        config.update(STREAM_CONFIG)

    def once(mode: str):
        plan = FaultPlan.from_seed(seed, **overrides)
        cfg = {**config, "tracing": {"enabled": True, "mode": mode}}
        ch = ChaosHarness(plan, nodes=make_nodes(nodes), config=cfg)
        quiet_io = io.StringIO()
        ch.harness.cluster.logger.stream = quiet_io
        ch.harness.manager.logger.stream = quiet_io
        ch.harness.scheduler.log.stream = quiet_io
        ch.harness.defrag.log.stream = quiet_io
        ch.apply(sweep_workload())
        ch.run_chaos()
        return (
            settled_fingerprint(ch.raw_store),
            dict(sorted(plan.counts.items())),
            ch.harness.cluster.tracer.mode,
        )

    t0 = time.perf_counter()
    error = None
    fp_same = draws_same = False
    counts: dict = {}
    try:
        fp_full, draws_full, mode_full = once("full")
        fp_agg, draws_agg, mode_agg = once("aggregate")
        assert mode_full == "full" and mode_agg == "aggregate"
        fp_same = fp_full == fp_agg
        draws_same = draws_full == draws_agg
        counts = draws_full
    except Exception as exc:  # a failing seed must not stop the sweep
        error = f"{type(exc).__name__}: {exc}"
    return {
        "seed": seed,
        "ok": fp_same and draws_same and error is None,
        "fingerprint_identical": fp_same,
        "fault_draws_identical": draws_same,
        "faults_injected": counts,
        "error": error,
        "wall_seconds": round(time.perf_counter() - t0, 3),
    }


def federation_workload() -> list:
    """The federation sweep's workload: a fan of independent gangs (one
    routing decision each) across two namespaces — enough of them that a
    whole member's committed set is a real drain, small enough that a
    3-member sweep stays CI-sized."""
    from grove_tpu.api.meta import ObjectMeta
    from grove_tpu.api.types import (
        Container,
        PodCliqueSet,
        PodCliqueSetSpec,
        PodCliqueSetTemplateSpec,
        PodCliqueSpec,
        PodCliqueTemplateSpec,
        PodSpec,
    )

    return [
        PodCliqueSet(
            metadata=ObjectMeta(
                name=f"fed-{j}",
                namespace="team-a" if j % 2 else "team-b",
            ),
            spec=PodCliqueSetSpec(
                replicas=1,
                template=PodCliqueSetTemplateSpec(
                    cliques=[
                        PodCliqueTemplateSpec(
                            name="w",
                            spec=PodCliqueSpec(
                                replicas=4,
                                pod_spec=PodSpec(
                                    containers=[
                                        Container(
                                            name="m",
                                            resources={"cpu": 1.0},
                                        )
                                    ]
                                ),
                            ),
                        )
                    ]
                ),
            ),
        )
        for j in range(9)
    ]


def _build_federation(nodes: int, wal_root: str):
    from grove_tpu.cluster import make_nodes as _mk
    from grove_tpu.federation import FederationCoordinator

    config = {
        **FEDERATION_CONFIG,
        "durability": {
            **DURABILITY_CONFIG,
            "wal_dir": str(Path(wal_root) / "wal"),
        },
    }
    fed = FederationCoordinator(
        config,
        [_mk(nodes, name_prefix=f"c{i}-n") for i in range(3)],
    )
    quiet = io.StringIO()
    for cell in fed.cells:
        cell.harness.cluster.logger.stream = quiet
        cell.harness.manager.logger.stream = quiet
        cell.harness.scheduler.log.stream = quiet
        cell.harness.defrag.log.stream = quiet
    return fed


def federation_baseline(nodes: int) -> dict:
    """The fault-free federation fixpoint the chaotic runs must converge
    back to (merged survivor-side workload fingerprint)."""
    import tempfile

    from grove_tpu.chaos import federation_fingerprint

    with tempfile.TemporaryDirectory(prefix="grove-fed-base-") as td:
        fed = _build_federation(nodes, td)
        try:
            for pcs in federation_workload():
                fed.apply(pcs)
            fed.settle()
            for _ in range(4):
                fed.advance(2.0)
            return federation_fingerprint(fed)
        finally:
            fed.close()


def run_federation_seed(seed: int, nodes: int, baseline: dict,
                        trace_dir: Path | None = None,
                        explain_dir: Path | None = None) -> dict:
    """One seeded federation chaos run: whole-cluster outage, cluster
    partitions and coordinator crashes over the 3-member harness, judged
    against the fault-free federation fixpoint. The three federation
    rates are fixed (not mix-scaled): they are the only draws this
    driver makes, so every seed exercises the failover machinery."""
    import tempfile

    from grove_tpu.chaos import FederationChaos

    plan = FaultPlan(
        seed=seed,
        cluster_outage_rate=0.1,
        cluster_partition_rate=0.08,
        coordinator_crash_rate=0.05,
        chaos_steps=40,
        step_seconds=2.0,
    )
    t0 = time.perf_counter()
    error = None
    post: dict = {}
    fed = None
    with tempfile.TemporaryDirectory(prefix=f"grove-fed-{seed}-") as td:
        try:
            fed = _build_federation(nodes, td)
            post = FederationChaos(plan, fed).run(federation_workload())
        except Exception as exc:  # a failing seed must not stop the sweep
            error = f"{type(exc).__name__}: {exc}"
        finally:
            if fed is not None:
                fed.close()
    fingerprint_ok = bool(post) and post["fingerprint"] == baseline
    violations = post.get("invariant_violations", [])
    ok = fingerprint_ok and not violations and error is None
    result = {
        "seed": seed,
        "ok": ok,
        "fingerprint_match": fingerprint_ok,
        "invariant_violations": violations,
        "error": error,
        "faults_injected": dict(sorted(plan.counts.items())),
        "fence_proofs": post.get("fence_proofs", 0),
        "coordinator_crashes": post.get("coordinator_crashes", 0),
        "outage_cluster": post.get("outage_cluster"),
        "cluster_states": post.get("cluster_states", {}),
        "wall_seconds": round(time.perf_counter() - t0, 3),
    }
    outage = post.get("outage")
    if outage is not None and post.get("drained_at") is not None:
        result["drain_seconds"] = round(
            post["drained_at"] - outage["declared_at"], 3
        )
    if not ok and trace_dir is not None:
        # the federation postmortem: per-member lifecycle + routing
        # verdicts + the wedged set, the global-layer analog of the
        # flight-recorder dump
        trace_path = str(trace_dir / f"seed-{seed}-federation-flight.json")
        with open(trace_path, "w") as fh:
            json.dump(post, fh, indent=2, default=str)
            fh.write("\n")
        result["flight_dump"] = trace_path
    if explain_dir is not None and post.get("wedged", {}).get("wedged"):
        explain_path = str(
            explain_dir / f"seed-{seed}-federation-explain.json"
        )
        with open(explain_path, "w") as fh:
            json.dump(post["wedged"], fh, indent=2, default=str)
            fh.write("\n")
        result["explain_dump"] = explain_path
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=60,
                    help="number of seeds to sweep (default 60)")
    ap.add_argument("--start", type=int, default=0,
                    help="first seed (default 0)")
    ap.add_argument("--nodes", type=int, default=24,
                    help="cluster size (default 24)")
    ap.add_argument("--json", dest="json_path", default=None,
                    metavar="PATH",
                    help="also write the full sweep matrix (per-seed "
                         "results + summary) as one JSON document — the "
                         "CI artifact format")
    ap.add_argument("--trace-dir", dest="trace_dir", default=None,
                    metavar="DIR",
                    help="write a flight-recorder postmortem "
                         "(seed-N-flight.json: recent spans + errors + "
                         "events + the wedged-object summary) for every "
                         "FAILING seed; open with python -m "
                         "grove_tpu.observability.trace")
    ap.add_argument("--explain-dir", dest="explain_dir", default=None,
                    metavar="DIR",
                    help="write a placement-decision dump "
                         "(seed-N-explain.json: reason codes + "
                         "elimination funnels + preemption audits) for "
                         "every seed that settles with unscheduled "
                         "gangs; render with python -m "
                         "grove_tpu.observability.explain")
    ap.add_argument("--shards", type=int, default=1,
                    help="run the control plane horizontally sharded "
                         "across N worker replicas (default 1 = classic "
                         "single manager) and add the shard-failover "
                         "fault axis: seeded worker crashes (shards must "
                         "fail over within one lease duration), frozen "
                         "shard-map views, and handoff storms; "
                         "convergence is checked against the "
                         "single-replica fault-free fixpoint with the "
                         "ownership audit armed")
    ap.add_argument("--durability", action="store_true",
                    help="arm the durable-store fault axis: the harness "
                         "runs with a write-ahead-logged store "
                         "(per-seed temp wal_dir) and the plan adds "
                         "seeded whole-process crashes that recover "
                         "from disk mid-plan (snapshot + WAL replay, "
                         "soft state re-derived), torn WAL tails, "
                         "corrupted snapshots (recovery falls back to "
                         "the previous retained generation), and disk "
                         "stalls; convergence is checked against the "
                         "same fault-free fixpoint. Composable with "
                         "--shards N (whole-fleet process crashes "
                         "recover the sharded control plane from disk "
                         "mid-plan) and --partitions K")
    ap.add_argument("--partitions", type=int, default=1,
                    help="with --durability: run the durable store "
                         "PARTITIONED into K per-(namespace, kind) WAL/"
                         "snapshot chains (cluster/durability."
                         "PartitionedLog) and add the partition-scoped "
                         "fault axis — crashes with one partition's "
                         "tail torn (divergent streams merged back at "
                         "recovery) and per-partition disk stalls; "
                         "1 = the classic single WAL")
    ap.add_argument("--replication", action="store_true",
                    help="with --durability: arm the HA-replication "
                         "fault axis — the store runs with a SEMI-SYNC "
                         "log-shipping standby (cluster/replication.py) "
                         "and the plan adds seeded tailer stalls (lag "
                         "grows, semi-sync degrades for the window, "
                         "catch-up at stall end), mid-plan standby "
                         "promotions (the control plane fails over to "
                         "the promoted store and a fresh standby "
                         "re-arms), dual-leader fence proofs (the "
                         "deposed leader's append must be refused and "
                         "its WAL directory byte-unchanged, else the "
                         "seed fails), and standby crashes re-seeding "
                         "from the leader's snapshots; convergence is "
                         "checked against the same fault-free fixpoint "
                         "and the standby must end the run caught up")
    ap.add_argument("--serving", action="store_true",
                    help="arm the elastic-serving fault axis: serving is "
                         "configured with a FLAT traffic trace feeding "
                         "the kubelet->aggregation->HPA metrics "
                         "pipeline, the scaling group gets an HPA, and "
                         "the plan adds seeded traffic spikes (the loop "
                         "must scale up and stabilize back down after "
                         "disarm) and metrics-pipeline dropouts (stale "
                         "samples must never drive scale-down); "
                         "convergence is checked against the fault-free "
                         "traffic-driven equilibrium")
    ap.add_argument("--hierarchical", action="store_true",
                    help="run the placement engine's HIERARCHICAL "
                         "two-level solve under fire: the workload gains "
                         "a rack-level pack constraint (confinement) and "
                         "the solver's forced-flat min-nodes threshold "
                         "drops to 0, so every solve takes the coarse "
                         "domain-level pruning + per-domain sub-engine "
                         "path — node faults/cordons land between dirty "
                         "ticks and must ride the shard rebind path, "
                         "never a stale re-score; convergence is checked "
                         "against the fault-free fixpoint under the SAME "
                         "config")
    ap.add_argument("--defrag", action="store_true",
                    help="arm the continuous-defragmentation fault axis: "
                         "defrag is enabled on a tight sweep cadence and "
                         "the plan adds seeded migration storms (forced "
                         "relaxed-threshold sweeps: stage + evict waves "
                         "mid-chaos), crashes right after a storm "
                         "(migration tickets are soft state; evicted "
                         "gangs must still re-place through the general "
                         "solve), and destination-node faults before the "
                         "re-bind — with the disruption-budget audit "
                         "armed; convergence is checked against the "
                         "fault-free fixpoint (migrations move gangs, "
                         "and node assignment is outside the "
                         "fingerprint by contract)")
    ap.add_argument("--tenant-skew", dest="tenant_skew",
                    action="store_true",
                    help="enable tenant-skew load faults: tenancy "
                         "(quota admission + DRF fairness) is configured "
                         "with two tight-burst tenants, and seeded skew "
                         "bursts land in one tenant's namespace per "
                         "fault (some shed with QuotaExceeded); the "
                         "skew leaves at disarm, so convergence is "
                         "checked against the same fault-free fixpoint")
    ap.add_argument("--stream", action="store_true",
                    help="arm the streaming-admission fault axis: the "
                         "scheduler runs the continuous admission front "
                         "(SLO deadline budgets, micro-batch windows, "
                         "backpressure + brownout shedding; "
                         "grove_tpu/streaming) and the plan adds seeded "
                         "~10x burst storms (the front must shed with "
                         "structured DeadlineExceeded, never wedge; the "
                         "storm load leaves at disarm and shed workload "
                         "gangs re-admit once the queue drains) and "
                         "arrival stalls (deadline budgets burn through "
                         "the hold); convergence is checked against the "
                         "fault-free fixpoint under the SAME config and "
                         "the queue must end the run drained")
    ap.add_argument("--slo", action="store_true",
                    help="run the continuous SLO evaluator "
                         "(observability/slo.py) through every storm on "
                         "a tight sweep cadence and make the alert "
                         "lifecycle a per-seed invariant: with a storm "
                         "axis armed (--stream / --tenant-skew) at "
                         "least one alert must transition "
                         "pending->firing DURING the fault, and every "
                         "firing alert must resolve within a bounded "
                         "post-settle drain. Evaluation consumes zero "
                         "fault-plan draws (Events ride the raw store), "
                         "so seeds replay bit-identically with or "
                         "without it")
    ap.add_argument("--scorecard", dest="scorecard_path", default=None,
                    metavar="PATH",
                    help="with --slo: write every seed's final SLO "
                         "scorecard as one JSON document "
                         "({'seeds': {seed: card}}) — the CI artifact; "
                         "render with python -m "
                         "grove_tpu.observability.slo")
    ap.add_argument("--aggregate-ab", dest="aggregate_ab",
                    action="store_true",
                    help="sweep the ALWAYS-ON TRACING bit-identity "
                         "contract instead of the convergence matrix: "
                         "each seed runs twice — full-ring tracing vs "
                         "tracing.mode aggregate — and must produce the "
                         "same settled workload fingerprint with the "
                         "exact same fault-plan draw counts (the causal "
                         "ledger and critical-path folder do no store "
                         "writes and consume no RNG). Composes with "
                         "--stream (the CI streaming-chaos smoke) but "
                         "not with the other single-cluster axes")
    ap.add_argument("--federation", action="store_true",
                    help="sweep the FEDERATION fault axis instead of the "
                         "single-cluster matrix: a 3-member federation "
                         "(grove_tpu/federation, per-seed temp WAL "
                         "dirs, durability always on) under seeded "
                         "whole-cluster outages (declare + fence + "
                         "drain into survivors, the zombie append "
                         "refused and its directory byte-unchanged), "
                         "cluster partitions (short blips must NOT "
                         "fail over; ones outliving the window must), "
                         "and coordinator crashes (routing state "
                         "rebuilt from the durable journal); "
                         "convergence is checked against a fault-free "
                         "federation fixpoint. Standalone — not "
                         "composable with the single-cluster axes")
    args = ap.parse_args(argv)
    if args.federation and (
        args.durability or args.replication or args.shards > 1
        or args.serving or args.hierarchical or args.defrag
        or args.tenant_skew or args.stream
    ):
        ap.error("--federation is its own sweep axis (every member "
                 "already runs durable); it does not compose with the "
                 "single-cluster axes")
    if args.partitions > 1 and not args.durability:
        ap.error("--partitions requires --durability (there is no WAL "
                 "to partition without it)")
    if args.replication and not args.durability:
        ap.error("--replication requires --durability (the standby "
                 "tails the leader's WAL stream)")
    if args.aggregate_ab and (
        args.federation or args.durability or args.replication
        or args.shards > 1 or args.serving or args.hierarchical
        or args.defrag or args.tenant_skew or args.slo
    ):
        ap.error("--aggregate-ab composes only with --stream (it is an "
                 "A/B of the SAME run, not another fault axis)")
    trace_dir = None
    if args.trace_dir:
        trace_dir = Path(args.trace_dir)
        trace_dir.mkdir(parents=True, exist_ok=True)
    explain_dir = None
    if args.explain_dir:
        explain_dir = Path(args.explain_dir)
        explain_dir.mkdir(parents=True, exist_ok=True)

    if args.aggregate_ab:
        results = []
        failed = []
        for seed in range(args.start, args.start + args.seeds):
            result = run_aggregate_ab(seed, args.nodes,
                                      stream=args.stream)
            print(json.dumps(result), flush=True)
            results.append(result)
            if not result["ok"]:
                failed.append(seed)
        summary = {
            "swept": args.seeds,
            "start": args.start,
            "nodes": args.nodes,
            "aggregate_ab": True,
            "stream": args.stream,
            "failed_seeds": failed,
            "ok": not failed,
        }
        print(json.dumps(summary), flush=True)
        if args.json_path:
            with open(args.json_path, "w") as fh:
                json.dump(
                    {"summary": summary, "results": results}, fh, indent=2
                )
                fh.write("\n")
        return 1 if failed else 0

    if args.federation:
        baseline = federation_baseline(args.nodes)
        results = []
        failed = []
        for seed in range(args.start, args.start + args.seeds):
            result = run_federation_seed(
                seed, args.nodes, baseline,
                trace_dir=trace_dir, explain_dir=explain_dir,
            )
            print(json.dumps(result), flush=True)
            results.append(result)
            if not result["ok"]:
                failed.append(seed)
        summary = {
            "swept": args.seeds,
            "start": args.start,
            "nodes": args.nodes,
            "federation": True,
            "failed_seeds": failed,
            "ok": not failed,
        }
        print(json.dumps(summary), flush=True)
        if args.json_path:
            with open(args.json_path, "w") as fh:
                json.dump(
                    {"summary": summary, "results": results}, fh, indent=2
                )
                fh.write("\n")
        return 1 if failed else 0

    # the baseline fixpoint must be computed under the SAME config the
    # chaos runs use (tenancy changes PodGang defaulting) — but always
    # SINGLE-replica: the sharded runs must converge to the same
    # workload state a lone manager reaches (sharding is
    # workload-invisible by contract)
    baseline_config = dict(TENANT_SKEW_CONFIG) if args.tenant_skew else {}
    if args.serving:
        baseline_config = {**baseline_config, **SERVING_CONFIG}
    if args.hierarchical:
        baseline_config = {**baseline_config, **HIERARCHICAL_CONFIG}
    if args.defrag:
        baseline_config = {**baseline_config, **DEFRAG_CONFIG}
    if args.stream:
        baseline_config = {**baseline_config, **STREAM_CONFIG}
    baseline_h = Harness(
        nodes=make_nodes(args.nodes),
        config=baseline_config or None,
    )
    baseline_h.apply(sweep_workload(scaled=args.serving,
                                    hierarchical=args.hierarchical))
    baseline_h.settle()
    if args.stream:
        # the streaming front parks sub-batch arrivals on window timers
        # and settle() never advances the clock — drain the windows the
        # way the chaotic runs' settle_recovered does, so the baseline
        # fixpoint is the fully-placed one
        for _ in range(8):
            baseline_h.advance(
                STREAM_CONFIG["stream"]["window_max_seconds"]
            )
    if args.serving:
        # drive the HPA loop to its flat-trace equilibrium: the chaotic
        # runs must converge back to exactly this fleet shape
        for _ in range(4):
            baseline_h.advance(11.0)
            baseline_h.autoscale()
    baseline = settled_fingerprint(baseline_h.store)

    results = []
    failed = []
    scorecards = {}
    for seed in range(args.start, args.start + args.seeds):
        result = run_seed(seed, args.nodes, baseline, trace_dir=trace_dir,
                          explain_dir=explain_dir,
                          tenant_skew=args.tenant_skew,
                          shards=args.shards,
                          durability=args.durability,
                          partitions=args.partitions,
                          replication=args.replication,
                          serving=args.serving,
                          hierarchical=args.hierarchical,
                          defrag=args.defrag,
                          stream=args.stream,
                          slo=args.slo)
        # the full scorecard is an artifact, not a log line — pop it
        # off the printed result and collect it for --scorecard
        card = result.pop("slo_scorecard", None)
        if card is not None:
            scorecards[str(seed)] = card
        print(json.dumps(result), flush=True)
        results.append(result)
        if not result["ok"]:
            failed.append(seed)
    if args.scorecard_path and scorecards:
        with open(args.scorecard_path, "w") as fh:
            json.dump({"seeds": scorecards}, fh, indent=2)
            fh.write("\n")
    summary = {
        "swept": args.seeds,
        "start": args.start,
        "nodes": args.nodes,
        "shards": args.shards,
        "durability": args.durability,
        "partitions": args.partitions,
        "replication": args.replication,
        "serving": args.serving,
        "hierarchical": args.hierarchical,
        "defrag": args.defrag,
        "stream": args.stream,
        "slo": args.slo,
        "failed_seeds": failed,
        "ok": not failed,
    }
    print(json.dumps(summary), flush=True)
    if args.json_path:
        with open(args.json_path, "w") as fh:
            json.dump(
                {"summary": summary, "results": results}, fh, indent=2
            )
            fh.write("\n")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
