"""Profile the warm control-plane settle at the stress config on CPU.

Usage: python scripts/profile_settle.py [replicas] [nodes] [--cumtime]
"""
import cProfile
import pstats
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, ".")
from bench import bench_controlplane  # noqa: E402
import bench as bench_mod  # noqa: E402


def main():
    replicas = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 5000
    sort = "cumtime" if "--cumtime" in sys.argv else "tottime"

    from grove_tpu.api.types import Pod
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness

    # reproduce bench_controlplane's warm path under the profiler
    h = Harness(
        nodes=make_nodes(
            nodes, allocatable={"cpu": 32.0, "memory": 128.0, "tpu": 8.0}
        )
    )
    pcs = None
    # reuse bench's pcs builder via bench_controlplane internals: inline it
    from grove_tpu.api.meta import ObjectMeta as Meta
    from grove_tpu.api.types import (
        Container, PodCliqueSet, PodCliqueSetSpec, PodCliqueSetTemplateSpec,
        PodCliqueSpec, PodCliqueTemplateSpec, PodSpec,
    )

    def mk(name):
        return PodCliqueSet(
            metadata=Meta(name=name),
            spec=PodCliqueSetSpec(
                replicas=replicas,
                template=PodCliqueSetTemplateSpec(
                    cliques=[
                        PodCliqueTemplateSpec(
                            name="w",
                            spec=PodCliqueSpec(
                                replicas=8,
                                pod_spec=PodSpec(
                                    containers=[
                                        Container(name="m", resources={"cpu": 1.0})
                                    ]
                                ),
                            ),
                        )
                    ]
                ),
            ),
        )

    t0 = time.perf_counter()
    h.apply(mk("cpwarm"))
    h.settle()
    print(f"cold settle: {time.perf_counter() - t0:.2f}s", file=sys.stderr)

    pr = cProfile.Profile()
    t0 = time.perf_counter()
    pr.enable()
    h.apply(mk("cpbench"))
    h.settle()
    pr.disable()
    warm = time.perf_counter() - t0
    bound = sum(1 for p in h.store.scan(Pod.KIND) if p.node_name)
    print(f"warm settle: {warm:.2f}s bound={bound}", file=sys.stderr)
    st = pstats.Stats(pr, stream=sys.stderr)
    st.sort_stats(sort).print_stats(45)


if __name__ == "__main__":
    main()
