"""Test configuration: force an 8-device virtual CPU mesh.

All solver/parallel tests run on CPU with 8 virtual devices so multi-chip
sharding (Mesh/pjit/shard_map) is exercised without TPU hardware, mirroring
how the driver dry-runs the multichip path.

The environment registers the axon TPU backend from sitecustomize at
interpreter startup and programmatically sets jax_platforms="axon,cpu", so
setting JAX_PLATFORMS in the environment is NOT enough — jax is already
imported and configured before this file runs. Override the live jax config
instead (backends initialize lazily, so this takes effect as long as no
array op ran yet) and set the XLA host-device-count flag before the CPU
client is created.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert jax.device_count() == 8, (
    f"expected 8 virtual CPU devices, got {jax.device_count()} "
    f"on backend {jax.default_backend()!r}"
)
