"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

All solver/parallel tests run on CPU with 8 virtual devices so multi-chip
sharding (Mesh/pjit/shard_map) is exercised without TPU hardware, mirroring
how the driver dry-runs the multichip path.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
