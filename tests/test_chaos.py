"""Deterministic chaos suite: the convergence contract under
infrastructure failure.

The fuzz suite (test_fuzz_controlplane.py) sweeps WORKLOAD interleavings;
this suite sweeps INFRASTRUCTURE failure — transient store faults,
conflict storms, stale reads, delayed events, forced compaction, manager
crash-restarts (between and mid-reconcile), kubelet stalls, clock jumps —
through seeded, bit-reproducible FaultPlans. The contract asserted for
every shipped seed: once faults stop, the post-fault settle reaches the
SAME workload-level fixpoint a fault-free run reaches (and the fuzz
invariants hold), retries observably back off exponentially until the
configured cap, and a breaker-degraded controller recovers.

A failing seed reproduces exactly:
    python scripts/chaos_sweep.py --start <seed> --seeds 1
"""

import io

import pytest

from grove_tpu.api.types import PodCliqueScalingGroupConfig, PodCliqueSet
from grove_tpu.chaos import (
    ChaosHarness,
    ChaosStore,
    FaultPlan,
    ManagerCrash,
    TransientFault,
    check_invariants,
    settled_fingerprint,
)
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness
from grove_tpu.controller.runtime import (
    BREAKER_CLOSED,
    BREAKER_OPEN,
)

from test_e2e_basic import clique, simple_pcs

#: the shipped fast seeds (CI-sized; scripts/chaos_sweep.py is the wide
#: matrix). All verified convergent — a regression on any is a real
#: robustness break, and the seed reproduces it standalone.
CHAOS_SEEDS = (0, 3, 7, 9, 21)

NODES = 24


def chaos_workload():
    """Startup ordering + a scaling group: gang create/defer, gates,
    scaled gangs and RBAC are all on the fault path."""
    return simple_pcs(
        cliques=[
            clique("fe", replicas=2),
            clique("be", replicas=3, starts_after=["fe"]),
        ],
        replicas=2,
        startup="CliqueStartupTypeExplicit",
        sgs=[
            PodCliqueScalingGroupConfig(
                name="g", clique_names=["be"], replicas=2, min_available=1
            )
        ],
    )


def quiet(ch: ChaosHarness) -> ChaosHarness:
    """Silence the expected fault-storm error logs."""
    buf = io.StringIO()
    ch.harness.cluster.logger.stream = buf
    ch.harness.manager.logger.stream = buf
    return ch


@pytest.fixture(scope="module")
def baseline():
    """The fault-free fixpoint every chaotic run must converge to."""
    h = Harness(nodes=make_nodes(NODES))
    h.apply(chaos_workload())
    h.settle()
    return settled_fingerprint(h.store)


def run_seed(seed: int) -> ChaosHarness:
    ch = quiet(ChaosHarness(FaultPlan.from_seed(seed),
                            nodes=make_nodes(NODES)))
    ch.apply(chaos_workload())
    ch.run_chaos()
    return ch


@pytest.mark.chaos
class TestConvergenceContract:
    @pytest.mark.parametrize("seed", CHAOS_SEEDS)
    def test_post_fault_settle_matches_fault_free_fixpoint(
        self, seed, baseline
    ):
        ch = run_seed(seed)
        assert ch.plan.total_injected > 0, (
            "a chaos seed that injects nothing proves nothing"
        )
        assert check_invariants(ch.raw_store) == []
        fp = settled_fingerprint(ch.raw_store)
        assert fp == baseline, (
            f"seed {seed} diverged after faults stopped "
            f"(faults: {ch.plan.counts})"
        )
        # degraded states healed: every breaker closed, no retry chains
        assert ch.manager.resilience_snapshot() == {}
        # and the errors surfaced DURING the storm were cleared on
        # recovery (also covered by the fingerprint's last_errors counts)
        pcs = ch.raw_store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.last_errors == []

    def test_same_seed_is_bit_reproducible(self):
        a = run_seed(CHAOS_SEEDS[0])
        b = run_seed(CHAOS_SEEDS[0])
        assert a.plan.counts == b.plan.counts
        assert a.manager_restarts == b.manager_restarts
        assert settled_fingerprint(a.raw_store) == settled_fingerprint(
            b.raw_store
        )

    def test_crash_only_plan_replays_to_identical_state(self, baseline):
        """Isolates the crash-restart fault: a manager killed between and
        mid-way through reconciles (every other fault off) must
        replay/relist to the identical settled state."""
        plan = FaultPlan.from_seed(
            1234,
            write_fault_rate=0.0, conflict_burst_rate=0.0,
            stale_read_rate=0.0, event_delay_rate=0.0,
            kubelet_stall_rate=0.0, clock_jump_rate=0.0,
            manager_crash_rate=0.35, midflight_crash_rate=0.03,
            compaction_rate=0.15,
        )
        ch = quiet(ChaosHarness(plan, nodes=make_nodes(NODES)))
        ch.apply(chaos_workload())
        ch.run_chaos()
        assert ch.manager_restarts > 0, "the plan must actually crash it"
        assert settled_fingerprint(ch.raw_store) == baseline
        assert check_invariants(ch.raw_store) == []

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(40, 52))
    def test_wide_seed_matrix(self, seed, baseline):
        """The in-test slice of the wide sweep (scripts/chaos_sweep.py
        covers more); excluded from the tier-1 gate by the slow marker."""
        ch = run_seed(seed)
        assert check_invariants(ch.raw_store) == []
        assert settled_fingerprint(ch.raw_store) == baseline


class TestBackoff:
    def _failing_harness(self, **controller_cfg):
        """A harness whose SCHEDULER permanently fails. The scheduler is
        the clean probe for requeue timing: it has no record_error hook,
        so a failure writes nothing back to the store and the retry chain
        stays single (a status-writing reconciler's own error event
        enqueues a second, interleaved chain)."""
        h = Harness(
            nodes=make_nodes(4),
            config={"controllers": controller_cfg} if controller_cfg else None,
        )
        h.settle()
        self._original = h.scheduler.reconcile
        h.scheduler.reconcile = lambda req: (
            (_ for _ in ()).throw(RuntimeError("permanently failing"))
        )
        # a node create is watched ONLY by the scheduler: one chain
        h.store.create(make_nodes(1, name_prefix="poke")[0])
        return h

    def test_error_requeue_gaps_grow_exponentially_to_cap(self):
        """The acceptance criterion: virtual-time gaps between error
        requeues grow (strictly, jitter notwithstanding) until they pin
        at error_backoff_max_seconds."""
        h = self._failing_harness(
            error_backoff_base_seconds=1.0,
            error_backoff_max_seconds=60.0,
            error_retry_budget=100,  # keep the breaker out of this test
        )
        gaps = []
        for _ in range(10):
            h.settle()
            nxt = h.manager.next_requeue_at()
            assert nxt is not None
            gaps.append(nxt - h.clock.now())
            h.advance(nxt - h.clock.now() + 1e-6)
        for earlier, later in zip(gaps, gaps[1:]):
            assert later >= earlier, gaps
        # strict growth until the cap region...
        below_cap = [g for g in gaps if g < 60.0]
        for earlier, later in zip(below_cap, below_cap[1:]):
            assert later > earlier, gaps
        # ...then pinned exactly at the cap
        assert gaps[0] < 1.01, gaps  # base-sized first retry
        assert gaps[-1] == 60.0, gaps
        assert gaps[-2] == 60.0, gaps

    def test_jitter_is_deterministic_and_desynchronizing(self):
        from grove_tpu.controller.runtime import ControllerManager, Request
        from grove_tpu.cluster.store import ObjectStore

        m = ControllerManager(ObjectStore())
        r1 = Request("default", "a")
        r2 = Request("default", "b")
        # deterministic: same inputs, same delay
        assert m._backoff_delay("c", r1, 3) == m._backoff_delay("c", r1, 3)
        # desynchronizing: distinct requests get distinct delays
        assert m._backoff_delay("c", r1, 3) != m._backoff_delay("c", r2, 3)
        # bounded jitter: within [0.75, 1.0) of nominal
        for attempt in range(1, 6):
            nominal = 1.0 * 2 ** (attempt - 1)
            d = m._backoff_delay("c", r1, attempt)
            assert 0.75 * nominal <= d < nominal * 1.0 + 1e-9

    def test_success_resets_the_retry_chain(self):
        h = self._failing_harness(error_backoff_base_seconds=1.0,
                                  error_backoff_max_seconds=60.0)
        h.settle()
        h.advance(2.0)  # second failure: chain depth 2+
        snap = h.manager.resilience_snapshot()
        assert snap["scheduler"]["max_attempts"] >= 2
        # heal the reconciler: the next retry succeeds and resets
        h.scheduler.reconcile = self._original
        h.advance(10.0)
        assert h.manager.resilience_snapshot() == {}
        assert h.cluster.metrics.gauge("grove_manager_backoff_depth").value(
            controller="scheduler"
        ) == 0.0


class TestCircuitBreaker:
    def _broken_harness(self, budget=3):
        h = Harness(
            nodes=make_nodes(4),
            config={"controllers": {
                "error_backoff_base_seconds": 1.0,
                "error_backoff_max_seconds": 30.0,
                "error_retry_budget": budget,
            }},
        )
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        self._original = h.manager.controllers[0].reconcile
        h.manager.controllers[0].reconcile = lambda req: (
            (_ for _ in ()).throw(RuntimeError("down hard"))
        )
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        pcs.spec.replicas = 2
        h.store.update(pcs)
        return h

    def _fail_until_open(self, h, max_hops=10):
        for _ in range(max_hops):
            h.settle()
            if h.manager.breaker_state("podcliqueset") == BREAKER_OPEN:
                return
            nxt = h.manager.next_requeue_at()
            h.advance(nxt - h.clock.now() + 1e-6)
        raise AssertionError("breaker never opened")

    def test_budget_exhaustion_opens_breaker_and_degrades(self):
        h = self._broken_harness(budget=3)
        self._fail_until_open(h)
        m = h.cluster.metrics
        assert m.counter("grove_manager_breaker_opens_total").value(
            controller="podcliqueset"
        ) == 1
        assert m.gauge("grove_manager_breaker_state").value(
            controller="podcliqueset"
        ) == 1.0
        snap = h.manager.resilience_snapshot()
        assert snap["podcliqueset"]["breaker"] == "open"
        # degraded, not dead: work PARKS on the cool-down instead of
        # running (other controllers unaffected)
        reconciles_before = m.counter(
            "grove_manager_reconcile_total"
        ).value(controller="podcliqueset")
        h.advance(5.0)  # within the cool-down
        assert m.counter("grove_manager_reconcile_total").value(
            controller="podcliqueset"
        ) == reconciles_before
        assert h.manager.pending_requeue_count > 0

    def test_half_open_probe_recovers(self):
        h = self._broken_harness(budget=3)
        self._fail_until_open(h)
        # heal the underlying failure while the breaker is open
        h.manager.controllers[0].reconcile = self._original
        cooldown = h.config.controllers.error_backoff_max_seconds
        h.advance(cooldown + 1.0)  # probe fires, succeeds, breaker closes
        assert h.manager.breaker_state("podcliqueset") == BREAKER_CLOSED
        assert h.cluster.metrics.gauge("grove_manager_breaker_state").value(
            controller="podcliqueset"
        ) == 0.0
        live = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert live.status.last_errors == []
        assert live.status.last_operation.state == "Succeeded"

    def test_fresh_request_failing_half_open_probe_reopens(self):
        """The probe need not be the request that tripped the breaker: a
        DIFFERENT request (attempt count 1, far below budget) failing
        while half-open must re-open it, not leave it stuck half-open
        with degraded-mode protection off."""
        from grove_tpu.cluster.store import ObjectStore
        from grove_tpu.controller.runtime import (
            ControllerManager,
            Request,
            Result,
        )

        store = ObjectStore()
        m = ControllerManager(store, error_backoff_base_seconds=1.0,
                              error_backoff_max_seconds=10.0,
                              error_retry_budget=2)

        class Flaky:
            name = "c"
            watch_kinds = frozenset()
            healthy = False

            def map_event(self, event):
                return []

            def reconcile(self, req):
                if not self.healthy:
                    raise RuntimeError("down")
                return Result()

        c = Flaky()
        m.register(c)
        m._enqueue("c", Request("d", "a"))
        m.run_once()  # attempt 1
        store.clock.advance(2.0)
        m.run_once()  # attempt 2 = budget: breaker opens
        assert m.breaker_state("c") == BREAKER_OPEN
        store.clock.advance(11.0)  # past the cool-down
        m._enqueue("c", Request("d", "b"))  # FRESH request is the probe
        m.run_once()
        assert m.breaker_state("c") == BREAKER_OPEN, (
            "a failing half-open probe must re-open regardless of the "
            "probe request's own attempt count"
        )
        # and the re-opened breaker still recovers once healthy
        c.healthy = True
        store.clock.advance(11.0)
        m.run_once()
        assert m.breaker_state("c") == BREAKER_CLOSED

    def test_failed_probe_reopens(self):
        h = self._broken_harness(budget=3)
        self._fail_until_open(h)
        cooldown = h.config.controllers.error_backoff_max_seconds
        h.advance(cooldown + 1.0)  # probe fires and fails: re-open
        assert h.manager.breaker_state("podcliqueset") == BREAKER_OPEN
        assert h.cluster.metrics.counter(
            "grove_manager_breaker_opens_total"
        ).value(controller="podcliqueset") == 2
        # heal; the NEXT cool-down recovers
        h.manager.controllers[0].reconcile = self._original
        h.advance(cooldown + 1.0)
        assert h.manager.breaker_state("podcliqueset") == BREAKER_CLOSED


class TestManagerRestart:
    def test_fresh_manager_replays_to_identical_state(self):
        h = Harness(nodes=make_nodes(NODES))
        h.apply(chaos_workload())
        h.settle()
        before = settled_fingerprint(h.store)
        h._build_manager()  # fresh manager, cursor 0: full replay
        h.settle()
        assert settled_fingerprint(h.store) == before

    def test_fresh_manager_relists_past_compaction(self):
        h = Harness(nodes=make_nodes(NODES))
        h.apply(chaos_workload())
        h.settle()
        before = settled_fingerprint(h.store)
        h.store.compact_events(h.store.last_seq)  # horizon ahead of 0
        h._build_manager()  # cursor 0 is now behind: 410-Gone relist
        h.settle()
        assert settled_fingerprint(h.store) == before
        assert h.manager.event_cursor >= h.store.compaction_horizon


class TestChaosStoreUnit:
    def _armed(self, plan=None):
        from grove_tpu.cluster.cluster import Cluster

        c = Cluster(nodes=make_nodes(2))
        cs = ChaosStore(c.store, plan or FaultPlan(seed=0,
                                                  write_fault_rate=1.0))
        cs.armed = True
        return c, cs

    def test_user_actor_and_lease_exempt(self):
        from grove_tpu.controller.leaderelection import Lease
        from grove_tpu.api.meta import ObjectMeta

        c, cs = self._armed()
        # user-actor writes never fault (fixture setup stays reliable)
        cs.create(Lease(metadata=ObjectMeta(name="x", namespace="ns")))
        # operator-identity writes to the Lease kind are also exempt
        with cs.impersonate("system:serviceaccount:grove-system:op"):
            lease = cs.get(Lease.KIND, "ns", "x")
            lease.holder_identity = "op"
            cs.update(lease)

    def test_operator_writes_fault_and_map_to_conflict(self):
        from grove_tpu.api.auxiliary import PriorityClass
        from grove_tpu.api.meta import ObjectMeta
        from grove_tpu.controller.errors import to_grove_error

        c, cs = self._armed()
        with cs.impersonate("system:serviceaccount:grove-system:op"):
            with pytest.raises(TransientFault) as exc:
                cs.create(PriorityClass(
                    metadata=ObjectMeta(name="p", namespace=""), value=1.0
                ))
        err = to_grove_error(exc.value, "op")
        assert err.code == "ERR_STORE_CONFLICT"
        assert cs.plan.counts["write_fault"] >= 1
        # nothing committed: the fault fired before the write landed
        assert cs.get(PriorityClass.KIND, "", "p") is None

    def test_manager_crash_is_not_swallowed_by_recover_panic(self):
        """ManagerCrash must escape the manager's except-Exception guard:
        a dead process records nothing and requeues nothing."""
        assert not issubclass(ManagerCrash, Exception)
        plan = FaultPlan(seed=0, write_fault_rate=0.0,
                         conflict_burst_rate=0.0,
                         midflight_crash_rate=1.0)
        c, cs = self._armed(plan)
        from grove_tpu.api.auxiliary import PriorityClass
        from grove_tpu.api.meta import ObjectMeta

        with cs.impersonate("system:serviceaccount:grove-system:op"):
            with pytest.raises(ManagerCrash):
                cs.create(PriorityClass(
                    metadata=ObjectMeta(name="p", namespace=""), value=1.0
                ))
        # the mid-flight crash fires AFTER the commit: the write survives
        assert cs.get(PriorityClass.KIND, "", "p") is not None

    def test_delayed_events_truncate_without_gaps(self):
        from grove_tpu.api.auxiliary import PriorityClass
        from grove_tpu.api.meta import ObjectMeta

        plan = FaultPlan(seed=0, event_delay_rate=1.0, event_delay_reads=1)
        c, cs = self._armed(plan)
        cursor = cs.last_seq
        for i in range(4):
            c.store.create(PriorityClass(
                metadata=ObjectMeta(name=f"p{i}", namespace=""), value=1.0
            ))
        held = cs.events_since(cursor)
        assert len(held) < 4, "delivery hold must truncate"
        if held:
            cursor = held[-1].seq
        cs.armed = False  # faults stop: delivery resumes with no gap
        rest = cs.events_since(cursor)
        assert [e.name for e in held] + [e.name for e in rest] == [
            f"p{i}" for i in range(4)
        ], "delayed delivery must never skip an event"


class TestStaleCliqueReadStarvation:
    """Chaos-found (node-fault sweep, seed 6): a clique recreated by the
    gang-restart flow can be hidden from peek by informer lag exactly
    when its pod work is pending. Returning success there ate the dirty
    bit and starved the clique at zero pods — with no pod in existence,
    no event ever wakes the reconciler again. Not-visible + dirty now
    retries on the timer with the bit restored; genuine deletions stop
    the loop via their Deleted event (or the retry bound)."""

    def _reconciler(self):
        h = Harness(nodes=make_nodes(4))
        rec = next(
            c for c in h.manager.controllers if c.name == "podclique"
        )
        return h, rec

    def test_not_visible_with_pending_work_restores_dirty_and_retries(self):
        from grove_tpu.controller.runtime import Request

        h, rec = self._reconciler()
        key = ("default", "ghost")
        rec._pods_dirty.add(key)
        res = rec.reconcile(Request("default", "ghost"))
        assert res.requeue_after is not None
        assert key in rec._pods_dirty, "pending pod work must survive"

    def test_retry_is_bounded_for_a_genuinely_gone_clique(self):
        from grove_tpu.controller.runtime import Request

        h, rec = self._reconciler()
        key = ("default", "ghost")
        req = Request("default", "ghost")
        for _ in range(rec.NOT_VISIBLE_RETRIES):
            rec._pods_dirty.add(key)
            assert rec.reconcile(req).requeue_after is not None
        rec._pods_dirty.add(key)
        res = rec.reconcile(req)
        assert res.requeue_after is None, "the loop must terminate"
        assert key not in rec._not_visible

    def test_deleted_event_stops_the_retry_loop(self):
        from grove_tpu.api.types import PodClique
        from grove_tpu.cluster.store import Event
        from grove_tpu.controller.runtime import Request

        h, rec = self._reconciler()
        key = ("default", "ghost")
        rec._pods_dirty.add(key)
        assert rec.reconcile(Request("default", "ghost")).requeue_after
        rec.map_event(Event(
            seq=1, type="Deleted", kind=PodClique.KIND,
            namespace="default", name="ghost", obj=None,
        ))
        assert key not in rec._pods_dirty
        assert key not in rec._not_visible
        res = rec.reconcile(Request("default", "ghost"))
        assert res.requeue_after is None

    def test_visible_again_clears_the_counter_and_syncs(self):
        """After a lagging read catches up, the retried reconcile runs
        the pod component and rebuilds the clique's pods."""
        h, rec = self._reconciler()
        h.apply(chaos_workload())
        h.settle()
        pods = h.store.list("Pod")
        assert pods and all(p.node_name for p in pods)
        assert rec._not_visible == {}
