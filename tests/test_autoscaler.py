"""Autoscaler controller suite: the k8s HPA algorithm's edges.

The AS* e2e cases in test_e2e_updates.py prove the happy path end to
end; this file pins the controller semantics the diurnal serving loop
leans on — the tolerance band, min/max clamping, the
missing-metrics-never-scale-down rule (absent AND stale samples),
PCSG-vs-PodClique pod selection, the scale-down stabilization window,
sample GC for deleted pods, and HPA admission.
"""

import pytest

from grove_tpu.api import ValidationError
from grove_tpu.api.auxiliary import (
    HorizontalPodAutoscaler,
    HPASpec,
)
from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import (
    AutoScalingConfig,
    Pod,
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueScalingGroupConfig,
)
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness

from test_e2e_basic import clique, simple_pcs


def scaled_pcs(min_replicas=1, max_replicas=5, target=0.5):
    return simple_pcs(
        name="as",
        cliques=[clique("w", replicas=2)],
        sgs=[PodCliqueScalingGroupConfig(
            name="grp", clique_names=["w"], replicas=2, min_available=1,
            scale_config=AutoScalingConfig(
                min_replicas=min_replicas, max_replicas=max_replicas,
                target_utilization=target,
            ))],
    )


def harness(config=None):
    return Harness(nodes=make_nodes(16), config=config)


def observe_all(h, utilization):
    for p in h.store.list(Pod.KIND):
        h.autoscaler.observe(p.metadata.name, utilization)


def grp_replicas(h):
    return h.store.get(
        PodCliqueScalingGroup.KIND, "default", "as-0-grp"
    ).spec.replicas


class TestToleranceAndClamping:
    def test_within_tolerance_no_scale(self):
        h = harness()
        h.apply(scaled_pcs())
        h.settle()
        observe_all(h, 0.54)  # ratio 1.08, inside the 0.1 band
        h.autoscale()
        assert grp_replicas(h) == 2

    def test_just_outside_tolerance_scales(self):
        h = harness()
        h.apply(scaled_pcs())
        h.settle()
        observe_all(h, 0.56)  # ratio 1.12 > 1.1
        h.autoscale()
        assert grp_replicas(h) == 3

    def test_max_clamp(self):
        h = harness()
        h.apply(scaled_pcs(max_replicas=3))
        h.settle()
        observe_all(h, 5.0)  # ratio 10 -> desired 20, clamped
        h.autoscale()
        assert grp_replicas(h) == 3

    def test_min_clamp(self):
        h = harness(config={
            "autoscaler": {"scale_down_stabilization_seconds": 0.0}
        })
        h.apply(scaled_pcs(min_replicas=2))
        h.settle()
        observe_all(h, 0.01)  # desired 1, clamped up to min 2
        h.autoscale()
        assert grp_replicas(h) == 2

    def test_float_dust_does_not_overscale(self):
        """126/120/0.7 = 1.5000000000000002: a bare ceil would scale
        2 -> 4; the epsilon-guarded one lands on 3 like the k8s
        milli-unit math."""
        h = harness()
        h.apply(scaled_pcs(target=0.7))
        h.settle()
        observe_all(h, 1.05)  # ratio "1.5"
        h.autoscale()
        assert grp_replicas(h) == 3


class TestMissingMetrics:
    def test_no_samples_no_scale(self):
        h = harness()
        h.apply(scaled_pcs())
        h.settle()
        h.autoscale()
        assert grp_replicas(h) == 2

    def test_stale_samples_never_drive_scale_down(self):
        h = harness(config={
            "autoscaler": {
                "metrics_max_age_seconds": 30.0,
                "scale_down_stabilization_seconds": 0.0,
            }
        })
        h.apply(scaled_pcs())
        h.settle()
        observe_all(h, 0.05)  # would scale to min...
        h.advance(31.0)       # ...but the samples age past the horizon
        h.autoscale()
        assert grp_replicas(h) == 2

    def test_fresh_samples_do_scale_down(self):
        h = harness(config={
            "autoscaler": {
                "metrics_max_age_seconds": 30.0,
                "scale_down_stabilization_seconds": 0.0,
            }
        })
        h.apply(scaled_pcs())
        h.settle()
        observe_all(h, 0.05)
        h.autoscale()
        assert grp_replicas(h) == 1


class TestPodSelection:
    def test_pcsg_target_averages_only_its_pods(self):
        """The PCSG-target HPA selects by the grove.io/
        podcliquescalinggroup label: samples on the standalone clique's
        pods must not feed it."""
        h = harness()
        pcs = simple_pcs(
            name="as",
            cliques=[clique("w", replicas=2), clique("solo", replicas=2)],
            sgs=[PodCliqueScalingGroupConfig(
                name="grp", clique_names=["w"], replicas=2, min_available=1,
                scale_config=AutoScalingConfig(
                    min_replicas=1, max_replicas=5, target_utilization=0.5,
                ))],
        )
        h.apply(pcs)
        h.settle()
        from grove_tpu.api import constants

        for p in h.store.list(Pod.KIND):
            if constants.LABEL_PCSG in p.metadata.labels:
                h.autoscaler.observe(p.metadata.name, 0.5)  # on target
            else:
                h.autoscaler.observe(p.metadata.name, 5.0)  # screaming
        h.autoscale()
        assert grp_replicas(h) == 2  # the solo pods' load is not ours

    def test_clique_target_scales_pod_count(self):
        """A standalone clique with scale_config gets a
        PodClique-target HPA whose writes change the clique's pod count
        directly (selection by the grove.io/podclique label)."""
        h = harness()
        pcs = simple_pcs(
            name="as",
            cliques=[clique("solo", replicas=2)],
        )
        pcs.spec.template.cliques[0].spec.scale_config = AutoScalingConfig(
            min_replicas=1, max_replicas=6, target_utilization=0.5,
        )
        h.apply(pcs)
        h.settle()
        observe_all(h, 1.0)  # 2x target
        h.autoscale()
        pclq = h.store.get(PodClique.KIND, "default", "as-0-solo")
        assert pclq.spec.replicas == 4
        pods = [p for p in h.store.list(Pod.KIND) if p.status.ready]
        assert len(pods) == 4


class TestStabilizationWindow:
    def cfg(self, window):
        return {"autoscaler": {
            "scale_down_stabilization_seconds": window,
            "metrics_max_age_seconds": 600.0,
            "sync_interval_seconds": 10.0,
        }}

    def test_scale_down_held_by_recent_high_recommendation(self):
        h = harness(config=self.cfg(120.0))
        h.apply(scaled_pcs())
        h.settle()
        observe_all(h, 1.0)   # recommends 4
        h.autoscale()
        assert grp_replicas(h) == 4
        observe_all(h, 0.05)  # noisy trough: raw recommendation is min
        h.advance(20.0)
        h.autoscale()
        # the 4-recommendation is still inside the window: held
        assert grp_replicas(h) == 4
        holds = h.cluster.metrics.counter(
            "grove_autoscaler_stabilized_holds_total"
        )
        assert holds.total() >= 1

    def test_scale_down_applies_after_window_expires(self):
        h = harness(config=self.cfg(120.0))
        h.apply(scaled_pcs())
        h.settle()
        observe_all(h, 1.0)
        h.autoscale()
        assert grp_replicas(h) == 4
        h.advance(121.0)      # the high recommendation ages out
        observe_all(h, 0.05)
        h.autoscale()
        assert grp_replicas(h) == 1

    def test_zero_window_scales_down_immediately(self):
        h = harness(config=self.cfg(0.0))
        h.apply(scaled_pcs())
        h.settle()
        observe_all(h, 1.0)
        h.autoscale()
        observe_all(h, 0.05)
        h.advance(1.0)
        h.autoscale()
        assert grp_replicas(h) == 1

    def test_scale_up_is_never_stabilized(self):
        h = harness(config=self.cfg(300.0))
        h.apply(scaled_pcs())
        h.settle()
        observe_all(h, 1.0)
        h.autoscale()
        assert grp_replicas(h) == 4  # immediate, window is down-only


class TestMetricsGC:
    def test_samples_of_deleted_pods_are_pruned(self):
        h = harness()
        h.apply(scaled_pcs())
        h.settle()
        pipeline = h.cluster.pod_metrics
        observe_all(h, 0.5)
        live = len(pipeline)
        for i in range(50):
            h.autoscaler.observe(f"ghost-{i}", 1.0)
        assert len(pipeline) == live + 50
        h.autoscale()  # the sweep GCs entries for pods that don't exist
        assert len(pipeline) == live
        gced = h.cluster.metrics.counter(
            "grove_autoscaler_samples_gced_total"
        )
        assert gced.total() == 50

    def test_churn_does_not_grow_the_aggregator(self):
        """Scale up then down: the deleted scaled pods' samples leave on
        the next sweep instead of surviving forever."""
        h = harness(config={
            "autoscaler": {"scale_down_stabilization_seconds": 0.0}
        })
        h.apply(scaled_pcs())
        h.settle()
        observe_all(h, 1.0)
        h.autoscale()
        assert grp_replicas(h) == 4
        observe_all(h, 0.05)
        h.advance(1.0)
        h.autoscale()
        assert grp_replicas(h) == 1
        h.autoscale()
        pipeline = h.cluster.pod_metrics
        live = {
            (p.metadata.namespace, p.metadata.name)
            for p in h.store.list(Pod.KIND)
        }
        # hand-fed observe() samples live under the ANY_NAMESPACE
        # sentinel; either way every surviving key names a live pod
        allowed = live | {
            (pipeline.ANY_NAMESPACE, name) for _, name in live
        }
        assert set(pipeline._samples) <= allowed


class TestHPAAdmission:
    def mk(self, **kw):
        spec = dict(
            target_kind=PodCliqueScalingGroup.KIND, target_name="t",
            min_replicas=1, max_replicas=3, target_utilization=0.5,
        )
        spec.update(kw)
        return HorizontalPodAutoscaler(
            metadata=ObjectMeta(name="h"), spec=HPASpec(**spec)
        )

    def test_valid_hpa_admitted(self):
        h = harness()
        h.store.create(self.mk())

    def test_min_above_max_rejected(self):
        h = harness()
        with pytest.raises(ValidationError, match="min_replicas"):
            h.store.create(self.mk(min_replicas=4, max_replicas=3))

    def test_min_below_one_rejected(self):
        h = harness()
        with pytest.raises(ValidationError, match="min_replicas"):
            h.store.create(self.mk(min_replicas=0))

    def test_nonpositive_target_rejected(self):
        h = harness()
        with pytest.raises(ValidationError, match="target_utilization"):
            h.store.create(self.mk(target_utilization=0.0))

    def test_unscalable_target_kind_rejected(self):
        h = harness()
        with pytest.raises(ValidationError, match="target_kind"):
            h.store.create(self.mk(target_kind="Pod"))

    def test_template_scale_config_min_above_max_rejected(self):
        h = harness()
        with pytest.raises(ValidationError, match="minReplicas"):
            h.apply(scaled_pcs(min_replicas=6, max_replicas=5))

    def test_template_scale_config_bad_target_rejected(self):
        h = harness()
        with pytest.raises(ValidationError, match="targetUtilization"):
            h.apply(scaled_pcs(target=1.5))


class TestConfigValidation:
    def test_bad_autoscaler_knobs_rejected(self):
        from grove_tpu.api.config import load_operator_config

        with pytest.raises(ValidationError) as exc:
            load_operator_config({
                "autoscaler": {
                    "sync_interval_seconds": 0,
                    "scale_down_stabilization_seconds": -1,
                    "metrics_max_age_seconds": -5,
                }
            })
        msg = str(exc.value)
        assert "sync_interval_seconds" in msg
        assert "scale_down_stabilization_seconds" in msg
        assert "metrics_max_age_seconds" in msg

    def test_max_age_below_sync_interval_rejected(self):
        from grove_tpu.api.config import load_operator_config

        with pytest.raises(ValidationError, match="metrics_max_age"):
            load_operator_config({
                "autoscaler": {
                    "sync_interval_seconds": 60.0,
                    "metrics_max_age_seconds": 30.0,
                }
            })

    def test_reservation_reuse_must_be_bool(self):
        from grove_tpu.api.config import load_operator_config

        with pytest.raises(ValidationError, match="reservation_reuse"):
            load_operator_config({"solver": {"reservation_reuse": 1}})
