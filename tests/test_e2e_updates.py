"""Rolling-update (RU*) and autoscaling (AS*) E2E suites, after the
reference's rolling_updates_test.go RU7-RU21 scenario family."""

from grove_tpu.api import constants
from grove_tpu.api.types import (
    AutoScalingConfig,
    Pod,
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueSet,
    PodCliqueScalingGroupConfig,
)
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness
from grove_tpu.controller.common import stable_hash

from test_e2e_basic import clique, simple_pcs


def bump_image(harness, name="simple1"):
    pcs = harness.store.get(PodCliqueSet.KIND, "default", name)
    for c in pcs.spec.template.cliques:
        c.spec.pod_spec.containers[0].image = "app:v2"
    return harness.store.update(pcs)


def pod_hashes(harness):
    return {
        p.metadata.name: p.metadata.labels[constants.LABEL_POD_TEMPLATE_HASH]
        for p in harness.store.list(Pod.KIND)
    }


class TestRU_RollingUpdates:
    def test_ru1_single_replica_rolls_all_pods(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs())
        h.settle()
        before = pod_hashes(h)
        pcs = bump_image(h)
        target = stable_hash(pcs.spec.template.cliques[0].spec.pod_spec)
        h.settle()
        after = pod_hashes(h)
        assert set(after.values()) == {target}
        assert all(before[n] != after[n] for n in after)
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.rolling_update_progress.completed
        from grove_tpu.controller.common import pcs_generation_hash

        assert pcs.status.current_generation_hash == pcs_generation_hash(pcs)
        assert pcs.status.updated_replicas == 1
        # workload converged back to fully ready
        assert all(p.status.ready for p in h.store.list(Pod.KIND))

    def test_ru2_two_replicas_roll_one_at_a_time(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs(replicas=2))
        h.settle()
        bump_image(h)
        # drive manually: after the first manager pass only ONE replica may
        # have received the new template
        h.manager.settle()
        specs = {
            p.metadata.name: stable_hash(p.spec.pod_spec)
            for p in h.store.list(PodClique.KIND)
        }
        distinct = set(specs.values())
        assert len(distinct) == 2, "one replica updating, one still old"
        h.settle()
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.rolling_update_progress.completed
        assert pcs.status.updated_replicas == 2
        assert all(p.status.ready for p in h.store.list(Pod.KIND))

    def test_ru3_pod_at_a_time_no_availability_collapse(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs(cliques=[clique("w", replicas=3)]))
        h.settle()
        bump_image(h)
        # step the loop tick by tick: at no point may more than one of the
        # three pods be missing/unready (single-pod-at-a-time for ready pods)
        for _ in range(64):
            progressed = h.manager.run_once()
            h.kubelet.tick()
            pods = [
                p for p in h.store.list(Pod.KIND)
                if p.metadata.deletion_timestamp is None
            ]
            ready = sum(1 for p in pods if p.status.ready)
            assert ready >= 2, f"availability collapsed to {ready}"
            if progressed == 0:
                pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
                prog = pcs.status.rolling_update_progress
                if prog is not None and prog.completed:
                    break
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.rolling_update_progress.completed

    def test_ru1b_pclq_rollout_status_parity(self):
        """PodCliqueStatus.updated_replicas + rolling_update_progress are
        written by the pod-at-a-time rollout (podclique.go:104-137): the
        progress appears mid-flight with a current_pod, then completes."""
        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs(cliques=[clique("w", replicas=3)]))
        h.settle()
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        assert pclq.status.updated_replicas == 3  # fresh pods match template
        bump_image(h)
        saw_inflight = False
        for _ in range(64):
            progressed = h.manager.run_once()
            h.kubelet.tick()
            pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
            prog = pclq.status.rolling_update_progress
            if prog is not None and not prog.completed:
                saw_inflight = True
                # current_pod is set while a victim awaits replacement; None
                # only in the gap where the replacement pod is being created
                assert pclq.status.updated_replicas == len(prog.updated_pods)
            if progressed == 0:
                pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
                p = pcs.status.rolling_update_progress
                if p is not None and p.completed:
                    break
        assert saw_inflight, "rollout progress never surfaced mid-flight"
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        assert pclq.status.updated_replicas == 3
        prog = pclq.status.rolling_update_progress
        assert prog is not None and prog.completed and prog.current_pod is None
        assert len(prog.updated_pods) == 3

    def test_ru4_pcsg_rolls_replica_at_a_time(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(simple_pcs(
            name="sg",
            cliques=[clique("w", replicas=2)],
            sgs=[PodCliqueScalingGroupConfig(name="grp", clique_names=["w"],
                                             replicas=3, min_available=2)],
        ))
        h.settle()
        bump_image(h, "sg")
        h.settle()
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "sg-0-grp")
        assert pcsg.status.rolling_update_progress.completed
        assert sorted(pcsg.status.rolling_update_progress.updated_replica_indices) \
            == [0, 1, 2]
        target = stable_hash(
            h.store.get(PodCliqueSet.KIND, "default", "sg")
            .spec.template.cliques[0].spec.pod_spec
        )
        assert set(pod_hashes(h).values()) == {target}

    def test_ru5_update_during_scale_out(self):
        """RU x scale race: scale-out lands mid-update; everything converges
        to the new template at the larger size."""
        h = Harness(nodes=make_nodes(16))
        h.apply(simple_pcs(
            name="sg",
            cliques=[clique("w", replicas=1)],
            sgs=[PodCliqueScalingGroupConfig(name="grp", clique_names=["w"],
                                             replicas=2, min_available=1)],
        ))
        h.settle()
        bump_image(h, "sg")
        h.manager.run_once()  # update starts
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "sg-0-grp")
        pcsg.spec.replicas = 4
        h.store.update(pcsg)
        h.settle()
        target = stable_hash(
            h.store.get(PodCliqueSet.KIND, "default", "sg")
            .spec.template.cliques[0].spec.pod_spec
        )
        hashes = pod_hashes(h)
        assert len(hashes) == 4
        assert set(hashes.values()) == {target}
        assert all(p.status.ready for p in h.store.list(Pod.KIND))


class TestAS_Autoscaling:
    def scaled_pcs(self):
        pcs = simple_pcs(
            name="as",
            cliques=[clique("w", replicas=2)],
            sgs=[PodCliqueScalingGroupConfig(
                name="grp", clique_names=["w"], replicas=2, min_available=1,
                scale_config=AutoScalingConfig(min_replicas=1, max_replicas=5,
                                               target_utilization=0.5))],
        )
        return pcs

    def test_as1_hpa_object_created(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.scaled_pcs())
        h.settle()
        hpa = h.store.get("HorizontalPodAutoscaler", "default", "as-0-grp-hpa")
        assert hpa is not None
        assert hpa.spec.target_kind == PodCliqueScalingGroup.KIND
        assert (hpa.spec.min_replicas, hpa.spec.max_replicas) == (1, 5)

    def test_as2_scale_out_on_load_creates_scaled_gangs(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.scaled_pcs())
        h.settle()
        for p in h.store.list(Pod.KIND):
            h.autoscaler.observe(p.metadata.name, 1.0)  # 2x the 0.5 target
        h.autoscale()
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "as-0-grp")
        assert pcsg.spec.replicas == 4  # ceil(2 * 1.0/0.5)
        gangs = sorted(g.metadata.name for g in h.store.list("PodGang"))
        assert gangs == ["as-0", "as-0-grp-0", "as-0-grp-1", "as-0-grp-2"]
        assert all(p.status.ready for p in h.store.list(Pod.KIND))

    def test_as3_scale_in_on_idle(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.scaled_pcs())
        h.settle()
        for p in h.store.list(Pod.KIND):
            h.autoscaler.observe(p.metadata.name, 0.05)
        h.autoscale()
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "as-0-grp")
        assert pcsg.spec.replicas == 1
        assert sorted(g.metadata.name for g in h.store.list("PodGang")) == ["as-0"]

    def test_as4_within_tolerance_no_scale(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.scaled_pcs())
        h.settle()
        for p in h.store.list(Pod.KIND):
            h.autoscaler.observe(p.metadata.name, 0.52)  # within 10% of 0.5
        h.autoscale()
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "as-0-grp")
        assert pcsg.spec.replicas == 2

    def test_as5_no_metrics_no_scale(self):
        """Missing metrics must never drive scale-down (review finding)."""
        h = Harness(nodes=make_nodes(16))
        h.apply(self.scaled_pcs())
        h.settle()
        h.autoscale()  # no observe() calls at all
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "as-0-grp")
        assert pcsg.spec.replicas == 2
