"""Rolling-update (RU*) and autoscaling (AS*) E2E suites, after the
reference's rolling_updates_test.go RU7-RU21 scenario family."""

from grove_tpu.api import constants
from grove_tpu.api.types import (
    AutoScalingConfig,
    Pod,
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueSet,
    PodCliqueScalingGroupConfig,
)
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness
from grove_tpu.controller.common import stable_hash

from test_e2e_basic import clique, simple_pcs


def bump_image(harness, name="simple1", tag="app:v2"):
    pcs = harness.store.get(PodCliqueSet.KIND, "default", name)
    for c in pcs.spec.template.cliques:
        c.spec.pod_spec.containers[0].image = tag
    return harness.store.update(pcs)


def pod_hashes(harness):
    return {
        p.metadata.name: p.metadata.labels[constants.LABEL_POD_TEMPLATE_HASH]
        for p in harness.store.list(Pod.KIND)
    }


class TestRU_RollingUpdates:
    def test_ru1_single_replica_rolls_all_pods(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs())
        h.settle()
        before = pod_hashes(h)
        pcs = bump_image(h)
        target = stable_hash(pcs.spec.template.cliques[0].spec.pod_spec)
        h.settle()
        after = pod_hashes(h)
        assert set(after.values()) == {target}
        assert all(before[n] != after[n] for n in after)
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.rolling_update_progress.completed
        from grove_tpu.controller.common import pcs_generation_hash

        assert pcs.status.current_generation_hash == pcs_generation_hash(pcs)
        assert pcs.status.updated_replicas == 1
        # workload converged back to fully ready
        assert all(p.status.ready for p in h.store.list(Pod.KIND))

    def test_ru2_two_replicas_roll_one_at_a_time(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs(replicas=2))
        h.settle()
        bump_image(h)
        # drive manually: after the first manager pass only ONE replica may
        # have received the new template
        h.manager.settle()
        specs = {
            p.metadata.name: stable_hash(p.spec.pod_spec)
            for p in h.store.list(PodClique.KIND)
        }
        distinct = set(specs.values())
        assert len(distinct) == 2, "one replica updating, one still old"
        h.settle()
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.rolling_update_progress.completed
        assert pcs.status.updated_replicas == 2
        assert all(p.status.ready for p in h.store.list(Pod.KIND))

    def test_ru3_pod_at_a_time_no_availability_collapse(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs(cliques=[clique("w", replicas=3)]))
        h.settle()
        bump_image(h)
        # step the loop tick by tick: at no point may more than one of the
        # three pods be missing/unready (single-pod-at-a-time for ready pods)
        for _ in range(64):
            progressed = h.manager.run_once()
            h.kubelet.tick()
            pods = [
                p for p in h.store.list(Pod.KIND)
                if p.metadata.deletion_timestamp is None
            ]
            ready = sum(1 for p in pods if p.status.ready)
            assert ready >= 2, f"availability collapsed to {ready}"
            if progressed == 0:
                pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
                prog = pcs.status.rolling_update_progress
                if prog is not None and prog.completed:
                    break
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.rolling_update_progress.completed

    def test_ru1b_pclq_rollout_status_parity(self):
        """PodCliqueStatus.updated_replicas + rolling_update_progress are
        written by the pod-at-a-time rollout (podclique.go:104-137): the
        progress appears mid-flight with a current_pod, then completes."""
        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs(cliques=[clique("w", replicas=3)]))
        h.settle()
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        assert pclq.status.updated_replicas == 3  # fresh pods match template
        bump_image(h)
        saw_inflight = False
        for _ in range(64):
            progressed = h.manager.run_once()
            h.kubelet.tick()
            pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
            prog = pclq.status.rolling_update_progress
            if prog is not None and not prog.completed:
                saw_inflight = True
                # current_pod is set while a victim awaits replacement; None
                # only in the gap where the replacement pod is being created
                assert pclq.status.updated_replicas == len(prog.updated_pods)
            if progressed == 0:
                pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
                p = pcs.status.rolling_update_progress
                if p is not None and p.completed:
                    break
        assert saw_inflight, "rollout progress never surfaced mid-flight"
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        assert pclq.status.updated_replicas == 3
        prog = pclq.status.rolling_update_progress
        assert prog is not None and prog.completed and prog.current_pod is None
        assert len(prog.updated_pods) == 3

    def test_ru4_pcsg_rolls_replica_at_a_time(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(simple_pcs(
            name="sg",
            cliques=[clique("w", replicas=2)],
            sgs=[PodCliqueScalingGroupConfig(name="grp", clique_names=["w"],
                                             replicas=3, min_available=2)],
        ))
        h.settle()
        bump_image(h, "sg")
        h.settle()
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "sg-0-grp")
        assert pcsg.status.rolling_update_progress.completed
        assert sorted(pcsg.status.rolling_update_progress.updated_replica_indices) \
            == [0, 1, 2]
        target = stable_hash(
            h.store.get(PodCliqueSet.KIND, "default", "sg")
            .spec.template.cliques[0].spec.pod_spec
        )
        assert set(pod_hashes(h).values()) == {target}

    def test_ru5_update_during_scale_out(self):
        """RU x scale race: scale-out lands mid-update; everything converges
        to the new template at the larger size."""
        h = Harness(nodes=make_nodes(16))
        h.apply(simple_pcs(
            name="sg",
            cliques=[clique("w", replicas=1)],
            sgs=[PodCliqueScalingGroupConfig(name="grp", clique_names=["w"],
                                             replicas=2, min_available=1)],
        ))
        h.settle()
        bump_image(h, "sg")
        h.manager.run_once()  # update starts
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "sg-0-grp")
        pcsg.spec.replicas = 4
        h.store.update(pcsg)
        h.settle()
        target = stable_hash(
            h.store.get(PodCliqueSet.KIND, "default", "sg")
            .spec.template.cliques[0].spec.pod_spec
        )
        hashes = pod_hashes(h)
        assert len(hashes) == 4
        assert set(hashes.values()) == {target}
        assert all(p.status.ready for p in h.store.list(Pod.KIND))


class TestAS_Autoscaling:
    def scaled_pcs(self):
        pcs = simple_pcs(
            name="as",
            cliques=[clique("w", replicas=2)],
            sgs=[PodCliqueScalingGroupConfig(
                name="grp", clique_names=["w"], replicas=2, min_available=1,
                scale_config=AutoScalingConfig(min_replicas=1, max_replicas=5,
                                               target_utilization=0.5))],
        )
        return pcs

    def test_as1_hpa_object_created(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.scaled_pcs())
        h.settle()
        hpa = h.store.get("HorizontalPodAutoscaler", "default", "as-0-grp-hpa")
        assert hpa is not None
        assert hpa.spec.target_kind == PodCliqueScalingGroup.KIND
        assert (hpa.spec.min_replicas, hpa.spec.max_replicas) == (1, 5)

    def test_as2_scale_out_on_load_creates_scaled_gangs(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.scaled_pcs())
        h.settle()
        for p in h.store.list(Pod.KIND):
            h.autoscaler.observe(p.metadata.name, 1.0)  # 2x the 0.5 target
        h.autoscale()
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "as-0-grp")
        assert pcsg.spec.replicas == 4  # ceil(2 * 1.0/0.5)
        gangs = sorted(g.metadata.name for g in h.store.list("PodGang"))
        assert gangs == ["as-0", "as-0-grp-0", "as-0-grp-1", "as-0-grp-2"]
        assert all(p.status.ready for p in h.store.list(Pod.KIND))

    def test_as3_scale_in_on_idle(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.scaled_pcs())
        h.settle()
        for p in h.store.list(Pod.KIND):
            h.autoscaler.observe(p.metadata.name, 0.05)
        h.autoscale()
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "as-0-grp")
        assert pcsg.spec.replicas == 1
        assert sorted(g.metadata.name for g in h.store.list("PodGang")) == ["as-0"]

    def test_as4_within_tolerance_no_scale(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.scaled_pcs())
        h.settle()
        for p in h.store.list(Pod.KIND):
            h.autoscaler.observe(p.metadata.name, 0.52)  # within 10% of 0.5
        h.autoscale()
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "as-0-grp")
        assert pcsg.spec.replicas == 2

    def test_as5_no_metrics_no_scale(self):
        """Missing metrics must never drive scale-down (review finding)."""
        h = Harness(nodes=make_nodes(16))
        h.apply(self.scaled_pcs())
        h.settle()
        h.autoscale()  # no observe() calls at all
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "as-0-grp")
        assert pcsg.spec.replicas == 2


class TestRR_ReservationReuse:
    """Reservation reuse (podgang.go:66-72 — the reference declares
    ReuseReservationRef but never consumes it; grove_tpu sets AND honors
    it): updates and gang rebuilds return pods to their prior nodes when
    capacity allows, minimizing topology churn."""

    def one_cpu_nodes(self, n):
        return make_nodes(n, racks_per_block=2, hosts_per_rack=4,
                          allocatable={"cpu": 1.0, "memory": 8.0, "tpu": 0.0})

    def placements(self, h):
        return {p.metadata.name: p.node_name for p in h.store.list(Pod.KIND)}

    def test_rr1_update_replacements_return_to_prior_nodes(self):
        h = Harness(nodes=self.one_cpu_nodes(8))
        # confine initial placement to the high nodes, then open the low
        # ones: naive re-placement of replacements would prefer fresh
        # low-index nodes, so staying put proves the reuse path
        for i in range(4):
            h.cluster.cordon(f"node-{i}")
        h.apply(simple_pcs(cliques=[clique("w", replicas=3, cpu=1.0)]))
        h.settle()
        before = self.placements(h)
        assert all(before.values())
        for i in range(4):
            h.cluster.uncordon(f"node-{i}")
        h.settle()
        bump_image(h)
        h.settle()
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        after = self.placements(h)
        assert after == before, f"{before} -> {after}"
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.rolling_update_progress.completed

    def test_rr2_gang_rebuild_returns_to_reserved_nodes(self):
        from grove_tpu.api.podgang import PodGang

        h = Harness(nodes=self.one_cpu_nodes(8))
        for i in range(4):
            h.cluster.cordon(f"node-{i}")  # rack 0 off: placement in rack 1
        pcs = simple_pcs(cliques=[clique("w", replicas=2, cpu=1.0)])
        pcs.spec.template.termination_delay = 60.0
        h.apply(pcs)
        h.settle()
        gang = h.store.get(PodGang.KIND, "default", "simple1-0")
        ref = gang.spec.reuse_reservation_ref
        assert ref is not None and ref.name == "simple1-0"
        before = self.placements(h)
        for i in range(4):
            h.cluster.uncordon(f"node-{i}")
        h.settle()
        # crash -> breach -> gang termination -> full replica rebuild
        h.kubelet.crash_pod("default", "simple1-0-w-0")
        h.settle()
        h.advance(61.0)
        h.settle()
        after = self.placements(h)
        assert set(after) == set(before)
        assert after == before, (
            f"rebuilt gang abandoned its reservation: {before} -> {after}"
        )
        assert all(p.status.ready for p in h.store.list(Pod.KIND))

    def test_rr3_reservation_never_inverts_priority(self):
        """Advisor r3: a higher-priority gang WITHOUT a reservation is
        SKIPPED (not a stop sign) by the reserve pre-pass — but a
        reservation only commits while the remaining capacity still
        covers every skipped higher-priority gang's demand, so reuse can
        never starve them."""
        import numpy as np

        from grove_tpu.api.meta import NamespacedName, ObjectMeta
        from grove_tpu.api.podgang import PodGang, PodGangSpec
        from grove_tpu.solver import SolverGang

        def sg(name, priority):
            return SolverGang(
                name=name, namespace="default",
                demand=np.asarray([[1.0, 0.0, 0.0]], np.float32),
                pod_names=[f"{name}-p0"],
                group_ids=np.zeros(1, np.int32), group_names=["g0"],
                group_required_level=np.array([-1], np.int32),
                group_preferred_level=np.array([-1], np.int32),
                priority=priority,
            )

        def pg(h, name, ref=None):
            g = PodGang(metadata=ObjectMeta(name=name, namespace="default"))
            if ref:
                g.spec = PodGangSpec(reuse_reservation_ref=NamespacedName(
                    namespace="default", name=ref))
            return h.store.create(g)

        # AMPLE capacity: the skipped hi gang cannot be starved, so the
        # reserved lo gang binds back onto node-0 (reuse no longer
        # disabled by one unreserved higher-priority gang)
        h = Harness(nodes=self.one_cpu_nodes(4))
        sched = h.scheduler
        snapshot = h.cluster.topology_snapshot()
        free = snapshot.free.copy()
        sched._reservations[("default", "lo")] = ("node-0",)
        by_name = {
            "hi": pg(h, "hi"), "lo": pg(h, "lo", ref="lo"),
        }
        remaining = sched._try_reserved(
            [sg("lo", 0.0), sg("hi", 10.0)], by_name, snapshot, free
        )
        assert [g.name for g in remaining] == ["hi"]
        n0 = snapshot.node_index["node-0"]
        assert free[n0, 0] == 0.0, "lo reserve-placed on node-0"

        # SCARCE capacity (1 node): committing lo would starve hi -> lo
        # must fall through to the priority-ordered general solve
        h2 = Harness(nodes=self.one_cpu_nodes(1))
        sched2 = h2.scheduler
        snap2 = h2.cluster.topology_snapshot()
        free2 = snap2.free.copy()
        before2 = free2.copy()
        sched2._reservations[("default", "lo")] = ("node-0",)
        by_name2 = {
            "hi": pg(h2, "hi"), "lo": pg(h2, "lo", ref="lo"),
        }
        remaining2 = sched2._try_reserved(
            [sg("lo", 0.0), sg("hi", 10.0)], by_name2, snap2, free2
        )
        assert sorted(g.name for g in remaining2) == ["hi", "lo"]
        np.testing.assert_allclose(free2, before2)

    def test_rr4_reservation_guard_sees_fragmentation(self):
        """The no-inversion guard is an EXACT trial placement, not
        aggregate math: a reserved gang whose commit would take the only
        node a skipped higher-priority gang fits on must fall through to
        the general solve, even when aggregate capacity looks ample."""
        import numpy as np

        from grove_tpu.api.meta import NamespacedName, ObjectMeta
        from grove_tpu.api.podgang import PodGang, PodGangSpec
        from grove_tpu.api.types import Node
        from grove_tpu.solver import SolverGang

        # node-0 has 4 cpu; nodes 1-3 have 1 cpu (aggregate 7)
        nodes = []
        for i, cpu in enumerate((4.0, 1.0, 1.0, 1.0)):
            nodes.append(Node(
                metadata=ObjectMeta(name=f"node-{i}"),
                allocatable={"cpu": cpu, "memory": 8.0, "tpu": 0.0},
            ))
        h = Harness(nodes=nodes)
        sched = h.scheduler
        snapshot = h.cluster.topology_snapshot()
        free = snapshot.free.copy()
        before = free.copy()

        def sg(name, priority, cpu, pods=1):
            return SolverGang(
                name=name, namespace="default",
                demand=np.tile(
                    np.asarray([[cpu, 0.0, 0.0]], np.float32), (pods, 1)
                ),
                pod_names=[f"{name}-p{i}" for i in range(pods)],
                group_ids=np.zeros(pods, np.int32), group_names=["g0"],
                group_required_level=np.array([-1], np.int32),
                group_preferred_level=np.array([-1], np.int32),
                priority=priority,
            )

        def pg(name, ref=None):
            g = PodGang(metadata=ObjectMeta(name=name, namespace="default"))
            if ref:
                g.spec = PodGangSpec(reuse_reservation_ref=NamespacedName(
                    namespace="default", name=ref))
            return h.store.create(g)

        # lo's reservation (4 pods on node-0) would consume the ONLY node
        # hi's 3-cpu pod fits on; aggregate 7 - 4 >= 3 lies
        sched._reservations[("default", "lo")] = ("node-0",)
        by_name = {
            "hi": pg("hi"),
            "lo": pg("lo", ref="lo"),
        }
        remaining = sched._try_reserved(
            [sg("hi", 10.0, cpu=3.0), sg("lo", 0.0, cpu=1.0, pods=4)],
            by_name, snapshot, free,
        )
        assert sorted(g.name for g in remaining) == ["hi", "lo"]
        np.testing.assert_allclose(free, before)


class TestOR_OperatorRestart:
    """Checkpoint/resume analog (SURVEY §5): all orchestration progress
    lives in CR status, so a fresh operator process (new Harness over the
    same store) resumes mid-flight work — the reference's operator
    restarts rely on exactly this (rolling-update progress in status,
    podcliqueset.go:96-118; breach clocks in condition timestamps)."""

    def restart(self, h):
        """A brand-new manager/controllers/scheduler over the same cluster
        state — the operator process replaced mid-flight."""
        return Harness(cluster=h.cluster)

    def test_restart_mid_rolling_update_resumes(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(simple_pcs(name="r", replicas=2,
                           cliques=[clique("w", replicas=2, cpu=1.0)]))
        h.settle()
        bump_image(h, "r")
        # drive partway: first replica mid-update
        for _ in range(6):
            h.manager.run_once()
            h.kubelet.tick()
        pcs = h.store.get(PodCliqueSet.KIND, "default", "r")
        prog = pcs.status.rolling_update_progress
        assert prog is not None and not prog.completed
        h2 = self.restart(h)
        h2.settle()
        pcs = h2.store.get(PodCliqueSet.KIND, "default", "r")
        assert pcs.status.rolling_update_progress.completed
        target = stable_hash(pcs.spec.template.cliques[0].spec.pod_spec)
        assert set(pod_hashes(h2).values()) == {target}
        assert all(p.status.ready for p in h2.store.list(Pod.KIND))

    def test_restart_mid_termination_delay_keeps_breach_clock(self):
        h = Harness(nodes=make_nodes(8))
        pcs = simple_pcs(cliques=[clique("w", replicas=2, cpu=1.0)])
        pcs.spec.template.termination_delay = 60.0
        h.apply(pcs)
        h.settle()
        h.kubelet.crash_pod("default", "simple1-0-w-0")
        h.settle()
        h.advance(40.0)  # 40s into the 60s delay
        old_uid = h.store.get(Pod.KIND, "default",
                              "simple1-0-w-0").metadata.uid
        h2 = self.restart(h)
        # the breach clock came from the persisted condition timestamp,
        # not operator memory: 21 more seconds completes the 60s delay
        # (the kubelet, like the node fleet, is cluster state and survives
        # the operator restart by construction)
        h2.settle()
        assert h2.store.get(Pod.KIND, "default",
                            "simple1-0-w-0").metadata.uid == old_uid
        h2.advance(21.0)
        h2.settle()
        new_pod = h2.store.get(Pod.KIND, "default", "simple1-0-w-0")
        assert new_pod is not None and new_pod.metadata.uid != old_uid
        assert all(p.status.ready for p in h2.store.list(Pod.KIND))

    def test_restart_with_pending_backlog_schedules(self):
        h = Harness(nodes=make_nodes(2, allocatable={"cpu": 1.0,
                                                     "memory": 8.0,
                                                     "tpu": 0.0}))
        h.cluster.cordon("node-0")
        h.cluster.cordon("node-1")
        h.apply(simple_pcs(cliques=[clique("w", replicas=2, cpu=1.0)]))
        h.settle()
        assert all(not p.node_name for p in h.store.list(Pod.KIND))
        h2 = self.restart(h)
        h2.cluster.uncordon("node-0")
        h2.cluster.uncordon("node-1")
        h2.settle()
        assert all(p.node_name for p in h2.store.list(Pod.KIND))

    def test_restart_after_event_compaction_relists(self):
        """A fresh manager whose cursor fell behind the compaction
        horizon recovers via the informer relist path (410 Gone analog):
        synthetic Added events rediscover every object and the mid-flight
        update completes."""
        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs(name="r", cliques=[clique("w", replicas=3,
                                                     cpu=1.0)]))
        h.settle()
        bump_image(h, "r")
        for _ in range(4):
            h.manager.run_once()
            h.kubelet.tick()
        h.manager.compact_processed_events()  # history gone mid-flight
        h2 = self.restart(h)  # new cursor=0 < compaction horizon
        h2.settle()
        pcs = h2.store.get(PodCliqueSet.KIND, "default", "r")
        assert pcs.status.rolling_update_progress.completed
        target = stable_hash(pcs.spec.template.cliques[0].spec.pod_spec)
        assert set(pod_hashes(h2).values()) == {target}
        assert all(p.status.ready for p in h2.store.list(Pod.KIND))
