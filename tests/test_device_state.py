"""Device-resident free-state tests (solver/engine.py _sync_free et al).

The delta upload path is an optimization of WHERE the free matrix lives,
never of what is computed: after any seeded sequence of declared
mutations the resident device buffer must decode bit-equal to a fresh
full encode, the O(1) epoch guard must make exactly the adopt/reject
decisions the old O(N*R) content compare made, and a mutation that
bypasses the note_free_rows superset contract must fail loudly under
solver.device_state_verify — never be adopted silently. The chaos class
asserts the end-to-end version: identical pod placements between the
delta and full engines under seeded node_flap / domain_outage storms.
"""

import dataclasses
import io

import numpy as np
import pytest

from grove_tpu.cluster import Cluster, make_nodes
from grove_tpu.controller import Harness
from grove_tpu.observability import MetricsRegistry
from grove_tpu.observability.tracing import Tracer
from grove_tpu.solver import PlacementEngine

from test_cluster import make_pod
from test_solver import cluster, gang


def flip_schedulable(snap, rows):
    """A rebuild-shaped snapshot: same statics, `rows` toggled."""
    sched = snap.schedulable.copy()
    sched[list(rows)] = ~sched[list(rows)]
    return dataclasses.replace(snap, schedulable=sched)


def decoded_state(eng):
    """Host view of the resident device buffer (unpadded rows)."""
    return np.asarray(eng._state.dev)[: eng.snapshot.num_nodes]


class TestStateSync:
    def test_seeded_deltas_decode_bit_equal_to_full_encode(self):
        """Property: after K seeded random rounds of declared row
        mutations, unknown-scope declarations, and schedulable flips via
        rebind, the device buffer always decodes bit-equal to a fresh
        full encode of the current free matrix."""
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=16.0)
        eng = PlacementEngine(snap)
        rng = np.random.default_rng(11)
        n = snap.num_nodes
        free = snap.free.copy()
        eng._sync_free(free)
        epochs = [eng._state.epoch]
        for k in range(40):
            kind = rng.integers(4)
            if kind == 0:  # declared row churn (bind/unbind shape)
                rows = rng.choice(n, size=int(rng.integers(1, 5)),
                                  replace=False)
                free[rows] *= rng.uniform(
                    0.3, 1.0, size=(rows.size, 1)
                ).astype(np.float32)
                eng.note_free_rows(rows.tolist())
            elif kind == 1:  # unknown-scope declaration (full diff)
                row = int(rng.integers(n))
                free[row] = snap.capacity[row]
                eng.note_free_rows(None)
            elif kind == 2:  # schedulable flip riding rebind's delta
                rows = rng.choice(n, size=2, replace=False)
                snap2 = flip_schedulable(eng.snapshot, rows)
                assert eng.rebind(snap2)
            # kind == 3: no mutation at all (pure hit round)
            eng._sync_free(free)
            masked = eng._masked_free(free)
            np.testing.assert_array_equal(eng._state.mirror, masked)
            np.testing.assert_array_equal(decoded_state(eng), masked)
            epochs.append(eng._state.epoch)
        # epochs are monotonic and moved only on content change
        assert epochs == sorted(epochs)
        st = eng._state
        assert st.hits > 0 and st.delta_uploads > 0 and st.full_uploads >= 1

    def test_unchanged_content_is_a_hit_not_an_upload(self):
        snap = cluster()
        eng = PlacementEngine(snap)
        free = snap.free.copy()
        e0 = eng._sync_free(free)
        e1 = eng._sync_free(snap.free.copy())  # same content, other array
        assert e0 == e1
        assert eng._state.hits == 1
        assert eng._state.full_uploads == 1
        assert eng._state.delta_uploads == 0

    def test_bulk_divergence_falls_back_to_full_upload(self):
        snap = cluster(blocks=4, racks=4, hosts=8, cpu=16.0)  # 128 nodes
        eng = PlacementEngine(snap)
        assert snap.num_nodes > eng._delta_rows_max
        free = snap.free.copy()
        eng._sync_free(free)
        free *= 0.5  # every row moved: a delta would ship the matrix
        eng.note_free_rows(range(snap.num_nodes))
        eng._sync_free(free)
        assert eng._state.full_uploads == 2
        assert eng._state.delta_uploads == 0
        np.testing.assert_array_equal(
            decoded_state(eng), eng._masked_free(free)
        )

    def test_undeclared_mutation_raises_under_verify(self):
        """A row mutated OUTSIDE a row-scoped declaration is the breach:
        the sync only re-reads the declared rows, so the mirror goes
        stale and the verify tripwire must fire. (With no declaration at
        all the sync runs the full diff and stays correct by itself.)"""
        snap = cluster()
        eng = PlacementEngine(snap, state_verify=True)
        free = snap.free.copy()
        eng._sync_free(free)
        free[1] *= 0.5
        eng.note_free_rows((1,))  # declared: fine
        free[3] *= 0.5  # contract breach: mutated, never declared
        with pytest.raises(RuntimeError, match="not declared"):
            eng._sync_free(free)

    def test_invalidate_forces_full_reupload_keeps_epoch_monotonic(self):
        snap = cluster()
        eng = PlacementEngine(snap)
        free = snap.free.copy()
        e0 = eng._sync_free(free)
        eng.invalidate_device_state()
        e1 = eng._sync_free(free)
        assert e1 > e0  # never reuses an epoch a dispatch may hold
        assert eng._state.full_uploads == 2

    def test_out_of_range_declarations_are_ignored(self):
        snap = cluster()
        eng = PlacementEngine(snap)
        free = snap.free.copy()
        eng._sync_free(free)
        eng.note_free_rows([-3, snap.num_nodes + 7])
        e = eng._sync_free(free)
        assert e == 1 and eng._state.hits == 1


class TestEpochGuard:
    def test_unchanged_dispatch_adopted_via_epoch(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [gang("a", pods=2, cpu=2.0), gang("b", pods=4, cpu=6.0)]
        eng = PlacementEngine(snap)
        fresh = eng.solve(gangs)
        handle = eng.dispatch(gangs, free=snap.free.copy())
        assert handle.free0 is None  # the cache drops the O(N*R) payload
        res = eng.solve(gangs, free=snap.free.copy(), dispatch=handle)
        assert res.stats.get("dispatch_overlap") == 1.0
        for name in fresh.placed:
            np.testing.assert_array_equal(
                res.placed[name].node_indices,
                fresh.placed[name].node_indices,
            )

    def test_declared_mutation_bumps_epoch_and_rejects_dispatch(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [gang("a", pods=2, cpu=2.0)]
        eng = PlacementEngine(snap)
        handle = eng.dispatch(gangs, free=snap.free.copy())
        free = snap.free.copy()
        free[0] -= 1.0
        eng.note_free_rows((0,))
        res = eng.solve(gangs, free=free, dispatch=handle)
        assert "dispatch_overlap" not in res.stats
        assert res.num_placed == 1

    def test_epoch_guard_decides_like_the_content_compare(self):
        """Under state_verify the engine re-runs the O(N*R) compare next
        to every epoch decision and raises on disagreement — both the
        adopt and the reject branch must pass it."""
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [gang("a", pods=2, cpu=2.0)]
        eng = PlacementEngine(snap, state_verify=True)
        handle = eng.dispatch(gangs, free=snap.free.copy())
        assert handle.free0 is not None  # verify retains the payload
        res = eng.solve(gangs, free=snap.free.copy(), dispatch=handle)
        assert res.stats.get("dispatch_overlap") == 1.0
        handle = eng.dispatch(gangs, free=snap.free.copy())
        free = snap.free.copy()
        free[1] -= 2.0
        eng.note_free_rows((1,))
        res = eng.solve(gangs, free=free, dispatch=handle)
        assert "dispatch_overlap" not in res.stats

    def test_cache_off_keeps_legacy_content_compare(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [gang("a", pods=2, cpu=2.0)]
        eng = PlacementEngine(snap, state_cache=False)
        handle = eng.dispatch(gangs, free=snap.free.copy())
        assert handle.free0 is not None
        res = eng.solve(gangs, free=snap.free.copy(), dispatch=handle)
        assert res.stats.get("dispatch_overlap") == 1.0
        handle = eng.dispatch(gangs, free=snap.free.copy())
        free = snap.free.copy()
        free[0] -= 1.0  # no declaration needed: content compare
        res = eng.solve(gangs, free=free, dispatch=handle)
        assert "dispatch_overlap" not in res.stats

    def test_rebind_between_dispatch_and_solve_rejects_stale_mask(self):
        """Cordoning a capacity-bearing node between dispatch and solve
        changes the MASKED content while the raw matrix is untouched —
        both regimes must refuse the stale-mask scores (a raw content
        compare would adopt them)."""
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [gang("a", pods=2, cpu=2.0)]
        for kwargs in ({"state_cache": False}, {"state_verify": True}):
            eng = PlacementEngine(snap, **kwargs)
            handle = eng.dispatch(gangs, free=snap.free.copy())
            snap2 = flip_schedulable(snap, [0])
            assert eng.rebind(snap2)
            # must neither adopt nor (verify regime) false-alarm a
            # note_free_rows breach: the epoch guard and the masked
            # content compare agree the dispatch is stale
            res = eng.solve(gangs, free=snap2.free.copy(), dispatch=handle)
            assert "dispatch_overlap" not in res.stats
            assert res.num_placed == 1
            used = np.concatenate(
                [p.node_indices for p in res.placed.values()]
            )
            assert 0 not in used

    def test_cache_off_adopted_dispatch_pays_one_upload(self):
        """With the cache off, the full H2D belongs to the device phase
        that actually runs: an adopted dispatch must not trigger a
        second, never-consumed upload in solve()."""
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [gang("a", pods=2, cpu=2.0)]
        eng = PlacementEngine(snap, state_cache=False)
        handle = eng.dispatch(gangs, free=snap.free.copy())
        res = eng.solve(gangs, free=snap.free.copy(), dispatch=handle)
        assert res.stats.get("dispatch_overlap") == 1.0
        assert eng._state.full_uploads == 1

    def test_cache_off_matches_cache_on_placements(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [
            gang("a", pods=2, cpu=2.0),
            gang("b", pods=4, cpu=6.0, required=1),
            gang("c", pods=3, cpu=3.0, preferred=2),
        ]
        on = PlacementEngine(snap).solve(gangs, free=snap.free.copy())
        off = PlacementEngine(snap, state_cache=False).solve(
            gangs, free=snap.free.copy()
        )
        assert set(on.placed) == set(off.placed)
        for name in on.placed:
            np.testing.assert_array_equal(
                on.placed[name].node_indices, off.placed[name].node_indices
            )


class TestRebind:
    def test_schedulable_flip_rides_the_delta_path(self):
        snap = cluster(blocks=2, racks=2, hosts=2, cpu=8.0)
        eng = PlacementEngine(snap, state_verify=True)
        eng._sync_free(snap.free.copy())
        full0 = eng._state.full_uploads
        snap2 = flip_schedulable(snap, [0])  # cordon-shaped rebuild
        assert eng.rebind(snap2)
        assert eng.snapshot is snap2
        eng._sync_free(snap2.free.copy())
        assert eng._state.full_uploads == full0  # no rebuild re-encode
        assert eng._state.delta_uploads == 1
        # the cordoned row is zeroed in the resident state
        assert (decoded_state(eng)[0] == 0.0).all()
        # and solves avoid it
        res = eng.solve([gang(f"g{i}", pods=2, cpu=8.0) for i in range(4)],
                        free=snap2.free.copy())
        used = np.concatenate(
            [p.node_indices for p in res.placed.values()]
        )
        assert 0 not in used

    def test_rebind_rejects_static_encoding_change(self):
        snap = cluster(blocks=2, racks=2, hosts=2)
        eng = PlacementEngine(snap)
        other = cluster(blocks=2, racks=2, hosts=4)  # node set differs
        assert not eng.rebind(other)
        cap = cluster(blocks=2, racks=2, hosts=2, cpu=16.0)  # capacity
        assert not eng.rebind(cap)


class TestClusterFreeJournal:
    def test_first_drain_is_unknown_then_tracks_rows(self):
        c = Cluster(nodes=make_nodes(4))
        snap = c.topology_snapshot()
        assert c.consume_free_dirty(snap) is None  # nobody consumed yet
        assert c.consume_free_dirty(snap) == []
        c.store.create(make_pod("p", node="node-2"))
        c.kubelet.run_to_quiesce()
        snap = c.topology_snapshot()
        assert c.consume_free_dirty(snap) == [2]
        assert c.consume_free_dirty(snap) == []

    def test_rebuild_past_compaction_resets_to_unknown(self):
        c = Cluster(nodes=make_nodes(4))
        snap = c.topology_snapshot()
        c.consume_free_dirty(snap)
        c.store.create(make_pod("p", node="node-1"))
        c.kubelet.run_to_quiesce()
        # compact past the usage cursor: incremental accounting must
        # rebuild, and per-row tracking is lost
        c.store.compact_events(c.store.last_seq + 1)
        snap = c.topology_snapshot()
        assert c.consume_free_dirty(snap) is None

    def test_snapshot_free_epoch_moves_with_usage(self):
        c = Cluster(nodes=make_nodes(4))
        e0 = c.topology_snapshot().free_epoch
        assert c.topology_snapshot().free_epoch == e0  # no usage motion
        c.store.create(make_pod("p", node="node-0"))
        c.kubelet.run_to_quiesce()
        assert c.topology_snapshot().free_epoch > e0


class TestObservability:
    def test_upload_metrics_span_and_debug_summary(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        registry = MetricsRegistry()
        tracer = Tracer()
        eng = PlacementEngine(snap, metrics=registry, tracer=tracer)
        gangs = [gang("a", pods=2, cpu=2.0)]
        free = snap.free.copy()
        eng.solve(gangs, free=free)  # full upload
        free2 = snap.free.copy()
        free2[5] -= 1.0
        eng.note_free_rows((5,))
        eng.solve(gangs, free=free2)  # delta upload
        ups = registry.counter("grove_solver_state_uploads_total")
        assert ups.value(kind="full") == 1.0
        assert ups.value(kind="delta") >= 1.0
        tb = registry.counter("grove_solver_transport_bytes_total")
        assert tb.value(kind="state_full") > 0
        assert tb.value(kind="state_delta") > 0
        assert tb.value(kind="results") > 0
        kinds = {
            s.attrs.get("kind") for s in tracer.finished
            if s.name == "engine.delta_apply"
        }
        assert {"full", "delta"} <= kinds
        ds = eng.debug_summary()["device_state"]
        assert ds["cache_enabled"] and ds["resident"]
        assert ds["full_uploads"] == 1 and ds["delta_uploads"] >= 1
        assert ds["epoch"] >= 2 and ds["checksum"] is not None

    def test_cache_off_summary_reports_disabled(self):
        snap = cluster()
        ds = PlacementEngine(snap, state_cache=False).debug_summary()[
            "device_state"
        ]
        assert not ds["cache_enabled"]
        assert ds["checksum"] is None


class TestSchedulerContract:
    """End-to-end superset-contract enforcement: a full control-plane run
    under solver.device_state_verify must never trip the O(N*R) debug
    compare — every free mutation (bind commits, reservation reuse,
    vacated-hint singles, node lifecycle) reaches note_free_rows."""

    CFG = {"solver": {"device_state_verify": True}}

    def test_bind_cordon_fail_recover_under_verify(self):
        from test_e2e_basic import clique, simple_pcs

        h = Harness(nodes=make_nodes(16), config=self.CFG)
        h.apply(simple_pcs(cliques=[clique("w", replicas=6)], replicas=2))
        h.settle()
        from grove_tpu.api.types import Pod

        bound = [p for p in h.store.scan(Pod.KIND) if p.node_name]
        assert len(bound) == 12
        victim = bound[0].node_name
        h.cluster.cordon(victim)
        h.settle()
        h.cluster.fail_node(victim)
        h.clock.advance(120.0)
        h.settle()
        h.cluster.recover_node(victim)
        h.cluster.uncordon(victim)
        h.settle()
        # repaired: every pod bound again, no verify RuntimeError raised
        assert all(p.node_name for p in h.store.scan(Pod.KIND))

    def test_scale_and_delete_under_verify(self):
        from test_e2e_basic import clique, simple_pcs

        h = Harness(nodes=make_nodes(16), config=self.CFG)
        pcs = simple_pcs(cliques=[clique("w", replicas=4)], replicas=1)
        h.apply(pcs)
        h.settle()
        obj = h.store.get(pcs.KIND, "default", pcs.metadata.name)
        obj.spec.replicas = 3
        h.store.update(obj)
        h.settle()
        h.store.delete(pcs.KIND, "default", pcs.metadata.name)
        h.settle()
        from grove_tpu.api.types import Pod

        assert not list(h.store.scan(Pod.KIND))


class TestKwargGating:
    def test_partial_capability_engine_gets_only_accepted_kwargs(self):
        """An engine naming state_cache but NOT state_verify (no
        **kwargs) must be constructed with only the knob it accepts —
        each capability kwarg is gated individually."""
        from test_e2e_basic import clique, simple_pcs

        class PartialEngine(PlacementEngine):
            def __init__(self, snapshot, top_k=8, commit_chunk=32,
                         bucket_min=8, native_repair=True, metrics=None,
                         state_cache=True):
                super().__init__(
                    snapshot, top_k=top_k, commit_chunk=commit_chunk,
                    bucket_min=bucket_min, native_repair=native_repair,
                    metrics=metrics, state_cache=state_cache,
                )

        h = Harness(
            nodes=make_nodes(8),
            engine_cls=PartialEngine,
            config={"solver": {"device_state_verify": True}},
        )
        h.apply(simple_pcs(cliques=[clique("w", replicas=4)], replicas=1))
        h.settle()
        from grove_tpu.api.types import Pod

        assert all(p.node_name for p in h.store.scan(Pod.KIND))


def _full_reference(snap, gangs, free=None, fairness=None):
    """Fresh pre-PR7 reference solve: cache off, split dispatches, no
    incremental — the semantics every fast path must reproduce bitwise."""
    eng = PlacementEngine(snap, state_cache=False, fused=False,
                          incremental=False)
    return eng.solve(gangs, free=free, fairness=fairness)


def assert_same_placements(a, b):
    assert sorted(a.placed) == sorted(b.placed)
    for name in a.placed:
        np.testing.assert_array_equal(
            a.placed[name].node_indices, b.placed[name].node_indices
        )
    assert a.unplaced == b.unplaced


class TestFusedStaging:
    """The fused path stages _sync_free deltas into the next device
    launch instead of dispatching a standalone scatter — one program
    launch per warm solve, with the mirror/epoch committing at sync time
    and the device buffer catching up at the launch."""

    def test_warm_solve_is_one_fused_dispatch(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        eng = PlacementEngine(snap, state_verify=True, incremental=False)
        gangs = [gang(f"g{i}", pods=2, cpu=2.0) for i in range(4)]
        eng.solve(gangs, free=snap.free.copy())
        assert eng._dispatches == {"fused": 1, "split": 0,
                                   "incremental": 0, "whatif": 0}

    def test_staged_delta_rides_the_fused_launch(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        eng = PlacementEngine(snap, state_verify=True, incremental=False)
        gangs = [gang(f"g{i}", pods=2, cpu=2.0) for i in range(4)]
        eng.solve(gangs, free=snap.free.copy())
        free = snap.free.copy()
        free[2] *= 0.5
        eng.note_free_rows([2])
        res = eng.solve(gangs, free=free.copy())
        assert res.num_placed == 4
        # the delta was counted as an upload but rode the fused launch:
        # no standalone scatter (= no split dispatch), nothing staged
        # left behind, and the resident buffer caught up exactly
        assert eng._state.delta_uploads == 1
        assert eng._dispatches == {"fused": 2, "split": 0,
                                   "incremental": 0, "whatif": 0}
        assert eng._staged is None
        np.testing.assert_array_equal(
            decoded_state(eng), eng._masked_free(free)
        )

    def test_staged_rows_merge_latest_and_full_upload_supersedes(self):
        snap = cluster(blocks=4, racks=4, hosts=8, cpu=16.0)  # 128 nodes
        eng = PlacementEngine(snap)
        assert snap.num_nodes > eng._delta_rows_max
        free = snap.free.copy()
        eng._sync_free(free)
        free[3] *= 0.5
        eng.note_free_rows([3])
        eng._sync_free(free, defer=True)
        assert eng._staged is not None and 3 in eng._staged
        free[3] *= 0.5  # re-stage the same row: latest values win
        eng.note_free_rows([3])
        eng._sync_free(free, defer=True)
        np.testing.assert_array_equal(
            eng._staged[3], eng._masked_free(free)[3]
        )
        # bulk divergence forces a full upload, which supersedes the
        # staged rows (re-scattering them would write stale values)
        free *= 0.25
        eng.note_free_rows(range(snap.num_nodes))
        eng._sync_free(free, defer=True)
        assert eng._staged is None
        np.testing.assert_array_equal(
            decoded_state(eng), eng._masked_free(free)
        )

    def test_verify_accounts_for_staged_rows(self):
        """With rows staged (device buffer lagging), the verify tripwire
        must not false-alarm — and must still fire on a genuine breach."""
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        eng = PlacementEngine(snap, state_verify=True)
        free = snap.free.copy()
        eng._sync_free(free)
        free[1] *= 0.5
        eng.note_free_rows([1])
        eng._sync_free(free, defer=True)  # staged; verify ran clean
        # a row-scoped declaration that EXCLUDES a mutated row is the
        # breach (with no declaration the full diff stays correct)
        free[4] *= 0.5
        eng.note_free_rows([2])
        with pytest.raises(RuntimeError, match="not declared"):
            eng._sync_free(free, defer=True)

    def test_split_engine_keeps_standalone_scatter(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        eng = PlacementEngine(snap, fused=False)
        gangs = [gang("a", pods=2, cpu=2.0)]
        eng.solve(gangs, free=snap.free.copy())
        free = snap.free.copy()
        free[2] *= 0.5
        eng.note_free_rows([2])
        eng.solve(gangs, free=free.copy())
        # split regime: score launches + the standalone delta scatter
        assert eng._dispatches["fused"] == 0
        assert eng._dispatches["split"] == 3  # 2 scores + 1 scatter


class TestIncremental:
    """Dirty-row re-solve tiers: zero-dispatch reuse for an unchanged
    backlog, O(dirty) re-score for a churn tick, full-solve fallback on
    any invalidation — all bit-equal to the full reference."""

    def _armed(self, n_gangs=6):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        eng = PlacementEngine(snap, state_verify=True)
        gangs = [gang(f"g{i}", pods=2, cpu=2.0) for i in range(n_gangs)]
        first = eng.solve(gangs, free=snap.free.copy())
        assert first.num_placed == n_gangs
        return snap, eng, gangs

    def test_identical_retry_tick_reuses_without_dispatch(self):
        snap, eng, gangs = self._armed()
        before = dict(eng._dispatches)
        res = eng.solve(gangs, free=snap.free.copy())
        assert res.stats.get("reused") == 1.0
        assert eng._dispatches == before  # zero device launches
        assert eng._inc_reuse_hits == 1
        assert_same_placements(res, _full_reference(snap, gangs))

    def test_dirty_tick_rescores_only_dirty_rows_bit_equal(self):
        snap, eng, gangs = self._armed()
        gangs[1] = gang("h1", pods=2, cpu=3.0)
        gangs[4] = gang("h4", pods=2, cpu=1.0)
        free = snap.free.copy()
        res = eng.solve(gangs, free=free)
        assert res.stats.get("incremental") == 1.0
        assert res.stats.get("incremental_rows") == 2.0
        assert eng._dispatches["incremental"] == 1
        ref_free = snap.free.copy()
        ref = _full_reference(snap, gangs, free=ref_free)
        assert_same_placements(res, ref)
        np.testing.assert_array_equal(free, ref_free)

    def test_removed_gangs_ride_the_permutation(self):
        snap, eng, gangs = self._armed()
        subset = gangs[:3] + gangs[4:]  # one gang left the backlog
        res = eng.solve(subset, free=snap.free.copy())
        assert res.stats.get("incremental") == 1.0
        assert res.stats.get("incremental_rows") == 0.0
        assert_same_placements(res, _full_reference(snap, subset))

    def test_fairness_change_dirties_the_gang(self):
        snap, eng, gangs = self._armed()
        fair = {"g2": 0.75}
        res = eng.solve(gangs, free=snap.free.copy(), fairness=fair)
        assert res.stats.get("incremental") == 1.0
        assert res.stats.get("incremental_rows") == 1.0
        assert_same_placements(
            res, _full_reference(snap, gangs, fairness=fair)
        )

    def test_epoch_divergence_falls_back_then_resumes(self):
        snap, eng, gangs = self._armed()
        free = snap.free.copy()
        free[2] *= 0.5
        eng.note_free_rows([2])
        res = eng.solve(gangs, free=free.copy())
        assert "incremental" not in res.stats
        assert "reused" not in res.stats
        assert_same_placements(
            res, _full_reference(snap, gangs, free=free.copy())
        )
        # the full solve re-armed the cache on the NEW content: a dirty
        # tick against it rides the incremental path again
        gangs[0] = gang("h0", pods=2, cpu=2.0)
        res2 = eng.solve(gangs, free=free.copy())
        assert res2.stats.get("incremental") == 1.0
        assert_same_placements(
            res2, _full_reference(snap, gangs, free=free.copy())
        )

    def test_mostly_dirty_backlog_takes_the_full_path(self):
        snap, eng, gangs = self._armed()
        fresh = [gang(f"x{i}", pods=2, cpu=2.0) for i in range(6)]
        res = eng.solve(fresh, free=snap.free.copy())
        assert "incremental" not in res.stats
        assert_same_placements(res, _full_reference(snap, fresh))

    def test_dispatch_adoption_of_incremental_scores(self):
        snap, eng, gangs = self._armed()
        gangs[2] = gang("h2", pods=2, cpu=2.5)
        handle = eng.dispatch(gangs, free=snap.free.copy())
        assert handle.path == "incremental" and handle.rows == 1
        res = eng.solve(gangs, free=snap.free.copy(), dispatch=handle)
        assert res.stats.get("dispatch_overlap") == 1.0
        assert res.stats.get("incremental") == 1.0
        assert_same_placements(res, _full_reference(snap, gangs))

    def test_invalidate_clears_the_value_cache(self):
        snap, eng, gangs = self._armed()
        eng.invalidate_device_state()
        assert eng._inc is None
        res = eng.solve(gangs, free=snap.free.copy())
        assert "incremental" not in res.stats and "reused" not in res.stats
        assert_same_placements(res, _full_reference(snap, gangs))

    def test_metrics_and_debug_summary(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        registry = MetricsRegistry()
        eng = PlacementEngine(snap, metrics=registry)
        gangs = [gang(f"g{i}", pods=2, cpu=2.0) for i in range(5)]
        eng.solve(gangs, free=snap.free.copy())
        eng.solve(gangs, free=snap.free.copy())  # reuse tier
        gangs[0] = gang("h0", pods=2, cpu=2.0)
        eng.solve(gangs, free=snap.free.copy())  # incremental tier
        disp = registry.counter("grove_solver_dispatches_total")
        assert disp.value(kind="fused") == 1.0
        assert disp.value(kind="incremental") == 1.0
        rows = registry.counter("grove_solver_incremental_rows_total")
        assert rows.total() == 1.0
        ds = eng.debug_summary()["device_state"]
        assert ds["fused"] and ds["incremental"]
        assert ds["dispatches"] == {"fused": 1, "split": 0,
                                    "incremental": 1, "whatif": 0}
        assert ds["incremental_rows"] == 1
        assert ds["reuse_hits"] == 1
        assert ds["value_cache_resident"]


class TestIncrementalChaosFallback:
    """Node faults between dirty ticks — fail_node/recover_node/cordon
    all land as rebind()s or full rebuilds on the engine — must force
    the FULL-solve fallback, never a stale re-score against the old
    schedulable mask."""

    def test_cordon_shaped_rebind_between_dirty_ticks(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        eng = PlacementEngine(snap, state_verify=True)
        gangs = [gang(f"g{i}", pods=2, cpu=2.0) for i in range(6)]
        eng.solve(gangs, free=snap.free.copy())
        gangs[0] = gang("h0", pods=2, cpu=2.0)
        res = eng.solve(gangs, free=snap.free.copy())
        assert res.stats.get("incremental") == 1.0
        # node 0 cordons between ticks: the rebind must clear the value
        # cache (cached rows embed the old mask)
        snap2 = flip_schedulable(eng.snapshot, [0])
        assert eng.rebind(snap2)
        assert eng._inc is None
        gangs[1] = gang("h1", pods=2, cpu=2.0)
        res2 = eng.solve(gangs, free=snap2.free.copy())
        assert "incremental" not in res2.stats
        assert "reused" not in res2.stats
        used = np.concatenate(
            [p.node_indices for p in res2.placed.values()]
        )
        assert 0 not in used  # a stale re-score could land here
        assert_same_placements(
            res2,
            _full_reference(snap2, gangs, free=snap2.free.copy()),
        )
        # uncordon rides rebind the same way, and the tier resumes
        # after one full solve re-arms the cache on the new mask
        snap3 = flip_schedulable(eng.snapshot, [0])
        assert eng.rebind(snap3)
        eng.solve(gangs, free=snap3.free.copy())
        gangs[2] = gang("h2", pods=2, cpu=2.0)
        res3 = eng.solve(gangs, free=snap3.free.copy())
        assert res3.stats.get("incremental") == 1.0
        assert_same_placements(
            res3,
            _full_reference(snap3, gangs, free=snap3.free.copy()),
        )

    def test_fail_recover_cordon_between_ticks_under_verify(self):
        """Full control-plane version: dirty ticks (new workloads) are
        interleaved with fail_node -> recover_node -> cordon/uncordon;
        with the incremental engine + verify tripwire armed (the
        deployed default config), every gang must still repair onto live
        capacity and no stale-state RuntimeError may fire."""
        from test_e2e_basic import clique, simple_pcs

        h = Harness(
            nodes=make_nodes(16),
            config={"solver": {"device_state_verify": True}},
        )
        h.apply(simple_pcs(cliques=[clique("w", replicas=4)], replicas=2))
        h.settle()
        from grove_tpu.api.types import Pod

        bound = [p for p in h.store.scan(Pod.KIND) if p.node_name]
        assert len(bound) == 8
        victim = bound[0].node_name
        h.cluster.fail_node(victim)
        h.clock.advance(120.0)
        h.settle()
        # dirty tick while the node is down
        h.apply(simple_pcs(name="tick-a",
                           cliques=[clique("w", replicas=2)], replicas=1))
        h.settle()
        h.cluster.recover_node(victim)
        h.settle()
        h.cluster.cordon(victim)
        h.settle()
        # dirty tick under the cordon: nothing may land on the victim
        h.apply(simple_pcs(name="tick-b",
                           cliques=[clique("w", replicas=2)], replicas=1))
        h.settle()
        pods = list(h.store.scan(Pod.KIND))
        assert all(p.node_name for p in pods)
        assert all(
            p.node_name != victim
            for p in pods
            if p.metadata.labels.get("app.kubernetes.io/part-of")
            == "tick-b"
        )
        h.cluster.uncordon(victim)
        h.settle()
        # the deployed default engine is fused (+ incremental)
        summary = h.scheduler.debug_state()["engine"]
        assert summary["device_state"]["fused"]
        assert summary["device_state"]["incremental"]


def _placements(store) -> dict:
    from grove_tpu.api.types import Pod

    return {
        (p.metadata.namespace, p.metadata.name): p.node_name
        for p in store.scan(Pod.KIND)
    }


@pytest.mark.chaos
class TestChaosEquivalence:
    """Seeded node-fault storms (node_flap, domain_outage) solved by the
    fused+incremental engine (the deployed default, verify tripwire
    armed), the split delta engine, and the full-re-encode engine must
    land every pod on the SAME node: chaos draws are bit-reproducible
    per seed, so any divergence is a fast path changing placements —
    and node faults between solves exercise exactly the rebind/rebuild
    invalidations the incremental bookkeeping must honor."""

    @pytest.mark.parametrize("seed", (3, 9))
    def test_node_fault_seed_places_identically(self, seed):
        from grove_tpu.chaos import ChaosHarness, FaultPlan

        from test_chaos import chaos_workload, quiet

        runs = []
        for cfg in (
            {"solver": {"device_state_cache": True,
                        "device_state_verify": True}},
            {"solver": {"device_state_cache": True,
                        "device_state_verify": True,
                        "fused_solve": False,
                        "incremental_resolve": False}},
            {"solver": {"device_state_cache": False,
                        "fused_solve": False,
                        "incremental_resolve": False}},
        ):
            plan = FaultPlan.from_seed(
                seed,
                node_flap_rate=0.12,
                domain_outage_rate=0.04,
            )
            ch = quiet(ChaosHarness(plan, nodes=make_nodes(24), config=cfg))
            ch.apply(chaos_workload())
            ch.run_chaos()
            assert ch.plan.counts.get("node_flap", 0) + ch.plan.counts.get(
                "domain_outage", 0
            ) > 0, "a storm that injects no node faults proves nothing"
            runs.append(_placements(ch.raw_store))
        assert runs[0] == runs[1] == runs[2]
