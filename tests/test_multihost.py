"""Multi-host (multi-process) SPMD parity for the sharded engine.

Two REAL OS processes form a JAX cluster (Gloo-backed on CPU; the same
code rides ICI/DCN on TPU pods), each contributing 2 virtual devices to
a 4-device global mesh. Both run the identical sharded solve; the test
asserts (a) each process independently reaches the same placements and
(b) they match the single-process engine bit for bit — the property
`grove_tpu/parallel/multihost.py` documents: the engine is multi-host
ready by construction because inputs are global and results replicated.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
repo = sys.argv[3]
sys.path.insert(0, repo)
sys.path.insert(0, os.path.join(repo, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
from grove_tpu.parallel import initialize_multihost
pid, nprocs = initialize_multihost(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
)
assert nprocs == 2 and pid == int(sys.argv[2])
from test_solver import cluster, gang
from grove_tpu.parallel import ShardedPlacementEngine, make_solver_mesh
from grove_tpu.solver import PlacementEngine

snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
gangs = [
    gang("a", pods=2, cpu=2.0),
    gang("b", pods=4, cpu=6.0, required=1),
    gang("c", pods=3, cpu=3.0, preferred=2),
]
mesh = make_solver_mesh()  # all 4 GLOBAL devices across both processes
assert len(jax.devices()) == 4
res = ShardedPlacementEngine(snap, mesh).solve(gangs)
# single-device reference INSIDE the worker (same jax build/flags):
single = PlacementEngine(snap).solve(gangs)
sig = sorted(
    (n, tuple(int(x) for x in p.node_indices))
    for n, p in res.placed.items()
)
ref = sorted(
    (n, tuple(int(x) for x in p.node_indices))
    for n, p in single.placed.items()
)
assert sig == ref, f"multihost diverged from single-device: {sig} vs {ref}"
print("RESULT", sig, flush=True)
"""


#: the pure-jax capability probe: form the same 2-process Gloo cluster
#: the real test uses and execute ONE cross-process computation (a jit
#: over an array sharded across the 4-device global mesh). Some jaxlib
#: builds form the cluster fine but cannot EXECUTE multi-process
#: computations on the CPU backend — that is an environment capability
#: gap, not an engine parity regression, and the probe separates the two.
_PROBE_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=sys.argv[1], num_processes=2,
    process_id=int(sys.argv[2]),
)
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()), ("d",))
x = jax.device_put(jnp.arange(4.0), NamedSharding(mesh, P("d")))
print("PROBE", float(jax.jit(jnp.sum)(x)), flush=True)
"""

_CAPABILITY_GAP = "Multiprocess computations aren't implemented"

#: session cache: None = not probed yet, "" = capable, else skip reason
_probe_result: str | None = None


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_worker_pair(worker: str, timeout: int) -> tuple[list[int], str]:
    """Spawn the 2-process CPU Gloo pair running `worker`; returns the
    return codes and combined output."""
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker,
             f"127.0.0.1:{port}", str(i), repo],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(2)
    ]
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outputs.append(out)
    finally:
        # a worker hung in the Gloo handshake must not orphan the pair
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    return [p.returncode for p in procs], "\n".join(outputs)


def _multihost_skip_reason() -> str:
    """'' when this environment can execute cross-process computations,
    else the skip reason. Probed once per session. ONLY the known
    capability gap skips — any other probe failure returns '' so the
    real test runs and reports the regression loudly."""
    global _probe_result
    if _probe_result is None:
        try:
            rcs, out = _run_worker_pair(_PROBE_WORKER, timeout=120)
        except Exception as exc:
            # a broken probe must not mask an engine regression
            rcs, out = [0, 0], f"probe error: {exc}"
        if any(rc != 0 for rc in rcs) and _CAPABILITY_GAP in out:
            _probe_result = (
                "this jaxlib's CPU backend cannot execute multi-process "
                f"computations ({_CAPABILITY_GAP!r}); the 2-process "
                "parity test needs a build with cross-process CPU "
                "collectives or a real multi-host TPU slice"
            )
        else:
            _probe_result = ""
    return _probe_result


@pytest.mark.skipif(
    os.environ.get("JAX_PLATFORMS", "cpu") not in ("", "cpu"),
    reason="multi-process Gloo cluster runs on the CPU backend",
)
def test_two_process_cluster_reaches_identical_placements():
    reason = _multihost_skip_reason()
    if reason:
        pytest.skip(reason)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER,
             f"127.0.0.1:{port}", str(i), repo],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(2)
    ]
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outputs.append(out)
    finally:
        # a worker hung in the Gloo handshake must not orphan the pair
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-2000:]}"
    results = [
        line for out in outputs for line in out.splitlines()
        if line.startswith("RESULT ")
    ]
    assert len(results) == 2
    # both processes must hold the identical, bitwise-equal placements
    assert results[0] == results[1]


def test_initialize_multihost_no_config_is_single_host_noop(monkeypatch):
    from grove_tpu.parallel import initialize_multihost

    for var in ("GROVE_TPU_COORDINATOR", "GROVE_TPU_NUM_PROCESSES",
                "GROVE_TPU_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    assert initialize_multihost() == (0, 1)


def test_initialize_multihost_partial_config_names_the_gaps(monkeypatch):
    from grove_tpu.parallel import initialize_multihost

    for var in ("GROVE_TPU_COORDINATOR", "GROVE_TPU_NUM_PROCESSES",
                "GROVE_TPU_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("GROVE_TPU_NUM_PROCESSES", "2")
    with pytest.raises(ValueError, match="GROVE_TPU_COORDINATOR"):
        initialize_multihost()
