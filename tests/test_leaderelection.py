"""HA leader election (manager.go:98-104): one active manager per lease;
a standby takes over when the leader stops renewing or releases."""

from grove_tpu.api.types import Pod
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness

from test_e2e_basic import clique, simple_pcs

HA = {"leader_election": {"enabled": True, "lease_duration_seconds": 15.0}}


def ha_pair():
    leader = Harness(nodes=make_nodes(8), config=dict(HA))
    standby = Harness(cluster=leader.cluster)
    return leader, standby


def test_standby_runs_nothing_while_leader_holds_lease():
    leader, standby = ha_pair()
    leader.manager.run_once()  # first to try wins the lease
    leader.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
    assert standby.manager.run_once() == 0  # cannot acquire: stands by
    leader.settle()
    pods = leader.store.list(Pod.KIND)
    assert len(pods) == 2 and all(p.status.ready for p in pods)
    # the whole settle ran under ONE leader
    assert leader.elector.is_leader() and not standby.elector.is_leader()


def test_standby_takes_over_after_lease_expiry():
    leader, standby = ha_pair()
    leader.settle()  # leader acquires
    assert leader.elector.is_leader()
    # leader "crashes": stops running; work arrives meanwhile
    leader.cluster.store.create(
        simple_pcs(cliques=[clique("w", replicas=2)])
    )
    assert standby.manager.run_once() == 0  # lease still fresh
    standby.clock.advance(16.0)  # past lease_duration: holder is stale
    standby.settle()
    assert standby.elector.is_leader()
    pods = standby.store.list(Pod.KIND)
    assert len(pods) == 2 and all(p.node_name and p.status.ready
                                  for p in pods)


def test_clean_release_hands_off_immediately():
    leader, standby = ha_pair()
    leader.settle()
    leader.elector.release()  # graceful shutdown (ReleaseOnCancel)
    standby.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
    standby.settle()  # no lease wait needed
    assert standby.elector.is_leader()
    assert all(p.status.ready for p in standby.store.list(Pod.KIND))


def test_no_split_brain_under_alternating_steps():
    """Interleaved run_once calls never let both managers reconcile in
    the same window while the lease is fresh."""
    leader, standby = ha_pair()
    leader.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
    for _ in range(16):
        a = leader.manager.run_once()
        b = standby.manager.run_once()
        leader.kubelet.tick()
        assert b == 0, "standby reconciled while the leader held the lease"
        if a == 0:
            break
    assert all(p.status.ready for p in leader.store.list(Pod.KIND))


def test_standby_autoscale_is_a_noop():
    """HPA sweeps are leader-only: a standby's periodic autoscale() must
    not mutate scale targets (split-brain guard)."""
    from grove_tpu.api.types import (
        AutoScalingConfig,
        PodCliqueScalingGroup,
        PodCliqueScalingGroupConfig,
    )

    leader, standby = ha_pair()
    pcs = simple_pcs(
        name="as",
        cliques=[clique("w", replicas=2)],
        sgs=[PodCliqueScalingGroupConfig(
            name="grp", clique_names=["w"], replicas=2, min_available=1,
            scale_config=AutoScalingConfig(min_replicas=1, max_replicas=5,
                                           target_utilization=0.5))],
    )
    leader.apply(pcs)
    leader.settle()
    for p in leader.store.list(Pod.KIND):
        standby.autoscaler.observe(p.metadata.name, 1.0)  # 2x target
    standby.autoscale()  # not the leader: must not scale
    pcsg = standby.store.get(PodCliqueScalingGroup.KIND, "default", "as-0-grp")
    assert pcsg.spec.replicas == 2
    # the leader's sweep does scale
    for p in leader.store.list(Pod.KIND):
        leader.autoscaler.observe(p.metadata.name, 1.0)
    leader.autoscale()
    pcsg = leader.store.get(PodCliqueScalingGroup.KIND, "default", "as-0-grp")
    assert pcsg.spec.replicas == 4


def test_failover_with_in_flight_solve_dispatch():
    """A leader that dies AFTER pre_round dispatched its accelerator
    solve (pending state held in ITS scheduler instance) must not leak
    that work into the successor: the standby's scheduler has its own
    clean state, re-derives the backlog, and binds everything — and the
    dead leader's pending dispatch is simply garbage."""
    leader, standby = ha_pair()
    leader.settle()
    assert leader.elector.is_leader()
    # work arrives; drive the leader only as far as the dispatch: run
    # rounds until its scheduler holds a pending solve, then "crash" it
    leader.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
    for _ in range(8):
        leader.manager._drain_events()
        leader.manager._pop_due_requeues()
        # run pre_round by hand (what run_once does before reconciles)
        leader.scheduler.pre_round()
        if leader.scheduler._pending is not None:
            break  # dispatched; now the leader dies mid-round
        leader.manager.run_once()
    assert leader.scheduler._pending is not None, (
        "setup failed: the leader never reached a dispatched solve"
    )
    # the standby takes over after lease expiry and finishes the job
    standby.clock.advance(16.0)
    standby.settle()
    assert standby.elector.is_leader()
    pods = standby.store.list(Pod.KIND)
    assert len(pods) == 2
    assert all(p.node_name and p.status.ready for p in pods)
    # the dead leader's pending dispatch never reached the store: every
    # bind is attributed to the standby's scheduler
    assert standby.cluster.metrics.counter(
        "grove_scheduler_gangs_scheduled_total"
    ).total() >= 1


def test_shard_coordinator_role_fails_over_on_lease_expiry():
    """The sharded control plane's COORDINATOR role rides the same lease
    machinery: when the worker holding grove-shard-coordinator dies, a
    survivor acquires it after expiry and keeps reconciling the shard
    map (orphan reassignment still happens — no frozen map)."""
    from grove_tpu.controller.sharding import (
        COORDINATOR_LEASE,
        SHARD_NAMESPACE,
        ShardMap,
        SHARD_MAP_NAME,
    )
    from grove_tpu.controller.leaderelection import Lease

    h = Harness(nodes=make_nodes(8),
                config={"controllers": {"shards": 3}})
    h.settle()
    sm = h.manager
    lease = h.store.get(Lease.KIND, SHARD_NAMESPACE, COORDINATOR_LEASE)
    assert lease is not None and lease.holder_identity
    coord = lease.holder_identity
    idx = next(w.index for w in sm.workers if w.identity == coord)
    assert sm.kill_worker(idx)
    h.advance(11.0)  # past the worker lease duration
    h.settle()
    lease = h.store.get(Lease.KIND, SHARD_NAMESPACE, COORDINATOR_LEASE)
    assert lease.holder_identity and lease.holder_identity != coord
    # and the new coordinator reassigned the dead worker's shards
    m = h.store.get(ShardMap.KIND, SHARD_NAMESPACE, SHARD_MAP_NAME)
    assert coord not in m.assignments.values()


def test_shard_worker_lease_renewal_rides_every_round():
    """Worker heartbeat leases renew at the top of each round; a live
    fleet's leases are never stale by more than one round's clock."""
    from grove_tpu.controller.leaderelection import Lease
    from grove_tpu.controller.sharding import (
        SHARD_NAMESPACE,
        WORKER_LEASE_PREFIX,
    )

    h = Harness(nodes=make_nodes(8),
                config={"controllers": {"shards": 2}})
    h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
    h.settle()
    h.advance(5.0)
    now = h.clock.now()
    for lease in h.store.scan(Lease.KIND, namespace=SHARD_NAMESPACE):
        if lease.metadata.name.startswith(WORKER_LEASE_PREFIX):
            assert lease.holder_identity
            assert now - lease.renew_time <= lease.lease_duration_seconds


def test_randomized_ha_interleavings_never_split_brain():
    """Randomized HA fuzz (CI-sized; a 20x40 sweep ran clean offline):
    two managers over one store, random interleaving of which replica
    runs, lease expiries, and workload ops. At no step may both hold the
    lease, and after a final expiry + settles everything binds."""
    import numpy as np

    import bench as bench_mod

    for seed in (0, 5, 11):
        rng = np.random.default_rng(seed)
        a = Harness(
            nodes=make_nodes(
                20, allocatable={"cpu": 16.0, "memory": 64.0, "tpu": 8.0}
            ),
            config=dict(HA),
        )
        b = Harness(cluster=a.cluster)
        alive = []
        for step in range(25):
            op = rng.choice(
                ["apply", "delete", "scale", "runA", "runB", "expire"]
            )
            if op == "apply" and len(alive) < 4:
                name = f"ha{seed}-{step}"
                a.store.create(bench_mod._churn_pcs(name, 1))
                alive.append(name)
            elif op == "delete" and alive:
                victim = alive.pop(int(rng.integers(0, len(alive))))
                a.store.delete("PodCliqueSet", "default", victim)
            elif op == "scale" and alive:
                t = alive[int(rng.integers(0, len(alive)))]
                pcs = a.store.get("PodCliqueSet", "default", t)
                if pcs is not None and pcs.metadata.deletion_timestamp is None:
                    pcs.spec.replicas = int(rng.integers(1, 4))
                    a.store.update(pcs)
            elif op == "runA":
                ran = a.manager.run_once()
                a.kubelet.tick()
                # the REAL split-brain invariant: a replica that executed
                # reconciles must be the lease holder (a naive
                # both-is_leader check is a tautology — one Lease, one
                # holder string)
                assert ran == 0 or a.elector.is_leader(), (
                    f"seed {seed} step {step}: A reconciled without lease"
                )
            elif op == "runB":
                ran = b.manager.run_once()
                b.kubelet.tick()
                assert ran == 0 or b.elector.is_leader(), (
                    f"seed {seed} step {step}: B reconciled without lease"
                )
            elif op == "expire":
                a.clock.advance(float(rng.integers(8, 20)))
        a.clock.advance(30.0)
        a.settle()
        b.settle()
        a.settle()
        pods = a.store.scan(Pod.KIND)
        assert all(p.node_name for p in pods), (
            f"seed {seed}: unbound pods after final settles"
        )
