"""Startup-ordering E2E suite (SO1-SO4 in the reference,
operator/e2e/tests/startup_ordering_test.go): InOrder/Explicit orderings
verified by readiness-time comparison, like the reference compares container
start timestamps."""

from grove_tpu.api import constants
from grove_tpu.api.types import CliqueStartupType, Pod
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness

from test_e2e_basic import clique, simple_pcs


def ready_order(harness):
    """Pods grouped by clique template, with the tick at which each became
    ready (derived by stepping the kubelet one tick at a time)."""
    order: dict[str, int] = {}
    tick = 0
    for _ in range(32):
        harness.manager.settle()
        changed = harness.kubelet.tick()
        tick += 1
        for pod in harness.store.list(Pod.KIND):
            name = pod.metadata.labels[constants.LABEL_PODCLIQUE]
            if pod.status.ready and name not in order:
                order[name] = tick
        if changed == 0:
            harness.manager.settle()
            if harness.kubelet.tick() == 0:
                break
    return order


class TestStartupOrdering:
    def test_so1_any_order_all_start_together(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs(cliques=[clique("a"), clique("b")]))
        h.settle()
        pods = h.store.list(Pod.KIND)
        assert all(p.status.ready for p in pods)
        assert all(
            constants.ANNOTATION_WAIT_FOR not in p.metadata.annotations
            for p in pods
        )

    def test_so2_explicit_dag(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(
            simple_pcs(
                cliques=[
                    clique("router"),
                    clique("pf", starts_after=["router"]),
                    clique("dc", starts_after=["router", "pf"]),
                ],
                startup=CliqueStartupType.EXPLICIT,
            )
        )
        order = ready_order(h)
        assert order["simple1-0-router"] < order["simple1-0-pf"]
        assert order["simple1-0-pf"] < order["simple1-0-dc"]
        # wait-for annotations carry '<fqn>:<minAvailable>'
        pod = h.store.get(Pod.KIND, "default", "simple1-0-dc-0")
        assert (
            pod.metadata.annotations[constants.ANNOTATION_WAIT_FOR]
            == "simple1-0-router:2,simple1-0-pf:2"
        )

    def test_so3_in_order_chains_previous_clique(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(
            simple_pcs(
                cliques=[clique("a"), clique("b"), clique("c")],
                startup=CliqueStartupType.IN_ORDER,
            )
        )
        order = ready_order(h)
        assert order["simple1-0-a"] < order["simple1-0-b"] < order["simple1-0-c"]

    def test_so4_min_available_unlocks_dependents(self):
        # parent minAvailable=1 of 3: dependent starts once ONE parent pod
        # is ready, not all three
        h = Harness(nodes=make_nodes(8))
        h.apply(
            simple_pcs(
                cliques=[
                    clique("parent", replicas=3, min_available=1),
                    clique("child", replicas=1, starts_after=["parent"]),
                ],
                startup=CliqueStartupType.EXPLICIT,
            )
        )
        h.settle()
        pod = h.store.get(Pod.KIND, "default", "simple1-0-child-0")
        assert pod.metadata.annotations[constants.ANNOTATION_WAIT_FOR] == (
            "simple1-0-parent:1"
        )
        assert pod.status.ready
