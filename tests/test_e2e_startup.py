"""Startup-ordering E2E suite (SO1-SO4 in the reference,
operator/e2e/tests/startup_ordering_test.go): InOrder/Explicit orderings
verified by readiness-time comparison, like the reference compares container
start timestamps."""

from grove_tpu.api import constants
from grove_tpu.api.types import CliqueStartupType, Pod
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness

from test_e2e_basic import clique, simple_pcs


def ready_order(harness):
    """Pods grouped by clique template, with the tick at which each became
    ready (derived by stepping the kubelet one tick at a time)."""
    order: dict[str, int] = {}
    tick = 0
    for _ in range(32):
        harness.manager.settle()
        changed = harness.kubelet.tick()
        tick += 1
        for pod in harness.store.list(Pod.KIND):
            name = pod.metadata.labels[constants.LABEL_PODCLIQUE]
            if pod.status.ready and name not in order:
                order[name] = tick
        if changed == 0:
            harness.manager.settle()
            if harness.kubelet.tick() == 0:
                break
    return order


class TestStartupOrdering:
    def test_so1_any_order_all_start_together(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs(cliques=[clique("a"), clique("b")]))
        h.settle()
        pods = h.store.list(Pod.KIND)
        assert all(p.status.ready for p in pods)
        assert all(
            constants.ANNOTATION_WAIT_FOR not in p.metadata.annotations
            for p in pods
        )

    def test_so2_explicit_dag(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(
            simple_pcs(
                cliques=[
                    clique("router"),
                    clique("pf", starts_after=["router"]),
                    clique("dc", starts_after=["router", "pf"]),
                ],
                startup=CliqueStartupType.EXPLICIT,
            )
        )
        order = ready_order(h)
        assert order["simple1-0-router"] < order["simple1-0-pf"]
        assert order["simple1-0-pf"] < order["simple1-0-dc"]
        # wait-for annotations carry '<fqn>:<minAvailable>'
        pod = h.store.get(Pod.KIND, "default", "simple1-0-dc-0")
        assert (
            pod.metadata.annotations[constants.ANNOTATION_WAIT_FOR]
            == "simple1-0-router:2,simple1-0-pf:2"
        )

    def test_so3_in_order_chains_previous_clique(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(
            simple_pcs(
                cliques=[clique("a"), clique("b"), clique("c")],
                startup=CliqueStartupType.IN_ORDER,
            )
        )
        order = ready_order(h)
        assert order["simple1-0-a"] < order["simple1-0-b"] < order["simple1-0-c"]

    def test_so4_min_available_unlocks_dependents(self):
        # parent minAvailable=1 of 3: dependent starts once ONE parent pod
        # is ready, not all three
        h = Harness(nodes=make_nodes(8))
        h.apply(
            simple_pcs(
                cliques=[
                    clique("parent", replicas=3, min_available=1),
                    clique("child", replicas=1, starts_after=["parent"]),
                ],
                startup=CliqueStartupType.EXPLICIT,
            )
        )
        h.settle()
        pod = h.store.get(Pod.KIND, "default", "simple1-0-child-0")
        assert pod.metadata.annotations[constants.ANNOTATION_WAIT_FOR] == (
            "simple1-0-parent:1"
        )
        assert pod.status.ready


class TestStartupOrderingAcrossGroups:
    """SO5/SO6: startsAfter across scaling-group boundaries
    (GenerateDependencyNamesForBasePodGang, componentutils
    podcliquescalinggroup.go:70-83; scaled replicas order only within
    their own gang instance, pcsg podclique.go:391-408)."""

    def test_so5_standalone_waits_for_pcsg_base_replicas(self):
        from grove_tpu.api.types import PodCliqueScalingGroupConfig

        h = Harness(nodes=make_nodes(16))
        pcs = simple_pcs(
            cliques=[
                clique("worker", replicas=2),
                clique("router", starts_after=["worker"]),
            ],
            sgs=[PodCliqueScalingGroupConfig(
                name="sg", clique_names=["worker"], replicas=2,
                min_available=1)],
            startup=CliqueStartupType.EXPLICIT,
        )
        h.apply(pcs)
        order = ready_order(h)
        # router waits on the BASE group replica (sg-0), which must be
        # ready strictly before it
        assert order["simple1-0-sg-0-worker"] < order["simple1-0-router"]
        pods = h.store.list(Pod.KIND)
        router = [p for p in pods if "-router-" in p.metadata.name][0]
        dep = router.metadata.annotations[constants.ANNOTATION_WAIT_FOR]
        assert "simple1-0-sg-0-worker" in dep
        assert "simple1-0-sg-1-worker" not in dep, (
            "scaled replicas must not gate cross-group dependents"
        )

    def test_so6_scaled_replica_orders_within_its_own_instance(self):
        from grove_tpu.api.types import PodCliqueScalingGroupConfig

        h = Harness(nodes=make_nodes(16))
        pcs = simple_pcs(
            cliques=[
                clique("a", replicas=1),
                clique("b", replicas=1, starts_after=["a"]),
            ],
            sgs=[PodCliqueScalingGroupConfig(
                name="sg", clique_names=["a", "b"], replicas=2,
                min_available=1)],
            startup=CliqueStartupType.EXPLICIT,
        )
        h.apply(pcs)
        order = ready_order(h)
        # within each gang instance b follows its own a
        assert order["simple1-0-sg-0-a"] < order["simple1-0-sg-0-b"]
        assert order["simple1-0-sg-1-a"] < order["simple1-0-sg-1-b"]
        pods = h.store.list(Pod.KIND)
        b1 = [p for p in pods if "sg-1-b" in p.metadata.name][0]
        dep = b1.metadata.annotations[constants.ANNOTATION_WAIT_FOR]
        assert "simple1-0-sg-1-a" in dep and "sg-0-a" not in dep, (
            "a scaled replica orders only within its own instance"
        )


class TestRBACEnforcement:
    """The RBAC trio is consumed, not decorative: the startup barrier's
    pod watch runs as the pod's ServiceAccount identity, and a missing
    RoleBinding leaves the watch Forbidden and the barrier closed
    (reference: grove-initc authenticates its pod watches with the SA
    token secret, initc/internal/wait.go:76-90)."""

    def ordered_pcs(self):
        return simple_pcs(
            cliques=[clique("a"), clique("b", starts_after=["a"])],
            startup=CliqueStartupType.EXPLICIT,
        )

    def test_pod_watch_without_role_is_forbidden(self):
        from grove_tpu.cluster.store import Forbidden
        import pytest

        h = Harness(nodes=make_nodes(8))
        h.apply(self.ordered_pcs())
        h.settle()
        # the provisioned identity is authorized...
        h.store.authorize_read(
            "system:serviceaccount:default:simple1-sa", "watch", "pods",
            "default",
        )
        # ...an unprovisioned one is not, nor cross-namespace access
        with pytest.raises(Forbidden):
            h.store.authorize_read(
                "system:serviceaccount:default:rogue-sa", "watch", "pods",
                "default",
            )
        with pytest.raises(Forbidden):
            h.store.authorize_read(
                "system:serviceaccount:other:simple1-sa", "watch", "pods",
                "default",
            )
        # non-SA actors (operator, users) are not constrained by ns roles
        h.store.authorize_read("user", "watch", "pods", "default")

    def test_missing_rolebinding_keeps_barrier_closed(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(self.ordered_pcs())
        # let the control plane create+bind everything, but keep the
        # kubelet from ticking so nothing is ready yet
        h.manager.settle()
        # revoke the grant with the operator "offline": only the kubelet
        # runs, so the self-healing reconciler cannot restore the binding
        h.store.delete("RoleBinding", "default", "simple1-pod-reader")
        for _ in range(8):
            h.kubelet.tick()
        pods = {p.metadata.name: p for p in h.store.list(Pod.KIND)}
        a_ready = [p.status.ready for n, p in pods.items() if "-a-" in n]
        b_ready = [p.status.ready for n, p in pods.items() if "-b-" in n]
        assert all(a_ready), "independent clique unaffected"
        assert not any(b_ready), "Forbidden watch must keep the barrier closed"
        # the operator comes back: RBAC self-heals (sync recreates the
        # binding) and the barrier opens
        h.settle()
        assert h.store.get("RoleBinding", "default",
                           "simple1-pod-reader") is not None
        assert all(p.status.ready for p in h.store.list(Pod.KIND))

    def test_pods_carry_service_account_identity(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(self.ordered_pcs())
        h.settle()
        for p in h.store.list(Pod.KIND):
            assert p.spec.service_account_name == "simple1-sa"
