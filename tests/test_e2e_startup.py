"""Startup-ordering E2E suite (SO1-SO4 in the reference,
operator/e2e/tests/startup_ordering_test.go): InOrder/Explicit orderings
verified by readiness-time comparison, like the reference compares container
start timestamps."""

from grove_tpu.api import constants
from grove_tpu.api.types import CliqueStartupType, Pod
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness

from test_e2e_basic import clique, simple_pcs


def ready_order(harness):
    """Pods grouped by clique template, with the tick at which each became
    ready (derived by stepping the kubelet one tick at a time)."""
    order: dict[str, int] = {}
    tick = 0
    for _ in range(32):
        harness.manager.settle()
        changed = harness.kubelet.tick()
        tick += 1
        for pod in harness.store.list(Pod.KIND):
            name = pod.metadata.labels[constants.LABEL_PODCLIQUE]
            if pod.status.ready and name not in order:
                order[name] = tick
        if changed == 0:
            harness.manager.settle()
            if harness.kubelet.tick() == 0:
                break
    return order


class TestStartupOrdering:
    def test_so1_any_order_all_start_together(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs(cliques=[clique("a"), clique("b")]))
        h.settle()
        pods = h.store.list(Pod.KIND)
        assert all(p.status.ready for p in pods)
        assert all(
            constants.ANNOTATION_WAIT_FOR not in p.metadata.annotations
            for p in pods
        )

    def test_so2_explicit_dag(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(
            simple_pcs(
                cliques=[
                    clique("router"),
                    clique("pf", starts_after=["router"]),
                    clique("dc", starts_after=["router", "pf"]),
                ],
                startup=CliqueStartupType.EXPLICIT,
            )
        )
        order = ready_order(h)
        assert order["simple1-0-router"] < order["simple1-0-pf"]
        assert order["simple1-0-pf"] < order["simple1-0-dc"]
        # wait-for annotations carry '<fqn>:<minAvailable>'
        pod = h.store.get(Pod.KIND, "default", "simple1-0-dc-0")
        assert (
            pod.metadata.annotations[constants.ANNOTATION_WAIT_FOR]
            == "simple1-0-router:2,simple1-0-pf:2"
        )

    def test_so3_in_order_chains_previous_clique(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(
            simple_pcs(
                cliques=[clique("a"), clique("b"), clique("c")],
                startup=CliqueStartupType.IN_ORDER,
            )
        )
        order = ready_order(h)
        assert order["simple1-0-a"] < order["simple1-0-b"] < order["simple1-0-c"]

    def test_so4_min_available_unlocks_dependents(self):
        # parent minAvailable=1 of 3: dependent starts once ONE parent pod
        # is ready, not all three
        h = Harness(nodes=make_nodes(8))
        h.apply(
            simple_pcs(
                cliques=[
                    clique("parent", replicas=3, min_available=1),
                    clique("child", replicas=1, starts_after=["parent"]),
                ],
                startup=CliqueStartupType.EXPLICIT,
            )
        )
        h.settle()
        pod = h.store.get(Pod.KIND, "default", "simple1-0-child-0")
        assert pod.metadata.annotations[constants.ANNOTATION_WAIT_FOR] == (
            "simple1-0-parent:1"
        )
        assert pod.status.ready


class TestRBACEnforcement:
    """The RBAC trio is consumed, not decorative: the startup barrier's
    pod watch runs as the pod's ServiceAccount identity, and a missing
    RoleBinding leaves the watch Forbidden and the barrier closed
    (reference: grove-initc authenticates its pod watches with the SA
    token secret, initc/internal/wait.go:76-90)."""

    def ordered_pcs(self):
        return simple_pcs(
            cliques=[clique("a"), clique("b", starts_after=["a"])],
            startup=CliqueStartupType.EXPLICIT,
        )

    def test_pod_watch_without_role_is_forbidden(self):
        from grove_tpu.cluster.store import Forbidden
        import pytest

        h = Harness(nodes=make_nodes(8))
        h.apply(self.ordered_pcs())
        h.settle()
        # the provisioned identity is authorized...
        h.store.authorize_read(
            "system:serviceaccount:default:simple1-sa", "watch", "pods",
            "default",
        )
        # ...an unprovisioned one is not, nor cross-namespace access
        with pytest.raises(Forbidden):
            h.store.authorize_read(
                "system:serviceaccount:default:rogue-sa", "watch", "pods",
                "default",
            )
        with pytest.raises(Forbidden):
            h.store.authorize_read(
                "system:serviceaccount:other:simple1-sa", "watch", "pods",
                "default",
            )
        # non-SA actors (operator, users) are not constrained by ns roles
        h.store.authorize_read("user", "watch", "pods", "default")

    def test_missing_rolebinding_keeps_barrier_closed(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(self.ordered_pcs())
        # let the control plane create+bind everything, but keep the
        # kubelet from ticking so nothing is ready yet
        h.manager.settle()
        # revoke the grant with the operator "offline": only the kubelet
        # runs, so the self-healing reconciler cannot restore the binding
        h.store.delete("RoleBinding", "default", "simple1-pod-reader")
        for _ in range(8):
            h.kubelet.tick()
        pods = {p.metadata.name: p for p in h.store.list(Pod.KIND)}
        a_ready = [p.status.ready for n, p in pods.items() if "-a-" in n]
        b_ready = [p.status.ready for n, p in pods.items() if "-b-" in n]
        assert all(a_ready), "independent clique unaffected"
        assert not any(b_ready), "Forbidden watch must keep the barrier closed"
        # the operator comes back: RBAC self-heals (sync recreates the
        # binding) and the barrier opens
        h.settle()
        assert h.store.get("RoleBinding", "default",
                           "simple1-pod-reader") is not None
        assert all(p.status.ready for p in h.store.list(Pod.KIND))

    def test_pods_carry_service_account_identity(self):
        h = Harness(nodes=make_nodes(8))
        h.apply(self.ordered_pcs())
        h.settle()
        for p in h.store.list(Pod.KIND):
            assert p.spec.service_account_name == "simple1-sa"
