"""Tests for topology encoding (grove_tpu.topology)."""

import numpy as np

from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import Node, TopologyLevel
from grove_tpu.topology import (
    HOST_LABEL_KEY,
    default_cluster_topology,
    encode_topology,
)


def make_node(name, labels, cpu=8.0, mem=32e9, tpu=4.0, unschedulable=False):
    return Node(
        metadata=ObjectMeta(name=name, labels=dict(labels)),
        allocatable={"cpu": cpu, "memory": mem, "tpu": tpu},
        unschedulable=unschedulable,
    )


def two_rack_nodes():
    nodes = []
    for b in range(2):
        for r in range(2):
            for h in range(2):
                # rack label value repeats across blocks on purpose: the
                # path-prefix encoding must still keep them distinct domains.
                nodes.append(
                    make_node(
                        f"n-{b}-{r}-{h}",
                        {"topo/block": f"block-{b}", "topo/rack": f"rack-{r}"},
                    )
                )
    return nodes


def topo():
    return default_cluster_topology(
        [
            TopologyLevel(domain="block", key="topo/block"),
            TopologyLevel(domain="rack", key="topo/rack"),
        ]
    )


class TestDefaultClusterTopology:
    def test_host_level_auto_added_and_sorted(self):
        ct = default_cluster_topology(
            [
                TopologyLevel(domain="rack", key="topo/rack"),
                TopologyLevel(domain="block", key="topo/block"),
            ]
        )
        assert [lv.domain for lv in ct.spec.levels] == ["block", "rack", "host"]
        assert ct.spec.levels[-1].key == HOST_LABEL_KEY
        assert ct.metadata.name == "grove-topology"

    def test_host_not_duplicated(self):
        ct = default_cluster_topology(
            [TopologyLevel(domain="host", key="custom/host")]
        )
        assert [lv.domain for lv in ct.spec.levels] == ["host"]


class TestEncodeTopology:
    def test_shapes_and_hierarchical_ids(self):
        snap = encode_topology(topo(), two_rack_nodes())
        assert snap.num_levels == 3  # block, rack, host
        assert snap.num_nodes == 8
        # 2 blocks, 4 racks (2 per block despite repeated label), 8 hosts
        assert list(snap.num_domains) == [2, 4, 8]
        # rack ids differ across blocks even though the label value repeats
        rack_ids = snap.domain_ids[1]
        assert rack_ids[0] == rack_ids[1]          # same block, same rack
        assert rack_ids[0] != rack_ids[2]          # same block, other rack
        assert rack_ids[0] != rack_ids[4]          # other block, same label

    def test_membership_matrix(self):
        snap = encode_topology(topo(), two_rack_nodes())
        m = snap.membership(1)  # racks
        assert m.shape == (8, 4)
        np.testing.assert_allclose(m.sum(axis=1), np.ones(8))
        np.testing.assert_allclose(m.sum(axis=0), np.full(4, 2.0))

    def test_capacity_free_usage(self):
        nodes = two_rack_nodes()
        snap = encode_topology(
            topo(), nodes, usage={"n-0-0-0": {"cpu": 3.0, "tpu": 2.0}}
        )
        ci = snap.resource_names.index("cpu")
        ti = snap.resource_names.index("tpu")
        ni = snap.node_index["n-0-0-0"]
        assert snap.capacity[ni, ci] == 8.0
        assert snap.free[ni, ci] == 5.0
        assert snap.free[ni, ti] == 2.0
        other = snap.node_index["n-1-1-1"]
        assert snap.free[other, ci] == 8.0

    def test_unschedulable_and_missing_labels(self):
        nodes = two_rack_nodes()
        nodes[3].unschedulable = True
        nodes.append(make_node("n-orphan", {}))  # no topology labels at all
        snap = encode_topology(topo(), nodes)
        assert not snap.schedulable[3]
        assert snap.schedulable[0]
        # Orphan gets singleton domains — never packs with labelled nodes.
        orphan = snap.node_index["n-orphan"]
        for level in range(snap.num_levels):
            same = (snap.domain_ids[level] == snap.domain_ids[level, orphan]).sum()
            assert same == 1

    def test_level_index_lookup(self):
        snap = encode_topology(topo(), two_rack_nodes())
        assert snap.level_index("topo/rack") == 1
        assert snap.level_index(HOST_LABEL_KEY) == 2


def test_host_level_inserted_above_numa():
    """Auto-added host level must sort above numa (review finding r1-2)."""
    from grove_tpu.api.types import TopologyLevel

    ct = default_cluster_topology(
        [
            TopologyLevel(domain="rack", key="topo/rack"),
            TopologyLevel(domain="numa", key="topo/numa"),
        ]
    )
    # default path appends host before sorting
    assert [lv.domain for lv in ct.spec.levels] == ["rack", "host", "numa"]

    # encode path: two hosts in one rack, each with numa-0 — numa domains
    # must stay distinct per host.
    from grove_tpu.api.types import ClusterTopology, ClusterTopologySpec

    raw = ClusterTopology(
        spec=ClusterTopologySpec(
            levels=[
                TopologyLevel(domain="rack", key="topo/rack"),
                TopologyLevel(domain="numa", key="topo/numa"),
            ]
        )
    )
    nodes = [
        make_node("hostA", {"topo/rack": "r0", "topo/numa": "numa-0"}),
        make_node("hostB", {"topo/rack": "r0", "topo/numa": "numa-0"}),
    ]
    snap = encode_topology(raw, nodes)
    assert snap.level_keys == ["topo/rack", HOST_LABEL_KEY, "topo/numa"]
    numa_level = snap.level_index("topo/numa")
    assert snap.domain_ids[numa_level, 0] != snap.domain_ids[numa_level, 1]
