"""Control-plane operation-sequence fuzz (CI-sized).

The scenario suites (GS/RU/SO/PP/FT) pin specific shapes; this sweeps
RANDOM interleavings of the full operation alphabet — apply, cascade
delete, replica scale, container crash/recovery, pod eviction, node
add/remove, virtual-time advance — and checks global invariants after
every settle:

  1. no ACTIVE pod is bound to a node that no longer exists (node loss
     must sweep its pods to Failed),
  2. per-node capacity is never exceeded by active bound pods,
  3. settle always reaches a fixpoint (settle() itself raises if not).

A larger sweep (60 solver seeds, 12x40-op control-plane sequences) ran
clean during round 5; these fixed seeds keep the net in CI at ~seconds.
"""

import numpy as np

import bench as bench_mod
from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import Node, Pod
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness

import pytest

_TERMINAL = ("Failed", "Succeeded")


def _check_invariants(h, seed: int, step) -> None:
    store = h.store
    pods = store.scan(Pod.KIND)
    nodes = {n.metadata.name for n in store.scan(Node.KIND)}
    usage: dict[str, dict[str, float]] = {}
    for p in pods:
        active = (
            p.metadata.deletion_timestamp is None
            and p.status.phase.value not in _TERMINAL
        )
        if p.node_name and active:
            assert p.node_name in nodes, (
                f"seed {seed} step {step}: active pod {p.metadata.name} "
                f"bound to lost node {p.node_name}"
            )
            u = usage.setdefault(p.node_name, {})
            for res, amt in p.spec.total_requests().items():
                u[res] = u.get(res, 0.0) + amt
    for n in store.scan(Node.KIND):
        for res, used in usage.get(n.metadata.name, {}).items():
            assert used <= n.allocatable.get(res, 0.0) + 1e-6, (
                f"seed {seed} step {step}: node {n.metadata.name} "
                f"over-committed on {res}: {used}"
            )


@pytest.mark.parametrize("seed", (0, 3, 7))
def test_random_operation_sequences_hold_invariants(seed):
    rng = np.random.default_rng(seed)
    h = Harness(
        nodes=make_nodes(
            30, allocatable={"cpu": 16.0, "memory": 64.0, "tpu": 8.0}
        )
    )
    alive: list[str] = []
    for step in range(25):
        op = rng.choice(
            ["apply", "delete", "scale", "crash", "evict", "recover",
             "advance", "node_add", "node_del"]
        )
        if op == "apply" and len(alive) < 5:
            name = f"w{seed}-{step}"
            h.apply(bench_mod._churn_pcs(name, int(rng.integers(1, 4))))
            alive.append(name)
        elif op == "delete" and alive:
            victim = alive.pop(int(rng.integers(0, len(alive))))
            h.store.delete("PodCliqueSet", "default", victim)
        elif op == "scale" and alive:
            target = alive[int(rng.integers(0, len(alive)))]
            pcs = h.store.get("PodCliqueSet", "default", target)
            if pcs is not None and pcs.metadata.deletion_timestamp is None:
                pcs.spec.replicas = int(rng.integers(1, 5))
                h.store.update(pcs)
        elif op in ("crash", "evict", "recover"):
            bound = [p for p in h.store.scan(Pod.KIND) if p.node_name]
            if bound:
                p = bound[int(rng.integers(0, len(bound)))]
                getattr(h.kubelet, f"{op}_pod")(
                    p.metadata.namespace, p.metadata.name
                )
        elif op == "advance":
            h.advance(float(rng.integers(1, 30)))
            _check_invariants(h, seed, step)
            continue
        elif op == "node_add":
            h.store.create(
                Node(
                    metadata=ObjectMeta(name=f"xn{seed}-{step}"),
                    allocatable={"cpu": 16.0, "memory": 64.0, "tpu": 8.0},
                )
            )
        elif op == "node_del":
            extras = [
                n for n in h.store.scan(Node.KIND)
                if n.metadata.name.startswith("xn")
            ]
            if extras:
                h.store.delete(Node.KIND, "default", extras[0].metadata.name)
        h.settle()
        _check_invariants(h, seed, step)
    # let every pending retry/termination timer fire and re-check
    h.advance(120.0)
    _check_invariants(h, seed, "final")
