"""Property tests for the placement engine: on RANDOM problems, every
placement the engine (and the serial baseline, and the sharded engine)
returns must satisfy the hard-feasibility contract exactly —
  - cumulative node capacity is never exceeded,
  - a gang's required pack level puts all its pods in ONE domain there,
  - per-group and constraint-group required levels hold,
  - node eligibility (selectors/taints) is never violated,
  - results are deterministic for a seed.
The scenario suites check specific shapes; this sweeps the space.
"""

import numpy as np
import pytest

from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import Node, TopologyLevel
from grove_tpu.solver import PlacementEngine, SolverGang, solve_serial
from grove_tpu.topology import default_cluster_topology, encode_topology

SEEDS = range(8)


def random_problem(seed: int):
    rng = np.random.default_rng(seed)
    blocks = int(rng.integers(2, 4))
    racks = int(rng.integers(1, 4))
    hosts = int(rng.integers(2, 5))
    nodes = []
    i = 0
    for b in range(blocks):
        for r in range(racks):
            for h in range(hosts):
                labels = {"t/block": f"b{b}", "t/rack": f"b{b}r{r}"}
                if rng.random() < 0.3:
                    labels["accel"] = "v5"
                node = Node(
                    metadata=ObjectMeta(name=f"n{i}", labels=labels),
                    allocatable={
                        "cpu": float(rng.integers(4, 17)),
                        "memory": float(rng.integers(16, 65)),
                        "tpu": float(rng.integers(0, 9)),
                    },
                )
                if rng.random() < 0.15:
                    node.taints = ["reserved"]
                if rng.random() < 0.1:
                    node.unschedulable = True
                nodes.append(node)
                i += 1
    ct = default_cluster_topology([
        TopologyLevel(domain="block", key="t/block"),
        TopologyLevel(domain="rack", key="t/rack"),
    ])
    snap = encode_topology(ct, nodes)

    gangs = []
    for gi in range(int(rng.integers(6, 20))):
        num_groups = int(rng.integers(1, 3))
        demand, gids, greq, gpref = [], [], [], []
        pod_elig = []
        any_elig = False
        for grp in range(num_groups):
            pods = int(rng.integers(1, 5))
            sel = rng.random() < 0.25
            tol = rng.random() < 0.5
            for _ in range(pods):
                demand.append([
                    float(rng.integers(1, 5)),
                    float(rng.integers(1, 9)),
                    float(rng.integers(0, 3)),
                ])
                gids.append(grp)
                if sel or snap.has_taints:
                    mask = snap.eligibility(
                        {"accel": "v5"} if sel else {},
                        ["reserved"] if tol else [],
                    )
                    if mask.all():
                        pod_elig.append(None)
                    else:
                        pod_elig.append(mask)
                        any_elig = True
                else:
                    pod_elig.append(None)
            greq.append(int(rng.integers(-1, 2)))
            gpref.append(-1)
        required = int(rng.integers(-1, 2))
        gangs.append(SolverGang(
            name=f"g{gi:03d}",
            namespace="fuzz",
            demand=np.asarray(demand, np.float32),
            pod_names=[f"g{gi:03d}-p{j}" for j in range(len(demand))],
            group_ids=np.asarray(gids, np.int32),
            group_names=[f"grp{j}" for j in range(num_groups)],
            group_required_level=np.asarray(greq, np.int32),
            group_preferred_level=np.asarray(gpref, np.int32),
            required_level=required,
            preferred_level=int(rng.integers(-1, 3)),
            priority=float(rng.integers(0, 3)),
            pod_elig=pod_elig if any_elig else None,
        ))
    return snap, gangs


def assert_result_valid(snap, gangs, result):
    by_name = {g.name: g for g in gangs}
    free = snap.free.copy()
    for name, placement in result.placed.items():
        gang = by_name[name]
        assign = placement.node_indices
        assert len(assign) == gang.num_pods
        for p in range(gang.num_pods):
            ni = int(assign[p])
            assert snap.schedulable[ni], f"{name} pod {p} on cordoned node"
            if gang.pod_elig is not None and gang.pod_elig[p] is not None:
                assert gang.pod_elig[p][ni], f"{name} pod {p} ineligible node"
            free[ni] -= gang.demand[p]
        # gang-level required pack
        if gang.required_level >= 0:
            ids = snap.domain_ids[gang.required_level, assign]
            assert (ids == ids[0]).all(), f"{name} breaks gang pack level"
        # per-group required pack
        for grp in range(len(gang.group_names)):
            lvl = int(gang.group_required_level[grp])
            if lvl >= 0:
                sel = gang.group_ids == grp
                ids = snap.domain_ids[lvl, assign[sel]]
                assert (ids == ids[0]).all(), f"{name}/{grp} breaks group pack"
        for members, req, _pref in gang.constraint_groups:
            if req >= 0:
                sel = np.isin(gang.group_ids, members)
                ids = snap.domain_ids[req, assign[sel]]
                assert (ids == ids[0]).all(), f"{name} breaks constraint group"
    assert (free >= -1e-4).all(), "cumulative capacity exceeded"


@pytest.mark.parametrize("seed", SEEDS)
def test_engine_placements_satisfy_hard_contract(seed):
    snap, gangs = random_problem(seed)
    result = PlacementEngine(snap).solve(gangs)
    assert_result_valid(snap, gangs, result)
    assert len(result.placed) + len(result.unplaced) == len(gangs)


@pytest.mark.parametrize("seed", SEEDS)
def test_serial_placements_satisfy_hard_contract(seed):
    snap, gangs = random_problem(seed)
    result = solve_serial(snap, gangs)
    assert_result_valid(snap, gangs, result)


@pytest.mark.parametrize("seed", (0, 3, 6))
def test_engine_deterministic_per_seed(seed):
    snap, gangs = random_problem(seed)
    r1 = PlacementEngine(snap).solve(gangs)
    r2 = PlacementEngine(snap).solve(gangs)
    assert set(r1.placed) == set(r2.placed)
    for name in r1.placed:
        np.testing.assert_array_equal(
            r1.placed[name].node_indices, r2.placed[name].node_indices
        )


@pytest.mark.parametrize("seed", (1, 4))
def test_sharded_engine_satisfies_hard_contract(seed):
    from grove_tpu.parallel import ShardedPlacementEngine, make_solver_mesh

    snap, gangs = random_problem(seed)
    mesh = make_solver_mesh()
    result = ShardedPlacementEngine(snap, mesh).solve(gangs)
    assert_result_valid(snap, gangs, result)
    single = PlacementEngine(snap).solve(gangs)
    assert set(result.placed) == set(single.placed)


@pytest.mark.parametrize("seed", (0, 2, 5, 7))
def test_native_serial_matches_python_on_random_problems(seed):
    from grove_tpu.native import native_available, solve_serial_native

    if not native_available():
        pytest.skip("no native toolchain")
    snap, gangs = random_problem(seed)
    for g in gangs:
        # the C++ baseline does not implement gang-level PREFERRED packing
        # (a soft node-choice policy); strip it so both paths make
        # identical choices and knock-on feasibility stays comparable
        g.preferred_level = -1
    nat = solve_serial_native(snap, gangs)
    ser = solve_serial(snap, gangs)
    assert nat is not None
    assert_result_valid(snap, gangs, nat)
    assert set(nat.placed) == set(ser.placed)
    for name in nat.placed:
        np.testing.assert_array_equal(
            nat.placed[name].node_indices, ser.placed[name].node_indices
        )
