"""End-to-end slice: apply a PodCliqueSet -> reconcile -> gangs -> bound,
ready pods (the samples/simple/simple1.yaml quickstart of the reference,
driven against the simulated cluster)."""

import pytest

from grove_tpu.api import constants
from grove_tpu.api.meta import ObjectMeta, get_condition
from grove_tpu.api.podgang import PodGang, PodGangPhase
from grove_tpu.api.types import (
    Container,
    Pod,
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueSet,
    PodCliqueSetSpec,
    PodCliqueSetTemplateSpec,
    PodCliqueScalingGroupConfig,
    PodCliqueSpec,
    PodCliqueTemplateSpec,
    PodSpec,
)
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness


def clique(name, replicas=2, min_available=None, cpu=1.0, starts_after=()):
    return PodCliqueTemplateSpec(
        name=name,
        spec=PodCliqueSpec(
            replicas=replicas,
            min_available=min_available,
            starts_after=list(starts_after),
            pod_spec=PodSpec(
                containers=[Container(name="main", resources={"cpu": cpu})]
            ),
        ),
    )


def simple_pcs(name="simple1", replicas=1, cliques=None, sgs=None, startup=None):
    return PodCliqueSet(
        metadata=ObjectMeta(name=name),
        spec=PodCliqueSetSpec(
            replicas=replicas,
            template=PodCliqueSetTemplateSpec(
                cliques=cliques or [clique("fe"), clique("be")],
                pod_clique_scaling_group_configs=sgs or [],
                startup_type=startup,
            ),
        ),
    )


@pytest.fixture
def harness():
    return Harness(nodes=make_nodes(16, racks_per_block=2, hosts_per_rack=4))


class TestSimpleEndToEnd:
    def test_pods_created_gated_then_bound_and_ready(self, harness):
        harness.apply(simple_pcs())
        harness.settle()
        pods = harness.store.list(Pod.KIND)
        assert len(pods) == 4  # 2 cliques x 2 replicas
        assert all(p.node_name for p in pods), "all pods bound"
        assert all(not p.spec.scheduling_gates for p in pods)
        assert all(p.status.ready for p in pods)

    def test_podcliques_and_podgang_created(self, harness):
        harness.apply(simple_pcs())
        harness.settle()
        pclqs = harness.store.list(PodClique.KIND)
        assert sorted(p.metadata.name for p in pclqs) == [
            "simple1-0-be", "simple1-0-fe",
        ]
        gangs = harness.store.list(PodGang.KIND)
        assert [g.metadata.name for g in gangs] == ["simple1-0"]
        gang = gangs[0]
        assert gang.status.phase == PodGangPhase.RUNNING
        assert gang.status.placement_score is not None
        assert {gr.name for gr in gang.spec.pod_groups} == {
            "simple1-0-fe", "simple1-0-be",
        }
        # all pods referenced
        assert sum(len(gr.pod_references) for gr in gang.spec.pod_groups) == 4

    def test_env_hostname_subdomain_wiring(self, harness):
        harness.apply(simple_pcs())
        harness.settle()
        pod = harness.store.get(Pod.KIND, "default", "simple1-0-fe-0")
        assert pod.spec.hostname == "simple1-0-fe-0"
        assert pod.spec.subdomain == "simple1-0"
        env = pod.spec.containers[0].env
        assert env[constants.ENV_PCS_NAME] == "simple1"
        assert env[constants.ENV_PCLQ_NAME] == "simple1-0-fe"
        assert env[constants.ENV_PCLQ_POD_INDEX] == "0"
        svc = harness.store.get("Service", "default", "simple1-0")
        assert svc is not None and svc.publish_not_ready_addresses

    def test_multi_replica_creates_per_replica_trees(self, harness):
        harness.apply(simple_pcs(replicas=2))
        harness.settle()
        assert len(harness.store.list(PodClique.KIND)) == 4
        gangs = sorted(g.metadata.name for g in harness.store.list(PodGang.KIND))
        assert gangs == ["simple1-0", "simple1-1"]
        assert len(harness.store.list(Pod.KIND)) == 8

    def test_status_counts(self, harness):
        harness.apply(simple_pcs())
        harness.settle()
        pclq = harness.store.get(PodClique.KIND, "default", "simple1-0-fe")
        s = pclq.status
        assert (s.replicas, s.ready_replicas, s.scheduled_replicas,
                s.schedule_gated_replicas) == (2, 2, 2, 0)
        cond = get_condition(s.conditions, constants.CONDITION_PODCLIQUE_SCHEDULED)
        assert cond.status == "True"
        pcs = harness.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.available_replicas == 1

    def test_delete_cascades(self, harness):
        harness.apply(simple_pcs())
        harness.settle()
        harness.store.delete(PodCliqueSet.KIND, "default", "simple1")
        harness.settle()
        assert harness.store.get(PodCliqueSet.KIND, "default", "simple1") is None
        assert harness.store.list(Pod.KIND) == []
        assert harness.store.list(PodClique.KIND) == []
        assert harness.store.list(PodGang.KIND) == []


class TestScalingGroupEndToEnd:
    def pcs(self):
        return simple_pcs(
            name="dis",
            cliques=[clique("router", replicas=1),
                     clique("prefill", replicas=2),
                     clique("decode", replicas=2)],
            sgs=[PodCliqueScalingGroupConfig(
                name="workers", clique_names=["prefill", "decode"],
                replicas=3, min_available=2)],
        )

    def test_base_and_scaled_gangs(self, harness):
        harness.apply(self.pcs())
        harness.settle()
        gangs = {g.metadata.name: g for g in harness.store.list(PodGang.KIND)}
        # base gang + one scaled gang (replicas 3, minAvailable 2)
        assert sorted(gangs) == ["dis-0", "dis-0-workers-0"]
        base = gangs["dis-0"]
        group_names = {gr.name for gr in base.spec.pod_groups}
        assert group_names == {
            "dis-0-router",
            "dis-0-workers-0-prefill", "dis-0-workers-0-decode",
            "dis-0-workers-1-prefill", "dis-0-workers-1-decode",
        }
        scaled = gangs["dis-0-workers-0"]
        assert {gr.name for gr in scaled.spec.pod_groups} == {
            "dis-0-workers-2-prefill", "dis-0-workers-2-decode",
        }
        assert (scaled.metadata.labels[constants.LABEL_BASE_PODGANG] == "dis-0")

    def test_pcsg_env_wiring(self, harness):
        """PCSG-owned pods carry the group env trio, incl. the template pod
        count (pcsg/components/podclique/podclique.go:214-228,303-330)."""
        harness.apply(self.pcs())
        harness.settle()
        pod = harness.store.get(Pod.KIND, "default", "dis-0-workers-1-prefill-0")
        env = pod.spec.containers[0].env
        assert env[constants.ENV_PCSG_NAME] == "dis-0-workers"
        assert env[constants.ENV_PCSG_INDEX] == "1"
        # prefill(2) + decode(2) pods per PCSG replica template
        assert env[constants.ENV_PCSG_TEMPLATE_NUM_PODS] == "4"
        # standalone pods carry no PCSG env
        router = harness.store.get(Pod.KIND, "default", "dis-0-router-0")
        assert constants.ENV_PCSG_NAME not in router.spec.containers[0].env

    def test_all_pods_bound_and_pcsg_status(self, harness):
        harness.apply(self.pcs())
        harness.settle()
        pods = harness.store.list(Pod.KIND)
        # router 1 + 3 pcsg replicas x (2 prefill + 2 decode) = 13
        assert len(pods) == 13
        assert all(p.node_name and p.status.ready for p in pods)
        pcsg = harness.store.get(PodCliqueScalingGroup.KIND, "default",
                                 "dis-0-workers")
        assert pcsg.status.replicas == 3
        assert pcsg.status.scheduled_replicas == 3
        assert pcsg.status.available_replicas == 3


class TestNodeSelectorEndToEnd:
    """node_selector/tolerations enforced through the full control plane
    (reference: the delegated scheduler honors the embedded corev1.PodSpec,
    operator/api/core/v1alpha1/podclique.go:60-63)."""

    def harness_with_accel(self, accel_count=4, total=8):
        nodes = make_nodes(total, racks_per_block=2, hosts_per_rack=4)
        accel = set()
        for n in nodes[:accel_count]:
            n.metadata.labels["accel"] = "v5"
            accel.add(n.metadata.name)
        return Harness(nodes=nodes), accel

    def selector_pcs(self, selector, cpu=1.0):
        cl = clique("fe", replicas=2, cpu=cpu)
        cl.spec.pod_spec.node_selector = dict(selector)
        return simple_pcs(cliques=[cl, clique("be", replicas=1, cpu=cpu)])

    def test_selector_pods_land_on_matching_nodes(self):
        harness, accel = self.harness_with_accel()
        harness.apply(self.selector_pcs({"accel": "v5"}))
        harness.settle()
        pods = harness.store.list(Pod.KIND)
        assert all(p.node_name for p in pods)
        for p in pods:
            if p.spec.node_selector:
                assert p.node_name in accel, p.metadata.name

    def test_impossible_selector_holds_the_whole_gang(self):
        harness, _ = self.harness_with_accel(accel_count=0)
        harness.apply(self.selector_pcs({"accel": "v5"}))
        harness.settle()
        # all-or-nothing: the selector-bound clique cannot land anywhere,
        # so NO pod of the gang binds and the gang reports Unschedulable
        pods = harness.store.list(Pod.KIND)
        assert pods and all(not p.node_name for p in pods)
        gang = harness.store.list(PodGang.KIND)[0]
        cond = get_condition(gang.status.conditions, "Scheduled")
        assert cond is not None and cond.status == "False"
        # the condition carries the STRUCTURED reason code (explain.py):
        # the selector excludes every node, so eligibility is the verdict
        assert cond.reason == "EligibilityExcluded"
        assert "eligibility" in cond.message

    def test_tainted_nodes_repel_untolerated_pods(self):
        nodes = make_nodes(8, racks_per_block=2, hosts_per_rack=4)
        for n in nodes[:6]:
            n.taints = ["reserved"]
        harness = Harness(nodes=nodes)
        harness.apply(simple_pcs())
        harness.settle()
        pods = harness.store.list(Pod.KIND)
        untainted = {n.metadata.name for n in nodes[6:]}
        assert all(p.node_name in untainted for p in pods), [
            (p.metadata.name, p.node_name) for p in pods
        ]


class TestMultiNamespace:
    """Namespaces isolate workloads end to end: same-named objects in two
    namespaces coexist, selection/scheduling never crosses, and deleting
    one tree leaves the other untouched."""

    def test_same_names_in_two_namespaces(self):
        h = Harness(nodes=make_nodes(16))
        for ns in ("team-a", "team-b"):
            pcs = simple_pcs(cliques=[clique("w", replicas=2, cpu=1.0)])
            pcs.metadata.namespace = ns
            h.apply(pcs)
        h.settle()
        for ns in ("team-a", "team-b"):
            pods = h.store.list(Pod.KIND, namespace=ns)
            assert len(pods) == 2 and all(
                p.node_name and p.status.ready for p in pods
            )
            gang = h.store.get(PodGang.KIND, ns, "simple1-0")
            assert gang is not None
            assert all(
                ref.namespace == ns
                for gr in gang.spec.pod_groups
                for ref in gr.pod_references
            )
        # cascade delete one namespace's tree; the other is untouched
        h.store.delete(PodCliqueSet.KIND, "team-a", "simple1")
        h.settle()
        assert h.store.list(Pod.KIND, namespace="team-a") == []
        assert h.store.get(PodGang.KIND, "team-a", "simple1-0") is None
        b_pods = h.store.list(Pod.KIND, namespace="team-b")
        assert len(b_pods) == 2 and all(p.status.ready for p in b_pods)


class TestSchedulerNameRouting:
    """schedulerName routing: pods naming a foreign scheduler are never
    touched by the gang scheduler (the reference routes its pods to KAI
    by schedulerName the same way); empty or grove-tpu-scheduler is ours."""

    def foreign_pcs(self):
        pcs = simple_pcs(cliques=[clique("w", replicas=2, cpu=1.0)])
        for c in pcs.spec.template.cliques:
            c.spec.pod_spec.scheduler_name = "third-party-scheduler"
        return pcs

    def test_foreign_gang_is_left_to_its_scheduler(self):
        h = Harness(nodes=make_nodes(4))
        h.apply(self.foreign_pcs())
        h.settle()
        pods = h.store.list(Pod.KIND)
        # operator machinery ran (pods exist, ungated, gang created) but
        # OUR scheduler never bound them or wrote Unschedulable
        assert pods and all(not p.spec.scheduling_gates for p in pods)
        assert all(not p.node_name for p in pods)
        gang = h.store.get(PodGang.KIND, "default", "simple1-0")
        assert gang is not None
        assert get_condition(gang.status.conditions, "Scheduled") is None
        # an "external scheduler" binds them AND writes the PodGang
        # contract's status — exactly KAI's duty in the reference (gate
        # removal for scaled pods reads the base gang's Scheduled that the
        # OWNING scheduler writes, syncflow.go:306-345)
        from grove_tpu.api.meta import set_condition

        for i, p in enumerate(pods):
            h.store.bind_pod("default", p.metadata.name, f"node-{i}")

        def external_scheduled(status):
            set_condition(status.conditions, "Scheduled", "True",
                          reason="ExternallyPlaced", now=h.clock.now())

        h.store.patch_status(PodGang.KIND, "default", "simple1-0",
                             external_scheduled)
        h.settle()
        assert all(p.status.ready for p in h.store.list(Pod.KIND))

    def test_mixed_empty_and_foreign_scheduler_rejected(self):
        """Empty schedulerName counts as the framework's own in the
        single-name rule: mixing it with a foreign name would deadlock
        the gang (half its pods routed elsewhere)."""
        import pytest

        from grove_tpu.api.validation import ValidationError

        pcs = simple_pcs(cliques=[clique("a", replicas=1),
                                  clique("b", replicas=1)])
        pcs.spec.template.cliques[1].spec.pod_spec.scheduler_name = "kai"
        h = Harness(nodes=make_nodes(4))
        with pytest.raises(ValidationError) as err:
            h.apply(pcs)
        assert "single scheduler" in str(err.value)

    def test_explicit_grove_scheduler_name_is_ours(self):
        pcs = simple_pcs(cliques=[clique("w", replicas=2, cpu=1.0)])
        for c in pcs.spec.template.cliques:
            c.spec.pod_spec.scheduler_name = constants.SCHEDULER_NAME
        h = Harness(nodes=make_nodes(4))
        h.apply(pcs)
        h.settle()
        assert all(p.node_name and p.status.ready
                   for p in h.store.list(Pod.KIND))
