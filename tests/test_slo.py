"""SLO engine (grove_tpu/observability/slo.py): SLOConfig validation,
exact error-budget arithmetic, the multi-window burn-rate alert state
machine (pending -> firing -> resolved, with Events / counters / tenant
queue conditions), sampler-ring bounds, soft-state survival across
cold_restart, re-warm counter baselining, sweep cadence gating, the
scorecard surfaces (debug_dump, gRPC Debug, CLI), the shared verdict
vocabulary, and chaos interplay (alerts fire DURING the fault and
resolve after settle; seeds replay bit-identically with SLO on or off).
"""

import json

import pytest

from grove_tpu.api.config import load_operator_config
from grove_tpu.api.meta import get_condition
from grove_tpu.api.validation import ValidationError
from grove_tpu.chaos import ChaosHarness, FaultPlan, settled_fingerprint
from grove_tpu.cluster import make_nodes
from grove_tpu.cluster.clock import SimClock
from grove_tpu.controller import Harness
from grove_tpu.observability.metrics import MetricsRegistry
from grove_tpu.observability.slo import (
    ALERT_FIRING,
    ALERT_INACTIVE,
    ALERT_PENDING,
    ALERT_RESOLVED,
    SLO_VIOLATION_CONDITION,
    VERDICT_BREACH,
    VERDICT_BURNING,
    VERDICT_OK,
    SLOEngine,
    compose_scorecard,
    main as slo_main,
    render_scorecard,
    static_entry,
    worst_verdict,
)
from grove_tpu.service.server import PlacementService

from test_chaos import NODES, chaos_workload, quiet

#: tight windows sized to the 5s test sweep cadence. page_short equals
#: the cadence on purpose: the short window then holds exactly one SLI
#: sample, which makes trip/untrip transitions single-sweep-precise.
SLO_BASE = {
    "enabled": True,
    "sync_interval_seconds": 5.0,
    "budget_window_seconds": 120.0,
    "pending_for_seconds": 0.0,
    "page_short_seconds": 5.0,
    "page_long_seconds": 30.0,
    "page_burn_threshold": 5.0,
    "ticket_short_seconds": 30.0,
    "ticket_long_seconds": 90.0,
    "ticket_burn_threshold": 2.0,
}

SHED_OBJECTIVE = {
    "name": "shed-ceiling", "kind": "shed_rate",
    "target": 0.9, "ceiling_per_second": 1.0,
}


def slo_cfg(**over):
    return load_operator_config({"slo": {**SLO_BASE, **over}}).slo


def engine(**over):
    """A bare engine on its own registry + virtual clock (no Harness)."""
    registry = MetricsRegistry()
    clock = SimClock()
    return SLOEngine(slo_cfg(**over), registry, clock), registry, clock


# -- config validation --------------------------------------------------------

class TestSLOConfigValidation:
    def test_disabled_by_default(self):
        cfg = load_operator_config({}).slo
        assert cfg.enabled is False
        # defaults are themselves valid: enabling is a one-line change
        load_operator_config({"slo": {"enabled": True}})

    def test_valid_block_round_trips(self):
        cfg = slo_cfg(objectives=[SHED_OBJECTIVE])
        assert cfg.enabled
        assert cfg.sync_interval_seconds == 5.0
        assert cfg.objectives == [SHED_OBJECTIVE]

    @pytest.mark.parametrize("over,needle", [
        ({"sync_interval_seconds": 0}, "sync_interval_seconds"),
        ({"page_burn_threshold": -1.0}, "page_burn_threshold"),
        # inverted window pair: the long window must cover the short
        ({"page_short_seconds": 40.0}, "page_long_seconds"),
        ({"ticket_short_seconds": 91.0}, "ticket_long_seconds"),
        # budget accounting must cover the slowest alert window
        ({"budget_window_seconds": 50.0}, "budget_window_seconds"),
        ({"pending_for_seconds": -1.0}, "pending_for_seconds"),
        ({"max_samples_per_series": 0}, "max_samples_per_series"),
        ({"history_limit": 0}, "history_limit"),
        ({"objectives": "nope"}, "objectives: must be a list"),
        ({"objectives": [{"kind": "shed_rate"}]}, "name"),
        ({"objectives": [SHED_OBJECTIVE, SHED_OBJECTIVE]}, "duplicate"),
        ({"objectives": [{"name": "x", "kind": "wat"}]}, "unknown kind"),
        ({"objectives": [{"name": "x", "kind": "shed_rate",
                          "target": 1.5}]}, "target"),
        ({"objectives": [{"name": "x", "kind": "shed_rate",
                          "ceiling_per_second": 0}]}, "ceiling_per_second"),
        ({"objectives": [{"name": "x", "kind": "shed_rate",
                          "typo_field": 1}]}, "unknown field"),
        ({"objectives": [{"name": "x", "kind": "shed_rate",
                          "per_tenant": "yes"}]}, "per_tenant"),
        ({"objectives": [{"name": "x", "kind": "failover_wall",
                          "max_failovers": -1}]}, "max_failovers"),
    ])
    def test_invalid_blocks_rejected(self, over, needle):
        with pytest.raises(ValidationError, match=needle):
            slo_cfg(**over)


# -- budget arithmetic (acceptance: sums exactly) -----------------------------

class TestBudgetArithmetic:
    def run_sweeps(self, eng, registry, clock, bad_at):
        """10 sweeps at 5s cadence; shed hard during the sweeps in
        `bad_at` (rate 2.0/s over the 1.0/s ceiling -> one bad unit)."""
        sheds = registry.counter("grove_stream_shed_total", "")
        for i in range(10):
            if i > 0:
                clock.advance(5.0)
            if i in bad_at:
                sheds.inc(10.0)
            eng.sweep()

    def test_budget_sums_exactly(self):
        eng, registry, clock = engine(objectives=[SHED_OBJECTIVE])
        self.run_sweeps(eng, registry, clock, bad_at={4, 5})
        (entry,) = eng.scorecard()["slos"]
        s = entry["samples"]
        # probe SLI: one unit per sweep, and good + bad == total exactly
        assert s == {"good": 8.0, "bad": 2.0, "total": 10.0}
        b = entry["error_budget"]
        # target 0.9 over 10 units allows exactly 1 bad unit; 2 spent
        assert b["allowed_bad"] == pytest.approx(1.0)
        assert b["spent_bad"] == 2.0
        assert b["spent_fraction"] == pytest.approx(2.0)
        assert b["remaining_fraction"] == pytest.approx(-1.0)
        assert b["remaining_clamped"] == 0.0
        assert entry["verdict"] == VERDICT_BREACH

    def test_zero_traffic_spends_nothing(self):
        eng, registry, clock = engine(objectives=[
            {"name": "bind-p99", "kind": "bind_latency_p99",
             "target": 0.9, "threshold_seconds": 1.0},
        ])
        for _ in range(3):
            eng.sweep()
            clock.advance(5.0)
        (entry,) = eng.scorecard()["slos"]
        # a ratio SLI with no events has an empty budget, not a spent one
        assert entry["samples"]["total"] == 0
        assert entry["error_budget"]["spent_fraction"] == 0.0
        assert entry["error_budget"]["remaining_fraction"] == 1.0
        assert entry["verdict"] == VERDICT_OK

    def test_clean_run_keeps_full_budget(self):
        eng, registry, clock = engine(objectives=[SHED_OBJECTIVE])
        self.run_sweeps(eng, registry, clock, bad_at=set())
        (entry,) = eng.scorecard()["slos"]
        assert entry["samples"] == {"good": 10.0, "bad": 0.0, "total": 10.0}
        assert entry["error_budget"]["remaining_fraction"] == 1.0
        assert entry["verdict"] == VERDICT_OK
        g = registry.get("grove_slo_error_budget_remaining")
        assert g.value(slo="shed-ceiling") == 1.0


# -- alert state machine ------------------------------------------------------

class TestAlertStateMachine:
    def page_state(self, eng):
        return eng._alerts[("shed-ceiling", None, "page")]["state"]

    def test_pending_firing_resolved_lifecycle(self):
        eng, registry, clock = engine(objectives=[SHED_OBJECTIVE])
        sheds = registry.counter("grove_stream_shed_total", "")
        eng.sweep()  # t=0 baseline
        for _ in range(2):  # t=5, t=10: sustained over-ceiling shedding
            clock.advance(5.0)
            sheds.inc(10.0)
            eng.sweep()
        assert self.page_state(eng) == ALERT_FIRING
        assert eng.firing()  # and it is visible to the chaos drain gate
        c = registry.get("grove_slo_alerts_total")
        assert c.value(slo="shed-ceiling", severity="page") == 1.0
        for _ in range(2):  # recovery: the short page window forgets fast
            clock.advance(5.0)
            eng.sweep()
        assert self.page_state(eng) == ALERT_RESOLVED
        # the ticket pair's slower short window (30s) lags by design —
        # a few more quiet sweeps age the bad samples out of it
        for _ in range(6):
            if not eng.firing():
                break
            clock.advance(5.0)
            eng.sweep()
        assert eng.firing() == []
        states = [
            (h["severity"], h["from"], h["to"]) for h in eng.history
            if h["severity"] == "page"
        ]
        assert states == [
            ("page", ALERT_INACTIVE, ALERT_PENDING),
            ("page", ALERT_PENDING, ALERT_FIRING),
            ("page", ALERT_FIRING, ALERT_RESOLVED),
        ]

    def test_one_sample_spike_never_pages(self):
        # pending_for 0 still demands one strictly-later confirming
        # sweep: a single bad interval goes pending and falls back
        eng, registry, clock = engine(objectives=[SHED_OBJECTIVE])
        sheds = registry.counter("grove_stream_shed_total", "")
        eng.sweep()
        clock.advance(5.0)
        sheds.inc(10.0)
        eng.sweep()
        assert self.page_state(eng) == ALERT_PENDING
        clock.advance(5.0)
        eng.sweep()  # quiet interval: the spike never confirmed
        assert self.page_state(eng) == ALERT_INACTIVE
        c = registry.get("grove_slo_alerts_total")
        page_firings = (
            c.value(slo="shed-ceiling", severity="page") if c else 0.0
        )
        assert page_firings == 0.0
        assert [h["to"] for h in eng.history if h["severity"] == "page"] == [
            ALERT_PENDING, ALERT_INACTIVE,
        ]

    def test_burning_entry_verdict(self):
        # a wide budget window keeps allowed_bad above the burst the
        # page pair needs to trip: burning, not yet a breach
        eng, registry, clock = engine(
            objectives=[SHED_OBJECTIVE], budget_window_seconds=600.0,
        )
        sheds = registry.counter("grove_stream_shed_total", "")
        for i in range(30):  # a long good history
            clock.advance(5.0)
            eng.sweep()
        for _ in range(3):  # burst until the 30s page_long window trips
            sheds.inc(10.0)
            clock.advance(5.0)
            eng.sweep()
        (entry,) = eng.scorecard()["slos"]
        assert entry["alerts"]["page"]["state"] == ALERT_PENDING
        b = entry["error_budget"]
        assert b["spent_bad"] == 3.0 and b["spent_bad"] < b["allowed_bad"]
        assert entry["verdict"] == VERDICT_BURNING

    def test_rewarm_baselines_cumulative_counters(self):
        # a genuinely new process re-warms: first sight of a cumulative
        # counter baselines it (delta 0) — restarts never manufacture
        # alerts out of pre-existing totals
        registry = MetricsRegistry()
        registry.counter("grove_stream_shed_total", "").inc(1e6)
        clock = SimClock(start=500.0)
        eng = SLOEngine(slo_cfg(objectives=[SHED_OBJECTIVE]), registry, clock)
        for _ in range(3):
            eng.sweep()
            clock.advance(5.0)
        assert eng.firing() == []
        assert list(eng.history) == []
        (entry,) = eng.scorecard()["slos"]
        assert entry["samples"]["bad"] == 0.0

    def test_sampler_rings_stay_bounded(self):
        eng, registry, clock = engine(
            objectives=[SHED_OBJECTIVE], max_samples_per_series=8,
        )
        for _ in range(40):
            eng.sweep()
            clock.advance(5.0)
        assert all(len(r) <= 8 for r in eng._sli.values())
        assert all(len(r) <= 8 for r in eng._rings.values())


# -- harness integration: events, conditions, cadence, surfaces ---------------

TENANT_SLO_CONFIG = {
    "tenancy": {
        "enabled": True,
        "tenants": [{"name": "acme", "guaranteed": {"cpu": 4.0}}],
    },
    "slo": {
        **SLO_BASE,
        "objectives": [
            {"name": "bind-p99", "kind": "bind_latency_p99",
             "target": 0.9, "threshold_seconds": 1.0, "per_tenant": True},
        ],
    },
}


class TestHarnessIntegration:
    def slow_harness(self):
        h = Harness(nodes=make_nodes(4), config=TENANT_SLO_CONFIG)
        assert h.cluster.slo is not None
        return h

    def observe_slow_binds(self, h, n=10):
        hist = h.cluster.metrics.histogram(
            "grove_scheduler_tenant_bind_latency_seconds", ""
        )
        for _ in range(n):
            hist.observe(5.0, tenant="acme")

    def test_alert_emits_events_and_stamps_queue_condition(self):
        h = self.slow_harness()
        h.slo_sweep()  # baseline
        for _ in range(2):
            h.clock.advance(5.0)
            self.observe_slow_binds(h)
            h.slo_sweep()
        firing = h.cluster.slo.firing()
        assert {(f["slo"], f["tenant"]) for f in firing} == {
            ("bind-p99", "acme"),
        }
        q = h.cluster.tenancy.queues["acme"]
        cond = get_condition(q.conditions, SLO_VIOLATION_CONDITION)
        assert cond is not None and cond.status == "True"
        reasons = {e.reason for e in h.store.scan("Event")}
        assert "SLOBurnRate" in reasons
        # recovery: quiet sweeps resolve (the ticket pair's 30s short
        # window lags the page's), clear the condition, and emit the
        # recovered Event
        for _ in range(8):
            if not h.cluster.slo.firing():
                break
            h.clock.advance(5.0)
            h.slo_sweep()
        assert h.cluster.slo.firing() == []
        cond = get_condition(q.conditions, SLO_VIOLATION_CONDITION)
        assert cond.status == "False"
        assert "SLORecovered" in {e.reason for e in h.store.scan("Event")}

    def test_maybe_slo_sweep_honors_cadence(self):
        h = self.slow_harness()
        assert h.maybe_slo_sweep() is True  # first call always sweeps
        assert h.maybe_slo_sweep() is False  # inside the interval
        h.clock.advance(4.9)
        assert h.maybe_slo_sweep() is False
        h.clock.advance(0.2)
        assert h.maybe_slo_sweep() is True

    def test_disabled_harness_has_no_engine(self):
        h = Harness(nodes=make_nodes(2))
        assert getattr(h.cluster, "slo", None) is None
        assert h.slo_sweep() is None
        assert h.maybe_slo_sweep() is False
        assert h.slo_scorecard() == {"enabled": False}
        assert "slo" not in h.debug_dump()

    def test_scorecard_surfaces(self):
        h = self.slow_harness()
        h.slo_sweep()
        card = h.slo_scorecard()
        assert card["enabled"] and card["source"] == "engine"
        assert [e["slo"] for e in card["slos"]] == ["bind-p99"]
        assert h.debug_dump()["slo"] == card
        # the gRPC Debug service serves the same scorecard (injection
        # only; callable without a server)
        svc = PlacementService(slo=h.cluster.slo)
        dump = json.loads(PlacementService.debug(svc, b""))
        assert dump["slo"]["enabled"] is True
        assert [e["slo"] for e in dump["slo"]["slos"]] == ["bind-p99"]
        json.dumps(card)  # JSON-safe end to end

    def test_engine_survives_cold_restart(self, tmp_path):
        config = {
            **TENANT_SLO_CONFIG,
            "durability": {
                "fsync": "never", "snapshot_interval_seconds": 30.0,
                "wal_max_bytes": 65536, "wal_dir": str(tmp_path / "wal"),
            },
        }
        h = Harness(nodes=make_nodes(4), config=config)
        eng = h.cluster.slo
        h.slo_sweep()
        for _ in range(2):
            h.clock.advance(5.0)
            self.observe_slow_binds(h)
            h.slo_sweep()
        history_before = list(eng.history)
        assert eng.firing()
        stats = h.cold_restart()
        assert stats["outcome"] == "clean"
        # soft state: the engine object rides the cluster through the
        # restart with rings, alert state and history intact
        assert h.cluster.slo is eng
        assert list(eng.history) == history_before
        # and post-restart sweeps still work (Events now target the
        # recovered store) — quiet intervals resolve the alert
        for _ in range(8):
            if not eng.firing():
                break
            h.clock.advance(5.0)
            h.slo_sweep()
        assert eng.firing() == []


# -- shared verdict vocabulary (bench rides the same schema) ------------------

class TestVerdictVocabulary:
    def test_worst_verdict_ranks(self):
        assert worst_verdict([]) == VERDICT_OK
        assert worst_verdict([VERDICT_OK, VERDICT_BURNING]) == VERDICT_BURNING
        assert worst_verdict(
            [VERDICT_BURNING, VERDICT_BREACH, VERDICT_OK]
        ) == VERDICT_BREACH

    def test_static_entry_thresholds(self):
        bad = static_entry("p99", "bind_latency_p99", 31.0, threshold=30.0,
                           unit="seconds")
        assert bad["verdict"] == VERDICT_BREACH
        ok = static_entry("p99", "bind_latency_p99", 29.0, threshold=30.0)
        assert ok["verdict"] == VERDICT_OK
        # higher_is_better flips the comparison (sustained-rate floors)
        rate = static_entry("rate", "sustained_rate", 4.0, threshold=5.0,
                            higher_is_better=True)
        assert rate["verdict"] == VERDICT_BREACH

    def test_compose_scorecard_envelope(self):
        card = compose_scorecard([
            static_entry("a", "shed_count", 0.0),
            static_entry("b", "bind_latency_p99", 2.0, threshold=1.0),
        ])
        assert card["source"] == "static"
        assert card["verdict"] == VERDICT_BREACH
        rendered = render_scorecard(card)
        assert "BREACH" in rendered and "a" in rendered

    def test_cli_renders_scorecard_files(self, tmp_path, capsys):
        h = Harness(nodes=make_nodes(2), config={"slo": SLO_BASE})
        h.slo_sweep()
        path = tmp_path / "card.json"
        path.write_text(json.dumps({"seeds": {"0": h.slo_scorecard()}}))
        assert slo_main([str(path)]) == 0
        out = capsys.readouterr().out
        assert "== 0 ==" in out and "verdict=" in out
        assert slo_main([str(path), "--json"]) == 0
        json.loads(capsys.readouterr().out)
        assert slo_main(["--demo"]) == 0


# -- chaos interplay (acceptance: lifecycle under fault, bit-identity) --------

#: chaos-sized SLO config (scripts/chaos_sweep.py SLO_CONFIG shape):
#: windows sized to the 2s chaos step and the post-storm drain
CHAOS_SLO = {
    "enabled": True,
    "sync_interval_seconds": 4.0,
    "budget_window_seconds": 600.0,
    "pending_for_seconds": 0.0,
    "page_short_seconds": 8.0,
    "page_long_seconds": 24.0,
    "page_burn_threshold": 5.0,
    "ticket_short_seconds": 24.0,
    "ticket_long_seconds": 80.0,
    "ticket_burn_threshold": 2.0,
    "objectives": [
        # wall sized to the plain chaos workload's 80s storm: it places
        # fast when healthy, so 10s of backlog is already a real stall
        # (scripts/chaos_sweep.py gates the production 30s wall against
        # its bigger storm workloads)
        {"name": "starvation", "kind": "starvation",
         "target": 0.98, "max_starved_seconds": 10.0},
        {"name": "failover-wall", "kind": "failover_wall",
         "target": 0.999, "max_failovers": 0},
    ],
}

CHAOS_SLO_SEED = 3


def run_chaos_seed(seed, slo):
    ch = quiet(ChaosHarness(
        FaultPlan.from_seed(seed),
        nodes=make_nodes(NODES),
        config={"slo": CHAOS_SLO} if slo else None,
    ))
    ch.apply(chaos_workload())
    ch.run_chaos()
    return ch


@pytest.mark.chaos
class TestChaosInterplay:
    def test_seed_replays_bit_identically_with_slo_enabled(self):
        """The acceptance invariant: SLO sweeps consume ZERO fault-plan
        draws (Events ride the raw store), so a pre-existing seed's
        fault sequence and settled state are bit-identical with the
        evaluator on or off."""
        plain = run_chaos_seed(CHAOS_SLO_SEED, slo=False)
        with_slo = run_chaos_seed(CHAOS_SLO_SEED, slo=True)
        assert with_slo.plan.counts == plain.plan.counts
        assert with_slo.manager_restarts == plain.manager_restarts
        assert settled_fingerprint(with_slo.raw_store) == (
            settled_fingerprint(plain.raw_store)
        )

    def test_alerts_fire_during_fault_and_resolve_after_settle(self):
        """The lifecycle gate: a violated SLO's alert must reach firing
        DURING the storm (sweeps run through it on their cadence), and
        the post-settle drain must resolve every one."""
        ch = run_chaos_seed(CHAOS_SLO_SEED, slo=True)
        eng = ch.harness.cluster.slo
        fired = [h for h in eng.history if h["to"] == ALERT_FIRING]
        assert fired, "no alert fired during the fault storm"
        # drain on the sweep cadence until every alert resolves
        for _ in range(80):
            if not eng.firing():
                break
            ch.clock.advance(4.0)
            ch.harness.slo_sweep(store=ch.raw_store)
        assert eng.firing() == [], (
            f"alerts failed to resolve after settle: {eng.firing()}"
        )
        resolved = [h for h in eng.history if h["to"] == ALERT_RESOLVED]
        assert resolved
        # and the postmortem artifact reflects the episode
        card = ch.harness.slo_scorecard()
        assert card["alert_history"]
