"""Native serial scorer parity vs the Python serial baseline."""

import numpy as np
import pytest

from grove_tpu.native import native_available, solve_serial_native
from grove_tpu.solver import solve_serial

from test_solver import cluster, gang

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no native toolchain"
)


def backlog():
    return [
        gang("a", pods=2, cpu=2.0),
        gang("b", pods=4, cpu=6.0, required=1),
        gang("c", pods=3, cpu=3.0),
        gang("d", pods=4, cpu=6.0,
             group_levels=[(2, 1, -1), (2, 1, -1)], required=0),
        gang("infeasible", pods=4, cpu=9.0),
        gang("prio", pods=1, cpu=8.0, priority=5.0),
    ]


class TestNativeParity:
    def test_matches_python_serial(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = backlog()
        py = solve_serial(snap, gangs)
        cc = solve_serial_native(snap, gangs)
        assert cc is not None
        assert set(cc.placed) == set(py.placed)
        assert set(cc.unplaced) == set(py.unplaced)
        for name in py.placed:
            np.testing.assert_array_equal(
                cc.placed[name].node_indices, py.placed[name].node_indices
            )
            assert cc.placed[name].placement_score == pytest.approx(
                py.placed[name].placement_score
            )

    def test_capacity_respected_under_contention(self):
        snap = cluster(blocks=1, racks=2, hosts=2, cpu=8.0)
        gangs = [gang(f"g{i}", pods=2, cpu=8.0, required=1) for i in range(3)]
        cc = solve_serial_native(snap, gangs)
        py = solve_serial(snap, gangs)
        assert set(cc.placed) == set(py.placed)
        used = np.zeros_like(snap.free)
        for p in cc.placed.values():
            for j, n in enumerate(p.node_indices):
                used[n] += p.gang.demand[j]
        assert (used <= snap.free + 1e-6).all()

    def test_cordoned_nodes_skipped(self):
        snap = cluster(blocks=1, racks=1, hosts=2, cpu=8.0)
        snap.schedulable[0] = False
        cc = solve_serial_native(snap, [gang("a", pods=1, cpu=2.0)])
        assert list(cc.placed["a"].node_indices) == [1]


class TestNativeRepairParity:
    def test_engine_native_repair_matches_python_repair(self):
        snap = cluster(blocks=2, racks=4, hosts=4, cpu=8.0)
        gangs = [
            gang(f"g{i}", pods=2, cpu=4.0, tpu=2.0, required=1) for i in range(8)
        ] + [
            gang("lw", pods=4, cpu=6.0,
                 group_levels=[(2, 1, -1), (2, 1, -1)], required=0),
            gang("big", pods=6, cpu=5.0),
        ]
        from grove_tpu.solver import PlacementEngine

        nat = PlacementEngine(snap, native_repair=True).solve(gangs)
        py = PlacementEngine(snap, native_repair=False).solve(gangs)
        assert set(nat.placed) == set(py.placed)
        for name in py.placed:
            np.testing.assert_array_equal(
                nat.placed[name].node_indices, py.placed[name].node_indices
            )
        assert nat.stats["fallbacks"] == py.stats["fallbacks"]


def test_native_holds_predeclared_unschedulable_gangs():
    """A gang whose required pack level is unresolved must be HELD by the
    native path with its reason, never weakened to best-effort (parity
    with solve_serial; review finding)."""
    import numpy as np
    import pytest

    from grove_tpu.native import native_available, solve_serial_native
    from grove_tpu.solver import SolverGang
    from grove_tpu.solver.problem import UNRESOLVED_LEVEL

    from test_solver import cluster, gang

    if not native_available():
        pytest.skip("no native toolchain")
    snap = cluster()
    held = gang("held", pods=2, cpu=1.0)
    held.required_level = UNRESOLVED_LEVEL
    held.unschedulable_reason = "required topology level(s) unavailable: zone"
    ok = gang("ok", pods=2, cpu=1.0)
    res = solve_serial_native(snap, [held, ok])
    assert res is not None
    assert res.unplaced == {
        "held": "required topology level(s) unavailable: zone"
    }
    assert set(res.placed) == {"ok"}
