"""Native serial scorer parity vs the Python serial baseline."""

import numpy as np
import pytest

from grove_tpu.native import native_available, solve_serial_native
from grove_tpu.solver import solve_serial

from test_solver import cluster, gang

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no native toolchain"
)


def backlog():
    return [
        gang("a", pods=2, cpu=2.0),
        gang("b", pods=4, cpu=6.0, required=1),
        gang("c", pods=3, cpu=3.0),
        gang("d", pods=4, cpu=6.0,
             group_levels=[(2, 1, -1), (2, 1, -1)], required=0),
        gang("infeasible", pods=4, cpu=9.0),
        gang("prio", pods=1, cpu=8.0, priority=5.0),
    ]


class TestNativeParity:
    def test_matches_python_serial(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = backlog()
        py = solve_serial(snap, gangs)
        cc = solve_serial_native(snap, gangs)
        assert cc is not None
        assert set(cc.placed) == set(py.placed)
        assert set(cc.unplaced) == set(py.unplaced)
        for name in py.placed:
            np.testing.assert_array_equal(
                cc.placed[name].node_indices, py.placed[name].node_indices
            )
            assert cc.placed[name].placement_score == pytest.approx(
                py.placed[name].placement_score
            )

    def test_capacity_respected_under_contention(self):
        snap = cluster(blocks=1, racks=2, hosts=2, cpu=8.0)
        gangs = [gang(f"g{i}", pods=2, cpu=8.0, required=1) for i in range(3)]
        cc = solve_serial_native(snap, gangs)
        py = solve_serial(snap, gangs)
        assert set(cc.placed) == set(py.placed)
        used = np.zeros_like(snap.free)
        for p in cc.placed.values():
            for j, n in enumerate(p.node_indices):
                used[n] += p.gang.demand[j]
        assert (used <= snap.free + 1e-6).all()

    def test_cordoned_nodes_skipped(self):
        snap = cluster(blocks=1, racks=1, hosts=2, cpu=8.0)
        snap.schedulable[0] = False
        cc = solve_serial_native(snap, [gang("a", pods=1, cpu=2.0)])
        assert list(cc.placed["a"].node_indices) == [1]


class TestNativeRepairParity:
    def test_engine_native_repair_matches_python_repair(self):
        snap = cluster(blocks=2, racks=4, hosts=4, cpu=8.0)
        gangs = [
            gang(f"g{i}", pods=2, cpu=4.0, tpu=2.0, required=1) for i in range(8)
        ] + [
            gang("lw", pods=4, cpu=6.0,
                 group_levels=[(2, 1, -1), (2, 1, -1)], required=0),
            gang("big", pods=6, cpu=5.0),
        ]
        from grove_tpu.solver import PlacementEngine

        nat = PlacementEngine(snap, native_repair=True).solve(gangs)
        py = PlacementEngine(snap, native_repair=False).solve(gangs)
        assert set(nat.placed) == set(py.placed)
        for name in py.placed:
            np.testing.assert_array_equal(
                nat.placed[name].node_indices, py.placed[name].node_indices
            )
        assert nat.stats["fallbacks"] == py.stats["fallbacks"]


def test_native_holds_predeclared_unschedulable_gangs():
    """A gang whose required pack level is unresolved must be HELD by the
    native path with its reason, never weakened to best-effort (parity
    with solve_serial; review finding)."""
    import numpy as np
    import pytest

    from grove_tpu.native import native_available, solve_serial_native
    from grove_tpu.solver import SolverGang
    from grove_tpu.solver.problem import UNRESOLVED_LEVEL

    from test_solver import cluster, gang

    if not native_available():
        pytest.skip("no native toolchain")
    snap = cluster()
    held = gang("held", pods=2, cpu=1.0)
    held.required_level = UNRESOLVED_LEVEL
    held.unschedulable_reason = "required topology level(s) unavailable: zone"
    ok = gang("ok", pods=2, cpu=1.0)
    res = solve_serial_native(snap, [held, ok])
    assert res is not None
    assert res.unplaced == {
        "held": "required topology level(s) unavailable: zone"
    }
    assert set(res.placed) == {"ok"}


def grouped_gang(name, group_sizes, cg=None, cpu=2.0, required=-1,
                 preferred=-1, group_req=None, group_pref=None, priority=0.0):
    """Gang with explicit per-group sizes, optional constraint groups
    (cg: list of (member group indices, req, pref)) and per-group
    required/preferred levels."""
    from grove_tpu.solver import SolverGang

    n_groups = len(group_sizes)
    group_req = group_req or [-1] * n_groups
    group_pref = group_pref or [-1] * n_groups
    demand, gids = [], []
    for gi, cnt in enumerate(group_sizes):
        for _ in range(cnt):
            demand.append([cpu, 1.0, 0.0])
            gids.append(gi)
    return SolverGang(
        name=name,
        namespace="default",
        demand=np.asarray(demand, dtype=np.float32),
        pod_names=[f"{name}-p{i}" for i in range(len(demand))],
        group_ids=np.asarray(gids, dtype=np.int32),
        group_names=[f"g{i}" for i in range(n_groups)],
        group_required_level=np.asarray(group_req, dtype=np.int32),
        group_preferred_level=np.asarray(group_pref, dtype=np.int32),
        required_level=required,
        preferred_level=preferred,
        priority=priority,
        constraint_groups=list(cg or []),
    )


def _assert_identical(cc, py):
    assert cc is not None
    assert set(cc.placed) == set(py.placed)
    assert set(cc.unplaced) == set(py.unplaced)
    for name in py.placed:
        np.testing.assert_array_equal(
            cc.placed[name].node_indices, py.placed[name].node_indices
        )
        assert cc.placed[name].placement_score == pytest.approx(
            py.placed[name].placement_score
        )


class TestNativeGroupedParity:
    """Round-4 coverage (VERDICT r3 #3): constraint groups and PREFERRED
    levels — the leader/worker PCSG shape (reference README.md:38-44) —
    must take the native path with placements identical to fit.py."""

    def test_constraint_group_parity(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [
            # prefill/decode pair: each group rack-packed, the PAIR
            # block-packed via a constraint group
            grouped_gang("lw0", [3, 3], cg=[([0, 1], 0, -1)],
                         group_req=[1, 1]),
            grouped_gang("lw1", [2, 2], cg=[([0, 1], 0, 1)],
                         group_req=[1, 1]),
            grouped_gang("plain", [4]),
        ]
        _assert_identical(
            solve_serial_native(snap, gangs), solve_serial(snap, gangs)
        )

    def test_group_preferred_parity(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [
            grouped_gang("p0", [4, 2], group_pref=[1, -1]),
            grouped_gang("p1", [2, 2], group_req=[0, -1], group_pref=[1, 1]),
        ]
        _assert_identical(
            solve_serial_native(snap, gangs), solve_serial(snap, gangs)
        )

    def test_gang_preferred_parity(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [
            grouped_gang("gp0", [6], required=0, preferred=1),
            grouped_gang("gp1", [4], preferred=0),
        ]
        _assert_identical(
            solve_serial_native(snap, gangs), solve_serial(snap, gangs)
        )

    def test_engine_repair_grouped_no_fallback_divergence(self):
        """The engine's native repair must accept grouped gangs (no
        Python-path fallback) and match the Python repair placements."""
        from grove_tpu.solver import PlacementEngine
        from grove_tpu.native.serial_native import gang_native_compatible

        snap = cluster(blocks=2, racks=4, hosts=4, cpu=8.0)
        gangs = [
            grouped_gang(f"lw{i}", [2, 2], cg=[([0, 1], 0, -1)],
                         group_req=[1, 1], cpu=3.0)
            for i in range(6)
        ] + [
            grouped_gang(f"pref{i}", [4], required=0, preferred=1, cpu=2.0)
            for i in range(4)
        ]
        assert all(gang_native_compatible(g) for g in gangs)
        nat = PlacementEngine(snap, native_repair=True).solve(gangs)
        py = PlacementEngine(snap, native_repair=False).solve(gangs)
        assert set(nat.placed) == set(py.placed) == {g.name for g in gangs}
        for name in py.placed:
            np.testing.assert_array_equal(
                nat.placed[name].node_indices, py.placed[name].node_indices
            )
        assert nat.stats["fallbacks"] == py.stats["fallbacks"]

    def test_fuzz_grouped_parity(self):
        """Randomized grouped backlogs: native serial == Python serial,
        placement for placement."""
        rng = np.random.default_rng(42)
        for trial in range(25):
            snap = cluster(
                blocks=int(rng.integers(1, 3)),
                racks=int(rng.integers(1, 4)),
                hosts=int(rng.integers(2, 5)),
                cpu=float(rng.integers(4, 10)),
            )
            gangs = []
            for i in range(int(rng.integers(2, 7))):
                n_groups = int(rng.integers(1, 4))
                sizes = [int(rng.integers(1, 4)) for _ in range(n_groups)]
                group_req = [int(rng.integers(-1, 3)) for _ in range(n_groups)]
                group_pref = [int(rng.integers(-1, 3)) for _ in range(n_groups)]
                cg = []
                if n_groups >= 2 and rng.random() < 0.5:
                    members = list(range(int(rng.integers(2, n_groups + 1))))
                    cg = [(members, int(rng.integers(-1, 2)),
                           int(rng.integers(-1, 3)))]
                gangs.append(
                    grouped_gang(
                        f"t{trial}g{i}", sizes, cg=cg,
                        cpu=float(rng.integers(1, 5)),
                        required=int(rng.integers(-1, 2)),
                        preferred=int(rng.integers(-1, 3)),
                        group_req=group_req, group_pref=group_pref,
                        priority=float(rng.integers(0, 3)),
                    )
                )
            _assert_identical(
                solve_serial_native(snap, gangs), solve_serial(snap, gangs)
            )


def test_multiple_constraint_groups_parity():
    """Two disjoint constraint groups in one gang (e.g. prefill-pair +
    decode-pair co-location islands) place identically to fit.py."""
    snap = cluster(blocks=2, racks=3, hosts=4, cpu=10.0)
    gangs = [
        grouped_gang(
            "multi", [2, 2, 2, 2],
            cg=[([0, 1], 0, 1), ([2, 3], 0, -1)],
            group_req=[1, 1, 1, 1],
            cpu=2.0,
        ),
        grouped_gang("bg", [3], cpu=1.0),
    ]
    _assert_identical(
        solve_serial_native(snap, gangs), solve_serial(snap, gangs)
    )
