"""Native serial scorer parity vs the Python serial baseline."""

import numpy as np
import pytest

from grove_tpu.native import native_available, solve_serial_native
from grove_tpu.solver import solve_serial

from test_solver import cluster, gang

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no native toolchain"
)


def backlog():
    return [
        gang("a", pods=2, cpu=2.0),
        gang("b", pods=4, cpu=6.0, required=1),
        gang("c", pods=3, cpu=3.0),
        gang("d", pods=4, cpu=6.0,
             group_levels=[(2, 1, -1), (2, 1, -1)], required=0),
        gang("infeasible", pods=4, cpu=9.0),
        gang("prio", pods=1, cpu=8.0, priority=5.0),
    ]


class TestNativeParity:
    def test_matches_python_serial(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = backlog()
        py = solve_serial(snap, gangs)
        cc = solve_serial_native(snap, gangs)
        assert cc is not None
        assert set(cc.placed) == set(py.placed)
        assert set(cc.unplaced) == set(py.unplaced)
        for name in py.placed:
            np.testing.assert_array_equal(
                cc.placed[name].node_indices, py.placed[name].node_indices
            )
            assert cc.placed[name].placement_score == pytest.approx(
                py.placed[name].placement_score
            )

    def test_capacity_respected_under_contention(self):
        snap = cluster(blocks=1, racks=2, hosts=2, cpu=8.0)
        gangs = [gang(f"g{i}", pods=2, cpu=8.0, required=1) for i in range(3)]
        cc = solve_serial_native(snap, gangs)
        py = solve_serial(snap, gangs)
        assert set(cc.placed) == set(py.placed)
        used = np.zeros_like(snap.free)
        for p in cc.placed.values():
            for j, n in enumerate(p.node_indices):
                used[n] += p.gang.demand[j]
        assert (used <= snap.free + 1e-6).all()

    def test_cordoned_nodes_skipped(self):
        snap = cluster(blocks=1, racks=1, hosts=2, cpu=8.0)
        snap.schedulable[0] = False
        cc = solve_serial_native(snap, [gang("a", pods=1, cpu=2.0)])
        assert list(cc.placed["a"].node_indices) == [1]


class TestNativeRepairParity:
    def test_engine_native_repair_matches_python_repair(self):
        snap = cluster(blocks=2, racks=4, hosts=4, cpu=8.0)
        gangs = [
            gang(f"g{i}", pods=2, cpu=4.0, tpu=2.0, required=1) for i in range(8)
        ] + [
            gang("lw", pods=4, cpu=6.0,
                 group_levels=[(2, 1, -1), (2, 1, -1)], required=0),
            gang("big", pods=6, cpu=5.0),
        ]
        from grove_tpu.solver import PlacementEngine

        nat = PlacementEngine(snap, native_repair=True).solve(gangs)
        py = PlacementEngine(snap, native_repair=False).solve(gangs)
        assert set(nat.placed) == set(py.placed)
        for name in py.placed:
            np.testing.assert_array_equal(
                nat.placed[name].node_indices, py.placed[name].node_indices
            )
        assert nat.stats["fallbacks"] == py.stats["fallbacks"]


def test_native_holds_predeclared_unschedulable_gangs():
    """A gang whose required pack level is unresolved must be HELD by the
    native path with its reason, never weakened to best-effort (parity
    with solve_serial; review finding)."""
    import numpy as np
    import pytest

    from grove_tpu.native import native_available, solve_serial_native
    from grove_tpu.solver import SolverGang
    from grove_tpu.solver.problem import UNRESOLVED_LEVEL

    from test_solver import cluster, gang

    if not native_available():
        pytest.skip("no native toolchain")
    snap = cluster()
    held = gang("held", pods=2, cpu=1.0)
    held.required_level = UNRESOLVED_LEVEL
    held.unschedulable_reason = "required topology level(s) unavailable: zone"
    ok = gang("ok", pods=2, cpu=1.0)
    res = solve_serial_native(snap, [held, ok])
    assert res is not None
    assert res.unplaced == {
        "held": "required topology level(s) unavailable: zone"
    }
    assert set(res.placed) == {"ok"}


def grouped_gang(name, group_sizes, cg=None, cpu=2.0, required=-1,
                 preferred=-1, group_req=None, group_pref=None, priority=0.0):
    """Gang with explicit per-group sizes, optional constraint groups
    (cg: list of (member group indices, req, pref)) and per-group
    required/preferred levels."""
    from grove_tpu.solver import SolverGang

    n_groups = len(group_sizes)
    group_req = group_req or [-1] * n_groups
    group_pref = group_pref or [-1] * n_groups
    demand, gids = [], []
    for gi, cnt in enumerate(group_sizes):
        for _ in range(cnt):
            demand.append([cpu, 1.0, 0.0])
            gids.append(gi)
    return SolverGang(
        name=name,
        namespace="default",
        demand=np.asarray(demand, dtype=np.float32),
        pod_names=[f"{name}-p{i}" for i in range(len(demand))],
        group_ids=np.asarray(gids, dtype=np.int32),
        group_names=[f"g{i}" for i in range(n_groups)],
        group_required_level=np.asarray(group_req, dtype=np.int32),
        group_preferred_level=np.asarray(group_pref, dtype=np.int32),
        required_level=required,
        preferred_level=preferred,
        priority=priority,
        constraint_groups=list(cg or []),
    )


def _assert_identical(cc, py):
    assert cc is not None
    assert set(cc.placed) == set(py.placed)
    assert set(cc.unplaced) == set(py.unplaced)
    for name in py.placed:
        np.testing.assert_array_equal(
            cc.placed[name].node_indices, py.placed[name].node_indices
        )
        assert cc.placed[name].placement_score == pytest.approx(
            py.placed[name].placement_score
        )


class TestNativeGroupedParity:
    """Round-4 coverage (VERDICT r3 #3): constraint groups and PREFERRED
    levels — the leader/worker PCSG shape (reference README.md:38-44) —
    must take the native path with placements identical to fit.py."""

    def test_constraint_group_parity(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [
            # prefill/decode pair: each group rack-packed, the PAIR
            # block-packed via a constraint group
            grouped_gang("lw0", [3, 3], cg=[([0, 1], 0, -1)],
                         group_req=[1, 1]),
            grouped_gang("lw1", [2, 2], cg=[([0, 1], 0, 1)],
                         group_req=[1, 1]),
            grouped_gang("plain", [4]),
        ]
        _assert_identical(
            solve_serial_native(snap, gangs), solve_serial(snap, gangs)
        )

    def test_group_preferred_parity(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [
            grouped_gang("p0", [4, 2], group_pref=[1, -1]),
            grouped_gang("p1", [2, 2], group_req=[0, -1], group_pref=[1, 1]),
        ]
        _assert_identical(
            solve_serial_native(snap, gangs), solve_serial(snap, gangs)
        )

    def test_gang_preferred_parity(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [
            grouped_gang("gp0", [6], required=0, preferred=1),
            grouped_gang("gp1", [4], preferred=0),
        ]
        _assert_identical(
            solve_serial_native(snap, gangs), solve_serial(snap, gangs)
        )

    def test_engine_repair_grouped_no_fallback_divergence(self):
        """The engine's native repair must accept grouped gangs (no
        Python-path fallback) and match the Python repair placements."""
        from grove_tpu.solver import PlacementEngine

        snap = cluster(blocks=2, racks=4, hosts=4, cpu=8.0)
        gangs = [
            grouped_gang(f"lw{i}", [2, 2], cg=[([0, 1], 0, -1)],
                         group_req=[1, 1], cpu=3.0)
            for i in range(6)
        ] + [
            grouped_gang(f"pref{i}", [4], required=0, preferred=1, cpu=2.0)
            for i in range(4)
        ]
        nat = PlacementEngine(snap, native_repair=True).solve(gangs)
        py = PlacementEngine(snap, native_repair=False).solve(gangs)
        assert set(nat.placed) == set(py.placed) == {g.name for g in gangs}
        for name in py.placed:
            np.testing.assert_array_equal(
                nat.placed[name].node_indices, py.placed[name].node_indices
            )
        assert nat.stats["fallbacks"] == py.stats["fallbacks"]

    def test_fuzz_grouped_parity(self):
        """Randomized grouped backlogs: native serial == Python serial,
        placement for placement."""
        rng = np.random.default_rng(42)
        for trial in range(25):
            snap = cluster(
                blocks=int(rng.integers(1, 3)),
                racks=int(rng.integers(1, 4)),
                hosts=int(rng.integers(2, 5)),
                cpu=float(rng.integers(4, 10)),
            )
            gangs = []
            for i in range(int(rng.integers(2, 7))):
                n_groups = int(rng.integers(1, 4))
                sizes = [int(rng.integers(1, 4)) for _ in range(n_groups)]
                group_req = [int(rng.integers(-1, 3)) for _ in range(n_groups)]
                group_pref = [int(rng.integers(-1, 3)) for _ in range(n_groups)]
                cg = []
                if n_groups >= 2 and rng.random() < 0.5:
                    members = list(range(int(rng.integers(2, n_groups + 1))))
                    cg = [(members, int(rng.integers(-1, 2)),
                           int(rng.integers(-1, 3)))]
                gangs.append(
                    grouped_gang(
                        f"t{trial}g{i}", sizes, cg=cg,
                        cpu=float(rng.integers(1, 5)),
                        required=int(rng.integers(-1, 2)),
                        preferred=int(rng.integers(-1, 3)),
                        group_req=group_req, group_pref=group_pref,
                        priority=float(rng.integers(0, 3)),
                    )
                )
            _assert_identical(
                solve_serial_native(snap, gangs), solve_serial(snap, gangs)
            )


def test_multiple_constraint_groups_parity():
    """Two disjoint constraint groups in one gang (e.g. prefill-pair +
    decode-pair co-location islands) place identically to fit.py."""
    snap = cluster(blocks=2, racks=3, hosts=4, cpu=10.0)
    gangs = [
        grouped_gang(
            "multi", [2, 2, 2, 2],
            cg=[([0, 1], 0, 1), ([2, 3], 0, -1)],
            group_req=[1, 1, 1, 1],
            cpu=2.0,
        ),
        grouped_gang("bg", [3], cpu=1.0),
    ]
    _assert_identical(
        solve_serial_native(snap, gangs), solve_serial(snap, gangs)
    )


# -- storecore: native clone/shallow for the object-store hot path --------
# (VERDICT r4 #1: the per-object write path in C behind the identical
# store API; these tests pin semantic parity with the Python cloners)


def _sample_pod():
    from grove_tpu.api.meta import ObjectMeta, OwnerReference
    from grove_tpu.api.types import Container, Pod, PodSpec

    return Pod(
        metadata=ObjectMeta(
            name="p0",
            namespace="ns",
            labels={"a": "b", "grove.io/x": "y"},
            finalizers=["f1"],
            owner_references=[OwnerReference(kind="K", name="o", uid="u1")],
        ),
        spec=PodSpec(
            containers=[Container(name="c", resources={"cpu": 1.0})],
            scheduling_gates=["g"],
        ),
    )


def test_storecore_builds_and_is_active():
    """The extension must build in this image (g++ + headers are baked
    in); if this fails the control plane silently runs the slow path."""
    from grove_tpu.cluster import store

    assert store.NATIVE_STORE_ACTIVE


def test_storecore_clone_parity_deep():
    from grove_tpu.cluster import store

    p = _sample_pod()
    for clone_fn in (store.clone, store._make_cloner(type(p))):
        c = clone_fn(p)
        assert c is not p
        assert c.metadata is not p.metadata
        assert c.metadata.labels == p.metadata.labels
        assert c.metadata.labels is not p.metadata.labels
        assert c.metadata.owner_references[0].uid == "u1"
        assert c.spec.containers[0].resources == {"cpu": 1.0}
        assert c.spec.containers[0].resources is not (
            p.spec.containers[0].resources
        )
        # deep independence: mutating the clone never reaches the source
        c.metadata.labels["a"] = "mutated"
        c.spec.containers[0].resources["cpu"] = 9.0
        assert p.metadata.labels["a"] == "b"
        assert p.spec.containers[0].resources["cpu"] == 1.0


def test_storecore_shallow_shares_fields():
    from grove_tpu.cluster import store

    p = _sample_pod()
    s = store._shallow(p)
    assert s is not p
    assert s.metadata is p.metadata
    assert s.spec is p.spec


def test_storecore_scalar_and_fallback_classes():
    from enum import Enum

    import numpy as np

    from grove_tpu.api.meta import NamespacedName
    from grove_tpu.cluster import store

    class Phase(str, Enum):
        RUNNING = "Running"

    # str-subclass scalars are identity (immutable), like the Python path
    assert store.clone(Phase.RUNNING) is Phase.RUNNING
    # frozen non-slots dataclass falls back to the generated Python cloner
    nn = NamespacedName("ns", "nm")
    assert store.clone(nn) == nn
    # exotic payloads (ndarray) fall back to deepcopy
    arr = np.arange(4)
    ca = store.clone(arr)
    assert ca is not arr and (ca == arr).all()
    # containers of mixed content
    tree = {"k": [1, "s", {"n": None}], "t": (1.0, True)}
    ct = store.clone(tree)
    assert ct == tree and ct is not tree and ct["k"] is not tree["k"]


def test_storecore_env_kill_switch(monkeypatch):
    """GROVE_TPU_NO_NATIVE_STORE=1 must keep the pure-Python path usable
    (bisection + toolchain-less deploys)."""
    import subprocess
    import sys

    code = (
        "import os; os.environ['GROVE_TPU_NO_NATIVE_STORE']='1';"
        "import jax; jax.config.update('jax_platforms','cpu');"
        "from grove_tpu.cluster import store;"
        "assert not store.NATIVE_STORE_ACTIVE;"
        "from grove_tpu.api.meta import ObjectMeta;"
        "from grove_tpu.api.types import Container, Pod, PodSpec;"
        "p=Pod(metadata=ObjectMeta(name='p', labels={'a': 'b'}),"
        "      spec=PodSpec(containers=[Container(name='c')]));"
        "c=store.clone(p);"
        "assert c.metadata.labels == p.metadata.labels;"
        "assert c.metadata.labels is not p.metadata.labels"
    )
    from pathlib import Path

    repo_root = str(Path(__file__).resolve().parents[1])
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=repo_root, timeout=120,
    )
    assert r.returncode == 0, r.stderr


def test_storecore_deep_nesting_raises_not_crashes():
    """A pathologically nested caller-supplied tree must surface
    RecursionError (like the Python cloners), never a C stack overflow."""
    from grove_tpu.cluster import store

    deep: list = []
    cur = deep
    for _ in range(100_000):
        nxt: list = []
        cur.append(nxt)
        cur = nxt
    with pytest.raises(RecursionError):
        store.clone(deep)


def test_tune_gc_smoke():
    """tune_gc adjusts thresholds and survives repeated calls; restore the
    defaults so the rest of the suite keeps the stock posture."""
    import gc

    from grove_tpu.tuning import tune_gc

    old = gc.get_threshold()
    try:
        tune_gc(freeze=False)
        assert gc.get_threshold()[0] == 100_000
        tune_gc(freeze=False, gen0_threshold=50_000)
        assert gc.get_threshold()[0] == 50_000
    finally:
        gc.set_threshold(*old)


class TestAbiHandshake:
    """The loader must refuse a library whose grove_native_abi() differs
    from build.EXPECTED_ABI (stale/foreign .so -> Python fallback, never
    undefined marshalling), and accept the current one."""

    def test_current_library_passes_handshake(self):
        from grove_tpu.native import build

        lib = build.load_library()
        if lib is None:
            pytest.skip("no native toolchain")
        assert lib.grove_native_abi() == build.EXPECTED_ABI

    def test_mismatched_abi_rejected(self, monkeypatch):
        from grove_tpu.native import build

        if build.load_library() is None:
            pytest.skip("no native toolchain")
        # reset the memoized loader and demand an ABI no library provides
        monkeypatch.setattr(build, "_lib", None)
        monkeypatch.setattr(build, "_tried", False)
        monkeypatch.setattr(build, "EXPECTED_ABI", 10**9)
        assert build.load_library() is None
        # and repair/solve degrade to the Python paths instead of crashing
        from grove_tpu.native import solve_serial_native

        snap = cluster(blocks=1, racks=2, hosts=2, cpu=8.0)
        assert solve_serial_native(snap, [gang("a", pods=2, cpu=1.0)]) is None
        # restore the real loader state for later tests in this process
        monkeypatch.undo()
        monkeypatch.setattr(build, "_lib", None)
        monkeypatch.setattr(build, "_tried", False)
        assert build.load_library() is not None


def test_enable_compilation_cache(tmp_path, monkeypatch):
    """enable_compilation_cache points JAX's persistent cache at the
    resolved directory (arg > env > tmp default) and returns it."""
    from grove_tpu.tuning import enable_compilation_cache

    import jax

    prev = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        explicit = enable_compilation_cache(str(tmp_path / "a"))
        assert explicit == str(tmp_path / "a")
        assert jax.config.jax_compilation_cache_dir == explicit
        monkeypatch.setenv("GROVE_TPU_COMPILE_CACHE", str(tmp_path / "b"))
        assert enable_compilation_cache() == str(tmp_path / "b")
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_min
        )
