"""Control-plane-at-scale tests (VERDICT r1 #4 / SURVEY §4 takeaway 3).

CI-speed variant of bench.py's bench_controlplane: drive the FULL path —
apply PCS -> gated pods -> deferred gangs -> scheduler -> bound/ready —
at a scale where the r1 per-event full-table rescans were quadratic, and
pin the store's label-index behavior those scans now rely on."""

from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.podgang import PodGang, PodGangPhase
from grove_tpu.api.types import (
    Container,
    Node,
    Pod,
    PodCliqueSet,
    PodCliqueSetSpec,
    PodCliqueSetTemplateSpec,
    PodCliqueSpec,
    PodCliqueTemplateSpec,
    PodSpec,
)
from grove_tpu.cluster import Cluster, make_nodes
from grove_tpu.controller import Harness


def wide_pcs(name, replicas, pods_per_clique=4):
    return PodCliqueSet(
        metadata=ObjectMeta(name=name),
        spec=PodCliqueSetSpec(
            replicas=replicas,
            template=PodCliqueSetTemplateSpec(
                cliques=[
                    PodCliqueTemplateSpec(
                        name="w",
                        spec=PodCliqueSpec(
                            replicas=pods_per_clique,
                            pod_spec=PodSpec(
                                containers=[
                                    Container(name="m", resources={"cpu": 1.0})
                                ]
                            ),
                        ),
                    )
                ]
            ),
        ),
    )


class TestControlPlaneScale:
    def test_full_path_at_scale_settles_and_binds(self):
        # 40 replicas x 4 pods on 300 nodes: every pod bound + ready, every
        # gang Running, in one settle
        h = Harness(nodes=make_nodes(300, allocatable={"cpu": 32.0,
                                                       "memory": 128.0,
                                                       "tpu": 8.0}))
        h.apply(wide_pcs("scale", 40))
        h.settle()
        pods = h.store.scan(Pod.KIND)
        assert len(pods) == 160
        assert all(p.node_name and p.status.ready for p in pods)
        gangs = h.store.scan(PodGang.KIND)
        assert len(gangs) == 40
        assert all(g.status.phase == PodGangPhase.RUNNING for g in gangs)
        m = h.cluster.metrics
        assert m.counter("grove_scheduler_gangs_scheduled_total").total() == 40

    def test_dirty_tracking_keeps_steady_state_cheap(self):
        # at quiescence, a single pod readiness flip must NOT re-examine the
        # whole world: reconcile count stays O(1)-ish, and the scheduler
        # only re-derives phases for the flipped pod's gang
        h = Harness(nodes=make_nodes(100))
        h.apply(wide_pcs("steady", 20))
        h.settle()
        h.kubelet.crash_pod("default", "steady-5-w-0")
        before = len(h.store._events)
        h.settle()
        churn = len(h.store._events) - before
        # crash -> pclq breach condition + gang unhealthy + pcs status:
        # a handful of writes, not hundreds (r1 rescanned everything)
        assert churn < 15, f"steady-state churn too high: {churn} events"

    def test_label_index_tracks_updates_and_deletes(self):
        c = Cluster(nodes=make_nodes(2))
        store = c.store

        def mk(name, labels):
            p = Pod(metadata=ObjectMeta(name=name, labels=labels),
                    spec=PodSpec(containers=[Container(name="c")]))
            return p

        store.create(mk("a", {"grp": "x"}))
        store.create(mk("b", {"grp": "x"}))
        store.create(mk("c", {"grp": "y"}))
        assert {p.metadata.name for p in store.scan(Pod.KIND,
                                                    labels={"grp": "x"})} == {"a", "b"}
        # label change on update re-indexes
        b = store.get(Pod.KIND, "default", "b")
        b.metadata.labels["grp"] = "y"
        store.update(b)
        assert {p.metadata.name for p in store.scan(Pod.KIND,
                                                    labels={"grp": "y"})} == {"b", "c"}
        assert [p.metadata.name for p in store.scan(Pod.KIND,
                                                    labels={"grp": "x"})] == ["a"]
        # delete drops index entries
        store.delete(Pod.KIND, "default", "c")
        assert {p.metadata.name for p in store.scan(Pod.KIND,
                                                    labels={"grp": "y"})} == {"b"}
        # unknown label value -> empty, not full scan
        assert store.scan(Pod.KIND, labels={"grp": "zzz"}) == []
        # list() uses the same index and still returns copies
        got = store.list(Pod.KIND, labels={"grp": "y"})
        got[0].metadata.labels["grp"] = "mutated"
        assert store.peek(Pod.KIND, "default", "b").metadata.labels["grp"] == "y"


class TestIncrementality:
    """Regression guards for the r3 scale work: steady-state events must
    trigger BOUNDED reconcile fan-out, not O(cliques) storms."""

    def settle_and_snapshot(self, replicas=30):
        h = Harness(nodes=make_nodes(200, allocatable={"cpu": 32.0,
                                                       "memory": 128.0,
                                                       "tpu": 8.0}))
        h.apply(wide_pcs("inc", replicas))
        h.settle()
        m = h.cluster.metrics
        before = {
            c: m.counter("grove_manager_reconcile_total").value(controller=c)
            for c in ("podcliqueset", "podclique")
        }
        return h, m, before

    def test_single_crash_reconciles_are_bounded(self):
        h, m, before = self.settle_and_snapshot()
        h.kubelet.crash_pod("default", "inc-0-w-0")
        h.settle()
        h.kubelet.recover_pod("default", "inc-0-w-0")
        h.settle()
        total = m.counter("grove_manager_reconcile_total")
        # one pod's crash+recovery must not fan out to every clique: the
        # podclique controller reconciles a handful of times, not ~replicas
        delta = total.value(controller="podclique") - before["podclique"]
        assert delta <= 12, f"podclique reconcile storm: {delta}"
        delta_pcs = total.value(controller="podcliqueset") - before["podcliqueset"]
        assert delta_pcs <= 12, f"pcs reconcile storm: {delta_pcs}"

    def test_gang_status_write_does_not_fan_out(self):
        h, m, before = self.settle_and_snapshot()
        # touch ONE gang's status (phase refresh path) and settle: the
        # podgang event must map only to ITS cliques (r3 map_event fix),
        # so podclique reconciles stay O(1), not O(replicas)
        gang = h.store.get(PodGang.KIND, "default", "inc-5")
        gang.status.placement_score = 0.999
        h.store.update_status(gang)
        h.settle()
        total = m.counter("grove_manager_reconcile_total")
        delta = total.value(controller="podclique") - before["podclique"]
        assert delta <= 4, f"gang event fanned out to {delta} clique reconciles"

    def test_pre_round_dispatch_overlaps_the_settle_solve(self):
        # the manager's pre_round hook lets the scheduler dispatch the
        # accelerator solve before the round's other reconciles; in a
        # clean bulk-apply settle (no writes land between dispatch and
        # consume) the reconcile must ADOPT the in-flight result, and
        # the outcome must be identical to the synchronous path
        h = Harness(nodes=make_nodes(60, allocatable={"cpu": 32.0,
                                                      "memory": 128.0,
                                                      "tpu": 8.0}))
        h.apply(wide_pcs("ovl", 10))
        h.settle()
        pods = h.store.scan(Pod.KIND)
        assert len(pods) == 40
        assert all(p.node_name and p.status.ready for p in pods)
        c = h.cluster.metrics.counter(
            "grove_scheduler_solve_dispatch_total",
            "pre_round solve dispatches by outcome at consume time",
        )
        assert c.value(outcome="overlapped") >= 1
        assert c.value(outcome="fresh") == 0

    def test_stale_pre_round_dispatch_falls_back_to_fresh_solve(self):
        # a write to a watched kind between dispatch and consume must
        # discard the pending dispatch - the reconcile re-fetches and
        # solves fresh, and still binds everything
        h = Harness(nodes=make_nodes(60, allocatable={"cpu": 32.0,
                                                      "memory": 128.0,
                                                      "tpu": 8.0}))
        h.apply(wide_pcs("stale", 4))
        # invalidate every pending dispatch with a capacity-moving write
        # (a Node create) landing between dispatch and consume
        sched = h.scheduler
        orig = sched.pre_round
        seq = iter(range(10_000))

        def poisoned_pre_round():
            orig()
            if sched._pending is not None:
                h.store.create(
                    Node(
                        metadata=ObjectMeta(name=f"late-{next(seq)}"),
                        allocatable={"cpu": 32.0, "memory": 128.0,
                                     "tpu": 8.0},
                    )
                )

        sched.pre_round = poisoned_pre_round
        h.settle()
        pods = h.store.scan(Pod.KIND)
        assert len(pods) == 16
        assert all(p.node_name and p.status.ready for p in pods)
        c = h.cluster.metrics.counter(
            "grove_scheduler_solve_dispatch_total",
            "pre_round solve dispatches by outcome at consume time",
        )
        assert c.value(outcome="fresh") >= 1

    def test_sustained_churn_binds_everything(self):
        # CI-speed variant of bench.py's sustained-churn regime (VERDICT
        # r4 #2): steady single-gang PCS arrival against a warm plane
        # with deletes, a scale event and a crash mixed in — every gang
        # that was not deleted must bind, and the stream must quiesce
        import bench as bench_mod

        h = Harness(nodes=make_nodes(120, allocatable={"cpu": 32.0,
                                                       "memory": 128.0,
                                                       "tpu": 8.0}))
        h.apply(bench_mod._churn_pcs("standing", 4))
        h.settle()
        stats = bench_mod.churn_workload(
            h, rate=16.0, duration=8.0, batch_dt=0.5, population=24,
            warmup_batches=1, scale_every=3.0, crash_every=2.5,
            update_every=3.0,
        )
        assert stats["created"] == 16 * 8
        assert stats["unbound_final"] == 0
        # accounting identity: every created gang is bound, still pending,
        # or was deleted before it could bind (censored, counted)
        assert (stats["bound"] + stats["unbound_final"]
                + stats["deleted_before_bind"]) == stats["created"]
        assert stats["deleted"] > 0
        assert stats["scale_events"] >= 1
        assert stats["crashes"] >= 1
        assert stats["updates"] >= 1  # rolling update advanced in-stream
        assert stats["p99_bind_seconds"] > 0
        # the plane quiesced: no leftover pending work
        from grove_tpu.api.types import Pod
        pods = h.store.scan(Pod.KIND)
        assert all(p.node_name for p in pods)
        # long-run hygiene: churn compacts the event log each batch, so
        # retention stays bounded by one batch's traffic, not the run
        assert h.store.event_log_length < 2000, (
            f"event log leaked: {h.store.event_log_length} retained"
        )
        # and the consumers survived compaction without relisting churn:
        # one more wave settles cleanly
        h.apply(bench_mod._churn_pcs("after-compact", 2))
        h.settle()
        pods = h.store.scan(Pod.KIND)
        assert all(p.node_name and p.status.ready for p in pods)

    def test_small_singles_rebind_skips_the_device(self):
        # a crash-replacement rebind (a handful of best-effort singles)
        # must bind via the exact serial path, not pay a device solve:
        # the backlog-bind histogram gains NO new observation while the
        # pod still lands back on a node
        h = Harness(nodes=make_nodes(40, allocatable={"cpu": 32.0,
                                                      "memory": 128.0,
                                                      "tpu": 8.0}))
        h.apply(wide_pcs("sg", 6))
        h.settle()
        solve_h = h.cluster.metrics.histogram(
            "grove_solver_backlog_bind_seconds"
        )
        solves_before = solve_h.count
        wall_before = solve_h.sum
        victim = h.store.scan(Pod.KIND)[0]
        prior_node = victim.node_name
        h.kubelet.evict_pod(victim.metadata.namespace, victim.metadata.name)
        # cordon the vacated node so the pod-level reservation fast path
        # cannot shortcut the rebind: the replacement must SEARCH, and
        # that search must be the serial path, not a device solve
        node = h.store.get(Node.KIND, "default", prior_node)
        node.unschedulable = True
        h.store.update(node)
        h.settle()
        pods = h.store.scan(Pod.KIND)
        assert len(pods) == 24
        assert all(p.node_name and p.status.ready for p in pods)
        replacement = h.store.peek(
            Pod.KIND, victim.metadata.namespace, victim.metadata.name
        )
        assert replacement.node_name != prior_node
        # the rebind IS recorded (unplaced singles must stay visible to
        # monitoring) but as serial-path observations, not device solves:
        # the added wall must be far below one device round trip
        assert solve_h.count > solves_before
        assert solve_h.sum - wall_before < 0.05, (
            "single-pod rebind paid a device solve"
        )
