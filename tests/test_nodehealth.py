"""Node lifecycle subsystem: heartbeat-driven NotReady, grace-period
eviction + topology-aware repair, flap damping, gang-aware drain, and
failure-domain outage recovery (cluster/nodehealth.py +
controller/nodemonitor.py).

The disruption story asserted end to end: detect (lease lag) -> grace
(no eviction inside pod_eviction_grace_seconds) -> evict (pods swept
Failed) -> re-place (gangs repaired onto healthy domains, NotReady nodes
excluded from the candidate set) -> converge (recovered nodes ride the
stable-ready window back in; chaos seeds reach the fault-free fixpoint).
"""

import io

import pytest

from grove_tpu.api.meta import ObjectMeta, get_condition
from grove_tpu.api.podgang import PodGang
from grove_tpu.api.types import (
    Container,
    Node,
    PodCliqueScalingGroupConfig,
    PodCliqueSet,
    PodCliqueSetSpec,
    PodCliqueSetTemplateSpec,
    PodCliqueSpec,
    PodCliqueTemplateSpec,
    PodSpec,
    node_ready,
)
from grove_tpu.chaos import (
    ChaosHarness,
    FaultPlan,
    check_invariants,
    settled_fingerprint,
)
from grove_tpu.cluster import make_nodes
from grove_tpu.cluster.inventory import RACK_KEY
from grove_tpu.cluster.store import NotFound, StoreError
from grove_tpu.controller import Harness

#: short lifecycle windows so tests advance seconds, not minutes; the
#: stable window deliberately exceeds the lease duration (the production
#: invariant api.config documents)
FAST_LIFECYCLE = {
    "node_lease_duration_seconds": 6.0,
    "pod_eviction_grace_seconds": 12.0,
    "node_stable_ready_seconds": 8.0,
}


def workload(name="w", replicas=4, min_available=None, cpu=1.0):
    return PodCliqueSet(
        metadata=ObjectMeta(name=name),
        spec=PodCliqueSetSpec(
            replicas=1,
            template=PodCliqueSetTemplateSpec(cliques=[
                PodCliqueTemplateSpec(
                    name="fe",
                    spec=PodCliqueSpec(
                        replicas=replicas,
                        min_available=min_available,
                        pod_spec=PodSpec(containers=[
                            Container(name="c", resources={"cpu": cpu})
                        ]),
                    ),
                )
            ]),
        ),
    )


def fast_harness(nodes=8, **cluster_overrides):
    return Harness(
        nodes=make_nodes(nodes, racks_per_block=2, hosts_per_rack=2),
        config={"cluster": {**FAST_LIFECYCLE, **cluster_overrides}},
    )


def bindings(h):
    """pod name -> (node, uid): the placement-stability fingerprint."""
    return {
        p.metadata.name: (p.node_name, p.metadata.uid)
        for p in h.store.list("Pod")
    }


def ready_node(h, name):
    return node_ready(h.store.get(Node.KIND, "default", name))


class TestHeartbeatNotReady:
    def test_lease_expiry_marks_not_ready_and_excludes_from_candidates(self):
        h = fast_harness()
        h.apply(workload())
        h.settle()
        victim = h.store.list("Pod")[0].node_name
        h.kubelet.fail_heartbeat(victim)
        h.advance(FAST_LIFECYCLE["node_lease_duration_seconds"] + 1.0)
        node = h.store.get(Node.KIND, "default", victim)
        assert not node_ready(node)
        cond = get_condition(node.status.conditions, "Ready")
        assert cond.status == "False" and cond.reason == "HeartbeatLost"
        snap = h.cluster.topology_snapshot()
        assert not snap.schedulable[snap.node_index[victim]]
        # detection is counted and evented
        assert h.cluster.metrics.counter(
            "grove_node_not_ready_total"
        ).total() >= 1
        events = [e for e in h.store.list("Event")
                  if e.reason == "NodeNotReady"]
        assert events and events[0].involved_name == victim

    def test_grace_eviction_then_repair_onto_healthy_nodes(self):
        h = fast_harness()
        h.apply(workload())
        h.settle()
        pods = h.store.list("Pod")
        victim = pods[0].node_name
        on_victim = sum(1 for p in pods if p.node_name == victim)
        assert on_victim > 0
        h.kubelet.fail_heartbeat(victim)
        # inside the grace: NotReady but ZERO evictions
        h.advance(7.0)
        assert not ready_node(h, victim)
        assert h.cluster.metrics.counter(
            "grove_node_pod_evictions_total"
        ).total() == 0
        # grace elapses: pods swept and replaced elsewhere, gang whole
        h.advance(FAST_LIFECYCLE["pod_eviction_grace_seconds"] + 1.0)
        pods = h.store.list("Pod")
        assert all(p.node_name != victim for p in pods)
        assert all(p.node_name and p.status.ready for p in pods)
        assert h.cluster.metrics.counter(
            "grove_node_pod_evictions_total"
        ).total() == on_victim
        gang = h.store.list(PodGang.KIND)[0]
        assert gang.status.phase.value == "Running"
        assert check_invariants(h.store) == []

    def test_recovered_node_waits_out_stable_ready_window(self):
        h = fast_harness()
        h.settle()
        h.kubelet.fail_heartbeat("node-0")
        h.advance(7.0)
        assert not ready_node(h, "node-0")
        h.kubelet.restore_heartbeat("node-0")
        h.advance(1.0)  # first post-recovery heartbeat starts the window
        assert not ready_node(h, "node-0"), "stabilizing, not yet Ready"
        snap = h.cluster.topology_snapshot()
        assert not snap.schedulable[snap.node_index["node-0"]]
        h.advance(FAST_LIFECYCLE["node_stable_ready_seconds"] + 1.0)
        assert ready_node(h, "node-0")
        snap = h.cluster.topology_snapshot()
        assert snap.schedulable[snap.node_index["node-0"]]

    def test_clock_jump_with_healthy_heartbeats_marks_nothing(self):
        """Lease lag is measured against the freshest cluster heartbeat,
        not wall-now: a virtual four-hour advance (gang-termination
        timers, chaos clock jumps) must not NotReady a healthy fleet."""
        h = fast_harness()
        h.apply(workload())
        h.settle()
        h.advance(4 * 3600.0)
        assert all(node_ready(n) for n in h.store.list(Node.KIND))
        assert h.cluster.metrics.counter(
            "grove_node_not_ready_total"
        ).total() == 0


class TestFlapStability:
    def test_ten_flap_cycles_zero_evictions_zero_rebindings(self):
        """The acceptance criterion: a node flipping NotReady/Ready
        inside the eviction grace must cause zero evictions and zero
        re-bindings — same pods, same uids, same nodes after 10 cycles."""
        h = fast_harness(pod_eviction_grace_seconds=120.0)
        h.apply(workload())
        h.settle()
        before = bindings(h)
        victim = next(iter(before.values()))[0]
        for _ in range(10):
            h.cluster.fail_node(victim)   # NotReady inside the grace
            h.advance(5.0)
            h.cluster.recover_node(victim)
            h.advance(1.0)                # heartbeat resumes
            h.advance(9.0)                # stable window elapses
        assert bindings(h) == before
        assert h.cluster.metrics.counter(
            "grove_node_pod_evictions_total"
        ).total() == 0
        assert ready_node(h, victim)


class TestGangAwareDrain:
    def test_drain_paces_on_min_available_and_empties_the_node(self):
        h = fast_harness()
        # minAvailable == replicas: zero PDB budget, so the drain gives
        # up one pod at a time and waits for each replacement to Ready
        h.apply(workload(replicas=6, min_available=6))
        h.settle()
        target = h.store.list("Pod")[0].node_name
        on_target = sum(
            1 for p in h.store.list("Pod") if p.node_name == target
        )
        h.cluster.drain(target)
        clique_name = "w-0-fe"
        min_ready_seen = 6
        for _ in range(40):
            h.advance(3.0)
            pclq = h.store.get("PodClique", "default", clique_name)
            min_ready_seen = min(min_ready_seen, pclq.status.ready_replicas)
            if h.cluster.node_drained(target):
                break
        assert h.cluster.node_drained(target)
        # paced: availability never dipped more than the one pod in flight
        assert min_ready_seen >= 5, min_ready_seen
        pods = h.store.list("Pod")
        assert all(p.node_name != target and p.status.ready for p in pods)
        m = h.cluster.metrics
        assert m.counter(
            "grove_node_drain_evictions_total"
        ).total() == on_target
        assert m.counter(
            "grove_node_drain_gang_terminations_total"
        ).total() == 0
        # the gang was never a disruption target
        gang = h.store.list(PodGang.KIND)[0]
        dt = get_condition(gang.status.conditions, "DisruptionTarget")
        assert dt is None or dt.status == "False"
        assert any(e.reason == "NodeDrained"
                   for e in h.store.list("Event"))

    def test_drain_falls_back_to_gang_termination_when_unrebuildable(self):
        # two 2-cpu nodes, a 4x1cpu gang filling both: no replacement can
        # ever land, so the drain must terminate the gang whole instead
        # of wedging it half-broken
        h = Harness(
            nodes=make_nodes(
                2, allocatable={"cpu": 2.0, "memory": 8.0, "tpu": 0.0}
            ),
            config={"cluster": FAST_LIFECYCLE},
        )
        h.apply(workload(name="tight", replicas=4, min_available=4))
        h.settle()
        assert all(p.node_name and p.status.ready
                   for p in h.store.list("Pod"))
        h.cluster.drain("node-1")
        for _ in range(10):
            h.advance(6.0)
            if h.cluster.node_drained("node-1"):
                break
        assert h.cluster.node_drained("node-1")
        assert h.cluster.metrics.counter(
            "grove_node_drain_gang_terminations_total"
        ).total() == 1
        gang = h.store.list(PodGang.KIND)[0]
        sch = get_condition(gang.status.conditions, "Scheduled")
        assert sch.status == "False"
        dt = get_condition(gang.status.conditions, "DisruptionTarget")
        assert dt is not None and dt.status == "True"
        # maintenance over: the gang rebuilds atomically
        h.cluster.uncordon("node-1")
        h.advance(6.0)
        pods = h.store.list("Pod")
        assert len(pods) == 4
        assert all(p.node_name and p.status.ready for p in pods)

    def test_concurrent_drains_share_one_pdb_budget(self):
        """Two nodes draining in the same monitor pass must not each
        spend the clique's disruption budget against the same pod
        snapshot: with minAvailable=5 of 6 (budget 1), a drain storm over
        two nodes may never dip ready below 5."""
        h = Harness(
            nodes=make_nodes(
                6, allocatable={"cpu": 2.0, "memory": 8.0, "tpu": 0.0}
            ),
            config={"cluster": FAST_LIFECYCLE},
        )
        h.apply(workload(replicas=6, min_available=5))
        h.settle()
        by_node: dict[str, int] = {}
        for p in h.store.list("Pod"):
            by_node[p.node_name] = by_node.get(p.node_name, 0) + 1
        targets = sorted(n for n, c in by_node.items() if c == 2)[:2]
        assert len(targets) == 2, by_node
        for t in targets:
            h.cluster.drain(t)
        min_ready = 6
        for _ in range(60):
            h.advance(3.0)
            pclq = h.store.get("PodClique", "default", "w-0-fe")
            min_ready = min(min_ready, pclq.status.ready_replicas)
            if all(h.cluster.node_drained(t) for t in targets):
                break
        assert all(h.cluster.node_drained(t) for t in targets)
        assert min_ready >= 5, min_ready
        assert h.cluster.metrics.counter(
            "grove_node_drain_gang_terminations_total"
        ).total() == 0

    def test_drain_budgets_are_per_namespace(self):
        """A multi-tenant node drains each namespace's clique under its
        own MinAvailable budget: a clique whose namespace differs from
        the node's first pod must be paced like any other, not dumped at
        once as budget-less orphans."""
        h = Harness(
            nodes=make_nodes(2, racks_per_block=2, hosts_per_rack=2),
            config={"cluster": FAST_LIFECYCLE},
        )
        for ns in ("team-a", "team-b"):
            w = workload(replicas=4, min_available=4)
            w.metadata.namespace = ns
            h.apply(w)
        h.settle()
        pods = h.store.list("Pod")
        assert all(p.node_name and p.status.ready for p in pods)
        # with two nodes both namespaces share each node
        target = "node-0"
        assert {
            p.metadata.namespace for p in pods if p.node_name == target
        } == {"team-a", "team-b"}
        h.cluster.drain(target)
        min_ready = {"team-a": 4, "team-b": 4}
        for _ in range(60):
            h.advance(3.0)
            for ns in min_ready:
                pclq = h.store.get("PodClique", ns, "w-0-fe")
                min_ready[ns] = min(
                    min_ready[ns], pclq.status.ready_replicas
                )
            if h.cluster.node_drained(target):
                break
        assert h.cluster.node_drained(target)
        # zero PDB budget in BOTH namespaces: each clique gave up at most
        # the one pod in flight at a time
        assert all(v >= 3 for v in min_ready.values()), min_ready
        assert h.cluster.metrics.counter(
            "grove_node_drain_gang_terminations_total"
        ).total() == 0

    def test_gang_termination_during_multi_node_drain_is_spent_once(self):
        """A gang terminated whole while draining node A must be recorded
        in the pass's evicted set: node B's drain in the SAME pass would
        otherwise still see the gang's deleted pods in its stale snapshot
        and re-delete them (NotFound out of reconcile, double-counted
        terminations)."""
        w = PodCliqueSet(
            metadata=ObjectMeta(name="span"),
            spec=PodCliqueSetSpec(
                replicas=1,
                template=PodCliqueSetTemplateSpec(cliques=[
                    PodCliqueTemplateSpec(
                        name=cn,
                        spec=PodCliqueSpec(
                            replicas=2, min_available=2,
                            pod_spec=PodSpec(containers=[
                                Container(
                                    name="c", resources={"cpu": 1.0}
                                )
                            ]),
                        ),
                    )
                    for cn in ("a", "b")
                ]),
            ),
        )
        # 4x1cpu pods exactly fill two 2-cpu nodes; both cliques are
        # whole with zero budget, and no replacement can ever land
        h = Harness(
            nodes=make_nodes(
                2, allocatable={"cpu": 2.0, "memory": 8.0, "tpu": 0.0}
            ),
            config={"cluster": FAST_LIFECYCLE},
        )
        h.apply(w)
        h.settle()
        assert all(p.node_name and p.status.ready
                   for p in h.store.list("Pod"))
        for node in ("node-0", "node-1"):
            h.cluster.drain(node)
        for _ in range(10):
            h.advance(6.0)
            if all(h.cluster.node_drained(n)
                   for n in ("node-0", "node-1")):
                break
        assert all(h.cluster.node_drained(n)
                   for n in ("node-0", "node-1"))
        assert h.cluster.metrics.counter(
            "grove_node_drain_gang_terminations_total"
        ).total() == 1
        # maintenance over: the gang rebuilds atomically
        for node in ("node-0", "node-1"):
            h.cluster.uncordon(node)
        h.advance(6.0)
        pods = h.store.list("Pod")
        assert len(pods) == 4
        assert all(p.node_name and p.status.ready for p in pods)


class TestDomainOutage:
    def test_rack_outage_marks_members_in_one_settle_and_repairs(self):
        h = fast_harness()  # 8 nodes, racks of 2
        h.apply(workload())
        h.settle()
        rack_of = {
            n.metadata.name: n.metadata.labels[RACK_KEY]
            for n in h.store.list(Node.KIND)
        }
        victim_rack = rack_of[h.store.list("Pod")[0].node_name]
        failed = h.cluster.fail_domain(RACK_KEY, victim_rack)
        assert len(failed) == 2
        h.settle()  # ONE tick: every member NotReady, no clock advance
        assert all(not ready_node(h, f) for f in failed)
        snap = h.cluster.topology_snapshot()
        assert not any(snap.schedulable[snap.node_index[f]]
                       for f in failed)
        # grace passes: displaced gang repairs onto healthy racks
        h.advance(FAST_LIFECYCLE["pod_eviction_grace_seconds"] + 1.0)
        pods = h.store.list("Pod")
        assert all(
            p.status.ready and rack_of[p.node_name] != victim_rack
            for p in pods
        )
        assert check_invariants(h.store) == []
        # recovery rides the stable window back in
        h.cluster.recover_domain(RACK_KEY, victim_rack)
        h.advance(1.0)
        h.advance(FAST_LIFECYCLE["node_stable_ready_seconds"] + 1.0)
        assert all(ready_node(h, f) for f in failed)

    def test_unknown_domain_raises(self):
        h = fast_harness()
        with pytest.raises(NotFound):
            h.cluster.fail_domain(RACK_KEY, "no-such-rack")


class TestSchedulerStaleStateOnNodeLoss:
    def test_node_delete_purges_reservations_and_vacated_hints(self):
        h = fast_harness()
        h.apply(workload())
        h.settle()
        sched = h.scheduler
        victim = h.store.list("Pod")[0].node_name
        assert any(victim in nodes
                   for nodes in sched._reservations.values())
        h.store.delete(Node.KIND, "default", victim)
        h.settle()
        # pods rebuilt off the deleted node; no stale memory pins to it
        pods = h.store.list("Pod")
        assert all(p.node_name and p.node_name != victim
                   and p.status.ready for p in pods)
        assert not any(victim in nodes
                       for nodes in sched._reservations.values())
        assert victim not in sched._vacated.values()
        assert check_invariants(h.store) == []

    def test_outage_does_not_pin_gang_to_not_ready_reservation(self):
        """A NotReady (but not deleted) node stays in reservation memory;
        the reuse pre-pass must skip it via the schedulable filter and
        the displaced gang must repair onto healthy domains."""
        h = fast_harness()
        h.apply(workload())
        h.settle()
        rack_of = {
            n.metadata.name: n.metadata.labels[RACK_KEY]
            for n in h.store.list(Node.KIND)
        }
        victim_rack = rack_of[h.store.list("Pod")[0].node_name]
        h.cluster.fail_domain(RACK_KEY, victim_rack)
        h.advance(FAST_LIFECYCLE["pod_eviction_grace_seconds"] + 1.0)
        pods = h.store.list("Pod")
        assert all(
            p.status.ready and rack_of[p.node_name] != victim_rack
            for p in pods
        )


class TestCordonHardening:
    def test_unknown_node_raises_clear_not_found(self):
        h = fast_harness()
        for op in (h.cluster.cordon, h.cluster.uncordon, h.cluster.drain):
            with pytest.raises(NotFound, match="no-such-node"):
                op("no-such-node")

    def test_cordon_survives_transient_conflict_storm(self):
        """Bare read-modify-write lost the cordon when the first update
        raised; the retry loop re-reads and re-applies."""
        h = fast_harness()
        h.settle()
        real_update = h.store.update
        failures = {"left": 3}

        def stormy(obj):
            if obj.KIND == Node.KIND and failures["left"] > 0:
                failures["left"] -= 1
                raise StoreError("simulated write conflict")
            return real_update(obj)

        h.store.update = stormy
        try:
            h.cluster.cordon("node-0")
        finally:
            h.store.update = real_update
        assert failures["left"] == 0
        assert h.store.get(Node.KIND, "default", "node-0").unschedulable

    def test_exhausted_retries_surface_the_error(self):
        h = fast_harness()
        h.settle()
        real_update = h.store.update
        h.store.update = lambda obj: (_ for _ in ()).throw(
            StoreError("permanent conflict")
        )
        try:
            with pytest.raises(StoreError, match="permanent conflict"):
                h.cluster.cordon("node-0")
        finally:
            h.store.update = real_update


class TestConfigKnobs:
    def test_new_knobs_validate(self):
        from grove_tpu.api.config import load_operator_config
        from grove_tpu.api.validation import ValidationError

        cfg = load_operator_config({"cluster": FAST_LIFECYCLE})
        assert cfg.cluster.node_lease_duration_seconds == 6.0
        with pytest.raises(ValidationError, match="node_lease_duration"):
            load_operator_config(
                {"cluster": {"node_lease_duration_seconds": 0}}
            )
        with pytest.raises(ValidationError, match="pod_eviction_grace"):
            load_operator_config(
                {"cluster": {"pod_eviction_grace_seconds": -1}}
            )
        with pytest.raises(ValidationError, match="node_stable_ready"):
            load_operator_config(
                {"cluster": {"node_stable_ready_seconds": 0}}
            )
        # the dead-node guard's invariant is enforced, not just documented:
        # a stable window shorter than the lease duration would let a dead
        # node ride a stale-but-recent lease back to Ready
        with pytest.raises(ValidationError, match="node_stable_ready"):
            load_operator_config(
                {"cluster": {"node_lease_duration_seconds": 40.0,
                             "node_stable_ready_seconds": 10.0}}
            )
        with pytest.raises(ValidationError, match="unknown field"):
            load_operator_config({"cluster": {"bogus": 1}})
        with pytest.raises(ValidationError, match="node_monitor_enabled"):
            load_operator_config(
                {"controllers": {"node_monitor_enabled": "yes"}}
            )

    def test_monitor_can_be_disabled(self):
        h = Harness(
            nodes=make_nodes(4),
            config={"controllers": {"node_monitor_enabled": False}},
        )
        assert h.node_monitor is None
        h.apply(workload())
        h.settle()
        # heartbeat loss goes unnoticed without the monitor
        h.kubelet.fail_heartbeat("node-0")
        h.advance(120.0)
        assert ready_node(h, "node-0")

    def test_debug_dump_exposes_node_lifecycle(self):
        h = fast_harness()
        h.settle()
        dump = h.debug_dump()
        assert "node_lifecycle" in dump
        assert dump["node_lifecycle"]["drain_in_flight"] is False


@pytest.mark.chaos
class TestNodeFaultChaos:
    """The settle-fixpoint assertion extended over the four node fault
    types: once faults stop and the infrastructure is repaired, every
    seed converges to the fault-free workload fingerprint."""

    #: verified convergent with all four node fault types injected
    SEEDS = (0, 2, 6, 7)
    NODES = 24

    def _workload(self):
        from test_e2e_basic import clique, simple_pcs

        return simple_pcs(
            cliques=[
                clique("fe", replicas=2),
                clique("be", replicas=3, starts_after=["fe"]),
            ],
            replicas=2,
            startup="CliqueStartupTypeExplicit",
            sgs=[PodCliqueScalingGroupConfig(
                name="g", clique_names=["be"], replicas=2, min_available=1
            )],
        )

    def _plan(self, seed):
        return FaultPlan.from_seed(
            seed,
            node_flap_rate=0.2, heartbeat_loss_rate=0.12,
            domain_outage_rate=0.06, drain_storm_rate=0.06,
        )

    @pytest.fixture(scope="class")
    def baseline(self):
        h = Harness(nodes=make_nodes(self.NODES),
                    config={"cluster": FAST_LIFECYCLE})
        h.apply(self._workload())
        h.settle()
        return settled_fingerprint(h.store)

    @pytest.mark.parametrize("seed", SEEDS)
    def test_node_fault_seed_reaches_fault_free_fixpoint(
        self, seed, baseline
    ):
        ch = ChaosHarness(
            self._plan(seed),
            nodes=make_nodes(self.NODES),
            config={"cluster": FAST_LIFECYCLE},
        )
        buf = io.StringIO()
        ch.harness.cluster.logger.stream = buf
        ch.harness.manager.logger.stream = buf
        ch.apply(self._workload())
        ch.run_chaos()
        node_faults = {
            k: v for k, v in ch.plan.counts.items()
            if k in ("node_flap", "heartbeat_loss", "domain_outage",
                     "drain_storm")
        }
        assert node_faults, "the seed must exercise the node fault axis"
        assert check_invariants(ch.raw_store) == []
        assert settled_fingerprint(ch.raw_store) == baseline, (
            f"seed {seed} diverged (faults: {ch.plan.counts})"
        )
        # repaired infrastructure: every node Ready and uncordoned again
        for node in ch.raw_store.list(Node.KIND):
            assert node_ready(node) and not node.unschedulable


def test_node_lifecycle_tour_runs():
    """The executable doc (examples/operations_tour.py) for the node
    lifecycle subsystem runs end to end without the service extras."""
    import sys
    from pathlib import Path

    sys.path.insert(
        0, str(Path(__file__).resolve().parent.parent / "examples")
    )
    import operations_tour

    operations_tour.node_lifecycle_tour()
