"""E2E race matrix: gang scheduling x scaling (GS5-GS12) and rolling
update x scale-in/out races (RU10-RU21), after the reference's scenarios
(operator/e2e/tests/gang_scheduling_test.go:329-1187 and
rolling_updates_test.go). The reference drives capacity with node
cordons against 1-pod-per-node k3d workers; here 1-cpu nodes give the
same forcing. Races are driven by interleaving store mutations between
partial manager.run_once() steps instead of settling between actions.
"""

from grove_tpu.api import constants
from grove_tpu.api.types import (
    Pod,
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueSet,
    PodCliqueScalingGroupConfig,
)
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness
from grove_tpu.controller.common import stable_hash

from test_e2e_basic import clique, simple_pcs
from test_e2e_updates import bump_image, pod_hashes

RETRY = constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1


def wl2(name="wl2", replicas=1, pcsg_replicas=2):
    """workload2 shape (e2e/yaml/workload2.yaml): standalone pc-a
    (replicas 2, minAvailable 1) + sg-x{pc-b(1), pc-c(3, minAvailable 1)}
    x pcsg_replicas with group minAvailable 1 -> 10 pods per PCS replica.
    Base gang min pods: pc-a-0 + sg-x-0-{pc-b-0, pc-c-0} = 3; each scaled
    sg-x replica gangs 2 min pods."""
    return simple_pcs(
        name=name,
        replicas=replicas,
        cliques=[
            clique("pc-a", replicas=2, min_available=1, cpu=1.0),
            clique("pc-b", replicas=1, cpu=1.0),
            clique("pc-c", replicas=3, min_available=1, cpu=1.0),
        ],
        sgs=[
            PodCliqueScalingGroupConfig(
                name="sg-x", clique_names=["pc-b", "pc-c"],
                replicas=pcsg_replicas, min_available=1,
            )
        ],
    )


def farm(h_nodes: int) -> Harness:
    """1-cpu nodes (1 pod per node), ALL cordoned: uncordon() meters out
    capacity exactly like the reference's cordon-based starvation."""
    h = Harness(
        nodes=make_nodes(
            h_nodes, racks_per_block=4, hosts_per_rack=4,
            allocatable={"cpu": 1.0, "memory": 8.0, "tpu": 0.0},
        )
    )
    for i in range(h_nodes):
        h.cluster.cordon(f"node-{i}")
    h._next_uncordon = 0
    return h


def uncordon(h: Harness, k: int) -> None:
    for i in range(h._next_uncordon, h._next_uncordon + k):
        h.cluster.uncordon(f"node-{i}")
    h._next_uncordon += k
    h.settle()
    h.advance(RETRY)  # starved best-effort pods sit on the retry timer


def bound(h: Harness) -> set[str]:
    return {p.metadata.name for p in h.store.list(Pod.KIND) if p.node_name}


def scale_pcsg(h: Harness, fqn: str, replicas: int) -> None:
    pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", fqn)
    pcsg.spec.replicas = replicas
    h.store.update(pcsg)


def drive_until(h, predicate, max_steps=128):
    """Step the manager+kubelet until predicate() holds (races are driven
    between partial steps, never via full settles)."""
    for _ in range(max_steps):
        h.manager.run_once()
        h.kubelet.tick()
        if predicate():
            return True
    return False


def scale_pcs(h: Harness, name: str, replicas: int) -> None:
    pcs = h.store.get(PodCliqueSet.KIND, "default", name)
    pcs.spec.replicas = replicas
    h.store.update(pcs)


class TestGS_MinReplicaScaling:
    """GS5-GS8: minReplicas x PCSG scaling under capacity starvation."""

    def test_gs5_min_replicas_bind_first_then_rest(self):
        h = farm(10)
        h.apply(wl2())
        h.settle()
        pods = h.store.list(Pod.KIND)
        assert len(pods) == 10 and not bound(h)
        uncordon(h, 3)
        # exactly the base-gang min pods (all-or-nothing at min-replica cut)
        assert bound(h) == {
            "wl2-0-pc-a-0", "wl2-0-sg-x-0-pc-b-0", "wl2-0-sg-x-0-pc-c-0",
        }
        uncordon(h, 7)
        assert len(bound(h)) == 10
        # 1-cpu nodes force the reference's distinct-nodes property
        nodes_used = {p.node_name for p in h.store.list(Pod.KIND)}
        assert len(nodes_used) == 10

    def test_gs6_pcsg_scale_out_gangs_new_min_first(self):
        h = farm(14)
        h.apply(wl2())
        h.settle()
        uncordon(h, 3)
        assert len(bound(h)) == 3
        uncordon(h, 7)
        assert len(bound(h)) == 10
        scale_pcsg(h, "wl2-0-sg-x", 3)
        h.settle()
        assert len(h.store.list(Pod.KIND)) == 14
        assert len(bound(h)) == 10  # new pods pending: no capacity
        uncordon(h, 2)
        # the new scaled gang's min pods bind (sg-x-2: pc-b-0 + pc-c-0)
        assert bound(h) >= {
            "wl2-0-sg-x-2-pc-b-0", "wl2-0-sg-x-2-pc-c-0",
        }
        assert len(bound(h)) == 12
        uncordon(h, 2)
        assert len(bound(h)) == 14

    def test_gs7_scaled_gang_outranks_best_effort_singles(self):
        """GS7 step 6: with the base gang placed and capacity for 2, the
        NEXT scaled gang's min pods win over the base gang's best-effort
        extras (gang all-or-nothing before best-effort singles)."""
        h = farm(10)
        h.apply(wl2())
        h.settle()
        uncordon(h, 3)
        assert len(bound(h)) == 3
        uncordon(h, 2)
        assert bound(h) >= {
            "wl2-0-sg-x-1-pc-b-0", "wl2-0-sg-x-1-pc-c-0",
        }
        assert len(bound(h)) == 5
        uncordon(h, 5)
        assert len(bound(h)) == 10

    def test_gs8_scale_out_while_everything_pending(self):
        h = farm(14)
        h.apply(wl2())
        h.settle()
        scale_pcsg(h, "wl2-0-sg-x", 3)
        h.settle()
        assert len(h.store.list(Pod.KIND)) == 14 and not bound(h)
        uncordon(h, 3)
        # base only: scaled-gang pods stay gated until the base schedules
        assert len(bound(h)) == 3
        uncordon(h, 4)
        # both scaled gangs (sg-x-1, sg-x-2) bind their 2 min pods each
        assert len(bound(h)) == 7
        uncordon(h, 7)
        assert len(bound(h)) == 14


class TestGS_PCSScaling:
    """GS9-GS12: PCS replica scaling x minReplicas under starvation."""

    def test_gs9_pcs_scale_out_second_replica_mins_first(self):
        h = farm(20)
        h.apply(wl2())
        h.settle()
        uncordon(h, 3)
        uncordon(h, 7)
        assert len(bound(h)) == 10
        scale_pcs(h, "wl2", 2)
        h.settle()
        assert len(h.store.list(Pod.KIND)) == 20
        uncordon(h, 3)
        assert bound(h) >= {
            "wl2-1-pc-a-0", "wl2-1-sg-x-0-pc-b-0", "wl2-1-sg-x-0-pc-c-0",
        }
        assert len(bound(h)) == 13
        uncordon(h, 7)
        assert len(bound(h)) == 20

    def test_gs10_early_pcs_scale_both_bases_bind_together(self):
        h = farm(20)
        h.apply(wl2())
        h.settle()
        scale_pcs(h, "wl2", 2)
        h.settle()
        assert len(h.store.list(Pod.KIND)) == 20 and not bound(h)
        uncordon(h, 6)
        # both base gangs' min pods (3 each)
        assert len(bound(h)) == 6
        uncordon(h, 4)
        # both sg-x-1 scaled gangs (2 each)
        assert len(bound(h)) == 10
        uncordon(h, 10)
        assert len(bound(h)) == 20

    def test_gs11_interleaved_pcs_and_pcsg_scaling(self):
        h = farm(28)
        h.apply(wl2())
        h.settle()
        uncordon(h, 3)
        uncordon(h, 7)
        assert len(bound(h)) == 10
        scale_pcsg(h, "wl2-0-sg-x", 3)
        h.settle()
        uncordon(h, 2)
        assert len(bound(h)) == 12
        uncordon(h, 2)
        assert len(bound(h)) == 14
        scale_pcs(h, "wl2", 2)
        h.settle()
        assert len(h.store.list(Pod.KIND)) == 24  # replica 1 keeps template sg-x=2
        uncordon(h, 3)
        assert len(bound(h)) == 17
        uncordon(h, 7)
        assert len(bound(h)) == 24
        scale_pcsg(h, "wl2-1-sg-x", 3)
        h.settle()
        uncordon(h, 2)
        assert len(bound(h)) == 26
        uncordon(h, 2)
        assert len(bound(h)) == 28

    def test_gs12_complex_everything_scaled_while_pending(self):
        h = farm(28)
        h.apply(wl2())
        h.settle()
        scale_pcs(h, "wl2", 2)
        h.settle()
        scale_pcsg(h, "wl2-0-sg-x", 3)
        scale_pcsg(h, "wl2-1-sg-x", 3)
        h.settle()
        assert len(h.store.list(Pod.KIND)) == 28 and not bound(h)
        uncordon(h, 6)
        assert len(bound(h)) == 6  # both bases
        uncordon(h, 8)
        assert len(bound(h)) == 14  # 4 scaled gangs x 2 min pods
        uncordon(h, 14)
        assert len(bound(h)) == 28


class TestRU_UpdateUnderStarvation:
    def test_ru10_update_pauses_under_insufficient_capacity(self):
        """RU10 (rolling_updates_test.go:155-262): with all nodes cordoned
        the rollout may sacrifice at most its single in-flight victim, must
        then PAUSE (no second deletion while the replacement can't bind),
        and completes once capacity returns."""
        h = farm(8)
        for i in range(8):
            h.cluster.uncordon(f"node-{i}")
        h._next_uncordon = 8
        h.apply(simple_pcs(cliques=[clique("w", replicas=4, min_available=3,
                                           cpu=1.0)]))
        h.settle()
        assert len(bound(h)) == 4
        for i in range(8):
            h.cluster.cordon(f"node-{i}")
        h.settle()
        original = {p.metadata.name: p.metadata.uid
                    for p in h.store.list(Pod.KIND)}
        bump_image(h)
        h.settle()
        h.advance(RETRY)
        h.advance(300.0)
        pods = {p.metadata.name: p for p in h.store.list(Pod.KIND)}
        survivors = [n for n, uid in original.items()
                     if n in pods and pods[n].metadata.uid == uid]
        # at most ONE original pod replaced; everyone else still running
        assert len(survivors) >= 3, survivors
        ready = sum(1 for p in pods.values() if p.status.ready)
        assert ready >= 3, f"availability collapsed to {ready}"
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert not pcs.status.rolling_update_progress.completed
        # capacity returns -> rollout resumes and completes
        for i in range(8):
            h.cluster.uncordon(f"node-{i}")
        h.settle()
        h.advance(RETRY)
        h.advance(RETRY)
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.rolling_update_progress.completed
        target = stable_hash(pcs.spec.template.cliques[0].spec.pod_spec)
        assert set(pod_hashes(h).values()) == {target}
        assert all(p.status.ready for p in h.store.list(Pod.KIND))


class TestRU_PCSScaleRaces:
    def two_replica(self, name="r"):
        return simple_pcs(name=name, replicas=2,
                          cliques=[clique("w", replicas=2, cpu=1.0)])

    def test_ru11_pcs_scale_out_during_update(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.two_replica())
        h.settle()
        bump_image(h, "r")
        h.manager.run_once()  # update starts (one replica in flight)
        scale_pcs(h, "r", 3)
        h.settle()
        pcs = h.store.get(PodCliqueSet.KIND, "default", "r")
        assert pcs.status.rolling_update_progress.completed
        target = stable_hash(pcs.spec.template.cliques[0].spec.pod_spec)
        hashes = pod_hashes(h)
        assert len(hashes) == 6
        assert set(hashes.values()) == {target}
        # the scaled-out replica was born on the new template: its pods
        # were never churned by the update
        r2 = [p for p in h.store.list(Pod.KIND)
              if p.metadata.labels[constants.LABEL_PCS_REPLICA_INDEX] == "2"]
        assert r2 and all(
            p.metadata.labels[constants.LABEL_POD_TEMPLATE_HASH] == target
            for p in r2
        )

    def test_ru12_pcs_scale_in_while_final_ordinal_updating(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.two_replica())
        h.settle()
        bump_image(h, "r")

        def final_ordinal_in_flight():
            pcs = h.store.get(PodCliqueSet.KIND, "default", "r")
            prog = pcs.status.rolling_update_progress
            return (prog is not None and not prog.completed
                    and prog.current_replica_index is not None
                    and len(prog.updated_replica_indices) == 1)

        assert drive_until(h, final_ordinal_in_flight)
        pcs = h.store.get(PodCliqueSet.KIND, "default", "r")
        victim = pcs.status.rolling_update_progress.current_replica_index
        scale_pcs(h, "r", 1)  # scale in while ordinal `victim` mid-update
        h.settle()
        pcs = h.store.get(PodCliqueSet.KIND, "default", "r")
        prog = pcs.status.rolling_update_progress
        assert prog.completed, (
            f"update wedged: current_replica_index={prog.current_replica_index}"
            f" (victim was {victim}), updated={prog.updated_replica_indices}"
        )
        hashes = pod_hashes(h)
        assert len(hashes) == 2  # one replica left
        target = stable_hash(pcs.spec.template.cliques[0].spec.pod_spec)
        assert set(hashes.values()) == {target}
        # stale indices from scaled-away replicas must be pruned: status
        # can never report more updated replicas than exist
        assert pcs.status.updated_replicas <= pcs.spec.replicas

    def test_ru13_pcs_scale_in_after_update_completes(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.two_replica())
        h.settle()
        bump_image(h, "r")
        h.settle()
        pcs = h.store.get(PodCliqueSet.KIND, "default", "r")
        assert pcs.status.rolling_update_progress.completed
        scale_pcs(h, "r", 1)
        h.settle()
        hashes = pod_hashes(h)
        assert len(hashes) == 2
        pcs = h.store.get(PodCliqueSet.KIND, "default", "r")
        assert pcs.status.rolling_update_progress.completed
        assert all(p.status.ready for p in h.store.list(Pod.KIND))


class TestRU_PCSGScaleRaces:
    def sg_pcs(self, name="sg", replicas=2):
        return simple_pcs(
            name=name,
            cliques=[clique("w", replicas=2, cpu=1.0)],
            sgs=[PodCliqueScalingGroupConfig(
                name="grp", clique_names=["w"], replicas=replicas,
                min_available=1)],
        )

    def pcsg_prog(self, h, name="sg-0-grp"):
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", name)
        return pcsg.status.rolling_update_progress

    def test_ru14_pcsg_scale_out_during_update(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.sg_pcs())
        h.settle()
        bump_image(h, "sg")
        assert drive_until(
            h, lambda: (p := self.pcsg_prog(h)) is not None
            and p.current_replica_index is not None
        )
        scale_pcsg(h, "sg-0-grp", 3)
        h.settle()
        prog = self.pcsg_prog(h)
        assert prog.completed
        target = stable_hash(
            h.store.get(PodCliqueSet.KIND, "default", "sg")
            .spec.template.cliques[0].spec.pod_spec
        )
        hashes = pod_hashes(h)
        assert len(hashes) == 6
        assert set(hashes.values()) == {target}
        pcs = h.store.get(PodCliqueSet.KIND, "default", "sg")
        assert pcs.status.rolling_update_progress.completed

    def test_ru15_pcsg_scale_out_before_update(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(self.sg_pcs())
        h.settle()
        scale_pcsg(h, "sg-0-grp", 3)
        h.settle()
        # new replica born pre-update on the OLD template
        assert len(h.store.list(Pod.KIND)) == 6
        bump_image(h, "sg")
        h.settle()
        target = stable_hash(
            h.store.get(PodCliqueSet.KIND, "default", "sg")
            .spec.template.cliques[0].spec.pod_spec
        )
        hashes = pod_hashes(h)
        assert len(hashes) == 6
        assert set(hashes.values()) == {target}
        prog = self.pcsg_prog(h)
        assert prog.completed
        assert sorted(prog.updated_replica_indices) == [0, 1, 2]

    def test_ru16_pcsg_scale_in_while_last_replica_updating(self):
        h = Harness(nodes=make_nodes(24))
        h.apply(self.sg_pcs(replicas=3))
        h.settle()
        bump_image(h, "sg")
        assert drive_until(
            h, lambda: (p := self.pcsg_prog(h)) is not None
            and p.current_replica_index == 2
        )
        scale_pcsg(h, "sg-0-grp", 2)  # the updating replica disappears
        h.settle()
        prog = self.pcsg_prog(h)
        assert prog is not None and prog.completed, (
            f"PCSG update wedged on vanished replica: "
            f"current={prog.current_replica_index} "
            f"updated={prog.updated_replica_indices}"
        )
        pcs = h.store.get(PodCliqueSet.KIND, "default", "sg")
        assert pcs.status.rolling_update_progress.completed
        target = stable_hash(pcs.spec.template.cliques[0].spec.pod_spec)
        hashes = pod_hashes(h)
        assert len(hashes) == 4
        assert set(hashes.values()) == {target}
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "sg-0-grp")
        assert pcsg.status.updated_replicas <= pcsg.spec.replicas
        assert all(
            i < pcsg.spec.replicas
            for i in prog.updated_replica_indices
        )

    def test_ru17_pcsg_scale_in_before_update(self):
        h = Harness(nodes=make_nodes(24))
        h.apply(self.sg_pcs(replicas=3))
        h.settle()
        scale_pcsg(h, "sg-0-grp", 2)
        h.settle()
        assert len(h.store.list(Pod.KIND)) == 4
        bump_image(h, "sg")
        h.settle()
        prog = self.pcsg_prog(h)
        assert prog.completed
        target = stable_hash(
            h.store.get(PodCliqueSet.KIND, "default", "sg")
            .spec.template.cliques[0].spec.pod_spec
        )
        assert set(pod_hashes(h).values()) == {target}


class TestRU_PodCliqueScaleRaces:
    """RU18/RU20: standalone-PCLQ scale (the HPA path mutates
    PodClique.spec.replicas directly) racing its own pod-at-a-time
    rollout."""

    def scale_pclq(self, h, fqn, replicas):
        pclq = h.store.get(PodClique.KIND, "default", fqn)
        pclq.spec.replicas = replicas
        h.store.update(pclq)

    def mid_rollout(self, h, fqn="s-0-w"):
        pclq = h.store.get(PodClique.KIND, "default", fqn)
        prog = pclq.status.rolling_update_progress
        return prog is not None and not prog.completed

    def test_ru18_pclq_scale_out_during_update(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(simple_pcs(name="s", cliques=[clique("w", replicas=3,
                                                     min_available=2,
                                                     cpu=1.0)]))
        h.settle()
        bump_image(h, "s")
        assert drive_until(h, lambda: self.mid_rollout(h))
        self.scale_pclq(h, "s-0-w", 4)
        h.settle()
        h.advance(RETRY)
        pods = h.store.list(Pod.KIND)
        assert len(pods) == 4
        target = stable_hash(
            h.store.get(PodCliqueSet.KIND, "default", "s")
            .spec.template.cliques[0].spec.pod_spec
        )
        assert set(pod_hashes(h).values()) == {target}
        assert all(p.status.ready for p in pods)
        pclq = h.store.get(PodClique.KIND, "default", "s-0-w")
        assert pclq.status.rolling_update_progress.completed
        assert pclq.status.updated_replicas == 4

    def test_ru20_pclq_scale_in_during_update(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(simple_pcs(name="s", cliques=[clique("w", replicas=3,
                                                     min_available=2,
                                                     cpu=1.0)]))
        h.settle()
        bump_image(h, "s")
        assert drive_until(h, lambda: self.mid_rollout(h))
        self.scale_pclq(h, "s-0-w", 2)
        h.settle()
        h.advance(RETRY)
        pods = h.store.list(Pod.KIND)
        assert len(pods) == 2
        target = stable_hash(
            h.store.get(PodCliqueSet.KIND, "default", "s")
            .spec.template.cliques[0].spec.pod_spec
        )
        assert set(pod_hashes(h).values()) == {target}
        assert all(p.status.ready for p in pods)
        pclq = h.store.get(PodClique.KIND, "default", "s-0-w")
        assert pclq.status.rolling_update_progress.completed
        assert pclq.status.updated_replicas == 2


class TestRU_PodCliqueScaleBeforeUpdate:
    """RU19/RU21: standalone-PCLQ scale BEFORE the update starts; the
    resized clique then rolls to the new template exactly once."""

    def apply_s(self, h):
        h.apply(simple_pcs(name="s", cliques=[clique("w", replicas=3,
                                                     min_available=2,
                                                     cpu=1.0)]))
        h.settle()

    def scale_pclq(self, h, replicas):
        pclq = h.store.get(PodClique.KIND, "default", "s-0-w")
        pclq.spec.replicas = replicas
        h.store.update(pclq)
        h.settle()
        h.advance(RETRY)

    def finish(self, h, expect_pods):
        h.settle()
        h.advance(RETRY)
        pods = h.store.list(Pod.KIND)
        assert len(pods) == expect_pods
        target = stable_hash(
            h.store.get(PodCliqueSet.KIND, "default", "s")
            .spec.template.cliques[0].spec.pod_spec
        )
        assert set(pod_hashes(h).values()) == {target}
        assert all(p.status.ready for p in pods)
        pclq = h.store.get(PodClique.KIND, "default", "s-0-w")
        assert pclq.status.rolling_update_progress.completed
        assert pclq.status.updated_replicas == expect_pods

    def test_ru19_pclq_scale_out_before_update(self):
        h = Harness(nodes=make_nodes(16))
        self.apply_s(h)
        self.scale_pclq(h, 5)
        assert len(h.store.list(Pod.KIND)) == 5
        before_uids = {p.metadata.uid for p in h.store.list(Pod.KIND)}
        bump_image(h, "s")
        self.finish(h, expect_pods=5)
        # every pod was replaced exactly once (all new uids)
        after_uids = {p.metadata.uid for p in h.store.list(Pod.KIND)}
        assert not (before_uids & after_uids)

    def test_ru21_pclq_scale_in_before_update(self):
        h = Harness(nodes=make_nodes(16))
        self.apply_s(h)
        self.scale_pclq(h, 2)
        assert len(h.store.list(Pod.KIND)) == 2
        bump_image(h, "s")
        self.finish(h, expect_pods=2)


class TestRU_TerminationDuringUpdate:
    """The remaining named race: a replica breaches MinAvailable and its
    termination delay expires WHILE the rolling update is mid-flight —
    gang termination rebuilds the replica and the update still completes
    on the new template."""

    def test_gang_termination_mid_update_converges(self):
        h = Harness(nodes=make_nodes(16))
        pcs = simple_pcs(name="t", replicas=2,
                         cliques=[clique("w", replicas=2, cpu=1.0)])
        pcs.spec.template.termination_delay = 30.0
        h.apply(pcs)
        h.settle()
        bump_image(h, "t")
        # start the update, then crash BOTH pods of the OTHER replica so
        # it breaches while ordinal 0/1 is mid-update
        for _ in range(3):
            h.manager.run_once()
            h.kubelet.tick()
        pcs_live = h.store.get(PodCliqueSet.KIND, "default", "t")
        updating = pcs_live.status.rolling_update_progress.current_replica_index
        victim_replica = 1 - updating
        for i in range(2):
            h.kubelet.crash_pod("default", f"t-{victim_replica}-w-{i}")
        h.settle()
        # the breach clock runs out mid-update -> gang termination rebuilds
        h.advance(31.0)
        h.settle()
        h.advance(RETRY)
        h.advance(RETRY)
        pcs_live = h.store.get(PodCliqueSet.KIND, "default", "t")
        assert pcs_live.status.rolling_update_progress.completed
        target = stable_hash(pcs_live.spec.template.cliques[0].spec.pod_spec)
        assert set(pod_hashes(h).values()) == {target}
        pods = h.store.list(Pod.KIND)
        assert len(pods) == 4 and all(p.status.ready for p in pods)


class TestRU_BackToBackTemplateChanges:
    """A second template change lands while the first update is
    mid-flight: the update restarts toward the NEW target and every pod
    converges to v3 — no pod is left on v2, no wedge."""

    def test_back_to_back_updates_converge_on_final_template(self):
        h = Harness(nodes=make_nodes(16))
        h.apply(simple_pcs(name="bb", replicas=2,
                           cliques=[clique("w", replicas=2, cpu=1.0)]))
        h.settle()

        bump_image(h, "bb", tag="app:v2")
        for _ in range(4):  # v2 rollout mid-flight
            h.manager.run_once()
            h.kubelet.tick()
        pcs = h.store.get(PodCliqueSet.KIND, "default", "bb")
        assert not pcs.status.rolling_update_progress.completed
        v2_target = pcs.status.rolling_update_progress.target_generation_hash
        bump_image(h, "bb", tag="app:v3")  # restart toward the new target
        h.settle()
        h.advance(RETRY)
        pcs = h.store.get(PodCliqueSet.KIND, "default", "bb")
        prog = pcs.status.rolling_update_progress
        assert prog.completed
        assert prog.target_generation_hash != v2_target
        assert pcs.status.current_generation_hash == prog.target_generation_hash
        target = stable_hash(pcs.spec.template.cliques[0].spec.pod_spec)
        hashes = pod_hashes(h)
        assert len(hashes) == 4
        assert set(hashes.values()) == {target}, "a pod stuck on v2"
        assert all(p.status.ready for p in h.store.list(Pod.KIND))


class TestRU_PreemptionDuringUpdate:
    """Cross-feature race: a high-priority gang preempts the updating
    workload's SCALED gang mid-rolling-update. The update of the base
    replica still completes; the victim re-queues at its priority."""

    def test_preemption_mid_update_still_converges(self):
        from grove_tpu.api.auxiliary import PriorityClass
        from grove_tpu.api.meta import ObjectMeta, get_condition
        from grove_tpu.api.podgang import PodGang

        h = Harness(nodes=make_nodes(
            4, racks_per_block=2, hosts_per_rack=2,
            allocatable={"cpu": 1.0, "memory": 8.0, "tpu": 0.0}))
        low = simple_pcs(
            name="low",
            cliques=[clique("w", replicas=2, cpu=1.0)],
            sgs=[PodCliqueScalingGroupConfig(
                name="grp", clique_names=["w"], replicas=2,
                min_available=1)],
        )
        h.apply(low)
        h.settle()
        assert len(bound(h)) == 4  # cluster exactly full
        bump_image(h, "low", tag="app:v2")
        for _ in range(3):  # update mid-flight
            h.manager.run_once()
            h.kubelet.tick()
        h.store.create(PriorityClass(
            metadata=ObjectMeta(name="gold", namespace=""), value=1000.0))
        hi = simple_pcs(name="hi", cliques=[clique("w", replicas=2,
                                                   cpu=1.0)])
        hi.spec.template.priority_class_name = "gold"
        h.apply(hi)  # needs 2; cluster is full -> preempts low's scaled gang
        h.settle()
        h.advance(RETRY)
        h.advance(RETRY)
        # high-priority workload placed
        hi_gang = h.store.get(PodGang.KIND, "default", "hi-0")
        assert get_condition(hi_gang.status.conditions,
                             "Scheduled").status == "True"
        assert h.cluster.metrics.counter(
            "grove_scheduler_preemptions_total").total() >= 1
        # base gang survived the preemption...
        base = h.store.get(PodGang.KIND, "default", "low-0")
        assert get_condition(base.status.conditions,
                             "Scheduled").status == "True"
        # ...and the update PAUSES (RU10 semantics: the displaced scaled
        # replica cannot re-ready on a full cluster) instead of wedging or
        # collapsing availability
        pcs = h.store.get(PodCliqueSet.KIND, "default", "low")
        assert not pcs.status.rolling_update_progress.completed
        # capacity returns -> victim re-places AND the update completes
        for n in make_nodes(2, name_prefix="extra",
                            allocatable={"cpu": 1.0, "memory": 8.0,
                                         "tpu": 0.0}):
            h.store.create(n)
        h.advance(RETRY)
        h.advance(RETRY)
        pcs = h.store.get(PodCliqueSet.KIND, "default", "low")
        assert pcs.status.rolling_update_progress.completed
        target = stable_hash(pcs.spec.template.cliques[0].spec.pod_spec)
        low_pods = h.store.list(Pod.KIND,
                                labels={constants.LABEL_PART_OF: "low"})
        assert len(low_pods) == 4
        assert all(
            p.node_name and p.status.ready
            and p.metadata.labels[constants.LABEL_POD_TEMPLATE_HASH] == target
            for p in low_pods
        )
        scaled = h.store.get(PodGang.KIND, "default", "low-0-grp-0")
        assert get_condition(scaled.status.conditions,
                             "Scheduled").status == "True"
