"""Continuous defragmentation (controller/defrag.py): DefragConfig
validation, the solver what-if API (device path, state isolation,
dispatch attribution), the shared DisruptionLedger, the scheduler's
migration machinery (tickets, make-before-break binds, reservation
staleness on migration), the end-to-end sweep (admission arithmetic,
audits, rate bound, budget sharing with preemption), and defrag chaos
(migration storms, crash mid-migration, destination node faults)."""

import numpy as np
import pytest

from grove_tpu.api.config import load_operator_config
from grove_tpu.api.meta import ObjectMeta, get_condition
from grove_tpu.api.podgang import PodGang, PodGangConditionType
from grove_tpu.api.types import (
    Container,
    Pod,
    PodCliqueSet,
    PodCliqueSetSpec,
    PodCliqueSetTemplateSpec,
    PodCliqueSpec,
    PodCliqueTemplateSpec,
    PodSpec,
)
from grove_tpu.api.validation import ValidationError
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness
from grove_tpu.solver import PlacementEngine
from grove_tpu.tenancy import DisruptionLedger

from test_solver import cluster, gang

DEFRAG = {
    "enabled": True,
    "sync_interval_seconds": 60.0,
    "min_score_gain": 0.05,
    "migration_cost_score": 0.02,
    "max_moves_per_sweep": 4,
    "max_evictions_per_hour": 120.0,
}


def pcs(name, pods, cpu=1.0):
    return PodCliqueSet(
        metadata=ObjectMeta(name=name),
        spec=PodCliqueSetSpec(
            replicas=1,
            template=PodCliqueSetTemplateSpec(cliques=[
                PodCliqueTemplateSpec(
                    name="w",
                    spec=PodCliqueSpec(
                        replicas=pods,
                        pod_spec=PodSpec(containers=[
                            Container(name="m", resources={"cpu": cpu})
                        ]),
                    ),
                )
            ]),
        ),
    )


def gang_nodes(h, name):
    g = next(
        x for x in h.store.scan(PodGang.KIND)
        if x.metadata.name.startswith(name)
    )
    nodes = [
        h.store.peek(Pod.KIND, r.namespace, r.name).node_name
        for gr in g.spec.pod_groups for r in gr.pod_references
    ]
    return g, nodes


def frag_harness(config=None, tenants=None):
    """Deterministic fragmentation: 8 nodes (2 blocks x 2 racks x
    2 hosts, 2 cpu each) filled by 16 one-cpu singles that stack node
    by node; freeing ONE cpu on two different BLOCKS forces the 2-pod
    target gang to span the cluster root (score 0.25); freeing a whole
    node in one rack then gives the defragmenter a host-level (1.0)
    destination. Returns (harness, {single gang name -> node})."""
    cfg = {"defrag": dict(DEFRAG)}
    if config:
        cfg.update(config)
    if tenants is not None:
        cfg["tenancy"] = {"enabled": True, "tenants": tenants}
    h = Harness(
        nodes=make_nodes(
            8, racks_per_block=2, hosts_per_rack=2,
            allocatable={"cpu": 2.0, "memory": 16.0, "tpu": 0.0},
        ),
        config=cfg,
    )
    for i in range(16):
        h.apply(pcs(f"s{i}", 1, 1.0))
        h.settle()
    node_of = {}
    for g in h.store.scan(PodGang.KIND):
        ref = g.spec.pod_groups[0].pod_references[0]
        node_of[g.metadata.name.split("-")[0]] = h.store.peek(
            Pod.KIND, ref.namespace, ref.name
        ).node_name
    return h, node_of


def free_one_on(h, node_of, node):
    """Delete one filler single bound to `node` (cascade via its PCS)."""
    for name, n in sorted(node_of.items()):
        if n == node:
            h.store.delete(PodCliqueSet.KIND, "default", name)
            del node_of[name]
            return name
    raise AssertionError(f"no filler on {node}")


def spanning_target(h, node_of):
    """Free 1 cpu on two different blocks, place the 2-pod target gang
    across them, and return (gang, its nodes)."""
    nodes = sorted(set(node_of.values()))
    free_one_on(h, node_of, nodes[0])   # block 0
    free_one_on(h, node_of, nodes[4])   # block 1
    h.settle()
    h.apply(pcs("target", 2, 1.0))
    h.settle()
    g, placed = gang_nodes(h, "target")
    assert g.status.placement_score == 0.25  # spans the cluster root
    return g, placed


# -- config validation --------------------------------------------------------

class TestDefragConfig:
    def test_disabled_by_default(self):
        cfg = load_operator_config(None)
        assert cfg.defrag.enabled is False

    def test_valid_config_loads(self):
        cfg = load_operator_config({"defrag": dict(DEFRAG)})
        assert cfg.defrag.enabled and cfg.defrag.min_score_gain == 0.05

    @pytest.mark.parametrize("field,value", [
        ("sync_interval_seconds", 0),
        ("min_score_gain", 0),
        ("migration_cost_score", -0.1),
        ("max_moves_per_sweep", 0),
        ("max_evictions_per_hour", 0),
        ("candidates_per_sweep", 0),
        ("enabled", "yes"),
    ])
    def test_invalid_configs_rejected(self, field, value):
        with pytest.raises(ValidationError) as err:
            load_operator_config({"defrag": {field: value}})
        assert f"defrag.{field}" in str(err.value)

    def test_budget_window_validated(self):
        with pytest.raises(ValidationError) as err:
            load_operator_config(
                {"tenancy": {"disruption_budget_window_seconds": 0}}
            )
        assert "disruption_budget_window_seconds" in str(err.value)


# -- the solver what-if API ---------------------------------------------------

class TestWhatIf:
    def setup_engine(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        eng = PlacementEngine(snap, state_verify=True)
        gangs = [gang(f"g{i}", pods=2, cpu=2.0) for i in range(4)]
        free = snap.free.copy()
        eng.solve(gangs, free=free)
        return snap, eng, gangs, free

    def test_whatif_counts_its_own_kind_and_mutates_nothing(self):
        snap, eng, gangs, free = self.setup_engine()
        # the FIRST what-if may legitimately stage the previous solve's
        # repair commits (free was mutated in place — a real content
        # change, delta-staged like any sync)
        res = eng.whatif_scores(
            [gang("w0", pods=2, cpu=2.0)], free=free
        )
        assert res is not None
        top_val, top_dom, order = res
        assert top_val.shape == top_dom.shape
        assert [g.name for g in order] == ["w0"]
        assert eng._dispatches["whatif"] == 1
        # from here the content is synced: a second what-if mutates
        # NOTHING resident — epoch, incremental cache, staged rows are
        # all untouched (staged is peeked, never consumed)
        epoch = eng._state.epoch
        inc = eng._inc
        staged = None if eng._staged is None else dict(eng._staged)
        res2 = eng.whatif_scores(
            [gang("w1", pods=2, cpu=2.0)], free=free
        )
        assert res2 is not None
        assert eng._dispatches["whatif"] == 2
        assert eng._state.epoch == epoch
        assert eng._inc is inc
        assert (eng._staged or None) == (staged or None)
        # and a real solve afterwards passes the armed state_verify
        # tripwire — the what-ifs corrupted nothing resident
        res3 = eng.solve(
            [gang(f"h{i}", pods=2, cpu=2.0) for i in range(3)],
            free=free,
        )
        assert res3.num_placed == 3

    def test_whatif_rankings_match_a_real_solve(self):
        snap, eng, gangs, free = self.setup_engine()
        probe = gang("w0", pods=2, cpu=2.0)
        top_val, top_dom, order = eng.whatif_scores([probe], free=free)
        # the top-ranked domain admits an exact placement (the engine's
        # own repair discipline)
        from grove_tpu.solver.fit import place_gang_in_domain

        node_idx, level = eng.space.nodes_of(
            int(top_dom[0, 0]), np.flatnonzero(snap.schedulable)
        )
        trial = free.copy()
        assert place_gang_in_domain(
            probe, snap, trial, node_idx, level
        ) is not None

    def test_free_rows_overlay_changes_the_ranking(self):
        snap = cluster(blocks=1, racks=2, hosts=2, cpu=4.0)
        eng = PlacementEngine(snap)
        filler = [gang(f"f{i}", pods=1, cpu=4.0) for i in range(2)]
        free = snap.free.copy()
        res = eng.solve(filler, free=free)  # fills rack 0 (2 nodes)
        assert res.num_placed == 2
        probe = gang("w0", pods=1, cpu=4.0)
        committed = sorted(
            i for p in res.placed.values() for i in p.node_indices
        )
        # hypothetically return a committed node's capacity: the
        # what-if against the overlay must score strictly better
        # somewhere than against the residual state
        base_val, _, _ = eng.whatif_scores([probe], free=free)
        over_val, _, _ = eng.whatif_scores(
            [probe], free=free,
            free_rows={committed[0]: snap.capacity[committed[0]]},
        )
        assert over_val.max() > base_val.max()

    def test_cache_off_returns_none(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        eng = PlacementEngine(snap, state_cache=False)
        eng.solve([gang("g0", pods=2, cpu=2.0)], free=snap.free.copy())
        assert eng.whatif_scores(
            [gang("w0", pods=2, cpu=2.0)], free=snap.free.copy()
        ) is None

    def test_unsynced_engine_returns_none(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        eng = PlacementEngine(snap)
        assert eng.whatif_scores([gang("w0", pods=2, cpu=2.0)]) is None

    def test_dispatch_counts_surface(self):
        snap, eng, gangs, free = self.setup_engine()
        counts = eng.dispatch_counts()
        assert counts["fused"] == 1 and counts["whatif"] == 0
        assert counts["state_full_uploads"] == 1


# -- the shared disruption ledger ---------------------------------------------

class TestDisruptionLedger:
    def test_charge_spent_breakdown(self):
        led = DisruptionLedger(window_seconds=60.0)
        led.charge("a", "preemption", now=0.0)
        led.charge("a", "defrag", now=10.0, n=2)
        assert led.spent("a", now=10.0) == 3
        assert led.breakdown("a", now=10.0) == {
            "preemption": 1, "defrag": 2,
        }
        assert led.spent("b", now=10.0) == 0

    def test_window_expiry(self):
        led = DisruptionLedger(window_seconds=60.0)
        led.charge("a", "defrag", now=0.0)
        assert led.spent("a", now=59.0) == 1
        assert led.spent("a", now=61.0) == 0
        assert led.breakdown("a", now=61.0) == {}

    def test_charge_prunes_expired_entries_for_unread_tenants(self):
        """Review regression: tenants without a configured budget are
        charged (preemption charges every victim tenant) but never
        read — pruning must happen on write too, or the ledger grows
        without bound across weeks of eviction churn."""
        led = DisruptionLedger(window_seconds=60.0)
        for i in range(100):
            led.charge("unread", "preemption", now=float(i * 61))
        assert len(led._spends["unread"]) == 1

    def test_manager_owns_one_ledger_across_configure(self):
        from grove_tpu.tenancy import TenancyManager

        cfg = load_operator_config({"tenancy": {
            "enabled": True, "tenants": [{"name": "a"}],
            "disruption_budget_window_seconds": 30.0,
        }}).tenancy
        m = TenancyManager(cfg)
        led = m.ledger
        assert led.window == 30.0
        m.configure(cfg)
        assert m.ledger is led  # spends survive reconfiguration


# -- migration machinery (scheduler) ------------------------------------------

class TestMigrationMachinery:
    def test_stage_purges_reservation_and_tombstones(self):
        h, node_of = frag_harness()
        sched = h.scheduler
        key = ("default", "s0-0")
        assert key in sched._reservations
        sched.stage_migration(
            "default", "s0-0", ("node-9",), [("default", "p")]
        )
        assert key not in sched._reservations
        assert key in sched._migrated
        assert sched._migrations[key] == ("node-9",)

    def test_migration_bind_hit_and_tombstone_cleared(self):
        h, node_of = frag_harness()
        g, placed = spanning_target(h, node_of)
        # free a whole node in one rack and sweep: the move must land
        # exactly on the held destination (make-before-break hit)
        nodes = sorted(set(node_of.values()))
        free_one_on(h, node_of, nodes[1])
        free_one_on(h, node_of, nodes[1])
        h.settle()
        stats = h.defrag_sweep()
        assert stats["admitted"] == 1
        dest = tuple(h.scheduler._migrations[("default", g.metadata.name)])
        h.settle()
        g2, placed2 = gang_nodes(h, "target")
        assert sorted(set(placed2)) == sorted(set(dest))
        assert g2.status.placement_score == 1.0
        ctr = h.cluster.metrics.counter(
            "grove_scheduler_migration_bind_total"
        )
        assert ctr.value(outcome="hit") == 1
        key = ("default", g.metadata.name)
        assert key not in sched_migrated(h)
        # the fresh reservation points at the DESTINATION
        assert set(h.scheduler._reservations[key]) == set(dest)
        # DisruptionTarget cleared at re-bind (reference vocabulary)
        cond = get_condition(
            g2.status.conditions,
            PodGangConditionType.DISRUPTION_TARGET.value,
        )
        assert cond is not None and cond.status == "False"

    def test_miss_migrated_blocks_vacated_source_reuse(self):
        """Satellite regression: a same-named successor of a migrated
        gang must NOT re-place onto the vacated source slot while the
        move is in flight — today's staleness bug."""
        h, node_of = frag_harness()
        g, placed = spanning_target(h, node_of)
        source = sorted(set(placed))
        sched = h.scheduler
        key = ("default", g.metadata.name)
        old_reservation = sched._reservations[key]
        assert sorted(old_reservation) == source
        # stage the move but DELETE the gang's PCS before it re-binds
        # (the scale-down-mid-migration window), then recreate the
        # same-named workload: reuse must count miss-migrated, not
        # silently re-place onto the source
        nodes = sorted(set(node_of.values()))
        free_one_on(h, node_of, nodes[1])
        free_one_on(h, node_of, nodes[1])
        h.settle()
        stats = h.defrag_sweep()
        assert stats["admitted"] == 1
        h.store.delete(PodCliqueSet.KIND, "default", "target")
        h.settle()
        # the successor names its predecessor (same gang name)
        h.apply(pcs("target", 2, 1.0))
        h.settle()
        ctr = h.cluster.metrics.counter(
            "grove_scheduler_reservation_reuse_total"
        )
        assert ctr.value(outcome="miss-migrated") >= 1
        g2, placed2 = gang_nodes(h, "target")
        # it re-placed (general solve), not necessarily on the source
        assert all(placed2)

    def test_vacated_hints_suppressed_for_migrated_pods(self):
        h, node_of = frag_harness()
        g, placed = spanning_target(h, node_of)
        nodes = sorted(set(node_of.values()))
        free_one_on(h, node_of, nodes[1])
        free_one_on(h, node_of, nodes[1])
        h.settle()
        pod_keys = [
            (r.namespace, r.name)
            for gr in g.spec.pod_groups for r in gr.pod_references
        ]
        stats = h.defrag_sweep()
        assert stats["admitted"] == 1
        h.settle()
        for key in pod_keys:
            assert key not in h.scheduler._vacated
        assert not h.scheduler._migration_suppress

    def test_overflow_valve_evicts_oldest_not_in_flight(self):
        """Review regression: the suppress/tombstone overflow valves
        must evict the OLDEST entries — a wholesale clear would wipe
        the move being staged right now, letting its deletions seed
        vacated hints at the just-freed source."""
        h, _ = frag_harness()
        sched = h.scheduler
        sched._migration_suppress = {
            ("stale", f"p{i}"): None for i in range(100_000)
        }
        sched._migrated = {
            ("stale", f"g{i}"): None for i in range(100_000)
        }
        fresh = [("default", "fresh-0"), ("default", "fresh-1")]
        sched.stage_migration("default", "fresh", ("node-1",), fresh)
        assert all(k in sched._migration_suppress for k in fresh)
        assert ("default", "fresh") in sched._migrated
        assert ("stale", "p0") not in sched._migration_suppress
        assert len(sched._migration_suppress) == 100_000

    def test_unstage_rolls_back_ticket_and_suppressions(self):
        """A failed eviction after staging must not strand the ticket:
        the gang would never re-enter the backlog to consume it, and a
        pending ticket excludes it from future sweeps forever."""
        h, node_of = frag_harness()
        sched = h.scheduler
        pod_keys = [("default", "p0"), ("default", "p1")]
        sched.stage_migration("default", "s0-0", ("node-9",), pod_keys)
        sched.unstage_migration("default", "s0-0", pod_keys)
        assert ("default", "s0-0") not in sched._migrations
        assert not sched._migration_suppress
        # the tombstone stays: the old reservation was already purged
        assert ("default", "s0-0") in sched._migrated

    def test_eviction_rate_window_survives_manager_restart(self):
        """The rolling evictions/hour window is cluster-owned (like the
        disruption ledger): a crash-restart cannot launder a fresh
        hourly allowance."""
        h, _ = frag_harness()
        h.defrag._evictions.append(h.clock.now())
        h._build_manager()  # the chaos crash-restart path
        assert len(h.defrag._evictions) == 1
        assert h.defrag._evictions is h.cluster.defrag_evictions

    def test_node_delete_purges_tickets(self):
        h, node_of = frag_harness()
        sched = h.scheduler
        sched.stage_migration(
            "default", "ghost", ("node-1", "node-2"), []
        )
        from grove_tpu.api.types import Node

        h.store.delete(Node.KIND, "default", "node-1")
        h.settle()
        assert ("default", "ghost") not in sched._migrations


def sched_migrated(h):
    return h.scheduler._migrated


# -- the end-to-end sweep -----------------------------------------------------

class TestDefragSweep:
    def test_admitted_move_improves_score_via_device_whatif(self):
        h, node_of = frag_harness()
        g, _ = spanning_target(h, node_of)
        nodes = sorted(set(node_of.values()))
        free_one_on(h, node_of, nodes[1])
        free_one_on(h, node_of, nodes[1])
        h.settle()
        stats = h.defrag_sweep()
        assert stats["admitted"] == 1
        assert stats["whatif"] == "device"
        h.settle()
        g2, _ = gang_nodes(h, "target")
        assert g2.status.placement_score == 1.0
        # the fleet gauge follows
        assert h.cluster.metrics.gauge(
            "grove_scheduler_placement_score"
        ).value() == 1.0
        # per-gang scores surface in the debug dump (satellite)
        dump = h.debug_dump()
        assert dump["scheduler"]["placement"]["gangs"][
            f"default/{g2.metadata.name}"
        ] == 1.0
        assert dump["defrag"]["moves_total"] == 1

    def test_whatif_attribution_has_no_full_reencode(self):
        h, node_of = frag_harness()
        g, _ = spanning_target(h, node_of)
        nodes = sorted(set(node_of.values()))
        free_one_on(h, node_of, nodes[1])
        free_one_on(h, node_of, nodes[1])
        h.settle()
        h.defrag_sweep()
        kinds = h.defrag.dispatch_kinds
        assert kinds.get("whatif", 0) == 1
        # the acceptance contract: no full re-encode attributable to
        # the sweep — the what-if rode the resident state + row deltas
        assert kinds.get("fused", 0) == 0
        assert kinds.get("split", 0) == 0
        assert kinds.get("state_full_uploads", 0) == 0

    def test_migration_audit_records_gain_cost_and_verdict(self):
        h, node_of = frag_harness()
        g, placed = spanning_target(h, node_of)
        nodes = sorted(set(node_of.values()))
        free_one_on(h, node_of, nodes[1])
        free_one_on(h, node_of, nodes[1])
        h.settle()
        h.defrag_sweep()
        ex = h.cluster.decisions.explain("default", g.metadata.name)
        rec = next(
            r for r in reversed(ex["records"])
            if r["outcome"] == "migration"
        )
        d = rec["detail"]
        assert d["verdict"] == "admitted"
        assert d["consumer"] == "defrag"
        assert d["current_score"] == 0.25
        assert d["candidate_score"] == 1.0
        assert d["gain"] == 0.75
        assert d["migration_cost"] == 0.02
        assert d["net_gain"] == 0.73
        assert sorted(d["from"]) == sorted(set(placed))
        assert d["to"]

    def test_rejected_gain_audited(self):
        h, node_of = frag_harness(
            config={"defrag": {**DEFRAG, "min_score_gain": 0.9}}
        )
        g, _ = spanning_target(h, node_of)
        nodes = sorted(set(node_of.values()))
        free_one_on(h, node_of, nodes[1])
        free_one_on(h, node_of, nodes[1])
        h.settle()
        stats = h.defrag_sweep()
        assert stats["admitted"] == 0
        assert stats["rejected"] == {"rejected-gain": 1}
        ex = h.cluster.decisions.explain("default", g.metadata.name)
        rec = next(
            r for r in reversed(ex["records"])
            if r["outcome"] == "migration"
        )
        assert rec["detail"]["verdict"] == "rejected-gain"
        # nothing was disturbed
        g2, _ = gang_nodes(h, "target")
        assert g2.status.placement_score == 0.25

    def test_eviction_rate_bound(self):
        h, node_of = frag_harness(
            config={"defrag": {**DEFRAG, "max_evictions_per_hour": 1}}
        )
        g, _ = spanning_target(h, node_of)
        nodes = sorted(set(node_of.values()))
        free_one_on(h, node_of, nodes[1])
        free_one_on(h, node_of, nodes[1])
        h.settle()
        assert h.defrag_sweep()["admitted"] == 1
        h.settle()
        # fragment a second gang the same way on the other block
        free_one_on(h, node_of, nodes[2])
        free_one_on(h, node_of, nodes[6])
        h.settle()
        h.apply(pcs("target2", 2, 1.0))
        h.settle()
        free_one_on(h, node_of, nodes[3])
        free_one_on(h, node_of, nodes[3])
        h.settle()
        stats = h.defrag_sweep()
        assert stats["admitted"] == 0
        assert stats["rejected"].get("rejected-rate", 0) >= 1
        # the rolling hour window releases the bound
        h.advance(3601.0)
        stats = h.defrag_sweep()
        assert stats["admitted"] == 1

    def test_disabled_by_default_and_cadence(self):
        h = Harness(nodes=make_nodes(4))
        assert h.maybe_defrag() is False
        assert h.defrag_sweep() is None
        h2, _ = frag_harness()
        assert h2.maybe_defrag() is True   # first opportunity sweeps
        assert h2.maybe_defrag() is False  # within the interval
        h2.advance(61.0)
        assert h2.maybe_defrag() is True


# -- budget sharing with preemption -------------------------------------------

class TestBudgetSharing:
    def tenant_harness(self, budget):
        return frag_harness(tenants=[
            {"name": "default", "disruption_budget": budget},
        ])

    def test_defrag_rejects_when_preemption_spent_the_budget(self):
        h, node_of = self.tenant_harness(budget=1)
        g, _ = spanning_target(h, node_of)
        nodes = sorted(set(node_of.values()))
        free_one_on(h, node_of, nodes[1])
        free_one_on(h, node_of, nodes[1])
        h.settle()
        # preemption spent the tenant's budget within the window
        h.cluster.tenancy.ledger.charge(
            "default", "preemption", h.clock.now()
        )
        stats = h.defrag_sweep()
        assert stats["admitted"] == 0
        assert stats["rejected"] == {"rejected-budget": 1}
        ex = h.cluster.decisions.explain("default", g.metadata.name)
        rec = next(
            r for r in reversed(ex["records"])
            if r["outcome"] == "migration"
        )
        d = rec["detail"]
        assert d["verdict"] == "rejected-budget"
        # the audit names which consumer spent what (satellite)
        assert d["budget"] == {
            "limit": 1, "spent_by": {"preemption": 1},
        }
        # outside the window the budget frees up again
        h.advance(61.0)
        assert h.defrag_sweep()["admitted"] == 1

    def test_defrag_spend_blocks_preemption_in_the_window(self):
        """The reverse direction, through the scheduler's own budget
        check: a defrag charge in the window leaves no budget for a
        preemption round — one window can never double-spend."""
        h, node_of = self.tenant_harness(budget=1)
        now = h.clock.now()
        led = h.cluster.tenancy.ledger
        led.charge("default", "defrag", now)
        budget = h.cluster.tenancy.disruption_budget("default")
        assert led.spent("default", now) >= budget
        assert led.breakdown("default", now) == {"defrag": 1}

    def test_armed_audit_raises_on_overspend(self):
        h, node_of = self.tenant_harness(budget=1)
        h.defrag.audit = True
        h.cluster.tenancy.ledger.charge(
            "default", "defrag", h.clock.now(), n=2
        )
        with pytest.raises(RuntimeError) as err:
            h.defrag.sweep()
        assert "disruption-budget audit" in str(err.value)
        assert "defrag" in str(err.value)

    def test_admitted_move_charges_the_shared_ledger(self):
        h, node_of = self.tenant_harness(budget=3)
        g, _ = spanning_target(h, node_of)
        nodes = sorted(set(node_of.values()))
        free_one_on(h, node_of, nodes[1])
        free_one_on(h, node_of, nodes[1])
        h.settle()
        assert h.defrag_sweep()["admitted"] == 1
        assert h.cluster.tenancy.ledger.breakdown(
            "default", h.clock.now()
        ) == {"defrag": 1}


# -- chaos --------------------------------------------------------------------

CHAOS_DEFRAG = {"defrag": {**DEFRAG, "sync_interval_seconds": 20.0,
                           "max_moves_per_sweep": 2}}


def chaos_workload():
    from test_e2e_basic import simple_pcs

    return simple_pcs(name="chaos", replicas=2)


class TestDefragChaos:
    def baseline(self):
        from grove_tpu.chaos import settled_fingerprint

        h = Harness(nodes=make_nodes(16), config=CHAOS_DEFRAG)
        h.apply(chaos_workload())
        h.settle()
        return settled_fingerprint(h.store)

    @pytest.mark.parametrize("seed", [2, 7])
    def test_migration_storm_seeds_converge(self, seed):
        from grove_tpu.chaos import (
            ChaosHarness,
            FaultPlan,
            check_invariants,
            settled_fingerprint,
        )

        plan = FaultPlan.from_seed(
            seed, migration_storm_rate=0.5, migration_crash_rate=0.3,
            migration_node_fault_rate=0.4,
        )
        ch = ChaosHarness(
            plan, nodes=make_nodes(16), config=CHAOS_DEFRAG
        )
        assert ch.harness.defrag.audit is True  # armed by construction
        ch.apply(chaos_workload())
        ch.settle()
        ch.run_chaos()
        assert plan.counts.get("migration_storm", 0) > 0
        assert settled_fingerprint(ch.raw_store) == self.baseline()
        assert check_invariants(ch.raw_store) == []

    def test_rate_zero_plans_never_draw_defrag_faults(self):
        from grove_tpu.chaos import ChaosHarness, FaultPlan

        plan = FaultPlan.from_seed(3)
        ch = ChaosHarness(
            plan, nodes=make_nodes(16), config=CHAOS_DEFRAG
        )
        ch.apply(chaos_workload())
        ch.run_chaos()
        assert "migration_storm" not in plan.counts
        assert "migration_crash" not in plan.counts
        assert "migration_node_fault" not in plan.counts
