"""Operator configuration API tests (api/config/v1alpha1 parity).

The reference drives the operator from a validated OperatorConfiguration
YAML (types.go:57-202, validation.go); here configs decode from dicts with
strict unknown-field rejection, aggregate validation errors, and every
formerly-hard-coded knob observably changes behavior through the Harness.
"""

import pytest

from grove_tpu.api import ValidationError
from grove_tpu.api.config import (
    load_operator_config,
    validate_operator_config,
)
from grove_tpu.api.types import Pod, PodCliqueSet
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness

from test_e2e_basic import clique, simple_pcs


class TestConfigDecode:
    def test_empty_dict_yields_defaults(self):
        cfg = load_operator_config({})
        assert cfg.workload_defaults.termination_delay_seconds == 4 * 3600
        assert cfg.solver.top_k == 8
        assert cfg.controllers.sync_retry_interval_seconds == 5.0
        assert cfg.autoscaler.tolerance == 0.1
        assert not cfg.authorization.enabled

    def test_nested_overrides(self):
        cfg = load_operator_config(
            {
                "workload_defaults": {"termination_delay_seconds": 60.0},
                "solver": {"top_k": 4, "native_repair": False},
                "log": {"level": "debug", "format": "json"},
            }
        )
        assert cfg.workload_defaults.termination_delay_seconds == 60.0
        assert cfg.solver.top_k == 4
        assert not cfg.solver.native_repair
        assert cfg.solver.commit_chunk == 32  # untouched default
        assert cfg.log.level == "debug"

    def test_unknown_field_rejected(self):
        with pytest.raises(ValidationError, match="unknown field"):
            load_operator_config({"solver": {"topk": 4}})

    def test_errors_aggregate(self):
        with pytest.raises(ValidationError) as e:
            load_operator_config(
                {
                    "solver": {"top_k": 0, "gang_bucket_minimum": 6},
                    "autoscaler": {"tolerance": 2.0},
                    "log": {"level": "verbose"},
                }
            )
        msgs = e.value.errors
        assert len(msgs) == 4, msgs
        assert any("top_k" in m for m in msgs)
        assert any("power of two" in m for m in msgs)
        assert any("tolerance" in m for m in msgs)
        assert any("log.level" in m for m in msgs)

    def test_authorization_validation(self):
        errs = validate_operator_config(
            load_operator_config({"authorization": {"enabled": True}})
        )
        assert errs == []  # default identity satisfies the requirement
        with pytest.raises(ValidationError, match="operator_identity"):
            load_operator_config(
                {"authorization": {"enabled": True, "operator_identity": ""}}
            )

    def test_device_state_verify_requires_cache(self):
        errs = validate_operator_config(
            load_operator_config(
                {"solver": {"device_state_cache": False}}
            )
        )
        assert errs == []  # cache off alone is a valid A/B regime
        with pytest.raises(ValidationError, match="device_state_verify"):
            load_operator_config(
                {"solver": {"device_state_cache": False,
                            "device_state_verify": True}}
            )

    def test_backoff_fields_decode_and_defaults(self):
        cfg = load_operator_config({})
        assert cfg.controllers.error_backoff_base_seconds == 1.0
        assert cfg.controllers.error_backoff_max_seconds == 60.0
        assert cfg.controllers.error_retry_budget == 8
        cfg = load_operator_config(
            {"controllers": {"error_backoff_base_seconds": 0.5,
                             "error_backoff_max_seconds": 30.0,
                             "error_retry_budget": 3}}
        )
        assert cfg.controllers.error_backoff_base_seconds == 0.5
        assert cfg.controllers.error_backoff_max_seconds == 30.0
        assert cfg.controllers.error_retry_budget == 3

    def test_backoff_validation(self):
        with pytest.raises(ValidationError, match="error_backoff_base_seconds"):
            load_operator_config(
                {"controllers": {"error_backoff_base_seconds": 0}}
            )
        with pytest.raises(ValidationError, match="error_backoff_max_seconds"):
            load_operator_config(
                {"controllers": {"error_backoff_base_seconds": 10.0,
                                 "error_backoff_max_seconds": 5.0}}
            )
        with pytest.raises(ValidationError, match="error_retry_budget"):
            load_operator_config(
                {"controllers": {"error_retry_budget": 0}}
            )
        with pytest.raises(ValidationError, match="error_retry_budget"):
            load_operator_config(
                {"controllers": {"error_retry_budget": 2.5}}
            )
        # aggregated, decode-style: all three problems in one raise
        with pytest.raises(ValidationError) as e:
            load_operator_config(
                {"controllers": {"error_backoff_base_seconds": -1,
                                 "error_backoff_max_seconds": "x",
                                 "error_retry_budget": True}}
            )
        assert sum(
            "error_" in m for m in e.value.errors
        ) == 3, e.value.errors

    def test_backoff_knobs_reach_manager(self):
        h = Harness(
            nodes=make_nodes(2),
            config={"controllers": {"error_backoff_base_seconds": 2.0,
                                    "error_backoff_max_seconds": 40.0,
                                    "error_retry_budget": 4}},
        )
        assert h.manager.error_backoff_base_seconds == 2.0
        assert h.manager.error_backoff_max_seconds == 40.0
        assert h.manager.error_retry_budget == 4

    def test_durability_defaults_and_decode(self):
        cfg = load_operator_config({})
        assert cfg.durability.wal_dir is None  # off by default
        assert cfg.durability.fsync == "commit"
        assert cfg.durability.keep_snapshots == 2
        cfg = load_operator_config({"durability": {
            "wal_dir": "/tmp/grove-wal",
            "fsync": "snapshot",
            "snapshot_interval_seconds": 60.0,
            "wal_max_bytes": 1 << 20,
            "keep_snapshots": 3,
        }})
        assert cfg.durability.wal_dir == "/tmp/grove-wal"
        assert cfg.durability.fsync == "snapshot"
        assert cfg.durability.snapshot_interval_seconds == 60.0

    def test_durability_rejected_combinations(self):
        # disabling is wal_dir: null, never the empty string
        with pytest.raises(ValidationError, match="wal_dir"):
            load_operator_config({"durability": {"wal_dir": ""}})
        with pytest.raises(ValidationError, match="fsync"):
            load_operator_config(
                {"durability": {"fsync": "always"}}  # not a policy
            )
        with pytest.raises(ValidationError,
                           match="snapshot_interval_seconds"):
            load_operator_config(
                {"durability": {"snapshot_interval_seconds": 0}}
            )
        # a segment bound below one record forces a snapshot per write
        with pytest.raises(ValidationError, match="wal_max_bytes"):
            load_operator_config({"durability": {"wal_max_bytes": 512}})
        # < 2 retained generations breaks corrupted-snapshot fallback
        with pytest.raises(ValidationError, match="keep_snapshots"):
            load_operator_config({"durability": {"keep_snapshots": 1}})
        # aggregated like every other block
        with pytest.raises(ValidationError) as e:
            load_operator_config({"durability": {
                "fsync": "maybe",
                "wal_max_bytes": -1,
                "keep_snapshots": 0,
            }})
        assert sum("durability" in m for m in e.value.errors) == 3

    def test_durability_knobs_reach_the_store(self, tmp_path):
        h = Harness(
            nodes=make_nodes(2),
            config={"durability": {"wal_dir": str(tmp_path / "wal"),
                                   "fsync": "never"}},
        )
        assert h.cluster.durability is not None
        assert h.store.durability is h.cluster.durability
        assert h.cluster.durability.config.fsync == "never"
        # and off-by-default leaves the store WAL-less
        assert Harness(nodes=make_nodes(2)).cluster.durability is None

    def test_topology_levels_validation(self):
        with pytest.raises(ValidationError, match="duplicate domain"):
            load_operator_config(
                {
                    "topology_aware_scheduling": {
                        "levels": [
                            {"domain": "rack", "key": "a"},
                            {"domain": "rack", "key": "b"},
                        ]
                    }
                }
            )


class TestConfigChangesBehavior:
    def test_workload_defaults_flow_into_admission(self):
        h = Harness(
            nodes=make_nodes(4),
            config={
                "workload_defaults": {
                    "termination_delay_seconds": 123.0,
                    "replicas": 2,
                }
            },
        )
        pcs = simple_pcs(cliques=[clique("w", replicas=1)])
        pcs.spec.replicas = None  # let defaulting fill it
        h.apply(pcs)
        h.settle()
        live = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert live.spec.template.termination_delay == 123.0
        assert live.spec.replicas == 2
        assert len(h.store.list(Pod.KIND)) == 2  # one pod per PCS replica

    def test_scheduler_retry_interval_from_config(self):
        h = Harness(
            nodes=make_nodes(1, allocatable={"cpu": 1.0, "memory": 1.0,
                                             "tpu": 0.0}),
            config={"controllers": {"sync_retry_interval_seconds": 60.0}},
        )
        h.apply(simple_pcs(cliques=[clique("w", replicas=2, cpu=2.0)]))
        h.settle()
        assert all(not p.node_name for p in h.store.list(Pod.KIND))
        # the unschedulable gang's retry is paced by the configured 60s,
        # not the built-in 5s default
        next_retry = h.manager.next_requeue_at()
        assert next_retry is not None
        assert next_retry == pytest.approx(h.clock.now() + 60.0, abs=1e-6)

    def test_solver_knobs_reach_engine(self):
        captured = {}

        class Probe:
            def __init__(self, snapshot, **kwargs):
                captured.update(kwargs)
                from grove_tpu.solver import PlacementEngine

                self._e = PlacementEngine(snapshot, **kwargs)

            def solve(self, gangs, free=None):
                return self._e.solve(gangs, free=free)

        h = Harness(
            nodes=make_nodes(2),
            engine_cls=Probe,
            config={
                "solver": {
                    "top_k": 3,
                    "commit_chunk": 16,
                    "gang_bucket_minimum": 4,
                    "native_repair": False,
                    "device_state_cache": True,
                    "device_state_verify": True,
                }
            },
        )
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        assert captured.pop("metrics") is h.cluster.metrics
        # the scheduler injects the CLUSTER-owned decision ring so
        # explanations survive engine rebuilds (observability/explain.py)
        assert captured.pop("decision_log") is h.cluster.decisions
        assert captured == {
            "top_k": 3,
            "commit_chunk": 16,
            "bucket_min": 4,
            "native_repair": False,
            "state_cache": True,
            "state_verify": True,
            "fused": True,
            "incremental": True,
            "hierarchical": True,
            "hier_prune_level": None,
            "hier_min_nodes": 4096,
            "hier_parallel_workers": None,
            "pallas_core": None,
            "device_commit": None,
            "pallas_precision": "fp32",
        }
        assert all(p.node_name for p in h.store.list(Pod.KIND))

    def test_topology_levels_seed_bootstrap(self):
        nodes = make_nodes(4, racks_per_block=2, hosts_per_rack=2)
        for i, n in enumerate(nodes):
            n.metadata.labels["t/zone"] = f"z{i % 2}"
        h = Harness(
            nodes=nodes,
            config={
                "topology_aware_scheduling": {
                    "levels": [{"domain": "zone", "key": "t/zone"}]
                }
            },
        )
        snap = h.cluster.topology_snapshot()
        assert "t/zone" in snap.level_keys

    def test_topology_disabled_ignores_constraints(self):
        from grove_tpu.api.types import (
            TopologyConstraintSpec,
            TopologyPackConstraintSpec,
        )

        # with TAS disabled a zone-required workload schedules UNCONSTRAINED
        # (reference: no KAI Topology CR, no constraint translation) —
        # distinct from enabled-but-missing-level, which HOLDS the gang
        h = Harness(
            nodes=make_nodes(4),
            config={"topology_aware_scheduling": {"enabled": False}},
        )
        pcs = simple_pcs(cliques=[clique("w", replicas=2, cpu=1.0)])
        pcs.spec.template.topology_constraint = TopologyConstraintSpec(
            pack_constraint=TopologyPackConstraintSpec(required="zone")
        )
        h.apply(pcs)
        h.settle()
        assert all(p.node_name for p in h.store.list(Pod.KIND))


class TestAuthorization:
    """Managed-resource protection (authorization webhook analog):
    non-operator actors cannot mutate operator-created children."""

    def harness(self, **az):
        return Harness(
            nodes=make_nodes(4),
            config={"authorization": {"enabled": True, **az}},
        )

    def test_user_cannot_mutate_managed_resources(self):
        from grove_tpu.api.types import PodClique
        from grove_tpu.cluster.store import Forbidden

        h = self.harness()
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        assert pclq is not None
        # direct store calls run as the unprivileged "user" actor
        pclq.spec.replicas = 99
        with pytest.raises(Forbidden, match="may not update"):
            h.store.update(pclq)
        with pytest.raises(Forbidden, match="may not delete"):
            h.store.delete(PodClique.KIND, "default", "simple1-0-w")
        with pytest.raises(Forbidden, match="may not update"):
            h.store.remove_finalizer(
                PodClique.KIND, "default", "simple1-0-w",
                pclq.metadata.finalizers[0],
            )

    def test_user_still_owns_their_podcliqueset(self):
        from grove_tpu.api.types import PodCliqueSet

        h = self.harness()
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        pcs.spec.replicas = 2  # user-applied object: freely mutable
        h.store.update(pcs)
        h.settle()
        assert len(h.store.list(Pod.KIND)) == 4

    def test_controllers_and_lifecycle_unaffected(self):
        # the full reconcile lifecycle (create children, bind, gang
        # terminate, cascade delete) runs as the operator identity
        from grove_tpu.api.types import PodCliqueSet

        h = self.harness()
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        assert all(p.node_name for p in h.store.list(Pod.KIND))
        h.store.delete(PodCliqueSet.KIND, "default", "simple1")
        h.settle()
        assert h.store.list(Pod.KIND) == []

    def test_exempt_actor_allowed(self):
        from grove_tpu.api.types import PodClique

        h = self.harness(exempt_actors=["admin@corp"])
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        pclq.spec.replicas = 3
        with h.store.impersonate("admin@corp"):
            h.store.update(pclq)
        h.settle()
        assert h.store.get(
            PodClique.KIND, "default", "simple1-0-w"
        ).spec.replicas == 3

    def test_disable_protection_annotation(self):
        from grove_tpu.api import constants
        from grove_tpu.api.types import PodClique

        h = self.harness()
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        pclq.metadata.annotations[
            constants.ANNOTATION_DISABLE_MANAGED_RESOURCE_PROTECTION
        ] = "true"
        with h.store.impersonate(h.config.authorization.operator_identity):
            h.store.update(pclq)
        # now the user may touch it
        fresh = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        fresh.spec.replicas = 5
        h.store.update(fresh)

    def test_pod_delete_always_permitted(self):
        """handler.go:121-135: Pod DELETE is exempt for any actor (drain/
        eviction agents must not be blocked); Pod UPDATE stays protected."""
        from grove_tpu.api.types import Pod
        from grove_tpu.cluster.store import Forbidden

        h = self.harness()
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        pod = h.store.get(Pod.KIND, "default", "simple1-0-w-0")
        pod.spec.priority_class_name = "tampered"
        with pytest.raises(Forbidden, match="may not update"):
            h.store.update(pod)
        h.store.delete(Pod.KIND, "default", "simple1-0-w-0")  # allowed
        h.settle()  # reconciler replaces the pod
        assert h.store.get(Pod.KIND, "default", "simple1-0-w-0") is not None

    def test_disable_protection_via_owning_pcs(self):
        """Annotating the parent PodCliqueSet opts out the whole tree
        (reference resolves the annotation from the owning PCS)."""
        from grove_tpu.api import constants
        from grove_tpu.api.types import PodClique, PodCliqueSet

        h = self.harness()
        pcs = simple_pcs(cliques=[clique("w", replicas=2)])
        pcs.metadata.annotations[
            constants.ANNOTATION_DISABLE_MANAGED_RESOURCE_PROTECTION
        ] = "true"
        h.apply(pcs)
        h.settle()
        # child carries no annotation of its own, yet the user may touch it
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        assert constants.ANNOTATION_DISABLE_MANAGED_RESOURCE_PROTECTION \
            not in pclq.metadata.annotations
        pclq.spec.replicas = 5
        h.store.update(pclq)
