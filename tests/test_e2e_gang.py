"""Gang-scheduling + failure-path E2E suites.

Named GS*/FT* after the reference's E2E scenario naming
(operator/e2e/tests/gang_scheduling_test.go GS1-GS12): all-or-nothing under
insufficient capacity, scale-out gangs, minAvailable semantics, breach ->
TerminationDelay -> gang termination -> recovery.
"""

from grove_tpu.api import constants
from grove_tpu.api.meta import get_condition
from grove_tpu.api.podgang import PodGang, PodGangConditionType
from grove_tpu.api.types import Pod, PodClique, PodCliqueSet
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness

from test_e2e_basic import clique, simple_pcs


def cond(obj, ctype):
    return get_condition(obj.status.conditions, ctype)


class TestGS_AllOrNothing:
    def test_gs1_insufficient_capacity_nothing_binds(self):
        # 2 nodes x 4 cpu; gang needs 3 pods x 3 cpu in ONE... total 9 > 8
        h = Harness(nodes=make_nodes(2, allocatable={"cpu": 4.0, "memory": 8.0,
                                                     "tpu": 0.0}))
        h.apply(simple_pcs(cliques=[clique("w", replicas=3, cpu=3.0)]))
        h.settle()
        pods = h.store.list(Pod.KIND)
        assert len(pods) == 3
        assert all(not p.node_name for p in pods), "all-or-nothing: none bind"
        gang = h.store.get(PodGang.KIND, "default", "simple1-0")
        sched = cond(gang, PodGangConditionType.SCHEDULED.value)
        assert sched is not None and sched.status == "False"
        # structured reason code (explain.py): 9 cpu demanded, 8 free —
        # a capacity verdict, with the binding resource in the message
        assert sched.reason == "InsufficientCapacity"
        assert "cpu" in sched.message
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.available_replicas == 0  # never-scheduled != available

    def test_gs2_capacity_freed_then_gang_binds(self):
        h = Harness(nodes=make_nodes(2, allocatable={"cpu": 4.0, "memory": 8.0,
                                                     "tpu": 0.0}))
        h.apply(simple_pcs(cliques=[clique("w", replicas=3, cpu=3.0)]))
        h.settle()
        # add capacity -> retry timer fires -> gang binds
        for node in make_nodes(2, name_prefix="extra",
                               allocatable={"cpu": 4.0, "memory": 8.0, "tpu": 0.0}):
            h.store.create(node)
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        pods = h.store.list(Pod.KIND)
        assert all(p.node_name for p in pods)

    def test_gs3_min_available_partial_gang(self):
        # clique replicas=4, minAvailable=2: gang is 2 pods; the other 2
        # bind best-effort
        h = Harness(nodes=make_nodes(4, allocatable={"cpu": 2.0, "memory": 8.0,
                                                     "tpu": 0.0}))
        h.apply(simple_pcs(cliques=[clique("w", replicas=4, min_available=2,
                                           cpu=1.5)]))
        h.settle()
        gang = h.store.get(PodGang.KIND, "default", "simple1-0")
        assert gang.spec.pod_groups[0].min_replicas == 2
        bound = [p for p in h.store.list(Pod.KIND) if p.node_name]
        # 4 nodes x 2cpu, 1.5cpu pods -> one per node: all 4 fit
        assert len(bound) == 4

    def test_gs4_two_pcs_contend_no_partial_binding(self):
        # capacity for exactly one gang; the other must stay fully pending
        h = Harness(nodes=make_nodes(2, allocatable={"cpu": 3.0, "memory": 8.0,
                                                     "tpu": 0.0}))
        h.apply(simple_pcs(name="a", cliques=[clique("w", replicas=2, cpu=2.5)]))
        h.apply(simple_pcs(name="b", cliques=[clique("w", replicas=2, cpu=2.5)]))
        h.settle()
        bound_by_pcs = {"a": 0, "b": 0}
        for p in h.store.list(Pod.KIND):
            if p.node_name:
                bound_by_pcs[p.metadata.labels[constants.LABEL_PART_OF]] += 1
        assert sorted(bound_by_pcs.values()) == [0, 2], bound_by_pcs


class TestFT_FailureAndTermination:
    def two_replica_pcs(self):
        return simple_pcs(cliques=[clique("w", replicas=2, cpu=1.0)])

    def test_ft1_crash_sets_breach_and_phase(self):
        h = Harness(nodes=make_nodes(4))
        h.apply(self.two_replica_pcs())
        h.settle()
        h.kubelet.crash_pod("default", "simple1-0-w-0")
        h.settle()
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        breach = cond(pclq, constants.CONDITION_MIN_AVAILABLE_BREACHED)
        assert breach.status == "True"
        gang = h.store.get(PodGang.KIND, "default", "simple1-0")
        unhealthy = cond(gang, PodGangConditionType.UNHEALTHY.value)
        assert unhealthy.status == "True"
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.available_replicas == 0

    def test_ft2_recovery_clears_breach(self):
        h = Harness(nodes=make_nodes(4))
        h.apply(self.two_replica_pcs())
        h.settle()
        h.kubelet.crash_pod("default", "simple1-0-w-0")
        h.settle()
        h.kubelet.recover_pod("default", "simple1-0-w-0")
        h.settle()
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        assert cond(pclq, constants.CONDITION_MIN_AVAILABLE_BREACHED).status == "False"

    def test_ft3_gang_termination_after_delay(self):
        h = Harness(nodes=make_nodes(4))
        pcs = self.two_replica_pcs()
        pcs.spec.template.termination_delay = 60.0
        h.apply(pcs)
        h.settle()
        old_pod_uid = h.store.get(Pod.KIND, "default", "simple1-0-w-0").metadata.uid
        h.kubelet.crash_pod("default", "simple1-0-w-0")
        h.settle()
        # before the delay expires nothing is terminated
        h.advance(30.0)
        assert h.store.get(PodClique.KIND, "default", "simple1-0-w") is not None
        assert (h.store.get(Pod.KIND, "default", "simple1-0-w-0").metadata.uid
                == old_pod_uid)
        # crashed pod stays crashed; after the delay the whole replica is
        # rebuilt (gang restart) with fresh pods that start CLEAN even when
        # hole-filling reuses the crashed pod's name
        h.advance(31.0)
        h.settle()
        new_pod = h.store.get(Pod.KIND, "default", "simple1-0-w-0")
        assert new_pod is not None and new_pod.metadata.uid != old_pod_uid
        assert all(p.status.ready for p in h.store.list(Pod.KIND))

    def test_ft4_evicted_pod_replaced_and_rebound(self):
        h = Harness(nodes=make_nodes(4))
        h.apply(self.two_replica_pcs())
        h.settle()
        h.kubelet.evict_pod("default", "simple1-0-w-1")
        h.settle()
        pod = h.store.get(Pod.KIND, "default", "simple1-0-w-1")
        assert pod is not None and pod.node_name and pod.status.ready

    def test_ft5_unschedulable_gang_never_ticks_termination(self):
        h = Harness(nodes=make_nodes(1, allocatable={"cpu": 1.0, "memory": 1.0,
                                                     "tpu": 0.0}))
        pcs = self.two_replica_pcs()  # needs 2 cpu total, only 1 available
        pcs.spec.template.termination_delay = 60.0
        h.apply(pcs)
        h.settle()
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        assert cond(pclq, constants.CONDITION_MIN_AVAILABLE_BREACHED).status == "False"
        h.advance(3600.0)
        # cliques still exist; no termination churn for a never-scheduled gang
        assert h.store.get(PodClique.KIND, "default", "simple1-0-w") is not None


class TestGS_TopologyGating:
    """A PCS demanding a pack level the cluster topology doesn't carry must
    be HELD (Unschedulable with reason + TopologyLevelsUnavailable), not
    scheduled unconstrained; adding the level to the stored ClusterTopology
    unblocks it live (no restart)."""

    def test_unknown_required_domain_holds_gang_then_recovers(self):
        from grove_tpu.api.types import (
            ClusterTopology,
            TopologyConstraintSpec,
            TopologyLevel,
            TopologyPackConstraintSpec,
            sort_topology_levels,
        )

        nodes = make_nodes(4, racks_per_block=2, hosts_per_rack=2)
        for n in nodes:
            n.metadata.labels["t/zone"] = "z0"
        h = Harness(nodes=nodes)
        pcs = simple_pcs(cliques=[clique("w", replicas=2, cpu=1.0)])
        pcs.spec.template.topology_constraint = TopologyConstraintSpec(
            pack_constraint=TopologyPackConstraintSpec(required="zone")
        )
        h.apply(pcs)
        h.settle()
        pods = h.store.list(Pod.KIND)
        assert len(pods) == 2
        assert all(not p.node_name for p in pods), (
            "hard constraint must hold the gang, not weaken to unconstrained"
        )
        gang = h.store.get(PodGang.KIND, "default", "simple1-0")
        sched = cond(gang, PodGangConditionType.SCHEDULED.value)
        assert sched is not None and sched.status == "False"
        assert "unavailable" in sched.message
        pcs_live = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        topo = cond(pcs_live, constants.CONDITION_TOPOLOGY_LEVELS_UNAVAILABLE)
        assert topo is not None and topo.status == "True"
        assert "zone" in topo.message

        # live topology update: add the zone level -> gang schedules
        ct = h.store.get(
            ClusterTopology.KIND,
            h.cluster.topology.metadata.namespace,
            h.cluster.topology.metadata.name,
        )
        ct.spec.levels = sort_topology_levels(
            ct.spec.levels + [TopologyLevel(domain="zone", key="t/zone")]
        )
        h.store.update(ct)
        h.settle()
        pods = h.store.list(Pod.KIND)
        assert all(p.node_name for p in pods)
        pcs_live = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        topo = cond(pcs_live, constants.CONDITION_TOPOLOGY_LEVELS_UNAVAILABLE)
        assert topo.status == "False"


class TestFT_DisruptionTarget:
    def test_ft6_gang_termination_marks_disruption_target(self):
        h = Harness(nodes=make_nodes(4))
        pcs = simple_pcs(cliques=[clique("w", replicas=2, cpu=1.0)])
        pcs.spec.template.termination_delay = 60.0
        h.apply(pcs)
        h.settle()
        h.kubelet.crash_pod("default", "simple1-0-w-0")
        h.settle()
        seq = h.store.last_seq
        h.advance(61.0)
        h.settle()
        # the victim gang was marked DisruptionTarget BEFORE deletion
        # (podgang.go:156-169) — visible in the watch event stream
        events = [
            e for e in h.store.events_since(seq)
            if e.kind == PodGang.KIND and e.name == "simple1-0"
        ]
        marked = [
            e for e in events
            if e.type == "Modified"
            and (c := cond(e.obj, PodGangConditionType.DISRUPTION_TARGET.value))
            is not None
            and c.status == "True"
            and c.reason == "GangTerminationDelayExpired"
        ]
        deleted = [e for e in events if e.type == "Deleted"]
        assert marked and deleted
        assert marked[0].seq < deleted[0].seq


class TestGS_PriorityClass:
    def test_priorityclass_object_orders_contention(self):
        from grove_tpu.api.auxiliary import PriorityClass
        from grove_tpu.api.meta import ObjectMeta

        # capacity for exactly one gang; priority decides which. Nodes start
        # cordoned so BOTH gangs are pending in the same backlog when
        # capacity appears (otherwise whichever reconciles first binds).
        h = Harness(nodes=make_nodes(2, allocatable={"cpu": 3.0, "memory": 8.0,
                                                     "tpu": 0.0}))
        h.cluster.cordon("node-0")
        h.cluster.cordon("node-1")
        h.store.create(
            PriorityClass(metadata=ObjectMeta(name="gold", namespace=""),
                          value=500.0)
        )
        a = simple_pcs(name="a", cliques=[clique("w", replicas=2, cpu=2.5)])
        b = simple_pcs(name="b", cliques=[clique("w", replicas=2, cpu=2.5)])
        b.spec.template.priority_class_name = "gold"
        h.apply(a)
        h.apply(b)
        h.settle()
        assert all(not p.node_name for p in h.store.list(Pod.KIND))
        h.cluster.uncordon("node-0")
        h.cluster.uncordon("node-1")
        h.settle()
        bound_by_pcs = {"a": 0, "b": 0}
        for p in h.store.list(Pod.KIND):
            if p.node_name:
                bound_by_pcs[p.metadata.labels[constants.LABEL_PART_OF]] += 1
        # without the PriorityClass object "a" would win on name order
        assert bound_by_pcs == {"a": 0, "b": 2}

    def test_priority_resolution_semantics(self):
        from grove_tpu.api.auxiliary import PriorityClass
        from grove_tpu.api.meta import ObjectMeta
        from grove_tpu.api.podgang import PodGang, PodGangSpec

        h = Harness(nodes=make_nodes(1))

        def gang_with(pc_name):
            g = PodGang(metadata=ObjectMeta(name="g"))
            g.spec = PodGangSpec(priority_class_name=pc_name)
            return g

        prio = h.scheduler._priority_of
        # seeded system classes are real objects, not name heuristics
        assert prio(gang_with("system-node-critical")) == 2_000_001_000.0
        assert prio(gang_with("system-cluster-critical")) == 2_000_000_000.0
        assert prio(gang_with("unknown-high")) == 0.0  # no suffix heuristics
        assert prio(gang_with(None)) == 0.0
        h.store.create(
            PriorityClass(metadata=ObjectMeta(name="dft", namespace=""),
                          value=7.0, global_default=True)
        )
        assert prio(gang_with(None)) == 7.0


class TestPP_PriorityPreemption:
    """Priority preemption (exceeds the reference, which outsources
    reclaim to KAI): capacity-starved higher-priority gangs evict
    lower-priority SCALED gangs — never base gangs."""

    def full_cluster(self):
        from grove_tpu.api.types import PodCliqueScalingGroupConfig

        # 4 one-cpu nodes, fully packed by a low-priority PCS:
        # base gang (grp-0: 2 pods) + scaled gang (grp-1: 2 pods)
        h = Harness(nodes=make_nodes(
            4, racks_per_block=2, hosts_per_rack=2,
            allocatable={"cpu": 1.0, "memory": 8.0, "tpu": 0.0}))
        low = simple_pcs(
            name="low",
            cliques=[clique("w", replicas=2, cpu=1.0)],
            sgs=[PodCliqueScalingGroupConfig(
                name="grp", clique_names=["w"], replicas=2, min_available=1)],
        )
        h.apply(low)
        h.settle()
        assert all(p.node_name for p in h.store.list(Pod.KIND))
        return h

    def high_pcs(self, pods=2):
        hi = simple_pcs(name="hi", cliques=[clique("w", replicas=pods,
                                                   cpu=1.0)])
        hi.spec.template.priority_class_name = "gold"
        return hi

    def seed_gold(self, h):
        from grove_tpu.api.auxiliary import PriorityClass
        from grove_tpu.api.meta import ObjectMeta

        h.store.create(PriorityClass(
            metadata=ObjectMeta(name="gold", namespace=""), value=1000.0))

    def test_pp1_high_priority_evicts_scaled_gang_never_base(self):
        h = self.full_cluster()
        self.seed_gold(h)
        h.apply(self.high_pcs(pods=2))
        h.settle()
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        # the high-priority gang is placed...
        hi_pods = h.store.list(Pod.KIND, labels={constants.LABEL_PART_OF: "hi"})
        assert len(hi_pods) == 2 and all(p.node_name for p in hi_pods)
        hi_gang = h.store.get(PodGang.KIND, "default", "hi-0")
        assert cond(hi_gang, PodGangConditionType.SCHEDULED.value).status == "True"
        # ...the low-priority BASE gang is untouched...
        base = h.store.get(PodGang.KIND, "default", "low-0")
        assert cond(base, PodGangConditionType.SCHEDULED.value).status == "True"
        base_pods = [
            p for p in h.store.list(Pod.KIND,
                                    labels={constants.LABEL_PART_OF: "low"})
            if p.metadata.labels.get(constants.LABEL_PODGANG) == "low-0"
        ]
        assert base_pods and all(p.node_name for p in base_pods)
        # ...and the SCALED gang was the victim: DisruptionTarget marked,
        # unscheduled, waiting for capacity
        scaled = h.store.get(PodGang.KIND, "default", "low-0-grp-0")
        dt = cond(scaled, PodGangConditionType.DISRUPTION_TARGET.value)
        assert dt is not None and dt.status == "True" and dt.reason == "Preempted"
        sched = cond(scaled, PodGangConditionType.SCHEDULED.value)
        assert sched.status == "False"
        assert h.cluster.metrics.counter(
            "grove_scheduler_preemptions_total").total() == 1

    def test_pp2_victim_returns_when_capacity_appears(self):
        h = self.full_cluster()
        self.seed_gold(h)
        h.apply(self.high_pcs(pods=2))
        h.settle()
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        # new capacity arrives -> the evicted scaled gang re-places and its
        # DisruptionTarget clears
        for node in make_nodes(2, name_prefix="extra",
                               allocatable={"cpu": 1.0, "memory": 8.0,
                                            "tpu": 0.0}):
            h.store.create(node)
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        scaled = h.store.get(PodGang.KIND, "default", "low-0-grp-0")
        assert cond(scaled, PodGangConditionType.SCHEDULED.value).status == "True"
        dt = cond(scaled, PodGangConditionType.DISRUPTION_TARGET.value)
        assert dt is not None and dt.status == "False"
        assert all(p.node_name for p in h.store.list(Pod.KIND))

    def test_pp3_no_eviction_when_victims_cannot_free_enough(self):
        h = self.full_cluster()
        self.seed_gold(h)
        # needs 4 cpu; evicting the only scaled gang frees 2 -> pointless
        # disruption must NOT happen
        h.apply(self.high_pcs(pods=4))
        h.settle()
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        scaled = h.store.get(PodGang.KIND, "default", "low-0-grp-0")
        assert cond(scaled, PodGangConditionType.SCHEDULED.value).status == "True"
        dt = cond(scaled, PodGangConditionType.DISRUPTION_TARGET.value)
        assert dt is None or dt.status != "True"
        assert h.cluster.metrics.counter(
            "grove_scheduler_preemptions_total").total() == 0
        hi_gang = h.store.get(PodGang.KIND, "default", "hi-0")
        assert cond(hi_gang, PodGangConditionType.SCHEDULED.value).status == "False"

    def test_pp4_equal_priority_never_preempts(self):
        h = self.full_cluster()
        hi = simple_pcs(name="hi", cliques=[clique("w", replicas=2, cpu=1.0)])
        h.apply(hi)  # same (zero) priority as "low"
        h.settle()
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        assert h.cluster.metrics.counter(
            "grove_scheduler_preemptions_total").total() == 0
        scaled = h.store.get(PodGang.KIND, "default", "low-0-grp-0")
        assert cond(scaled, PodGangConditionType.SCHEDULED.value).status == "True"

    def test_pp5_residual_free_counts_toward_feasibility(self):
        """Freed victim capacity PLUS residual free capacity makes the
        preemptor feasible: 1 free cpu + 1 evicted cpu covers a 2-cpu
        gang (review finding: freed-alone accounting refused this)."""
        from grove_tpu.api.types import PodCliqueScalingGroupConfig

        h = Harness(nodes=make_nodes(
            4, racks_per_block=2, hosts_per_rack=2,
            allocatable={"cpu": 1.0, "memory": 8.0, "tpu": 0.0}))
        low = simple_pcs(
            name="low",
            cliques=[clique("w", replicas=1, cpu=1.0)],
            sgs=[PodCliqueScalingGroupConfig(
                name="grp", clique_names=["w"], replicas=2, min_available=1)],
        )
        h.apply(low)  # base 1 + scaled 1 -> 3 nodes used... (w replicas=1)
        h.settle()
        used = sum(1 for p in h.store.list(Pod.KIND) if p.node_name)
        assert used == 2  # base gang pod + scaled gang pod; 2 cpu free? no: 4-2=2
        # fill one more node with a second scaled replica
        pcsg = h.store.get("PodCliqueScalingGroup", "default", "low-0-grp")
        pcsg.spec.replicas = 3
        h.store.update(pcsg)
        h.settle()
        assert sum(1 for p in h.store.list(Pod.KIND) if p.node_name) == 3
        self.seed_gold(h)
        h.apply(self.high_pcs(pods=2))  # needs 2; 1 free + 1 evictable
        h.settle()
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        hi_gang = h.store.get(PodGang.KIND, "default", "hi-0")
        assert cond(hi_gang, PodGangConditionType.SCHEDULED.value).status == "True"
        # exactly ONE scaled gang evicted (not both)
        assert h.cluster.metrics.counter(
            "grove_scheduler_preemptions_total").total() == 1

    def test_pp6_no_eviction_of_victims_preemptor_cannot_use(self):
        """A selector-pinned preemptor must not destroy scaled gangs whose
        nodes it could never run on (review finding: eligibility-blind
        freed accounting evicted them anyway)."""
        from grove_tpu.api.types import PodCliqueScalingGroupConfig

        nodes = make_nodes(4, racks_per_block=2, hosts_per_rack=2,
                           allocatable={"cpu": 1.0, "memory": 8.0,
                                        "tpu": 0.0})
        for n in nodes[:2]:
            n.metadata.labels["pool"] = "a"
        h = Harness(nodes=nodes)
        # pool a fully used by a base gang (unevictable); pool b holds a
        # low-priority scaled gang
        occupier = simple_pcs(name="occ",
                              cliques=[clique("w", replicas=2, cpu=1.0)])
        occupier.spec.template.cliques[0].spec.pod_spec.node_selector = {
            "pool": "a"}
        h.apply(occupier)
        low = simple_pcs(
            name="low",
            cliques=[clique("w", replicas=1, cpu=1.0)],
            sgs=[PodCliqueScalingGroupConfig(
                name="grp", clique_names=["w"], replicas=2, min_available=1)],
        )
        h.apply(low)
        h.settle()
        self.seed_gold(h)
        hi = self.high_pcs(pods=1)
        hi.spec.template.cliques[0].spec.pod_spec.node_selector = {"pool": "a"}
        h.apply(hi)
        h.settle()
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        # pool b's scaled gang untouched; preemptor waits
        assert h.cluster.metrics.counter(
            "grove_scheduler_preemptions_total").total() == 0
        scaled = h.store.get(PodGang.KIND, "default", "low-0-grp-0")
        assert cond(scaled, PodGangConditionType.SCHEDULED.value).status == "True"
        hi_gang = h.store.get(PodGang.KIND, "default", "hi-0")
        assert cond(hi_gang, PodGangConditionType.SCHEDULED.value).status == "False"


class TestFT_NodeLoss:
    """FT7: node deletion with bound pods (the node-lifecycle + pod GC
    failure model). Pods on a vanished node are lost, replaced, and
    rebound to surviving capacity; the gang recovers."""

    def test_ft7_node_deletion_replaces_and_rebinds_pods(self):
        from grove_tpu.api.types import Node

        h = Harness(nodes=make_nodes(4))
        h.apply(simple_pcs(cliques=[clique("w", replicas=2, cpu=1.0)]))
        h.settle()
        placements = {p.metadata.name: p.node_name
                      for p in h.store.list(Pod.KIND)}
        lost = next(iter(placements.values()))
        h.store.delete(Node.KIND, "default", lost)
        h.settle()
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        pods = h.store.list(Pod.KIND)
        assert len(pods) == 2
        assert all(p.node_name and p.node_name != lost for p in pods), [
            (p.metadata.name, p.node_name) for p in pods
        ]
        assert all(p.status.ready for p in pods)
        gang = h.store.get(PodGang.KIND, "default", "simple1-0")
        assert cond(gang, PodGangConditionType.UNHEALTHY.value).status == "False"

    def test_ft7b_total_node_loss_holds_pods_pending(self):
        from grove_tpu.api.types import Node

        h = Harness(nodes=make_nodes(1))
        h.apply(simple_pcs(cliques=[clique("w", replicas=1, cpu=1.0)]))
        h.settle()
        h.store.delete(Node.KIND, "default", "node-0")
        h.settle()
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        pods = h.store.list(Pod.KIND)
        assert pods and all(not p.node_name for p in pods), [
            (p.metadata.name, p.node_name) for p in pods
        ]
        # capacity returns -> recovery
        for n in make_nodes(1, name_prefix="new"):
            h.store.create(n)
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        pods = h.store.list(Pod.KIND)
        assert all(p.node_name == "new-0" and p.status.ready for p in pods)

def test_pp7_foreign_scheduled_gangs_are_never_preempted():
    """Routing contract: grove must not evict pods of a gang owned by
    a foreign scheduler, no matter the priorities."""
    from grove_tpu.api.meta import ObjectMeta, set_condition
    from grove_tpu.api.auxiliary import PriorityClass
    from grove_tpu.api.types import PodCliqueScalingGroupConfig

    h = Harness(nodes=make_nodes(
        4, racks_per_block=2, hosts_per_rack=2,
        allocatable={"cpu": 1.0, "memory": 8.0, "tpu": 0.0}))
    low = simple_pcs(
        name="low",
        cliques=[clique("w", replicas=1, cpu=1.0)],
        sgs=[PodCliqueScalingGroupConfig(
            name="grp", clique_names=["w"], replicas=4, min_available=1)],
    )
    for c in low.spec.template.cliques:
        c.spec.pod_spec.scheduler_name = "third-party-scheduler"
    h.apply(low)
    h.settle()
    # the external scheduler fills the cluster and writes the contract
    pods = h.store.list(Pod.KIND)
    for i, p in enumerate(sorted(pods, key=lambda x: x.metadata.name)):
        h.store.bind_pod("default", p.metadata.name, f"node-{i}")
    for g in h.store.list(PodGang.KIND):
        def mark(status):
            set_condition(status.conditions, "Scheduled", "True",
                          reason="ExternallyPlaced", now=h.clock.now())
        h.store.patch_status(PodGang.KIND, "default", g.metadata.name, mark)
    h.settle()
    h.store.create(PriorityClass(
        metadata=ObjectMeta(name="gold", namespace=""), value=1000.0))
    hi = simple_pcs(name="hi", cliques=[clique("w", replicas=1, cpu=1.0)])
    hi.spec.template.priority_class_name = "gold"
    h.apply(hi)
    h.settle()
    h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
    # no preemption of the foreign gangs; our gang waits
    assert h.cluster.metrics.counter(
        "grove_scheduler_preemptions_total").total() == 0
    assert all(p.node_name for p in h.store.list(
        Pod.KIND, labels={constants.LABEL_PART_OF: "low"}))


class TestPP_TrialPlacement:
    """Advisor r3 (medium): eviction must be licensed by an EXACT trial
    placement, not aggregate capacity math — victims freeing fragments on
    different nodes must never be destroyed for a preemptor whose pod
    needs one whole node."""

    def test_pp8_fragmented_victims_are_not_evicted(self):
        from grove_tpu.api.auxiliary import PriorityClass
        from grove_tpu.api.meta import ObjectMeta
        from grove_tpu.api.types import PodCliqueScalingGroupConfig

        # 2 nodes x 2 cpu. Per PCS replica: base gang (1 pod, 1 cpu) +
        # scaled gang (1 pod, 1 cpu); BFD packs each replica's pair onto
        # one node -> A: base0+scaled0, B: base1+scaled1. Cluster full.
        h = Harness(nodes=make_nodes(
            2, racks_per_block=2, hosts_per_rack=1,
            allocatable={"cpu": 2.0, "memory": 8.0, "tpu": 0.0}))
        low = simple_pcs(
            name="low", replicas=2,
            cliques=[clique("w", replicas=1, cpu=1.0)],
            sgs=[PodCliqueScalingGroupConfig(
                name="grp", clique_names=["w"], replicas=2, min_available=1)],
        )
        h.apply(low)
        h.settle()
        assert all(p.node_name for p in h.store.list(Pod.KIND))
        h.store.create(PriorityClass(
            metadata=ObjectMeta(name="gold", namespace=""), value=1000.0))
        # preemptor: ONE pod needing a WHOLE node (2 cpu). Evicting both
        # scaled gangs frees 1 cpu on each node -- aggregate 2 >= 2, but
        # no single node fits the pod. Nothing may be disturbed.
        hi = simple_pcs(name="hi", cliques=[clique("w", replicas=1, cpu=2.0)])
        hi.spec.template.priority_class_name = "gold"
        h.apply(hi)
        h.settle()
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        assert h.cluster.metrics.counter(
            "grove_scheduler_preemptions_total").total() == 0
        for name in ("low-0-grp-0", "low-1-grp-0"):
            scaled = h.store.get(PodGang.KIND, "default", name)
            assert cond(
                scaled, PodGangConditionType.SCHEDULED.value
            ).status == "True", name
        hi_gang = h.store.get(PodGang.KIND, "default", "hi-0")
        assert cond(
            hi_gang, PodGangConditionType.SCHEDULED.value
        ).status == "False"


class TestSchedulerLRU:
    """Advisor r3: crossing the reservation-memory bound evicts the
    OLDEST entry, not the whole map."""

    def test_vacated_lru_keeps_hot_entries(self):
        # 8 real nodes: hints are only recorded for nodes that still
        # exist (a vanished node makes a useless — and purged — hint)
        h = Harness(nodes=make_nodes(8))
        sched = h.scheduler
        sched.VACATED_LRU_MAX = 4
        from grove_tpu.cluster.store import Event as Ev
        from grove_tpu.api.types import Pod

        def deleted(name, node):
            from grove_tpu.api.meta import ObjectMeta
            from grove_tpu.api.types import PodSpec

            pod = Pod(
                metadata=ObjectMeta(
                    name=name, namespace="default",
                    labels={constants.LABEL_PODGANG: "g"},
                ),
                spec=PodSpec(),
            )
            pod.node_name = node
            return Ev(seq=0, type="Deleted", kind=Pod.KIND,
                      namespace="default", name=name, obj=pod)

        for i in range(4):
            sched.map_event(deleted(f"p{i}", f"node-{i}"))
        # refresh p0 (re-delete): now p1 is the oldest
        sched.map_event(deleted("p0", "node-5"))
        sched.map_event(deleted("p4", "node-4"))  # crosses the bound
        keys = {k[1] for k in sched._vacated}
        assert "p1" not in keys, "oldest entry evicted"
        assert keys == {"p0", "p2", "p3", "p4"}
        assert sched._vacated[("default", "p0")] == "node-5"
        # a vanished node never enters the hint map
        sched.map_event(deleted("p9", "gone-node"))
        assert ("default", "p9") not in sched._vacated
