"""Durable state store: WAL + snapshots + cold-restart recovery.

The contract (cluster/durability.py): every committed mutation is
write-ahead logged, snapshots bound replay, and recovery — latest valid
snapshot + WAL replay, torn-tail tolerant — rebuilds a BIT-IDENTICAL
store: objects, retained event log, compaction horizon, kind serials,
and the seq/uid counters all resume exactly where the crashed store
stopped. On top of it, `Harness.cold_restart` re-derives all soft state
(leases expired, ShardMap rebuilt, scheduler reservations reconstructed,
caches invalidated) and settles to the same fixpoint a never-crashed run
holds; chaos arms it as the `process_crash` / `wal_torn_write` /
`snapshot_corruption` / `disk_stall` faults.
"""

import io
import os

import pytest

from grove_tpu.api.auxiliary import PriorityClass
from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import Pod, PodCliqueSet
from grove_tpu.chaos import (
    ChaosHarness,
    FaultPlan,
    check_invariants,
    settled_fingerprint,
)
from grove_tpu.cluster import make_nodes
from grove_tpu.cluster.durability import DurabilityError, DurableLog
from grove_tpu.cluster.store import ObjectStore
from grove_tpu.controller import Harness

from test_e2e_basic import clique, simple_pcs

NODES = 16

#: fast-cadence durability config: snapshots actually happen in tests
DUR = {
    "fsync": "never",
    "snapshot_interval_seconds": 30.0,
    "wal_max_bytes": 65536,
}


def durable_config(wal_dir, **overrides):
    return {"durability": {**DUR, "wal_dir": str(wal_dir), **overrides}}


def durable_harness(tmp_path, nodes=NODES, **config):
    cfg = durable_config(tmp_path / "wal")
    cfg.update(config)
    return Harness(nodes=make_nodes(nodes), config=cfg)


def assert_bit_identical(recovered: ObjectStore, live: ObjectStore):
    """The tentpole claim, field by field: the recovered store IS the
    crashed store up to the last durable record."""
    assert recovered.last_seq == live.last_seq
    assert recovered.compaction_horizon == live.compaction_horizon
    assert recovered._kind_serial == live._kind_serial
    assert recovered._uid == live._uid
    assert recovered.event_log_length == live.event_log_length
    for mine, theirs in zip(recovered._events, live._events):
        assert mine == theirs
    live_objs = {k: b for k, b in live._objs.items() if b}
    rec_objs = {k: b for k, b in recovered._objs.items() if b}
    assert rec_objs.keys() == live_objs.keys()
    for kind, bucket in live_objs.items():
        assert rec_objs[kind].keys() == bucket.keys(), kind
        for key, obj in bucket.items():
            assert rec_objs[kind][key] == obj, (kind, key)


def workload():
    return simple_pcs(cliques=[clique("w", replicas=3)])


class TestWalRoundTrip:
    def test_recover_is_bit_identical(self, tmp_path):
        h = durable_harness(tmp_path)
        h.apply(workload())
        h.settle()
        recovered = ObjectStore.recover(str(tmp_path / "wal"))
        assert recovered.recovery_stats["outcome"] == "clean"
        assert_bit_identical(recovered, h.store)
        assert settled_fingerprint(recovered) == settled_fingerprint(
            h.store
        )

    def test_every_mutation_path_is_journaled(self, tmp_path):
        """create / update / update_status / patch_status / bind_pod /
        ungate_pod / finalizers / delete / GC all flow through _emit and
        therefore the WAL; the replayed store matches after each."""
        h = durable_harness(tmp_path)
        h.apply(workload())
        h.settle()
        store = h.store
        # spec update (generation bump)
        pcs = store.get(PodCliqueSet.KIND, "default", "simple1")
        pcs.spec.replicas = 2
        store.update(pcs)
        h.settle()
        # user-level delete cascades through finalizers + GC
        store.delete(PodCliqueSet.KIND, "default", "simple1")
        h.settle()
        assert store.list(Pod.KIND) == []
        recovered = ObjectStore.recover(str(tmp_path / "wal"))
        assert_bit_identical(recovered, store)

    def test_uid_counter_never_recycles_after_recovery(self, tmp_path):
        h = durable_harness(tmp_path)
        store = h.store
        pc = store.create(PriorityClass(
            metadata=ObjectMeta(name="doomed", namespace=""), value=1.0
        ))
        store.delete(PriorityClass.KIND, "", "doomed")
        recovered = ObjectStore.recover(str(tmp_path / "wal"))
        mine = recovered.create(PriorityClass(
            metadata=ObjectMeta(name="next", namespace=""), value=1.0
        ))
        theirs = store.create(PriorityClass(
            metadata=ObjectMeta(name="next", namespace=""), value=1.0
        ))
        assert mine.metadata.uid == theirs.metadata.uid
        assert mine.metadata.uid != pc.metadata.uid
        assert mine.metadata.resource_version == (
            theirs.metadata.resource_version
        )

    def test_durability_off_by_default(self, tmp_path):
        h = Harness(nodes=make_nodes(4))
        assert h.cluster.durability is None
        assert h.store.durability is None
        with pytest.raises(RuntimeError, match="durability"):
            h.cluster.cold_restart()

    def test_fresh_cluster_refuses_a_populated_wal_dir(self, tmp_path):
        durable_harness(tmp_path)
        with pytest.raises(DurabilityError, match="already holds"):
            durable_harness(tmp_path)


class TestTornTail:
    def test_torn_inflight_append_loses_nothing_committed(self, tmp_path):
        h = durable_harness(tmp_path)
        h.apply(workload())
        h.settle()
        h.cluster.durability.tear_tail()
        recovered = ObjectStore.recover(str(tmp_path / "wal"))
        assert recovered.recovery_stats["outcome"] == "torn_tail"
        assert recovered.recovery_stats["torn_tail"] is True
        assert_bit_identical(recovered, h.store)

    def test_truncated_committed_record_rewinds_exactly_one_write(
        self, tmp_path
    ):
        """A crash can also tear a record whose write DID commit in
        memory (fsync raced the power cut): recovery rewinds to the
        previous record — a consistent earlier state, never a mangled
        one."""
        h = durable_harness(tmp_path)
        store = h.store
        store.create(PriorityClass(
            metadata=ObjectMeta(name="kept", namespace=""), value=1.0
        ))
        seq_before = store.last_seq
        store.create(PriorityClass(
            metadata=ObjectMeta(name="torn", namespace=""), value=2.0
        ))
        log = h.cluster.durability
        seg = log._segment_path(log.segment_bases()[-1])
        size = os.path.getsize(seg)
        log._segment.flush()
        with open(seg, "r+b") as fh:
            fh.truncate(size - 7)  # mid-way through the last record
        recovered = ObjectStore.recover(str(tmp_path / "wal"))
        assert recovered.recovery_stats["outcome"] == "torn_tail"
        assert recovered.last_seq == seq_before
        assert recovered.peek(PriorityClass.KIND, "", "kept") is not None
        assert recovered.peek(PriorityClass.KIND, "", "torn") is None


class TestSnapshotFallback:
    def _two_snapshots(self, tmp_path):
        h = durable_harness(tmp_path)
        h.apply(workload())
        h.settle()
        log = h.cluster.durability
        log.snapshot(h.store, force=True)
        h.apply(simple_pcs(cliques=[clique("x", replicas=2)],
                           name="simple2"))
        h.settle()
        log.snapshot(h.store, force=True)
        assert len(log.snapshot_seqs()) == 2
        return h, log

    def test_corrupt_newest_falls_back_to_previous(self, tmp_path):
        h, log = self._two_snapshots(tmp_path)
        newest = log.snapshot_seqs()[-1]
        log.corrupt_latest_snapshot()
        recovered = ObjectStore.recover(str(tmp_path / "wal"))
        stats = recovered.recovery_stats
        assert stats["outcome"] == "snapshot_fallback"
        assert stats["snapshots_skipped"] == 1
        assert stats["snapshot_seq"] < newest
        assert stats["wal_records_replayed"] > 0  # the longer suffix
        assert_bit_identical(recovered, h.store)
        # the corrupt image is QUARANTINED: it must never count as a
        # retained generation again (a later prune trusting it would
        # drop the WAL records its fallback needs)
        names = os.listdir(tmp_path / "wal")
        assert any(n.endswith(".corrupt") for n in names)
        assert newest not in log.snapshot_seqs()

    def test_sole_snapshot_corrupt_replays_full_wal(self, tmp_path):
        """With an incomplete retention window nothing was pruned, so a
        corrupted sole snapshot falls all the way back to the empty
        store + full genesis-WAL replay — still exact."""
        h = Harness(nodes=make_nodes(NODES), config=durable_config(
            tmp_path / "wal", wal_max_bytes=1 << 22,
        ))
        h.apply(workload())
        h.settle()
        log = h.cluster.durability
        log.snapshot(h.store, force=True)
        assert len(log.snapshot_seqs()) == 1
        log.corrupt_latest_snapshot()
        recovered = ObjectStore.recover(str(tmp_path / "wal"))
        stats = recovered.recovery_stats
        assert stats["outcome"] == "snapshot_fallback"
        assert stats["snapshot_seq"] == 0  # empty state + full replay
        assert_bit_identical(recovered, h.store)

    def test_corruption_beyond_the_retention_window_fails_loud(
        self, tmp_path
    ):
        """keep_snapshots=2 guarantees surviving ONE corrupted snapshot.
        Corrupting every retained generation after truncation has pruned
        the genesis WAL leaves a history gap — recovery must refuse to
        splice disjoint histories into a silently inconsistent store."""
        h, log = self._two_snapshots(tmp_path)
        assert log.wal_floor() > 0  # full window: genesis was pruned
        for seq in list(log.snapshot_seqs()):
            path = log._snapshot_path(seq)
            with open(path, "r+b") as fh:
                fh.seek(os.path.getsize(path) // 2)
                fh.write(b"\xde\xad\xbe\xef")
        with pytest.raises(DurabilityError, match="gap"):
            ObjectStore.recover(str(tmp_path / "wal"))


class TestWalTruncationInvariant:
    """WAL truncation vs compact_events — the pinned invariants:

    1. segments are pruned only when covered by the OLDEST retained
       snapshot (wal_floor() <= oldest retained seq, once the retention
       window is full; nothing pruned before then), and
    2. the in-memory event-compaction horizon never constrains recovery,
       because compaction is itself a journaled record — an aggressive
       compact_events far beyond the last snapshot must not cost
       recovery fidelity.
    """

    def test_wal_floor_never_outruns_oldest_retained_snapshot(
        self, tmp_path
    ):
        h = durable_harness(tmp_path)
        log = h.cluster.durability
        for i in range(5):
            h.apply(simple_pcs(cliques=[clique("w", replicas=1)],
                               name=f"pcs{i}"))
            h.settle()
            log.snapshot(h.store, force=True)
            snaps = log.snapshot_seqs()
            assert len(snaps) <= h.config.durability.keep_snapshots
            assert log.wal_floor() <= snaps[0]
            # every retained snapshot can anchor a recovery: the segment
            # chain from it to the head is contiguous
            bases = log.segment_bases()
            assert bases == sorted(bases)
            assert any(b <= snaps[0] for b in bases)

    def test_incomplete_retention_window_prunes_nothing(self, tmp_path):
        """With fewer than keep_snapshots generations on disk the deepest
        fallback is the empty store + full WAL — pruning anything would
        break it (the bug the quarantine + horizon rule closed)."""
        h = Harness(nodes=make_nodes(NODES), config=durable_config(
            tmp_path / "wal", wal_max_bytes=1 << 22,
        ))
        h.apply(workload())
        h.settle()
        log = h.cluster.durability
        log.snapshot(h.store, force=True)
        assert len(log.snapshot_seqs()) == 1
        assert log.wal_floor() == 0  # the genesis segment survived

    def test_compaction_beyond_snapshot_is_replayed_not_lost(
        self, tmp_path
    ):
        h = durable_harness(tmp_path)
        h.apply(workload())
        h.settle()
        log = h.cluster.durability
        log.snapshot(h.store, force=True)
        # more history, then compact PAST the snapshot — the horizon
        # outruns the last snapshot, which must cost nothing: the
        # compaction is a WAL record, and the WAL retains everything
        # since the snapshot regardless of the in-memory horizon
        h.apply(simple_pcs(cliques=[clique("x", replicas=2)],
                           name="simple2"))
        h.settle()
        dropped = h.store.compact_events(h.store.last_seq)
        assert dropped > 0
        assert h.store.compaction_horizon > log.last_snapshot_seq
        assert log.wal_floor() <= log.snapshot_seqs()[0]
        recovered = ObjectStore.recover(str(tmp_path / "wal"))
        assert_bit_identical(recovered, h.store)
        # and the recovered consumers relist exactly like live ones
        assert recovered.event_log_length == h.store.event_log_length

    def test_compaction_before_snapshot_roundtrips(self, tmp_path):
        h = durable_harness(tmp_path)
        h.apply(workload())
        h.settle()
        h.compact_events()
        h.cluster.durability.snapshot(h.store, force=True)
        h.apply(simple_pcs(cliques=[clique("x", replicas=1)],
                           name="simple2"))
        h.settle()
        recovered = ObjectStore.recover(str(tmp_path / "wal"))
        assert_bit_identical(recovered, h.store)


class TestColdRestart:
    def test_cold_restart_settles_to_identical_fixpoint(self, tmp_path):
        h = durable_harness(tmp_path)
        h.apply(workload())
        h.settle()
        fixpoint = settled_fingerprint(h.store)
        stats = h.cold_restart()
        assert stats["outcome"] == "clean"
        h.settle()
        assert settled_fingerprint(h.store) == fixpoint
        assert check_invariants(h.store) == []
        # the restarted plane still schedules NEW work (soft state —
        # reservations, engines, usage accounting — actually rebuilt)
        h.apply(simple_pcs(cliques=[clique("y", replicas=2)],
                           name="after"))
        h.settle()
        pods = h.store.list(Pod.KIND)
        assert all(p.node_name and p.status.ready for p in pods)

    def test_cold_restart_expires_leader_lease(self, tmp_path):
        from grove_tpu.controller.leaderelection import Lease

        h = durable_harness(
            tmp_path, leader_election={"enabled": True}
        )
        h.apply(workload())
        h.settle()
        le = h.config.leader_election
        assert h.store.get(
            Lease.KIND, le.lease_namespace, le.lease_name
        ) is not None
        h.cold_restart()
        # the dead process's lease is gone; the rebuilt manager
        # re-acquires on its next settle, and node heartbeat leases
        # (infrastructure state) survived
        assert h.store.get(
            Lease.KIND, le.lease_namespace, le.lease_name
        ) is None
        from grove_tpu.cluster.nodehealth import NODE_LEASE_NAMESPACE

        assert h.store.scan(Lease.KIND, namespace=NODE_LEASE_NAMESPACE)
        h.settle()
        assert h.store.get(
            Lease.KIND, le.lease_namespace, le.lease_name
        ) is not None

    def test_cold_restart_rebuilds_shard_map(self, tmp_path):
        from grove_tpu.controller.sharding import (
            SHARD_MAP_NAME,
            SHARD_NAMESPACE,
            ShardMap,
        )

        h = durable_harness(
            tmp_path, controllers={"shards": 2}
        )
        h.apply(workload())
        h.settle()
        fixpoint = settled_fingerprint(h.store)
        old_map = h.store.get(ShardMap.KIND, SHARD_NAMESPACE, SHARD_MAP_NAME)
        assert old_map is not None
        h.cold_restart()
        h.settle()
        new_map = h.store.get(ShardMap.KIND, SHARD_NAMESPACE, SHARD_MAP_NAME)
        assert new_map is not None
        assert new_map.metadata.uid != old_map.metadata.uid  # rebuilt
        assert settled_fingerprint(h.store) == fixpoint

    def test_kubelet_relists_against_the_recovered_store(self, tmp_path):
        h = durable_harness(tmp_path)
        h.apply(workload())
        h.settle()
        # a node-level fault in flight across the crash: kubelet-side
        # infrastructure truth must survive the control-plane restart
        victim = h.store.scan("Node")[0].metadata.name
        h.kubelet.fail_heartbeat(victim)
        h.cold_restart()
        assert victim in h.kubelet.heartbeat_failed
        assert h.kubelet.event_cursor == h.store.last_seq
        h.settle()
        assert check_invariants(h.store) == []


class TestNewProcessBoot:
    """Harness.recover: booting a GENUINELY NEW process from the files
    alone — the disaster-recovery path where the crashed predecessor's
    Python objects are gone (cold_restart covers the in-process model)."""

    def test_recover_boots_to_the_same_fixpoint_and_resumes_journaling(
        self, tmp_path
    ):
        cfg = durable_config(tmp_path / "wal")
        old = Harness(nodes=make_nodes(NODES), config=cfg)
        old.apply(workload())
        old.settle()
        fixpoint = settled_fingerprint(old.store)
        old.cluster.durability.close()  # the old process is gone
        del old

        h = Harness.recover(cfg)
        assert h.store.recovery_stats["outcome"] == "clean"
        h.settle()
        assert settled_fingerprint(h.store) == fixpoint
        assert check_invariants(h.store) == []
        # journaling RESUMED into the same dir: new work lands on disk
        # and a further file-level recovery sees it
        h.apply(simple_pcs(cliques=[clique("z", replicas=2)],
                           name="after-boot"))
        h.settle()
        again = ObjectStore.recover(str(tmp_path / "wal"))
        assert settled_fingerprint(again) == settled_fingerprint(h.store)
        assert again.last_seq == h.store.last_seq

    def test_recover_survives_torn_tail_on_disk(self, tmp_path):
        cfg = durable_config(tmp_path / "wal")
        old = Harness(nodes=make_nodes(NODES), config=cfg)
        old.apply(workload())
        old.settle()
        fixpoint = settled_fingerprint(old.store)
        old.cluster.durability.tear_tail()  # crash mid-append
        old.cluster.durability.close()
        del old
        h = Harness.recover(cfg)
        assert h.store.recovery_stats["outcome"] == "torn_tail"
        h.settle()
        assert settled_fingerprint(h.store) == fixpoint

    def test_recover_from_an_empty_directory_fails_loud(self, tmp_path):
        """A mistyped-but-existing path (or a freshly mounted empty
        volume) must never 'recover' to an empty cluster on the disaster
        recovery path — the history would appear silently lost."""
        (tmp_path / "empty").mkdir()
        with pytest.raises(DurabilityError, match="no durable state"):
            ObjectStore.recover(str(tmp_path / "empty"))
        with pytest.raises(DurabilityError, match="no durable state"):
            Harness.recover(durable_config(tmp_path / "empty"))

    def test_sharded_recover_rebuilds_the_map_before_serving(
        self, tmp_path
    ):
        """Harness.recover expires the dead fleet's ShardMap BEFORE the
        managers are built (a ShardedManager constructed against the
        stale map would adopt its shard width instead of the config's)."""
        from grove_tpu.controller.sharding import (
            SHARD_MAP_NAME,
            SHARD_NAMESPACE,
            ShardMap,
        )

        cfg = durable_config(tmp_path / "wal")
        cfg["controllers"] = {"shards": 2}
        old = Harness(nodes=make_nodes(NODES), config=cfg)
        old.apply(workload())
        old.settle()
        fixpoint = settled_fingerprint(old.store)
        old_uid = old.store.get(
            ShardMap.KIND, SHARD_NAMESPACE, SHARD_MAP_NAME
        ).metadata.uid
        old.cluster.durability.close()
        del old
        h = Harness.recover(cfg)
        h.settle()
        assert settled_fingerprint(h.store) == fixpoint
        new_map = h.store.get(
            ShardMap.KIND, SHARD_NAMESPACE, SHARD_MAP_NAME
        )
        assert new_map is not None and new_map.metadata.uid != old_uid

    def test_from_durable_guards(self, tmp_path):
        from grove_tpu.api.config import load_operator_config
        from grove_tpu.cluster.cluster import Cluster

        with pytest.raises(ValueError, match="wal_dir"):
            Cluster.from_durable(load_operator_config({}))
        cfg = durable_config(tmp_path / "wal")
        Harness(nodes=make_nodes(2), config=cfg)
        with pytest.raises(ValueError, match="neither"):
            Cluster(
                nodes=make_nodes(2),
                recovered_store=ObjectStore.recover(
                    str(tmp_path / "wal")
                ),
            )


class TestObservability:
    def test_debug_dump_durability_block_and_metrics(self, tmp_path):
        h = durable_harness(tmp_path)
        h.apply(workload())
        h.settle()
        dump = h.debug_dump()["store"]["durability"]
        assert dump["enabled"] is True
        assert dump["wal_records_total"] > 0
        assert dump["wal_bytes_total"] > 0
        assert dump["last_recovery"] is None
        m = h.cluster.metrics
        assert m.counter("grove_store_wal_records_total").total() == (
            dump["wal_records_total"]
        )
        assert m.counter("grove_store_wal_bytes_total").total() == (
            dump["wal_bytes_total"]
        )
        h.cold_restart()
        h.settle()
        dump = h.debug_dump()["store"]["durability"]
        assert dump["last_recovery"]["outcome"] == "clean"
        assert dump["last_snapshot_seq"] > 0  # the recovery checkpoint
        assert m.counter("grove_store_recoveries_total").value(
            outcome="clean"
        ) == 1.0

    def test_disabled_dump_shape(self):
        h = Harness(nodes=make_nodes(2))
        assert h.debug_dump()["store"]["durability"] == {"enabled": False}


@pytest.mark.chaos
class TestChaosRecoveryEquivalence:
    """The recovery equivalence gate (acceptance criterion): for >= 10
    chaos seeds with process_crash armed — whole-process crashes
    recovering from disk mid-plan, torn WAL tails, corrupted snapshots,
    disk stalls on top of the full classic fault mix — the recovered
    run's settle state is fingerprint-identical to the fault-free
    fixpoint. Wide matrix: scripts/chaos_sweep.py --durability."""

    SEEDS = tuple(range(10))

    @pytest.fixture(scope="class")
    def baseline(self):
        h = Harness(nodes=make_nodes(NODES))
        h.apply(workload())
        h.settle()
        return settled_fingerprint(h.store)

    def _run(self, seed, tmp_path):
        plan = FaultPlan.from_seed(
            seed,
            process_crash_rate=0.15,
            wal_torn_write_rate=0.4,
            snapshot_corruption_rate=0.3,
            disk_stall_rate=0.1,
        )
        ch = ChaosHarness(
            plan, nodes=make_nodes(NODES),
            config=durable_config(tmp_path / f"wal{seed}"),
        )
        quiet = io.StringIO()
        ch.harness.cluster.logger.stream = quiet
        ch.harness.manager.logger.stream = quiet
        ch.apply(workload())
        ch.run_chaos()
        return ch

    @pytest.mark.parametrize("seed", SEEDS)
    def test_recovered_settle_matches_fault_free_fixpoint(
        self, seed, tmp_path, baseline
    ):
        ch = self._run(seed, tmp_path)
        assert settled_fingerprint(ch.raw_store) == baseline, (
            f"seed {seed} diverged (faults: {ch.plan.counts}, "
            f"recoveries: {ch.recovery_stats})"
        )
        assert check_invariants(ch.raw_store) == []
        if ch.process_restarts:
            assert len(ch.recovery_stats) == ch.process_restarts
            assert all(
                s["outcome"] in (
                    "clean", "torn_tail", "snapshot_fallback"
                )
                for s in ch.recovery_stats
            )

    def test_matrix_actually_exercised_every_recovery_path(
        self, tmp_path, baseline
    ):
        """A vacuous gate must not read as coverage: across the seed
        matrix, crashes happened and every outcome class appeared."""
        outcomes: set[str] = set()
        crashes = 0
        for seed in self.SEEDS:
            ch = self._run(seed, tmp_path)
            crashes += ch.process_restarts
            outcomes.update(s["outcome"] for s in ch.recovery_stats)
        assert crashes >= len(self.SEEDS), "process_crash barely fired"
        assert outcomes >= {"clean", "torn_tail", "snapshot_fallback"}

    def test_durability_seed_is_bit_reproducible(self, tmp_path):
        a = self._run(5, tmp_path / "a")
        b = self._run(5, tmp_path / "b")
        assert a.plan.counts == b.plan.counts
        assert a.process_restarts == b.process_restarts
        assert [s["outcome"] for s in a.recovery_stats] == [
            s["outcome"] for s in b.recovery_stats
        ]
        assert settled_fingerprint(a.raw_store) == settled_fingerprint(
            b.raw_store
        )

    def test_wedged_summary_names_the_replay_position(self, tmp_path):
        """The flight-recorder postmortem carries the recovery audit
        trail: which snapshot each crash recovered from and where WAL
        replay stopped."""
        ch = self._run(0, tmp_path)
        wedged = ch.wedged_summary()
        assert wedged["process_restarts"] == ch.process_restarts
        assert len(wedged["recoveries"]) == ch.process_restarts
        for rec in wedged["recoveries"]:
            assert "snapshot_seq" in rec
            assert "recovered_last_seq" in rec
            assert "wal_records_replayed" in rec
