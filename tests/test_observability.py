"""Error-model + observability tests.

VERDICT r1 #3: (a) a reconciler failure becomes a typed error written to
the owning PCS's status.last_errors/last_operation (errors.go:90-103,
reconcile_error_recorder.go analog); (b) an in-framework metrics registry
carries the north-star numbers and controllers emit k8s-style Events
(constants.go:36-98)."""

import pytest

from grove_tpu.api.podgang import PodGang
from grove_tpu.api.types import Pod, PodClique, PodCliqueSet
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness
from grove_tpu.observability import ClusterEvent, MetricsRegistry
from grove_tpu.observability.events import (
    REASON_GANG_TERMINATED,
    REASON_PODGANG_SCHEDULED,
    REASON_PODGANG_UNSCHEDULABLE,
)

from test_e2e_basic import clique, simple_pcs


class TestErrorSurfacing:
    def test_reconciler_crash_surfaces_to_pcs_status(self):
        h = Harness(nodes=make_nodes(4))
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()

        # kill the PCS reconciler mid-flight: every reconcile now raises
        original = h.manager.controllers[0].reconcile
        calls = {"n": 0}

        def boom(request):
            calls["n"] += 1
            raise RuntimeError("injected reconciler crash")

        h.manager.controllers[0].reconcile = boom
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        pcs.spec.replicas = 2  # trigger a reconcile
        h.store.update(pcs)
        h.settle()  # must NOT hang or raise: error is caught + recorded

        live = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert len(live.status.last_errors) == 1
        err = live.status.last_errors[0]
        assert err.code == "ERR_INTERNAL"
        assert "injected reconciler crash" in err.description
        assert live.status.last_operation.state == "Error"
        assert calls["n"] >= 1
        assert h.manager.errors, "manager records the failure too"

        # recovery: restore the reconciler, retry fires on the error
        # interval, status clears
        h.manager.controllers[0].reconcile = original
        h.advance(h.config.controllers.sync_retry_interval_seconds + 0.1)
        live = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert live.status.last_errors == []
        assert live.status.last_operation.state == "Succeeded"
        assert len(h.store.list(Pod.KIND)) == 4  # replica 2 got built

    def test_success_stamps_last_operation(self):
        h = Harness(nodes=make_nodes(4))
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        live = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert live.status.last_operation is not None
        assert live.status.last_operation.state == "Succeeded"
        assert live.status.last_errors == []

    def test_child_reconciler_error_on_child_status(self):
        # each kind carries its OWN last_errors (podclique.go:107-108) —
        # a failing PodClique reconciler surfaces on the PodClique, and the
        # healthy PCS reconciler's success pass must NOT clear it
        h = Harness(nodes=make_nodes(4))
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        pclq_rec = next(
            c for c in h.manager.controllers if c.name == "podclique"
        )
        original = pclq_rec.reconcile
        pclq_rec.reconcile = lambda req: (_ for _ in ()).throw(
            ValueError("child blew up")
        )
        # poke the PodClique so its reconciler runs
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        pclq.spec.replicas = 3
        with h.store.impersonate(h.config.authorization.operator_identity):
            h.store.update(pclq)
        h.settle()
        live = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        assert live.status.last_errors
        assert "child blew up" in live.status.last_errors[0].description
        assert live.status.last_operation.state == "Error"
        # PCS's own reconcile stays green
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert pcs.status.last_errors == []
        # recovery clears the child's error
        pclq_rec.reconcile = original
        h.advance(h.config.controllers.sync_retry_interval_seconds + 0.1)
        live = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        assert live.status.last_errors == []
        assert live.status.last_operation.state == "Succeeded"


class TestRecordStatusErrorIdempotency:
    """The anti-livelock guarantee record_status_error's docstring claims:
    a REPEATING identical error must not re-stamp timestamps (its own
    status write would otherwise re-trigger the manager forever), while a
    CHANGED error must."""

    def _failing_harness(self):
        h = Harness(nodes=make_nodes(4))
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        return h

    def test_repeating_error_does_not_restamp(self):
        from grove_tpu.controller.errors import (
            GroveError,
            record_status_error,
        )

        h = self._failing_harness()
        err = GroveError("ERR_SYNC_FAILED", "op", "same failure")
        record_status_error(h.store, PodCliqueSet.KIND, "default",
                            "simple1", err)
        live = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        stamped = live.status.last_errors[0].observed_at
        op_stamped = live.status.last_operation.last_update_time
        rv = live.metadata.resource_version
        h.clock.advance(10.0)
        # identical error later: no timestamp movement, NO status write
        record_status_error(h.store, PodCliqueSet.KIND, "default",
                            "simple1", err)
        live = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert live.status.last_errors[0].observed_at == stamped
        assert live.status.last_operation.last_update_time == op_stamped
        assert live.metadata.resource_version == rv, (
            "identical error must not produce a store write"
        )

    def test_changed_error_restamps(self):
        from grove_tpu.controller.errors import (
            GroveError,
            record_status_error,
        )

        h = self._failing_harness()
        record_status_error(
            h.store, PodCliqueSet.KIND, "default", "simple1",
            GroveError("ERR_SYNC_FAILED", "op", "first failure"),
        )
        h.clock.advance(10.0)
        record_status_error(
            h.store, PodCliqueSet.KIND, "default", "simple1",
            GroveError("ERR_STORE_CONFLICT", "op", "different failure"),
        )
        live = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        assert live.status.last_errors[0].observed_at == h.clock.now()
        assert live.status.last_errors[0].code == "ERR_STORE_CONFLICT"
        assert (
            live.status.last_operation.last_update_time == h.clock.now()
        )


class TestResilienceMetrics:
    """Backoff/breaker observability: the retry flow feeds the registry
    and the debug dump (the new resilience families in the text
    exposition are what an operator alerts on)."""

    def test_retry_metrics_and_exposition(self):
        h = Harness(nodes=make_nodes(4))
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        original = h.manager.controllers[0].reconcile
        h.manager.controllers[0].reconcile = lambda req: (
            (_ for _ in ()).throw(RuntimeError("flaky"))
        )
        pcs = h.store.get(PodCliqueSet.KIND, "default", "simple1")
        pcs.spec.replicas = 2
        h.store.update(pcs)
        h.settle()
        h.advance(2.0)  # one backoff retry fires and fails again
        m = h.cluster.metrics
        retries = m.counter("grove_manager_reconcile_retries_total")
        assert retries.value(controller="podcliqueset") >= 2
        depth = m.gauge("grove_manager_backoff_depth")
        assert depth.value(controller="podcliqueset") >= 2
        dump = h.debug_dump()
        res = dump["manager"]["resilience"]["podcliqueset"]
        assert res["breaker"] == "closed"
        assert res["retrying_requests"] == 1
        assert res["max_attempts"] >= 2
        assert dump["manager"]["backoff"]["retry_budget"] == (
            h.config.controllers.error_retry_budget
        )
        text = m.render()
        assert 'grove_manager_reconcile_retries_total{controller="podcliqueset"}' in text
        assert "grove_manager_backoff_depth" in text
        # recovery zeroes the depth gauge and clears the retry chain
        h.manager.controllers[0].reconcile = original
        h.advance(h.config.controllers.error_backoff_max_seconds + 1)
        assert depth.value(controller="podcliqueset") == 0.0
        assert h.debug_dump()["manager"]["resilience"] == {}

    def test_chaos_fault_metrics_exported(self):
        from grove_tpu.chaos import ChaosHarness, FaultPlan

        ch = ChaosHarness(FaultPlan.from_seed(3), nodes=make_nodes(8))
        import io

        quiet = io.StringIO()
        ch.harness.cluster.logger.stream = quiet
        ch.harness.manager.logger.stream = quiet
        ch.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        ch.run_chaos()
        m = ch.harness.cluster.metrics
        faults = m.counter("grove_chaos_faults_injected_total")
        assert faults.total() > 0
        assert faults.total() == ch.plan.total_injected
        text = m.render()
        assert "grove_chaos_faults_injected_total" in text


class TestMetrics:
    def test_registry_primitives(self):
        r = MetricsRegistry()
        c = r.counter("c", "help")
        c.inc()
        c.inc(2.0, kind="x")
        assert c.total() == 3.0
        assert c.value(kind="x") == 2.0
        h = r.histogram("h")
        for v in (0.1, 0.2, 0.3, 0.4):
            h.observe(v)
        assert h.count == 4
        assert h.percentile(50) == pytest.approx(0.2, abs=0.11)
        assert h.percentile(99) == 0.4
        g = r.gauge("g")
        g.set(7.0)
        assert g.value() == 7.0
        text = r.render()
        assert "# TYPE c counter" in text
        assert 'c{kind="x"} 2.0' in text
        assert "h_count 4" in text

    def test_scheduler_feeds_registry(self):
        h = Harness(nodes=make_nodes(4))
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        m = h.cluster.metrics
        assert m.counter("grove_scheduler_gangs_scheduled_total").total() == 1
        assert m.counter("grove_solver_gangs_placed_total").total() >= 1
        bind = m.histogram("grove_scheduler_gang_bind_latency_seconds")
        assert bind.count == 1
        assert m.histogram("grove_solver_backlog_bind_seconds").count >= 1
        score = m.histogram("grove_solver_placement_score")
        assert 0.0 < score.mean() <= 1.0

    def test_unschedulable_counted(self):
        h = Harness(nodes=make_nodes(1, allocatable={"cpu": 1.0,
                                                     "memory": 1.0,
                                                     "tpu": 0.0}))
        h.apply(simple_pcs(cliques=[clique("w", replicas=2, cpu=3.0)]))
        h.settle()
        m = h.cluster.metrics
        assert m.counter(
            "grove_scheduler_gangs_unschedulable_total"
        ).total() == 1


class TestEvents:
    def test_schedule_and_creation_events(self):
        h = Harness(nodes=make_nodes(4))
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        events = h.store.list(ClusterEvent.KIND)
        reasons = {e.reason for e in events}
        assert REASON_PODGANG_SCHEDULED in reasons
        assert "CreateSuccessful" in reasons
        sched = next(e for e in events
                     if e.reason == REASON_PODGANG_SCHEDULED)
        assert sched.involved_kind == PodGang.KIND
        assert sched.involved_name == "simple1-0"
        assert sched.reporting_controller == "scheduler"
        assert sched.type == "Normal"

    def test_unschedulable_and_termination_events(self):
        h = Harness(nodes=make_nodes(4))
        pcs = simple_pcs(cliques=[clique("w", replicas=2)])
        pcs.spec.template.termination_delay = 60.0
        h.apply(pcs)
        h.settle()
        h.kubelet.crash_pod("default", "simple1-0-w-0")
        h.settle()
        h.advance(61.0)
        events = h.store.list(ClusterEvent.KIND)
        term = [e for e in events if e.reason == REASON_GANG_TERMINATED]
        assert term and term[0].type == "Warning"
        assert term[0].involved_kind == PodCliqueSet.KIND

    def test_event_dedup_bumps_count(self):
        h = Harness(nodes=make_nodes(2, allocatable={"cpu": 1.0,
                                                     "memory": 1.0,
                                                     "tpu": 0.0}))
        h.apply(simple_pcs(cliques=[clique("w", replicas=2, cpu=3.0)]))
        h.settle()
        # the unschedulable event exists once with count 1 (status-change
        # gated); crash through more failed cycles via capacity flap
        evts = [e for e in h.store.list(ClusterEvent.KIND)
                if e.reason == REASON_PODGANG_UNSCHEDULABLE]
        assert len(evts) == 1
        assert evts[0].count >= 1


class TestLogging:
    def test_log_config_drives_output(self):
        import io

        from grove_tpu.api.config import load_operator_config
        from grove_tpu.cluster import Cluster

        buf = io.StringIO()
        cluster = Cluster(
            nodes=make_nodes(4),
            config=load_operator_config(
                {"log": {"level": "debug", "format": "json"}}
            ),
        )
        cluster.logger.stream = buf
        h = Harness(cluster=cluster)
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        out = buf.getvalue()
        assert '"logger": "grove.scheduler"' in out
        assert '"msg": "backlog solved"' in out
        assert '"placed": 1' in out
        # info level filters the debug records out
        buf2 = io.StringIO()
        c2 = Cluster(nodes=make_nodes(4))  # default level: info
        c2.logger.stream = buf2
        h2 = Harness(cluster=c2)
        h2.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h2.settle()
        assert "backlog solved" not in buf2.getvalue()

    def test_reconcile_errors_logged(self):
        import io

        from grove_tpu.cluster import Cluster

        buf = io.StringIO()
        cluster = Cluster(nodes=make_nodes(4))
        cluster.logger.stream = buf
        h = Harness(cluster=cluster)
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        h.scheduler.reconcile = lambda req: (_ for _ in ()).throw(
            OSError("tunnel down")
        )
        h.store.create(make_nodes(1, name_prefix="poke")[0])
        h.settle()
        assert "reconcile failed" in buf.getvalue()
        assert "tunnel down" in buf.getvalue()


class TestManagerMetrics:
    """controller-runtime metrics analog: workqueue depth, per-controller
    reconcile totals/errors/durations (manager.go:94-96 exposes these for
    the reference's controllers; grove_tpu feeds its own registry)."""

    def test_reconcile_metrics_flow(self):
        from test_e2e_basic import clique, simple_pcs

        from grove_tpu.cluster import make_nodes
        from grove_tpu.controller import Harness

        from grove_tpu.api.types import PodCliqueScalingGroupConfig

        h = Harness(nodes=make_nodes(8))
        h.apply(simple_pcs(
            cliques=[clique("w", replicas=2)],
            sgs=[PodCliqueScalingGroupConfig(name="g", clique_names=["w"],
                                             replicas=2, min_available=1)],
        ))
        h.settle()
        m = h.cluster.metrics
        total = m.counter("grove_manager_reconcile_total")
        for controller in ("podcliqueset", "podclique",
                           "podcliquescalinggroup", "scheduler"):
            assert total.value(controller=controller) > 0, controller
        dur = m.get("grove_manager_reconcile_duration_seconds")
        assert dur is not None and dur.count > 0
        assert dur.percentile(99, controller="scheduler") > 0
        assert m.counter("grove_manager_reconcile_errors_total").total() == 0
        # registered + rendered in the Prometheus exposition
        text = m.render()
        assert 'grove_manager_reconcile_total{controller="scheduler"}' in text

    def test_error_counter_increments_on_failing_reconcile(self):
        from grove_tpu.api.validation import ValidationError
        from grove_tpu.cluster import make_nodes
        from grove_tpu.cluster.store import Admission
        from grove_tpu.controller import Harness
        from test_e2e_basic import clique, simple_pcs

        h = Harness(nodes=make_nodes(4))
        h.store.register_admission(
            "Pod",
            Admission(validate=lambda p: (_ for _ in ()).throw(
                ValidationError(["quota"]))),
        )
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        errs = h.cluster.metrics.counter("grove_manager_reconcile_errors_total")
        assert errs.value(controller="podclique") > 0


class TestExpositionEscaping:
    """Satellite (PR 3): Prometheus text-format escaping — label values
    containing backslash, double-quote, or newline previously rendered
    invalid/ambiguous exposition text."""

    def test_label_values_escaped_per_spec(self):
        r = MetricsRegistry()
        r.counter("c", "help").inc(kind='a"b\\c\nd')
        text = r.render()
        assert 'c{kind="a\\"b\\\\c\\nd"} 1.0' in text
        assert "\nd" not in text.replace("\\nd", ""), "raw newline leaked"

    def test_help_text_escaped(self):
        r = MetricsRegistry()
        r.counter("c", "line1\nline2\\tail").inc()
        text = r.render()
        assert "# HELP c line1\\nline2\\\\tail" in text

    def test_quantile_labels_flow_through_escaping_path(self):
        r = MetricsRegistry()
        h = r.histogram("h", "help")
        h.observe(1.0, tier='we"ird')
        text = r.render()
        # the quantile label and the user label render through ONE
        # formatting path, escaped together
        assert 'h{quantile="0.50",tier="we\\"ird"} 1.0' in text
        assert 'h_count{tier="we\\"ird"} 1' in text


class TestHistogramBounds:
    """Satellite (PR 3): bounded histogram memory at 10^5-gang scale —
    exact percentiles below the cap, deterministic reservoir past it,
    exact count/sum throughout, and reset() for long-lived harnesses."""

    def test_exact_below_cap(self):
        from grove_tpu.observability.metrics import Histogram

        h = Histogram("h", max_observations=100)
        for v in range(50):
            h.observe(float(v))
        assert h.count == 50
        assert h.percentile(100) == 49.0
        assert h.percentile(0) == 0.0

    def test_reservoir_caps_memory_keeps_exact_totals(self):
        from grove_tpu.observability.metrics import Histogram

        h = Histogram("h", max_observations=128)
        n = 5000
        for v in range(n):
            h.observe(float(v))
        assert len(h._series[()]) == 128, "raw samples capped"
        assert h.count == n, "count stays exact past the cap"
        assert h.series_count() == n
        assert h.sum == pytest.approx(n * (n - 1) / 2)
        assert h.mean() == pytest.approx((n - 1) / 2)
        # a uniform reservoir's median estimates the true median
        assert h.percentile(50) == pytest.approx(n / 2, rel=0.35)

    def test_reservoir_deterministic(self):
        from grove_tpu.observability.metrics import Histogram

        def fill():
            h = Histogram("h", max_observations=32)
            for v in range(1000):
                h.observe(float(v), shard="s1")
            return list(h._series[(("shard", "s1"),)])

        assert fill() == fill(), "replayable: no global RNG involved"

    def test_reset_drops_all_series(self):
        from grove_tpu.observability.metrics import Histogram

        h = Histogram("h", max_observations=16)
        for v in range(40):
            h.observe(float(v), k="a")
        h.reset()
        assert h.count == 0
        assert h.sum == 0.0
        assert h.percentile(50, k="a") == 0.0
        h.observe(3.0, k="a")
        assert h.count == 1 and h.percentile(50, k="a") == 3.0


class TestEventDedupCollision:
    """Satellite (PR 3): the dedup key must not collide for
    prefix-overlapping (name, reason) pairs."""

    def test_prefix_overlap_yields_distinct_events(self):
        from grove_tpu.api.meta import ObjectMeta
        from grove_tpu.api.types import Pod, PodSpec
        from grove_tpu.observability.events import EventRecorder

        h = Harness(nodes=make_nodes(2))
        rec = EventRecorder(h.store, controller="test")
        p1 = Pod(metadata=ObjectMeta(name="pod-a-b"), spec=PodSpec())
        p2 = Pod(metadata=ObjectMeta(name="pod-a"), spec=PodSpec())
        h.store.create(p1)
        h.store.create(p2)
        rec.warning(p1, "c", "first")
        rec.warning(p2, "b-c", "second")
        evts = [e for e in h.store.list(ClusterEvent.KIND)
                if e.reporting_controller == "test"]
        assert len(evts) == 2, "prefix-overlapping pairs must not merge"
        assert {e.count for e in evts} == {1}

    def test_same_triple_still_dedups(self):
        from grove_tpu.api.meta import ObjectMeta
        from grove_tpu.api.types import Pod, PodSpec
        from grove_tpu.observability.events import EventRecorder

        h = Harness(nodes=make_nodes(2))
        rec = EventRecorder(h.store, controller="test")
        p = Pod(metadata=ObjectMeta(name="pod-a"), spec=PodSpec())
        h.store.create(p)
        rec.warning(p, "r", "m1")
        rec.warning(p, "r", "m2")
        evts = [e for e in h.store.list(ClusterEvent.KIND)
                if e.reporting_controller == "test"]
        assert len(evts) == 1
        assert evts[0].count == 2

    def test_dedup_name_collision_free(self):
        from grove_tpu.observability.events import EventRecorder

        a = EventRecorder.dedup_name("Pod", "pod-a-b", "c")
        b = EventRecorder.dedup_name("Pod", "pod-a", "b-c")
        assert a != b
        # stable across calls (it IS the store key)
        assert a == EventRecorder.dedup_name("Pod", "pod-a-b", "c")


class TestHistogramSeriesHygiene:
    """Satellite (PR 18): Histogram gains remove()/label_sets()
    (Counter/Gauge parity) so per-tenant latency series can be
    reconciled away with their owning tenant."""

    def test_remove_and_label_sets_parity(self):
        from grove_tpu.observability.metrics import Histogram

        h = Histogram("h", max_observations=16)
        h.observe(1.0, tenant="a")
        h.observe(2.0, tenant="b")
        assert sorted(ls["tenant"] for ls in h.label_sets()) == ["a", "b"]
        assert h.remove(tenant="a") is True
        assert h.remove(tenant="a") is False, "second remove: gone"
        assert [ls["tenant"] for ls in h.label_sets()] == ["b"]
        # every accumulator dropped, not just the exposition
        assert h.series_count(tenant="a") == 0
        assert h.percentile(50, tenant="a") == 0.0
        assert h.count == 1 and h.sum == 2.0

    def test_removed_series_leaves_exposition(self):
        from grove_tpu.observability.metrics import MetricsRegistry

        r = MetricsRegistry()
        h = r.histogram("grove_lat", "help")
        h.observe(1.0, tenant="gone")
        h.observe(2.0, tenant="kept")
        h.remove(tenant="gone")
        text = r.render()
        assert 'tenant="kept"' in text
        assert 'tenant="gone"' not in text

    def test_tenant_teardown_drops_latency_series(self):
        """The tenancy export applies the established
        label_sets/remove pattern to the per-tenant bind-latency
        histogram: a removed tenant's series leaves /metrics."""
        from grove_tpu.observability import MetricsRegistry
        from grove_tpu.tenancy import TenancyManager

        from test_solver import cluster
        from test_tenancy import tenancy_cfg

        registry = MetricsRegistry()
        m = TenancyManager(
            tenancy_cfg([
                {"name": "t-live", "guaranteed": {"cpu": 4.0}},
                {"name": "t-dead", "guaranteed": {"cpu": 4.0}},
            ]),
            metrics=registry,
        )
        hist = registry.histogram(
            "grove_scheduler_tenant_bind_latency_seconds", "help"
        )
        hist.observe(0.5, tenant="t-live")
        hist.observe(0.7, tenant="t-dead")
        snap = cluster()
        h = Harness(nodes=make_nodes(4))
        m.refresh_and_export(
            h.store, snap, h.cluster.pod_demand_fn(snap.resource_names)
        )
        assert sorted(
            ls["tenant"] for ls in hist.label_sets()
        ) == ["t-dead", "t-live"]
        m.configure(tenancy_cfg([
            {"name": "t-live", "guaranteed": {"cpu": 4.0}},
        ]))
        m.refresh_and_export(
            h.store, snap, h.cluster.pod_demand_fn(snap.resource_names)
        )
        assert [ls["tenant"] for ls in hist.label_sets()] == ["t-live"]
        assert 't-dead' not in registry.render()


class TestHistogramEstimation:
    """Satellite (PR 18): percentiles past the downsampling cap are
    estimates and must SAY so — is_estimated() programmatically and an
    estimated="true" exposition label on the quantile lines."""

    def test_is_estimated_flips_at_cap(self):
        from grove_tpu.observability.metrics import Histogram

        h = Histogram("h", max_observations=64)
        for v in range(64):
            h.observe(float(v), k="a")
        assert h.is_estimated(k="a") is False, "at the cap: still exact"
        h.observe(64.0, k="a")
        assert h.is_estimated(k="a") is True
        assert h.is_estimated(k="missing") is False

    def test_estimated_label_rendered_past_cap_only(self):
        from grove_tpu.observability.metrics import MetricsRegistry

        r = MetricsRegistry()
        h = r.histogram("h", "help")
        h.max_observations = 8
        for v in range(8):
            h.observe(float(v), tier="exact")
        for v in range(20):
            h.observe(float(v), tier="est")
        text = r.render()
        assert 'h{estimated="true",quantile="0.99",tier="est"}' in text
        assert 'estimated="true",quantile="0.50",tier="est"' in text
        # the exact series carries NO estimated label
        assert 'tier="exact"' in text
        for line in text.splitlines():
            if 'tier="exact"' in line:
                assert "estimated" not in line
        # _sum/_count lines never carry it (they stay exact throughout)
        for line in text.splitlines():
            if line.startswith(("h_sum", "h_count")):
                assert "estimated" not in line

    def test_estimated_label_escapes_with_user_labels(self):
        from grove_tpu.observability.metrics import MetricsRegistry

        r = MetricsRegistry()
        h = r.histogram("h", "help")
        h.max_observations = 4
        for v in range(9):
            h.observe(float(v), tier='we"ird')
        text = r.render()
        # one formatting path: estimated + quantile + escaped user label
        assert ('h{estimated="true",quantile="0.50",tier="we\\"ird"}'
                in text)

    def test_reservoir_percentile_within_band_of_exact(self):
        """Seeded stream at 20x the cap: the reservoir estimate must
        land within a pinned band of the exact percentile (the
        deterministic LCG makes the band assertable, not flaky)."""
        from grove_tpu.observability.metrics import Histogram

        cap = 256
        n = 20 * cap
        h = Histogram("h", max_observations=cap)
        # seeded LCG stream (values in [0, 1000))
        x = 12345
        exact = []
        for _ in range(n):
            x = (x * 6364136223846793005 + 1442695040888963407) % (1 << 64)
            v = (x >> 33) % 1000
            exact.append(float(v))
            h.observe(float(v))
        assert h.is_estimated() is True
        exact.sort()

        def exact_pct(q):
            idx = min(n - 1, max(0, round(q / 100 * (n - 1))))
            return exact[idx]

        # pinned accuracy bands on the value scale (range 0..999): a
        # 256-sample uniform reservoir holds percentiles well inside
        # +/-10% of range for the mid quantiles, +/-5% at the tail
        assert abs(h.percentile(50) - exact_pct(50)) <= 100.0
        assert abs(h.percentile(90) - exact_pct(90)) <= 100.0
        assert abs(h.percentile(99) - exact_pct(99)) <= 50.0
        # count_over scales the retained count by true/retained and
        # must land within the same kind of band
        true_over = sum(1 for v in exact if v > 500.0)
        est_over = h.count_over(500.0)
        assert abs(est_over - true_over) <= 0.15 * n
