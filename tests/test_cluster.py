"""Store/kubelet/cluster tests (the apiserver-equivalent machinery)."""

import pytest

from grove_tpu.api import constants
from grove_tpu.api.meta import ObjectMeta, OwnerReference
from grove_tpu.api.types import (
    Container,
    Pod,
    PodClique,
    PodCliqueSet,
    PodCliqueSetSpec,
    PodCliqueSetTemplateSpec,
    PodCliqueTemplateSpec,
    PodCliqueSpec,
    PodPhase,
    PodSpec,
)
from grove_tpu.cluster import Cluster, make_nodes
from grove_tpu.cluster.store import AlreadyExists, NotFound


def simple_pcs(name="web", replicas=1):
    return PodCliqueSet(
        metadata=ObjectMeta(name=name),
        spec=PodCliqueSetSpec(
            replicas=replicas,
            template=PodCliqueSetTemplateSpec(
                cliques=[
                    PodCliqueTemplateSpec(
                        name="fe",
                        spec=PodCliqueSpec(
                            replicas=2,
                            pod_spec=PodSpec(
                                containers=[
                                    Container(name="c", resources={"cpu": 1.0})
                                ]
                            ),
                        ),
                    )
                ]
            ),
        ),
    )


def make_pod(name, node="", gates=(), wait_for="", pclq=""):
    labels = {constants.LABEL_PODCLIQUE: pclq} if pclq else {}
    ann = {constants.ANNOTATION_WAIT_FOR: wait_for} if wait_for else {}
    pod = Pod(
        metadata=ObjectMeta(name=name, labels=labels, annotations=ann),
        spec=PodSpec(
            containers=[Container(name="c", resources={"cpu": 1.0})],
            scheduling_gates=list(gates),
        ),
    )
    pod.node_name = node
    return pod


class TestStore:
    def test_create_get_versioning(self):
        c = Cluster(nodes=make_nodes(4))
        pcs = c.store.create(simple_pcs())
        assert pcs.metadata.uid and pcs.metadata.generation == 1
        # admission ran: defaults applied
        assert pcs.spec.template.termination_delay == 4 * 3600
        with pytest.raises(AlreadyExists):
            c.store.create(simple_pcs())

    def test_admission_rejects_invalid(self):
        from grove_tpu.api import ValidationError

        c = Cluster()
        bad = simple_pcs()
        bad.spec.template.cliques = []
        with pytest.raises(ValidationError):
            c.store.create(bad)
        assert c.store.get("PodCliqueSet", "default", "web") is None

    def test_generation_bumps_only_on_spec_change(self):
        c = Cluster()
        pcs = c.store.create(simple_pcs())
        pcs.metadata.labels["x"] = "y"
        pcs = c.store.update(pcs)
        assert pcs.metadata.generation == 1
        pcs.spec.replicas = 3
        pcs = c.store.update(pcs)
        assert pcs.metadata.generation == 2
        # status write never bumps generation
        pcs.status.replicas = 3
        c.store.update_status(pcs)
        pcs = c.store.get("PodCliqueSet", "default", "web")
        assert pcs.metadata.generation == 2
        assert pcs.status.replicas == 3

    def test_finalizer_gated_delete(self):
        c = Cluster()
        c.store.create(simple_pcs())
        c.store.add_finalizer("PodCliqueSet", "default", "web",
                              constants.FINALIZER_PCS)
        c.store.delete("PodCliqueSet", "default", "web")
        obj = c.store.get("PodCliqueSet", "default", "web")
        assert obj is not None and obj.metadata.deletion_timestamp is not None
        c.store.remove_finalizer("PodCliqueSet", "default", "web",
                                 constants.FINALIZER_PCS)
        assert c.store.get("PodCliqueSet", "default", "web") is None
        types = [e.type for e in c.store.events_since(0)
                 if e.kind == "PodCliqueSet"]
        assert types[-1] == "Deleted"

    def test_orphan_collection(self):
        c = Cluster()
        owner = c.store.create(simple_pcs())
        pod = make_pod("p1")
        pod.metadata.owner_references = [
            OwnerReference(kind="PodCliqueSet", name="web",
                           uid=owner.metadata.uid)
        ]
        c.store.create(pod)
        assert c.store.collect_orphans() == 0
        c.store.delete("PodCliqueSet", "default", "web")
        assert c.store.collect_orphans() == 1
        assert c.store.get(Pod.KIND, "default", "p1") is None

    def test_events_since(self):
        c = Cluster()
        seq0 = c.store.last_seq
        c.store.create(simple_pcs())
        evs = c.store.events_since(seq0)
        assert [e.type for e in evs] == ["Added"]
        assert c.store.events_since(c.store.last_seq) == []

    def test_not_found(self):
        c = Cluster()
        with pytest.raises(NotFound):
            c.store.delete("Pod", "default", "nope")


class TestKubelet:
    def test_gated_pod_stays_pending(self):
        c = Cluster(nodes=make_nodes(2))
        c.store.create(make_pod("p", node="node-0",
                                gates=[constants.PODGANG_PENDING_CREATION_GATE]))
        c.kubelet.run_to_quiesce()
        assert c.store.get(Pod.KIND, "default", "p").status.phase == PodPhase.PENDING

    def test_bound_pod_runs_and_readies(self):
        c = Cluster(nodes=make_nodes(2))
        c.store.create(make_pod("p", node="node-0"))
        c.kubelet.run_to_quiesce()
        pod = c.store.get(Pod.KIND, "default", "p")
        assert pod.status.phase == PodPhase.RUNNING
        assert pod.status.ready and pod.status.ever_started

    def test_startup_barrier(self):
        c = Cluster(nodes=make_nodes(2))
        c.store.create(make_pod("leader-0", node="node-0", pclq="leader"))
        c.store.create(make_pod("worker-0", node="node-1", pclq="worker",
                                wait_for="leader:1"))
        # worker cannot ready before leader
        c.kubelet.tick()
        worker = c.store.get(Pod.KIND, "default", "worker-0")
        assert not worker.status.ready
        c.kubelet.run_to_quiesce()
        leader = c.store.get(Pod.KIND, "default", "leader-0")
        worker = c.store.get(Pod.KIND, "default", "worker-0")
        assert leader.status.ready and worker.status.ready

    def test_malformed_wait_for_is_unsatisfiable_not_fatal(self):
        # a malformed minAvailable used to raise out of parse_wait_for and
        # kill the whole kubelet tick; it must instead hold ONLY that
        # pod's barrier, warn once, and self-heal on correction
        from grove_tpu.cluster.kubelet import parse_wait_for
        from grove_tpu.observability.events import (
            REASON_INVALID_STARTUP_BARRIER,
        )

        with pytest.raises(ValueError):
            parse_wait_for("leader:not-a-number")
        with pytest.raises(ValueError):
            parse_wait_for("no-colon-at-all")

        c = Cluster(nodes=make_nodes(2))
        c.store.create(make_pod("ok", node="node-0", pclq="leader"))
        c.store.create(make_pod("bad", node="node-1", pclq="worker",
                                wait_for="leader:not-a-number"))
        c.kubelet.run_to_quiesce()  # must not raise
        assert c.store.get(Pod.KIND, "default", "ok").status.ready
        # the pod starts (containers run) but its barrier never opens
        bad = c.store.get(Pod.KIND, "default", "bad")
        assert bad.status.phase == PodPhase.RUNNING
        assert not bad.status.ready
        events = [e for e in c.store.list("Event")
                  if e.reason == REASON_INVALID_STARTUP_BARRIER]
        assert len(events) == 1 and events[0].type == "Warning"
        assert "leader:not-a-number" in events[0].message
        count0 = events[0].count
        c.kubelet.tick()
        c.kubelet.tick()
        events = [e for e in c.store.list("Event")
                  if e.reason == REASON_INVALID_STARTUP_BARRIER]
        assert events[0].count == count0, "warned once, not per tick"
        # corrected annotation self-heals without kubelet intervention
        pod = c.store.get(Pod.KIND, "default", "bad")
        pod.metadata.annotations[constants.ANNOTATION_WAIT_FOR] = "leader:1"
        c.store.update(pod)
        c.kubelet.run_to_quiesce()
        assert c.store.get(Pod.KIND, "default", "bad").status.ready

    def test_crash_recover_and_evict(self):
        c = Cluster(nodes=make_nodes(1))
        c.store.create(make_pod("p", node="node-0"))
        c.kubelet.run_to_quiesce()
        c.kubelet.crash_pod("default", "p")
        pod = c.store.get(Pod.KIND, "default", "p")
        assert pod.status.phase == PodPhase.RUNNING
        assert not pod.status.ready and pod.status.restart_count == 1
        c.kubelet.run_to_quiesce()  # stays crashed
        assert not c.store.get(Pod.KIND, "default", "p").status.ready
        c.kubelet.recover_pod("default", "p")
        c.kubelet.run_to_quiesce()
        assert c.store.get(Pod.KIND, "default", "p").status.ready
        c.kubelet.evict_pod("default", "p")
        assert c.store.get(Pod.KIND, "default", "p").status.phase == PodPhase.FAILED


class TestClusterFacade:
    def test_snapshot_with_usage_and_cordon(self):
        c = Cluster(nodes=make_nodes(8, racks_per_block=2, hosts_per_rack=2))
        c.store.create(make_pod("p", node="node-0"))
        c.kubelet.run_to_quiesce()
        c.cordon("node-1")
        snap = c.topology_snapshot()
        assert snap.num_nodes == 8
        ci = snap.resource_names.index("cpu")
        assert snap.free[0, ci] == snap.capacity[0, ci] - 1.0
        assert not snap.schedulable[1]
        # levels inferred from inventory labels: block, rack, host
        assert snap.num_levels == 3

    def test_pod_demand_fn(self):
        c = Cluster(nodes=make_nodes(1))
        c.store.create(make_pod("p"))
        fn = c.pod_demand_fn(["cpu", "memory", "tpu"])
        assert list(fn("default", "p")) == [1.0, 0.0, 0.0]
        assert fn("default", "missing") is None


class TestLiveTopology:
    def test_topology_snapshot_follows_store_update(self):
        from grove_tpu.api.types import ClusterTopology, TopologyLevel, sort_topology_levels

        nodes = make_nodes(4, racks_per_block=2, hosts_per_rack=2)
        for n in nodes:
            n.metadata.labels["t/zone"] = "z0"
        c = Cluster(nodes=nodes)
        assert "t/zone" not in c.topology_snapshot().level_keys
        ct = c.store.get(
            ClusterTopology.KIND,
            c.topology.metadata.namespace,
            c.topology.metadata.name,
        )
        ct.spec.levels = sort_topology_levels(
            ct.spec.levels + [TopologyLevel(domain="zone", key="t/zone")]
        )
        c.store.update(ct)
        # the snapshot must track the STORED topology, not the bootstrap copy
        snap = c.topology_snapshot()
        assert "t/zone" in snap.level_keys
        zl = snap.level_index("t/zone")
        assert snap.domains_at(zl) == 1  # all four nodes share zone z0


class TestManagerErrorBound:
    def test_permanently_failing_reconciler_bounded_errors(self):
        """A reconciler that fails forever must not grow manager.errors
        without bound (advisor r2); last-N-per-key survive compaction."""
        from grove_tpu.cluster.store import ObjectStore
        from grove_tpu.controller.runtime import ControllerManager, Request

        class Broken:
            name = "broken"

            def map_event(self, event):
                return []

            def reconcile(self, request):
                raise RuntimeError("boom")

        mgr = ControllerManager(ObjectStore())
        mgr.register(Broken())
        for _ in range(500):
            mgr._enqueue("broken", Request("default", "x"))
            mgr.run_once()
        # bounded: at most 2x the per-key allowance after compaction cycles
        assert len(mgr.errors) <= 2 * mgr.max_errors_per_key + 64
        assert all(c == "broken" for c, _, _ in mgr.errors)


class TestSlowStartBatching:
    """Slow-start create/delete pacing (utils/concurrent.go:72-105): a
    failing write path sees one probe, not the whole diff."""

    def test_batches_grow_exponentially(self):
        from grove_tpu.controller.concurrency import run_with_slow_start

        calls = []
        tasks = [(f"t{i}", lambda i=i: calls.append(i)) for i in range(11)]
        result = run_with_slow_start(tasks)
        assert calls == list(range(11))
        assert len(result.succeeded) == 11
        assert not result.has_errors and not result.skipped

    def test_halts_after_failing_batch_and_skips_rest(self):
        from grove_tpu.controller.concurrency import run_with_slow_start

        calls = []

        def ok(i):
            calls.append(i)

        def boom(i):
            calls.append(i)
            raise RuntimeError("apiserver down")

        # batches: [0], [1,2], [3,4,5,6] — task 4 fails; batch finishes
        # (5, 6 still attempted), tasks 7..10 are skipped
        tasks = [(f"t{i}", (lambda i=i: boom(i)) if i == 4 else
                  (lambda i=i: ok(i))) for i in range(11)]
        result = run_with_slow_start(tasks)
        assert calls == [0, 1, 2, 3, 4, 5, 6]
        assert [n for n, _ in result.errors] == ["t4"]
        assert result.skipped == ["t7", "t8", "t9", "t10"]

    def test_failing_pod_admission_sees_one_probe_create(self):
        from grove_tpu.cluster.store import Admission
        from grove_tpu.controller import Harness

        h = Harness(nodes=make_nodes(8))
        attempts = []

        from grove_tpu.api.validation import ValidationError

        def reject(pod):
            attempts.append(pod.metadata.name)
            raise ValidationError(["pod quota exhausted"])

        h.store.register_admission("Pod", Admission(validate=reject))
        from test_e2e_basic import clique as e2e_clique, simple_pcs as e2e_pcs

        h.apply(e2e_pcs(cliques=[e2e_clique("w", replicas=8)]))
        h.settle()
        # slow start probes with ONE create per reconcile, never the
        # whole 8-pod diff (a second reconcile may re-probe once)
        assert set(attempts) == {"simple1-0-w-0"}, attempts
        assert len(attempts) <= 3
        assert len(h.store.list(Pod.KIND)) == 0
        pclq = h.store.get(PodClique.KIND, "default", "simple1-0-w")
        assert pclq.status.last_errors
        assert "skipped by slow start" in pclq.status.last_errors[0].description
        # quota returns -> retry interval recreates everything
        h.store.register_admission("Pod", Admission())
        h.advance(constants.COMPONENT_SYNC_RETRY_INTERVAL_SECONDS + 0.1)
        assert len(h.store.list(Pod.KIND)) == 8
        assert all(p.status.ready for p in h.store.list(Pod.KIND))


class TestEventCompaction:
    """Bounded watch window: long simulations compact drained events; a
    consumer resuming below the horizon gets an explicit error (the
    apiserver's 410 Gone analog), never a silent gap."""

    def test_compaction_and_resume_contract(self):
        from grove_tpu.cluster.store import StoreError

        c = Cluster()
        c.store.create(simple_pcs())
        mid = c.store.last_seq
        c.store.create(simple_pcs(name="web2"))
        last = c.store.last_seq
        dropped = c.store.compact_events(mid)
        assert dropped > 0
        assert c.store.last_seq == last  # horizon never rewinds last_seq
        # resume above the horizon works; below it is an explicit error
        assert all(e.seq > mid for e in c.store.events_since(mid))
        with pytest.raises(StoreError):
            c.store.events_since(0)

    def test_manager_compacts_only_drained_events(self):
        from test_e2e_basic import clique, simple_pcs as e2e_pcs

        from grove_tpu.controller import Harness

        h = Harness(nodes=make_nodes(4))
        h.apply(e2e_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        assert h.manager.compact_processed_events() > 0
        # the control plane keeps working across the compaction
        h.apply(e2e_pcs(name="second", cliques=[clique("w", replicas=2)]))
        h.settle()
        assert all(p.node_name and p.status.ready
                   for p in h.store.list(Pod.KIND))
        # compacting everything after settle leaves an empty log: the
        # second settle produced fresh events, so the compact drops them
        assert h.manager.compact_processed_events() > 0
        assert len(h.store._events) == 0


def test_incremental_usage_matches_full_scan():
    """usage() is maintained incrementally off the watch log; after
    arbitrary churn (binds, failures, deletes, compaction-forced relist)
    it must match a from-scratch accounting scan."""
    from grove_tpu.api.types import Pod, PodPhase
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness
    from test_e2e_basic import clique, simple_pcs

    def scratch(cluster):
        out = {}
        for pod in cluster.store.scan(Pod.KIND):
            if not cluster._counted(pod):
                continue
            per_node = out.setdefault(pod.node_name, {})
            for res, amount in pod.spec.total_requests().items():
                per_node[res] = per_node.get(res, 0.0) + amount
        return out

    def assert_match(cluster):
        inc, full = cluster.usage(), scratch(cluster)
        nodes = set(inc) | set(full)
        for n in nodes:
            a, b = inc.get(n, {}), full.get(n, {})
            for res in set(a) | set(b):
                assert a.get(res, 0.0) == pytest.approx(
                    b.get(res, 0.0), abs=1e-9
                ), (n, res)

    h = Harness(nodes=make_nodes(8))
    h.apply(simple_pcs(cliques=[clique("w", replicas=4)]))
    h.settle()
    assert_match(h.cluster)
    assert h.cluster.usage(), "bound pods must be accounted"
    # failure churn: eviction releases capacity, replacement re-binds
    h.kubelet.evict_pod("default", "simple1-0-w-0")
    h.settle()
    assert_match(h.cluster)
    # direct delete
    h.store.delete(Pod.KIND, "default", "simple1-0-w-1")
    h.settle()
    assert_match(h.cluster)
    # compaction pushes the cursor past the horizon: rebuild path
    h.manager.compact_processed_events()
    h.store.compact_events(h.store.last_seq)
    assert_match(h.cluster)
    # and the cache keeps tracking after the rebuild
    h.apply(simple_pcs(name="second", cliques=[clique("w", replicas=2)]))
    h.settle()
    assert_match(h.cluster)
