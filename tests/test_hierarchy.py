"""Hierarchical two-level solve (solver/hierarchy.py + engine path).

The load-bearing invariant is ADMISSIBILITY: the coarse domain-level
pass works on aggregates, which may only OVER-admit — it must never
prune a domain the exact (flat) solve would place into. The property
sweep below brute-forces that against the exact placement primitive
itself. Everything else rides on it: score-equality vs the flat engine,
shard-local incrementality, sharded parity, dispatch adoption, and the
forced-flat fallback triggers.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

from grove_tpu.api.config import ValidationError, load_operator_config
from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import Node, TopologyLevel
from grove_tpu.observability.explain import UnsatCode, unsat_code
from grove_tpu.solver import PlacementEngine, SolverGang
from grove_tpu.solver.fit import place_gang_in_domain
from grove_tpu.solver.hierarchy import (
    coarse_admissible,
    coarse_assign,
    shift_level,
    subset_snapshot,
)
from grove_tpu.topology import default_cluster_topology, encode_topology


def make_cluster(num_nodes: int, cpu: float = 32.0):
    """3-tier block/rack/host topology (16 hosts/rack, 4 racks/block)."""
    nodes = []
    for i in range(num_nodes):
        b, rem = divmod(i, 64)
        r = rem // 16
        nodes.append(
            Node(
                metadata=ObjectMeta(
                    name=f"n{i}",
                    labels={"t/block": f"b{b}", "t/rack": f"b{b}r{r}"},
                ),
                allocatable={"cpu": cpu, "memory": 128.0, "tpu": 8.0},
            )
        )
    ct = default_cluster_topology(
        [
            TopologyLevel(domain="block", key="t/block"),
            TopologyLevel(domain="rack", key="t/rack"),
        ]
    )
    return encode_topology(ct, nodes)


def make_gang(name: str, pods: int = 4, cpu: float = 4.0,
              required: int = 0, preferred: int = 1,
              priority: float = 0.0, pod_elig=None) -> SolverGang:
    demand = np.tile(
        np.array([cpu, 8.0, 1.0], np.float32), (pods, 1)
    )
    return SolverGang(
        name=name,
        namespace="t",
        demand=demand,
        pod_names=[f"{name}-p{j}" for j in range(pods)],
        group_ids=np.zeros(pods, np.int32),
        group_names=["w"],
        group_required_level=np.array([-1], np.int32),
        group_preferred_level=np.array([-1], np.int32),
        required_level=required,
        preferred_level=preferred,
        priority=priority,
        pod_elig=pod_elig,
    )


def seeded_problem(seed: int, num_nodes: int = 192, num_gangs: int = 24):
    """A seeded partially-loaded cluster + mixed backlog: varied
    demands, priorities, pack levels, a few eligibility-masked pods —
    the admissibility sweep's input distribution."""
    rng = np.random.default_rng(seed)
    snap = make_cluster(num_nodes)
    free = snap.free.copy()
    # pre-commit seeded load: some racks near-full, some untouched
    rows = rng.choice(num_nodes, size=num_nodes // 2, replace=False)
    frac = rng.uniform(0.1, 1.0, size=(rows.size, 1)).astype(np.float32)
    free[rows] = (free[rows] * frac).astype(np.float32)
    # and one block drained near-empty so the aggregate-capacity cut
    # genuinely fires (a lightly loaded block is never cut — over-
    # admission is the norm, the sweep needs real pruning to exercise)
    drained = int(rng.integers(0, int(snap.num_domains[0])))
    free[snap.domain_ids[0] == drained] *= np.float32(0.01)
    gangs = []
    for i in range(num_gangs):
        pods = int(rng.integers(2, 8))
        cpu = float(rng.choice([2.0, 4.0, 8.0, 16.0]))
        required = int(rng.choice([0, 0, 1]))
        pod_elig = None
        if rng.random() < 0.25:
            # one shared seeded mask over half the pods
            mask = np.zeros(num_nodes, dtype=bool)
            mask[rng.choice(num_nodes, size=num_nodes // 3,
                            replace=False)] = True
            pod_elig = [mask if p % 2 == 0 else None
                        for p in range(pods)]
        gangs.append(
            make_gang(
                f"g{seed:02d}-{i:03d}", pods=pods, cpu=cpu,
                required=required,
                preferred=int(rng.choice([1, 2, -1])),
                priority=float(rng.integers(0, 3)),
                pod_elig=pod_elig,
            )
        )
    return snap, free, gangs


class TestAdmissibility:
    """Satellite: the domain-level aggregate must NEVER prune a domain
    the flat solve could place into (over-admission allowed,
    under-admission is the correctness bug)."""

    @pytest.mark.parametrize("seed", range(12))
    def test_never_prunes_a_placeable_domain(self, seed):
        snap, free, gangs = seeded_problem(seed)
        level = 0
        fm = np.where(
            snap.schedulable[:, None], free, 0.0
        ).astype(np.float32)
        order = sorted(gangs, key=lambda g: g.name)
        admissible, _dom_free, stats, _cls = coarse_admissible(
            order, snap, fm, level
        )
        ids = snap.domain_ids[level]
        nd = int(snap.num_domains[level])
        sched = np.flatnonzero(snap.schedulable)
        for i, g in enumerate(order):
            for d in range(nd):
                if admissible[i, d]:
                    continue
                # pruned: the EXACT primitive must also fail here,
                # against the same pre-solve free content
                node_idx = sched[ids[sched] == d]
                trial = free.copy()
                assign = place_gang_in_domain(
                    g, snap, trial, node_idx, level
                )
                assert assign is None, (
                    f"seed {seed}: pruner cut domain {d} for {g.name} "
                    "but exact placement succeeds there (under-"
                    "admission)"
                )
        # the sweep must actually exercise pruning, not vacuously pass
        assert stats["pruned"] > 0

    def test_assignment_covers_admissible_only(self):
        snap, free, gangs = seeded_problem(3)
        fm = np.where(
            snap.schedulable[:, None], free, 0.0
        ).astype(np.float32)
        order = sorted(gangs, key=lambda g: g.name)
        admissible, dom_free, _, cls = coarse_admissible(order, snap, fm, 0)
        cap_scale = np.maximum(snap.capacity.max(axis=0), 1e-9)
        choices = coarse_assign(order, admissible, dom_free, cap_scale,
                                class_ids=cls)
        for i, alts in enumerate(choices):
            assert len(alts) == len(set(alts))
            for d in alts:
                assert admissible[i, d]


class TestScoreEquality:
    """The pinned hierarchical-vs-flat contract: identical placed set,
    identical per-gang placement scores, identical unplaced reason
    codes. Bitwise node assignments may differ (cross-domain ties)."""

    @pytest.mark.parametrize("seed", range(6))
    def test_seeded_score_equality(self, seed):
        snap, free, gangs = seeded_problem(seed, num_gangs=16)
        flat = PlacementEngine(snap)
        hier = PlacementEngine(snap, hierarchical=True)
        free_f, free_h = free.copy(), free.copy()
        rf = flat.solve(gangs, free=free_f)
        rh = hier.solve(gangs, free=free_h)
        assert rh.stats.get("hierarchical") == 1.0
        assert sorted(rf.placed) == sorted(rh.placed)
        for name, pf in rf.placed.items():
            assert rh.placed[name].placement_score == pf.placement_score
        for name, reason in rf.unplaced.items():
            assert unsat_code(rh.unplaced[name]) == unsat_code(reason)
        np.testing.assert_allclose(
            free_f.sum(axis=0), free_h.sum(axis=0), rtol=1e-5, atol=1e-3
        )

    def test_unplaceable_gang_same_code(self):
        snap = make_cluster(128)
        gangs = [make_gang("ok", cpu=4.0),
                 make_gang("huge", cpu=64.0)]  # no 32-cpu node fits
        rf = PlacementEngine(snap).solve(gangs, free=snap.free.copy())
        rh = PlacementEngine(snap, hierarchical=True).solve(
            gangs, free=snap.free.copy()
        )
        assert "huge" in rf.unplaced and "huge" in rh.unplaced
        assert unsat_code(rh.unplaced["huge"]) == UnsatCode.CAPACITY
        assert unsat_code(rh.unplaced["huge"]) == unsat_code(
            rf.unplaced["huge"]
        )


class TestForcedFlatTriggers:
    def _solve(self, eng, gangs):
        return eng.solve(gangs, free=eng.snapshot.free.copy())

    def test_unconfined_gang_forces_flat(self):
        snap = make_cluster(128)
        eng = PlacementEngine(snap, hierarchical=True)
        confined = [make_gang("a"), make_gang("b")]
        assert self._solve(eng, confined).stats.get("hierarchical")
        mixed = [make_gang("a"), make_gang("root", required=-1)]
        res = self._solve(eng, mixed)
        assert "hierarchical" not in res.stats
        assert res.num_placed == 2

    def test_min_nodes_forces_flat(self):
        snap = make_cluster(128)
        eng = PlacementEngine(snap, hierarchical=True,
                              hier_min_nodes=1000)
        res = self._solve(eng, [make_gang("a")])
        assert "hierarchical" not in res.stats

    def test_single_domain_forces_flat(self):
        snap = make_cluster(48)  # one block
        assert int(snap.num_domains[0]) == 1
        eng = PlacementEngine(snap, hierarchical=True)
        res = self._solve(eng, [make_gang("a", required=0)])
        assert "hierarchical" not in res.stats

    def test_knob_off_is_flat(self):
        snap = make_cluster(128)
        res = PlacementEngine(snap).solve(
            [make_gang("a")], free=snap.free.copy()
        )
        assert "hierarchical" not in res.stats

    def test_prune_level_clamped_to_confinement(self):
        snap = make_cluster(128)
        # configured narrower (rack=1) than nothing; gangs require
        # block(0) -> clamp to 0 so no gang spans its coarse domain
        eng = PlacementEngine(snap, hierarchical=True,
                              hier_prune_level=1)
        res = self._solve(eng, [make_gang("a", required=0)])
        assert res.stats["hier_level"] == 0.0
        # rack-confined backlog may genuinely prune at rack
        eng2 = PlacementEngine(snap, hierarchical=True)
        res2 = self._solve(eng2, [make_gang("a", required=1)])
        assert res2.stats["hier_level"] == 1.0


class TestShardLocalIncrementality:
    def test_domain_reuse_and_dirty_tick(self):
        snap = make_cluster(256)
        gangs = [make_gang(f"g{i:02d}") for i in range(12)]
        eng = PlacementEngine(snap, hierarchical=True)
        r1 = eng.solve(gangs, free=snap.free.copy())
        assert r1.stats["hier_fine_solves"] >= 1
        # identical repeat: every domain rides the reuse memo
        r2 = eng.solve(gangs, free=snap.free.copy())
        assert r2.stats["hier_fine_solves"] == 0
        assert r2.stats["hier_domain_reuse"] >= 1
        # dirty tick: one replaced gang -> its domain re-solves
        # incrementally (O(1) dirty rows), others keep the memo
        dirty = list(gangs)
        dirty[2] = make_gang("fresh-0")
        r3 = eng.solve(dirty, free=snap.free.copy())
        assert r3.stats.get("incremental") == 1.0
        assert r3.stats["hier_sub_incremental"] == 1
        assert r3.stats["incremental_rows"] <= 2.0
        ds = eng.debug_summary()["device_state"]
        assert ds["dispatches"]["incremental"] >= 1

    def test_incremental_off_disables_memo(self):
        snap = make_cluster(256)
        gangs = [make_gang(f"g{i:02d}") for i in range(8)]
        eng = PlacementEngine(snap, hierarchical=True, incremental=False)
        eng.solve(gangs, free=snap.free.copy())
        r2 = eng.solve(gangs, free=snap.free.copy())
        assert r2.stats["hier_domain_reuse"] == 0
        assert r2.stats["hier_fine_solves"] >= 1

    def test_counter_mirroring(self):
        snap = make_cluster(256)
        from grove_tpu.observability import MetricsRegistry

        reg = MetricsRegistry()
        eng = PlacementEngine(snap, hierarchical=True, metrics=reg)
        gangs = [make_gang(f"g{i:02d}") for i in range(8)]
        eng.solve(gangs, free=snap.free.copy())
        dirty = list(gangs)
        dirty[0] = make_gang("fresh-0")
        eng.solve(dirty, free=snap.free.copy())
        counter = reg.counter("grove_solver_dispatches_total")
        assert counter.value(kind="fused") >= 1
        assert counter.value(kind="incremental") >= 1


class TestRebindAndInvalidate:
    def test_cordon_flip_rides_rebind(self):
        snap = make_cluster(128)
        gangs = [make_gang(f"g{i:02d}") for i in range(6)]
        eng = PlacementEngine(snap, hierarchical=True)
        flat = PlacementEngine(snap)
        eng.solve(gangs, free=snap.free.copy())
        sched = snap.schedulable.copy()
        sched[5] = False
        snap2 = dataclasses.replace(snap, schedulable=sched)
        assert eng.rebind(snap2) and flat.rebind(snap2)
        rh = eng.solve(gangs, free=snap.free.copy())
        rf = flat.solve(gangs, free=snap.free.copy())
        assert sorted(rh.placed) == sorted(rf.placed)
        for name, pf in rf.placed.items():
            assert rh.placed[name].placement_score == pf.placement_score
        for p in rh.placed.values():
            assert 5 not in p.node_indices.tolist()

    def test_invalidate_drops_hier_state(self):
        snap = make_cluster(128)
        eng = PlacementEngine(snap, hierarchical=True)
        eng.solve([make_gang("a")], free=snap.free.copy())
        assert eng._hier is not None
        eng.invalidate_device_state()
        assert eng._hier is None
        res = eng.solve([make_gang("a")], free=snap.free.copy())
        assert res.num_placed == 1


class TestShardedHierarchy:
    def test_sharded_bitwise_matches_single(self):
        from grove_tpu.parallel import (
            ShardedPlacementEngine,
            make_solver_mesh,
        )

        assert jax.device_count() == 8
        mesh = make_solver_mesh()
        snap = make_cluster(256)
        gangs = [make_gang(f"g{i:02d}") for i in range(16)]
        f1, f2 = snap.free.copy(), snap.free.copy()
        r1 = ShardedPlacementEngine(
            snap, mesh, hierarchical=True
        ).solve(gangs, free=f1)
        r2 = PlacementEngine(snap, hierarchical=True).solve(
            gangs, free=f2
        )
        assert sorted(r1.placed) == sorted(r2.placed)
        for name, p1 in r1.placed.items():
            assert np.array_equal(
                p1.node_indices, r2.placed[name].node_indices
            )
        assert np.array_equal(f1, f2)

    def test_sharded_incremental_runs_shard_locally(self):
        from grove_tpu.parallel import (
            ShardedPlacementEngine,
            make_solver_mesh,
        )

        mesh = make_solver_mesh()
        snap = make_cluster(256)
        gangs = [make_gang(f"g{i:02d}") for i in range(16)]
        eng = ShardedPlacementEngine(snap, mesh, hierarchical=True)
        # the flat sharded path keeps incremental forced off...
        assert eng.incremental is False
        eng.solve(gangs, free=snap.free.copy())
        dirty = list(gangs)
        dirty[1] = make_gang("fresh-0")
        res = eng.solve(dirty, free=snap.free.copy())
        # ...but the domain-sharded hierarchy runs it shard-locally
        assert res.stats.get("incremental") == 1.0
        assert (
            eng.debug_summary()["device_state"]["dispatches"][
                "incremental"
            ]
            >= 1
        )

    def test_sub_engines_round_robin_devices(self):
        from grove_tpu.parallel import (
            ShardedPlacementEngine,
            make_solver_mesh,
        )

        mesh = make_solver_mesh()
        snap = make_cluster(256)  # 4 blocks
        # spread demand so several blocks are actually solved
        gangs = [
            make_gang(f"g{i:02d}", pods=8, cpu=16.0) for i in range(24)
        ]
        eng = ShardedPlacementEngine(snap, mesh, hierarchical=True)
        eng.solve(gangs, free=snap.free.copy())
        devs = {
            str(s.engine._device)
            for s in eng._hier.shards.values()
            if s.engine is not None
        }
        assert len(eng._hier.shards) >= 2
        assert len(devs) == len(
            {
                s.dom % len(mesh.local_devices)
                for s in eng._hier.shards.values()
                if s.engine is not None
            }
        )


def spread_problem(num_small: int = 10, num_big: int = 10):
    """A cluster + backlog whose coarse assignment genuinely SPREADS
    across blocks — the wave-parallel driver's input shape. Two demand
    classes + half the blocks drained below the big class's per-pod fit
    (EVERY resource tightened: the best-fit slack is the max over
    resources, so a cpu-only drain leaves memory slack dominant and the
    tie-broken pick collapses back onto one block): small gangs
    best-fit the tight drained blocks, big gangs are fit-cut there and
    land in the loose ones — multi-domain waves by construction."""
    snap = make_cluster(512)  # 8 blocks
    ids = snap.domain_ids[0]
    free = snap.free.copy()
    free[ids < 4] = np.minimum(
        free[ids < 4], np.array([8.0, 24.0, 2.0], np.float32)
    )
    gangs = [make_gang(f"s{i:02d}", pods=4, cpu=4.0)
             for i in range(num_small)]
    gangs += [make_gang(f"b{i:02d}", pods=4, cpu=16.0)
              for i in range(num_big)]
    return snap, free, gangs


def assert_bitwise(rs, rw, free_s, free_w):
    """The wave contract: bit-equal placements, identical unplaced
    reasons, identical post-solve free — not merely score-equal."""
    assert sorted(rs.placed) == sorted(rw.placed)
    for name, ps in rs.placed.items():
        pw = rw.placed[name]
        assert pw.pod_to_node == ps.pod_to_node, name
        assert np.array_equal(pw.node_indices, ps.node_indices), name
        assert pw.placement_score == ps.placement_score, name
    assert rs.unplaced == rw.unplaced
    assert np.array_equal(free_s, free_w)


class TestWaveParallel:
    """Wave-parallel fine solves (engine.py _run_wave): dispatch-all /
    collect-in-order across domains must be BIT-equal to the serial
    workers=0 path — domains partition node rows and collection commits
    in deterministic domain order, so only the overlap changes, never
    the result."""

    def _pair(self, snap, workers=4):
        serial = PlacementEngine(snap, hierarchical=True,
                                 hier_parallel_workers=0)
        wave = PlacementEngine(snap, hierarchical=True,
                               hier_parallel_workers=workers)
        return serial, wave

    def test_bit_equality_multi_domain_wave(self):
        snap, free, gangs = spread_problem()
        serial, wave = self._pair(snap)
        fs, fw = free.copy(), free.copy()
        rs = serial.solve(gangs, free=fs)
        rw = wave.solve(gangs, free=fw)
        # the wave driver must actually have run a parallel wave, else
        # the equality below is vacuous
        assert rw.stats["hier_wave_width"] >= 2
        assert rw.stats["hier_wave_workers"] >= 1
        assert rs.stats["hier_wave_workers"] == 0
        assert_bitwise(rs, rw, fs, fw)

    def test_bit_equality_under_churn(self):
        snap, free, gangs = spread_problem()
        serial, wave = self._pair(snap)
        rng = np.random.default_rng(5)
        n = snap.num_nodes
        widths = 0
        for rnd in range(4):
            rows = rng.choice(n, size=24, replace=False)
            scale = rng.uniform(0.4, 1.1, size=(rows.size, 1)).astype(
                np.float32
            )
            free[rows] = np.minimum(
                snap.capacity[rows], free[rows] * scale
            ).astype(np.float32)
            serial.note_free_rows(rows.tolist())
            wave.note_free_rows(rows.tolist())
            subset = [gangs[i] for i in sorted(
                rng.choice(len(gangs), size=12, replace=False)
            )]
            fs, fw = free.copy(), free.copy()
            rs = serial.solve(subset, free=fs)
            rw = wave.solve(subset, free=fw)
            widths = max(widths, int(rw.stats["hier_wave_width"]))
            assert_bitwise(rs, rw, fs, fw)
            free = fs
        assert widths >= 2

    def test_domain_reuse_and_dirty_tick_parity(self):
        snap, free, gangs = spread_problem()
        serial, wave = self._pair(snap)
        serial.solve(gangs, free=free.copy())
        wave.solve(gangs, free=free.copy())
        # identical repeat: both sides replay the domain-reuse memo
        fs, fw = free.copy(), free.copy()
        rs = serial.solve(gangs, free=fs)
        rw = wave.solve(gangs, free=fw)
        assert rw.stats["hier_domain_reuse"] >= 1
        assert (rw.stats["hier_domain_reuse"]
                == rs.stats["hier_domain_reuse"])
        assert rw.stats["hier_fine_solves"] == rs.stats["hier_fine_solves"]
        assert_bitwise(rs, rw, fs, fw)
        # dirty tick: one replaced gang re-solves its domain (the
        # shard-local incremental tier), clean domains keep the memo
        dirty = list(gangs)
        dirty[3] = make_gang("fresh-0", pods=4, cpu=4.0)
        fs, fw = free.copy(), free.copy()
        rs = serial.solve(dirty, free=fs)
        rw = wave.solve(dirty, free=fw)
        assert rw.stats.get("incremental") == rs.stats.get("incremental")
        assert rw.stats["hier_domain_reuse"] >= 1
        assert_bitwise(rs, rw, fs, fw)

    def test_fail_recover_rebind_mid_stream(self):
        """A chaos-shaped node fail/recover between solves: the
        schedulable flip rides rebind() into every shard, and the wave
        path must stay bitwise-aligned with the serial path through
        BOTH flips (stale shard state after a rebind would diverge)."""
        import dataclasses as dc

        snap, free, gangs = spread_problem()
        serial, wave = self._pair(snap)
        fs, fw = free.copy(), free.copy()
        assert_bitwise(serial.solve(gangs, free=fs),
                       wave.solve(gangs, free=fw), fs, fw)
        failed = 7
        for up in (False, True):  # fail_node, then recover_node
            sched = serial.snapshot.schedulable.copy()
            sched[failed] = up
            snap2 = dc.replace(serial.snapshot, schedulable=sched)
            assert serial.rebind(snap2) and wave.rebind(snap2)
            fs, fw = free.copy(), free.copy()
            rs = serial.solve(gangs, free=fs)
            rw = wave.solve(gangs, free=fw)
            assert_bitwise(rs, rw, fs, fw)
            if not up:
                for p in rw.placed.values():
                    assert failed not in p.node_indices.tolist()

    def test_workers_zero_is_serial(self):
        snap, free, gangs = spread_problem()
        eng = PlacementEngine(snap, hierarchical=True,
                              hier_parallel_workers=0)
        res = eng.solve(gangs, free=free.copy())
        assert res.stats["hier_wave_workers"] == 0.0
        assert res.stats["hier_waves"] >= 1
        assert eng._hier_pool is None  # the serial path builds no pool
        assert eng.debug_summary()["hierarchical"]["wave_workers"] == 0

    def test_auto_workers_resolution(self):
        snap = make_cluster(128)
        eng = PlacementEngine(snap, hierarchical=True)
        assert eng._wave_workers() >= 1
        assert (eng.debug_summary()["hierarchical"]["wave_workers"]
                == eng._wave_workers())

    def test_wave_stats_and_metrics(self):
        from grove_tpu.observability import MetricsRegistry

        snap, free, gangs = spread_problem()
        reg = MetricsRegistry()
        eng = PlacementEngine(snap, hierarchical=True,
                              hier_parallel_workers=2, metrics=reg)
        res = eng.solve(gangs, free=free.copy())
        assert res.stats["hier_waves"] >= 1
        assert res.stats["hier_wave_width"] >= 2
        assert res.stats["hier_fine_seconds"] > 0.0
        assert "hier_net_seconds" in res.stats
        walls = [res.stats["hier_fine_wall_min"],
                 res.stats["hier_fine_wall_med"],
                 res.stats["hier_fine_wall_max"]]
        assert walls == sorted(walls)
        h = reg.histogram("grove_solver_hier_wave_seconds")
        assert h.count == res.stats["hier_waves"]
        assert reg.gauge("grove_solver_hier_wave_width").value() >= 1

    def test_sharded_wave_bitwise_matches_serial(self):
        from grove_tpu.parallel import (
            ShardedPlacementEngine,
            make_solver_mesh,
        )

        mesh = make_solver_mesh()
        snap, free, gangs = spread_problem()
        serial = ShardedPlacementEngine(snap, mesh, hierarchical=True,
                                        hier_parallel_workers=0)
        wave = ShardedPlacementEngine(snap, mesh, hierarchical=True)
        # mesh auto resolution covers the local device fan-out
        assert wave._wave_workers() >= min(
            16, len(mesh.local_devices)
        )
        fs, fw = free.copy(), free.copy()
        rs = serial.solve(gangs, free=fs)
        rw = wave.solve(gangs, free=fw)
        assert rw.stats["hier_wave_width"] >= 2
        if len(mesh.local_devices) > 1:
            assert rw.stats["hier_wave_devices"] >= 2
        assert_bitwise(rs, rw, fs, fw)


class TestDispatchAdoption:
    def test_dispatch_carries_level_and_adopts(self):
        snap = make_cluster(128)
        gangs = [make_gang(f"g{i:02d}") for i in range(8)]
        eng = PlacementEngine(snap, hierarchical=True)
        h = eng.dispatch(gangs, free=snap.free.copy())
        assert h.path == "hierarchical"
        assert h.level == 0
        free_c = snap.free.copy()
        res = eng.solve(gangs, free=free_c, dispatch=h)
        assert res.stats.get("dispatch_overlap") == 1.0
        assert res.stats.get("hierarchical") == 1.0
        free_f = snap.free.copy()
        fresh = eng.solve(gangs, free=free_f)
        assert sorted(res.placed) == sorted(fresh.placed)
        assert np.array_equal(free_c, free_f)

    def test_stale_dispatch_refused(self):
        snap = make_cluster(128)
        gangs = [make_gang(f"g{i:02d}") for i in range(8)]
        eng = PlacementEngine(snap, hierarchical=True)
        h = eng.dispatch(gangs, free=snap.free.copy())
        stale = snap.free.copy()
        stale[3] *= 0.5
        eng.note_free_rows((3,))
        res = eng.solve(gangs, free=stale, dispatch=h)
        assert not res.stats.get("dispatch_overlap")
        assert res.num_placed == len(gangs)

    def test_changed_order_refused(self):
        snap = make_cluster(128)
        gangs = [make_gang(f"g{i:02d}") for i in range(8)]
        eng = PlacementEngine(snap, hierarchical=True)
        h = eng.dispatch(gangs, free=snap.free.copy())
        other = list(gangs[:-1]) + [make_gang("new")]
        res = eng.solve(other, free=snap.free.copy(), dispatch=h)
        assert not res.stats.get("dispatch_overlap")
        assert res.num_placed == len(other)


class TestSubSnapshot:
    def test_subset_snapshot_shape(self):
        snap = make_cluster(128)
        idx = np.flatnonzero(snap.domain_ids[0] == 1)
        sub = subset_snapshot(snap, idx, 0)
        assert sub.num_nodes == len(idx)
        assert sub.level_keys == snap.level_keys[1:]
        assert sub.num_levels == snap.num_levels - 1
        # rack ids re-densified 0..3, host ids 0..63
        assert int(sub.num_domains[0]) == 4
        assert sub.node_names == [snap.node_names[i] for i in idx]

    def test_shift_level(self):
        assert shift_level(-1, 0) == -1
        assert shift_level(0, 0) == -1   # at the prune level: sub-root
        assert shift_level(1, 0) == 0
        assert shift_level(2, 0) == 1
        assert shift_level(1, 1) == -1
        assert shift_level(2, 1) == 0


class TestConfigAndScheduler:
    def test_config_validation(self):
        load_operator_config(
            {"solver": {"hierarchical_solve": True,
                        "hierarchical_prune_level": 1,
                        "hierarchical_min_nodes": 0}}
        )
        # wave parallelism: None (auto), 0 (serial) and positive widths
        # are all valid
        for w in (None, 0, 4):
            load_operator_config(
                {"solver": {"hier_parallel_workers": w}}
            )
        with pytest.raises(ValidationError):
            load_operator_config(
                {"solver": {"hierarchical_solve": "yes"}}
            )
        with pytest.raises(ValidationError):
            load_operator_config(
                {"solver": {"hierarchical_prune_level": -2}}
            )
        with pytest.raises(ValidationError):
            load_operator_config(
                {"solver": {"hierarchical_min_nodes": -1}}
            )
        with pytest.raises(ValidationError):
            load_operator_config(
                {"solver": {"hier_parallel_workers": -1}}
            )
        with pytest.raises(ValidationError):
            load_operator_config(
                {"solver": {"hier_parallel_workers": "many"}}
            )

    def test_scheduler_threads_hierarchy_e2e(self):
        from grove_tpu.api.types import (
            Container,
            Pod,
            PodCliqueSet,
            PodCliqueSetSpec,
            PodCliqueSetTemplateSpec,
            PodCliqueSpec,
            PodCliqueTemplateSpec,
            PodSpec,
            TopologyConstraintSpec,
            TopologyPackConstraintSpec,
        )
        from grove_tpu.cluster import make_nodes
        from grove_tpu.controller import Harness

        h = Harness(
            nodes=make_nodes(32),
            config={"solver": {"hierarchical_min_nodes": 0,
                               "hier_parallel_workers": 2}},
        )
        pcs = PodCliqueSet(
            metadata=ObjectMeta(name="w"),
            spec=PodCliqueSetSpec(
                replicas=3,
                template=PodCliqueSetTemplateSpec(
                    cliques=[
                        PodCliqueTemplateSpec(
                            name="a",
                            spec=PodCliqueSpec(
                                replicas=4,
                                pod_spec=PodSpec(
                                    containers=[
                                        Container(
                                            name="m",
                                            resources={"cpu": 2.0},
                                        )
                                    ]
                                ),
                            ),
                        )
                    ],
                    topology_constraint=TopologyConstraintSpec(
                        pack_constraint=TopologyPackConstraintSpec(
                            required="rack"
                        )
                    ),
                ),
            ),
        )
        h.apply(pcs)
        h.settle()
        pods = h.store.scan(Pod.KIND)
        assert pods and all(p.node_name for p in pods)
        eng = (h.debug_dump().get("scheduler") or {}).get("engine") or {}
        hier = eng.get("hierarchical") or {}
        assert hier.get("enabled") is True
        assert hier.get("shards_built", 0) >= 1
        # the config knob threaded through to the engine
        assert hier.get("wave_workers") == 2

    def test_debug_summary_block(self):
        snap = make_cluster(128)
        eng = PlacementEngine(snap, hierarchical=True)
        block = eng.debug_summary()["hierarchical"]
        assert block == {
            "enabled": True,
            "wave_workers": eng._wave_workers(),
            "prune_level": None,
            "coarse_domains": None,
            "shards_built": 0,
            "last_pruned_pairs": 0,
            "last_admissible_pairs": 0,
        }
        eng.solve([make_gang("a")], free=snap.free.copy())
        block = eng.debug_summary()["hierarchical"]
        assert block["prune_level"] == 0
        assert block["shards_built"] >= 1
