"""Federation: multi-cluster scheduling with whole-cluster outage
failover (grove_tpu/federation).

The acceptance spine: a 3-member federation where a seeded whole-cluster
outage re-places the failed member's ENTIRE committed gang set onto
survivors within the declared drain window with zero committed-write
loss (seq + merged fingerprint asserted), the fenced member's directory
byte-unchanged and its zombie appends refused — plus the satellites:
FederationConfig validation, per-cluster metric series hygiene,
drain-under-budget discipline (one DisruptionLedger shared with
preemption/defrag), mid-drain survivor promotion, the NoFeasibleCluster
explain funnel, and coordinator crash recovery from the durable journal.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from grove_tpu.api.config import load_operator_config
from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import (
    Container,
    PodCliqueSet,
    PodCliqueSetSpec,
    PodCliqueSetTemplateSpec,
    PodCliqueSpec,
    PodCliqueTemplateSpec,
    PodSpec,
)
from grove_tpu.api.validation import ValidationError
from grove_tpu.chaos import (
    FaultPlan,
    FederationChaos,
    federation_fingerprint,
    federation_invariants,
)
from grove_tpu.cluster import make_nodes
from grove_tpu.cluster.durability import FencedAppend
from grove_tpu.federation import (
    FEDERATION_GAUGES,
    FederationCoordinator,
)
from grove_tpu.observability.explain import (
    PREEMPTIBLE_CODES,
    UnsatCode,
    classify_domain_cuts,
)
from grove_tpu.solver.hierarchy import cluster_level_aggregates


def gang(name, ns="default", pods=2, cpu=1.0):
    return PodCliqueSet(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodCliqueSetSpec(
            replicas=1,
            template=PodCliqueSetTemplateSpec(cliques=[
                PodCliqueTemplateSpec(name="w", spec=PodCliqueSpec(
                    role_name="w", replicas=pods, min_available=pods,
                    pod_spec=PodSpec(containers=[
                        Container(name="c", resources={"cpu": cpu})
                    ]),
                ))
            ]),
        ),
    )


def fed_config(root, clusters=3, extra=None, **fe):
    cfg = {
        "durability": {"wal_dir": os.path.join(str(root), "wal")},
        "federation": {"enabled": True, "clusters": clusters, **fe},
    }
    if extra:
        cfg.update(extra)
    return cfg


def build_fed(root, clusters=3, nodes_per=8, node_counts=None,
              extra=None, audit=False, **fe):
    counts = node_counts or [nodes_per] * clusters
    return FederationCoordinator(
        fed_config(root, clusters, extra=extra, **fe),
        [
            make_nodes(counts[i], name_prefix=f"c{i}-n")
            for i in range(clusters)
        ],
        audit=audit,
    )


def dir_listing(log):
    parts = getattr(log, "partitions", None) or [log]
    return {
        p.dir: sorted(
            (n, os.path.getsize(os.path.join(p.dir, n)))
            for n in os.listdir(p.dir)
        )
        for p in parts
    }


# -- satellite: FederationConfig validation -----------------------------------

class TestFederationConfig:
    def test_defaults_disabled_and_roundtrip(self):
        cfg = load_operator_config({})
        assert cfg.federation.enabled is False
        assert cfg.federation.clusters == 3

    def test_enabled_with_durability_root_accepted(self, tmp_path):
        cfg = load_operator_config(fed_config(tmp_path))
        assert cfg.federation.enabled

    @pytest.mark.parametrize("patch,needle", [
        ({"clusters": 1}, "clusters"),
        ({"clusters": 0}, "clusters"),
        ({"heartbeat_interval_seconds": 0}, "heartbeat_interval_seconds"),
        ({"outage_detection_window_seconds": 0},
         "outage_detection_window_seconds"),
        # the window must exceed the heartbeat interval or every member
        # is permanently suspect
        ({"heartbeat_interval_seconds": 60.0,
          "outage_detection_window_seconds": 45.0},
         "outage_detection_window_seconds"),
        ({"drain_window_seconds": 0}, "drain_window_seconds"),
        ({"drain_max_gangs_per_round": 0}, "drain_max_gangs_per_round"),
        ({"cluster_wal_dirs": ["/a"]}, "cluster_wal_dirs"),
        ({"cluster_wal_dirs": ["/a", "/a", "/b"]}, "cluster_wal_dirs"),
    ])
    def test_rejected_combos(self, tmp_path, patch, needle):
        cfg = fed_config(tmp_path)
        cfg["federation"].update(patch)
        with pytest.raises(ValidationError) as err:
            load_operator_config(cfg)
        assert needle in str(err.value)

    def test_enabled_requires_durability(self):
        with pytest.raises(ValidationError) as err:
            load_operator_config({"federation": {"enabled": True}})
        assert "durability" in str(err.value)

    def test_coordinator_dir_must_not_collide(self, tmp_path):
        cfg = fed_config(
            tmp_path,
            cluster_wal_dirs=["/w/a", "/w/b", "/w/c"],
            coordinator_wal_dir="/w/b",
        )
        with pytest.raises(ValidationError) as err:
            load_operator_config(cfg)
        assert "coordinator_wal_dir" in str(err.value)

    def test_unknown_field_rejected(self, tmp_path):
        cfg = fed_config(tmp_path)
        cfg["federation"]["bogus"] = 1
        with pytest.raises(ValidationError):
            load_operator_config(cfg)


# -- tentpole: cluster-level aggregates (the lifted coarse cuts) --------------

class TestClusterAggregates:
    def snapshots(self, counts):
        from grove_tpu.controller import Harness

        harnesses = [
            Harness(nodes=make_nodes(c, name_prefix=f"a{i}-n"))
            for i, c in enumerate(counts)
        ]
        return [h.cluster.topology_snapshot() for h in harnesses]

    def test_aggregates_sum_schedulable_free(self):
        snaps = self.snapshots([4, 8])
        cnt, free, max_free, axis = cluster_level_aggregates(snaps)
        assert cnt.tolist() == [4, 8]
        i_cpu = axis.index("cpu")
        assert free[0, i_cpu] == pytest.approx(4 * 32.0)
        assert free[1, i_cpu] == pytest.approx(8 * 32.0)
        assert max_free[0, i_cpu] == pytest.approx(32.0)

    def test_over_admit_contract(self):
        """A cluster whose whole aggregate free covers the demand is
        NEVER cut — the lifted predicates may only over-admit, exactly
        like the in-cluster hierarchical pruner's domain cuts."""
        snaps = self.snapshots([2, 6])
        cnt, free, max_free, axis = cluster_level_aggregates(snaps)
        i_cpu = axis.index("cpu")
        for demand_cpu in (1.0, 63.0, 64.0, 65.0, 192.0, 193.0):
            td = np.zeros(len(axis))
            td[i_cpu] = demand_cpu
            cordoned, agg_cut, remaining = classify_domain_cuts(
                td, free.copy(), cnt
            )
            for i in range(2):
                fits = demand_cpu <= free[i, i_cpu] + 1e-6
                if fits:
                    assert remaining[i], (
                        f"cluster {i} can hold {demand_cpu} cpu but was "
                        "cut — under-admission violates the contract"
                    )


# -- tentpole: routing + delegation -------------------------------------------

class TestRouting:
    def test_spread_and_delegation(self, tmp_path):
        fed = build_fed(tmp_path)
        homes = [fed.apply(gang(f"g{j}")) for j in range(9)]
        assert sorted(set(homes)) == ["c0", "c1", "c2"]
        fed.settle()
        for j, home in enumerate(homes):
            cell = fed.by_name[home]
            assert cell.cluster.store.peek(
                PodCliqueSet.KIND, "default", f"g{j}"
            ) is not None
        assert not federation_invariants(fed)
        fed.close()

    def test_routes_journaled(self, tmp_path):
        fed = build_fed(tmp_path)
        fed.apply(gang("solo"))
        routes = fed.journal.routes()
        assert routes[("default", "solo")].verdict == "Routed"
        assert routes[("default", "solo")].cluster in ("c0", "c1", "c2")
        fed.close()


# -- acceptance: whole-cluster outage failover --------------------------------

class TestOutageFailover:
    def failover(self, tmp_path, **fe):
        fed = build_fed(
            tmp_path,
            outage_detection_window_seconds=15.0,
            heartbeat_interval_seconds=2.0,
            **fe,
        )
        homes = [fed.apply(gang(f"g{j}")) for j in range(9)]
        fed.settle()
        before = federation_fingerprint(fed)
        victim = homes[0]
        fed.fail_cluster(victim)
        for _ in range(10):
            fed.advance(5.0)
        return fed, victim, before

    def test_outage_drains_whole_committed_set(self, tmp_path):
        fed, victim, before = self.failover(tmp_path)
        vc = fed.by_name[victim]
        assert vc.state == "drained"
        assert vc.outage_stats["gangs"] == 3
        # bounded window: declared -> drained inside the declared bound
        assert (vc.drained_at - vc.outage_stats["declared_at"]
                <= fed.config.federation.drain_window_seconds)
        # zero committed-write loss: the fenced history was read at its
        # committed head, and every gang lives on exactly one survivor
        assert vc.outage_stats["committed_last_seq"] > 0
        assert vc.outage_stats["recovery_outcome"] == "clean"
        assert not federation_invariants(fed)
        for home in fed._routes.values():
            assert home != victim
        # the merged workload fingerprint survives the failover
        assert federation_fingerprint(fed) == before
        fed.close()

    def test_fenced_directory_byte_unchanged_and_zombie_refused(
        self, tmp_path,
    ):
        fed, victim, _ = self.failover(tmp_path)
        vc = fed.by_name[victim]
        fenced = dir_listing(vc.cluster.durability)
        store = vc.cluster.store
        ev = store._events[-1]
        with pytest.raises(FencedAppend):
            vc.cluster.durability.commit(store, ev)
        assert dir_listing(vc.cluster.durability) == fenced
        # and the store's own commit path refuses too
        with pytest.raises(FencedAppend):
            store.create(gang("zombie"))
        assert dir_listing(vc.cluster.durability) == fenced
        fed.close()

    def test_outage_journaled_with_term(self, tmp_path):
        fed, victim, _ = self.failover(tmp_path)
        states = fed.journal.cluster_states()
        assert states[victim].state == "drained"
        assert states[victim].term >= 1
        fed.close()

    def test_short_partition_does_not_fail_over(self, tmp_path):
        fed = build_fed(
            tmp_path,
            outage_detection_window_seconds=30.0,
            heartbeat_interval_seconds=2.0,
        )
        [fed.apply(gang(f"g{j}")) for j in range(3)]
        fed.settle()
        fed.fail_cluster("c1")
        for _ in range(4):
            fed.advance(5.0)  # 20s lag < 30s window
        fed.heal_cluster("c1")
        fed.advance(5.0)
        assert fed.by_name["c1"].state == "ready"
        assert not federation_invariants(fed)
        fed.close()


# -- satellite: per-cluster metric series hygiene -----------------------------

class TestMetricSeriesHygiene:
    def series(self, fed, family):
        metric = fed.metrics.get(family)
        return sorted(
            labels["cluster"] for labels in metric.label_sets()
        ) if metric is not None else []

    def test_failed_cluster_series_leave_metrics(self, tmp_path):
        fed = build_fed(tmp_path, outage_detection_window_seconds=15.0)
        [fed.apply(gang(f"g{j}")) for j in range(6)]
        fed.settle()
        assert self.series(
            fed, "grove_federation_cluster_state"
        ) == ["c0", "c1", "c2"]
        assert "c1" in self.series(fed, "grove_federation_cluster_free")
        fed.fail_cluster("c1")
        for _ in range(10):
            fed.advance(5.0)
        assert fed.by_name["c1"].state == "drained"
        for family in FEDERATION_GAUGES:
            assert "c1" not in self.series(fed, family), family
        # survivors keep their series
        assert self.series(
            fed, "grove_federation_cluster_state"
        ) == ["c0", "c2"]
        fed.close()

    def test_free_series_leave_at_fence_not_at_drained(self, tmp_path):
        """A fenced member's capacity is not capacity: its free series
        leave the moment it stops being ready, while state/gangs stay
        visible through the drain."""
        fed = build_fed(
            tmp_path,
            outage_detection_window_seconds=15.0,
            drain_max_gangs_per_round=1,
        )
        [fed.apply(gang(f"g{j}", pods=1)) for j in range(9)]
        fed.settle()
        fed.fail_cluster("c0")
        for _ in range(4):
            fed.advance(5.0)
        vc = fed.by_name["c0"]
        if vc.state == "draining":  # still paced mid-drain
            assert "c0" not in self.series(
                fed, "grove_federation_cluster_free"
            )
            assert "c0" in self.series(
                fed, "grove_federation_cluster_state"
            )
        fed.close()


# -- satellite: drain under the shared disruption budget ----------------------

class TestDrainBudget:
    def budget_fed(self, tmp_path, budget=2, **fe):
        """Asymmetric members: c0 is twice the size, so least-loaded
        routing homes every team-a gang there — the drain then has one
        victim with the whole tenant on it."""
        extra = {"tenancy": {
            "enabled": True,
            "tenants": [{"name": "team-a", "disruption_budget": budget}],
        }}
        fe.setdefault("outage_detection_window_seconds", 15.0)
        fe.setdefault("drain_max_gangs_per_round", 2)
        fe.setdefault("drain_window_seconds", 600.0)
        return build_fed(
            tmp_path, node_counts=[16, 8, 8], extra=extra, audit=True,
            **fe,
        )

    def test_drain_paces_through_the_shared_ledger(self, tmp_path):
        fed = self.budget_fed(tmp_path, budget=2)
        homes = [fed.apply(gang(f"g{j}", ns="team-a")) for j in range(6)]
        assert set(homes) == {"c0"}
        fed.settle()
        fed.fail_cluster("c0")
        drained_windows = 0
        for _ in range(40):
            fed.advance(5.0)
            if fed.by_name["c0"].state == "drained":
                break
            drained_windows += 1
        vc = fed.by_name["c0"]
        assert vc.state == "drained"
        # budget 2/window over 6 gangs: the drain NEEDED multiple ledger
        # windows — the budget actually paced it
        assert vc.drained_at - vc.outage_stats["declared_at"] >= 60.0
        # every charge landed as the shared consumer, within budget (the
        # armed audit would have raised otherwise)
        spent_somewhere = False
        for cell in fed.cells:
            if cell.state != "ready":
                continue
            tenancy = cell.cluster.tenancy
            bd = tenancy.ledger.breakdown("team-a", cell.clock.now())
            assert set(bd) <= {"federation-drain"}
            spent_somewhere = spent_somewhere or bool(bd)
        assert not federation_invariants(fed)
        fed.close()

    def test_armed_audit_raises_on_overspend(self, tmp_path):
        fed = self.budget_fed(tmp_path, budget=1)
        fed.apply(gang("g0", ns="team-a"))
        fed.settle()
        surv = fed.by_name["c1"]
        surv.cluster.tenancy.ledger.charge(
            "team-a", "federation-drain", surv.clock.now(), n=3
        )
        with pytest.raises(RuntimeError, match="disruption-budget audit"):
            fed._audit_budgets()
        fed.close()

    def test_drain_shares_the_window_with_preemption(self, tmp_path):
        """A preemption charge in the window defers the drain — one
        window can never double-spend across consumers."""
        fed = self.budget_fed(tmp_path, budget=1, drain_window_seconds=900.0)
        homes = [fed.apply(gang(f"g{j}", ns="team-a")) for j in range(2)]
        assert set(homes) == {"c0"}
        fed.settle()
        # both survivors' ledgers are pre-spent by "preemption"
        for name in ("c1", "c2"):
            cell = fed.by_name[name]
            cell.cluster.tenancy.ledger.charge(
                "team-a", "preemption", cell.clock.now()
            )
        fed.fail_cluster("c0")
        for _ in range(4):
            fed.advance(5.0)
        vc = fed.by_name["c0"]
        assert vc.state == "draining"
        assert vc.drain_queue  # deferred: no budget anywhere
        # the window rolls, the drain completes
        for _ in range(20):
            fed.advance(10.0)
            if vc.state == "drained":
                break
        assert vc.state == "drained"
        assert not federation_invariants(fed)
        fed.close()


# -- satellite: mid-drain survivor promotion ----------------------------------

class TestMidDrainPromotion:
    def test_promote_survivor_mid_drain_no_strand_no_double_place(
        self, tmp_path,
    ):
        fed = build_fed(
            tmp_path, node_counts=[16, 8, 8],
            extra={"replication": {
                "enabled": True,
                "ack_mode": "semi-sync",
                # placeholder: the coordinator re-points each member's
                # standby at a sibling of its own WAL dir
                "standby_wal_dir": str(tmp_path / "standby"),
            }},
            outage_detection_window_seconds=15.0,
            drain_max_gangs_per_round=1,
        )
        homes = [fed.apply(gang(f"g{j}")) for j in range(6)]
        assert set(homes) == {"c0"}
        fed.settle()
        fed.fail_cluster("c0")
        for _ in range(4):
            fed.advance(5.0)
        vc = fed.by_name["c0"]
        assert vc.state == "draining"
        assert vc.drained_keys  # some gangs already re-homed
        # a survivor that received drained gangs loses ITS leader
        # mid-drain and promotes its standby
        dest = fed.by_name[sorted(set(vc.drained_keys.values()))[0]]
        dest.harness.promote_standby(force=True)
        for _ in range(20):
            fed.advance(5.0)
            if vc.state == "drained":
                break
        assert vc.state == "drained"
        # nothing stranded, nothing double-placed
        assert not federation_invariants(fed)
        for (ns, name), home in sorted(fed._routes.items()):
            assert fed.by_name[home].cluster.store.peek(
                PodCliqueSet.KIND, ns, name
            ) is not None
        fed.close()


# -- satellite: NoFeasibleCluster explain funnel ------------------------------

class TestNoFeasibleCluster:
    def test_unroutable_gang_gets_structured_diagnosis(self, tmp_path):
        fed = build_fed(tmp_path)
        # per-pod demand no node in ANY member can hold
        assert fed.apply(gang("huge", pods=1, cpu=64.0)) is None
        summary = fed.wedged_summary()
        entry = next(
            w for w in summary["wedged"]
            if w["name"] == "default/huge"
        )
        assert entry["home_cluster"] is None
        assert entry["routing_verdict"] == "NoFeasibleCluster"
        funnel = entry["explain"]["funnel"]
        assert funnel["level"] == "federation"
        assert funnel["clusters"] == 3
        assert funnel["cut_fit"] == 3
        assert entry["explain"]["code"] == "NoFeasibleCluster"
        # journaled with the verdict, and counted
        route = fed.journal.routes()[("default", "huge")]
        assert route.verdict == "NoFeasibleCluster"
        # structurally non-preemptible: the gang was cut ABOVE every
        # cluster's control plane
        assert UnsatCode.NO_FEASIBLE_CLUSTER not in PREEMPTIBLE_CODES
        fed.close()

    def test_unroutable_gang_retried_when_capacity_appears(self, tmp_path):
        fed = build_fed(tmp_path, nodes_per=2)
        # fill every member (2 nodes x 32 cpu each)
        fillers = [gang(f"f{j}", pods=2, cpu=32.0) for j in range(3)]
        for f in fillers:
            assert fed.apply(f) is not None
        fed.settle()
        target = gang("late", pods=2, cpu=32.0)
        assert fed.apply(target) is None
        assert ("default", "late") in fed._unroutable
        # free a member and settle: the retry routes it
        home = fed._routes[("default", "f0")]
        fed.by_name[home].cluster.store.delete(
            PodCliqueSet.KIND, "default", "f0"
        )
        del fed._routes[("default", "f0")]
        fed.by_name[home].harness.settle()
        fed.settle()
        assert ("default", "late") not in fed._unroutable
        assert fed._routes[("default", "late")] == home
        fed.close()

    def test_debug_dump_carries_federation_block(self, tmp_path):
        fed = build_fed(tmp_path)
        fed.apply(gang("g0"))
        fed.settle()
        home = fed._routes[("default", "g0")]
        dump = fed.by_name[home].harness.debug_dump()
        assert dump["federation"]["cluster"] == home
        assert dump["federation"]["state"] == "ready"
        fed.close()


# -- satellite + tentpole: coordinator durability -----------------------------

class TestCoordinatorCrash:
    def test_crash_recovers_routing_table(self, tmp_path):
        fed = build_fed(tmp_path)
        [fed.apply(gang(f"g{j}")) for j in range(6)]
        fed.settle()
        before = dict(fed._routes)
        fed.crash_recover()
        assert fed._routes == before
        fed.close()

    def test_crash_mid_drain_resumes_from_journal(self, tmp_path):
        fed = build_fed(
            tmp_path, node_counts=[16, 8, 8],
            outage_detection_window_seconds=15.0,
            drain_max_gangs_per_round=1,
        )
        homes = [fed.apply(gang(f"g{j}")) for j in range(6)]
        assert set(homes) == {"c0"}
        fed.settle()
        fed.fail_cluster("c0")
        for _ in range(4):
            fed.advance(5.0)
        vc = fed.by_name["c0"]
        assert vc.state == "draining"
        moved_before = dict(vc.drained_keys)
        fed.crash_recover()
        # the rebuilt drain state agrees with the journal: previously
        # drained gangs are NOT re-queued (no double-place), the rest are
        assert vc.state == "draining"
        for key, dest in moved_before.items():
            assert vc.drained_keys[key] == dest
        for _ in range(20):
            fed.advance(5.0)
            if vc.state == "drained":
                break
        assert vc.state == "drained"
        assert not federation_invariants(fed)
        assert sorted(fed._routes) == sorted(
            ("default", f"g{j}") for j in range(6)
        )
        fed.close()


# -- chaos determinism --------------------------------------------------------

class TestFederationChaos:
    def test_new_rates_absent_from_seed_mix(self):
        """Pre-existing seeds replay bit-identically: the federation
        rates default 0.0 and from_seed must NOT scale them into life."""
        for seed in (0, 7, 123):
            plan = FaultPlan.from_seed(seed)
            assert plan.cluster_outage_rate == 0.0
            assert plan.cluster_partition_rate == 0.0
            assert plan.coordinator_crash_rate == 0.0

    def run_once(self, root):
        fed = build_fed(
            root, nodes_per=6,
            heartbeat_interval_seconds=2.0,
            outage_detection_window_seconds=10.0,
            drain_window_seconds=400.0,
        )
        plan = FaultPlan(
            seed=11, cluster_outage_rate=0.15,
            cluster_partition_rate=0.1, coordinator_crash_rate=0.08,
            chaos_steps=25, step_seconds=2.0,
        )
        try:
            return FederationChaos(plan, fed).run(
                [gang(f"g{j}") for j in range(6)]
            )
        finally:
            fed.close()

    def test_seeded_run_replays_bit_identically(self, tmp_path):
        a = self.run_once(tmp_path / "a")
        b = self.run_once(tmp_path / "b")
        assert a["fault_counts"] == b["fault_counts"]
        assert a["cluster_states"] == b["cluster_states"]
        assert a["fingerprint"] == b["fingerprint"]
        assert a["invariant_violations"] == []
        assert b["invariant_violations"] == []
