"""Pallas execution tier (solver/pallas_core.py + engine wiring).

Everything here runs in INTERPRET mode: on the CPU test backend
`pallas_capability()` returns "interpret", so `pallas_core=True` lowers
the kernel through the pallas interpreter — same program, same fp32
arithmetic, bit-equal to the XLA `value_from_aggregates` chain. The
contract under test is the one docs/scheduling.md ("One-kernel solve")
promises:

  * fp32 kernel output is BIT-equal to the fused XLA scoring core, so
    every downstream consumer (top-k, commit scan, repair, incremental
    cache rows) is unperturbed;
  * the on-device commit ships [G, 2] committed placements whose
    conflict-free decode is bit-equal to the host candidate walk;
  * any kernel-launch failure permanently falls back to the XLA path
    (capability miss is a downgrade, never an error);
  * the SolverConfig knobs validate, and the auto default stays OFF on
    CPU so chaos seeds replay bit-identically.
"""

import numpy as np
import pytest

from grove_tpu.api.config import ValidationError, load_operator_config
from grove_tpu.solver import PlacementEngine
from grove_tpu.solver.engine import _NEG, value_from_aggregates
from grove_tpu.solver.pallas_core import (
    device_commit_scan,
    interpret_default,
    pallas_capability,
    pallas_value,
)

from test_hierarchy import seeded_problem
from test_solver import cluster, gang

pytestmark = pytest.mark.skipif(
    pallas_capability() is None, reason="pallas not importable"
)


def _rand_inputs(seed, g, d, r):
    """A seeded [G, D] scoring instance with every edge the kernel must
    mask: zero-cnt_fit columns, invalid rows, required levels no domain
    satisfies, negative fairness offsets."""
    rng = np.random.default_rng(seed)
    dom_free = rng.uniform(0.0, 32.0, (d, r)).astype(np.float32)
    cnt_fit = rng.integers(0, 3, (g, d)).astype(np.float32)
    dom_level = rng.integers(-1, 3, d).astype(np.int32)
    td = rng.uniform(0.0, 16.0, (g, r)).astype(np.float32)
    req = rng.integers(-1, 4, g).astype(np.int32)  # 3 = unsatisfiable
    pref = rng.integers(-1, 3, g).astype(np.int32)
    valid = rng.random(g) > 0.2
    cap = rng.uniform(1.0, 64.0, r).astype(np.float32)
    fair = rng.uniform(-1.0, 1.0, g).astype(np.float32)
    return dom_free, cnt_fit, dom_level, td, req, pref, valid, cap, fair


def _both(seed, g, d, r, precision="fp32"):
    args = _rand_inputs(seed, g, d, r)
    ref = np.asarray(value_from_aggregates(*args))
    out = np.asarray(
        pallas_value(*args, precision=precision, interpret=True)
    )
    return ref, out


def assert_same_placements(a, b):
    assert sorted(a.placed) == sorted(b.placed)
    for name in a.placed:
        np.testing.assert_array_equal(
            a.placed[name].node_indices, b.placed[name].node_indices
        )
    assert a.unplaced == b.unplaced


class TestKernelParity:
    """pallas_value vs value_from_aggregates, direct tensor-level."""

    @pytest.mark.parametrize(
        "g,d,r",
        [
            (8, 5, 3),     # smaller than one tile in both axes
            (64, 300, 3),  # multi-tile domains, ragged last tile
            (16, 129, 2),  # one-past-tile boundary column
            (1, 1, 1),     # degenerate single cell
            (128, 700, 4), # full gang tile, wide domain sweep
        ],
    )
    def test_fp32_bit_equal(self, g, d, r):
        for seed in (0, 7):
            ref, out = _both(seed, g, d, r)
            # bitwise: == on float arrays, no tolerance
            np.testing.assert_array_equal(out, ref)

    def test_masked_rows_and_columns_get_neg(self):
        args = list(_rand_inputs(3, 12, 40, 3))
        args[1][:, 5] = 0.0       # cnt_fit: no node in domain 5 fits
        args[6][4] = False        # gang 4 invalid
        args[4][9] = 99           # gang 9: required level > every domain
        ref = np.asarray(value_from_aggregates(*args))
        out = np.asarray(pallas_value(*args, interpret=True))
        np.testing.assert_array_equal(out, ref)
        assert np.all(out[:, 5] == _NEG)
        assert np.all(out[4] == _NEG)
        assert np.all(out[9] == _NEG)

    def test_bf16_masks_exact_values_close(self):
        """Reduced precision may move scores but NEVER the feasibility
        mask: _NEG cells are placed by fp32 comparisons in both tiers."""
        ref, out = _both(11, 32, 90, 3, precision="bf16")
        np.testing.assert_array_equal(out == _NEG, ref == _NEG)
        live = ref != _NEG
        np.testing.assert_allclose(
            out[live], ref[live], rtol=0.02, atol=0.05
        )

    def test_unknown_precision_rejected(self):
        args = _rand_inputs(0, 4, 4, 2)
        with pytest.raises(ValueError, match="precision"):
            pallas_value(*args, precision="fp16", interpret=True)

    def test_cpu_capability_is_interpret(self):
        assert pallas_capability() == "interpret"
        assert interpret_default() is True


class TestDeviceCommitScan:
    def test_matches_host_greedy_replay(self):
        """The lax.scan commit is the same greedy walk a host replay of
        the packed top-k performs: first residually-feasible candidate
        wins, demand subtracts down the ancestor chain."""
        rng = np.random.default_rng(5)
        g, d, r, k = 20, 12, 3, 4
        dom_free = rng.uniform(4.0, 30.0, (d, r)).astype(np.float32)
        # flat ancestor table: self + dummy-row padding
        anc = np.full((d, 3), d, dtype=np.int32)
        anc[:, 0] = np.arange(d)
        td = rng.uniform(1.0, 10.0, (g, r)).astype(np.float32)
        top_dom = np.stack(
            [rng.choice(d, size=k, replace=False) for _ in range(g)]
        ).astype(np.int32)
        top_val = rng.uniform(0.0, 5.0, (g, k)).astype(np.float32)
        top_val[3] = _NEG  # one all-infeasible row

        cv, cd = device_commit_scan(top_val, top_dom, dom_free, anc, td)
        cv, cd = np.asarray(cv), np.asarray(cd)
        assert cv.shape == (g, 1) and cd.shape == (g, 1)

        resid = np.concatenate(
            [dom_free, np.zeros((1, r), np.float32)]
        )
        for i in range(g):
            want_v, want_d = _NEG, None
            for j in range(k):
                dj = int(top_dom[i, j])
                if top_val[i, j] > _NEG / 2 and np.all(
                    resid[dj] + 1e-6 >= td[i]
                ):
                    want_v, want_d = top_val[i, j], dj
                    for a in anc[dj]:
                        resid[a] -= td[i]
                    break
            assert cv[i, 0] == np.float32(want_v)
            if want_d is not None:
                assert cd[i, 0] == want_d

    def test_engine_parity_conflict_free(self):
        """On a backlog with aggregate == exact feasibility everywhere,
        the shipped [G, 2] placements decode bit-equal to the host
        candidate walk over the [G, 2K] list."""
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=16.0)
        gangs = [
            gang(f"g{i}", pods=2, cpu=2.0, required=i % 2 - 1,
                 preferred=(i % 3) - 1, priority=float(i % 3))
            for i in range(10)
        ]
        base = PlacementEngine(snap).solve(gangs, free=snap.free.copy())
        eng = PlacementEngine(snap, device_commit=True)
        assert eng.device_commit is True
        res = eng.solve(gangs, free=snap.free.copy())
        assert_same_placements(base, res)
        assert res.num_placed == 10
        disp = eng.debug_summary()["device_state"]["dispatches"]
        assert disp.get("device_commit", 0) >= 1


class TestEngineParity:
    """Whole-solve parity: pallas tier on vs default XLA core."""

    def test_flat_cold_and_warm_parity(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=12.0)
        gangs = [
            gang(f"g{i}", pods=3, cpu=2.0, required=(i % 3) - 1,
                 preferred=i % 2, priority=float(i % 2))
            for i in range(8)
        ]
        base = PlacementEngine(snap)
        eng = PlacementEngine(snap, pallas_core=True)
        assert eng.pallas_core is True
        for rnd in range(2):  # cold fused launch, then warm re-launch
            free = snap.free.copy()
            if rnd:  # perturb so the warm solve can't hit the
                free[0] *= 0.5  # zero-dispatch reuse memo
            a = base.solve(gangs, free=free.copy())
            b = eng.solve(gangs, free=free.copy())
            assert_same_placements(a, b)
            assert a.mean_placement_score() == b.mean_placement_score()
        disp = eng.debug_summary()["device_state"]["dispatches"]
        assert disp.get("pallas", 0) >= 2

    def test_tie_rows_parity(self):
        """Identical gangs produce exact value ties; the seeded jitter
        tie-break sits downstream of the kernel in both tiers, so the
        resolution is bit-identical."""
        snap = cluster(blocks=2, racks=2, hosts=2, cpu=8.0)
        gangs = [gang(f"twin{i}", pods=2, cpu=2.0) for i in range(6)]
        a = PlacementEngine(snap).solve(gangs, free=snap.free.copy())
        b = PlacementEngine(snap, pallas_core=True).solve(
            gangs, free=snap.free.copy()
        )
        assert_same_placements(a, b)

    @pytest.mark.parametrize("seed", [1, 4])
    def test_seeded_backlog_parity(self, seed):
        """Mixed seeded backlog incl. pod-eligibility masks and a
        drained block (test_hierarchy.seeded_problem)."""
        snap, free, gangs = seeded_problem(seed, num_gangs=16)
        a = PlacementEngine(snap).solve(gangs, free=free.copy())
        b = PlacementEngine(snap, pallas_core=True).solve(
            gangs, free=free.copy()
        )
        assert_same_placements(a, b)

    def test_hierarchical_sub_engines_inherit(self):
        snap, free, gangs = seeded_problem(2, num_gangs=16)
        a = PlacementEngine(snap, hierarchical=True).solve(
            gangs, free=free.copy()
        )
        eng = PlacementEngine(
            snap, hierarchical=True, pallas_core=True
        )
        b = eng.solve(gangs, free=free.copy())
        assert_same_placements(a, b)
        sub = next(iter(eng._hier.shards.values())).engine
        assert sub.pallas_core is True

    def test_whatif_scores_ride_kernel_tier(self):
        snap = cluster(blocks=2, racks=2, hosts=2, cpu=8.0)
        gangs = [gang(f"g{i}", pods=2, cpu=2.0) for i in range(4)]
        base = PlacementEngine(snap)
        eng = PlacementEngine(snap, pallas_core=True, device_commit=True)
        free = snap.free.copy()
        a = base.whatif_scores(gangs, free)
        b = eng.whatif_scores(gangs, free)
        for va, vb in zip(a, b):
            np.testing.assert_array_equal(va, vb)
        disp = eng.debug_summary()["device_state"]["dispatches"]
        # what-if rides the kernel but NEVER the device commit (defrag
        # consumes the full alternates list)
        assert disp.get("pallas", 0) >= 1
        assert disp.get("device_commit", 0) == 0


class TestCapabilityFallback:
    def test_auto_default_off_on_cpu(self):
        """Auto knobs resolve OFF where pallas does not lower natively —
        chaos seeds on the CPU backend replay bit-identically."""
        snap = cluster()
        eng = PlacementEngine(snap)
        assert eng.pallas_core is False
        assert eng.device_commit is False
        assert eng.debug_summary()["device_state"]["core_tier"] == "xla"

    def test_capability_none_resolves_core_off(self, monkeypatch):
        monkeypatch.setattr(
            "grove_tpu.solver.engine.pallas_capability", lambda: None
        )
        eng = PlacementEngine(cluster(), pallas_core=True)
        assert eng.pallas_core is False

    def test_kernel_failure_falls_back_to_xla(self, monkeypatch):
        """A launch failure with the kernel tier active downgrades the
        engine to the XLA path permanently and re-runs the launch — the
        solve still lands, bit-equal to the baseline."""
        import jax

        def boom(*a, **k):
            raise RuntimeError("no pallas lowering for backend")

        monkeypatch.setattr("grove_tpu.solver.engine.pallas_value", boom)
        # the fused program may already be compiled for common test
        # shapes with the pallas static: force a fresh trace so the
        # patched kernel is actually reached
        jax.clear_caches()
        snap = cluster(blocks=3, racks=2, hosts=1, cpu=24.0)
        gangs = [gang(f"g{i}", pods=2, cpu=3.0) for i in range(5)]
        base = PlacementEngine(snap).solve(gangs, free=snap.free.copy())
        eng = PlacementEngine(snap, pallas_core=True, device_commit=True)
        res = eng.solve(gangs, free=snap.free.copy())
        assert_same_placements(base, res)
        assert eng._pallas_fallbacks == 1
        assert eng.pallas_core is False
        assert eng.device_commit is False
        ds = eng.debug_summary()["device_state"]
        assert ds["core_tier"] == "xla"
        assert ds["pallas_fallbacks"] == 1
        # subsequent solves stay on the downgraded path, no re-raise
        res2 = eng.solve(gangs, free=snap.free.copy())
        assert_same_placements(base, res2)
        assert eng._pallas_fallbacks == 1


class TestConfigKnobs:
    def test_engine_rejects_unknown_precision(self):
        with pytest.raises(ValueError, match="pallas_precision"):
            PlacementEngine(cluster(), pallas_precision="fp16")

    def test_config_accepts_valid_knobs(self):
        cfg = load_operator_config(
            {
                "solver": {
                    "pallas_core": True,
                    "device_commit": False,
                    "pallas_precision": "bf16",
                }
            }
        )
        assert cfg.solver.pallas_core is True
        assert cfg.solver.device_commit is False
        assert cfg.solver.pallas_precision == "bf16"

    def test_config_auto_defaults_are_none(self):
        cfg = load_operator_config({})
        assert cfg.solver.pallas_core is None
        assert cfg.solver.device_commit is None
        assert cfg.solver.pallas_precision == "fp32"

    def test_config_rejects_bad_knobs(self):
        with pytest.raises(ValidationError) as exc:
            load_operator_config(
                {
                    "solver": {
                        "pallas_core": 1,
                        "device_commit": "yes",
                        "pallas_precision": "fp16",
                    }
                }
            )
        msg = str(exc.value)
        assert "config.solver.pallas_core" in msg
        assert "config.solver.device_commit" in msg
        assert "config.solver.pallas_precision" in msg


class TestObservabilitySurfaces:
    def test_debug_summary_reports_tier(self):
        snap = cluster(blocks=2, racks=2, hosts=2, cpu=8.0)
        eng = PlacementEngine(snap, pallas_core=True, device_commit=True)
        ds = eng.debug_summary()["device_state"]
        assert ds["core_tier"] == "pallas-fp32"
        assert ds["pallas_interpret"] is True
        assert ds["device_commit"] is True
        assert ds["pallas_fallbacks"] == 0

    def test_measure_device_split_commit_mode(self):
        snap = cluster(blocks=2, racks=2, hosts=2, cpu=8.0)
        eng = PlacementEngine(snap)
        gangs = [gang(f"g{i}", pods=2, cpu=2.0) for i in range(4)]
        saved = eng.device_commit
        out = eng.measure_device_split(gangs, iters=2, mode="commit")
        assert eng.device_commit == saved  # knob restored
        assert out["device_split_mode"] == "commit"
        assert out["device_commit_active"] is True
        assert out["device_core_tier"] == "xla"
        cand = out["device_result_bytes_candidates"]
        plc = out["device_result_bytes_placements"]
        assert plc < cand
        assert cand == plc * min(eng.top_k, eng.space.num_domains)
