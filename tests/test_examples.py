"""Every examples/ archetype converges: all pods bound and ready, gangs
Running — the reference's concept-overview samples as living code."""

import importlib
import re
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))

CASES = [
    ("single_node_aggregated", 4, 1),
    ("single_node_disaggregated", 5, 1),
    ("multi_node_aggregated", 10, 2),      # base + 1 scaled instance
    ("multi_node_disaggregated", 15, 2),   # base + 1 scaled prefill
    ("complete_inference_pipeline", 15, 3),
]


@pytest.mark.parametrize("module,pods,gangs", CASES)
def test_example_converges(module, pods, gangs):
    mod = importlib.import_module(module)
    from common import run

    h = run(mod.build(), nodes=64)
    pod_objs = h.store.list("Pod")
    assert len(pod_objs) == pods, [p.metadata.name for p in pod_objs]
    assert all(p.node_name and p.status.ready for p in pod_objs)
    gang_objs = h.store.list("PodGang")
    assert len(gang_objs) == gangs, [g.metadata.name for g in gang_objs]
    from grove_tpu.api.podgang import PodGangPhase

    assert all(g.status.phase == PodGangPhase.RUNNING for g in gang_objs)


def test_operations_tour_runs(capsys):
    """The ops example end to end: node lifecycle walkthrough always;
    service boundary, TLS rotation and introspection when the optional
    service dependencies are installed."""
    import operations_tour

    operations_tour.main()
    out = capsys.readouterr().out
    assert "node lifecycle: draining" in out
    assert "repaired onto healthy racks" in out
    assert "rack recovered" in out
    assert "cold restart: steady state journaled" in out
    assert "bit-identical store" in out
    assert "re-settled to the identical fixpoint" in out
    try:
        import grpc  # noqa: F401
        from cryptography import x509  # noqa: F401
    except ImportError:
        assert "service tour skipped" in out
        return
    assert "service Debug probe" in out
    assert "ROTATED listener (rotations=1)" in out


def test_readme_quickstart_runs_verbatim():
    """The README's Quickstart block is executed exactly as printed —
    a rotted snippet is the first thing a new user hits."""
    readme = (
        Path(__file__).resolve().parent.parent / "README.md"
    ).read_text()
    m = re.search(r"## Quickstart.*?```python\n(.*?)```", readme, re.S)
    assert m is not None, "README lost its Quickstart python block"
    exec(compile(m.group(1), "README-quickstart", "exec"), {})
