"""Partitioned durable write path: per-partition WALs behind one store.

The contract (cluster/durability.PartitionedLog): with
`DurabilityConfig.partitions` = K every committed mutation routes by
(namespace, kind) to one of K independent WAL segment chains + snapshot
generations, the store keeps its single logical seq/event-log for watch
semantics, and recovery — per-partition snapshot selection with the
classic corruption fallback + quarantine, then ONE globally seq-ordered
merged replay — rebuilds a store BIT-IDENTICAL to what a single WAL of
the same write history recovers, including torn tails and corrupt
snapshots on individual partitions. The round-scoped WriteBatch groups
its flush by partition so one partition's failure never blocks or
reorders another's writes.
"""

import io
import random

import pytest

from grove_tpu.api.config import load_operator_config
from grove_tpu.api.types import PodCliqueSet
from grove_tpu.chaos import (
    ChaosHarness,
    FaultPlan,
    check_invariants,
    settled_fingerprint,
)
from grove_tpu.cluster import make_nodes
from grove_tpu.cluster.clock import SimClock
from grove_tpu.cluster.durability import DurabilityError, PartitionedLog
from grove_tpu.cluster.store import ObjectStore
from grove_tpu.controller import Harness
from grove_tpu.controller.concurrency import WriteBatch
from grove_tpu.observability import MetricsRegistry

from test_durability import DUR, assert_bit_identical
from test_e2e_basic import clique, simple_pcs

NODES = 16


def part_config(wal_dir, partitions=4, **overrides):
    return {
        "durability": {
            **DUR, "wal_dir": str(wal_dir), "partitions": partitions,
            **overrides,
        }
    }


def part_harness(tmp_path, partitions=4, nodes=NODES, **config):
    cfg = part_config(tmp_path / "wal", partitions)
    cfg.update(config)
    return Harness(nodes=make_nodes(nodes), config=cfg)


def durability_cfg(wal_dir, partitions=1, **overrides):
    """A validated DurabilityConfig (the PartitionedLog constructor's
    input)."""
    return load_operator_config({
        "durability": {
            **DUR, "wal_dir": str(wal_dir), "partitions": partitions,
            **overrides,
        }
    }).durability


def seeded_history(h: Harness, seed: int) -> None:
    """Drive a seeded multi-namespace write history: applies, spec
    updates, deletes and clock advances — the same op sequence lands on
    any harness given the same seed, which is what lets a partitioned
    and a single-WAL store journal the IDENTICAL history."""
    rng = random.Random(f"part-hist-{seed}")
    names = []
    for i in range(3 + rng.randrange(3)):
        ns = f"ns{rng.randrange(4)}"
        name = f"w{seed}-{i}"
        pcs = simple_pcs(
            cliques=[clique("w", replicas=1 + rng.randrange(3))],
            name=name,
        )
        pcs.metadata.namespace = ns
        h.apply(pcs)
        names.append((ns, name))
        if rng.random() < 0.5:
            h.settle()
    h.settle()
    if names and rng.random() < 0.7:
        ns, name = names[rng.randrange(len(names))]
        pcs = h.store.get(PodCliqueSet.KIND, ns, name)
        pcs.spec.replicas = 1 + rng.randrange(2)
        h.store.update(pcs)
        h.settle()
    if len(names) > 1 and rng.random() < 0.7:
        ns, name = names.pop(rng.randrange(len(names)))
        h.store.delete(PodCliqueSet.KIND, ns, name)
        h.settle()
    h.advance(35.0)  # at least one snapshot cadence boundary


class TestPartitionedRoundTrip:
    def test_recover_is_bit_identical_and_merged(self, tmp_path):
        h = part_harness(tmp_path)
        h.apply(simple_pcs(cliques=[clique("w", replicas=3)]))
        h.settle()
        recovered = ObjectStore.recover(str(tmp_path / "wal"))
        stats = recovered.recovery_stats
        assert stats["outcome"] == "clean"
        assert set(stats["partitions"]) == {
            "p000", "p001", "p002", "p003"
        }
        assert_bit_identical(recovered, h.store)

    def test_writes_actually_spread_across_partitions(self, tmp_path):
        h = part_harness(tmp_path)
        seeded_history(h, 0)
        per = [
            p.wal_records_total
            for p in h.cluster.durability.partitions
        ]
        assert sum(1 for n in per if n > 0) >= 2, per
        assert sum(per) == h.cluster.durability.wal_records_total

    def test_cold_restart_settles_to_identical_fixpoint(self, tmp_path):
        h = part_harness(tmp_path)
        h.apply(simple_pcs(cliques=[clique("w", replicas=3)]))
        h.settle()
        fixpoint = settled_fingerprint(h.store)
        stats = h.cold_restart()
        assert stats["outcome"] == "clean"
        h.settle()
        assert settled_fingerprint(h.store) == fixpoint
        assert check_invariants(h.store) == []

    def test_new_process_boot_resumes_partitioned_journal(self, tmp_path):
        cfg = part_config(tmp_path / "wal")
        old = Harness(nodes=make_nodes(NODES), config=cfg)
        old.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        old.settle()
        fixpoint = settled_fingerprint(old.store)
        old.cluster.durability.close()
        del old
        h = Harness.recover(cfg)
        h.settle()
        assert settled_fingerprint(h.store) == fixpoint
        # journaling resumed into the same partition layout
        h.apply(simple_pcs(cliques=[clique("z", replicas=1)],
                           name="after-boot"))
        h.settle()
        again = ObjectStore.recover(str(tmp_path / "wal"))
        assert_bit_identical(again, h.store)


class TestRecoveryEquivalenceGate:
    """The acceptance gate: for 10 seeds, cold recovery from
    partitioned WALs is bit-identical to the single-WAL recovery of the
    SAME write history — objects, retained event log, compaction
    horizon, kind serials, seq/uid counters — including torn-tail and
    corrupt-snapshot cases on individual partitions."""

    SEEDS = tuple(range(10))

    def _pair(self, tmp_path, seed):
        hp = Harness(
            nodes=make_nodes(NODES),
            config=part_config(tmp_path / f"p{seed}"),
        )
        hs = Harness(
            nodes=make_nodes(NODES),
            config={"durability": {
                **DUR, "wal_dir": str(tmp_path / f"s{seed}")
            }},
        )
        for h in (hp, hs):
            seeded_history(h, seed)
        assert hp.store.last_seq == hs.store.last_seq  # same history
        return hp, hs

    @pytest.mark.parametrize("seed", SEEDS)
    def test_partitioned_recovery_matches_single_wal(
        self, seed, tmp_path
    ):
        hp, hs = self._pair(tmp_path, seed)
        rng = random.Random(f"part-fault-{seed}")
        dur = hp.cluster.durability
        case = rng.randrange(3)
        if case == 1:
            # torn tail on ONE partition: the in-flight garbage is
            # unacknowledged, so recovery still yields the full
            # committed history the single WAL recovers
            dur.tear_partition(rng.randrange(dur.num_partitions))
        elif case == 2 and dur.snapshot_seqs():
            # corrupt one partition's newest snapshot: that partition
            # falls back a generation (quarantining the image) and
            # replays the longer suffix — same final store
            dur.corrupt_partition_snapshot(
                rng.randrange(dur.num_partitions)
            )
        rp = ObjectStore.recover(str(tmp_path / f"p{seed}"))
        rs = ObjectStore.recover(str(tmp_path / f"s{seed}"))
        assert_bit_identical(rp, rs)
        assert_bit_identical(rp, hs.store)
        assert settled_fingerprint(rp) == settled_fingerprint(rs)

    def test_every_fault_case_appeared(self, tmp_path):
        """The seeded case draw must actually cover clean, torn and
        corrupt across the matrix (a vacuous gate must not read as
        coverage)."""
        cases = {
            random.Random(f"part-fault-{seed}").randrange(3)
            for seed in self.SEEDS
        }
        assert cases == {0, 1, 2}

    def test_compaction_merges_identically(self, tmp_path):
        hp, hs = self._pair(tmp_path, 99)
        for h in (hp, hs):
            h.compact_events()
            h.apply(simple_pcs(cliques=[clique("after", replicas=1)],
                               name="post-compact"))
            h.settle()
        rp = ObjectStore.recover(str(tmp_path / "p99"))
        rs = ObjectStore.recover(str(tmp_path / "s99"))
        assert rp.compaction_horizon > 0
        assert_bit_identical(rp, rs)


class TestPartitionRouting:
    def test_partition_map_pins_kinds(self, tmp_path):
        cfg = durability_cfg(
            tmp_path / "w", partitions=4,
            partition_map={"Pod": 3, "ns1/Pod": 1},
        )
        log = PartitionedLog(cfg, SimClock())
        assert log.partition_of("default", "Pod") == 3
        assert log.partition_of("anywhere", "Pod") == 3
        # the namespace-qualified pin wins over the bare kind
        assert log.partition_of("ns1", "Pod") == 1

    def test_unpinned_kinds_hash_stably(self, tmp_path):
        cfg = durability_cfg(tmp_path / "w", partitions=4)
        log = PartitionedLog(cfg, SimClock())
        seen = {
            log.partition_of(f"ns{i}", "Pod") for i in range(16)
        }
        assert len(seen) > 1  # namespaces actually spread
        assert log.partition_of("ns0", "Pod") == log.partition_of(
            "ns0", "Pod"
        )

    def test_config_validation(self):
        with pytest.raises(ValueError, match="partitions"):
            load_operator_config(
                {"durability": {"partitions": 0}}
            )
        with pytest.raises(ValueError, match="partition_map"):
            load_operator_config(
                {"durability": {"partitions": 4,
                                "partition_map": {"Pod": 9}}}
            )
        with pytest.raises(ValueError, match="partition_map"):
            load_operator_config(
                {"durability": {"partition_map": {"Pod": 0}}}
            )


class TestLayoutGuards:
    def test_fresh_partitioned_refuses_populated_dir(self, tmp_path):
        part_harness(tmp_path)
        with pytest.raises(DurabilityError, match="already holds"):
            part_harness(tmp_path)

    def test_resume_refuses_changed_partition_count(self, tmp_path):
        cfg = part_config(tmp_path / "wal", partitions=4)
        h = Harness(nodes=make_nodes(4), config=cfg)
        h.cluster.durability.close()
        del h
        with pytest.raises(DurabilityError, match="layout"):
            Harness.recover(part_config(tmp_path / "wal", partitions=2))

    def test_classic_log_refuses_partitioned_dir(self, tmp_path):
        cfg = part_config(tmp_path / "wal", partitions=4)
        h = Harness(nodes=make_nodes(4), config=cfg)
        h.cluster.durability.close()
        del h
        with pytest.raises(DurabilityError, match="partitioned"):
            Harness.recover(
                {"durability": {**DUR, "wal_dir": str(tmp_path / "wal")}}
            )

    def test_partitioned_log_refuses_single_wal_dir(self, tmp_path):
        h = Harness(
            nodes=make_nodes(4),
            config={"durability": {**DUR,
                                   "wal_dir": str(tmp_path / "wal")}},
        )
        h.cluster.durability.close()
        del h
        with pytest.raises(DurabilityError, match="single-WAL"):
            PartitionedLog(
                durability_cfg(tmp_path / "wal", partitions=2),
                SimClock(),
            )

    def test_recovery_refuses_a_vanished_partition_dir(self, tmp_path):
        """A missing pNNN directory is LOST HISTORY, not a smaller
        deployment — recovery must refuse the incomplete set instead of
        handing back a silently holey store."""
        import shutil

        h = part_harness(tmp_path)
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        h.cluster.durability.close()
        shutil.rmtree(tmp_path / "wal" / "p002")
        with pytest.raises(DurabilityError, match="incomplete"):
            ObjectStore.recover(str(tmp_path / "wal"))

    def test_ambiguous_dir_fails_loud(self, tmp_path):
        h = part_harness(tmp_path)
        h.cluster.durability.close()
        # drop a classic segment next to the partition dirs
        (tmp_path / "wal" / f"wal-{0:020d}.log").write_bytes(b"GRVWAL1\n")
        with pytest.raises(DurabilityError, match="BOTH"):
            ObjectStore.recover(str(tmp_path / "wal"))


class TestPartitionMetrics:
    def test_partition_labeled_series_and_totals(self, tmp_path):
        h = part_harness(tmp_path)
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        m = h.cluster.metrics
        ctr = m.counter("grove_store_wal_records_total")
        series = {
            s["partition"] for s in ctr.label_sets() if "partition" in s
        }
        assert len(series) >= 2
        dump = h.debug_dump()["store"]["durability"]
        assert ctr.total() == dump["wal_records_total"]
        assert dump["partitions"] == 4
        assert set(dump["per_partition"]) == {
            "p000", "p001", "p002", "p003"
        }
        assert m.gauge("grove_store_partitions").value() == 4.0

    def test_stale_partition_series_leave_metrics(self, tmp_path):
        """The hygiene regression (the PR 8 shard-series shape): a
        registry that outlives a wider layout must not export dead pNNN
        series forever — PartitionedLog reconciles its families at
        construction."""
        reg = MetricsRegistry()
        for fam in PartitionedLog.METRIC_FAMILIES:
            ctr = reg.counter(fam, "x")
            ctr.inc()  # the unlabeled classic series must survive
            for p in range(4):
                ctr.inc(partition=str(p))
        PartitionedLog(
            durability_cfg(tmp_path / "w", partitions=2), SimClock(),
            metrics=reg,
        )
        for fam in PartitionedLog.METRIC_FAMILIES:
            parts = {
                s.get("partition")
                for s in reg.counter(fam).label_sets()
            }
            assert parts == {None, "0", "1"}, fam


class TestPartitionAwareWriteBatch:
    def test_partition_failure_requeues_without_blocking_others(self):
        """The satellite contract: a failed task on partition A requeues
        (with its slow-start-skipped remainder) while partition B's
        flush lands whole, in enqueue order."""
        done = []

        def ok(name):
            return lambda: done.append(name)

        def boom():
            raise RuntimeError("store down")

        wb = WriteBatch()
        wb.put("a1", "a1", boom, partition_key=("nsa", "Pod"))
        wb.put("a2", "a2", ok("a2"), partition_key=("nsa", "Pod"))
        wb.put("b1", "b1", ok("b1"), partition_key=("nsb", "Pod"))
        wb.put("b2", "b2", ok("b2"), partition_key=("nsb", "Pod"))
        result = wb.flush(
            partition_of=lambda ns, kind: 0 if ns == "nsa" else 1
        )
        assert done == ["b1", "b2"]  # B flushed whole, in order
        assert [n for n, _ in result.errors] == ["a1"]
        assert result.skipped == ["a2"]  # A's remainder slow-start-skips
        assert len(wb) == 2  # a1 + a2 requeued, b tasks are NOT
        # the retry flush (fault cleared) lands the requeued partition
        wb._tasks["a1"][1] = ok("a1")
        retry = wb.flush(
            partition_of=lambda ns, kind: 0 if ns == "nsa" else 1
        )
        assert not retry.has_errors and done == ["b1", "b2", "a1", "a2"]

    def test_unkeyed_tasks_share_the_residual_group(self):
        done = []
        wb = WriteBatch()
        wb.put("a", "a", lambda: done.append("a"),
               partition_key=("ns", "Pod"))
        wb.put("x", "x", lambda: done.append("x"))  # no partition key
        result = wb.flush(partition_of=lambda ns, kind: 7)
        assert not result.has_errors
        assert done == ["a", "x"]  # global enqueue order preserved

    def test_without_partitioner_failure_halts_the_round(self):
        """The classic single-WAL behavior is unchanged: no partitioner
        means one slow-start run over everything."""
        done = []
        wb = WriteBatch()
        wb.put("a1", "a1", lambda: (_ for _ in ()).throw(RuntimeError()),
               partition_key=("nsa", "Pod"))
        wb.put("b1", "b1", lambda: done.append("b1"),
               partition_key=("nsb", "Pod"))
        result = wb.flush()
        assert done == []
        assert result.skipped == ["b1"]
        assert len(wb) == 2

    def test_manager_flush_routes_by_store_partition(self, tmp_path):
        """e2e: a partitioned durable harness with round batching wires
        the durable router into the flush (the settle exercising it
        must land partition-labeled WAL series from batched writes)."""
        h = part_harness(tmp_path)
        assert h.config.controllers.round_write_batching
        assert h.store.durability.partition_of("a", "Pod") is not None
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        recovered = ObjectStore.recover(str(tmp_path / "wal"))
        assert_bit_identical(recovered, h.store)


@pytest.mark.chaos
class TestPartitionedChaos:
    """Partition-scoped faults (partition_wal_divergence: a crash with
    one partition's tail torn while the others keep later committed
    records; partition_disk_stall: one partition's snapshot cadence
    defers) — convergent to the fault-free fixpoint, draw-guarded so
    every pre-existing seed replays bit-identically."""

    SEEDS = (0, 1, 2)

    @pytest.fixture(scope="class")
    def baseline(self):
        h = Harness(nodes=make_nodes(NODES))
        h.apply(simple_pcs(cliques=[clique("w", replicas=3)]))
        h.settle()
        return settled_fingerprint(h.store)

    def _run(self, seed, tmp_path, partitions=4):
        plan = FaultPlan.from_seed(
            seed,
            process_crash_rate=0.12,
            wal_torn_write_rate=0.3,
            snapshot_corruption_rate=0.25,
            partition_divergence_rate=0.25,
            partition_stall_rate=0.2,
        )
        ch = ChaosHarness(
            plan, nodes=make_nodes(NODES),
            config=part_config(tmp_path / f"wal{seed}", partitions),
        )
        quiet = io.StringIO()
        ch.harness.cluster.logger.stream = quiet
        ch.harness.manager.logger.stream = quiet
        ch.apply(simple_pcs(cliques=[clique("w", replicas=3)]))
        ch.run_chaos()
        return ch

    @pytest.mark.parametrize("seed", SEEDS)
    def test_partition_fault_seeds_converge(self, seed, tmp_path, baseline):
        ch = self._run(seed, tmp_path)
        assert settled_fingerprint(ch.raw_store) == baseline, (
            f"seed {seed} diverged (faults: {ch.plan.counts}, "
            f"recoveries: {ch.recovery_stats})"
        )
        assert check_invariants(ch.raw_store) == []

    def test_matrix_fired_partition_faults(self, tmp_path, baseline):
        counts: dict = {}
        for seed in self.SEEDS:
            ch = self._run(seed, tmp_path)
            for k, v in ch.plan.counts.items():
                counts[k] = counts.get(k, 0) + v
        assert counts.get("partition_wal_divergence", 0) > 0
        assert counts.get("partition_disk_stall", 0) > 0

    def test_partition_draws_skipped_on_single_wal(self, tmp_path):
        """Capability guard: the same plan over UNPARTITIONED durability
        must never fire a partition fault (and the draws are skipped
        entirely, keeping single-WAL seeds' sequences intact)."""
        plan = FaultPlan.from_seed(
            0,
            partition_divergence_rate=0.9,
            partition_stall_rate=0.9,
        )
        ch = ChaosHarness(
            plan, nodes=make_nodes(NODES),
            config={"durability": {
                **DUR, "wal_dir": str(tmp_path / "wal")
            }},
        )
        quiet = io.StringIO()
        ch.harness.cluster.logger.stream = quiet
        ch.harness.manager.logger.stream = quiet
        ch.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        ch.run_chaos()
        assert "partition_wal_divergence" not in ch.plan.counts
        assert "partition_disk_stall" not in ch.plan.counts

    def test_seed_is_bit_reproducible(self, tmp_path):
        a = self._run(1, tmp_path / "a")
        b = self._run(1, tmp_path / "b")
        assert a.plan.counts == b.plan.counts
        assert settled_fingerprint(a.raw_store) == settled_fingerprint(
            b.raw_store
        )
