"""Elastic-serving suite: the traffic engine, the metrics pipeline, the
closed scale loop, reservation reuse on scale cycles, and the
traffic-fault chaos convergence contract (grove_tpu/serving/,
docs/operations.md "Elastic serving")."""

import pytest

from grove_tpu.api import ValidationError
from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import (
    AutoScalingConfig,
    Container,
    Pod,
    PodCliqueScalingGroup,
    PodCliqueScalingGroupConfig,
    PodCliqueSet,
    PodCliqueSetSpec,
    PodCliqueSetTemplateSpec,
    PodCliqueSpec,
    PodCliqueTemplateSpec,
    PodSpec,
)
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness
from grove_tpu.serving import (
    PodMetrics,
    SpikeEvent,
    TrafficTrace,
    WorkloadShape,
)

#: a flat trace (base == peak, no noise) whose equilibrium at these
#: numbers is PCSG replicas 3: 126 rps over 2 PCS x 3 PCSG x 3 be-pods
#: x 10 rps/pod = 0.7 utilization, exactly on target
FLAT_SERVING = {
    "serving": {
        "enabled": True,
        "trace": {"base_rps": 126.0, "peak_rps": 126.0, "noise": 0.0},
        "workloads": [
            {"clique": "be", "shape": "decode", "rps_per_replica": 10.0,
             "demand_fraction": 1.0},
        ],
    },
    "autoscaler": {
        "sync_interval_seconds": 10.0,
        "scale_down_stabilization_seconds": 30.0,
    },
}


def serving_workload():
    """The chaos-sweep workload shape with an HPA on the scaling group
    (scripts/chaos_sweep.py sweep_workload(scaled=True))."""
    return PodCliqueSet(
        metadata=ObjectMeta(name="chaos"),
        spec=PodCliqueSetSpec(
            replicas=2,
            template=PodCliqueSetTemplateSpec(
                cliques=[
                    PodCliqueTemplateSpec(
                        name="fe",
                        spec=PodCliqueSpec(
                            replicas=2,
                            pod_spec=PodSpec(containers=[
                                Container(name="m", resources={"cpu": 1.0})
                            ]),
                        ),
                    ),
                    PodCliqueTemplateSpec(
                        name="be",
                        spec=PodCliqueSpec(
                            replicas=3,
                            pod_spec=PodSpec(containers=[
                                Container(name="m", resources={"cpu": 1.0})
                            ]),
                        ),
                    ),
                ],
                pod_clique_scaling_group_configs=[
                    PodCliqueScalingGroupConfig(
                        name="g", clique_names=["be"],
                        replicas=2, min_available=1,
                        scale_config=AutoScalingConfig(
                            min_replicas=1, max_replicas=4,
                            target_utilization=0.7,
                        ),
                    )
                ],
            ),
        ),
    )


def drive_to_equilibrium(h, sweeps=5):
    for _ in range(sweeps):
        h.advance(11.0)
        h.autoscale()


def grp_replicas(h, name="chaos-0-g"):
    return h.store.get(PodCliqueScalingGroup.KIND, "default", name).spec.replicas


class TestTrafficTrace:
    def test_diurnal_swing_spans_base_to_peak(self):
        tr = TrafficTrace(base_rps=100.0, peak_rps=1000.0,
                          period_seconds=3600.0, noise=0.0)
        assert tr.demand(0.0) == pytest.approx(100.0)
        assert tr.demand(1800.0) == pytest.approx(1000.0)
        assert tr.demand(3600.0) == pytest.approx(100.0)

    def test_demand_is_a_pure_function_of_time(self):
        """Calling demand() repeatedly, out of order, or from a second
        identically-configured instance gives bit-identical values —
        the chaos-replay contract."""
        a = TrafficTrace(base_rps=50, peak_rps=500, period_seconds=600,
                         noise=0.2, seed=7)
        b = TrafficTrace(base_rps=50, peak_rps=500, period_seconds=600,
                         noise=0.2, seed=7)
        times = [0.0, 17.0, 599.0, 17.0, 301.5, 0.0]
        assert [a.demand(t) for t in times] == [b.demand(t) for t in reversed(times)][::-1]
        assert a.demand(17.0) == a.demand(17.0)

    def test_noise_draw_depends_on_bucket_not_call_count(self):
        tr = TrafficTrace(base_rps=100, peak_rps=100, noise=0.3, seed=3,
                          sample_seconds=15.0)
        v1 = tr.demand(16.0)
        for _ in range(10):
            tr.demand(500.0)
        assert tr.demand(16.0) == v1
        # different seed, different stream
        assert TrafficTrace(base_rps=100, peak_rps=100, noise=0.3, seed=4,
                            sample_seconds=15.0).demand(16.0) != v1

    def test_spikes_multiply_while_active(self):
        tr = TrafficTrace(
            base_rps=100, peak_rps=100, noise=0.0,
            spikes=[SpikeEvent(at_seconds=10, duration_seconds=5,
                               multiplier=3.0)],
        )
        assert tr.demand(9.9) == pytest.approx(100.0)
        assert tr.demand(12.0) == pytest.approx(300.0)
        assert tr.demand(15.0) == pytest.approx(100.0)

    def test_workload_shape_math(self):
        w = WorkloadShape(clique="d", shape="decode", rps_per_replica=50.0,
                          demand_fraction=0.5)
        assert w.utilization(1000.0, 20) == pytest.approx(0.5)
        assert w.utilization(1000.0, 0) == 1.0  # no capacity = saturated
        assert w.required_pods(1000.0, 0.7) == 15  # 500/(50*0.7)=14.3

    def test_shape_defaults_fill_in(self):
        w = WorkloadShape(clique="p", shape="prefill")
        assert w.rps_per_replica == 25.0
        assert w.demand_fraction == 0.45


class TestPodMetrics:
    def test_staleness_horizon(self):
        pm = PodMetrics(max_age_seconds=30.0)
        pm.report("p", 0.5, now=100.0)
        assert pm.get("p", 120.0) == 0.5
        assert pm.get("p", 131.0) is None
        assert pm.get("ghost", 0.0) is None

    def test_gc_drops_dead_pods(self):
        pm = PodMetrics()
        for i in range(5):
            pm.report(f"p{i}", 0.1, now=0.0)
        live = {("default", "p0"), ("default", "p3")}
        assert pm.gc(live) == 3
        assert len(pm) == 2

    def test_namespaced_pods_do_not_collide(self):
        """Same-named pods in two namespaces keep independent samples —
        a name-keyed map would let one tier's reports overwrite the
        other's and cross-scale the HPAs."""
        pm = PodMetrics()
        pm.report("serve-0-w-0", 0.2, now=0.0, namespace="a")
        pm.report("serve-0-w-0", 0.9, now=0.0, namespace="b")
        assert pm.get("serve-0-w-0", 0.0, namespace="a") == 0.2
        assert pm.get("serve-0-w-0", 0.0, namespace="b") == 0.9

    def test_dropout_suppresses_reports(self):
        pm = PodMetrics()
        pm.dropout_steps = 2
        pm.report("p", 0.5, now=0.0)
        assert pm.get("p", 0.0) is None
        assert pm.dropped_total == 1
        pm.tick_dropout()
        pm.tick_dropout()
        pm.report("p", 0.5, now=1.0)
        assert pm.get("p", 1.0) == 0.5


class TestServingConfig:
    def test_enabled_requires_workloads(self):
        with pytest.raises(ValidationError, match="workloads"):
            Harness(nodes=make_nodes(4),
                    config={"serving": {"enabled": True}})

    def test_bad_trace_rejected(self):
        from grove_tpu.api.config import load_operator_config

        with pytest.raises(ValidationError) as exc:
            load_operator_config({"serving": {"trace": {
                "base_rps": 100.0, "peak_rps": 50.0, "noise": -1,
                "bogus": 1,
            }}})
        msg = str(exc.value)
        assert "peak_rps" in msg and "noise" in msg and "bogus" in msg

    def test_bad_workload_rejected(self):
        from grove_tpu.api.config import load_operator_config

        with pytest.raises(ValidationError) as exc:
            load_operator_config({"serving": {"workloads": [
                {"clique": "a", "shape": "nosuch"},
                {"clique": "a", "demand_fraction": 2.0},
                {"shape": "decode"},
            ]}})
        msg = str(exc.value)
        assert "shape" in msg and "duplicate" in msg and "clique" in msg


class TestScaleLoop:
    """The closed loop: trace -> kubelet reporting -> aggregation ->
    HPA sync -> scale subresource -> scaled PodGangs -> bound pods."""

    def test_kubelet_reports_into_the_pipeline(self):
        h = Harness(nodes=make_nodes(24), config=FLAT_SERVING)
        h.apply(serving_workload())
        h.settle()
        pipeline = h.cluster.pod_metrics
        assert len(pipeline) > 0
        # only the configured tier's pods report (fe has no workload)
        be_pods = {
            (p.metadata.namespace, p.metadata.name)
            for p in h.store.list(Pod.KIND)
            if "-g-" in p.metadata.name
        }
        assert set(pipeline._samples) <= {
            (p.metadata.namespace, p.metadata.name)
            for p in h.store.list(Pod.KIND)
        }
        assert be_pods & set(pipeline._samples)
        assert h.cluster.metrics.gauge(
            "grove_serving_demand_rps"
        ).value() == pytest.approx(126.0)

    def test_traffic_drives_scale_to_equilibrium(self):
        h = Harness(nodes=make_nodes(24), config=FLAT_SERVING)
        h.apply(serving_workload())
        h.settle()
        drive_to_equilibrium(h)
        assert grp_replicas(h, "chaos-0-g") == 3
        assert grp_replicas(h, "chaos-1-g") == 3
        # the loop created the scaled gangs and bound their pods
        gangs = sorted(g.metadata.name for g in h.store.list("PodGang"))
        assert "chaos-0-g-1" in gangs
        assert all(p.status.ready for p in h.store.list(Pod.KIND))

    def test_spike_scales_up_then_stabilizes_back(self):
        h = Harness(nodes=make_nodes(24), config=FLAT_SERVING)
        h.apply(serving_workload())
        h.settle()
        drive_to_equilibrium(h)
        h.cluster.serving.inject_spike(h.clock.now(), 60.0, 3.0)
        h.advance(11.0)
        h.autoscale()
        assert grp_replicas(h) == 4  # clamped at max
        h.cluster.serving.clear_injected()
        # past the stabilization window the fleet returns to equilibrium
        h.advance(45.0)
        h.autoscale()
        drive_to_equilibrium(h, sweeps=2)
        assert grp_replicas(h) == 3

    def test_dropout_holds_the_fleet(self):
        h = Harness(nodes=make_nodes(24), config=FLAT_SERVING)
        h.apply(serving_workload())
        h.settle()
        drive_to_equilibrium(h)
        pm = h.cluster.pod_metrics
        pm.dropout_steps = 10**6  # pipeline outage
        # make every sample stale: without fresh metrics the HPA must
        # HOLD at 3, not collapse to min
        h.advance(200.0)
        h.autoscale()
        h.advance(11.0)
        h.autoscale()
        assert grp_replicas(h) == 3
        pm.dropout_steps = 0
        drive_to_equilibrium(h, sweeps=2)
        assert grp_replicas(h) == 3

    def test_hpa_sync_cadence_is_config_driven(self):
        h = Harness(nodes=make_nodes(24), config=FLAT_SERVING)
        h.apply(serving_workload())
        h.settle()
        assert h.maybe_autoscale() is True   # first opportunity sweeps
        assert h.maybe_autoscale() is False  # same instant: not due
        h.advance(9.0)
        assert h.maybe_autoscale() is False  # inside the 10s interval
        h.advance(2.0)
        assert h.maybe_autoscale() is True

    def test_debug_dump_carries_serving_section(self):
        h = Harness(nodes=make_nodes(24), config=FLAT_SERVING)
        h.apply(serving_workload())
        h.settle()
        dump = h.debug_dump()["serving"]
        assert dump["trace"]["base_rps"] == 126.0
        assert dump["workloads"][0]["clique"] == "be"
        assert dump["pipeline"]["samples"] > 0


class TestReservationReuseOnScaleCycle:
    def one_pcs(self):
        return PodCliqueSet(
            metadata=ObjectMeta(name="s"),
            spec=PodCliqueSetSpec(
                replicas=1,
                template=PodCliqueSetTemplateSpec(
                    cliques=[PodCliqueTemplateSpec(
                        name="w",
                        spec=PodCliqueSpec(
                            replicas=3,
                            pod_spec=PodSpec(containers=[
                                Container(name="m", resources={"cpu": 1.0})
                            ]),
                        ),
                    )],
                    pod_clique_scaling_group_configs=[
                        PodCliqueScalingGroupConfig(
                            name="g", clique_names=["w"],
                            replicas=3, min_available=1,
                        )
                    ],
                ),
            ),
        )

    def scale(self, h, replicas):
        pcsg = h.store.get(PodCliqueScalingGroup.KIND, "default", "s-0-g")
        pcsg.spec.replicas = replicas
        h.store.update(pcsg)
        h.settle()

    def placements(self, h):
        return {
            p.metadata.name: p.node_name
            for p in h.store.list(Pod.KIND)
            if "-g-" in p.metadata.name
        }

    def test_scale_cycle_reuses_vacated_slots(self):
        h = Harness(nodes=make_nodes(
            24, allocatable={"cpu": 1.0, "memory": 8.0, "tpu": 0.0}
        ))
        h.apply(self.one_pcs())
        h.settle()
        before = self.placements(h)
        self.scale(h, 1)   # trough: scaled gangs deleted
        self.scale(h, 3)   # ramp: same-named gangs recreated
        ctr = h.cluster.metrics.counter(
            "grove_scheduler_reservation_reuse_total"
        )
        assert ctr.value(outcome="hit") == 2  # both scaled gangs
        assert self.placements(h) == before  # topology-stable

    def test_reuse_disabled_by_config(self):
        h = Harness(
            nodes=make_nodes(
                24, allocatable={"cpu": 1.0, "memory": 8.0, "tpu": 0.0}
            ),
            config={"solver": {"reservation_reuse": False}},
        )
        h.apply(self.one_pcs())
        h.settle()
        self.scale(h, 1)
        self.scale(h, 3)
        ctr = h.cluster.metrics.counter(
            "grove_scheduler_reservation_reuse_total"
        )
        assert ctr.total() == 0  # the pre-pass never ran
        # the workload still converges through the general solve
        assert all(p.status.ready for p in h.store.list(Pod.KIND))


class TestServingChaos:
    """The acceptance contract: with traffic_spike/metrics_dropout armed
    the chaotic run must converge to the fault-free traffic-driven
    equilibrium once the faults leave at disarm (the wide sweep is
    scripts/chaos_sweep.py --serving)."""

    def baseline(self):
        h = Harness(nodes=make_nodes(24), config=FLAT_SERVING)
        h.apply(serving_workload())
        h.settle()
        for _ in range(4):
            h.advance(11.0)
            h.autoscale()
        from grove_tpu.chaos import settled_fingerprint

        return settled_fingerprint(h.store)

    def run_seed(self, seed):
        from grove_tpu.chaos import ChaosHarness, FaultPlan

        plan = FaultPlan.from_seed(
            seed, traffic_spike_rate=0.3, metrics_dropout_rate=0.25,
        )
        ch = ChaosHarness(plan, nodes=make_nodes(24), config=FLAT_SERVING)
        ch.apply(serving_workload())
        ch.settle()
        for _ in range(4):
            ch.harness.advance(11.0)
            ch.harness.autoscale()
        ch.run_chaos()
        return ch, plan

    @pytest.mark.parametrize("seed", [1, 5])
    def test_traffic_faults_converge_to_fault_free_fixpoint(self, seed):
        from grove_tpu.chaos import check_invariants, settled_fingerprint

        baseline = self.baseline()
        ch, plan = self.run_seed(seed)
        assert settled_fingerprint(ch.raw_store) == baseline
        assert check_invariants(ch.raw_store) == []
        # the seed actually exercised the serving fault vocabulary
        assert (
            plan.counts.get("traffic_spike", 0)
            + plan.counts.get("metrics_dropout", 0)
        ) > 0
        # disarm repair really ran
        assert ch.harness.cluster.serving.injected_spikes == ()
        assert ch.harness.cluster.pod_metrics.dropout_steps == 0

    def test_rate_zero_plans_never_draw_serving_faults(self):
        """Pre-existing seeds' draw sequences are untouched: a plan with
        the default 0 rates injects nothing even with serving armed."""
        from grove_tpu.chaos import ChaosHarness, FaultPlan

        plan = FaultPlan.from_seed(3)
        ch = ChaosHarness(plan, nodes=make_nodes(24), config=FLAT_SERVING)
        ch.apply(serving_workload())
        ch.run_chaos()
        assert "traffic_spike" not in plan.counts
        assert "metrics_dropout" not in plan.counts
