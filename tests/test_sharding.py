"""Horizontally sharded control plane (controller/sharding.py): shard-map
handoff edges, the ownership invariant, lease-based failover, dedication,
metric series hygiene, and the round write batcher."""

import pytest

from grove_tpu.api.types import Pod
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness
from grove_tpu.controller.concurrency import WriteBatch
from grove_tpu.controller.sharding import (
    SHARD_MAP_NAME,
    SHARD_NAMESPACE,
    ShardMap,
    shard_of,
)

from test_e2e_basic import clique, simple_pcs

SHARDED = {"controllers": {"shards": 4, "shard_lease_duration_seconds": 10.0}}


def sharded_harness(nodes=16, **cfg):
    config = {"controllers": {**SHARDED["controllers"], **cfg}}
    h = Harness(nodes=make_nodes(nodes), config=config)
    h.manager.audit = True  # every round asserts single ownership
    return h


def shard_map(h) -> ShardMap:
    return h.store.get(ShardMap.KIND, SHARD_NAMESPACE, SHARD_MAP_NAME)


# -- basics ----------------------------------------------------------------
def test_sharded_settle_reaches_single_replica_state():
    h = sharded_harness()
    h.apply(simple_pcs(cliques=[clique("w", replicas=2),
                                clique("x", replicas=3)]))
    h.settle()
    pods = h.store.list(Pod.KIND)
    assert len(pods) == 5 and all(p.node_name and p.status.ready
                                  for p in pods)


def test_shard_of_is_stable_and_scheduler_reserved():
    n = h_num = 64
    assert shard_of("default", "a", n) == shard_of("default", "a", n)
    assert 0 <= shard_of("ns", "name", n) < n
    # the gang scheduler's singleton maps to the RESERVED shard one past
    # the hash range (its owner stays dedicated)
    assert shard_of("", "schedule", h_num) == h_num


def test_bootstrap_map_covers_every_shard_once():
    h = sharded_harness()
    m = shard_map(h)
    assert m is not None and m.epoch >= 1
    idents = {w.identity for w in h.manager.workers}
    assert set(m.assignments) == set(h.manager.all_shards)
    assert set(m.assignments.values()) <= idents
    # dedication: the scheduler shard's owner holds ONLY that shard
    sched_owner = m.assignments[h.manager.scheduler_shard]
    others = [s for s, w in m.assignments.items()
              if w == sched_owner and s != h.manager.scheduler_shard]
    assert others == []


def test_ownership_audit_runs_clean_through_settles():
    h = sharded_harness()
    for i in range(3):
        h.apply(simple_pcs(name=f"a{i}", cliques=[clique("w", replicas=2)]))
        h.settle()
    pods = h.store.list(Pod.KIND)
    assert len(pods) == 6 and all(p.status.ready for p in pods)


# -- failover --------------------------------------------------------------
def test_crashed_worker_shards_fail_over_within_lease_duration():
    h = sharded_harness()
    h.settle()
    sm = h.manager
    _s, owner = sm.shard_owner("", "schedule")
    idx = next(w.index for w in sm.workers if w.identity == owner)
    assert sm.kill_worker(idx)
    t0 = h.clock.now()
    h.apply(simple_pcs(name="fo", cliques=[clique("w", replicas=2)]))
    h.settle()
    # scheduler shard orphaned: nothing binds until the lease expires
    assert all(not p.node_name for p in h.store.scan(Pod.KIND))
    lease = h.config.controllers.shard_lease_duration_seconds
    h.advance(lease + 1.0)
    h.settle()
    pods = h.store.scan(Pod.KIND)
    assert pods and all(p.node_name and p.status.ready for p in pods)
    assert h.clock.now() - t0 <= lease + 2.0  # bounded by one lease
    _s, new_owner = sm.shard_owner("", "schedule")
    assert new_owner and new_owner != owner


def test_kill_refuses_last_live_worker():
    h = sharded_harness()
    sm = h.manager
    assert sm.kill_worker(0) and sm.kill_worker(1) and sm.kill_worker(2)
    assert not sm.kill_worker(3)  # a survivor must remain
    assert sm.workers[3].alive


def test_revived_worker_rejoins_and_rebalances():
    h = sharded_harness()
    h.settle()
    sm = h.manager
    assert sm.kill_worker(1)
    h.advance(11.0)
    h.settle()
    m = shard_map(h)
    assert "worker-1" not in m.assignments.values()
    sm.revive_worker(1)
    h.advance(1.0)
    h.settle()
    h.advance(1.0)
    h.settle()
    m = shard_map(h)
    assert "worker-1" in m.assignments.values()  # rebalanced back in
    h.apply(simple_pcs(name="post", cliques=[clique("w", replicas=2)]))
    h.settle()
    assert all(p.status.ready for p in h.store.list(Pod.KIND))


# -- handoff edges ---------------------------------------------------------
def test_rebalance_is_two_phase_and_never_double_reconciles():
    """A live->live move waits in `pending` until the CURRENT owner
    releases; until then the successor does not serve it (audit armed
    throughout — a double reconcile in one pass raises)."""
    h = sharded_harness()
    h.settle()
    sm = h.manager
    # revoke every shard of worker-0 (as a handoff storm would)
    moves = sm.chaos_revoke_worker(0)
    assert moves > 0
    m = shard_map(h)
    assert m.pending  # decided, not yet transferred
    for s, target in m.pending.items():
        assert m.assignments[s] == "worker-0" and target != "worker-0"
    # drive work through the storm: the audit would catch any overlap
    h.apply(simple_pcs(name="storm", cliques=[clique("w", replicas=3)]))
    h.settle()
    m = shard_map(h)
    assert not any(
        owner == "worker-0" for s, owner in m.assignments.items()
        if s != sm.scheduler_shard
    ) or not m.pending  # releases completed (or still draining cleanly)
    assert all(p.status.ready for p in h.store.list(Pod.KIND))


def test_stale_map_worker_defers_rather_than_fighting():
    """A worker whose map view is frozen keeps serving its own shards
    only while the view is younger than one lease duration; past that it
    serves NOTHING (owned empty) until a fresh read succeeds — and its
    shards, never released, stay assigned to it (no fight)."""
    h = sharded_harness()
    h.settle()
    sm = h.manager
    w = sm.workers[0]
    owned_before = set(w.owned)
    assert owned_before
    w.stale_map_hold = 1000  # freeze refreshes
    # within one lease duration: still serving the cached shards
    h.apply(simple_pcs(name="st1", cliques=[clique("w", replicas=2)]))
    h.settle()
    assert w.owned == owned_before
    # age the view past the lease duration: the worker defers
    h.advance(sm.lease_duration + 1.0)
    h.settle()
    assert w.owned == set()
    assert w.deferred_rounds > 0
    # its lease kept renewing (steps still run), so the leader did NOT
    # reassign its shards out from under it
    m = shard_map(h)
    assert any(v == w.identity for v in m.assignments.values())
    # thaw: the worker relists its shards back in and work completes
    w.stale_map_hold = 0
    h.apply(simple_pcs(name="st2", cliques=[clique("w", replicas=2)]))
    h.settle()
    assert w.owned == owned_before
    assert all(p.status.ready for p in h.store.list(Pod.KIND))


def test_clean_shutdown_releases_shards_immediately():
    """release-on-cancel analog: stop_worker hands shards to survivors
    in one map write — no lease wait — and its metric series leave
    /metrics."""
    h = sharded_harness()
    h.settle()
    sm = h.manager
    gauge = h.cluster.metrics.gauge("grove_manager_shard_assignments")
    assert any(
        ls.get("shard") == "worker-0" for ls in gauge.label_sets()
    )
    sm.stop_worker(0)
    m = shard_map(h)
    assert "worker-0" not in m.assignments.values()
    # immediately serviceable: no clock advance needed
    h.apply(simple_pcs(name="cs", cliques=[clique("w", replicas=2)]))
    h.settle()
    assert all(p.status.ready for p in h.store.list(Pod.KIND))
    # series hygiene (regression): the departed worker's gauge AND
    # handoff-counter series are gone from the exposition
    assert not any(
        ls.get("shard") == "worker-0" for ls in gauge.label_sets()
    )
    hand = h.cluster.metrics.counter("grove_manager_shard_handoffs_total")
    assert not any(
        dict(k).get("shard") == "worker-0" for k in hand._values
    )
    rendered = h.cluster.metrics.render()
    assert 'shard="worker-0"' not in rendered


def test_assignment_gauge_tracks_the_map():
    h = sharded_harness()
    h.settle()
    m = shard_map(h)
    gauge = h.cluster.metrics.gauge("grove_manager_shard_assignments")
    counts = {}
    for owner in m.assignments.values():
        counts[owner] = counts.get(owner, 0) + 1
    for ident, n in counts.items():
        assert gauge.value(shard=ident) == float(n)
    # manager-scoped gauges export PER-WORKER series under sharding (an
    # unlabeled shared gauge would be last-writer-wins across replicas)
    depth = h.cluster.metrics.gauge("grove_manager_workqueue_depth")
    workers = {
        ls.get("worker") for ls in depth.label_sets() if "worker" in ls
    }
    assert workers == {w.identity for w in h.manager.workers}


def test_crashed_worker_retains_series_until_reassigned_then_updates():
    h = sharded_harness()
    h.settle()
    sm = h.manager
    assert sm.kill_worker(2)
    h.advance(11.0)
    h.settle()
    gauge = h.cluster.metrics.gauge("grove_manager_shard_assignments")
    # shards moved: the dead worker owns nothing, survivors grew
    assert not any(
        ls.get("shard") == "worker-2" for ls in gauge.label_sets()
    )


# -- surfaces --------------------------------------------------------------
def test_debug_dump_carries_sharding_section():
    h = sharded_harness()
    h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
    h.settle()
    d = h.debug_dump()
    sharding = d["sharding"]
    assert sharding["num_shards"] == 4 * 16
    assert sharding["map_epoch"] >= 1
    assert len(sharding["workers"]) == 4
    assert sharding["coordinator"] in {
        w["identity"] for w in sharding["workers"]
    }
    owned = [s for w in sharding["workers"] for s in w["owned_shards"]]
    assert len(owned) == len(set(owned))  # disjoint ownership


def test_single_replica_mode_is_unchanged():
    """shards=1 keeps the classic ControllerManager (no ShardMap, no
    worker leases)."""
    h = Harness(nodes=make_nodes(8))
    h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
    h.settle()
    assert shard_map(h) is None
    assert not hasattr(h.manager, "workers")


def test_config_validation():
    from grove_tpu.api.config import load_operator_config
    from grove_tpu.api.validation import ValidationError

    with pytest.raises(ValidationError, match="shards"):
        load_operator_config({"controllers": {"shards": 0}})
    with pytest.raises(ValidationError, match="shard_lease_duration"):
        load_operator_config(
            {"controllers": {"shards": 2,
                             "shard_lease_duration_seconds": 0}}
        )
    with pytest.raises(ValidationError, match="round_write_batching"):
        load_operator_config(
            {"controllers": {"round_write_batching": "yes"}}
        )
    with pytest.raises(ValidationError, match="incompatible"):
        load_operator_config({
            "controllers": {"shards": 2},
            "leader_election": {"enabled": True},
        })


# -- standby observability (satellite fix) ---------------------------------
def test_standby_is_distinguishable_from_wedged():
    """A healthy standby surfaces standing_by=True in the resilience
    dump and grove_manager_is_leader=0; the leader reads 1."""
    leader = Harness(
        nodes=make_nodes(8),
        config={"leader_election": {"enabled": True}},
    )
    standby = Harness(cluster=leader.cluster)
    leader.manager.run_once()  # acquires
    assert standby.manager.run_once() == 0
    assert standby.manager.resilience_snapshot()["standing_by"] is True
    assert leader.manager.resilience_snapshot()["standing_by"] is False
    dump = standby.debug_dump()
    assert dump["manager"]["resilience"]["standing_by"] is True
    assert dump["manager"]["is_leader"] is False
    gauge = leader.cluster.metrics.gauge("grove_manager_is_leader")
    assert gauge.value() in (0.0, 1.0)


# -- round write batcher ---------------------------------------------------
def test_write_batch_coalesces_and_flushes():
    calls = []
    b = WriteBatch()
    assert not b.put("k1", "t1", lambda: calls.append("a"))
    assert b.put("k1", "t1", lambda: calls.append("b"))  # coalesced
    assert not b.append("k2", "t2", lambda items: calls.append(items), 1)
    assert b.append("k2", "t2", None, 2)  # merged into k2's item list
    result = b.flush()
    assert calls == ["b", [1, 2]]
    assert len(result.succeeded) == 2 and not result.has_errors
    assert len(b) == 0


def test_write_batch_requeues_failures_for_next_flush():
    state = {"fail": True}

    def task():
        if state["fail"]:
            raise RuntimeError("transient")

    b = WriteBatch()
    b.put("k", "t", task)
    result = b.flush()
    assert result.has_errors and len(b) == 1  # requeued
    state["fail"] = False
    result = b.flush()
    assert not result.has_errors and len(b) == 0


def test_event_records_compact_through_round_batch():
    """N identical records within one round land as ONE store write with
    count=N (the dedup compaction, amortized)."""
    from grove_tpu.observability.events import ClusterEvent, EventRecorder

    h = Harness(nodes=make_nodes(4))
    rec = EventRecorder(h.store, controller="t")
    batch = WriteBatch()
    rec.batch = batch
    pcs = simple_pcs(cliques=[clique("w", replicas=1)])
    h.apply(pcs)
    before = h.store.last_seq
    for _ in range(5):
        rec.normal(pcs, "TestReason", "msg")
    assert h.store.last_seq == before  # nothing landed yet
    batch.flush()
    events = [
        e for e in h.store.scan(ClusterEvent.KIND)
        if e.reason == "TestReason"
    ]
    assert len(events) == 1 and events[0].count == 5
    event_writes = [
        e for e in h.store.events_since(before) if e.kind == "Event"
    ]
    assert len(event_writes) == 1  # one write for five records
