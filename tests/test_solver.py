"""Solver tests: exact fit primitives, serial baseline, TPU engine parity."""

import numpy as np
import pytest

from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import Node, TopologyLevel
from grove_tpu.solver import (
    PlacementEngine,
    SolverGang,
    place_gang_in_domain,
    placement_score_for_nodes,
    solve_serial,
)
from grove_tpu.topology import default_cluster_topology, encode_topology


def make_node(name, labels, cpu=8.0, mem=32.0, tpu=4.0):
    return Node(
        metadata=ObjectMeta(name=name, labels=dict(labels)),
        allocatable={"cpu": cpu, "memory": mem, "tpu": tpu},
    )


def cluster(blocks=2, racks=2, hosts=2, cpu=8.0, tpu=4.0):
    """blocks x racks x hosts nodes with block/rack topology."""
    nodes = []
    for b in range(blocks):
        for r in range(racks):
            for h in range(hosts):
                nodes.append(
                    make_node(
                        f"n{b}{r}{h}",
                        {"t/block": f"b{b}", "t/rack": f"r{r}"},
                        cpu=cpu,
                        tpu=tpu,
                    )
                )
    ct = default_cluster_topology(
        [
            TopologyLevel(domain="block", key="t/block"),
            TopologyLevel(domain="rack", key="t/rack"),
        ]
    )
    return encode_topology(ct, nodes)


def gang(name, pods, cpu=1.0, tpu=0.0, required=-1, preferred=-1,
         group_levels=None, priority=0.0, snap=None):
    """Uniform-pod gang; group_levels: list of (pod_count, req, pref)."""
    if group_levels is None:
        group_levels = [(pods, -1, -1)]
    demand, gids, greq, gpref, names = [], [], [], [], []
    for gi, (cnt, req, pref) in enumerate(group_levels):
        for _ in range(cnt):
            demand.append([cpu, 1.0, tpu])
            gids.append(gi)
        greq.append(req)
        gpref.append(pref)
        names.append(f"g{gi}")
    return SolverGang(
        name=name,
        namespace="default",
        demand=np.asarray(demand, dtype=np.float32),
        pod_names=[f"{name}-p{i}" for i in range(len(demand))],
        group_ids=np.asarray(gids, dtype=np.int32),
        group_names=names,
        group_required_level=np.asarray(greq, dtype=np.int32),
        group_preferred_level=np.asarray(gpref, dtype=np.int32),
        required_level=required,
        preferred_level=preferred,
        priority=priority,
    )


class TestFitPrimitives:
    def test_simple_placement_packs_one_host(self):
        snap = cluster()
        free = snap.free.copy()
        g = gang("g", pods=2, cpu=2.0)
        nodes = np.arange(snap.num_nodes)
        assign = place_gang_in_domain(g, snap, free, nodes)
        assert assign is not None
        # both pods fit one host and BFD packs tightest -> same node
        assert assign[0] == assign[1]
        ci = snap.resource_names.index("cpu")
        assert free[assign[0], ci] == pytest.approx(4.0)

    def test_infeasible_returns_none_and_rolls_back(self):
        snap = cluster(blocks=1, racks=1, hosts=1)
        free = snap.free.copy()
        before = free.copy()
        g = gang("g", pods=3, cpu=4.0)  # 12 cpu > 8 on the only host
        assign = place_gang_in_domain(g, snap, free, np.arange(1))
        assert assign is None
        np.testing.assert_allclose(free, before)  # no partial commit

    def test_group_required_level_within_gang_domain(self):
        snap = cluster()  # levels: block=0, rack=1, host=2
        free = snap.free.copy()
        # two groups of 2 pods; each group must pack in ONE rack
        g = gang("g", pods=4, cpu=6.0,
                 group_levels=[(2, 1, -1), (2, 1, -1)], required=0)
        assign = place_gang_in_domain(g, snap, free, np.arange(snap.num_nodes), -1)
        assert assign is not None
        rack_ids = snap.domain_ids[1, assign]
        assert rack_ids[0] == rack_ids[1]
        assert rack_ids[2] == rack_ids[3]
        block_ids = snap.domain_ids[0, assign]
        assert len(set(block_ids.tolist())) == 1  # gang required block

    def test_placement_score(self):
        snap = cluster()
        # one host => 1.0 (4 levels incl host: L=3 -> (2+2)/(3+1)=1)
        assert placement_score_for_nodes(snap, np.array([0, 0])) == 1.0
        # same rack, different host
        s_rack = placement_score_for_nodes(snap, np.array([0, 1]))
        # same block, different rack
        s_block = placement_score_for_nodes(snap, np.array([0, 2]))
        # different blocks
        s_root = placement_score_for_nodes(snap, np.array([0, 4]))
        assert 0 < s_root < s_block < s_rack < 1.0


class TestSerial:
    def test_packs_narrowest_and_scores(self):
        snap = cluster()
        res = solve_serial(snap, [gang("a", pods=2, cpu=2.0)])
        assert res.num_placed == 1
        assert res.placed["a"].placement_score == 1.0  # fits one host

    def test_all_or_nothing_capacity(self):
        snap = cluster(blocks=1, racks=1, hosts=2, cpu=8.0)
        # gang of 3 x 6cpu: only 2 hosts of 8 => infeasible as a gang
        res = solve_serial(snap, [gang("a", pods=3, cpu=6.0)])
        assert res.num_placed == 0
        assert "a" in res.unplaced

    def test_required_level_unsatisfiable(self):
        snap = cluster(hosts=2, cpu=8.0)
        # 4 pods x 6 cpu can't fit one rack (2 hosts x 8 cpu)
        res = solve_serial(snap, [gang("a", pods=4, cpu=6.0, required=1)])
        assert res.num_placed == 0
        # relax to block level: 4 hosts available
        res2 = solve_serial(snap, [gang("a", pods=4, cpu=6.0, required=0)])
        assert res2.num_placed == 1
        blocks = snap.domain_ids[0, res2.placed["a"].node_indices]
        assert len(set(blocks.tolist())) == 1

    def test_priority_order_under_contention(self):
        snap = cluster(blocks=1, racks=1, hosts=1, cpu=8.0)
        low = gang("low", pods=1, cpu=6.0, priority=0.0)
        high = gang("high", pods=1, cpu=6.0, priority=10.0)
        res = solve_serial(snap, [low, high])
        assert "high" in res.placed
        assert "low" in res.unplaced

    def test_contention_spills_to_other_racks(self):
        snap = cluster(blocks=1, racks=2, hosts=2, cpu=8.0)
        gangs = [gang(f"g{i}", pods=2, cpu=8.0, required=1) for i in range(2)]
        res = solve_serial(snap, gangs)
        assert res.num_placed == 2
        racks = {
            name: set(snap.domain_ids[1, p.node_indices].tolist())
            for name, p in res.placed.items()
        }
        assert racks["g0"].isdisjoint(racks["g1"])


class TestEngineParity:
    """The TPU path must match serial hard-feasibility outcomes."""

    def test_engine_places_like_serial(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [
            gang("a", pods=2, cpu=2.0),
            gang("b", pods=4, cpu=6.0, required=1),
            gang("c", pods=3, cpu=3.0, preferred=2),
        ]
        serial = solve_serial(snap, gangs)
        eng = PlacementEngine(snap).solve(gangs)
        assert set(eng.placed) == set(serial.placed)
        for name in eng.placed:
            assert eng.placed[name].placement_score == pytest.approx(
                serial.placed[name].placement_score
            )

    def test_engine_respects_capacity_all_or_nothing(self):
        snap = cluster(blocks=1, racks=1, hosts=2, cpu=8.0)
        res = PlacementEngine(snap).solve([gang("a", pods=3, cpu=6.0)])
        assert res.num_placed == 0

    def test_engine_contention_many_gangs(self):
        snap = cluster(blocks=2, racks=4, hosts=2, cpu=8.0, tpu=4.0)
        gangs = [
            gang(f"g{i}", pods=2, cpu=4.0, tpu=2.0, required=1)
            for i in range(8)
        ]  # 8 gangs x 2 pods, each rack fits exactly one gang's 2 pods...
        res = PlacementEngine(snap).solve(gangs)
        serial = solve_serial(snap, gangs)
        assert res.num_placed == serial.num_placed
        # capacity never violated
        used = np.zeros_like(snap.free)
        for p in res.placed.values():
            for pod_i, n in enumerate(p.node_indices):
                used[n] += p.gang.demand[pod_i]
        assert (used <= snap.free + 1e-6).all()

    def test_engine_group_constraints(self):
        snap = cluster(blocks=2, racks=2, hosts=2, cpu=8.0)
        g = gang("g", pods=4, cpu=6.0,
                 group_levels=[(2, 1, -1), (2, 1, -1)], required=0)
        res = PlacementEngine(snap).solve([g])
        assert res.num_placed == 1
        rack_ids = snap.domain_ids[1, res.placed["g"].node_indices]
        assert rack_ids[0] == rack_ids[1]
        assert rack_ids[2] == rack_ids[3]


class TestRequiredLevelGating:
    """A REQUIRED pack level missing from the topology must hold the gang
    (solver/problem.py UNRESOLVED_LEVEL), never weaken to unconstrained."""

    def test_pre_declared_unschedulable_held_by_both_paths(self):
        snap = cluster()
        held = gang("held", pods=2, cpu=1.0)
        held.unschedulable_reason = "required topology level(s) unavailable: t/zone"
        ok = gang("ok", pods=2, cpu=1.0)
        eng = PlacementEngine(snap).solve([held, ok])
        assert eng.unplaced["held"] == held.unschedulable_reason
        assert "ok" in eng.placed
        ser = solve_serial(snap, [held, ok])
        assert ser.unplaced["held"] == held.unschedulable_reason
        assert "ok" in ser.placed

    def test_encode_marks_unknown_required_key(self):
        from grove_tpu.api.meta import NamespacedName, ObjectMeta
        from grove_tpu.api.podgang import (
            PodGang,
            PodGangSpec,
            PodGroup,
            TopologyConstraint,
            TopologyPackConstraint,
        )
        from grove_tpu.solver import encode_podgangs

        snap = cluster()
        demand = np.array([1.0, 1.0, 0.0], np.float32)

        def pg(name, required):
            return PodGang(
                metadata=ObjectMeta(name=name, namespace="default"),
                spec=PodGangSpec(
                    pod_groups=[
                        PodGroup(
                            name="w",
                            min_replicas=1,
                            pod_references=[
                                NamespacedName(namespace="default", name=f"{name}-p0")
                            ],
                        )
                    ],
                    topology_constraint=TopologyConstraint(
                        pack_constraint=TopologyPackConstraint(required=required)
                    ),
                ),
            )

        out = encode_podgangs(
            [pg("bad", "unresolved:zone"), pg("good", "t/rack")],
            snap,
            lambda ns, n: demand,
        )
        by_name = {g.name: g for g in out}
        assert "unavailable" in by_name["bad"].unschedulable_reason
        assert by_name["good"].unschedulable_reason is None
        assert by_name["good"].required_level == snap.level_index("t/rack")
        # unknown PREFERRED stays best-effort (-1), not unschedulable
        bad_pref = pg("pref", "t/rack")
        bad_pref.spec.topology_constraint.pack_constraint.preferred = "nope"
        bad_pref.spec.topology_constraint.pack_constraint.required = None
        (enc,) = encode_podgangs([bad_pref], snap, lambda ns, n: demand)
        assert enc.unschedulable_reason is None
        assert enc.preferred_level == -1


class TestValueNarrownessDominance:
    def test_narrowness_beats_extreme_slack_at_any_depth(self):
        """A broader domain must never outrank a feasible narrower one, even
        when the broader is overcommitted (strongly negative slack makes its
        -0.5*slack term maximally positive) and the narrower is maximally
        slack — the level weight scales with topology depth."""
        import jax.numpy as jnp

        from grove_tpu.solver.engine import value_from_aggregates

        dom_level = jnp.asarray(np.array([-1, 0, 1], np.int32))
        # level-0 domain overcommitted (free -100), level-1 domain huge
        dom_free = jnp.asarray(
            np.array([[300.0], [-100.0], [100.0]], np.float32)
        )
        cnt_fit = jnp.ones((1, 3), jnp.float32)
        value = np.asarray(
            value_from_aggregates(
                dom_free,
                cnt_fit,
                dom_level,
                jnp.asarray(np.array([[2.0]], np.float32)),
                jnp.asarray(np.array([-1], np.int32)),
                jnp.asarray(np.array([-1], np.int32)),
                jnp.asarray(np.array([True])),
                jnp.asarray(np.array([100.0], np.float32)),
            )
        )
        assert value[0].argmax() == 2, value


def snap_with_accel_labels(cpu=8.0):
    """2 blocks x 2 hosts; block b1's nodes carry accel=v5. Shared with
    tests/test_parallel.py's sharded eligibility test."""
    nodes = []
    for b in range(2):
        for h in range(2):
            labels = {"t/block": f"b{b}", "t/rack": "r0"}
            if b == 1:
                labels["accel"] = "v5"
            nodes.append(make_node(f"n{b}{h}", labels, cpu=cpu))
    ct = default_cluster_topology(
        [
            TopologyLevel(domain="block", key="t/block"),
            TopologyLevel(domain="rack", key="t/rack"),
        ]
    )
    return encode_topology(ct, nodes)


def constrained_gang(name, pods, cpu, snap, selector, tolerations=()):
    g = gang(name, pods=pods, cpu=cpu)
    mask = snap.eligibility(dict(selector), list(tolerations))
    g.pod_elig = [mask] * pods
    return g


class TestNodeEligibility:
    """node_selector + taint/toleration enforcement in both solve paths.

    The reference embeds full corev1.PodSpec whose selectors/taints the
    delegated scheduler honors (operator/api/core/v1alpha1/podclique.go:
    60-63); grove_tpu owns the scheduler, so the solve paths must enforce
    them as hard filters — a constrained gang is HELD, never misplaced.
    """

    def snap_with_labels(self, cpu=8.0):
        return snap_with_accel_labels(cpu=cpu)

    def constrained(self, name, pods, cpu, snap, selector, tolerations=()):
        return constrained_gang(name, pods, cpu, snap, selector, tolerations)

    def test_eligibility_mask(self):
        snap = self.snap_with_labels()
        mask = snap.eligibility({"accel": "v5"}, [])
        np.testing.assert_array_equal(mask, [False, False, True, True])
        # cache returns the same shared read-only array
        assert snap.eligibility({"accel": "v5"}, []) is mask
        assert not mask.flags.writeable

    def test_serial_places_only_on_selected_nodes(self):
        snap = self.snap_with_labels()
        g = self.constrained("g", pods=2, cpu=6.0, snap=snap,
                             selector={"accel": "v5"})
        res = solve_serial(snap, [g])
        assert "g" in res.placed
        assert set(res.placed["g"].node_indices.tolist()) <= {2, 3}

    def test_serial_holds_gang_rather_than_misplace(self):
        snap = self.snap_with_labels()
        # 3 pods x 6 cpu need 18 cpu on accel nodes (16 available there,
        # 32 cluster-wide): must be HELD even though unselected nodes fit
        g = self.constrained("g", pods=3, cpu=6.0, snap=snap,
                             selector={"accel": "v5"})
        res = solve_serial(snap, [g])
        assert res.placed == {}
        assert "g" in res.unplaced

    def test_engine_matches_serial_on_selectors(self):
        snap = self.snap_with_labels()
        gangs = [
            self.constrained("sel", pods=2, cpu=6.0, snap=snap,
                             selector={"accel": "v5"}),
            self.constrained("held", pods=3, cpu=6.0, snap=snap,
                             selector={"accel": "v5"}),
            # named to sort AFTER the constrained gangs: tie-break jitter
            # must not let an unconstrained gang squat on scarce accel
            # nodes before the selector-bound gang commits
            gang("zz-free", pods=2, cpu=2.0),
        ]
        res = PlacementEngine(snap).solve(gangs)
        ser = solve_serial(snap, gangs)
        assert set(res.placed) == set(ser.placed) == {"sel", "zz-free"}
        assert set(res.placed["sel"].node_indices.tolist()) <= {2, 3}
        assert "held" in res.unplaced

    def test_taints_repel_untolerated_pods(self):
        nodes = [
            make_node("n0", {"t/block": "b0", "t/rack": "r0"}),
            make_node("n1", {"t/block": "b0", "t/rack": "r0"}),
        ]
        nodes[0].taints = ["maintenance"]
        ct = default_cluster_topology(
            [
                TopologyLevel(domain="block", key="t/block"),
                TopologyLevel(domain="rack", key="t/rack"),
            ]
        )
        snap = encode_topology(ct, nodes)
        assert snap.has_taints
        # untolerated: only n1 eligible -> 2x6cpu gang held
        g1 = self.constrained("plain", pods=2, cpu=6.0, snap=snap,
                              selector={})
        # tolerated: both nodes usable -> placed
        g2 = self.constrained("tol", pods=2, cpu=6.0, snap=snap,
                              selector={}, tolerations=["maintenance"])
        for solve in (solve_serial, lambda s, gs: PlacementEngine(s).solve(gs)):
            res = solve(snap, [g1])
            assert "plain" in res.unplaced, solve
            res = solve(snap, [g2])
            assert "tol" in res.placed, solve

    def test_mixed_eligibility_within_one_gang(self):
        snap = self.snap_with_labels()
        g = gang("mix", pods=3, cpu=5.0)
        mask = snap.eligibility({"accel": "v5"}, [])
        # one pod pinned to accel nodes, two unconstrained
        g.pod_elig = [mask, None, None]
        res = PlacementEngine(snap).solve([g])
        assert "mix" in res.placed
        pinned = res.placed["mix"].node_indices[0]
        assert pinned in (2, 3)

    def test_native_paths_enforce_eligibility(self):
        """The C++ scorer enforces eligibility masks exactly: parity with
        the Python serial path on a selector-constrained backlog, and a
        held gang stays held."""
        from grove_tpu.native import native_available, solve_serial_native

        snap = self.snap_with_labels()
        g = self.constrained("g", pods=1, cpu=1.0, snap=snap,
                             selector={"accel": "v5"})
        if not native_available():
            import pytest

            pytest.skip("no native toolchain")
        gangs = [
            self.constrained("sel", pods=2, cpu=6.0, snap=snap,
                             selector={"accel": "v5"}),
            self.constrained("held", pods=3, cpu=6.0, snap=snap,
                             selector={"accel": "v5"}),
            gang("zz-free", pods=2, cpu=2.0),
        ]
        nat = solve_serial_native(snap, gangs)
        ser = solve_serial(snap, gangs)
        assert nat is not None
        assert set(nat.placed) == set(ser.placed) == {"sel", "zz-free"}
        for name in nat.placed:
            np.testing.assert_array_equal(
                nat.placed[name].node_indices,
                ser.placed[name].node_indices,
            )
        assert "held" in nat.unplaced

    def test_all_true_mask_treated_as_unconstrained(self):
        """A mask that excludes nothing must resolve to None so fully
        tolerating/unselective pods keep the fast paths (native repair,
        single-signature scoring) even in a tainted cluster."""
        from grove_tpu.solver.problem import pod_eligibility_mask

        snap = self.snap_with_labels()
        assert pod_eligibility_mask(snap, None, True) is None
        assert pod_eligibility_mask(snap, ({}, []), False) is None
        assert pod_eligibility_mask(snap, ({"accel": "v5"}, []), True) is not None

        nodes = [
            make_node("n0", {"t/block": "b0", "t/rack": "r0"}),
            make_node("n1", {"t/block": "b0", "t/rack": "r0"}),
        ]
        nodes[0].taints = ["maintenance"]
        ct = default_cluster_topology(
            [TopologyLevel(domain="block", key="t/block"),
             TopologyLevel(domain="rack", key="t/rack")]
        )
        tsnap = encode_topology(ct, nodes)
        # tolerates every taint -> effectively unconstrained
        assert pod_eligibility_mask(
            tsnap, ({}, ["maintenance"]), tsnap.has_taints
        ) is None
        # untolerated taint -> real mask
        mask = pod_eligibility_mask(tsnap, ({}, []), tsnap.has_taints)
        np.testing.assert_array_equal(mask, [False, True])


class TestAsyncDispatch:
    """engine.dispatch() + solve(dispatch=) must be bitwise what a fresh
    solve computes (same encode, same jitted fn), and stale hints must be
    rejected, never silently adopted (scheduler.pre_round overlap path)."""

    def test_dispatch_matches_fresh_solve(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [
            gang("a", pods=2, cpu=2.0),
            gang("b", pods=4, cpu=6.0, required=1),
            gang("c", pods=3, cpu=3.0, preferred=2),
        ]
        eng = PlacementEngine(snap)
        fresh = eng.solve(gangs)
        handle = eng.dispatch(gangs, free=snap.free.copy())
        adopted = eng.solve(gangs, free=snap.free.copy(), dispatch=handle)
        assert adopted.stats.get("dispatch_overlap") == 1.0
        assert set(adopted.placed) == set(fresh.placed)
        for name in fresh.placed:
            np.testing.assert_array_equal(
                adopted.placed[name].node_indices,
                fresh.placed[name].node_indices,
            )

    def test_stale_free_matrix_rejected(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        gangs = [gang("a", pods=2, cpu=2.0)]
        eng = PlacementEngine(snap)
        handle = eng.dispatch(gangs, free=snap.free.copy())
        free = snap.free.copy()
        free[0] -= 1.0  # capacity moved since dispatch
        res = eng.solve(gangs, free=free, dispatch=handle)
        assert "dispatch_overlap" not in res.stats
        assert res.num_placed == 1

    def test_different_gang_list_rejected(self):
        snap = cluster(blocks=2, racks=2, hosts=4, cpu=8.0)
        eng = PlacementEngine(snap)
        handle = eng.dispatch([gang("a", pods=2, cpu=2.0)],
                              free=snap.free.copy())
        # same names, RE-ENCODED objects: identity check must reject
        res = eng.solve([gang("a", pods=2, cpu=2.0)],
                        free=snap.free.copy(), dispatch=handle)
        assert "dispatch_overlap" not in res.stats
        assert res.num_placed == 1

    def test_dispatch_empty_backlog_returns_none(self):
        snap = cluster(blocks=1, racks=1, hosts=2, cpu=8.0)
        assert PlacementEngine(snap).dispatch([]) is None
