"""Tests for the span-tracing layer (observability/tracing.py): span
causality, the GangTimeline sum contract against the north-star bind
latency, flight-recorder bounds, Chrome-trace export, the chaos
postmortem dump, and the disabled-path zero-cost guarantee."""

import json

import pytest

from grove_tpu.chaos import ChaosHarness, FaultPlan
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness
from grove_tpu.solver import PlacementEngine
from grove_tpu.observability import tracing
from grove_tpu.observability.tracing import (
    GANG_PHASES,
    NOOP_TRACER,
    FlightRecorder,
    GangTimeline,
    Span,
    Tracer,
    chrome_trace,
)

from test_e2e_basic import clique, simple_pcs

_TICK = 1e-9  # "within one virtual-clock tick" (acceptance criterion)


def traced_harness(nodes=8, **node_kw):
    return Harness(
        nodes=make_nodes(nodes, **node_kw),
        config={"tracing": {"enabled": True}},
    )


def run_spread(h, rounds=12, dt=0.5):
    """Drive the control plane with the virtual clock advancing BETWEEN
    rounds, so gang lifecycle phases land at distinct virtual times
    (one settle() call runs at a single virtual instant)."""
    for _ in range(rounds):
        h.clock.advance(dt)
        h.manager.run_once()
        h.clock.advance(dt)
        h.kubelet.tick()
    h.settle()


class TestSpanCausality:
    def test_parent_child_nesting(self):
        tr = Tracer()
        with tr.span("outer") as outer:
            with tr.span("mid") as mid:
                with tr.span("inner") as inner:
                    assert tr.open_depth == 3
        assert outer.parent_id is None
        assert mid.parent_id == outer.span_id
        assert inner.parent_id == mid.span_id
        assert len(tr.finished) == 3

    def test_reentrant_same_name_nesting(self):
        # a reconcile driving a nested manager round re-enters the same
        # instrumentation site: the stack must nest, not confuse spans
        tr = Tracer()
        with tr.span("manager.reconcile", controller="a") as a:
            with tr.span("manager.reconcile", controller="b") as b:
                pass
        assert b.parent_id == a.span_id

    def test_exception_unwind_records_error_and_pops(self):
        tr = Tracer()
        with pytest.raises(ValueError):
            with tr.span("outer"):
                with tr.span("inner"):
                    raise ValueError("boom")
        assert tr.open_depth == 0
        by_name = {sp.name: sp for sp in tr.finished}
        assert "ValueError: boom" in by_name["inner"].attrs["error"]

    def test_skipped_exit_tolerated(self):
        # a crash raised through a crash-restart can skip __exit__ calls;
        # finishing an outer span must clear the abandoned inner frames
        tr = Tracer()
        outer = tr.span("outer")
        tr._enter(outer)
        inner = tr.span("inner")
        tr._enter(inner)  # never finished
        tr._finish(outer)
        assert tr.open_depth == 0

    def test_point_parents_to_open_span(self):
        tr = Tracer()
        with tr.span("solve") as solve:
            pt = tr.point("bind", gang="ns/g")
        assert pt.parent_id == solve.span_id
        assert pt.v0 == pt.v1

    def test_e2e_bind_ancestry_reaches_solve(self):
        h = traced_harness()
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        spans = list(h.cluster.tracer.finished)
        by_id = {sp.span_id: sp for sp in spans}
        binds = [sp for sp in spans if sp.name == "scheduler.bind"]
        assert binds, "the bound gang must emit a scheduler.bind point"
        for bind in binds:
            chain = []
            cur = bind
            while cur.parent_id is not None:
                cur = by_id[cur.parent_id]
                chain.append(cur.name)
            assert "scheduler.solve" in chain
            assert "manager.reconcile" in chain
        # the reconcile span wrapping the solve is the scheduler's
        solve = next(sp for sp in spans if sp.name == "scheduler.solve")
        rec = by_id[solve.parent_id]
        assert rec.name == "manager.reconcile"
        assert rec.attrs["controller"] == "scheduler"
        assert rec.attrs["outcome"] in ("ok", "requeue", "soft-error")


class TestGangTimeline:
    def test_phases_sum_to_bind_latency_plus_startup(self):
        h = traced_harness()
        h.apply(simple_pcs(
            replicas=2,
            cliques=[clique("fe", 2), clique("be", 2, starts_after=["fe"])],
            startup="CliqueStartupTypeExplicit",
        ))
        run_spread(h)
        tr = h.cluster.tracer
        tls = GangTimeline(tr.finished).timelines()
        assert len(tls) == 2, "both gangs reconstructed"
        bind_hist = h.cluster.metrics.histogram(
            "grove_scheduler_gang_bind_latency_seconds"
        )
        assert bind_hist.count == 2
        for key, tl in tls.items():
            assert tl["complete"], f"{key} incomplete: {tl}"
            assert tl["pods_expected"] == 4
            # telescoping: phases sum EXACTLY to (running - created)
            assert sum(tl["phases"].values()) == pytest.approx(
                tl["total"], abs=_TICK
            )
            assert tl["bind_latency"] + tl["startup"] == pytest.approx(
                tl["total"], abs=_TICK
            )
            assert all(v >= 0.0 for v in tl["phases"].values())
            assert set(tl["phases"]) == set(GANG_PHASES)
        # the decomposition's bind latency IS the recorded north-star
        # metric: per-gang values sum to the histogram's exact sum
        assert sum(tl["bind_latency"] for tl in tls.values()) == (
            pytest.approx(bind_hist.sum, abs=2 * _TICK)
        )
        # the spread run must actually exercise nonzero phases, or this
        # test proves nothing
        assert sum(tl["total"] for tl in tls.values()) > 0.0

    def test_debug_dump_flushes_phase_histogram_idempotently(self):
        h = traced_harness()
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        run_spread(h, rounds=4)
        d1 = h.debug_dump()["tracing"]
        assert d1["enabled"] is True
        assert d1["gang_timeline"]["complete"] == 1
        ph = h.cluster.metrics.histogram("grove_trace_gang_phase_seconds")
        count1 = ph.count
        assert count1 == len(GANG_PHASES)  # one observation per phase
        d2 = h.debug_dump()["tracing"]
        assert ph.count == count1, "repeated dumps must not double-count"
        assert d2["gang_timeline"]["complete"] == 1

    def test_rebound_gang_keeps_last_bind(self):
        # two binds for one gang (preempt + rebind): the timeline keys on
        # the LAST bind and ignores pod points that precede it
        tr = Tracer()
        with tr.span("scheduler.solve"):
            tr.point("scheduler.bind", gang="ns/g", created_at=0.0, pods=1)
        tr.point("kubelet.pod_start", namespace="ns", gang="g", pod="ns/p0")
        tr.point("kubelet.pod_ready", namespace="ns", gang="g", pod="ns/p0")
        tr.clock = type("C", (), {"now": staticmethod(lambda: 5.0)})()
        with tr.span("scheduler.solve"):
            tr.point("scheduler.bind", gang="ns/g", created_at=0.0, pods=1)
        tr.point("kubelet.pod_start", namespace="ns", gang="g", pod="ns/p0")
        tr.point("kubelet.pod_ready", namespace="ns", gang="g", pod="ns/p0")
        tls = GangTimeline(tr.finished).timelines()
        tl = tls["ns/g"]
        assert tl["complete"]
        assert tl["checkpoints"]["bound"] == 5.0
        assert tl["bind_latency"] == pytest.approx(5.0)


class TestFlightRecorder:
    def test_ring_wraparound_fixed_memory(self):
        fr = FlightRecorder(capacity=8)
        for i in range(20):
            fr.add_error("c", "ns", f"obj-{i}", "err", virtual_time=float(i))
        s = fr.summary()
        assert s["retained"] == 8
        assert s["appended"] == 20
        assert s["dropped"] == 12
        names = [e["name"] for e in fr.entries()]
        assert names == [f"obj-{i}" for i in range(12, 20)]

    def test_dump_mixes_spans_errors_events(self):
        fr = FlightRecorder(capacity=16)
        tr = Tracer(flight=fr)
        with tr.span("s"):
            pass
        tr.record_error("scheduler", "ns", "g", "boom", 1.0)
        fr.add_event("Warning", "R", "Pod", "p", "ns", "m", 2.0)
        dump = fr.dump(wedged={"x": 1})
        assert dump["format"] == "grove-flight/v1"
        assert dump["wedged"] == {"x": 1}
        assert {e["type"] for e in dump["entries"]} == {
            "span", "error", "event",
        }
        json.dumps(dump)  # JSON-able end to end

    def test_late_span_attrs_reach_flight_ring(self):
        # the runtime stamps outcome/attempt AFTER the reconcile span
        # closes (runtime.py "tags land after the fact"); the flight
        # entry aliases the span's live attrs dict, so postmortem dumps
        # must still carry them — a deep copy in add_span would silently
        # erase failed-vs-ok from every chaos artifact
        fr = FlightRecorder(capacity=8)
        tr = Tracer(flight=fr)
        with tr.span("manager.reconcile") as sp:
            pass
        sp.set(outcome="error", attempt=3)
        entry = json.loads(json.dumps(fr.dump()))["entries"][0]
        assert entry["attrs"] == {"outcome": "error", "attempt": 3}

    def test_events_feed_flight_via_store_hook(self):
        h = traced_harness()
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        types = {e["type"] for e in h.cluster.flight.entries()}
        assert "event" in types and "span" in types


class TestChaosFlightDump:
    def test_wedged_dump_names_stuck_gang(self):
        # a gang that can never place: the postmortem must NAME it
        ch = ChaosHarness(
            FaultPlan.from_seed(1, chaos_steps=0),
            nodes=make_nodes(2, allocatable={"cpu": 1.0, "memory": 1.0,
                                             "tpu": 0.0}),
        )
        ch.apply(simple_pcs(cliques=[clique("w", replicas=2, cpu=5.0)]))
        ch.settle()
        dump = ch.dump_flight()
        assert dump["summary"]["retained"] > 0
        stuck = dump["wedged"]["unscheduled_gangs"]
        assert [g["name"] for g in stuck] == ["default/simple1-0"]
        assert dump["wedged"]["stuck_pods"], "unbound pods named too"

    def test_failed_settle_autodumps_to_trace_path(self, tmp_path):
        path = tmp_path / "flight.json"
        ch = ChaosHarness(
            FaultPlan.from_seed(2, chaos_steps=0),
            nodes=make_nodes(4),
            trace_path=str(path),
        )
        ch.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        ch.settle()  # populate the ring before the wedge

        def boom(max_iters):
            raise RuntimeError("wedged")

        ch._settle_recovered = boom
        with pytest.raises(RuntimeError):
            ch.settle_recovered()
        data = json.loads(path.read_text())
        assert data["format"] == "grove-flight/v1"
        assert data["entries"]

    def test_chaos_run_converges_with_flight_recorder_on(self):
        # the always-on flight recorder must not perturb convergence
        plan = FaultPlan.from_seed(5)
        ch = ChaosHarness(plan, nodes=make_nodes(8))
        ch.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        ch.run_chaos()
        assert ch.flight.appended > 0


class TestChromeTrace:
    def _spans(self):
        h = traced_harness()
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        run_spread(h, rounds=4)
        return list(h.cluster.tracer.finished)

    def test_schema(self):
        spans = self._spans()
        doc = chrome_trace({"grove": spans})
        events = doc["traceEvents"]
        flows = [ev for ev in events if ev.get("cat") == "causal"]
        # metadata + one event per span (+ flow arrows along causal edges)
        assert len(events) - len(flows) == len(spans) + 1
        for ev in events:
            assert set(ev) >= {"name", "ph", "pid", "tid"}
            assert ev["ph"] in ("X", "i", "M", "s", "f")
            if ev["ph"] == "X":
                assert ev["dur"] >= 0.0
                assert ev["ts"] >= 0.0
            if ev["ph"] == "i":
                assert ev["s"] == "t"
            if ev["ph"] in ("s", "f"):
                assert isinstance(ev["id"], int)
                assert ev["ts"] >= 0.0
                if ev["ph"] == "f":
                    assert ev["bp"] == "e"  # bind at enclosing slice end
            elif ev["ph"] != "M":
                assert isinstance(ev["args"]["span_id"], int)
                for v in ev["args"].values():
                    assert isinstance(v, (str, int, float, bool, type(None)))
        json.loads(json.dumps(doc))  # loadable round trip

    def test_cli_converts_trace_and_flight_dumps(self, tmp_path, capsys):
        from grove_tpu.observability.trace import main as trace_main

        spans = self._spans()
        tr_dump = tmp_path / "dump.json"
        tr_dump.write_text(json.dumps(
            {"format": "grove-trace/v1",
             "spans": [sp.to_dict() for sp in spans]}
        ))
        out = tmp_path / "chrome.json"
        assert trace_main([str(tr_dump), "-o", str(out), "--summary"]) == 0
        doc = json.loads(out.read_text())
        plain = [
            ev for ev in doc["traceEvents"] if ev.get("cat") != "causal"
        ]
        assert len(plain) == len(spans) + 1

        fr = FlightRecorder(capacity=64)
        for sp in spans:
            fr.add_span(sp)
        fl_dump = tmp_path / "flight.json"
        fl_dump.write_text(json.dumps(fr.dump()))
        out2 = tmp_path / "chrome2.json"
        assert trace_main([str(fl_dump), "-o", str(out2)]) == 0
        assert json.loads(out2.read_text())["traceEvents"]

    def test_span_roundtrip(self):
        sp = Span(None, "n", 3, 1, 1.0, 2.0, {"k": "v"})
        sp.v1, sp.t1 = 4.0, 2.5
        back = Span.from_dict(json.loads(json.dumps(sp.to_dict())))
        assert back.to_dict() == sp.to_dict()

    def test_tracer_groups_share_one_time_axis(self):
        # regression: span t0/t1 are relative to the PRIVATE epoch of
        # the recording tracer, so merging raw span lists from tracers
        # created at different times stacked every group at ts~0 and
        # sequential bench stages rendered as concurrent. Passing the
        # Tracer objects shifts each group by its epoch delta from the
        # earliest one.
        a, b = Tracer(), Tracer()
        a._t_base, b._t_base = 100.0, 103.0  # b's epoch: 3 s after a's
        for tr in (a, b):
            sp = Span(None, "work", 1, None, 0.0, 0.25, {})
            sp.t1 = 0.5
            tr.finished.append(sp)
        xs = {
            ev["pid"]: ev
            for ev in chrome_trace({"a": a, "b": b})["traceEvents"]
            if ev["ph"] == "X"
        }
        assert xs[1]["ts"] == pytest.approx(0.25e6)  # earliest: no shift
        assert xs[2]["ts"] == pytest.approx(3.25e6)  # shifted by +3 s
        assert xs[1]["dur"] == xs[2]["dur"] == pytest.approx(0.25e6)
        # raw span lists keep the un-shifted single-tracer behavior
        raw = {
            ev["pid"]: ev
            for ev in chrome_trace(
                {"a": list(a.finished), "b": list(b.finished)}
            )["traceEvents"]
            if ev["ph"] == "X"
        }
        assert raw[1]["ts"] == raw[2]["ts"] == pytest.approx(0.25e6)


class TestDisabledPath:
    def test_noop_singleton_allocates_nothing(self, monkeypatch):
        assert NOOP_TRACER.span("a", x=1) is NOOP_TRACER.span("b")
        assert NOOP_TRACER.point("c") is NOOP_TRACER.span("d")
        # the overhead smoke: with tracing off, a full control-plane run
        # must construct ZERO Span objects
        def forbid(*a, **k):
            raise AssertionError("Span allocated on the disabled path")

        monkeypatch.setattr(tracing.Span, "__init__", forbid)
        h = Harness(nodes=make_nodes(8))
        assert h.cluster.tracer is NOOP_TRACER
        h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        h.settle()
        h.advance(5.0)
        assert NOOP_TRACER.finished == ()
        assert h.debug_dump()["tracing"] == {"enabled": False}

    def test_enable_tracing_idempotent_and_config_driven(self):
        h = traced_harness()
        t1 = h.cluster.tracer
        assert t1.enabled
        assert h.cluster.enable_tracing() is t1
        assert h.kubelet.tracer is t1
        assert h.manager.tracer is t1
        assert h.scheduler.tracer is t1

    def test_tracing_config_validated(self):
        from grove_tpu.api.config import load_operator_config

        with pytest.raises(Exception) as ei:
            load_operator_config({"tracing": {"max_spans": 0}})
        assert "tracing.max_spans" in str(ei.value)
        with pytest.raises(Exception) as ei:
            load_operator_config(
                {"tracing": {"flight_recorder_capacity": -1}}
            )
        assert "flight_recorder_capacity" in str(ei.value)

    def test_bounded_span_ring(self):
        tr = Tracer(max_spans=4)
        for i in range(10):
            with tr.span(f"s{i}"):
                pass
        assert len(tr.finished) == 4
        assert tr.spans_started == 10
        s = tr.summary()
        assert s["spans_retained"] == 4 and s["spans_started"] == 10


class StrictEngine(PlacementEngine):
    """PlacementEngine with a pre-tracing signature: no `tracer`
    keyword, no **kwargs — the shape of a user-supplied engine class
    written before this layer existed."""

    def __init__(self, snapshot, top_k=8, native_repair=True,
                 commit_chunk=32, bucket_min=8, metrics=None):
        super().__init__(snapshot, top_k=top_k,
                         native_repair=native_repair,
                         commit_chunk=commit_chunk,
                         bucket_min=bucket_min, metrics=metrics)


class TestTracerInjectionGate:
    def test_accepts_tracer_kwarg(self):
        assert tracing.accepts_tracer_kwarg(PlacementEngine)
        assert not tracing.accepts_tracer_kwarg(StrictEngine)

        class VarKw:
            def __init__(self, snapshot, **kwargs):
                pass

        assert tracing.accepts_tracer_kwarg(VarKw)

    def test_strict_engine_survives_always_on_chaos_tracing(self):
        # regression: ChaosHarness force-enables tracing for the flight
        # recorder, and the scheduler used to unconditionally inject
        # tracer= into the engine kwargs — a custom engine class with a
        # strict signature died with TypeError at its first solve. It
        # must instead run untraced.
        ch = ChaosHarness(
            FaultPlan.from_seed(3, chaos_steps=0),
            nodes=make_nodes(4),
            engine_cls=StrictEngine,
        )
        assert ch.harness.cluster.tracer.enabled
        ch.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        ch.settle()
        assert "tracer" not in ch.harness.scheduler._engine_kwargs
        # the gang still binds end-to-end; only ENGINE sub-spans are
        # missing, the scheduler/kubelet lifecycle is still traced
        hist = ch.harness.cluster.metrics.histogram(
            "grove_scheduler_gang_bind_latency_seconds"
        )
        assert hist.count == 1
        tls = GangTimeline(ch.harness.cluster.tracer.finished).timelines()
        assert len(tls) == 1 and next(iter(tls.values()))["complete"]
