"""Hardened-config matrix: the representative flows must work with
authorization AND leader election enabled together — every controller
write path has to run under the operator identity, or the authorizer
rejects it (regressions here mean a write escaped impersonation)."""

from grove_tpu.api.auxiliary import PriorityClass
from grove_tpu.api.meta import ObjectMeta, get_condition
from grove_tpu.api.podgang import PodGang
from grove_tpu.api.types import Pod, PodCliqueSet, PodCliqueScalingGroupConfig
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness
from grove_tpu.controller.common import stable_hash

from test_e2e_basic import clique, simple_pcs
from test_e2e_updates import bump_image, pod_hashes

HARDENED = {
    "authorization": {"enabled": True},
    "leader_election": {"enabled": True},
}


def test_full_lifecycle_under_authorization_and_ha():
    h = Harness(nodes=make_nodes(16), config=dict(HARDENED))
    pcs = simple_pcs(
        cliques=[clique("w", replicas=3, cpu=1.0)],
        sgs=[PodCliqueScalingGroupConfig(name="g", clique_names=["w"],
                                         replicas=2, min_available=1)],
    )
    pcs.spec.template.termination_delay = 30.0
    h.apply(pcs)
    h.settle()
    assert all(p.node_name and p.status.ready for p in h.store.list(Pod.KIND))
    # rolling update
    bump_image(h)
    h.settle()
    live = h.store.get(PodCliqueSet.KIND, "default", "simple1")
    assert live.status.rolling_update_progress.completed
    target = stable_hash(live.spec.template.cliques[0].spec.pod_spec)
    assert set(pod_hashes(h).values()) == {target}
    # crash -> gang termination -> rebuild
    h.kubelet.crash_pod("default", "simple1-0-g-0-w-0")
    h.settle()
    h.advance(31.0)
    h.settle()
    h.advance(5.1)
    assert all(p.status.ready for p in h.store.list(Pod.KIND))
    assert h.manager.errors == []


def test_preemption_under_authorization_and_ha():
    h = Harness(
        nodes=make_nodes(4, racks_per_block=2, hosts_per_rack=2,
                         allocatable={"cpu": 1.0, "memory": 8.0,
                                      "tpu": 0.0}),
        config=dict(HARDENED),
    )
    low = simple_pcs(
        name="low", cliques=[clique("w", replicas=2, cpu=1.0)],
        sgs=[PodCliqueScalingGroupConfig(name="grp", clique_names=["w"],
                                         replicas=2, min_available=1)],
    )
    h.apply(low)
    h.settle()
    h.store.create(PriorityClass(
        metadata=ObjectMeta(name="gold", namespace=""), value=1000.0))
    hi = simple_pcs(name="hi", cliques=[clique("w", replicas=2, cpu=1.0)])
    hi.spec.template.priority_class_name = "gold"
    h.apply(hi)
    h.settle()
    h.advance(5.1)
    hi_gang = h.store.get(PodGang.KIND, "default", "hi-0")
    assert get_condition(hi_gang.status.conditions,
                         "Scheduled").status == "True"
    assert h.cluster.metrics.counter(
        "grove_scheduler_preemptions_total").total() == 1
    assert h.manager.errors == []


def test_soak_combined_churn_under_hardened_config():
    """Twelve cycles of combined churn — scale out/in, template updates,
    crashes, node loss and return, event compaction — under authz + HA.
    The control plane must converge every cycle with zero manager errors
    and a bounded event log."""
    from grove_tpu.api.types import Node, PodCliqueScalingGroup

    h = Harness(nodes=make_nodes(24), config=dict(HARDENED))
    pcs = simple_pcs(
        name="soak",
        cliques=[clique("w", replicas=2, cpu=1.0)],
        sgs=[PodCliqueScalingGroupConfig(name="g", clique_names=["w"],
                                         replicas=2, min_available=1)],
    )
    pcs.spec.template.termination_delay = 30.0
    h.apply(pcs)
    h.settle()
    max_log = 0
    for cycle in range(12):
        if cycle % 3 == 0:
            # managed-kind scale needs an authorized identity under authz
            # (the HPA path runs as the operator; kubectl-scale would use
            # the scale subresource with its own RBAC)
            with h.store.impersonate(h.manager.identity):
                sg = h.store.get(PodCliqueScalingGroup.KIND, "default",
                                 "soak-0-g")
                sg.spec.replicas = 3 if sg.spec.replicas == 2 else 2
                h.store.update(sg)
        if cycle % 4 == 1:
            bump_image(h, "soak", tag=f"app:v{cycle}")
        if cycle % 4 == 2:
            h.kubelet.crash_pod("default", "soak-0-g-0-w-0")
            h.settle()
            h.kubelet.recover_pod("default", "soak-0-g-0-w-0")
        if cycle % 6 == 5:
            victim = next(p.node_name for p in h.store.list(Pod.KIND)
                          if p.node_name)
            h.store.delete(Node.KIND, "default", victim)
        h.settle()
        h.advance(5.1)
        h.advance(31.0)  # let any breach clocks fire and recover
        h.settle()
        h.manager.compact_processed_events()
        max_log = max(max_log, len(h.store._events))
        pods = h.store.list(Pod.KIND)
        assert pods and all(p.node_name and p.status.ready for p in pods), (
            f"cycle {cycle}: {[ (p.metadata.name, p.node_name, p.status.ready) for p in pods if not p.status.ready ]}"
        )
        assert h.manager.errors == [], f"cycle {cycle}: {h.manager.errors[-2:]}"
    assert max_log < 100, f"event log unbounded: {max_log}"
