"""API layer tests: naming, defaulting, validation (webhook parity).

Scenario model: /root/reference/operator/internal/webhook/admission/pcs/
{defaulting,validation}/*_test.go (table-driven).
"""

import pytest

from grove_tpu import api
from grove_tpu.api import naming


def make_pcs(name="simple1", cliques=None, sgs=None, startup=None, replicas=1):
    cliques = cliques if cliques is not None else [
        api.PodCliqueTemplateSpec(
            name="frontend",
            spec=api.PodCliqueSpec(
                replicas=2,
                pod_spec=api.PodSpec(
                    containers=[api.Container(name="c", resources={"cpu": 1})]
                ),
            ),
        )
    ]
    pcs = api.PodCliqueSet(
        metadata=api.ObjectMeta(name=name),
        spec=api.PodCliqueSetSpec(
            replicas=replicas,
            template=api.PodCliqueSetTemplateSpec(
                cliques=cliques,
                pod_clique_scaling_group_configs=sgs or [],
                startup_type=startup,
            ),
        ),
    )
    return pcs


class TestNaming:
    def test_grammar(self):
        assert naming.podclique_name("pcs", 0, "decode") == "pcs-0-decode"
        assert naming.pcsg_name("pcs", 1, "sga") == "pcs-1-sga"
        assert naming.base_podgang_name("pcs", 2) == "pcs-2"
        assert naming.scaled_podgang_name("pcs-0-sga", 0) == "pcs-0-sga-0"
        assert naming.pod_name("pcs-0-decode", 3) == "pcs-0-decode-3"

    def test_pcsg_replica_gang_routing(self):
        # Replicas below minAvailable belong to the base gang; beyond get
        # 0-based scaled gangs (namegen.go:100-115).
        assert (
            naming.podgang_name_for_pcsg_replica("pcs", 0, "pcs-0-sga", 1, 2)
            == "pcs-0"
        )
        assert (
            naming.podgang_name_for_pcsg_replica("pcs", 0, "pcs-0-sga", 2, 2)
            == "pcs-0-sga-0"
        )
        assert (
            naming.podgang_name_for_pcsg_replica("pcs", 0, "pcs-0-sga", 4, 2)
            == "pcs-0-sga-2"
        )


class TestDefaulting:
    def test_defaults_applied(self):
        pcs = make_pcs()
        pcs.spec.template.cliques[0].spec.min_available = None
        api.default_podcliqueset(pcs)
        tmpl = pcs.spec.template
        assert tmpl.startup_type == api.CliqueStartupType.ANY_ORDER
        assert tmpl.termination_delay == 4 * 3600
        assert tmpl.head_less_service_config.publish_not_ready_addresses
        assert tmpl.cliques[0].spec.min_available == 2  # defaults to replicas

    def test_pcsg_defaults(self):
        sgs = [api.PodCliqueScalingGroupConfig(name="sga", clique_names=["frontend"])]
        pcs = make_pcs(sgs=sgs)
        api.default_podcliqueset(pcs)
        sg = pcs.spec.template.pod_clique_scaling_group_configs[0]
        assert sg.replicas == 1 and sg.min_available == 1


class TestValidation:
    def _validate(self, pcs):
        api.default_podcliqueset(pcs)
        api.validate_podcliqueset(pcs)

    def test_valid_passes(self):
        self._validate(make_pcs())

    def test_bad_name(self):
        with pytest.raises(api.ValidationError, match="DNS-1123"):
            self._validate(make_pcs(name="Bad_Name"))

    def test_duplicate_clique_names(self):
        cl = [
            api.PodCliqueTemplateSpec(name="a", spec=api.PodCliqueSpec()),
            api.PodCliqueTemplateSpec(name="a", spec=api.PodCliqueSpec(role_name="b")),
        ]
        with pytest.raises(api.ValidationError, match="duplicate clique name"):
            self._validate(make_pcs(cliques=cl))

    def test_min_available_bounds(self):
        pcs = make_pcs()
        pcs.spec.template.cliques[0].spec.min_available = 5  # > replicas=2
        with pytest.raises(api.ValidationError, match="minAvailable"):
            self._validate(pcs)

    def test_starts_after_requires_explicit(self):
        cl = [
            api.PodCliqueTemplateSpec(name="a", spec=api.PodCliqueSpec()),
            api.PodCliqueTemplateSpec(
                name="b", spec=api.PodCliqueSpec(role_name="rb", starts_after=["a"])
            ),
        ]
        with pytest.raises(api.ValidationError, match="Explicit"):
            self._validate(make_pcs(cliques=cl))

    def test_starts_after_unknown_target(self):
        cl = [
            api.PodCliqueTemplateSpec(
                name="a", spec=api.PodCliqueSpec(starts_after=["ghost"])
            )
        ]
        with pytest.raises(api.ValidationError, match="unknown clique"):
            self._validate(make_pcs(cliques=cl, startup=api.CliqueStartupType.EXPLICIT))

    def test_cycle_detection(self):
        # a -> b -> c -> a (validation/podcliqueset.go:278-300 SCC parity).
        cl = [
            api.PodCliqueTemplateSpec(
                name="a", spec=api.PodCliqueSpec(starts_after=["c"])
            ),
            api.PodCliqueTemplateSpec(
                name="b", spec=api.PodCliqueSpec(role_name="rb", starts_after=["a"])
            ),
            api.PodCliqueTemplateSpec(
                name="c", spec=api.PodCliqueSpec(role_name="rc", starts_after=["b"])
            ),
        ]
        with pytest.raises(api.ValidationError, match="cycle"):
            self._validate(make_pcs(cliques=cl, startup=api.CliqueStartupType.EXPLICIT))

    def test_diamond_dag_ok(self):
        cl = [
            api.PodCliqueTemplateSpec(name="a", spec=api.PodCliqueSpec()),
            api.PodCliqueTemplateSpec(
                name="b", spec=api.PodCliqueSpec(role_name="rb", starts_after=["a"])
            ),
            api.PodCliqueTemplateSpec(
                name="c", spec=api.PodCliqueSpec(role_name="rc", starts_after=["a"])
            ),
            api.PodCliqueTemplateSpec(
                name="d",
                spec=api.PodCliqueSpec(role_name="rd", starts_after=["b", "c"]),
            ),
        ]
        self._validate(make_pcs(cliques=cl, startup=api.CliqueStartupType.EXPLICIT))

    def test_pcsg_unknown_clique(self):
        sgs = [api.PodCliqueScalingGroupConfig(name="sga", clique_names=["ghost"])]
        with pytest.raises(api.ValidationError, match="unknown clique"):
            self._validate(make_pcs(sgs=sgs))

    def test_pcsg_no_overlap(self):
        cl = [
            api.PodCliqueTemplateSpec(name="a", spec=api.PodCliqueSpec()),
            api.PodCliqueTemplateSpec(name="b", spec=api.PodCliqueSpec(role_name="rb")),
        ]
        sgs = [
            api.PodCliqueScalingGroupConfig(name="sg1", clique_names=["a"]),
            api.PodCliqueScalingGroupConfig(name="sg2", clique_names=["a", "b"]),
        ]
        with pytest.raises(api.ValidationError, match="already claimed"):
            self._validate(make_pcs(cliques=cl, sgs=sgs))

    def test_no_clique_hpa_inside_pcsg(self):
        cl = [
            api.PodCliqueTemplateSpec(
                name="a",
                spec=api.PodCliqueSpec(
                    scale_config=api.AutoScalingConfig(min_replicas=1, max_replicas=3)
                ),
            )
        ]
        sgs = [api.PodCliqueScalingGroupConfig(name="sga", clique_names=["a"])]
        with pytest.raises(api.ValidationError, match="scale only via the group"):
            self._validate(make_pcs(cliques=cl, sgs=sgs))

    def test_topology_strictness(self):
        # PCS requires rack-level pack; clique must not be broader (zone).
        pcs = make_pcs()
        pcs.spec.template.topology_constraint = api.TopologyConstraintSpec(
            pack_constraint=api.TopologyPackConstraintSpec(required="rack")
        )
        pcs.spec.template.cliques[0].spec.topology_constraint = (
            api.TopologyConstraintSpec(
                pack_constraint=api.TopologyPackConstraintSpec(required="zone")
            )
        )
        with pytest.raises(api.ValidationError, match="narrow"):
            self._validate(pcs)

    def test_update_immutability(self):
        old = make_pcs()
        new = make_pcs()
        new.spec.template.cliques = [
            api.PodCliqueTemplateSpec(name="other", spec=api.PodCliqueSpec())
        ]
        with pytest.raises(api.ValidationError, match="immutable"):
            api.validate_podcliqueset_update(old, new)


class TestConditions:
    def test_set_condition_flip_detection(self):
        conds = []
        assert api.set_condition(conds, "MinAvailableBreached", "True", now=1.0)
        assert not api.set_condition(conds, "MinAvailableBreached", "True", now=2.0)
        assert conds[0].last_transition_time == 1.0
        assert api.set_condition(conds, "MinAvailableBreached", "False", now=3.0)
        assert conds[0].last_transition_time == 3.0


def clique(name, replicas=2, min_available=None, starts_after=()):
    return api.PodCliqueTemplateSpec(
        name=name,
        spec=api.PodCliqueSpec(
            replicas=replicas,
            min_available=min_available,
            starts_after=list(starts_after),
            pod_spec=api.PodSpec(
                containers=[api.Container(name="c", resources={"cpu": 1})]
            ),
        ),
    )


def admit(pcs):
    api.default_podcliqueset(pcs)
    api.validate_podcliqueset(pcs)
    return pcs


class TestReviewFixes:
    """Behaviors pinned after the round-1 code review."""

    def test_pcsg_name_budget_includes_group_name(self):
        sgs = [api.PodCliqueScalingGroupConfig(
            name="prefill-workers-group", clique_names=["decode"])]
        pcs = make_pcs(name="inference-serving-clu",
                       cliques=[clique("decode")], sgs=sgs)
        with pytest.raises(api.ValidationError, match="exceeds"):
            admit(pcs)

    def test_unknown_topology_domain_sort_raises(self):
        with pytest.raises(ValueError, match="unknown topology domain"):
            api.sort_topology_levels(
                [api.TopologyLevel(domain="cube", key="topo/cube")])

    def test_invalid_scale_config_min_replicas_rejected_not_coerced(self):
        pcs = make_pcs(cliques=[clique("a")])
        pcs.spec.template.cliques[0].spec.scale_config = api.AutoScalingConfig(
            min_replicas=0, max_replicas=4)
        with pytest.raises(api.ValidationError, match="minReplicas must be >= 1"):
            admit(pcs)

    def test_self_loop_reported_once(self):
        pcs = make_pcs(cliques=[clique("a", starts_after=["a"])],
                       startup=api.CliqueStartupType.EXPLICIT)
        with pytest.raises(api.ValidationError) as ei:
            admit(pcs)
        assert len(ei.value.errors) == 1
        assert "cycle" in ei.value.errors[0]

    def test_update_minavailable_immutable_but_reorder_ok_anyorder(self):
        from grove_tpu.api.validation import validate_podcliqueset_update

        old = admit(make_pcs(cliques=[clique("a"), clique("b")]))
        new = admit(make_pcs(cliques=[clique("b"), clique("a")]))
        validate_podcliqueset_update(old, new)  # reorder OK under AnyOrder

        new2 = admit(make_pcs(cliques=[clique("a", min_available=1), clique("b")]))
        with pytest.raises(api.ValidationError, match="minAvailable is immutable"):
            validate_podcliqueset_update(old, new2)

    def test_update_reorder_rejected_when_explicit(self):
        from grove_tpu.api.validation import validate_podcliqueset_update

        old = admit(make_pcs(cliques=[clique("a"), clique("b")],
                             startup=api.CliqueStartupType.EXPLICIT))
        new = admit(make_pcs(cliques=[clique("b"), clique("a")],
                             startup=api.CliqueStartupType.EXPLICIT))
        with pytest.raises(api.ValidationError, match="order is immutable"):
            validate_podcliqueset_update(old, new)

    def test_cluster_topology_validation(self):
        from grove_tpu.api.types import (
            ClusterTopology, ClusterTopologySpec, TopologyLevel,
        )

        bad = ClusterTopology(spec=ClusterTopologySpec(levels=[
            TopologyLevel(domain="cube", key="t/cube"),
            TopologyLevel(domain="rack", key="t/rack"),
            TopologyLevel(domain="rack", key="t/rack"),
            TopologyLevel(domain="zone", key=""),
        ]))
        with pytest.raises(api.ValidationError) as ei:
            api.validate_cluster_topology(bad)
        msgs = " ".join(ei.value.errors)
        assert "unknown topology domain" in msgs
        assert "duplicate domain" in msgs
        assert "must not be empty" in msgs
        ok = ClusterTopology(spec=ClusterTopologySpec(levels=[
            TopologyLevel(domain="rack", key="t/rack")]))
        api.validate_cluster_topology(ok)

    def test_update_order_and_field_violations_reported_together(self):
        from grove_tpu.api.validation import validate_podcliqueset_update

        old = admit(make_pcs(cliques=[clique("a"), clique("b")],
                             startup=api.CliqueStartupType.EXPLICIT))
        new = admit(make_pcs(cliques=[clique("b"), clique("a", min_available=1)],
                             startup=api.CliqueStartupType.EXPLICIT))
        with pytest.raises(api.ValidationError) as ei:
            validate_podcliqueset_update(old, new)
        msgs = " ".join(ei.value.errors)
        assert "order is immutable" in msgs and "minAvailable is immutable" in msgs

    def test_standalone_name_budget_matches_reference_formula(self):
        # 20-char pcs + 25-char clique = 45 exactly -> accepted
        pcs = make_pcs(name="a" * 20, cliques=[clique("b" * 25)])
        admit(pcs)
        pcs2 = make_pcs(name="a" * 20, cliques=[clique("b" * 26)])
        with pytest.raises(api.ValidationError, match="exceeds"):
            admit(pcs2)

    def test_exact_generated_name_budget_counts_index_digits(self):
        # Boundary: combined components = 45 (passes the reference formula)
        # but replica-digit widths push the worst-case generated hostname
        # '<pcs>-<i>-<clique>-<k>' past a 63-char DNS label -> rejected.
        c = clique("b" * 25)
        c.spec.replicas = 2
        c.spec.scale_config = api.AutoScalingConfig(
            min_replicas=1, max_replicas=10**12, target_utilization=0.5
        )
        pcs = make_pcs(name="a" * 20, cliques=[c], replicas=10**4)
        # 20 + 1 + 4 + 1 + 25 + 1 + 12 = 64 > 63
        with pytest.raises(api.ValidationError, match="worst-case generated"):
            admit(pcs)
        # Same shapes with modest scale bounds fit: accepted
        c.spec.scale_config.max_replicas = 100
        pcs_ok = make_pcs(name="a" * 20, cliques=[c], replicas=10**4)
        admit(pcs_ok)  # 20+1+4+1+25+1+2 = 54 <= 63

    def test_exact_generated_name_budget_pcsg(self):
        # PCSG hostnames carry two extra components; huge HPA bounds on the
        # group overflow the DNS label even when the 45 budget holds.
        member = clique("c" * 15)
        member.spec.replicas = 4
        sg = api.PodCliqueScalingGroupConfig(
            name="s" * 10, clique_names=[member.name], replicas=2,
            min_available=1,
            scale_config=api.AutoScalingConfig(
                min_replicas=1, max_replicas=10**12, target_utilization=0.5
            ),
        )
        pcs = make_pcs(name="a" * 20, cliques=[member], sgs=[sg])
        # 20+1+1+1+10+1+12+1+15+1+1 = 64 > 63
        with pytest.raises(api.ValidationError, match="worst-case generated"):
            admit(pcs)
        sg.scale_config.max_replicas = 100
        admit(make_pcs(name="a" * 20, cliques=[member], sgs=[sg]))
