"""Placement-service tests: codec round-trip, the gRPC boundary, and the
full control plane driving a remote engine (the operator/external-
scheduler split of the reference, with grove_tpu's own engine behind it).
"""

import contextlib

import numpy as np
import pytest

pytest.importorskip("grpc", reason="service extra not installed")
pytest.importorskip("cryptography", reason="service extra not installed")

from grove_tpu.service import (
    PlacementService,
    RemotePlacementEngine,
    serve,
    snapshot_epoch,
)
from grove_tpu.service import codec
from grove_tpu.solver import PlacementEngine, solve_serial

from test_solver import cluster, gang, snap_with_accel_labels, constrained_gang


@pytest.fixture(scope="module")
def server_address(tmp_path_factory):
    sock = tmp_path_factory.mktemp("svc") / "placement.sock"
    address = f"unix:{sock}"
    server = serve(address)
    yield address
    server.stop(grace=None)


def backlog(snap):
    gangs = [
        gang("a", pods=2, cpu=2.0),
        gang("b", pods=4, cpu=6.0, required=1),
        gang("c", pods=4, cpu=6.0,
             group_levels=[(2, 1, -1), (2, 1, -1)], required=0),
        constrained_gang("sel", pods=2, cpu=6.0, snap=snap,
                         selector={"accel": "v5"}),
        constrained_gang("held", pods=3, cpu=6.0, snap=snap,
                         selector={"accel": "v5"}),
    ]
    return gangs


class TestCodec:
    def test_request_roundtrip(self):
        snap = snap_with_accel_labels()
        gangs = backlog(snap)
        data = codec.encode_solve_request("ep", gangs, snap.free.copy())
        epoch, decoded, free = codec.decode_solve_request(data)
        assert epoch == "ep"
        assert [g.name for g in decoded] == [g.name for g in gangs]
        for orig, back in zip(gangs, decoded):
            np.testing.assert_array_equal(orig.demand, back.demand)
            np.testing.assert_array_equal(orig.group_ids, back.group_ids)
            assert orig.required_level == back.required_level
            assert orig.constraint_groups == back.constraint_groups
            if orig.pod_elig is None:
                assert back.pod_elig is None
            else:
                for m1, m2 in zip(orig.pod_elig, back.pod_elig):
                    if m1 is None:
                        assert m2 is None
                    else:
                        np.testing.assert_array_equal(m1, m2)
        np.testing.assert_allclose(free, snap.free)

    def test_topology_roundtrip(self):
        snap = cluster()
        back = codec.decode_topology_snapshot(
            codec.encode_topology_snapshot(snap)
        )
        np.testing.assert_array_equal(back.domain_ids, snap.domain_ids)
        np.testing.assert_allclose(back.capacity, snap.capacity)
        assert back.node_names == snap.node_names
        assert snapshot_epoch(back) == snapshot_epoch(snap)


class TestServiceSolve:
    def test_remote_matches_local(self, server_address):
        snap = snap_with_accel_labels()
        gangs = backlog(snap)
        local = PlacementEngine(snap).solve(gangs)
        remote = RemotePlacementEngine(snap, server_address).solve(gangs)
        assert set(remote.placed) == set(local.placed)
        for name in remote.placed:
            np.testing.assert_array_equal(
                remote.placed[name].node_indices,
                local.placed[name].node_indices,
            )
        assert remote.unplaced == local.unplaced

    def test_remote_mirrors_residual_free(self, server_address):
        snap = cluster()
        eng = RemotePlacementEngine(snap, server_address)
        free = snap.free.copy()
        result = eng.solve([gang("a", pods=2, cpu=2.0)], free=free)
        assert result.num_placed == 1
        used = snap.free.sum() - free.sum()
        assert used == pytest.approx(2 * 2.0 + 2 * 1.0)  # cpu + memory col

    def test_unknown_epoch_is_failed_precondition(self, server_address):
        import grpc

        snap = cluster()
        eng = RemotePlacementEngine(snap, server_address)
        bad = codec.encode_solve_request(
            "deadbeef", [gang("a", pods=1)], snap.free.copy()
        )
        with pytest.raises(grpc.RpcError) as err:
            eng._solve(bad)
        assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION


class TestRemoteControlPlane:
    def test_full_control_plane_through_the_service(self, server_address):
        """apply -> pods -> gangs -> REMOTE solve -> bound/ready, with a
        selector-constrained clique — the operator/external-scheduler
        process split, end to end."""
        from functools import partial

        from grove_tpu.api.podgang import PodGang
        from grove_tpu.api.types import Pod
        from grove_tpu.cluster import make_nodes
        from grove_tpu.controller import Harness
        from test_e2e_basic import clique, simple_pcs

        nodes = make_nodes(8, racks_per_block=2, hosts_per_rack=4)
        for n in nodes[:4]:
            n.metadata.labels["accel"] = "v5"
        pcs = simple_pcs(cliques=[clique("fe", replicas=2),
                                  clique("be", replicas=2)])
        pcs.spec.template.cliques[0].spec.pod_spec.node_selector = {
            "accel": "v5"}
        h = Harness(
            nodes=nodes,
            engine_cls=partial(RemotePlacementEngine,
                               address=server_address),
        )
        h.apply(pcs)
        h.settle()
        pods = h.store.list(Pod.KIND)
        assert all(p.node_name and p.status.ready for p in pods)
        accel = {f"node-{i}" for i in range(4)}
        for p in pods:
            if p.spec.node_selector:
                assert p.node_name in accel
        gang_obj = h.store.list(PodGang.KIND)[0]
        assert gang_obj.status.placement_score == 1.0


def test_resync_after_server_restart(tmp_path):
    """A restarted (state-less) service must not wedge existing clients:
    the FAILED_PRECONDITION on the lost epoch triggers a re-Sync and the
    solve retries transparently."""
    addr = f"unix:{tmp_path}/restart.sock"
    server = serve(addr)
    snap = cluster()
    eng = RemotePlacementEngine(snap, addr, timeout_seconds=30.0)
    assert eng.solve([gang("a", pods=1)]).num_placed == 1
    server.stop(grace=None)
    server2 = serve(addr)  # fresh process state: epoch cache empty
    try:
        assert eng.solve([gang("b", pods=1)]).num_placed == 1
    finally:
        server2.stop(grace=None)


def _free_port():
    import socket

    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def test_tls_end_to_end(tmp_path):
    """The self-managed TLS analog of the reference's webhook cert
    rotation (cert.go:36-70): CA-signed server cert, client trusts the
    CA bundle, a PLAINTEXT client cannot talk to the TLS server."""
    import grpc

    from grove_tpu.service.server import serve
    from grove_tpu.service.tls import make_ca, issue_server_cert

    ca_cert, ca_key = make_ca()
    bundle = issue_server_cert(ca_cert, ca_key, hostname="127.0.0.1")
    address = f"127.0.0.1:{_free_port()}"
    server = serve(address, tls=bundle)
    try:
        snap = cluster()
        eng = RemotePlacementEngine(snap, address, root_ca=bundle.ca_cert,
                                    timeout_seconds=30.0)
        assert eng.solve([gang("a", pods=2, cpu=2.0)]).num_placed == 1
        # a plaintext client must not get through the TLS port
        with pytest.raises(grpc.RpcError):
            RemotePlacementEngine(snap, address, timeout_seconds=3.0)
    finally:
        server.stop(grace=None)


def test_cert_rotation_reissues_under_same_ca(tmp_path):
    from grove_tpu.service.tls import make_ca, issue_server_cert

    ca_cert, ca_key = make_ca()
    first = issue_server_cert(ca_cert, ca_key)
    second = issue_server_cert(ca_cert, ca_key)  # rotation = re-issue
    assert first.cert != second.cert
    assert first.ca_cert == second.ca_cert  # clients keep trusting the CA


def test_malformed_payloads_are_invalid_argument(server_address):
    """Garbage bytes must come back as INVALID_ARGUMENT with a message,
    not an opaque server crash."""
    import grpc

    snap = cluster()
    eng = RemotePlacementEngine(snap, server_address)
    for stub in (eng._sync, eng._solve):
        with pytest.raises(grpc.RpcError) as err:
            stub(b"not an npz payload", timeout=10.0)
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "malformed" in err.value.details()


def test_cert_renewal_loop_and_client_rechannel(tmp_path):
    """VERDICT r3 #4: the expiry-driven renewal loop. A virtual clock
    advances past the server cert's renewal window (and then its
    not_valid_after); the rotator re-issues under the same CA and the
    server hot-restarts its listener; an existing client completes a
    solve through the refreshed channel without error."""
    import datetime

    from grove_tpu.service import CertRotator, RotatingTLSServer
    from grove_tpu.service.tls import make_ca

    ca_cert, ca_key = make_ca()
    virtual_now = [datetime.datetime.now(datetime.timezone.utc)]
    rotator = CertRotator(
        ca_cert, ca_key, hostname="127.0.0.1", valid_days=365,
        renew_before_days=30.0, now_fn=lambda: virtual_now[0],
    )
    address = f"127.0.0.1:{_free_port()}"
    server = RotatingTLSServer(address, rotator)
    server.start()
    try:
        snap = cluster()
        eng = RemotePlacementEngine(snap, address,
                                    root_ca=rotator.bundle.ca_cert,
                                    timeout_seconds=30.0)
        assert eng.solve([gang("a", pods=1, cpu=1.0)]).num_placed == 1
        # fresh cert: nothing to do
        assert server.maybe_rotate() is False
        first_expiry = rotator.not_valid_after
        first_cert = rotator.bundle.cert
        # virtual clock crosses not_valid_after: renewal is overdue;
        # the rotator re-issues and the listener restarts. (The fresh
        # cert is necessarily signed against REAL time — the TLS
        # handshake validates real clocks — so re-issue is observed via
        # the new certificate, not a shifted expiry.)
        virtual_now[0] = first_expiry + datetime.timedelta(days=1)
        assert server.maybe_rotate() is True
        assert rotator.rotations == 1
        assert rotator.bundle.cert != first_cert  # observed re-issue
        assert rotator.not_valid_after >= first_expiry
        # the SAME client object completes a solve through the refreshed
        # channel (CA unchanged; transport retry handles the restart)
        assert eng.solve([gang("b", pods=1, cpu=1.0)]).num_placed == 1
        # and a brand-new client trusts the renewed cert via the same CA
        eng2 = RemotePlacementEngine(snap, address,
                                     root_ca=rotator.bundle.ca_cert,
                                     timeout_seconds=30.0)
        assert eng2.solve([gang("c", pods=1, cpu=1.0)]).num_placed == 1
    finally:
        server.stop(grace=None)


def test_ca_key_file_born_private(tmp_path):
    """Advisor r3: the persisted CA key must be created 0600 atomically,
    never exposed through a write-then-chmod window."""
    import stat

    from grove_tpu.service.tls import load_or_create_ca

    load_or_create_ca(tmp_path / "tls")
    mode = stat.S_IMODE((tmp_path / "tls" / "ca-key.pem").stat().st_mode)
    assert mode == 0o600


def test_debug_endpoint_and_harness_dump(server_address):
    """VERDICT r3 #6: the pprof-analog introspection surfaces. The
    service's Debug RPC reports engine-cache state + counters; the
    harness dump reports queue depths, store counts and per-controller
    reconcile percentiles."""
    import json

    import grpc

    from grove_tpu.service.codec import GRPC_MESSAGE_OPTIONS

    snap = cluster()
    eng = RemotePlacementEngine(snap, server_address, timeout_seconds=30.0)
    eng.solve([gang("dbg", pods=1, cpu=1.0)])
    with grpc.insecure_channel(
        server_address, options=GRPC_MESSAGE_OPTIONS
    ) as ch:
        dump = json.loads(
            ch.unary_unary("/grove.Placement/Debug")(b"", timeout=10.0)
        )
    assert dump["solves_total"] >= 1
    assert dump["syncs_total"] >= 1
    assert dump["uptime_seconds"] >= 0
    assert eng.epoch in dump["epochs"]
    assert dump["epochs"][eng.epoch]["num_nodes"] == snap.num_nodes

    # harness dump: drive a tiny control plane and introspect it
    from test_e2e_basic import clique, simple_pcs
    from grove_tpu.controller import Harness
    from grove_tpu.cluster import make_nodes

    h = Harness(nodes=make_nodes(4))
    h.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
    h.settle()
    d = h.debug_dump()
    json.dumps(d)  # the dump must be JSON-able as-is
    assert d["store"]["objects_by_kind"]["Pod"] == 2
    ctrl = d["manager"]["controllers"]
    assert ctrl["podclique"]["reconciles"] >= 1
    assert ctrl["scheduler"]["duration_seconds"]["count"] >= 1
    assert ctrl["scheduler"]["duration_seconds"]["p99"] >= 0
    assert d["manager"]["workqueue_depth"] == 0  # settled
    assert d["scheduler"]["engine"]["num_nodes"] == 4
    assert d["manager"]["is_leader"] is True


@contextlib.contextmanager
def _spawned_service(*extra_args, startup_timeout=60.0):
    """Spawn the placement server as a real subprocess, wait (bounded)
    for its listening banner, yield the process; SIGTERM + kill teardown.
    Shared by every subprocess-boundary test in this file.

    The banner wait reads the RAW pipe fd (select + os.read, no
    TextIOWrapper): mixing select with buffered readline can strand the
    banner in Python's internal buffer while select blocks on a drained
    fd — a full startup_timeout flake."""
    import os
    import select
    import signal
    import subprocess
    import sys
    import time

    proc = subprocess.Popen(
        [sys.executable, "-m", "grove_tpu.service.server", *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.monotonic() + startup_timeout
        fd = proc.stdout.fileno()
        buf = ""
        while "listening" not in buf:
            if proc.poll() is not None:
                raise RuntimeError(f"service failed to start:\n{buf}")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(
                    f"service never reported listening:\n{buf}"
                )
            ready, _, _ = select.select([fd], [], [], min(remaining, 1.0))
            if not ready:
                continue  # re-check liveness + deadline
            chunk = os.read(fd, 4096)
            if not chunk:
                raise RuntimeError(f"service stdout closed:\n{buf}")
            buf += chunk.decode(errors="replace")
        yield proc
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def test_console_script_deployment(tmp_path):
    """VERDICT r3 #9 (packaging): the documented deployment recipe works
    end to end — spawn the service process with a tls-dir, verify the
    TLS material appears, solve through the boundary, probe Debug as the
    health check (docs/operations.md)."""
    import json

    import grpc

    from grove_tpu.service.codec import GRPC_MESSAGE_OPTIONS

    tls_dir = tmp_path / "tls"
    address = f"127.0.0.1:{_free_port()}"
    with _spawned_service("--address", address, "--tls-dir", str(tls_dir)):
        # the recipe's TLS material exists, key born private
        import stat

        assert (tls_dir / "ca.pem").exists()
        assert (tls_dir / "server.pem").exists()
        mode = stat.S_IMODE((tls_dir / "ca-key.pem").stat().st_mode)
        assert mode == 0o600
        ca_pem = (tls_dir / "ca.pem").read_bytes()
        snap = cluster()
        # generous deadline: the spawned server cold-compiles its jit on
        # the first solve, and the shared dev tunnel can be congested
        eng = RemotePlacementEngine(snap, address, root_ca=ca_pem,
                                    timeout_seconds=120.0)
        assert eng.solve([gang("a", pods=1, cpu=1.0)]).num_placed == 1
        # health probe per the docs: Debug answers and shows the epoch
        creds = grpc.ssl_channel_credentials(root_certificates=ca_pem)
        with grpc.secure_channel(
            address, creds, options=GRPC_MESSAGE_OPTIONS
        ) as ch:
            dump = json.loads(
                ch.unary_unary("/grove.Placement/Debug")(b"", timeout=10.0)
            )
        assert dump["epochs"], "synced epoch visible to the health probe"


def test_debug_module_uses_only_public_surfaces():
    """VERDICT r4 #6: the introspection dumps must consume public
    accessors, not _-prefixed internals — a runtime/store refactor then
    breaks them loudly at the accessor instead of silently lying."""
    import inspect
    import re

    from grove_tpu.observability import debug

    src = inspect.getsource(debug)
    # attribute reads like obj._x (module-internal names and dunders ok)
    private_reads = [
        m.group(0)
        for m in re.finditer(r"\.\s*_(?!_)\w+", src)
    ]
    assert private_reads == [], (
        f"debug.py reads private attributes: {private_reads}"
    )


def test_debug_cli_fetches_service_dump(tmp_path):
    """The shell CLI (python -m grove_tpu.observability.debug) fetches
    and pretty-prints the service's Debug dump — covered as a real
    subprocess against a live server (VERDICT r4 #6)."""
    import json
    import subprocess
    import sys

    address = f"127.0.0.1:{_free_port()}"
    with _spawned_service("--address", address):
        out = subprocess.run(
            [sys.executable, "-m", "grove_tpu.observability.debug",
             "--address", address],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        dump = json.loads(out.stdout)
        assert "uptime_seconds" in dump
        assert "solves_total" in dump


def test_deploy_manifests_are_valid_and_reference_real_entrypoints():
    """deploy/ is the chart-analog (the reference ships operator/charts):
    the manifests must parse and every executable/module/env they name
    must exist in this tree — a renamed entry point or env var must fail
    here, not at kubectl apply time."""
    import pathlib

    import pytest

    yaml = pytest.importorskip("yaml")
    root = pathlib.Path(__file__).resolve().parent.parent
    docs = list(yaml.safe_load_all(
        (root / "deploy" / "placement-service.yaml").read_text()
    ))
    assert [d["kind"] for d in docs] == [
        "Namespace", "PersistentVolumeClaim", "Deployment", "Service"
    ]
    deployment = docs[2]
    container = deployment["spec"]["template"]["spec"]["containers"][0]
    # probes exec the debug CLI module — it must import and expose main
    probe_cmd = container["livenessProbe"]["exec"]["command"]
    assert probe_cmd[:3] == ["python", "-m", "grove_tpu.observability.debug"]
    import importlib

    assert hasattr(
        importlib.import_module("grove_tpu.observability.debug"), "main"
    )
    # env vars the image/env blocks set must be consumed somewhere real
    env_names = {e["name"] for e in container["env"]}
    assert env_names == {"GROVE_TPU_COMPILE_CACHE", "GROVE_TPU_NATIVE_CACHE"}
    from grove_tpu.native import build as native_build  # noqa: F401
    from grove_tpu import tuning  # noqa: F401

    assert "GROVE_TPU_NATIVE_CACHE" in (
        root / "grove_tpu" / "native" / "build.py"
    ).read_text()
    assert "GROVE_TPU_COMPILE_CACHE" in (
        root / "grove_tpu" / "tuning.py"
    ).read_text()
    # the Containerfile entrypoint is the console script from pyproject
    cf = (root / "deploy" / "Containerfile").read_text()
    assert 'ENTRYPOINT ["grove-placement-service"]' in cf
    assert "grove-placement-service" in (root / "pyproject.toml").read_text()
    # compose file parses and builds from the Containerfile
    compose = yaml.safe_load(
        (root / "deploy" / "docker-compose.yaml").read_text()
    )
    assert compose["services"]["placement-service"]["build"][
        "dockerfile"
    ] == "deploy/Containerfile"


def test_extra_sans_cover_service_dns_names(tmp_path):
    """--san adds the names clients actually dial (k8s Service DNS /
    extra IPs) to the server cert; without it, verification of any
    non-bind-address target fails (the deploy manifests depend on
    this)."""
    import grpc

    from grove_tpu.service.codec import GRPC_MESSAGE_OPTIONS

    tls_dir = tmp_path / "tls"
    port = _free_port()
    with _spawned_service(
        "--address", f"0.0.0.0:{port}", "--tls-dir", str(tls_dir),
        "--san", "127.0.0.1", "--san", "grove-placement.grove-system",
    ):
        ca_pem = (tls_dir / "ca.pem").read_bytes()
        creds = grpc.ssl_channel_credentials(root_certificates=ca_pem)
        # the numeric target only verifies because --san 127.0.0.1 put
        # an IPAddress SAN in the cert
        with grpc.secure_channel(
            f"127.0.0.1:{port}", creds, options=GRPC_MESSAGE_OPTIONS
        ) as ch:
            ch.unary_unary("/grove.Placement/Debug")(b"", timeout=30.0)
        # and the cert carries the k8s Service DNS name
        from cryptography import x509

        cert = x509.load_pem_x509_certificate(
            (tls_dir / "server.pem").read_bytes()
        )
        san = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName
        ).value
        assert "grove-placement.grove-system" in san.get_values_for_type(
            x509.DNSName
        )


def test_remote_dispatch_adopts_and_matches_fresh_solve(server_address):
    """RemotePlacementEngine.dispatch + solve(dispatch=) — the service-
    boundary twin of the local async API: adoption must be bitwise what
    a fresh RPC returns, stale hints must be rejected, and a settle
    through the harness must OVERLAP its solve via pre_round exactly as
    with the local engine."""
    snap = cluster()
    eng = RemotePlacementEngine(snap, server_address, timeout_seconds=60.0)
    gangs = [gang("d1", pods=2, cpu=1.0), gang("d2", pods=1, cpu=2.0)]
    fresh = eng.solve(gangs, free=snap.free.copy())
    handle = eng.dispatch(gangs, free=snap.free.copy())
    adopted = eng.solve(gangs, free=snap.free.copy(), dispatch=handle)
    assert adopted.stats.get("dispatch_overlap") == 1.0
    assert set(adopted.placed) == set(fresh.placed)
    for name in fresh.placed:
        np.testing.assert_array_equal(
            adopted.placed[name].node_indices,
            fresh.placed[name].node_indices,
        )
    # stale free -> rejected, fresh RPC still solves
    handle = eng.dispatch(gangs, free=snap.free.copy())
    moved = snap.free.copy()
    moved[0] -= 1.0
    res = eng.solve(gangs, free=moved, dispatch=handle)
    assert "dispatch_overlap" not in res.stats
    assert res.num_placed == len(gangs)
    assert eng.dispatch([], free=snap.free.copy()) is None


def test_remote_engine_settle_overlaps_via_pre_round(server_address):
    from functools import partial

    from grove_tpu.api.types import Pod
    from grove_tpu.cluster import make_nodes
    from grove_tpu.controller import Harness
    from test_e2e_basic import clique, simple_pcs

    h = Harness(
        nodes=make_nodes(8, racks_per_block=2, hosts_per_rack=4),
        engine_cls=partial(RemotePlacementEngine, address=server_address),
    )
    h.apply(simple_pcs(cliques=[clique("w", replicas=3)]))
    h.settle()
    pods = h.store.list(Pod.KIND)
    assert len(pods) == 3
    assert all(p.node_name and p.status.ready for p in pods)
    c = h.cluster.metrics.counter(
        "grove_scheduler_solve_dispatch_total",
        "pre_round solve dispatches by outcome at consume time",
    )
    assert c.value(outcome="overlapped") >= 1
