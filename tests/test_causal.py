"""Causal critical-path tracing (observability/causal.py + the tracing
integration): ledger token handoff, the telescoping guarantee (segments
sum EXACTLY to created->running), the seeded e2e across stream front +
sharded control plane + hierarchical solve, aggregate-mode agreement
with full tracing, Perfetto flow arrows crossing tracer groups, the
surfaces (debug dump / SLO scorecard / wedged postmortem) agreeing on
the dominating segment, and chaos bit-identity with aggregate mode on.
"""

import json

import pytest

from grove_tpu.chaos import ChaosHarness, FaultPlan, settled_fingerprint
from grove_tpu.cluster import make_nodes
from grove_tpu.controller import Harness
from grove_tpu.observability.causal import (
    SEGMENTS,
    CausalLedger,
    CriticalPathFolder,
    CriticalPathObservatory,
    next_token,
    tokens_of,
)
from grove_tpu.observability.tracing import (
    AggregateTracer,
    Span,
    Tracer,
    chrome_trace,
)

from test_e2e_basic import clique, simple_pcs

_TICK = 1e-9  # "exactly, within one virtual-clock tick" (acceptance)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def now(self):
        return self.t


def gang_life(tr, clock, key="default/g-0", created=0.0, hold_at=None):
    """One gang's synthetic life through every hop, at pinned virtual
    times: admit@1, solve 2->3 (interior walls 0.1/0.3/0.1), bind@3,
    started@4, ready@5."""
    ns, name = key.split("/")
    if hold_at is not None:
        clock.t = hold_at
        tr.point("scheduler.hold", gang=key, code="Insufficient")
    clock.t = 1.0
    tr.point("scheduler.stream_admit", gang=key, queue_wait=0.75)
    clock.t = 2.0
    with tr.span("scheduler.solve"):
        tr.point("engine.fused", encode_seconds=0.1, device_seconds=0.3,
                 repair_seconds=0.1)
        clock.t = 3.0
        tr.point("scheduler.bind", gang=key, created_at=created, pods=2)
    clock.t = 4.0
    for p in ("p0", "p1"):
        tr.point("kubelet.pod_start", namespace=ns, gang=name, pod=p)
    clock.t = 5.0
    for p in ("p0", "p1"):
        tr.point("kubelet.pod_ready", namespace=ns, gang=name, pod=p)


# -- ledger -------------------------------------------------------------------

class TestCausalLedger:
    def test_tokens_are_unique_and_monotonic(self):
        a, b = next_token(), next_token()
        assert b > a

    def test_emit_follow_handoff(self):
        led = CausalLedger()
        assert led.follow(("gang", "ns", "g")) is None
        tok = led.emit(("gang", "ns", "g"))
        assert led.follow(("gang", "ns", "g")) == tok
        prev, nxt = led.handoff(("gang", "ns", "g"))
        assert prev == tok and nxt != tok
        assert led.follow(("gang", "ns", "g")) == nxt
        assert led.summary()["emitted"] == 2

    def test_fifo_eviction_bounds_memory(self):
        led = CausalLedger(capacity=4)
        for i in range(10):
            led.emit(("gang", "ns", f"g{i}"))
        assert led.summary()["tracked"] == 4
        # oldest evicted: following it yields None (a broken arrow)
        assert led.follow(("gang", "ns", "g0")) is None
        assert led.follow(("gang", "ns", "g9")) is not None

    def test_tokens_of_normalizes(self):
        assert tokens_of(None) == ()
        assert tokens_of(7) == (7,)
        assert tokens_of([1, None, 3]) == (1, 3)


# -- telescoping (the load-bearing contract) ----------------------------------

class TestTelescoping:
    def _flush(self, tr):
        paths = []
        folder = CriticalPathFolder(sink=paths.append)
        folder.fold_all(tr.finished)
        return paths

    def test_segments_sum_exactly_to_created_to_running(self):
        clock = FakeClock()
        tr = Tracer(clock=clock)
        gang_life(tr, clock)
        (path,) = self._flush(tr)
        assert path["complete"]
        assert set(path["segments"]) == set(SEGMENTS)
        assert sum(path["segments"].values()) == pytest.approx(
            5.0, abs=_TICK
        )
        cp = path["checkpoints"]
        assert sum(path["segments"].values()) == pytest.approx(
            cp["running"] - cp["created"], abs=_TICK
        )
        assert path["total"] == pytest.approx(5.0, abs=_TICK)
        assert path["bind_latency"] == pytest.approx(3.0, abs=_TICK)

    def test_interior_split_follows_wall_weights(self):
        clock = FakeClock()
        tr = Tracer(clock=clock)
        gang_life(tr, clock)
        (path,) = self._flush(tr)
        seg = path["segments"]
        # solve window [2,3] split by 0.1/0.3/0.1 wall weights
        assert seg["encode"] == pytest.approx(0.2, abs=_TICK)
        assert seg["device"] == pytest.approx(0.6, abs=_TICK)
        assert seg["repair"] == pytest.approx(0.2, abs=_TICK)
        assert seg["admission"] == pytest.approx(1.0, abs=_TICK)
        assert seg["handoff"] == pytest.approx(1.0, abs=_TICK)
        assert seg["pod_startup"] == pytest.approx(1.0, abs=_TICK)
        assert seg["barrier_wait"] == pytest.approx(1.0, abs=_TICK)
        assert path["wall"]["device"] == pytest.approx(0.3, abs=_TICK)
        assert path["queue_wait"] == pytest.approx(0.75)

    def test_held_gang_bills_the_hold(self):
        clock = FakeClock()
        tr = Tracer(clock=clock)
        gang_life(tr, clock, hold_at=0.5)
        (path,) = self._flush(tr)
        assert path["segments"]["held"] == pytest.approx(0.5, abs=_TICK)
        assert path["held_reason"] == "Insufficient"
        assert sum(path["segments"].values()) == pytest.approx(
            5.0, abs=_TICK
        )

    def test_rebind_after_preemption_wins_last(self):
        # two binds for the same gang: pod points before the second bind
        # are ignored and the FINAL path anchors on the last bind
        clock = FakeClock()
        tr = Tracer(clock=clock)
        gang_life(tr, clock)  # first complete life, ready@5
        clock.t = 6.0
        with tr.span("scheduler.solve"):
            tr.point("scheduler.bind", gang="default/g-0",
                     created_at=0.0, pods=2)
        clock.t = 8.0
        for p in ("p0", "p1"):
            tr.point("kubelet.pod_start", namespace="default", gang="g-0",
                     pod=p)
            tr.point("kubelet.pod_ready", namespace="default", gang="g-0",
                     pod=p)
        paths = self._flush(tr)
        assert len(paths) == 2
        last = paths[-1]
        assert last["checkpoints"]["bound"] == pytest.approx(6.0)
        assert last["checkpoints"]["running"] == pytest.approx(8.0)
        assert sum(last["segments"].values()) == pytest.approx(
            8.0, abs=_TICK
        )

    def test_pending_path_for_wedged_gang(self):
        clock = FakeClock()
        tr = Tracer(clock=clock)
        clock.t = 0.5
        tr.point("scheduler.hold", gang="default/stuck-0",
                 code="Insufficient")
        folder = CriticalPathFolder()
        folder.fold_all(tr.finished)
        p = folder.pending_path("default/stuck-0", created_at=0.0, now=9.5)
        assert not p["complete"]
        assert p["held_reason"] == "Insufficient"
        assert p["segments"]["held"] == pytest.approx(9.0, abs=_TICK)
        assert p["total"] == pytest.approx(9.5, abs=_TICK)
        assert p["dominant"] == "held"
        assert folder.pending_path("default/never-seen-0") is None

    def test_folder_state_is_bounded(self):
        clock = FakeClock()
        tr = Tracer(clock=clock)
        for i in range(40):
            tr.point("scheduler.hold", gang=f"default/g-{i}", code="X")
        folder = CriticalPathFolder(max_marks=16)
        folder.fold_all(tr.finished)
        assert folder.summary()["pending_holds"] == 16
        assert folder.dropped > 0


# -- observatory --------------------------------------------------------------

class TestObservatory:
    def test_report_and_topk(self):
        clock = FakeClock()
        tr = Tracer(clock=clock)
        gang_life(tr, clock)
        paths = []
        CriticalPathFolder(sink=paths.append).fold_all(tr.finished)
        obs = CriticalPathObservatory(top_k=2)
        for p in paths:
            obs.observe(p)
        rep = obs.report()
        assert rep["paths"] == 1
        assert rep["segments"]["device"]["sum"] == pytest.approx(0.6)
        assert rep["top"][0]["gang"] == "default/g-0"
        assert rep["dominant_segment"] in SEGMENTS

    def test_histogram_series_per_segment(self):
        from grove_tpu.observability.metrics import MetricsRegistry

        clock = FakeClock()
        tr = Tracer(clock=clock)
        gang_life(tr, clock)
        reg = MetricsRegistry()
        tr.flush_critical_paths(reg)
        hist = reg.get("grove_trace_critical_path_seconds")
        for seg in SEGMENTS:
            assert hist.series_count(segment=seg) == 1

    def test_flush_is_idempotent_per_bind(self):
        from grove_tpu.observability.metrics import MetricsRegistry

        clock = FakeClock()
        tr = Tracer(clock=clock)
        gang_life(tr, clock)
        reg = MetricsRegistry()
        tr.flush_critical_paths(reg)
        tr.flush_critical_paths(reg)
        hist = reg.get("grove_trace_critical_path_seconds")
        assert hist.series_count(segment="device") == 1
        assert tr.critical.paths == 1


# -- aggregate mode -----------------------------------------------------------

class TestAggregateMode:
    def test_ring_is_skipped_but_paths_fold(self):
        clock = FakeClock()
        tr = AggregateTracer(clock=clock)
        gang_life(tr, clock)
        assert len(tr.finished) == 0  # no span ring at all
        rep = tr.flush_critical_paths()
        assert rep["paths"] == 1
        assert rep["segments"]["device"]["sum"] == pytest.approx(0.6)
        assert tr.summary()["paths_folded"] == 1

    def test_matches_full_mode_exactly(self):
        c1, c2 = FakeClock(), FakeClock()
        full, agg = Tracer(clock=c1), AggregateTracer(clock=c2)
        gang_life(full, c1)
        gang_life(agg, c2)
        rf, ra = full.flush_critical_paths(), agg.flush_critical_paths()
        assert rf["dominant_segment"] == ra["dominant_segment"]
        for seg in SEGMENTS:
            assert rf["segments"][seg]["sum"] == pytest.approx(
                ra["segments"][seg]["sum"], abs=_TICK
            )
        assert rf["top"][0]["segments"] == ra["top"][0]["segments"]

    def test_gang_path_reports_pending_waits(self):
        clock = FakeClock()
        tr = AggregateTracer(clock=clock)
        clock.t = 1.0
        tr.point("scheduler.stream_admit", gang="default/g-9",
                 queue_wait=1.0)
        clock.t = 4.0
        p = tr.gang_path("default/g-9", created_at=0.0)
        assert not p["complete"]
        assert p["segments"]["admission"] == pytest.approx(1.0)
        assert p["segments"]["handoff"] == pytest.approx(3.0)


# -- flow events --------------------------------------------------------------

class TestFlowEvents:
    def test_arrows_cross_tracer_groups(self):
        # the acceptance criterion: a merged dump renders CONNECTED flow
        # arrows across >= 2 tracer groups (pids) via shared token ids
        a, b = Tracer(), Tracer()
        led = CausalLedger()
        a.point("federation.route", pcs="ns/p",
                causal_emit=led.emit(("pcs", "ns", "p")))
        b.point("pcs.gang_create", gang="ns/p-0",
                causal_link=led.follow(("pcs", "ns", "p")))
        events = chrome_trace({"fed": a, "member": b})["traceEvents"]
        starts = [e for e in events if e["ph"] == "s"]
        ends = [e for e in events if e["ph"] == "f"]
        assert len(starts) == 1 and len(ends) == 1
        assert starts[0]["id"] == ends[0]["id"]
        assert starts[0]["pid"] != ends[0]["pid"]
        assert ends[0]["bp"] == "e"
        assert starts[0]["cat"] == ends[0]["cat"] == "causal"

    def test_span_roundtrip_preserves_causal_tokens(self):
        sp = Span(None, "scheduler.bind", 3, 1, 1.0, 2.0,
                  {"causal_link": 7, "causal_emit": [8, 9]})
        sp.v1, sp.t1 = 1.0, 2.0
        back = Span.from_dict(json.loads(json.dumps(sp.to_dict())))
        assert back.attrs["causal_link"] == 7
        assert back.attrs["causal_emit"] == [8, 9]
        assert back.to_dict() == sp.to_dict()

    def test_folder_accepts_dumped_dict_spans(self):
        # the trace-CLI path: fold to_dict() spans, not Span objects
        clock = FakeClock()
        tr = Tracer(clock=clock)
        gang_life(tr, clock)
        paths = []
        folder = CriticalPathFolder(sink=paths.append)
        folder.fold_all(json.loads(json.dumps(tr.dump()))["spans"])
        assert len(paths) == 1
        assert sum(paths[0]["segments"].values()) == pytest.approx(
            5.0, abs=_TICK
        )

    def test_trace_cli_prints_critical_path(self, tmp_path, capsys):
        from grove_tpu.observability.trace import main as trace_main

        clock = FakeClock()
        tr = Tracer(clock=clock)
        gang_life(tr, clock)
        dump = tmp_path / "dump.json"
        dump.write_text(json.dumps(tr.dump()))
        assert trace_main([str(dump), "--critical-path"]) == 0
        cap = capsys.readouterr()
        doc = json.loads(cap.out)  # chrome json on stdout
        assert any(e["ph"] == "X" for e in doc["traceEvents"])
        side = json.loads(cap.err)  # breakdown on stderr
        assert side["critical_path"]["paths"] == 1
        assert side["paths"][0]["gang"] == "default/g-0"


# -- seeded e2e: stream front + sharded control plane + hierarchical solve ----

E2E_CONFIG = {
    "tracing": {"enabled": True},
    "stream": {
        "enabled": True, "slo_seconds": 10.0,
        "window_min_seconds": 0.5, "window_max_seconds": 2.0,
        "max_batch_gangs": 4, "queue_cap_gangs": 16,
    },
    "controllers": {"shards": 4, "shard_lease_duration_seconds": 10.0},
    "solver": {"hierarchical_min_nodes": 4},
}


def e2e_harness(mode="full"):
    cfg = {k: dict(v) for k, v in E2E_CONFIG.items()}
    cfg["tracing"]["mode"] = mode
    return Harness(nodes=make_nodes(16, hosts_per_rack=4), config=cfg)


def packed_pcs(replicas=2):
    """A PCS whose gangs REQUIRE rack-packing: with >= 2 rack domains
    the scheduler takes the hierarchical coarse-prune + per-domain
    fine-solve path (solver/engine.py _hier_plan)."""
    from grove_tpu.api.types import (
        TopologyConstraintSpec,
        TopologyPackConstraintSpec,
    )

    pcs = simple_pcs(replicas=replicas,
                     cliques=[clique("w", replicas=2),
                              clique("x", replicas=3)])
    pcs.spec.template.topology_constraint = TopologyConstraintSpec(
        pack_constraint=TopologyPackConstraintSpec(required="rack")
    )
    return pcs


def run_spread(h, rounds=10, dt=0.5):
    for _ in range(rounds):
        h.clock.advance(dt)
        h.manager.run_once()
        h.clock.advance(dt)
        h.kubelet.tick()
    h.settle()


class TestEndToEnd:
    def _drive(self, mode="full"):
        h = e2e_harness(mode)
        h.apply(packed_pcs())
        run_spread(h)
        return h

    def test_paths_telescope_exactly_across_all_hops(self):
        h = self._drive()
        tr = h.cluster.tracer
        names = {sp.name for sp in tr.finished}
        # every hop actually fired on this topology + config
        assert {"scheduler.stream_admit", "scheduler.solve",
                "engine.hierarchical", "engine.fine_solve",
                "scheduler.bind", "kubelet.pod_ready"} <= names
        path = tr.gang_path("default/simple1-0")
        assert path is not None and path["complete"]
        cp = path["checkpoints"]
        assert sum(path["segments"].values()) == pytest.approx(
            cp["running"] - cp["created"], abs=_TICK
        )
        assert path["total"] == pytest.approx(
            cp["running"] - cp["created"], abs=_TICK
        )
        report = tr.flush_critical_paths(h.cluster.metrics)
        assert report["paths"] >= 1
        assert report["dominant_segment"] in SEGMENTS

    def test_causal_chain_links_admission_to_bind_to_pods(self):
        h = self._drive()
        by_name = {}
        for sp in h.cluster.tracer.finished:
            by_name.setdefault(sp.name, []).append(sp)
        emits = {
            t for sp in by_name["scheduler.stream_admit"]
            for t in tokens_of(sp.attrs.get("causal_emit"))
        }
        gang_creates = {
            t for sp in by_name["pcs.gang_create"]
            for t in tokens_of(sp.attrs.get("causal_emit"))
        }
        binds = by_name["scheduler.bind"]
        bind_links = {
            t for sp in binds for t in tokens_of(sp.attrs.get("causal_link"))
        }
        # the bind consumed a token minted by the admit hop (or the gang
        # create, for a gang bound in the same round it was admitted)
        assert bind_links & (emits | gang_creates)
        bind_emits = {
            t for sp in binds for t in tokens_of(sp.attrs.get("causal_emit"))
        }
        pod_links = {
            t for sp in by_name.get("kubelet.pod_start", [])
            for t in tokens_of(sp.attrs.get("causal_link"))
        }
        assert pod_links <= bind_emits and pod_links

    def test_aggregate_mode_agrees_with_full(self):
        full = self._drive("full")
        agg = self._drive("aggregate")
        assert agg.cluster.tracer.mode == "aggregate"
        assert len(agg.cluster.tracer.finished) == 0
        rf = full.cluster.tracer.flush_critical_paths()
        ra = agg.cluster.tracer.flush_critical_paths()
        assert rf["paths"] == ra["paths"] >= 2
        assert rf["dominant_segment"] == ra["dominant_segment"]
        for seg in SEGMENTS:
            assert rf["segments"][seg]["sum"] == pytest.approx(
                ra["segments"][seg]["sum"], abs=1e-6
            )

    def test_debug_dump_and_histogram_agree_on_dominant(self):
        h = self._drive()
        dump = h.debug_dump()
        cp = dump["tracing"]["critical_path"]
        assert cp["paths"] >= 1
        hist = h.cluster.metrics.get("grove_trace_critical_path_seconds")
        assert hist is not None
        for seg, agg in cp["segments"].items():
            assert hist.series_count(segment=seg) == agg["count"]
        # every per-gang dominant names a real segment, and the fleet
        # dominant is one of them
        tops = dump["tracing"]["critical_path"]["top"]
        assert all(t["dominant"] in SEGMENTS for t in tops)


# -- surfaces: scorecard + postmortem ----------------------------------------

class TestSurfaces:
    def test_firing_bind_slo_attaches_worst_offenders(self):
        from grove_tpu.api.config import load_operator_config
        from grove_tpu.observability.metrics import MetricsRegistry
        from grove_tpu.observability.slo import SLOEngine, VERDICT_OK

        cfg = load_operator_config({"slo": {
            "enabled": True, "sync_interval_seconds": 5.0,
            "budget_window_seconds": 120.0, "pending_for_seconds": 0.0,
            "page_short_seconds": 5.0, "page_long_seconds": 30.0,
            "page_burn_threshold": 5.0, "ticket_short_seconds": 30.0,
            "ticket_long_seconds": 90.0, "ticket_burn_threshold": 2.0,
            "objectives": [{"name": "bind-p99", "kind": "bind_latency_p99",
                            "target": 0.9, "threshold_seconds": 1.0}],
        }}).slo
        reg = MetricsRegistry()
        clock = FakeClock()
        eng = SLOEngine(cfg, reg, clock)
        tr = Tracer(clock=FakeClock())
        gang_life(tr, tr.clock)
        eng.path_source = tr
        hist = reg.histogram("grove_scheduler_gang_bind_latency_seconds")
        eng.sweep()  # baseline
        for _ in range(4):
            hist.observe(5.0)  # way over the 1s threshold
        clock.t = 5.0
        eng.sweep()
        (entry,) = eng.scorecard()["slos"]
        assert entry["verdict"] != VERDICT_OK
        attach = entry["critical_path"]
        assert attach["dominant_segment"] == \
            tr.flush_critical_paths()["dominant_segment"]
        assert attach["worst_offenders"][0]["gang"] == "default/g-0"

    def test_healthy_bind_slo_attaches_nothing(self):
        from grove_tpu.api.config import load_operator_config
        from grove_tpu.observability.metrics import MetricsRegistry
        from grove_tpu.observability.slo import SLOEngine

        cfg = load_operator_config({"slo": {
            "enabled": True,
            "objectives": [{"name": "bind-p99", "kind": "bind_latency_p99",
                            "target": 0.9, "threshold_seconds": 30.0}],
        }}).slo
        eng = SLOEngine(cfg, MetricsRegistry(), FakeClock())
        eng.path_source = Tracer()
        eng.sweep()
        (entry,) = eng.scorecard()["slos"]
        assert "critical_path" not in entry

    def test_wedged_postmortem_attaches_partial_path(self):
        # a gang that can never place: the flight dump's wedged section
        # must carry its reconstructed (partial) critical path
        ch = ChaosHarness(
            FaultPlan.from_seed(1, chaos_steps=0),
            nodes=make_nodes(2, allocatable={"cpu": 1.0, "memory": 1.0,
                                             "tpu": 0.0}),
            config={"tracing": {"enabled": True}},
        )
        ch.apply(simple_pcs(cliques=[clique("w", replicas=2, cpu=5.0)]))
        ch.settle()
        dump = ch.dump_flight()
        (stuck,) = dump["wedged"]["unscheduled_gangs"]
        assert stuck["name"] == "default/simple1-0"
        path = stuck["critical_path"]
        assert path is not None and not path["complete"]
        assert path["dominant"] in SEGMENTS
        assert path["total"] >= 0.0


# -- chaos bit-identity -------------------------------------------------------

class TestChaosBitIdentity:
    def _run(self, tracing):
        plan = FaultPlan.from_seed(11, chaos_steps=4)
        config = {"tracing": tracing} if tracing else {}
        ch = ChaosHarness(plan, nodes=make_nodes(8), config=config)
        ch.apply(simple_pcs(cliques=[clique("w", replicas=2)]))
        ch.run_chaos()
        return settled_fingerprint(ch.harness.store), dict(plan.counts)

    def test_aggregate_mode_is_bit_identical_on_chaos_seeds(self):
        # the ledger/folder do no store writes and draw no RNG: a chaos
        # seed must converge to the SAME fingerprint with the same
        # fault-plan draw counts whether tracing is off, full, or
        # aggregate (the satellite CI smoke pins this on real seeds)
        fp_off, counts_off = self._run(None)
        fp_full, counts_full = self._run({"enabled": True})
        fp_agg, counts_agg = self._run({"enabled": True,
                                        "mode": "aggregate"})
        assert fp_off == fp_full == fp_agg
        assert counts_off == counts_full == counts_agg
